"""Headline benchmark: transformer LM training throughput on real TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric = model-FLOPs utilization (MFU) of the flagship decoder-only LM train
step on the attached chip(s). The reference publishes no TPU numbers
(BASELINE.md); the north-star target there is >=40% MFU for Train — so
vs_baseline is MFU / 0.40.
"""
from __future__ import annotations

import json
import time


def _peak_flops_per_chip() -> float:
    """bf16 peak FLOP/s for the attached TPU generation. Hard-fails on an
    unrecognized chip: an MFU against a guessed peak is worse than no number
    (a v6e misread as v5e would inflate MFU ~4.7x)."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    # Public peak bf16 numbers (per chip).
    table = {
        "v6e": 918e12,
        "v6": 918e12,
        "v5e": 197e12,
        "v5 lite": 197e12,
        "v5litepod": 197e12,
        "v5p": 459e12,
        "v4": 275e12,
        "v3": 123e12,
        "v2": 45e12,
    }
    for name, peak in table.items():
        if name in kind:
            return peak
    if jax.default_backend() != "tpu":
        return 1.0  # CPU smoke runs: MFU is meaningless, report raw ratio
    raise RuntimeError(
        f"unrecognized TPU device_kind {kind!r}: add its bf16 peak to the "
        "table in bench.py — refusing to guess (MFU would be wrong)"
    )


def main():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import TransformerConfig, make_train_step
    from ray_tpu.parallel import MeshSpec, ShardingStrategy, logical_sharding, shard_pytree
    from ray_tpu.parallel.sharding import use_strategy

    on_tpu = jax.default_backend() == "tpu"
    n_dev = len(jax.devices())

    # ~250M-param Llama-style GQA model sized for one v5e chip (16 GB HBM).
    # n_kv_heads=4: the flash kernel reads grouped K/V natively (no repeat),
    # measured +8% tokens/sec over full-head KV on v5e.
    cfg = TransformerConfig(
        vocab_size=32_000,
        d_model=1024,
        n_layers=12,
        n_heads=16,
        n_kv_heads=4,
        d_ff=4096,
        max_seq_len=2048,
        remat=True,
        # Round-4 tuning (PROFILES.md): 1024x1024 flash tiles (the profiler
        # trace showed the 512x512 kernels at ~30% efficiency eating 18% of
        # the step) + dots-saveable remat policy. 0.45 -> 0.52 MFU on v5e.
        remat_policy="dots",
        attention_impl="auto",
        attention_block_q=1024,
        attention_block_k=1024,
    )
    batch, seq = (16, 2048) if on_tpu else (2, 256)
    if not on_tpu:
        cfg = TransformerConfig(
            vocab_size=1024, d_model=256, n_layers=2, n_heads=4, d_ff=512,
            max_seq_len=seq, attention_impl="reference",
        )

    mesh = MeshSpec(data=-1).build()
    strategy = ShardingStrategy.dp() if n_dev > 1 else ShardingStrategy.none()

    init_state, train_step, state_axes = make_train_step(cfg)
    with use_strategy(strategy), mesh:
        state = init_state(jax.random.PRNGKey(0))
        axes = state_axes(state)
        state = shard_pytree(state, axes, mesh, strategy)
        state_sh = logical_sharding(mesh, strategy, axes)
        batch_sh = strategy.sharding(mesh, ("batch", "seq"))
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size),
            batch_sh,
        )
        data = {"tokens": tokens}
        step = jax.jit(
            train_step,
            in_shardings=(state_sh, {"tokens": batch_sh}),
            out_shardings=(state_sh, None),
            donate_argnums=(0,),
        )
        # warmup / compile. NOTE: sync via host transfer of the loss —
        # block_until_ready is not a reliable fence on the tunneled TPU
        # platform, a D2H copy is.
        state, m = step(state, data)
        _ = float(m["loss"])
        iters = 20 if on_tpu else 3
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = step(state, data)
        loss_val = float(m["loss"])
        dt = (time.perf_counter() - t0) / iters

    # Model FLOPs: 6 * params * tokens (fwd+bwd) + attention term
    # 12 * L * d * S^2 * B ... use standard 6ND + 12*L*H*hd*S^2.
    n_params = sum(x.size for x in jax.tree.leaves(state["params"]))
    tokens_per_step = batch * seq
    flops = 6.0 * n_params * tokens_per_step + 12.0 * cfg.n_layers * cfg.d_model * seq * tokens_per_step
    mfu = flops / dt / (_peak_flops_per_chip() * n_dev)
    tokens_per_sec = tokens_per_step / dt

    print(json.dumps({
        "metric": "train_mfu_flagship_lm",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak_bf16",
        "vs_baseline": round(mfu / 0.40, 4),
        "detail": {
            "tokens_per_sec_per_chip": round(tokens_per_sec / n_dev, 1),
            "step_time_s": round(dt, 4),
            "params": n_params,
            "batch": batch,
            "seq": seq,
            "n_devices": n_dev,
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "peak_flops_per_chip": _peak_flops_per_chip(),
            "final_loss": round(loss_val, 4),
        },
    }))


if __name__ == "__main__":
    main()
