"""LLM serving benchmark: paged-KV continuous-batching engine TTFT + decode
throughput on the attached TPU (BASELINE.md target row: "Serve Llama-8B-class
on v5e, continuous batching, p50 TTFT tracked" — model scaled to the single
bench chip, same engine code path), measured at TWO levels:

- engine: request arrival -> first sampled token, inside the engine loop.
- serve:  first SSE byte observed by a raw socket client through the full
  stack (HTTP proxy -> streaming handle -> replica -> engine), i.e. what a
  real client sees. The reference measures client-side TTFT the same way
  (serve benchmarks hit the HTTP proxy).

Two subprocess phases because the tunneled TPU chip is single-process: the
engine phase claims it in-process; the serve phase pins the driver to CPU and
lets the replica worker claim the chip.

Prints one JSON line; writes BENCH_LLM.json.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def engine_phase():
    import jax
    import numpy as np

    from ray_tpu.llm import EngineConfig, LLMEngine
    from ray_tpu.models import TransformerConfig

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32_000, d_model=1024, n_layers=12, n_heads=16,
            n_kv_heads=4, d_ff=4096, max_seq_len=2048, attention_impl="auto",
        )
        # 32 slots over a dense-parity page pool: KV 12L x 4KV x 2048*32 x 64
        # bf16 = 805MB of 16GB HBM. Decode is parameter-bandwidth-bound, so
        # the wide batch is ~free.
        n_requests, prompt_len, max_tokens, slots = 32, 512, 64, 32
    else:  # CPU smoke
        cfg = TransformerConfig(
            vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=256, attention_impl="reference",
        )
        n_requests, prompt_len, max_tokens, slots = 4, 32, 8, 2

    engine = LLMEngine(
        cfg,
        engine_config=EngineConfig(
            max_slots=slots, max_seq=cfg.max_seq_len,
            prefill_buckets=(128, 256, 512, 1024),
            # Dense KV layout: top single-chip decode throughput (XLA-fused
            # einsum attention). kv_layout="paged" trades some of it for
            # page-budgeted memory elasticity (measured in tests).
        ),
    )
    rng = np.random.default_rng(0)

    # Compile every (bucket, k) prefill + both decode blocks outside the
    # measured window (a cold compile is seconds — it belongs to startup,
    # exactly like vLLM's warmup, not to a request's TTFT).
    engine.warmup(buckets=(prompt_len,))
    engine.generate(rng.integers(0, cfg.vocab_size, prompt_len), max_tokens=2)
    # Unloaded TTFT: one isolated request on an idle engine.
    unloaded = engine.generate(rng.integers(0, cfg.vocab_size, prompt_len), max_tokens=2)["ttft_s"]

    ttfts = []
    decoded = 0
    t_start = time.perf_counter()
    for i in range(n_requests):
        engine.add_request(f"q{i}", rng.integers(0, cfg.vocab_size, prompt_len), max_tokens)
    while engine.has_work():
        for rid, ev in engine.step().items():
            if ev.get("ttft_s") is not None and not ev.get("finished"):
                ttfts.append(ev["ttft_s"])
            if ev.get("finished"):
                if ev.get("ttft_s") is not None and len(ttfts) < n_requests:
                    ttfts.append(ev["ttft_s"])
                decoded += len(ev["tokens"])
    elapsed = time.perf_counter() - t_start

    ttfts = np.array(sorted(ttfts))
    out = {
        "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 4),
        "ttft_p99_s": round(float(np.percentile(ttfts, 99)), 4),
        "ttft_unloaded_s": round(float(unloaded), 4),
        "decode_tokens_per_sec": round(decoded / elapsed, 1),
        "requests": n_requests,
        "prompt_len": prompt_len,
        "max_tokens": max_tokens,
        "slots": slots,
        "total_wall_s": round(elapsed, 3),
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
    }
    print("ENGINE_RESULT " + json.dumps(out), flush=True)


def prefix_phase():
    """Prefix-cache TTFT on the canonical shared-system-prompt workload:
    cold (full prefill) vs PARTIAL hit (cached system prompt + tail-only
    prefill) vs EXACT hit (page copy, no prefill). Page-granular chained
    digests — llm/engine.py partial-prefix KV reuse."""
    import jax
    import numpy as np

    from ray_tpu.llm import EngineConfig, LLMEngine
    from ray_tpu.models import TransformerConfig

    on_tpu = jax.default_backend() == "tpu"
    # Same model as every serving phase (ONE shared table) so TTFTs compare.
    model_config, _, _, _, _, _ = _serving_config(on_tpu)
    cfg = TransformerConfig(**model_config)
    if on_tpu:
        sys_len, tail_len, trials, ps = 1024, 64, 4, 128
        buckets = (128, 1024, 1280)
    else:  # CPU smoke (longer context than the tiny table: room for sys+tail)
        import dataclasses

        cfg = dataclasses.replace(cfg, max_seq_len=1024)
        sys_len, tail_len, trials, ps = 256, 16, 2, 64
        buckets = (64, 256, 512)
    engine = LLMEngine(cfg, engine_config=EngineConfig(
        max_slots=8, max_seq=cfg.max_seq_len, prefill_buckets=buckets,
        kv_layout="paged", page_size=ps, prefix_cache=True,
    ))

    def prompt(sys_seed, tail_seed):
        r1 = np.random.default_rng(sys_seed)
        r2 = np.random.default_rng(tail_seed)
        return np.concatenate([
            r1.integers(0, cfg.vocab_size, sys_len),
            r2.integers(0, cfg.vocab_size, tail_len),
        ]).astype(np.int32)

    engine.warmup(buckets=(sys_len + tail_len,))
    # Warm every program variant incl. the tail-prefill + page copy.
    engine.generate(prompt(1000, 0), max_tokens=2)
    engine.generate(prompt(1000, 1), max_tokens=2)  # partial (compiles tail)
    engine.generate(prompt(1000, 1), max_tokens=2)  # exact (compiles copy)

    cold, partial, exact = [], [], []
    for t in range(trials):
        cold.append(engine.generate(prompt(2000 + t, 10 + t), max_tokens=2)["ttft_s"])
        partial.append(engine.generate(prompt(2000 + t, 50 + t), max_tokens=2)["ttft_s"])
        exact.append(engine.generate(prompt(2000 + t, 50 + t), max_tokens=2)["ttft_s"])
    stats = engine.prefix_cache_stats
    med = lambda xs: float(np.median(xs))  # noqa: E731 — round ratios LAST
    out = {
        "ttft_cold_s": round(med(cold), 4),
        "ttft_partial_hit_s": round(med(partial), 4),
        "ttft_exact_hit_s": round(med(exact), 4),
        "partial_speedup": round(med(cold) / max(med(partial), 1e-9), 2),
        "exact_speedup": round(med(cold) / max(med(exact), 1e-9), 2),
        "sys_len": sys_len, "tail_len": tail_len, "page_size": ps,
        "cache_stats": {k: stats[k] for k in ("hits", "partial_hits", "misses")},
        "backend": jax.default_backend(),
        "note": "speedups are meaningful on the TPU (prefill compute >> page "
                "copy); the CPU smoke's tiny model inverts them because the "
                "unrolled pool-copy program costs more than its prefill.",
    }
    print("PREFIX_RESULT " + json.dumps(out), flush=True)


def _probe_backend():
    """Ambient accelerator seen by a FRESH process (the driver here pins
    itself to CPU so the replica worker can claim the chip)."""
    probe = subprocess.run(
        [sys.executable, "-c",
         "import jax; print(jax.default_backend(), jax.devices()[0].device_kind)"],
        capture_output=True, text=True, timeout=300,
    )
    on_tpu = probe.stdout.strip().startswith("tpu")
    return on_tpu, probe.stdout.strip().split(" ", 1)[-1] if on_tpu else "cpu"


def _serving_config(on_tpu: bool):
    """(model_config, n_requests, prompt_len, max_tokens, slots, buckets) —
    ONE table shared by every serving phase so they measure the same model."""
    if on_tpu:
        return (dict(vocab_size=32_000, d_model=1024, n_layers=12, n_heads=16,
                     n_kv_heads=4, d_ff=4096, max_seq_len=2048, attention_impl="auto"),
                32, 512, 64, 32, (128, 256, 512, 1024))
    return (dict(vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                 d_ff=128, max_seq_len=256, attention_impl="reference"),
            4, 32, 8, 2, (32, 64))


def _sse_request(port, path, body: bytes, is_first_data, extra_headers: str = "",
                 assert_ok: bool = True):
    """Raw-socket POST; parse the chunked SSE reply. Returns (ttfb, chunks,
    wall): ttfb = seconds to the first chunk matching is_first_data.
    extra_headers: raw CRLF-terminated header lines (the scaleout phase's
    QoS class headers). assert_ok=False maps a non-200 (shed 429 / expired
    504) to (None, [], wall) instead of raising — overload phases count
    those as not-ok rather than aborting the bench."""
    import socket

    t0 = time.perf_counter()
    s = socket.create_connection(("127.0.0.1", port), timeout=600)
    s.sendall(
        (f"POST {path} HTTP/1.1\r\nhost: x\r\ncontent-length: {len(body)}\r\n"
         f"{extra_headers}\r\n").encode()
        + body
    )
    f = s.makefile("rb")
    status = f.readline()
    if b"200" not in status:
        if assert_ok:
            raise AssertionError(status)
        s.close()
        return None, [], time.perf_counter() - t0
    while True:  # headers
        if f.readline() in (b"\r\n", b""):
            break
    ttfb = None
    chunks = []
    while True:  # chunked body; first matching chunk = client TTFT
        size = int(f.readline().strip(), 16)
        if size == 0:
            f.readline()
            break
        data = f.read(size)
        f.read(2)
        if ttfb is None and is_first_data(data):
            ttfb = time.perf_counter() - t0
        chunks.append(data)
    s.close()
    return ttfb, chunks, time.perf_counter() - t0


def serve_phase():
    # Pin the DRIVER to CPU before jax initializes any backend; the replica
    # worker (separate process) inherits the ambient env and claims the TPU.
    import jax

    jax.config.update("jax_platforms", "cpu")
    import threading

    import numpy as np

    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    on_tpu, device_kind = _probe_backend()
    model, n_requests, prompt_len, max_tokens, slots, buckets = _serving_config(on_tpu)

    rt.init(num_cpus=8)
    serve.start()
    app = build_llm_app(
        model_config=model,
        engine_config={"max_slots": slots, "max_seq": model["max_seq_len"],
                       "prefill_buckets": buckets},
        warmup_buckets=(prompt_len,),
    )
    serve.run(app, name="bench", route_prefix="/llm", timeout_s=1200)
    port = serve.http_port()
    rng = np.random.default_rng(0)

    def one_request(out, idx):
        toks = rng.integers(0, model["vocab_size"], prompt_len).tolist()
        body = json.dumps({"tokens": toks, "max_tokens": max_tokens, "stream": True}).encode()
        ttfb, chunks, wall = _sse_request(port, "/llm", body, lambda d: b"data:" in d)
        n_tokens = 0
        for data in chunks:
            for line in data.decode().split("\n\n"):
                if line.startswith("data: ") and line != "data: [DONE]":
                    n_tokens += len(json.loads(line[6:]).get("new_tokens", []))
        out[idx] = (ttfb, n_tokens, wall)

    # Unloaded: one isolated request.
    res: dict = {}
    one_request(res, "warm")  # absorb any first-request stragglers
    one_request(res, "unloaded")
    unloaded = res["unloaded"][0]

    # Loaded: n_requests concurrent socket clients.
    threads = [threading.Thread(target=one_request, args=(res, i)) for i in range(n_requests)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    for i in range(n_requests):
        assert res[i][0] is not None, (
            f"request {i} produced no 'data:' chunk (ttfb is None); raw result: {res[i]!r}"
        )
    ttfts = sorted(res[i][0] for i in range(n_requests))
    decoded = sum(res[i][1] for i in range(n_requests))
    out = {
        "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 4),
        "ttft_p99_s": round(float(np.percentile(ttfts, 99)), 4),
        "ttft_unloaded_s": round(float(unloaded), 4),
        "decode_tokens_per_sec": round(decoded / wall, 1),
        "requests": n_requests,
        "total_wall_s": round(wall, 3),
        "backend": "tpu" if on_tpu else "cpu",
        "device_kind": device_kind,
    }
    print("SERVE_RESULT " + json.dumps(out), flush=True)
    serve.shutdown()
    rt.shutdown()


def openai_phase():
    """Client-level TEXT serving: tokens/s + TTFT observed by raw socket
    clients speaking the OpenAI /v1/completions SSE protocol (tokenize ->
    engine -> detokenize -> SSE), the full path a real client exercises."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import threading

    import numpy as np

    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.llm import build_openai_app

    on_tpu, device_kind = _probe_backend()
    model, n_requests, prompt_len, max_tokens, slots, buckets = _serving_config(on_tpu)

    rt.init(num_cpus=8)
    serve.start()
    app = build_openai_app(
        model_config=model,
        engine_config={"max_slots": slots, "max_seq": model["max_seq_len"],
                       "prefill_buckets": buckets},
        warmup_buckets=(prompt_len,),
        model_name="bench",
    )
    serve.run(app, name="bench_oai", route_prefix="/", timeout_s=1200)
    port = serve.http_port()
    rng = np.random.default_rng(0)
    # ~1 token/byte with the byte-level tokenizer: prompt_len ASCII chars
    # (+bos) lands in the same prefill bucket as the token-level phase.
    letters = np.array(list("abcdefghijklmnopqrstuvwxyz "))

    def one_request(out, idx):
        prompt = "".join(rng.choice(letters, prompt_len - 1))
        body = json.dumps({
            "model": "bench", "prompt": prompt, "max_tokens": max_tokens,
            "stream": True, "ignore_eos": True,
        }).encode()
        ttfb, _chunks, wall = _sse_request(
            port, "/v1/completions", body, lambda d: b'"text"' in d
        )
        out[idx] = (ttfb, wall)

    res: dict = {}
    one_request(res, "warm")
    one_request(res, "unloaded")
    threads = [threading.Thread(target=one_request, args=(res, i)) for i in range(n_requests)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    for i in range(n_requests):
        assert res[i][0] is not None, f"request {i} saw no text chunk: {res[i]!r}"
    ttfts = sorted(res[i][0] for i in range(n_requests))
    out = {
        # ignore_eos guarantees every request decodes exactly max_tokens.
        "client_tokens_per_sec": round(n_requests * max_tokens / wall, 1),
        "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 4),
        "ttft_unloaded_s": round(float(res["unloaded"][0]), 4),
        "requests": n_requests,
        "max_tokens": max_tokens,
        "total_wall_s": round(wall, 3),
        "backend": "tpu" if on_tpu else "cpu",
        "device_kind": device_kind,
    }
    print("OPENAI_RESULT " + json.dumps(out), flush=True)
    serve.shutdown()
    rt.shutdown()


def scaleout_phase():
    """Serve scale plane A/B: goodput + TTFT p50/p99 at 1, 2, and 3 replicas
    under an overload_storm-style mix (interactive trickle + best_effort
    flood with QoS headers), with the AUTOSCALER — not a static replica
    count — providing the capacity: the deployment starts at min_replicas=1
    and the QoS/demand signals must grow it. Each window's row is keyed by
    the replica count observed during that window.

    Honesty note (PROFILES round 13): the client threads, HTTP proxy,
    controller, and every replica process co-locate on this host's core
    budget — on the single-core bench host, added replicas also steal the
    clients' CPU, so the goodput slope here is a LOWER bound on the
    isolated-cluster slope."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import threading

    import numpy as np

    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    on_tpu, device_kind = _probe_backend()
    model, _n, prompt_len, max_tokens, slots, buckets = _serving_config(on_tpu)
    # Per-replica capacity small enough that the mix overloads one replica.
    slots = max(2, slots // 8)
    rt.init(num_cpus=8)
    serve.start()
    app = build_llm_app(
        model_config=model,
        engine_config={"max_slots": slots, "max_seq": model["max_seq_len"],
                       "prefill_buckets": buckets},
        warmup_buckets=(prompt_len,),
        autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                            "target_ongoing_requests": 1.0,
                            "upscale_delay_s": 0.5, "downscale_delay_s": 30.0,
                            "cooldown_s": 2.0},
    )
    serve.run(app, name="bench_scale", route_prefix="/llm", timeout_s=1200)
    port = serve.http_port()
    ctl = rt.get_actor("__serve_controller__", namespace="serve")
    rng = np.random.default_rng(0)
    duration = 90.0 if on_tpu else 45.0
    stop_at = time.perf_counter() + duration
    lock = threading.Lock()
    # (t_done, ttfb, ok, n_replicas_at_completion) per request.
    samples: list = []
    replicas_now = [1]

    def watch_replicas():
        import ray_tpu as rt  # noqa: F811

        while time.perf_counter() < stop_at:
            try:
                st = rt.get(ctl.get_serve_state.remote(), timeout=10)
                dep = st["apps"]["bench_scale"]["llm"]
                replicas_now[0] = len(dep["replicas"])
            except Exception:
                pass
            time.sleep(0.5)

    def flood(klass: str, think_s: float):
        toks = rng.integers(0, model["vocab_size"], prompt_len).tolist()
        body = json.dumps({"tokens": toks, "max_tokens": max_tokens,
                           "stream": True}).encode()
        while time.perf_counter() < stop_at:
            try:
                ttfb, _chunks, _wall = _sse_request(
                    port, "/llm", body, lambda d: b"data:" in d,
                    extra_headers=(f"x-priority: {klass}\r\n"
                                   "x-request-timeout-s: 60\r\n"),
                    assert_ok=False)
                ok = ttfb is not None
            except Exception:
                ttfb, ok = None, False
            with lock:
                samples.append((time.perf_counter(), ttfb, ok, replicas_now[0]))
            if think_s:
                time.sleep(think_s)

    watcher = threading.Thread(target=watch_replicas, daemon=True)
    watcher.start()
    threads = (
        [threading.Thread(target=flood, args=("interactive", 0.05)) for _ in range(2)]
        + [threading.Thread(target=flood, args=("best_effort", 0.0)) for _ in range(4)]
    )
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    st = rt.get(ctl.get_serve_state.remote(), timeout=30)
    dep = st["apps"]["bench_scale"]["llm"]
    decisions = [d for d in dep.get("decisions", []) if d.get("applied")]
    # Rows keyed by the replica count live when the request completed.
    rows = {}
    window_bounds = {}
    for t_done, ttfb, ok, nrep in samples:
        r = rows.setdefault(nrep, {"ok": 0, "fail": 0, "ttfts": []})
        r["ok" if ok else "fail"] += 1
        if ttfb is not None:
            r["ttfts"].append(ttfb)
        lo, hi = window_bounds.get(nrep, (t_done, t_done))
        window_bounds[nrep] = (min(lo, t_done), max(hi, t_done))
    table = {}
    for nrep in sorted(rows):
        r = rows[nrep]
        lo, hi = window_bounds[nrep]
        span = max(hi - lo, 1e-9)
        ttfts = sorted(r["ttfts"])
        pct = lambda p: (  # noqa: E731
            round(float(np.percentile(ttfts, p)), 4) if ttfts else None)
        table[str(nrep)] = {
            "goodput_req_s": round(r["ok"] / span, 2),
            "ttft_p50_s": pct(50), "ttft_p99_s": pct(99),
            "completed": r["ok"], "failed": r["fail"],
            "window_s": round(span, 1),
        }
    out = {
        "by_replicas": table,
        "final_replicas": len(dep["replicas"]),
        "applied_decisions": [
            {"action": d["action"], "to": d["to"], "reason": d["reason"]}
            for d in decisions
        ],
        "backend": "tpu" if on_tpu else "cpu",
        "device_kind": device_kind,
        "note": "autoscaled 1->N under load; single-core client co-location "
                "makes the goodput slope a lower bound (see PROFILES r13)",
    }
    print("SCALEOUT_RESULT " + json.dumps(out), flush=True)
    serve.shutdown()
    rt.shutdown()


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    results = {}
    for phase in ("engine", "serve", "openai", "prefix", "scaleout"):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), phase],
            capture_output=True, text=True, timeout=3600,
            cwd=here,
        )
        marker = f"{phase.upper()}_RESULT "
        for line in proc.stdout.splitlines():
            if line.startswith(marker):
                results[phase] = json.loads(line[len(marker):])
        if phase not in results:
            print(f"phase {phase} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-4000:]}",
                  file=sys.stderr)
            raise SystemExit(1)

    serve_r, engine_r = results["serve"], results["engine"]
    result = {
        "metric": "serve_ttft_p50",
        # Headline = CLIENT-observed p50 TTFT through the HTTP proxy.
        "value": serve_r["ttft_p50_s"],
        "unit": "s",
        "vs_baseline": None,  # reference publishes no TPU serving numbers (BASELINE.md)
        # First-class serve-vs-engine overhead so the serving stack's cost
        # trajectory is diffable across rounds: bare-engine decode throughput
        # over client-observed serve throughput (1.0 = the stack is free),
        # plus the TTFT the stack adds at p50.
        "serve_overhead_x": round(
            engine_r["decode_tokens_per_sec"]
            / max(serve_r["decode_tokens_per_sec"], 1e-9), 3),
        "serve_ttft_overhead_s": round(
            serve_r["ttft_p50_s"] - engine_r["ttft_p50_s"], 4),
        "detail": {
            "engine": engine_r,
            "serve": serve_r,
            "openai": results["openai"],
            "prefix": results["prefix"],
            "serve_scaleout": results["scaleout"],
            "note": "serve/openai phases co-locate 32 client threads + HTTP "
                    "proxy + replica process on this host's ONE cpu core; the "
                    "engine->client gap is the measuring fleet itself — "
                    "PROFILES.md round 4 attributes it experimentally (proxy "
                    "round trip 1.5-1.9ms under load; a lone probe client "
                    "sees engine-level TTFT through the same proxy). Loaded "
                    "p50 vs unloaded reflects serializing 32 simultaneous "
                    "512-token prefills through one chip.",
        },
    }
    print(json.dumps(result))
    with open(os.path.join(here, "BENCH_LLM.json"), "w") as f:
        json.dump(result, f, indent=1)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "engine":
        engine_phase()
    elif len(sys.argv) > 1 and sys.argv[1] == "serve":
        serve_phase()
    elif len(sys.argv) > 1 and sys.argv[1] == "openai":
        openai_phase()
    elif len(sys.argv) > 1 and sys.argv[1] == "prefix":
        prefix_phase()
    elif len(sys.argv) > 1 and sys.argv[1] == "scaleout":
        scaleout_phase()
    else:
        main()
