"""LLM serving benchmark: continuous-batching engine TTFT + decode throughput
on the attached TPU (BASELINE.md target row: "Serve Llama-8B-class on v5e,
continuous batching, p50 TTFT tracked" — model scaled to the single bench
chip, same engine code path).

Prints one JSON line; writes BENCH_LLM.json.
"""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax

    from ray_tpu.llm import EngineConfig, LLMEngine
    from ray_tpu.models import TransformerConfig

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32_000, d_model=1024, n_layers=12, n_heads=16,
            n_kv_heads=4, d_ff=4096, max_seq_len=2048, attention_impl="auto",
        )
        # 32 slots: KV cache 12L x 32 x 2048 x 4 x 64 bf16 = 805MB of 16GB HBM.
        # Decode is parameter-bandwidth-bound, so the wider batch is ~free;
        # admission never queues behind occupied slots at this request count.
        n_requests, prompt_len, max_tokens, slots = 32, 512, 64, 32
    else:  # CPU smoke
        cfg = TransformerConfig(
            vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=256, attention_impl="reference",
        )
        n_requests, prompt_len, max_tokens, slots = 4, 32, 8, 2

    engine = LLMEngine(
        cfg,
        engine_config=EngineConfig(
            max_slots=slots, max_seq=cfg.max_seq_len,
            prefill_buckets=(128, 256, 512, 1024),
        ),
    )
    rng = np.random.default_rng(0)

    # Compile every (bucket, k) prefill + the decode block outside the
    # measured window (a cold compile is seconds — it belongs to startup,
    # exactly like vLLM's warmup, not to a request's TTFT).
    engine.warmup(buckets=(prompt_len,))
    engine.generate(rng.integers(0, cfg.vocab_size, prompt_len), max_tokens=2)
    # Unloaded TTFT: one isolated request on an idle engine.
    unloaded = engine.generate(rng.integers(0, cfg.vocab_size, prompt_len), max_tokens=2)["ttft_s"]

    ttfts = []
    decoded = 0
    t_start = time.perf_counter()
    for i in range(n_requests):
        engine.add_request(f"q{i}", rng.integers(0, cfg.vocab_size, prompt_len), max_tokens)
    while engine.has_work():
        for rid, ev in engine.step().items():
            if ev.get("ttft_s") is not None and not ev.get("finished"):
                ttfts.append(ev["ttft_s"])
            if ev.get("finished"):
                if ev.get("ttft_s") is not None and len(ttfts) < n_requests:
                    ttfts.append(ev["ttft_s"])
                decoded += len(ev["tokens"])
    elapsed = time.perf_counter() - t_start

    ttfts = np.array(sorted(ttfts))
    result = {
        "metric": "serve_ttft_p50",
        "value": round(float(np.percentile(ttfts, 50)), 4),
        "unit": "s",
        "vs_baseline": None,  # reference publishes no TPU serving numbers (BASELINE.md)
        "detail": {
            "ttft_unloaded_s": round(float(unloaded), 4),
            "ttft_p99_s": round(float(np.percentile(ttfts, 99)), 4),
            "decode_tokens_per_sec": round(decoded / elapsed, 1),
            "requests": n_requests,
            "prompt_len": prompt_len,
            "max_tokens": max_tokens,
            "slots": slots,
            "total_wall_s": round(elapsed, 3),
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
        },
    }
    print(json.dumps(result))
    with open("BENCH_LLM.json", "w") as f:
        json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()
