"""Core-runtime microbenchmarks, mirroring the reference's release
microbenchmark suite (/root/reference/release/microbenchmark — results in
release/perf_metrics/microbenchmark.json, copied to BASELINE.md).

Prints one JSON line per row: {"metric": ..., "value": ..., "unit": ...,
"baseline": <m5.16xlarge number>, "vs_baseline": ...}. The baseline hardware
is a 64-core m5.16xlarge; this environment typically has 1 core, so
vs_baseline is a lower bound on per-core parity.

Run: python bench_core.py [--quick]
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

import ray_tpu as rt

QUICK = "--quick" in sys.argv
SCALE = 0.2 if QUICK else 1.0

# m5.16xlarge numbers from BASELINE.md (release/perf_metrics/microbenchmark.json).
BASELINES = {
    "1_1_actor_calls_sync": 1989.7,
    "1_1_actor_calls_async": 8591.5,
    "n_n_actor_calls_async": 22593.7,
    "1_1_async_actor_calls_sync": 1433.5,
    "1_1_async_actor_calls_async": 3853.3,
    "single_client_tasks_sync": 844.7,
    "single_client_tasks_async": 6769.6,
    "single_client_get_calls": 9361.1,
    "single_client_put_calls": 4116.4,
    "single_client_put_gigabytes": 18.2,
    "single_client_wait_1k_refs": 4.72,
    "placement_group_create_removal": 678.9,
}

RESULTS = []

# Network profile of the CURRENT phase: "quiet" (bare loopback) or
# "degraded_netem"/"degraded_sim" (shaped — see main()). Every row carries it
# so BENCH_CORE.json keeps both phases' rows side by side under one metric
# name without colliding.
_PROFILE = "quiet"
_PROFILE_DETAIL: dict = {}

# Quiet-loopback wire ceiling measured by bench_raw_socket_floor (MB/s).
# Every bandwidth headline reports itself as a fraction of this so "is the
# lane wire-speed yet?" is answerable from the JSON alone.
_FLOOR: dict = {}


def report(metric: str, ops: float, elapsed: float, unit: str = "ops/s", detail: dict | None = None):
    value = ops / elapsed
    base = BASELINES.get(metric)
    row = {
        "metric": metric,
        "value": round(value, 1),
        "unit": unit,
        "baseline": base,
        "vs_baseline": round(value / base, 3) if base else None,
        "profile": _PROFILE,
    }
    if detail:
        row["detail"] = detail
    floor = _FLOOR.get("mb_s")
    if floor:
        mb_s = value if "MB/s" in unit else value * 1e3 if unit == "GB/s" else None
        if mb_s is not None:
            row.setdefault("detail", {})["fraction_of_raw_socket_floor"] = round(mb_s / floor, 3)
    if _PROFILE_DETAIL:
        row.setdefault("detail", {})["net_profile"] = dict(_PROFILE_DETAIL)
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


def settle():
    """Drain the IO loop's callback backlog from the previous bench so its
    cost doesn't bleed into the next measurement (submissions are one-way
    fast-path callbacks; a wait() forces a full loop round trip)."""
    ref = rt.put(b"settle")
    rt.wait([ref], num_returns=1, timeout=10)
    time.sleep(0.1)


def timed(fn, n: int) -> float:
    settle()
    t0 = time.perf_counter()
    fn(n)
    return time.perf_counter() - t0


@rt.remote
class Sink:
    def ping(self):
        return b"ok"

    def with_arg(self, x):
        return b"ok"


@rt.remote(max_concurrency=64)
class AsyncSink:
    async def ping(self):
        return b"ok"


@rt.remote
def noop():
    return b"ok"


@rt.remote(num_returns="streaming")
def item_stream(k):
    for i in range(k):
        yield i


def _chaos_armed_noop():
    """Arm a schedule whose single rule can never match the RPC/exec hot
    path: every gate pass now runs the full enabled-path evaluation (fnmatch
    against the rule set) — the upper bound of what an armed-but-quiet
    chaos plane costs. The headline rows run with the plane OFF (plan=None:
    one attribute load + None check per gate), so disabled-path cost shows
    up only as this round's headline vs the previous round's."""
    from ray_tpu import chaos

    chaos.install(chaos.FaultSchedule.from_spec({
        "seed": 0,
        "rules": [{"site": "tpu.preempt", "kind": "preempt", "nth": 1 << 30}],
    }))


def bench_actor_sync(n):
    from ray_tpu import chaos
    from ray_tpu.util import tracing

    a = Sink.remote()
    rt.get(a.ping.remote(), timeout=60)

    def run(k):
        for _ in range(k):
            rt.get(a.ping.remote(), timeout=60)

    def run_traced(k):
        # Every call propagates the active span's context, emits exec-span
        # events on the actor worker, and records the submission event —
        # the full tracing-on cost.
        with tracing.span("bench_actor_sync"):
            for _ in range(k):
                rt.get(a.ping.remote(), timeout=60)

    elapsed = timed(run, n)
    traced = timed(run_traced, n)
    _chaos_armed_noop()
    try:
        armed = timed(run, n)
    finally:
        chaos.uninstall()
    # Flight-recorder A/B (ISSUE 15 quiet-path contract): the headline runs
    # with the always-on ring armed; disabling it strips the one deque
    # append per absorbed event, so the delta IS the black box's cost.
    from ray_tpu.obs import flight as _flight

    _flight.set_enabled(False)
    try:
        recorder_off = timed(run, n)
    finally:
        _flight.set_enabled(True)
    # Profiler A/B (ISSUE 19 armed-but-idle contract): interleaved pairs,
    # this process's sampler armed at the default 19 Hz vs disarmed, so
    # clock drift hits both arms. The remote worker's sampler stays armed
    # in both (it is always-on by design); the delta is what the sampling
    # thread costs the process under test.
    from ray_tpu.obs import profiler as _profiler

    was_armed = _profiler.armed()
    half = max(1, n // 2)
    prof_on_s, prof_off_s = [], []
    for _ in range(3):
        _profiler.arm(hz=19.0)
        prof_on_s.append(timed(run, half) / half)
        _profiler.disarm()
        prof_off_s.append(timed(run, half) / half)
    if was_armed:
        _profiler.arm(hz=19.0)
    prof_on, prof_off = min(prof_on_s), min(prof_off_s)
    # The A/B cannot resolve a sub-1% effect through this host's scheduling
    # noise (its sign flips run to run); the tick cost itself is
    # deterministic, so measure it directly: one _sample_once pass over this
    # process's live thread population, times hz, IS the armed-idle duty
    # cycle.
    _ps = _profiler.sampler()
    _me = threading.get_ident()
    for _ in range(5):
        _ps._sample_once(_me)  # warm the frame-render caches
    _t0 = time.perf_counter()
    for _ in range(200):
        _ps._sample_once(_me)
    prof_tick_s = (time.perf_counter() - _t0) / 200
    off_ops, on_ops, armed_ops = n / elapsed, n / traced, n / armed
    # The headline row stays tracing-OFF (comparable across rounds); the
    # on/off A/Bs ride in detail so BENCH_CORE.json tracks observability
    # and chaos-plane cost (overhead reported, not hidden).
    report("1_1_actor_calls_sync", n, elapsed, detail={
        "trace_overhead": {
            "off_ops_s": round(off_ops, 1),
            "on_ops_s": round(on_ops, 1),
            "overhead_pct": round((off_ops / on_ops - 1.0) * 100.0, 2),
        },
        "chaos_overhead": {
            "off_ops_s": round(off_ops, 1),
            "armed_noop_ops_s": round(armed_ops, 1),
            "overhead_pct": round((off_ops / armed_ops - 1.0) * 100.0, 2),
        },
        "obs_overhead": {
            "recorder_off_ops_s": round(n / recorder_off, 1),
            "recorder_on_ops_s": round(off_ops, 1),
            "overhead_pct": round((elapsed / recorder_off - 1.0) * 100.0, 2),
        },
        "profiler_overhead": {
            "off_ops_s": round(1.0 / prof_off, 1),
            "armed_ops_s": round(1.0 / prof_on, 1),
            "overhead_pct": round((prof_on / prof_off - 1.0) * 100.0, 2),
            "tick_cost_us": round(prof_tick_s * 1e6, 1),
            "duty_cycle_pct": round(prof_tick_s * 19.0 * 100.0, 3),
        },
    })


def _wire_batch_hist():
    """Driver-side envelope-size distribution for the measured window
    (send = submission coalescing, recv = reply coalescing), JSON-keyed."""
    from ray_tpu.core import rpc

    st = rpc.batch_stats()
    return {side: {str(k): v for k, v in h.items()} for side, h in st.items()}


def bench_actor_async(n):
    from ray_tpu import chaos
    from ray_tpu.core import rpc

    a = Sink.remote()
    rt.get(a.ping.remote(), timeout=60)

    def run(k):
        rpc.batch_stats(reset=True)
        rt.get([a.ping.remote() for _ in range(k)], timeout=120)

    elapsed = timed(run, n)
    _chaos_armed_noop()
    try:
        armed = timed(run, n)
    finally:
        chaos.uninstall()
    report("1_1_actor_calls_async", n, elapsed,
           detail={
               "wire_batches": _wire_batch_hist(),
               "chaos_overhead": {
                   "off_ops_s": round(n / elapsed, 1),
                   "armed_noop_ops_s": round(n / armed, 1),
                   "overhead_pct": round((armed / elapsed - 1.0) * 100.0, 2),
               },
           })


def bench_actor_nn_async(n):
    from ray_tpu.core import rpc

    actors = [Sink.remote() for _ in range(4)]
    rt.get([a.ping.remote() for a in actors], timeout=60)

    def run(k):
        rpc.batch_stats(reset=True)
        refs = [actors[i % len(actors)].ping.remote() for i in range(k)]
        rt.get(refs, timeout=120)

    report(
        "n_n_actor_calls_async", n, timed(run, n),
        detail={
            "wire_batches": _wire_batch_hist(),
            "host_cores": os.cpu_count(),
            "note": "baseline's n:n row runs n client processes against n server "
                    "actors spread over 64 cores (m5.16xlarge); here 1 driver + 4 "
                    "actor processes time-share ONE core, so ops/s ~= 1 / (total "
                    "per-call CPU of the whole pipeline) — a per-call-cost metric, "
                    "not a scale-out metric. Per-call CPU profile + the wire-format "
                    "optimizations it drove are in PROFILES.md.",
        },
    )


def bench_async_actor_sync(n):
    a = AsyncSink.remote()
    rt.get(a.ping.remote(), timeout=60)

    def run(k):
        for _ in range(k):
            rt.get(a.ping.remote(), timeout=60)

    report("1_1_async_actor_calls_sync", n, timed(run, n))


def bench_async_actor_async(n):
    a = AsyncSink.remote()
    rt.get(a.ping.remote(), timeout=60)

    def run(k):
        rt.get([a.ping.remote() for _ in range(k)], timeout=120)

    report("1_1_async_actor_calls_async", n, timed(run, n))


# State-introspection A/B (detail.state_overhead): normal tasks exercise the
# full lifecycle pipeline (submitted/dispatched/exec/finished events folded
# into the controller's per-task index), so the tasks_sync row carries the
# paired comparison. The OFF arm runs first in its OWN session with
# task_events_enabled=False propagated cluster-wide (workers adopt the head
# config), so executor-side emission is off too — not just the driver's.
_STATE_AB: dict = {}


def _tasks_sync_ops(n) -> float:
    rt.get(noop.remote(), timeout=60)

    def run(k):
        for _ in range(k):
            rt.get(noop.remote(), timeout=60)

    return n / timed(run, n)


def bench_tasks_sync_state_off(n):
    _STATE_AB["off_ops_s"] = _tasks_sync_ops(n)  # rides the ON row's detail


def bench_tasks_sync(n):
    ops = _tasks_sync_ops(n)
    detail = None
    off = _STATE_AB.get("off_ops_s")
    if off:
        detail = {"state_overhead": {
            "on_ops_s": round(ops, 1),
            "off_ops_s": round(off, 1),
            "overhead_pct": round((off / ops - 1.0) * 100.0, 2),
        }}
    report("single_client_tasks_sync", n, n / ops, detail=detail)


def bench_tasks_async(n):
    rt.get(noop.remote(), timeout=60)

    def run(k):
        rt.get([noop.remote() for _ in range(k)], timeout=300)

    report("single_client_tasks_async", n, timed(run, n))


def bench_streaming_items(n):
    """Streamed items/s through a full task-streaming round trip (pure CPU):
    executor generator -> batched generator_items frames -> owner absorb ->
    consumer rt.get per ref. The row the streaming fast lane is measured by;
    detail.stream_batches is the owner-side items-per-frame distribution
    (all-1s = the old per-item wire shape; deeper = coalescing working)."""
    from ray_tpu.core import worker as _worker

    got = sum(1 for _ in item_stream.remote(10))  # warm: worker + export
    assert got == 10

    def run(k):
        _worker.stream_batch_stats(reset=True)
        seen = 0
        for ref in item_stream.remote(k):
            rt.get(ref, timeout=120)
            seen += 1
        assert seen == k

    report(
        "streaming_generator_items", n, timed(run, n), unit="items/s",
        detail={"stream_batches": {
            str(k): v for k, v in _worker.stream_batch_stats().items()
        }},
    )


# QoS overload A/B (detail in the ON row): interleaved arms at ~3x offered
# load against a capacity-bounded serve app. Goodput is CLIENT-measured —
# interactive requests that returned 200 within their 0.5s budget — so the
# two arms are comparable even though only the ON arm sheds/expires
# server-side. The OFF arm runs first in its OWN session with
# Config.qos_enabled=False propagated cluster-wide (the proxy process reads
# it at actor creation), exactly like the state-introspection A/B.
_QOS_AB: dict = {}

_GOODPUT_BUDGET_S = 0.5


def _overload_goodput_arm(duration_s: float) -> dict:
    import urllib.error
    import urllib.request

    from ray_tpu import serve

    @serve.deployment(name="Bench", max_ongoing_requests=2)
    class Bench:
        def __call__(self, request):
            time.sleep(0.05)  # fixed 50ms service: capacity = 2/0.05 = 40 rps
            return "ok"

    serve.run(Bench.bind(), name="goodput", route_prefix="/goodput")
    port = serve.http_port()

    def one(headers: dict) -> tuple:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/goodput", data=b"{}", method="POST",
            headers=headers,
        )
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                code = resp.status
                resp.read()
        except urllib.error.HTTPError as e:
            code = e.code
            e.read()
        except Exception:
            # URLError / socket timeout must not kill a load thread — a
            # dead thread silently deflates that arm's goodput and skews
            # the A/B (same contract as the chaos scenario's hit()).
            code = -1
        return code, time.perf_counter() - t0

    # Quiet path (no overload, DEFAULT context — no QoS headers): the cost
    # of the plane's structural pieces alone; must be within noise of OFF.
    quiet = sorted(one({})[1] for _ in range(20))
    quiet_ms = quiet[len(quiet) // 2] * 1e3

    stop_at = time.monotonic() + duration_s
    lock = threading.Lock()
    inter: list = []  # (code, latency) per interactive request
    shed = [0]

    def flood(headers: dict, sink: list | None, think_s: float):
        while time.monotonic() < stop_at:
            code, lat = one(headers)
            with lock:
                if sink is not None:
                    sink.append((code, lat))
                if code == 429:
                    shed[0] += 1
            if think_s:
                time.sleep(think_s)

    threads = (
        [threading.Thread(target=flood,
                          args=({"x-priority": "best_effort", "x-tenant": f"bg{i % 2}"},
                                None, 0.0))
         for i in range(16)]
        + [threading.Thread(target=flood,
                            args=({"x-priority": "interactive", "x-tenant": "user"},
                                  inter, 0.02))
           for _ in range(3)]
    )
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 120)
    good = sum(1 for code, lat in inter if code == 200 and lat <= _GOODPUT_BUDGET_S)
    lats = sorted(lat for _, lat in inter) or [0.0]
    out = {
        "interactive_total": len(inter),
        "goodput_rps": round(good / duration_s, 1),
        "interactive_p99_s": round(lats[min(len(lats) - 1, int(len(lats) * 0.99))], 3),
        "sheds_429": shed[0],
        "quiet_ms": round(quiet_ms, 2),
    }
    serve.shutdown()
    return out


def bench_overload_goodput_off(_n):
    _QOS_AB["off"] = _overload_goodput_arm(3.0 if QUICK else 6.0)


def bench_overload_goodput(_n):
    duration = 3.0 if QUICK else 6.0
    on = _overload_goodput_arm(duration)
    off = _QOS_AB.get("off") or {}
    detail = {"on": on}
    if off:
        detail["off"] = off
        detail["goodput_x"] = round(
            on["goodput_rps"] / max(off["goodput_rps"], 0.1), 2)
        detail["quiet_overhead_pct"] = round(
            (on["quiet_ms"] / max(off["quiet_ms"], 1e-6) - 1.0) * 100.0, 2)
    report("overload_goodput", on["goodput_rps"] * duration, duration,
           unit="interactive req/s in budget", detail=detail)


def bench_get_calls(n):
    ref = rt.put(b"x" * 1024)

    def run(k):
        for _ in range(k):
            rt.get(ref, timeout=60)

    report("single_client_get_calls", n, timed(run, n))


def bench_put_calls(n):
    def run(k):
        for _ in range(k):
            rt.put(b"x" * 1024)

    report("single_client_put_calls", n, timed(run, n))


def bench_put_gigabytes(n_bytes):
    chunk = 64 * 1024 * 1024
    # ndarray payload: rides the protocol-5 out-of-band buffer path, so the
    # put is one scatter memcpy into shared memory (the realistic tensor case).
    # Only the latest ref is retained: pinning every put would wedge the
    # store at capacity and measure the eviction slow path, not bandwidth.
    data = np.ones(chunk, dtype=np.uint8)
    reps = max(1, n_bytes // chunk)
    last = None

    def run(k):
        nonlocal last
        for _ in range(k):
            last = rt.put(data)

    elapsed = timed(run, reps)
    # Host-ceiling evidence (VERDICT r2: "profile and attach"): the put path
    # is ONE scatter-memcpy into the shm arena; on this host the single-
    # thread warm memcpy ceiling bounds it. The 18.2 GB/s baseline ran on a
    # 64-core m5.16xlarge (multi-GB/s-per-channel DRAM); this box has 1 core.
    probe = bytearray(chunk)
    mv = memoryview(probe)
    mv[:] = data.data  # warm the destination pages
    t0 = time.perf_counter()
    for _ in range(5):
        mv[:] = data.data
    ceiling = 5 * chunk / 1e9 / (time.perf_counter() - t0)
    report(
        "single_client_put_gigabytes", reps * chunk / 1e9, elapsed, unit="GB/s",
        detail={
            "host_single_thread_memcpy_gbps": round(ceiling, 2),
            "fraction_of_host_memcpy_ceiling": round((reps * chunk / 1e9 / elapsed) / ceiling, 3),
            "note": "put = serialize_parts (zero-copy pickle-5 views) + one scatter "
                    "memcpy into the shm arena; bounded by this host's 1-core memcpy "
                    "bandwidth, measured inline above. Baseline hardware: 64-core "
                    "m5.16xlarge (release/microbenchmark tpl_64.yaml).",
        },
    )
    del last, mv, probe


def bench_raw_socket_floor(n_bytes):
    """The quiet-loopback wire ceiling this host can do AT ALL: a bare
    socketpair pump moving the same chunk size the raw lane ships, with the
    lane's irreducible per-byte work on both ends (one staging memcpy +
    HMAC-SHA256 on the sender, recv_into + HMAC-SHA256 on the receiver) and
    the lane's socket buffer tuning. No framing, no pickle, no event loop —
    anything the object lane loses below this number is protocol overhead,
    so every MB/s headline reports itself as a fraction of this floor."""
    import hashlib
    import hmac as _hmac
    import socket

    chunk = 1 << 20
    reps = max(8, n_bytes // chunk)
    a, b = socket.socketpair()
    for s in (a, b):
        s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4 << 20)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4 << 20)
    src = np.ones(chunk, dtype=np.uint8).data
    staging = memoryview(bytearray(chunk))
    rbuf = memoryview(bytearray(chunk))
    key = b"floor" * 4

    def drain():
        mac = _hmac.new(key, digestmod=hashlib.sha256)
        left = reps * chunk
        while left:
            got = b.recv_into(rbuf, min(len(rbuf), left))
            if not got:
                break
            mac.update(rbuf[:got])
            left -= got

    t = threading.Thread(target=drain)
    t.start()
    mac = _hmac.new(key, digestmod=hashlib.sha256)
    t0 = time.perf_counter()
    for _ in range(reps):
        staging[:] = src          # the one gather copy the lane performs
        mac.update(staging)
        a.sendall(staging)
    t.join(timeout=600)
    elapsed = time.perf_counter() - t0
    a.close()
    b.close()
    _FLOOR["mb_s"] = round(reps * chunk / 1e6 / elapsed, 1)
    report(
        "raw_socket_floor", reps * chunk / 1e6, elapsed, unit="MB/s",
        detail={
            "chunk_kib": chunk >> 10,
            "per_byte_work": "staging memcpy + HMAC-SHA256 (send), recv_into + HMAC-SHA256 (recv)",
            "note": "both ends time-share this host's cores exactly like the "
                    "real lane's two daemons; headline rows carry "
                    "fraction_of_raw_socket_floor against this number.",
        },
    )


# Wire-path A/B (detail.wire in the headline row): the legacy arm runs first
# in its OWN session with Config.raw_vectored_send=False propagated
# cluster-wide (daemons adopt the head config at registration), exactly like
# the state-introspection and QoS A/Bs. Both arms take per-round medians so
# a host hiccup in one round doesn't decide the comparison.
_WIRE_AB: dict = {}


def _large_object_pull_rounds(n_bytes, rounds=3):
    """Put N x 8 MiB objects on a second node per round, pull them to the
    driver's daemon over the raw-frame lane; per-round MB/s list + the pull
    manager for transfer-shape introspection. Fresh objects each round so
    every round re-crosses the wire (a cached object would measure the store,
    not the lane)."""
    from ray_tpu.core import api as _api

    chunk = 8 * 1024 * 1024
    reps = max(1, n_bytes // chunk)
    cluster = _api._global_cluster
    cluster.add_node(
        num_cpus=2, resources={"pull_src": float(reps * rounds) + 1},
        object_store_memory=512 * 1024 * 1024,
    )

    @rt.remote(resources={"pull_src": 1.0})
    def make(i, n):
        return np.full(n // 8, i, dtype=np.int64)

    pm = cluster.daemons[0].pull_manager
    rates = []
    stats = {}
    for rnd in range(rounds):
        refs = [make.remote(rnd * reps + i, chunk) for i in range(reps)]
        # Readiness only: the payloads are sealed in node B's arena; no bytes
        # have crossed to the head node yet.
        rt.wait(refs, num_returns=len(refs), timeout=600)
        b0, r0 = pm.bytes_in, pm.chunks_retried
        settle()
        t0 = time.perf_counter()
        for i, ref in enumerate(refs):
            arr = rt.get(ref, timeout=600)
            assert arr[0] == rnd * reps + i
            del arr
        elapsed = time.perf_counter() - t0
        rates.append(reps * chunk / 1e6 / elapsed)
        stats = {
            "window": pm.last_pull.get("window"),
            "mode": pm.last_pull.get("mode"),
            "sources": pm.last_pull.get("sources"),
            "chunks_retried": pm.chunks_retried - r0,
            "bytes_pulled": pm.bytes_in - b0,
            "objects": reps,
            "object_mb": chunk >> 20,
        }
        del refs
    return rates, stats


def bench_large_object_pull_legacy(n_bytes):
    """The legacy arm: per-buffer sequential writes through the asyncio
    transport (raw_vectored_send=False for this whole session). Rides the
    headline row's detail.wire."""
    rates, _ = _large_object_pull_rounds(n_bytes)
    _WIRE_AB["legacy_mb_s"] = round(sorted(rates)[len(rates) // 2], 1)


def bench_large_object_pull(n_bytes):
    """Cross-node object transfer bandwidth: put N x 8 MiB objects on a
    second node, get them on the driver (whose daemon pulls each object over
    the streaming raw-frame lane: pipelined window, multi-source striping,
    pickle-free chunks, single-sendmsg vectored frames, window-granular MAC).
    Reports the per-round median MB/s, the head daemon's transfer shape, and
    the vectored-vs-legacy wire A/B."""
    rates, stats = _large_object_pull_rounds(n_bytes)
    med = sorted(rates)[len(rates) // 2]
    detail = {
        "transfer": stats,
        "rounds_mb_s": [round(r, 1) for r in rates],
    }
    legacy = _WIRE_AB.pop("legacy_mb_s", None)
    if legacy:
        detail["wire"] = {
            "legacy_sendall_mb_s": legacy,
            "vectored_mb_s": round(med, 1),
            "vectored_vs_legacy_x": round(med / max(legacy, 0.1), 3),
        }
    report("large_object_pull", med, 1.0, unit="MB/s", detail=detail)


def bench_checkpoint_save_restore(n_bytes):
    """Checkpoint-plane A/B (ISSUE-10 acceptance): the same save pipeline
    driven synchronously (step blocks until the manifest commits — the
    save_pytree-shaped baseline) vs async double-buffered (step pays only
    the device->host snapshot). Reports save/restore MB/s, the per-step
    stall of both arms, and the dedup ratio of an incremental save with
    frozen params."""
    import shutil
    import tempfile

    from ray_tpu import ckpt as _ckpt

    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    half = max(1, n_bytes // 8)  # float32 halves: frozen + hot
    # The state is jax arrays (what a train step holds): immutable, so the
    # step-path snapshot is the genuine device->host cost, not a defensive
    # numpy copy.
    frozen = jnp.asarray(rng.standard_normal(half).astype(np.float32))
    steps = 4

    def run_arm(async_mode: bool):
        root = tempfile.mkdtemp(prefix="raytpu_bench_ckpt_")
        saver = _ckpt.AsyncSaver(root, num_to_keep=2)
        stalls, futs = [], []
        t_arm = time.perf_counter()
        try:
            for s in range(steps):
                tree = {"frozen": frozen,
                        "hot": jnp.asarray(rng.standard_normal(half).astype(np.float32))}
                t0 = time.perf_counter()
                if async_mode:
                    futs.append(saver.save_async(s, tree))
                else:
                    saver.save(s, tree)
                stalls.append(time.perf_counter() - t0)
            manifests = [f.result(timeout=600) for f in futs] if async_mode else []
            saver.wait_idle(timeout=600)
            wall = time.perf_counter() - t_arm
            last = saver.manifests.latest
            t0 = time.perf_counter()
            restored = _ckpt.restore(last, saver.chunks)
            restore_s = time.perf_counter() - t0
            assert restored["frozen"].nbytes == frozen.nbytes
            return {
                "stall_mean_s": sum(stalls) / len(stalls),
                "stall_max_s": max(stalls),
                "wall_s": wall,
                "dedup_ratio_incremental": last.dedup_ratio,
                "bytes_total": last["bytes_total"],
                "bytes_new_incremental": last["bytes_new"],
                "restore_mb_s": last["bytes_total"] / 1e6 / restore_s,
            }
        finally:
            saver.close()
            shutil.rmtree(root, ignore_errors=True)

    sync = run_arm(False)
    async_ = run_arm(True)
    total_mb = sync["bytes_total"] * steps / 1e6
    detail = {
        "ckpt": {
            "sync_stall_ms": round(sync["stall_mean_s"] * 1e3, 2),
            "async_stall_ms": round(async_["stall_mean_s"] * 1e3, 2),
            "async_stall_max_ms": round(async_["stall_max_s"] * 1e3, 2),
            # THE acceptance number: async step stall as a fraction of the
            # synchronous baseline (< 0.10 required).
            "stall_ratio": round(async_["stall_mean_s"] / max(sync["stall_mean_s"], 1e-9), 4),
            "dedup_ratio_incremental": round(async_["dedup_ratio_incremental"], 4),
            "incremental_bytes_fraction": round(
                async_["bytes_new_incremental"] / max(async_["bytes_total"], 1), 4),
            "restore_mb_s": round(async_["restore_mb_s"], 1),
        }
    }
    report("checkpoint_save_restore", total_mb, sync["wall_s"], unit="MB/s saved (sync arm)",
           detail=detail)


class _ReshardParty:
    """One host of the elastic-reshard bench: holds a deterministic slice
    of the state, exports/pulls through ray_tpu.elastic.transfer."""

    def export(self, tid, rank, world, rep_elems, win_elems):
        from ray_tpu.core import api as _api
        from ray_tpu.elastic import transfer

        rep = {"params": np.arange(rep_elems, dtype=np.float32)}
        shard = -(-win_elems // world)
        lo = min(win_elems, rank * shard)
        win = np.arange(lo, min(win_elems, lo + shard), dtype=np.float32)
        meta = transfer.export_state(tid, rank, rep,
                                     {"opt.0.m": (win, lo, win_elems)},
                                     seq=1, meta={})
        meta["addr"] = _api._require_worker().address
        return meta

    def pull(self, tid, sources, world, rank):
        from ray_tpu.core import api as _api
        from ray_tpu.elastic import transfer

        core = _api._require_worker()
        res = core._run(
            transfer.pull_state(core, tid, sources, world, rank),
            timeout=600)
        return res["stats"]

    def release(self, tid):
        from ray_tpu.elastic import transfer

        return transfer.release(tid)


def bench_elastic_reshard(n_bytes):
    """Elastic-plane A/B (ISSUE-13 acceptance): redistribute the same
    2-host state onto a 1-host mesh (a) LIVE over the raw-frame lane
    (multi-source pulls from two exporter workers into a third, zero
    pickle, zero disk) vs (b) the checkpoint-restore control (ckpt-plane
    sharded save once, rectangle-intersection restore per rep — the blob
    round trip the live path replaces). Arms interleave per rep so host
    drift hits both."""
    import shutil
    import tempfile

    from ray_tpu import ckpt as _ckpt

    rep_elems = max(1, n_bytes // 8)   # replicated params half
    win_elems = max(1, n_bytes // 8)   # sharded window half
    Party = rt.remote(_ReshardParty)
    a, b, c = Party.remote(), Party.remote(), Party.remote()
    root = tempfile.mkdtemp(prefix="raytpu_bench_reshard_")
    saver = _ckpt.AsyncSaver(root, num_to_keep=2)
    tree = {"params": np.arange(rep_elems, dtype=np.float32),
            "opt.0.m": np.arange(win_elems, dtype=np.float32)}
    saver.save(0, tree)  # the control's checkpoint exists BEFORE the resize
    manifest = saver.manifests.latest
    live_times, ctrl_times, live_stats = [], [], []
    total_bytes = (rep_elems + win_elems) * 4
    reps = 3
    try:
        for rep in range(reps):
            # Arm A: live reshard into a fresh target.
            tid = f"bench-{rep}"
            metas = [rt.get(w.export.remote(tid, r, 2, rep_elems, win_elems),
                            timeout=120) for r, w in ((0, a), (1, b))]
            t0 = time.perf_counter()
            stats = rt.get(c.pull.remote(tid, metas, 1, 0), timeout=600)
            live_times.append(time.perf_counter() - t0)
            live_stats.append(stats)
            for w in (a, b):
                rt.get(w.release.remote(tid), timeout=60)
            # Arm B: checkpoint-restore control, same target layout.
            t0 = time.perf_counter()
            restored = _ckpt.restore(manifest, saver.chunks)
            ctrl_times.append(time.perf_counter() - t0)
            assert restored["params"].nbytes == rep_elems * 4
        live = sorted(live_times)[len(live_times) // 2]
        ctrl = sorted(ctrl_times)[len(ctrl_times) // 2]
        st = live_stats[live_times.index(live)]
        report(
            "elastic_reshard_mb_s", total_bytes / 1e6, live, unit="MB/s",
            detail={
                "bytes": total_bytes,
                "wire_bytes": st["wire_bytes"],
                "failovers": st["failovers"],
                "ckpt_restore_mb_s": round(total_bytes / 1e6 / ctrl, 1),
                "live_vs_ckpt_restore_x": round(ctrl / live, 2),
                "reps": reps,
            })
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_allreduce_gbps(n_bytes):
    """Collective-plane A/B (ISSUE-12 acceptance): fp32 ring vs fp32
    coordinator vs int8 ring allreduce of one >= 1 MiB tensor across a
    2-rank gang. Effective GB/s = input tensor bytes / wall seconds per op
    (algorithmic bandwidth). Arms interleave round-robin so drift hits all
    three equally; medians reported."""
    from ray_tpu import collective as col

    world = 2
    n = max(1 << 20, n_bytes) // 4  # fp32 elements, >= 1 MiB
    reps = max(1, int(3 * SCALE))
    rounds_per_rep = 3

    @rt.remote
    class Member(col.CollectiveActorMixin):
        def arm(self, rank, kind, rounds, n):
            x = np.full((n,), rank + 1.0, np.float32)
            kwargs = ({"transport": "coordinator"} if kind == "coord"
                      else {"quantization": "int8"} if kind == "int8"
                      else {})
            col.barrier(group_name="bench")  # start the clock together
            t0 = time.perf_counter()
            for _ in range(rounds):
                out = col.allreduce(x, group_name="bench", **kwargs)
            elapsed = time.perf_counter() - t0
            assert abs(float(out[0]) - 3.0) < 0.1  # 1+2, quant within codec err
            return elapsed

    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, [0, 1], group_name="bench")
    times: dict = {"ring": [], "coord": [], "int8": []}
    settle()
    for _rep in range(reps):
        for kind in ("coord", "ring", "int8"):  # interleaved A/B/C
            got = rt.get([m.arm.remote(i, kind, rounds_per_rep, n)
                          for i, m in enumerate(members)], timeout=600)
            times[kind].append(max(got) / rounds_per_rep)
    med = {k: sorted(v)[len(v) // 2] for k, v in times.items()}
    nbytes = n * 4
    gbs = {k: nbytes / s / 1e9 for k, s in med.items()}
    col.destroy_collective_group("bench")
    report(
        "allreduce_gbps", nbytes / 1e9, med["ring"], unit="GB/s",
        detail={
            "tensor_mib": nbytes >> 20, "world": world,
            "coordinator_fp32_gb_s": round(gbs["coord"], 3),
            "ring_fp32_gb_s": round(gbs["ring"], 3),
            "ring_int8_gb_s": round(gbs["int8"], 3),
            "ring_vs_coordinator_x": round(gbs["ring"] / gbs["coord"], 2),
            "int8_vs_coordinator_x": round(gbs["int8"] / gbs["coord"], 2),
            # On a shaped (degraded) profile this is THE number: int8 ships
            # 1/4 the bytes, so the thinner the pipe the larger it gets.
            "int8_vs_ring_x": round(gbs["int8"] / gbs["ring"], 2),
        },
    )


def bench_train_step_overlap(n_steps):
    """Train-plane A/B (ISSUE-12): a data-parallel step whose backward
    produces 8 x 1 MiB grad buckets with real numpy compute between them —
    overlap ON pushes each bucket into its ring allreduce as produced
    (BucketedGradSync streaming) vs OFF (full backward, then one sync
    allreduce). Steps/s both arms, interleaved."""
    from ray_tpu import collective as col

    world = 2
    layers, layer_elems = 8, 256 * 1024  # 8 x 1 MiB fp32 grads
    steps = max(2, int(n_steps))

    @rt.remote
    class Member(col.CollectiveActorMixin):
        def arm(self, rank, overlap, steps):
            from ray_tpu.train.grad_sync import BucketedGradSync

            rng = np.random.default_rng(rank)
            # Per-layer backward compute sized like a real model's (backward
            # FLOPs far exceed grad bytes): a few matmul passes per 1 MiB of
            # grads. The transfer plane is IO-loop-thread CPU; this runs on
            # the executor thread, which is exactly what overlap hides.
            acts = rng.standard_normal((768, 768)).astype(np.float32)
            col.barrier(group_name="ov_bench")
            t0 = time.perf_counter()
            for _ in range(steps):
                gs = BucketedGradSync(
                    "ov_bench",
                    bucket_bytes=(2 << 20) if overlap else (1 << 30))
                for _l in range(layers):
                    # The "backward" compute for one layer.
                    acts = np.tanh(acts @ acts.T) * 0.1 + 0.9 * acts
                    grad = np.full((layer_elems,), float(rank + 1), np.float32)
                    gs.push(grad)
                reduced = gs.finish()
                assert len(reduced) == layers
            return time.perf_counter() - t0

    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, [0, 1], group_name="ov_bench")
    settle()
    elapsed: dict = {}
    for overlap in (False, True, False, True):  # interleaved pairs
        got = rt.get([m.arm.remote(i, overlap, steps)
                      for i, m in enumerate(members)], timeout=600)
        elapsed.setdefault(overlap, []).append(max(got))
    on = min(elapsed[True])
    off = min(elapsed[False])
    col.destroy_collective_group("ov_bench")
    report(
        "train_step_overlap", steps, on, unit="steps/s",
        detail={
            "overlap_on_steps_s": round(steps / on, 2),
            "overlap_off_steps_s": round(steps / off, 2),
            "overlap_speedup_x": round(off / on, 3),
            "grad_mib_per_step": layers * layer_elems * 4 >> 20,
            "world": world,
        },
    )


def bench_wait_1k_refs(n_rounds):
    refs = [rt.put(i) for i in range(1000)]

    def run(k):
        for _ in range(k):
            rt.wait(refs, num_returns=len(refs), timeout=120)

    report("single_client_wait_1k_refs", n_rounds, timed(run, n_rounds))


def bench_pg_create_removal(n):
    def run(k):
        for _ in range(k):
            pg = rt.placement_group([{"CPU": 0.001}], strategy="PACK")
            pg.ready(timeout=30)
            rt.remove_placement_group(pg)

    report("placement_group_create_removal", n, timed(run, n))


# The degraded-network profile: 150 MB/s and +1 ms per raw frame — a thin
# cross-rack pipe instead of bare loopback. First choice is kernel netem on
# lo (shapes EVERY socket); when tc/CAP_NET_ADMIN/the netem qdisc is
# unavailable the in-process token-bucket pacer on the raw lane
# (Config.net_shape_spec -> rpc._net_pace) stands in and the profile is
# named degraded_sim so the JSON never passes one off as the other.
_DEGRADED_SHAPE = {"rate_mb_s": 150.0, "delay_ms": 1.0}


def _netem_setup() -> tuple[bool, str]:
    """Try to install a netem qdisc on loopback; (ok, skip_reason)."""
    import subprocess

    cmd = ["tc", "qdisc", "add", "dev", "lo", "root", "netem",
           "delay", f"{_DEGRADED_SHAPE['delay_ms']}ms",
           "rate", f"{int(_DEGRADED_SHAPE['rate_mb_s'] * 8)}mbit"]
    try:
        p = subprocess.run(cmd, capture_output=True, text=True, timeout=10)
    except FileNotFoundError:
        return False, "tc not installed"
    except Exception as e:  # noqa: BLE001 - probe must never kill the suite
        return False, f"tc probe failed: {e}"
    if p.returncode == 0:
        return True, ""
    return False, (p.stderr or p.stdout).strip() or f"tc exited {p.returncode}"


def _netem_teardown():
    import subprocess

    subprocess.run(["tc", "qdisc", "del", "dev", "lo", "root"],
                   capture_output=True, timeout=10)


def main():
    global _PROFILE
    # Each bench runs in a fresh session (the reference's microbenchmark suite
    # re-inits Ray per benchmark the same way): on a small host, worker
    # processes left by a previous bench would otherwise steal cycles from
    # the next measurement.
    benches = [
        (bench_raw_socket_floor, int(256 * 1024 * 1024 * SCALE)),
        (bench_actor_sync, int(1000 * SCALE)),
        (bench_actor_async, int(3000 * SCALE)),
        (bench_actor_nn_async, int(3000 * SCALE)),
        (bench_async_actor_sync, int(1000 * SCALE)),
        (bench_async_actor_async, int(3000 * SCALE)),
        (bench_tasks_sync_state_off, int(500 * SCALE)),
        (bench_tasks_sync, int(500 * SCALE)),
        (bench_tasks_async, int(2000 * SCALE)),
        (bench_streaming_items, int(3000 * SCALE)),
        (bench_overload_goodput_off, 1),
        (bench_overload_goodput, 1),
        (bench_get_calls, int(3000 * SCALE)),
        (bench_put_calls, int(3000 * SCALE)),
        (bench_put_gigabytes, int(512 * 1024 * 1024 * SCALE)),
        (bench_large_object_pull_legacy, int(64 * 1024 * 1024 * SCALE)),
        (bench_large_object_pull, int(64 * 1024 * 1024 * SCALE)),
        (bench_checkpoint_save_restore, int(64 * 1024 * 1024 * SCALE)),
        (bench_elastic_reshard, int(32 * 1024 * 1024 * SCALE)),
        (bench_allreduce_gbps, 4 * 1024 * 1024),
        (bench_train_step_overlap, max(2, int(8 * SCALE))),
        (bench_wait_1k_refs, max(1, int(5 * SCALE))),
        (bench_pg_create_removal, int(200 * SCALE)),
    ]
    import os

    # Advertise the machine's REAL core count (the reference's ray.init()
    # default): faking more CPUs than cores oversubscribes the host with
    # worker processes and measures scheduler thrash, not the runtime
    # (16 fake CPUs on this 1-core box: 591 tasks/s; 1 real CPU: 9099).
    ncpu = float(os.cpu_count() or 1)
    from ray_tpu.core.config import get_config

    for fn, n in benches:
        # The state A/B's OFF arm disables lifecycle events for its whole
        # session (head config propagates to workers at registration); the
        # QoS A/B's OFF arm disables adaptive admission, and the wire A/B's
        # legacy arm disables vectored sends, the same way.
        get_config().task_events_enabled = fn is not bench_tasks_sync_state_off
        get_config().qos_enabled = fn is not bench_overload_goodput_off
        get_config().raw_vectored_send = fn is not bench_large_object_pull_legacy
        rt.init(num_cpus=ncpu, object_store_memory=512 * 1024 * 1024)
        try:
            fn(n)
        finally:
            rt.shutdown()
            get_config().task_events_enabled = True
            get_config().qos_enabled = True
            get_config().raw_vectored_send = True

    # Degraded-network phase: the transfer-plane headlines re-measured on a
    # shaped loopback. Rows keep their metric names and are distinguished by
    # the profile key.
    netem_ok, skip_reason = _netem_setup()
    _PROFILE = "degraded_netem" if netem_ok else "degraded_sim"
    _PROFILE_DETAIL.update({
        "shape": dict(_DEGRADED_SHAPE),
        "netem": netem_ok,
        **({} if netem_ok else {"netem_skip_reason": skip_reason}),
    })
    if not netem_ok:
        print(json.dumps({"note": "netem unavailable; degraded profile uses "
                                  "in-process raw-lane pacing",
                          "reason": skip_reason}), flush=True)
    degraded = [
        (bench_large_object_pull, int(64 * 1024 * 1024 * SCALE)),
        (bench_allreduce_gbps, 4 * 1024 * 1024),
        (bench_elastic_reshard, int(32 * 1024 * 1024 * SCALE)),
    ]
    try:
        for fn, n in degraded:
            if not netem_ok:
                get_config().net_shape_spec = json.dumps(_DEGRADED_SHAPE)
            rt.init(num_cpus=ncpu, object_store_memory=512 * 1024 * 1024)
            try:
                fn(n)
            finally:
                rt.shutdown()
                get_config().net_shape_spec = ""
    finally:
        if netem_ok:
            _netem_teardown()
        _PROFILE = "quiet"
        _PROFILE_DETAIL.clear()
    with open("BENCH_CORE.json", "w") as f:
        json.dump(RESULTS, f, indent=1)


if __name__ == "__main__":
    main()
