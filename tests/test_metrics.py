"""Metrics pipeline + dashboard: Prometheus exposition correctness
(contiguous metric blocks, cumulative histogram buckets, label escaping),
multi-reporter merge semantics (counters sum, gauges stay per-reporter),
dropped-event accounting, and the dashboard JSON endpoints on a live
cluster. Mirrors the reference's metrics-agent/exporter tests
(python/ray/tests/test_metrics_agent.py) at the controller layer."""
import json
import re
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu.util.metrics import prometheus_text


# ---------------------------------------------------------------------------
# exposition-format round-trip parser
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$")


def parse_prometheus(text: str) -> dict:
    """Strict-enough exposition parser: every sample must sit inside the
    block opened by its metric's TYPE line (contiguity), values must parse
    as floats, and TYPE must not repeat. Returns
    {name: {"type": kind, "samples": [(sample_name, labels, value)]}}."""
    metrics: dict = {}
    current = None
    closed = set()
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            if current is not None and current != name:
                closed.add(current)
            assert name not in metrics, f"TYPE repeated for {name}"
            assert name not in closed, f"{name} block reopened (samples interleaved)"
            metrics[name] = {"type": kind, "samples": []}
            current = name
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        sname, labels, value = m.group(1), m.group(2) or "", m.group(3)
        base = sname
        for suffix in ("_bucket", "_sum", "_count"):
            if sname.endswith(suffix) and sname[: -len(suffix)] in metrics:
                base = sname[: -len(suffix)]
                break
        assert base == current, (
            f"sample {sname} appears inside {current}'s block (non-contiguous)"
        )
        float(value)
        metrics[base]["samples"].append((sname, labels, value))
    return metrics


# ---------------------------------------------------------------------------
# prometheus_text unit tests (no cluster)
# ---------------------------------------------------------------------------

def test_prometheus_empty_registry():
    text = prometheus_text([])
    assert parse_prometheus(text) == {}


def test_prometheus_groups_interleaved_metrics():
    # A merged-series list can interleave metrics (multi-reporter dict merge
    # order): the renderer must still emit contiguous blocks.
    series = [
        {"name": "alpha", "kind": "counter", "description": "", "tags": {"w": "1"}, "value": 1.0},
        {"name": "beta", "kind": "gauge", "description": "", "tags": {}, "value": 2.0},
        {"name": "alpha", "kind": "counter", "description": "", "tags": {"w": "2"}, "value": 3.0},
    ]
    parsed = parse_prometheus(prometheus_text(series))
    assert set(parsed) == {"raytpu_alpha", "raytpu_beta"}
    assert len(parsed["raytpu_alpha"]["samples"]) == 2


def test_prometheus_histogram_cumulative_buckets():
    series = [{
        "name": "lat", "kind": "histogram", "description": "d", "tags": {"k": "v"},
        "value": 0.0, "buckets": [0.1, 1.0, 10.0], "counts": [2, 3, 1, 4],
        "sum": 12.5, "n": 10,
    }]
    text = prometheus_text(series)
    parsed = parse_prometheus(text)
    samples = parsed["raytpu_lat"]["samples"]
    values = [float(v) for s, _l, v in samples if s.endswith("_bucket")]
    # Cumulative: non-decreasing, +Inf equals total observations in-range.
    assert values == sorted(values)
    assert values == [2.0, 5.0, 6.0, 10.0]
    count = [float(v) for s, _l, v in samples if s.endswith("_count")]
    assert count == [10.0]
    assert any('le="+Inf"' in l for _s, l, _v in samples)


def test_prometheus_label_escaping():
    series = [{
        "name": "esc", "kind": "gauge", "description": "multi\nline",
        "tags": {"path": 'a"b\\c\nnew'}, "value": 1.0,
    }]
    text = prometheus_text(series)
    parsed = parse_prometheus(text)
    (_s, labels, _v), = parsed["raytpu_esc"]["samples"]
    assert '\\"' in labels and "\\\\" in labels and "\\n" in labels
    assert "\n" not in labels  # raw newline would break line-oriented parsing


def test_prometheus_unobserved_histogram_renders_empty():
    # A histogram series that exists (bound) but never observed must not
    # crash the renderer and must stay internally consistent.
    series = [{"name": "h", "kind": "histogram", "description": "", "tags": {}, "value": 0.0}]
    parsed = parse_prometheus(prometheus_text(series))
    samples = parsed["raytpu_h"]["samples"]
    assert [float(v) for s, _l, v in samples if s.endswith("_count")] == [0.0]


# ---------------------------------------------------------------------------
# controller merge semantics (direct Controller instance, no sockets)
# ---------------------------------------------------------------------------

def _mk_controller(**cfg_overrides):
    from ray_tpu.core.config import Config
    from ray_tpu.core.controller import Controller

    cfg = Config()
    for k, v in cfg_overrides.items():
        setattr(cfg, k, v)
    return Controller(cfg)


def _series(name, kind, value, tags=None, **extra):
    return {"name": name, "kind": kind, "description": "", "tags": tags or {},
            "value": value, "ts": time.time(), **extra}


def test_merge_counters_sum_across_reporters():
    c = _mk_controller()
    c.handle_report_metrics(None, {"reporter": "w1", "series": [_series("reqs", "counter", 3.0)]})
    c.handle_report_metrics(None, {"reporter": "w2", "series": [_series("reqs", "counter", 4.0)]})
    merged = {r["name"]: r for r in c.handle_get_metrics(None, {}) if r["name"] == "reqs"}
    assert merged["reqs"]["value"] == 7.0


def test_merge_gauges_stay_per_reporter():
    # Regression: gauges used to be summed like counters — a per-process
    # memory fraction of 0.3 + 0.5 reported 0.8 cluster-wide.
    c = _mk_controller()
    c.handle_report_metrics(None, {"reporter": "w1aaaaaaaaaaaaaa", "series": [_series("mem.frac", "gauge", 0.3)]})
    c.handle_report_metrics(None, {"reporter": "w2bbbbbbbbbbbbbb", "series": [_series("mem.frac", "gauge", 0.5)]})
    gauges = [r for r in c.handle_get_metrics(None, {}) if r["name"] == "mem.frac"]
    assert sorted(g["value"] for g in gauges) == [0.3, 0.5]
    assert all("reporter" in g["tags"] for g in gauges)
    assert len({g["tags"]["reporter"] for g in gauges}) == 2


def test_merge_histograms_sum_matching_buckets_only():
    c = _mk_controller()
    h1 = _series("lat", "histogram", 0.0, buckets=[1, 2], counts=[1, 0, 0], sum=0.5, n=1)
    h2 = _series("lat", "histogram", 0.0, buckets=[1, 2], counts=[0, 2, 0], sum=3.0, n=2)
    h3 = _series("lat", "histogram", 0.0, buckets=[5, 10], counts=[1, 0, 0], sum=2.0, n=1)
    c.handle_report_metrics(None, {"reporter": "w1", "series": [h1]})
    c.handle_report_metrics(None, {"reporter": "w2", "series": [h2]})
    c.handle_report_metrics(None, {"reporter": "w3", "series": [h3]})
    hists = [r for r in c.handle_get_metrics(None, {}) if r["name"] == "lat"]
    assert len(hists) == 2  # mismatched boundaries keep their own series
    merged = next(h for h in hists if h["buckets"] == [1, 2])
    assert merged["counts"] == [1, 2, 0] and merged["n"] == 3


def test_controller_counts_dropped_events():
    c = _mk_controller(event_buffer_size=8)
    for i in range(40):
        c._event("tick", i=i)
    assert c.events_dropped > 0
    # Task-event buffer trims are counted too and surfaced via get_events.
    c.handle_report_task_events(None, {"events": [{"ts": 0.0, "kind": "x"}] * (4 * 8 + 1)})
    out = c.handle_get_events(None, {"with_stats": True})
    assert out["dropped"]["controller_events"] == c.events_dropped
    assert out["dropped"]["task_events"] == c.task_events_dropped > 0
    # Metrics view carries the same counters.
    dropped = [r for r in c.handle_get_metrics(None, {}) if r["name"] == "events_dropped_total"]
    assert dropped and all(r["kind"] == "counter" for r in dropped)


def test_trace_index_bounded():
    c = _mk_controller()
    for i in range(c.MAX_TRACES + 20):
        c.handle_report_task_events(None, {"events": [
            {"ts": float(i), "kind": "span", "worker": "w", "name": f"t{i}",
             "trace_id": f"trace{i:04d}", "span_id": "s", "parent_id": ""},
        ]})
    assert len(c.traces) == c.MAX_TRACES
    assert c.traces_evicted == 20  # whole-trace evictions are tallied
    listed = c.handle_list_traces(None, {"limit": 10})
    assert len(listed) == 10
    assert listed[0]["trace_id"] == f"trace{c.MAX_TRACES + 19:04d}"  # newest first
    # filter by name
    assert c.handle_list_traces(None, {"q": listed[0]["name"]})


# ---------------------------------------------------------------------------
# live cluster: dashboard endpoints + /metrics round-trip
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def dash(shared_ray):
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    port = start_dashboard(port=0)
    yield port
    stop_dashboard()


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return r.status, r.read()


def test_dashboard_api_cluster(shared_ray, dash):
    status, body = _get(dash, "/api/cluster")
    assert status == 200
    state = json.loads(body)
    assert state["nodes"] and any(n["state"] == "ALIVE" for n in state["nodes"].values())


def test_dashboard_api_events_surfaces_drops(shared_ray, dash):
    status, body = _get(dash, "/api/events")
    assert status == 200
    payload = json.loads(body)
    assert "events" in payload
    assert set(payload["dropped"]) == {
        "controller_events", "task_events", "worker_events", "traces_evicted",
        "tasks_evicted", "flight_dumps",
    }


def test_metrics_exposition_live_round_trip(shared_ray, dash):
    from ray_tpu.core import api

    @rt.remote
    def burn():
        return 1

    rt.get([burn.remote() for _ in range(4)], timeout=120)
    core = api._require_worker()
    core._run(core._report_metrics())  # driver series land immediately

    # Worker-side series (task latency) arrive with the worker's reporter
    # tick; poll /metrics until present.
    deadline = time.time() + 45
    parsed = {}
    while time.time() < deadline:
        status, body = _get(dash, "/metrics")
        assert status == 200
        parsed = parse_prometheus(body.decode())
        if "raytpu_task_exec_latency_s" in parsed:
            break
        time.sleep(1.0)
    # Acceptance: envelope-batch, bytes-on-wire, object-store and
    # task-latency series flow through reporter -> controller -> /metrics.
    for name in ("raytpu_rpc_envelope_messages", "raytpu_rpc_bytes",
                 "raytpu_object_store_ops", "raytpu_object_store_bytes",
                 "raytpu_task_exec_latency_s", "raytpu_scheduler_queue_depth",
                 "raytpu_scheduler_pending"):
        assert name in parsed, f"{name} missing from /metrics ({sorted(parsed)})"
    assert parsed["raytpu_task_exec_latency_s"]["type"] == "histogram"
    # Histogram buckets cumulative on the live output too.
    values = [float(v) for s, _l, v in parsed["raytpu_task_exec_latency_s"]["samples"]
              if s.endswith("_bucket")]
    assert values and values == sorted(values)


def test_dashboard_api_traces_endpoint(shared_ray, dash):
    from ray_tpu.util import tracing

    @rt.remote
    def traced():
        return 2

    with tracing.span("dash-trace-test") as s:
        rt.get(traced.remote(), timeout=60)
    from ray_tpu.core import api

    core = api._require_worker()
    core._run(core._flush_task_events())
    deadline = time.time() + 30
    found = []
    while time.time() < deadline and not found:
        _status, body = _get(dash, "/api/traces?q=dash-trace-test")
        found = [t for t in json.loads(body) if t["trace_id"] == s.trace_id]
        if not found:
            time.sleep(0.5)
    assert found, "trace not indexed on /api/traces"
    _status, body = _get(dash, f"/api/traces?id={s.trace_id}")
    events = json.loads(body)
    assert any(e.get("kind") == "span" and e.get("name") == "dash-trace-test" for e in events)
