"""Collective host API + pipeline parallelism tests (8-dev CPU mesh)."""
import numpy as np
import pytest

import ray_tpu as rt


def test_collective_ops(shared_ray):
    from ray_tpu import collective as col

    @rt.remote
    class Rank:
        def __init__(self, rank, world):
            self.rank, self.world = rank, world

        def run(self):
            col.init_collective_group(self.world, self.rank, group_name="g1")
            out = {}
            out["allreduce"] = col.allreduce(np.full((4,), self.rank + 1.0), group_name="g1")
            out["bcast"] = col.broadcast(
                np.arange(3.0) if self.rank == 0 else None, src_rank=0, group_name="g1"
            )
            out["allgather"] = col.allgather(np.array([self.rank]), group_name="g1")
            out["rs"] = col.reducescatter(
                np.stack([np.full((2,), float(self.rank))] * self.world), group_name="g1"
            )
            col.barrier(group_name="g1")
            if self.rank == 0:
                col.send(np.array([42.0]), dst_rank=1, group_name="g1")
            if self.rank == 1:
                out["recv"] = col.recv(src_rank=0, group_name="g1")
            return out

    world = 3
    ranks = [Rank.options(max_concurrency=2).remote(i, world) for i in range(world)]
    outs = rt.get([r.run.remote() for r in ranks], timeout=120)
    np.testing.assert_allclose(outs[0]["allreduce"], np.full((4,), 6.0))  # 1+2+3
    for o in outs:
        np.testing.assert_allclose(o["bcast"], np.arange(3.0))
        assert [int(x) for x in o["allgather"]] == [0, 1, 2]
    # reducescatter: rank r gets sum over contributors of their r-th shard
    np.testing.assert_allclose(outs[1]["rs"], np.full((2,), 0.0 + 1.0 + 2.0))
    np.testing.assert_allclose(outs[1]["recv"], np.array([42.0]))
    from ray_tpu.collective.collective import _GROUP_PREFIX

    rt.kill(rt.get_actor(_GROUP_PREFIX + "g1"))


def test_pipeline_matches_sequential():
    import jax
    import jax.numpy as jnp

    from ray_tpu.parallel import MeshSpec
    from ray_tpu.parallel.pipeline import pipeline_apply

    n_stages, n_micro, mb, d = 4, 8, 2, 16
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (n_stages, d, d)) * 0.3
    b = jax.random.normal(jax.random.PRNGKey(1), (n_stages, d)) * 0.1
    params = {"w": w, "b": b}
    x = jax.random.normal(jax.random.PRNGKey(2), (n_micro, mb, d))

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    # sequential oracle
    ref = x
    for i in range(n_stages):
        ref = jnp.tanh(ref @ w[i] + b[i])

    mesh = MeshSpec(stage=4, data=2).build()
    with mesh:
        out = jax.jit(
            lambda p, xx: pipeline_apply(stage_fn, p, xx, mesh=mesh)
        )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_pipeline_single_stage_fallback():
    import jax
    import jax.numpy as jnp

    from ray_tpu.parallel import MeshSpec
    from ray_tpu.parallel.pipeline import pipeline_apply

    w = jax.random.normal(jax.random.PRNGKey(0), (3, 8, 8)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 2, 8))
    params = {"w": w}

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"])

    mesh = MeshSpec(data=-1).build()
    out = pipeline_apply(stage_fn, params, x, mesh=mesh)
    ref = x
    for i in range(3):
        ref = jnp.tanh(ref @ w[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_create_collective_group_declarative(shared_ray):
    from ray_tpu import collective as col

    @rt.remote
    class Member(col.CollectiveActorMixin):
        def compute(self):
            return col.allreduce(np.array([1.0]), group_name="decl").tolist()

    members = [Member.options(max_concurrency=2).remote() for _ in range(2)]
    col.create_collective_group(members, 2, [0, 1], group_name="decl")
    outs = rt.get([m.compute.remote() for m in members], timeout=60)
    assert outs == [[2.0], [2.0]]
    col.destroy_collective_group("decl")


def test_world_size_mismatch_raises(shared_ray):
    from ray_tpu import collective as col

    col.init_collective_group(3, 0, group_name="ws")
    with pytest.raises(ValueError, match="world_size"):
        col.init_collective_group(2, 0, group_name="ws")
    col.destroy_collective_group("ws")


def test_gang_restart_gets_fresh_epoch(shared_ray):
    """A restarted gang (same name, same world) must not read mailboxes of a
    dead gang that died mid-collective."""
    from ray_tpu import collective as col

    @rt.remote
    class Member(col.CollectiveActorMixin):
        def half_collective(self, rank):
            # Rank 0 contributes to allreduce round 0 but the round never
            # completes (rank 1 stays out) — simulates a gang dying
            # mid-collective with a 99 stranded in the epoch-1 mailbox.
            g = col.collective._group("gr")
            if rank == 0:
                box = rt.get(
                    g.actor.exchange.remote(
                        f"e{g.ensure_epoch()}:allreduce:0", rank, np.array([99.0]), 0.05
                    ),
                    timeout=30,
                )
                assert box is None, "half-collective must not complete"
            return True

        def full_collective(self):
            return col.allreduce(np.array([1.0]), group_name="gr").tolist()

    gang1 = [Member.options(max_concurrency=2).remote() for _ in range(2)]
    col.create_collective_group(gang1, 2, [0, 1], group_name="gr")
    rt.get([m.half_collective.remote(i) for i, m in enumerate(gang1)], timeout=60)
    for m in gang1:
        rt.kill(m)

    gang2 = [Member.options(max_concurrency=2).remote() for _ in range(2)]
    col.create_collective_group(gang2, 2, [0, 1], group_name="gr")
    outs = rt.get([m.full_collective.remote() for m in gang2], timeout=60)
    # With stale epoch-1 mailboxes the dead gang's 99s would leak in; the
    # fresh epoch must yield exactly 1+1.
    assert outs == [[2.0], [2.0]]
    col.destroy_collective_group("gr")
