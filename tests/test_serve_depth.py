"""Serve depth: model multiplexing (@serve.multiplexed + sticky routing),
binary RPC ingress (gRPC-proxy equivalent), event-driven waits (reference:
multiplex.py, proxy.py:534 gRPCProxy, long_poll.py)."""
import pickle
import socket
import time

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_cluster():
    rt.init(num_cpus=16)
    serve.start(proxy=False)
    yield rt
    serve.shutdown()
    rt.shutdown()


def test_multiplexed_lru_and_sticky_routing(serve_cluster):
    @serve.deployment(num_replicas=2, max_ongoing_requests=8)
    class MuxModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str):
            self.loads.append(model_id)
            return {"model": model_id, "replica": id(self)}

        def __call__(self, x):
            mid = serve.get_multiplexed_model_id()
            model = self.get_model(mid)
            return {"model": model["model"], "replica": model["replica"], "x": x}

        def load_count(self):
            return len(self.loads)

    handle = serve.run(MuxModel.bind(), name="mux", http=False)
    # 12 calls for one model: loaded ONCE (sticky routing + cache).
    outs = [handle.options(multiplexed_model_id="m1").remote(i).result(timeout=60)
            for i in range(12)]
    assert all(o["model"] == "m1" for o in outs)
    assert len({o["replica"] for o in outs}) == 1, "model m1 bounced between replicas"
    total_loads = sum(
        r.result(timeout=60) if hasattr(r, "result") else r
        for r in [handle.load_count.remote() for _ in range(1)]
    )
    # Exactly one load of m1 across the pool (other replica untouched).
    # (load_count hits ONE replica; sum over several calls covers both.)
    counts = [handle.load_count.remote().result(timeout=60) for _ in range(8)]
    assert max(counts) >= 1 and sum(counts) >= 1
    # LRU eviction: 3 models through a 2-model cache reloads the evicted one.
    for mid in ("a", "b", "c", "a"):
        out = handle.options(multiplexed_model_id=mid).remote(0).result(timeout=60)
        assert out["model"] == mid
    serve.delete("mux")


def test_get_multiplexed_model_id_empty_without_tag(serve_cluster):
    @serve.deployment
    def plain(x):
        return serve.get_multiplexed_model_id()

    handle = serve.run(plain.bind(), name="plain_mux", http=False)
    assert handle.remote(1).result(timeout=60) == ""
    serve.delete("plain_mux")


def test_binary_rpc_ingress(serve_cluster):
    @serve.deployment
    class Calc:
        def __call__(self, a, b=0):
            return {"sum": a + b}

        def mul(self, a, b):
            return a * b

    serve.run(Calc.bind(), name="rpc_app", route_prefix="/calc")
    port = serve.rpc_port()

    from ray_tpu.core import rpc as _rpc

    def rpc(app, dep, method, *args, **kwargs):
        s = socket.create_connection(("127.0.0.1", port), timeout=30)
        frame = pickle.dumps((app, dep, method, args, kwargs), protocol=5)
        frame = _rpc.frame_tag(frame) + frame  # session-authenticated ingress
        s.sendall(len(frame).to_bytes(4, "little") + frame)
        n = int.from_bytes(_readexact(s, 4), "little")
        reply = _readexact(s, n)
        if _rpc.get_auth_token():
            tag, reply = reply[:_rpc.FRAME_TAG_LEN], reply[_rpc.FRAME_TAG_LEN:]
            assert _rpc.frame_verify(tag, reply)
        status, payload = pickle.loads(reply)
        s.close()
        return status, payload

    status, out = rpc("rpc_app", "Calc", "__call__", 40, b=2)
    assert (status, out) == ("ok", {"sum": 42})
    status, out = rpc("rpc_app", "Calc", "mul", 6, 7)
    assert (status, out) == ("ok", 42)
    status, out = rpc("rpc_app", "Calc", "nope", 1)
    assert status == "err"
    serve.delete("rpc_app")


def _readexact(s, n):
    buf = b""
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("short read")
        buf += chunk
    return buf


def test_job_wait_event_driven(serve_cluster):
    """wait_until_finished returns promptly after the entrypoint exits (one
    blocking supervisor call, no 250ms polling)."""
    from ray_tpu.job.manager import JobSubmissionClient

    client = JobSubmissionClient()
    jid = client.submit_job("sleep 0.5; echo done")
    t0 = time.time()
    status = client.wait_until_finished(jid, timeout_s=60)
    assert status == "SUCCEEDED"
    assert time.time() - t0 < 30
    assert "done" in client.get_job_logs(jid)
