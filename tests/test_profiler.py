"""Continuous profiling & cost-attribution plane (ISSUE 19): always-on
wall-clock sampler, merged cluster flamegraphs, alert-triggered capture.

Layers, cheapest first:
  * pure units (no cluster): plane-attribution rule on fabricated frame
    records, shared stack renderer (health thread_dump rides it), fold
    accumulator bounds + counted evictions + the truthful-totals
    invariant, N-fake-worker merge into one tree with proc dedup,
    renderers (collapsed text / d3 tree / leaf self-time), the capture
    rate limiter (one capture per burn alert), local_fold dispatch;
  * live sampler in this process: hot-frame detection of a synthetic spin
    thread, epoch-ring bounds, per-trace scoping through the tracing
    hook, capture sessions (armed and disarmed, session bound typed),
    interleaved armed-vs-disabled overhead pairs, device profiling
    degrading typed-and-loud on this CPU-only host, flight dumps carrying
    their own flamegraph;
  * one live cluster: a traced serve request whose per-trace profile is
    retrievable from /api/profile and attributes its exec hop to the
    right plane buckets, plus the merged cluster flamegraph and the
    ?summary=1 rollup `raytpu status` reads.
"""
from __future__ import annotations

import json
import re
import threading
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu import serve
from ray_tpu.obs import profiler
from ray_tpu.obs import stacks


def _rec(*shorts):
    """Fabricated frame records (root first): one frame per short path."""
    return [(f"f{i}", s, 10 + i) for i, s in enumerate(shorts)]


def _fake_fold(proc, stack_counts, plane="app"):
    n = sum(stack_counts.values())
    return {"proc": proc, "hz": 19.0, "samples": n, "samples_dropped": 0,
            "stacks_evicted": 0, "stacks": dict(stack_counts),
            "planes": {plane: n}}


def _spin_thread(name="prof-spin"):
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(i * i for i in range(2000))

    t = threading.Thread(target=spin, name=name, daemon=True)
    t.start()
    return stop


# ---------------------------------------------------------------------------
# plane attribution + the shared stack renderer (no sampler)
# ---------------------------------------------------------------------------

def test_plane_attribution_buckets():
    # First ray_tpu frame from the leaf decides the plane.
    assert stacks.plane_of(_rec("app.py", "ray_tpu/serve/proxy.py")) == "serve"
    assert stacks.plane_of(_rec("ray_tpu/serve/proxy.py", "helper.py")) == "serve"
    assert stacks.plane_of(_rec("ray_tpu/collective/ring.py")) == "collective"
    assert stacks.plane_of(_rec("ray_tpu/data/dataset.py")) == "data"
    # The wire is its own cost center.
    assert stacks.plane_of(_rec("ray_tpu/core/worker.py", "ray_tpu/core/rpc.py")) == "rpc"
    # worker.py with user frames above it = user code under the executor.
    assert stacks.plane_of(_rec("ray_tpu/core/worker.py", "usercode.py")) == "exec"
    # worker.py as the leaf itself = the runtime's own bookkeeping.
    assert stacks.plane_of(_rec("ray_tpu/core/worker.py")) == "core"
    # The serve replica's user-handler dispatch works the same way: a
    # deployment handler burning above replica.py is the request's exec
    # hop; replica.py at the leaf is serve machinery.
    assert stacks.plane_of(
        _rec("ray_tpu/serve/replica.py", "my_deployment.py")) == "exec"
    assert stacks.plane_of(_rec("ray_tpu/serve/replica.py")) == "serve"
    # Top-level module -> module name.
    assert stacks.plane_of(_rec("ray_tpu/dashboard.py")) == "dashboard"
    # No ray_tpu frame anywhere -> app; empty stack -> app.
    assert stacks.plane_of(_rec("mine.py", "yours.py")) == "app"
    assert stacks.plane_of([]) == "app"
    # A leaf parked in a stdlib wait primitive is idle — even when ray_tpu
    # frames sit below it (a pool thread waiting for work is capacity).
    assert stacks.plane_of(_rec("ray_tpu/serve/proxy.py", "threading.py")) == "idle"
    assert stacks.plane_of(_rec("selectors.py")) == "idle"
    # ...but a ray_tpu file that happens to be NAMED like one is not.
    assert stacks.plane_of(_rec("ray_tpu/queue.py")) == "queue"


def test_shared_frame_renderer_and_paths():
    assert stacks.shorten_path("/v/site-packages/ray_tpu/serve/proxy.py") \
        == "ray_tpu/serve/proxy.py"
    assert stacks.shorten_path("/usr/lib/python3.10/threading.py") == "threading.py"
    assert stacks.format_frame("go", "ray_tpu/core/rpc.py", 7) \
        == "go (ray_tpu/core/rpc.py:7)"
    recs = _rec("a.py", "b.py")
    assert stacks.collapse(recs) == "f0 (a.py:10);f1 (b.py:11)"


def test_health_thread_dump_rides_shared_renderer():
    # Satellite: ONE stack formatter — the loop-lag thread dump names
    # frames exactly like the flamegraph does, so they cross-reference.
    from ray_tpu.obs import health

    dumps = health.thread_dump(max_frames=8)
    mine = [d for d in dumps
            if any("test_health_thread_dump_rides_shared_renderer" in line
                   for line in d["stack"])]
    assert mine, "this thread's stack missing from the dump"
    pat = re.compile(r".+ \(.+:\d+\)$")
    assert all(pat.match(line) for d in dumps for line in d["stack"])


# ---------------------------------------------------------------------------
# fold accumulator + merge: bounds, counted evictions, truthful totals
# ---------------------------------------------------------------------------

def _check_invariant(fold):
    assert fold["samples"] - fold["samples_dropped"] == sum(fold["stacks"].values())
    assert fold["samples"] == sum(fold["planes"].values())


def test_profile_bound_counts_evictions():
    p = profiler.Profile(max_stacks=2)
    p.add("a;b", "serve", 5)
    p.add("a;c", "serve", 3)
    p.add("a;d", "rpc", 2)   # table full: counted, never silent
    p.add("a;b", "serve", 1)  # existing stacks still accumulate
    f = p.fold()
    assert f["stacks"] == {"a;b": 6, "a;c": 3}
    assert f["stacks_evicted"] == 1 and f["samples_dropped"] == 2
    assert f["samples"] == 11
    _check_invariant(f)


def test_merge_folds_n_workers_one_tree():
    folds = [_fake_fold(f"w{i}", {"main;hot": 10 + i, f"main;only{i}": 1})
             for i in range(8)]
    merged = profiler.merge_folds(folds, max_stacks=1024)
    assert merged["procs"] == [f"w{i}" for i in range(8)]
    assert merged["stacks"]["main;hot"] == sum(10 + i for i in range(8))
    assert merged["samples"] == sum(f["samples"] for f in folds)
    _check_invariant(merged)
    # The tree renderer agrees with the fold: root value == kept samples.
    tree = profiler.to_tree(merged)
    assert tree["name"] == "all"
    assert tree["value"] == sum(merged["stacks"].values())
    main = tree["children"][0]
    assert main["name"] == "main" and main["value"] == tree["value"]
    # Collapsed text round-trips counts, hottest first.
    lines = profiler.to_collapsed(merged).splitlines()
    assert lines[0] == f"main;hot {merged['stacks']['main;hot']}"
    assert len(lines) == len(merged["stacks"])


def test_merge_folds_bounded_keeps_hot_path():
    folds = [_fake_fold(f"w{i}", {"hot;path": 100, f"cold;{i}": 1})
             for i in range(4)]
    merged = profiler.merge_folds(folds, max_stacks=2)
    assert "hot;path" in merged["stacks"] and merged["stacks"]["hot;path"] == 400
    assert len(merged["stacks"]) == 2
    assert merged["stacks_evicted"] >= 3  # displaced cold stacks are counted
    _check_invariant(merged)


def test_merge_folds_dedups_by_proc():
    # In-process topologies (head==driver) share one sampler: the same
    # proc's fold arriving via two fan-out legs must count ONCE.
    f = _fake_fold("headproc", {"a;b": 7})
    merged = profiler.merge_folds([f, dict(f)], max_stacks=64)
    assert merged["procs"] == ["headproc"]
    assert merged["samples"] == 7 and merged["stacks"]["a;b"] == 7
    # Garbage rows (error strings from dead daemons) are skipped.
    merged = profiler.merge_folds([f, "node x: timeout", None], max_stacks=64)
    assert merged["samples"] == 7


def test_top_frames_and_plane_split():
    fold = {"stacks": {"a;b;leaf": 6, "c;leaf": 4, "c;other": 1},
            "planes": {"serve": 8, "idle": 2}, "samples": 11,
            "samples_dropped": 0, "stacks_evicted": 0}
    assert profiler.top_frames(fold, 2) == [("leaf", 10), ("other", 1)]
    split = profiler.plane_split(fold)
    assert split[0] == ("serve", 0.8) and split[1] == ("idle", 0.2)


# ---------------------------------------------------------------------------
# capture rate limiter: one capture per burn alert, like flight dumps
# ---------------------------------------------------------------------------

def test_capture_limiter_once_per_alert():
    lim = profiler.CaptureLimiter(min_interval_s=2.0)
    assert lim.allow("slo-a", now=100.0)
    # The SAME objective re-alerting inside the window: suppressed, counted.
    assert not lim.allow("slo-a", now=100.5)
    assert not lim.allow("slo-a", now=101.9)
    assert lim.suppressed == 2
    # A different objective is its own budget.
    assert lim.allow("slo-b", now=100.5)
    # Past the window the same objective may capture again.
    assert lim.allow("slo-a", now=102.1)


def test_capture_limiter_key_table_bounded():
    lim = profiler.CaptureLimiter(min_interval_s=1.0)
    for i in range(400):
        lim.allow(f"obj-{i}", now=50.0)
    assert lim.keys_evicted >= 400 - 256 - 1
    assert len(lim._last) <= 256


# ---------------------------------------------------------------------------
# live sampler: hot frames, ring, traces, sessions, overhead
# ---------------------------------------------------------------------------

def test_sampler_finds_synthetic_spin_thread():
    s = profiler.Sampler(hz=97.0, proc="unit-hot")
    stop = _spin_thread("unit-hot-spin")
    s.start()
    try:
        deadline = time.time() + 15
        fold = {}
        while time.time() < deadline:
            fold = s.total_fold()
            hot = {st: n for st, n in fold["stacks"].items() if "spin" in st}
            if sum(hot.values()) >= 5:
                break
            time.sleep(0.1)
        assert hot and sum(hot.values()) >= 5, \
            f"spin thread never became hot: {list(fold['stacks'])[:5]}"
        _check_invariant(fold)
        assert fold["proc"] == "unit-hot" and fold["hz"] == 97.0
        # The spin frames render through the shared formatter.
        assert any(re.search(r"spin \(.+:\d+\)", st) for st in hot)
        # Plane attribution: the spin thread is non-ray_tpu code -> "app".
        assert fold["planes"].get("app", 0) >= 5
    finally:
        stop.set()
        s.stop()


def test_epoch_ring_bounded_and_counted():
    s = profiler.Sampler(hz=97.0, proc="unit-ring", epoch_s=0.25,
                         window_epochs=2)
    stop = _spin_thread("unit-ring-spin")
    s.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            st = s.status()
            if st["epochs_dropped"] > 0:
                break
            time.sleep(0.1)
        st = s.status()
        assert st["epochs"] <= 2
        assert st["epochs_dropped"] > 0, "ring overflow was never counted"
        # window_fold sees ring + live epoch; a tiny window sees less.
        wide = s.window_fold(60.0)
        assert wide["samples"] > 0 and wide["window_s"] == 60.0
        _check_invariant(wide)
    finally:
        stop.set()
        s.stop()


def test_per_trace_scoping_through_tracing_hook():
    # arm() wires the module sampler into tracing.activate/deactivate —
    # the exact path a traced exec span takes on a worker.
    from ray_tpu.util import tracing

    profiler.arm(hz=97.0, proc="unit-trace")
    noise = _spin_thread("unit-trace-noise")
    try:
        tok = tracing.activate(("trace-prof-1", "span-1"))
        assert tok is not None
        deadline = time.time() + 15
        while time.time() < deadline:
            if profiler.trace_fold("trace-prof-1")["samples"] >= 3:
                break
            sum(i * i for i in range(20000))  # visible work on THIS thread
        tracing.deactivate(tok)
        tf = profiler.trace_fold("trace-prof-1")
        assert tf["trace_id"] == "trace-prof-1" and tf["samples"] >= 3
        # The noise thread's frames never leak into the trace's fold.
        assert not any("unit-trace-noise" in st or "spin" in st
                       for st in tf["stacks"])
        # After deactivate the thread stops accruing to the trace.
        before = tf["samples"]
        time.sleep(0.2)
        assert profiler.trace_fold("trace-prof-1")["samples"] == before
        # Unknown traces are empty folds, not errors.
        assert profiler.trace_fold("no-such-trace")["samples"] == 0
    finally:
        noise.set()
        profiler.disarm()


def test_trace_registry_bounded_and_counted():
    s = profiler.Sampler(hz=0.0, proc="unit-bound", max_traces=8)
    for i in range(13):
        s.thread_trace_end(s.thread_trace_begin(f"tr-{i}"))
    st = s.status()
    assert st["traces"] <= 8
    assert st["traces_evicted"] >= 5


def test_capture_sessions_armed_and_disarmed():
    s = profiler.Sampler(hz=97.0, proc="unit-cap")
    stop = _spin_thread("unit-cap-spin")
    try:
        # Disarmed: capture() self-samples in the calling thread.
        cap = s.capture(seconds=0.3, hz=97.0)
        assert cap["samples"] > 0 and cap["duration_s"] == pytest.approx(0.3)
        assert any("spin" in st for st in cap["stacks"])
        _check_invariant(cap)
        # Armed: the background thread feeds the session accumulator.
        s.start()
        cap = s.capture(seconds=0.3)
        assert cap["samples"] > 0
        assert any("spin" in st for st in cap["stacks"])
        assert s.status()["sessions_started"] == 2
    finally:
        stop.set()
        s.stop()


def test_capture_session_bound_is_typed():
    s = profiler.Sampler(hz=0.0, proc="unit-busy")
    sids = [s.session_begin("cpu") for _ in range(profiler.MAX_SESSIONS)]
    with pytest.raises(profiler.ProfilerBusy, match="capture sessions"):
        s.session_begin("cpu")
    for sid in sids:
        s.session_end(sid)
    assert s.session_begin("cpu") is not None  # freed slots reopen


def test_local_fold_dispatch():
    profiler.arm(hz=97.0, proc="unit-dispatch")
    try:
        st = profiler.local_fold({"status": 1})
        assert st["armed"] and "occupancy" in st
        tf = profiler.local_fold({"trace_id": "nope"})
        assert tf["trace_id"] == "nope" and tf["samples"] == 0
        wf = profiler.local_fold({"window_s": 30.0})
        assert wf["window_s"] == 30.0
        cap = profiler.local_fold({"seconds": 0.1})
        assert cap["duration_s"] == pytest.approx(0.1)
        assert "stacks" in profiler.local_fold({})
    finally:
        profiler.disarm()


def test_aggregate_status_rollup():
    rows = [
        {"proc": "a", "armed": True, "hz": 19.0, "samples": 10,
         "samples_dropped": 1, "stacks": 5, "max_stacks": 10,
         "occupancy": 0.5, "traces": 2, "sessions": [{"kind": "cpu"}]},
        {"proc": "b", "armed": False, "hz": 7.0, "samples": 4,
         "samples_dropped": 0, "stacks": 9, "max_stacks": 10,
         "occupancy": 0.9, "traces": 0, "sessions": []},
        "node x: timeout",  # error rows never poison the rollup
    ]
    agg = profiler.aggregate_status(rows)
    assert agg["procs"] == 2 and agg["armed"] == 1
    assert agg["hz"] == 19.0 and agg["occupancy"] == 0.9  # worst occupancy
    assert agg["samples"] == 14 and agg["samples_dropped"] == 1
    assert agg["sessions"] == 1


def test_armed_idle_overhead_interleaved():
    """Interleaved armed-vs-disabled pairs on a pure-python workload. The
    authoritative <2% gate is bench_core's profiler_overhead row (best-of
    interleaved halves on the RPC path); this asserts the mechanism with CI
    slack — an always-on sampler that costs double digits is a regression
    whatever the weather."""
    def ops(reps):
        t0 = time.perf_counter()
        for _ in range(reps):
            sum(i * i for i in range(500))
        return reps / (time.perf_counter() - t0)

    reps = 400
    ops(reps)  # warm
    s = profiler.Sampler(hz=19.0, proc="unit-ovh")
    on, off = [], []
    try:
        for _ in range(5):
            s.start()
            on.append(ops(reps))
            s.stop()
            off.append(ops(reps))
    finally:
        s.stop()
    best_on, best_off = max(on), max(off)
    overhead = best_off / best_on - 1.0
    assert overhead < 0.10, \
        f"armed-but-idle sampler overhead {overhead:.1%} (on={best_on:.0f} " \
        f"off={best_off:.0f} ops/s)"


# ---------------------------------------------------------------------------
# device profiling: typed-and-loud degrade on this CPU-only host
# ---------------------------------------------------------------------------

def test_device_profiling_typed_on_cpu(tmp_path):
    from ray_tpu.util import tracing

    with pytest.raises(profiler.DeviceProfilerUnavailable, match="device_capture"):
        with profiler.device_capture(str(tmp_path)):
            pass
    # The public API routes through the same session gate and raises the
    # same typed error — no AttributeError mid-capture (satellite 1).
    with pytest.raises(profiler.DeviceProfilerUnavailable):
        with tracing.profile_tpu(str(tmp_path)):
            pass
    with pytest.raises(profiler.DeviceProfilerUnavailable, match="device_server"):
        tracing.profile_server()
    # The failed session never leaks a slot.
    assert not profiler.status()["sessions"]


def test_device_memory_records_gated_on_cpu():
    # jax on a CPU backend reports no memory_stats: the gauge list is empty
    # (and on hosts that never imported jax, nothing gets imported).
    recs = profiler.device_memory_records(ts=123.0)
    assert recs == [] or all(r["name"] == "tpu.device.bytes_in_use"
                             for r in recs)


# ---------------------------------------------------------------------------
# flight dumps carry their own flamegraph
# ---------------------------------------------------------------------------

def test_flight_dump_carries_profile_window(tmp_path):
    from ray_tpu.obs import flight

    profiler.arm(hz=97.0, proc="unit-flight")
    stop = _spin_thread("unit-flight-spin")
    try:
        deadline = time.time() + 15
        while (time.time() < deadline
               and profiler.window_fold(60.0)["samples"] < 3):
            time.sleep(0.1)
        rec = flight.FlightRecorder(capacity=16)
        rec.configure(proc_id="unit-flight", dump_dir=str(tmp_path))
        rec.record("unit.tick")
        path = rec.dump("manual", reason="profiler round trip")
        header, _events = flight.load_dump(path)
        prof = header.get("profile")
        assert prof and prof["samples"] >= 3, \
            "incident dump is missing its flamegraph"
        _check_invariant(prof)
    finally:
        stop.set()
        profiler.disarm()

    # Disarmed process: dumps simply omit the profile — never an error.
    rec = flight.FlightRecorder(capacity=4)
    rec.configure(proc_id="unit-flight2", dump_dir=str(tmp_path))
    rec.record("unit.tick")
    header, _ = flight.load_dump(rec.dump("manual"))
    assert "profile" not in header


# ---------------------------------------------------------------------------
# live cluster: traced request -> per-trace profile on /api/profile
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def prof_cluster():
    from ray_tpu.core.api import Cluster, init
    from ray_tpu.core.config import Config

    cfg = Config().apply_env()
    cfg.profile_hz = 97.0  # fast ticks so a ~300ms handler lands samples
    cluster = Cluster(initialize_head=False, config=cfg)
    cluster.add_node(num_cpus=16)
    init(address=cluster.address, config=cfg)
    serve.start(proxy=True)

    @serve.deployment
    class Burner:
        def __call__(self, request):
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 0.3:  # sampled, visible burn
                sum(i * i for i in range(1000))
            return {"ok": True}

    serve.run(Burner.bind(), name="prof_app", route_prefix="/prof")
    from ray_tpu import dashboard

    dash_port = dashboard.start_dashboard(port=0)
    yield serve.http_port(), dash_port
    dashboard.stop_dashboard()
    serve.shutdown()
    rt.shutdown()
    cluster.shutdown()


def _api(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=90) as r:
        assert r.status == 200
        ctype = r.headers.get("Content-Type", "")
        body = r.read()
    if ctype.startswith("application/json"):
        return json.loads(body)
    return body.decode()


def test_traced_request_profile_on_api(prof_cluster):
    http_port, dash_port = prof_cluster
    req = urllib.request.Request(f"http://127.0.0.1:{http_port}/prof",
                                 headers={"x-trace": "1"})
    with urllib.request.urlopen(req, timeout=90) as resp:
        assert resp.status == 200

    from ray_tpu.core import api as _api_mod

    core = _api_mod._require_worker()
    deadline = time.time() + 45
    trace_id = None
    while time.time() < deadline and trace_id is None:
        traces = core._run(core.controller.call(
            "list_traces", {"q": "serve.request"}))
        if traces:
            trace_id = traces[0]["trace_id"]
            break
        time.sleep(0.5)
    assert trace_id, "the traced request never reached the trace index"

    # The request's own flamegraph is retrievable from /api/profile, and
    # its exec hop lands in the right plane bucket: the handler's burn loop
    # is user code under the executor -> "exec".
    deadline = time.time() + 60
    fold = {}
    while time.time() < deadline:
        fold = _api(dash_port, f"/api/profile?trace={trace_id}")
        if fold.get("samples", 0) >= 2:
            break
        time.sleep(0.5)
    assert fold.get("samples", 0) >= 2, \
        f"per-trace profile never materialised: {fold}"
    assert fold.get("trace_id") == trace_id
    assert fold["planes"].get("exec", 0) >= 1, \
        f"exec hop not attributed: planes={fold.get('planes')}"
    _check_invariant(fold)


def test_cluster_flamegraph_and_summary_on_api(prof_cluster):
    http_port, dash_port = prof_cluster
    with urllib.request.urlopen(f"http://127.0.0.1:{http_port}/prof",
                                timeout=90) as resp:
        assert resp.status == 200

    fold = _api(dash_port, "/api/profile?window=120")
    assert fold["samples"] > 0 and fold["stacks"]
    # Merged across processes: the driver/head plus worker subprocesses.
    assert len(fold["procs"]) >= 2, fold["procs"]
    _check_invariant(fold)

    # Collapsed-stack text renders the same fold, hottest first.
    text = _api(dash_port, "/api/profile?window=120&fmt=collapsed")
    assert isinstance(text, str) and text
    first = text.splitlines()[0]
    assert re.match(r"^.+ \d+$", first), first

    # The ?summary=1 rollup backs the `raytpu status` one-liner.
    summary = _api(dash_port, "/api/profile?summary=1")
    agg = summary["aggregate"]
    assert agg["procs"] >= 2 and agg["armed"] >= 2
    assert agg["hz"] == pytest.approx(97.0)
    assert 0.0 <= agg["occupancy"] <= 1.0

    # Incident registry is reachable (empty here — nothing alerted).
    inc = _api(dash_port, "/api/profile?incidents=1")
    assert "incidents" in inc and "suppressed" in inc
