"""Multi-node scheduling, placement groups, label selectors, fault tolerance.

Reference analogue: python/ray/tests with ray_start_cluster fixtures
(cluster_utils.Cluster, conftest.py:686) — multi-node semantics on one
machine with fake resources.
"""
import time

import pytest

import ray_tpu as rt
from ray_tpu.core.task_spec import SchedulingStrategy


@pytest.fixture(scope="module")
def two_node_ray():
    from ray_tpu.core.api import Cluster, init, shutdown

    cluster = Cluster(initialize_head=False)
    n1 = cluster.add_node(num_cpus=2, resources={"gadget": 1.0}, labels={"zone": "a"})
    n2 = cluster.add_node(num_cpus=2, resources={"widget": 1.0}, labels={"zone": "b"})
    init(address=cluster.address)
    yield cluster, n1, n2
    shutdown()
    # shutdown() detaches the DRIVER only — an address-connected session
    # never owns the cluster it dialed. Leaving this cluster running leaked
    # its service thread + minted auth token into every later module (the
    # round-5 test_start_cli order sensitivity); conftest's module-boundary
    # sentinel now fails any module that forgets this line.
    cluster.shutdown()


def test_custom_resource_routing(two_node_ray):
    cluster, n1, n2 = two_node_ray

    @rt.remote(resources={"gadget": 1.0})
    def where():
        return rt.get_runtime_context().node_id

    assert rt.get(where.remote(), timeout=60) == n1.node_id

    @rt.remote(resources={"widget": 1.0})
    def where2():
        return rt.get_runtime_context().node_id

    assert rt.get(where2.remote(), timeout=60) == n2.node_id


def test_label_selector_scheduling(two_node_ray):
    cluster, n1, n2 = two_node_ray

    @rt.remote(label_selector={"zone": "b"})
    def where():
        return rt.get_runtime_context().node_id

    assert rt.get(where.remote(), timeout=60) == n2.node_id


def test_node_affinity(two_node_ray):
    cluster, n1, n2 = two_node_ray

    @rt.remote
    def where():
        return rt.get_runtime_context().node_id

    strat = SchedulingStrategy(kind="NODE_AFFINITY", node_id=n1.node_id)
    ref = where.options(scheduling_strategy=strat).remote()
    assert rt.get(ref, timeout=60) == n1.node_id


def test_infeasible_task_raises(two_node_ray):
    @rt.remote(num_cpus=1000)
    def huge():
        return 1

    with pytest.raises(Exception):
        rt.get(huge.remote(), timeout=10)


def test_placement_group_strict_spread(two_node_ray):
    cluster, n1, n2 = two_node_ray
    pg = rt.placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.ready(timeout=10)
    nodes = pg.bundle_nodes()
    assert len(set(nodes)) == 2

    @rt.remote
    def where():
        return rt.get_runtime_context().node_id

    ref = where.options(placement_group=pg, placement_group_bundle_index=0).remote()
    assert rt.get(ref, timeout=60) == nodes[0]
    rt.remove_placement_group(pg)


def _settle(expect_cpu):
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if rt.available_resources().get("CPU", 0) >= expect_cpu:
            return
        time.sleep(0.1)


def test_placement_group_pack(two_node_ray):
    _settle(4)  # wait for lingering task leases to be reaped
    pg = rt.placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=10)
    nodes = pg.bundle_nodes()
    assert len(set(nodes)) == 1  # both fit on one node
    rt.remove_placement_group(pg)


def test_placement_group_pending_until_capacity(two_node_ray):
    cluster, n1, n2 = two_node_ray
    # Demand exceeding the cluster -> PENDING, then satisfied by a new node.
    pg = rt.placement_group([{"CPU": 4}], strategy="PACK")
    assert not pg.ready(timeout=0.5)
    n3 = cluster.add_node(num_cpus=4)
    assert pg.ready(timeout=10)
    rt.remove_placement_group(pg)
    cluster.remove_node(n3)


def test_actor_restart_on_worker_death(two_node_ray):
    @rt.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.lives = 1

        def pid(self):
            import os

            return os.getpid()

        def die(self):
            import os

            os._exit(1)

    p = Phoenix.remote()
    pid1 = rt.get(p.pid.remote(), timeout=60)
    try:
        rt.get(p.die.remote(), timeout=10)
    except Exception:
        pass
    # The controller should restart the actor on a fresh worker.
    deadline = time.monotonic() + 30
    pid2 = None
    while time.monotonic() < deadline:
        try:
            pid2 = rt.get(p.pid.remote(), timeout=10)
            break
        except Exception:
            time.sleep(0.2)
    assert pid2 is not None and pid2 != pid1


def test_node_death_fails_actor(two_node_ray):
    cluster, n1, n2 = two_node_ray
    n3 = cluster.add_node(num_cpus=1, resources={"special": 1.0})

    @rt.remote(resources={"special": 1.0})
    class Doomed:
        def ping(self):
            return "pong"

    d = Doomed.remote()
    assert rt.get(d.ping.remote(), timeout=60) == "pong"
    cluster.remove_node(n3)
    with pytest.raises(Exception):
        rt.get(d.ping.remote(), timeout=10)


def test_object_transfer_between_nodes(two_node_ray):
    cluster, n1, n2 = two_node_ray
    import numpy as np

    @rt.remote(resources={"gadget": 1.0})
    def produce():
        return np.ones(300_000)  # large -> node 1 shm

    @rt.remote(resources={"widget": 1.0})
    def consume(a):
        return float(a.sum())

    # produce on node1, consume on node2 -> chunked pull between daemons
    assert rt.get(consume.remote(produce.remote()), timeout=90) == 300_000.0


def test_fake_tpu_slice_resources(two_node_ray):
    cluster, n1, n2 = two_node_ray
    from ray_tpu.accel.tpu import TPU_POD_TYPE_LABEL, TPU_SLICE_NAME_LABEL, TPU_WORKER_ID_LABEL

    # Fake 2-host v4-16 slice (reference test_jax_trainer.py:17-57 pattern).
    tpu_nodes = [
        cluster.add_node(
            num_cpus=1,
            resources={"TPU": 4.0, **({"TPU-v4-16-head": 1.0} if i == 0 else {})},
            labels={TPU_SLICE_NAME_LABEL: "slice-0", TPU_WORKER_ID_LABEL: str(i), TPU_POD_TYPE_LABEL: "v4-16"},
        )
        for i in range(2)
    ]
    assert rt.cluster_resources().get("TPU") == 8.0

    @rt.remote(num_cpus=0, num_tpus=4, label_selector={TPU_SLICE_NAME_LABEL: "slice-0"})
    def on_slice():
        return rt.get_runtime_context().node_id

    node_ids = rt.get([on_slice.remote() for _ in range(2)], timeout=90)
    assert set(node_ids) <= {n.node_id for n in tpu_nodes}
    for n in tpu_nodes:
        cluster.remove_node(n)


def test_kv_store(two_node_ray):
    from ray_tpu.core import api

    core = api._require_worker()
    core._run(core.controller.call("kv_put", {"ns": "test", "key": "k", "value": b"v"}))
    assert core._run(core.controller.call("kv_get", {"ns": "test", "key": "k"})) == b"v"
    assert core._run(core.controller.call("kv_keys", {"ns": "test", "prefix": "k"})) == ["k"]
