"""QoS plane: deadline propagation, priority classes, per-tenant fair
queuing, adaptive load shedding, and cancel-on-client-timeout.

Layers covered:
  * unit — RequestContext wire codec, FairWaitQueue policy (FIFO within a
    tenant, strict class priority, DRR tenant fairness), the AIMD admission
    controller (converges under standing delay, recovers after, never sheds
    the protected class), the replica's deadline gate;
  * router — the handle's fair admission queue under concurrent admits (the
    Condition.notify-scrum regression) and deadline expiry while queued;
  * cluster — deadline enforcement at the handle and worker hops (an
    expired request NEVER reaches user code), cancel-on-client-timeout
    actually freeing replica capacity, and the binary-RPC pickle lane
    honoring the client timeout (the proxy.py result(timeout=60) fix).

The end-to-end overload story (AIMD shedding + exact /metrics accounting
under 3x load) is the chaos scenario ``overload_storm``
(tests/test_chaos.py::test_overload_storm_scenario_smoke).
"""
from __future__ import annotations

import threading
import time

import pytest

import ray_tpu as rt
from ray_tpu import qos, serve
from ray_tpu.qos import AdmissionController, FairWaitQueue, Waiter
from ray_tpu.qos.context import to_wire
from ray_tpu.util import metrics as _metrics


def _counter_value(name: str, **tags) -> float:
    return sum(
        rec["value"] for rec in _metrics.snapshot()
        if rec["name"] == name
        and all(rec["tags"].get(k) == v for k, v in tags.items())
    )


# ---------------------------------------------------------------------------
# RequestContext + wire codec
# ---------------------------------------------------------------------------

def test_context_wire_roundtrip_and_nesting():
    assert qos.current() is None
    assert qos.current_wire() is None
    with qos.request_context(priority="batch", tenant="team-a", timeout_s=5) as ctx:
        assert ctx.rank == 1
        wire = qos.current_wire()
        back = qos.from_wire(wire)
        assert (back.priority, back.tenant) == ("batch", "team-a")
        assert 0 < back.remaining() <= 5
        # Nested contexts inherit missing fields and override present ones.
        with qos.request_context(priority="interactive") as inner:
            assert inner.tenant == "team-a" and inner.rank == 0
            assert inner.deadline == ctx.deadline
        assert qos.current().priority == "batch"
    assert qos.current() is None


def test_context_activate_deactivate_and_expiry():
    tok = qos.activate((2, "t9", time.time() - 1.0, "rid9"))
    try:
        ctx = qos.current()
        assert ctx.priority == "best_effort" and ctx.rid == "rid9"
        assert ctx.expired() and ctx.remaining() < 0
    finally:
        qos.deactivate(tok)
    assert qos.current() is None
    with pytest.raises(ValueError):
        qos.request_context(priority="urgent")


def test_raise_expired_counts_and_is_typed():
    before = _counter_value("serve.request.expired_total", hop="unit-test")
    with pytest.raises(qos.DeadlineExceeded):
        qos.raise_expired("unit-test", "fixture")
    assert _counter_value("serve.request.expired_total", hop="unit-test") == before + 1
    # Typed as a TimeoutError subclass: existing timeout handlers keep working.
    assert issubclass(qos.DeadlineExceeded, TimeoutError)


# ---------------------------------------------------------------------------
# FairWaitQueue policy
# ---------------------------------------------------------------------------

def _w(rank=0, tenant="t", deadline=None):
    return Waiter(rank=rank, tenant=tenant, deadline=deadline)


def test_fair_queue_fifo_within_tenant():
    q = FairWaitQueue()
    ws = [_w() for _ in range(10)]
    for w in ws:
        q.push(w)
    assert [q.pop_next() for _ in range(10)] == ws
    assert q.pop_next() is None and q.empty()


def test_fair_queue_strict_class_priority():
    q = FairWaitQueue()
    batch, best, inter = _w(rank=1), _w(rank=2), _w(rank=0)
    q.push(batch)
    q.push(best)
    q.push(inter)  # queued LAST, served FIRST
    assert q.pop_next() is inter
    assert q.pop_next() is batch
    assert q.pop_next() is best


def test_fair_queue_drr_tenant_fairness_under_skew():
    """Two tenants with wildly skewed offered load get ~equal admitted
    throughput within a class (the DRR contract)."""
    q = FairWaitQueue()
    flood = [_w(tenant="flood") for _ in range(30)]
    trickle = [_w(tenant="trickle") for _ in range(5)]
    for w in flood:
        q.push(w)
    for w in trickle:
        q.push(w)
    first10 = [q.pop_next() for _ in range(10)]
    by_tenant = {"flood": 0, "trickle": 0}
    for w in first10:
        by_tenant[w.tenant] += 1
    assert by_tenant == {"flood": 5, "trickle": 5}, by_tenant
    # Once the trickle drains, the flood gets everything.
    rest = [q.pop_next() for _ in range(25)]
    assert all(w.tenant == "flood" for w in rest)
    assert q.empty()


def test_fair_queue_weighted_tenants():
    q = FairWaitQueue(weights={"heavy": 2.0})
    for _ in range(12):
        q.push(_w(tenant="heavy"))
        q.push(_w(tenant="light"))
    first9 = [q.pop_next() for _ in range(9)]
    heavy = sum(1 for w in first9 if w.tenant == "heavy")
    assert heavy == 6, first9  # 2:1 service ratio


def test_fair_queue_lazy_discard():
    q = FairWaitQueue()
    a, b, c = _w(), _w(), _w()
    for w in (a, b, c):
        q.push(w)
    q.discard(b)
    assert len(q) == 2
    assert q.pop_next() is a
    assert q.pop_next() is c
    assert q.pop_next() is None


# ---------------------------------------------------------------------------
# AIMD admission controller
# ---------------------------------------------------------------------------

def test_aimd_converges_under_standing_delay_and_recovers():
    t = [0.0]
    ctl = AdmissionController(target_delay_s=0.1, min_limit=2, max_limit=64,
                              initial_limit=32, interval_s=1.0, now=lambda: t[0])
    # Standing queue: every window's MINIMUM delay exceeds target ->
    # multiplicative decrease all the way to the floor.
    for _ in range(12):
        t[0] += 1.1
        ctl.record_delay(0.5, rank=2)
    assert ctl.limit == 2.0, ctl.snapshot()
    # Load drops: delays below target -> additive recovery.
    for _ in range(10):
        t[0] += 1.1
        ctl.record_delay(0.01, rank=2)
    assert ctl.limit >= 10.0, ctl.snapshot()


def test_aimd_per_class_minima_interactive_cannot_mask_background_queue():
    """With strict priority, interactive delays are ~0 even when best_effort
    has a standing queue — a single global window-min would never decrease.
    The controller keys on the WORST class's window minimum."""
    t = [0.0]
    ctl = AdmissionController(target_delay_s=0.1, min_limit=2, max_limit=64,
                              initial_limit=32, interval_s=1.0, now=lambda: t[0])
    for _ in range(6):
        t[0] += 1.1
        ctl.record_delay(0.0, rank=0)   # interactive: jumped the queue
        ctl.record_delay(0.8, rank=2)   # best_effort: standing queue
    assert ctl.limit < 32.0, ctl.snapshot()


def test_admission_sheds_background_first_protects_interactive():
    ctl = AdmissionController(target_delay_s=0.1, min_limit=2, max_limit=64,
                              initial_limit=2, interval_s=3600.0)
    # best_effort cap = 0.6 * 2 = 1.2 against TOTAL inflight: admits while
    # inflight <= 1, sheds from the 3rd concurrent background request on.
    assert ctl.try_admit(2)[0]
    assert ctl.try_admit(2)[0]
    ok, retry_after = ctl.try_admit(2)
    assert not ok and retry_after >= 0.2
    # batch cap = 0.85 * 2 = 1.7: total inflight is already 2 -> sheds too.
    assert not ctl.try_admit(1)[0]
    # interactive caps against its OWN inflight (1.5 * 2 = 3), so the
    # converged-down limit and the background load cannot shed it.
    assert ctl.try_admit(0)[0]
    assert ctl.try_admit(0)[0]
    assert ctl.try_admit(0)[0]
    assert not ctl.try_admit(0)[0]  # own-class headroom exhausted
    ctl.release(0)
    assert ctl.try_admit(0)[0]


# ---------------------------------------------------------------------------
# router admission (offline _ReplicaSet: no cluster)
# ---------------------------------------------------------------------------

def _offline_rs(max_ongoing=1, replicas=("r1",)):
    from ray_tpu.serve.handle import _ReplicaSet

    rs = _ReplicaSet("qapp", "dep")
    rs._maybe_refresh = lambda: None  # membership is fixed for the test
    rs.replicas = {n: object() for n in replicas}
    rs.max_ongoing = max_ongoing
    return rs


def test_handle_admission_fifo_regression_no_notify_scrum():
    """Same tenant, concurrent admits: grants must follow ENQUEUE order.
    With the old Condition.notify_all scrum, whichever thread the OS woke
    first stole the freed slot — this pins the fair-queue handoff."""
    rs = _offline_rs(max_ongoing=1)
    holder = rs._admit(5.0)  # occupy the only slot
    started, admitted = [], []
    lock = threading.Lock()

    def worker(i):
        name, _ = rs._admit(10.0)
        with lock:
            admitted.append(i)
        rs._release(name)  # hand the slot to the next waiter in order

    threads = []
    for i in range(6):
        t = threading.Thread(target=worker, args=(i,))
        started.append(i)
        t.start()
        threads.append(t)
        time.sleep(0.05)  # deterministic enqueue order
    rs._release(holder[0])  # start the chain
    for t in threads:
        t.join(timeout=10)
    assert all(not t.is_alive() for t in threads)
    assert admitted == started, f"grant order {admitted} != enqueue order {started}"


def test_handle_admission_strict_priority_and_tenant_fairness():
    rs = _offline_rs(max_ongoing=1)
    holder = rs._admit(5.0)
    admitted = []
    lock = threading.Lock()

    def worker(tag, prio, tenant):
        with qos.request_context(priority=prio, tenant=tenant):
            name, _ = rs._admit(10.0)
        with lock:
            admitted.append(tag)
        rs._release(name)

    spec = (
        [("be-flood", "best_effort", "flood")] * 4
        + [("be-trickle", "best_effort", "trickle")] * 2
        + [("inter", "interactive", "u")] * 2
    )
    threads = []
    for tag, prio, tenant in spec:
        t = threading.Thread(target=worker, args=(tag, prio, tenant))
        t.start()
        threads.append(t)
        time.sleep(0.04)
    rs._release(holder[0])
    for t in threads:
        t.join(timeout=10)
    assert all(not t.is_alive() for t in threads)
    # Interactive jumps the whole best_effort queue despite arriving last...
    assert admitted[:2] == ["inter", "inter"], admitted
    # ...and within best_effort the two tenants alternate (DRR), so the
    # trickle tenant is fully served before the flood finishes.
    flood_after_trickle = admitted[2:].index("be-trickle")
    assert flood_after_trickle <= 1, admitted


def test_handle_admission_deadline_expires_while_queued():
    rs = _offline_rs(max_ongoing=1)
    holder = rs._admit(5.0)  # never released: the queue can't drain
    before = _counter_value("serve.request.expired_total", hop="handle")
    t0 = time.time()
    with qos.request_context(timeout_s=0.3):
        with pytest.raises(qos.DeadlineExceeded):
            rs._admit(30.0)
    assert time.time() - t0 < 2.0  # expired at ITS deadline, not the admit timeout
    assert _counter_value("serve.request.expired_total", hop="handle") == before + 1
    # A queue-free slot released later must not resurrect anything.
    rs._release(holder[0])
    assert len(rs._wfq) == 0


def test_handle_admission_plain_timeout_still_timeouterror():
    rs = _offline_rs(max_ongoing=1)
    rs._admit(5.0)
    with pytest.raises(TimeoutError) as err:
        rs._admit(0.2)
    assert not isinstance(err.value, qos.DeadlineExceeded)


def test_cancel_downstream_masks_the_request_context():
    """Regression (found by the overload_storm exact-accounting check): the
    cancel notification fired by an EXPIRED request's teardown inherited
    the dead context — the worker gate dropped the cancel itself with a
    SECOND counted expiry and the replica never saw it. Control-plane sends
    must carry no request context."""
    captured = []

    class FakeMethod:
        def remote(self, rid):
            captured.append(qos.current_wire())

    class FakeReplica:
        cancel_request = FakeMethod()

    rs = _offline_rs()
    rs.replicas = {"r1": FakeReplica()}
    tok = qos.activate((2, "t", time.time() - 5.0, "rid-x"))  # long expired
    try:
        rs._cancel_downstream("r1", "rid-x")
    finally:
        qos.deactivate(tok)
    assert captured == [None], captured


# ---------------------------------------------------------------------------
# replica inbox gate (direct instance: no cluster)
# ---------------------------------------------------------------------------

def test_replica_gate_drops_expired_before_user_code():
    from ray_tpu.serve.replica import Replica

    calls = []
    rep = Replica("a", "d", "r0", lambda x: calls.append(x) or "ran", (), {})
    before = _counter_value("serve.request.expired_total", hop="replica")
    tok = qos.activate((0, "t", time.time() - 0.5, "rid1"))
    try:
        with pytest.raises(qos.DeadlineExceeded):
            rep.handle_request("__call__", (1,), {})
    finally:
        qos.deactivate(tok)
    assert calls == [], "expired request reached user code"
    assert _counter_value("serve.request.expired_total", hop="replica") == before + 1
    assert rep.get_metrics()["ongoing"] == 0  # accounting unwound


def test_replica_cancel_event_and_early_cancel_memory():
    from ray_tpu.serve.replica import Replica

    seen = {}

    def body():
        ev = qos.cancel_event()
        seen["registered"] = ev is not None
        seen["pre_set"] = qos.cancel_requested()
        return "ok"

    rep = Replica("a", "d", "r0", body, (), {})
    # Cancel arriving BEFORE its request: remembered, event pre-set.
    rep.cancel_request("early-rid")
    tok = qos.activate((0, "t", None, "early-rid"))
    try:
        assert rep.handle_request("__call__", (), {}) == "ok"
    finally:
        qos.deactivate(tok)
    assert seen == {"registered": True, "pre_set": True}
    # Unknown rid after the request finished: nothing to cancel.
    assert rep.cancel_request("early-rid") is False


# ---------------------------------------------------------------------------
# cluster: end-to-end hops + cancel + the rpc-lane timeout fix
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def qos_cluster():
    rt.init(num_cpus=16)
    serve.start(proxy=False)
    yield rt
    serve.shutdown()
    rt.shutdown()


@serve.deployment(max_ongoing_requests=4)
class Probe:
    def __init__(self):
        self._lock = threading.Lock()
        self.invoked = 0
        self.cancelled = 0

    def __call__(self, x="-"):
        with self._lock:
            self.invoked += 1
        return {"ran": x}

    def wait_for_cancel(self):
        with self._lock:
            self.invoked += 1
        deadline = time.time() + 20
        while time.time() < deadline:
            if qos.cancel_requested():
                with self._lock:
                    self.cancelled += 1
                return "cancelled"
            time.sleep(0.02)
        return "completed"

    def stats(self):
        with self._lock:
            return {"invoked": self.invoked, "cancelled": self.cancelled}


def test_expired_at_handle_never_reaches_replica(qos_cluster):
    handle = serve.run(Probe.bind(), name="qhop", http=False)
    assert handle.remote("warm").result(timeout=30) == {"ran": "warm"}
    base = handle.stats.remote().result(timeout=30)["invoked"]
    with qos.request_context(deadline=time.time() - 1.0):
        with pytest.raises(qos.DeadlineExceeded):
            handle.remote("dead")
    assert handle.stats.remote().result(timeout=30)["invoked"] == base
    serve.delete("qhop")


def test_expired_at_worker_hop_typed_across_the_wire(qos_cluster):
    """Bypass the handle (direct replica actor call): the EXECUTOR-side
    worker-dispatch gate drops the expired call and the typed error crosses
    the wire (rt.get re-raises the pickled DeadlineExceeded cause)."""
    handle = serve.run(Probe.bind(), name="qworker", http=False)
    assert handle.remote("warm").result(timeout=30) == {"ran": "warm"}
    base = handle.stats.remote().result(timeout=30)["invoked"]
    info = rt.get(
        serve.api._get_controller().get_routing_info.remote("qworker", "Probe"),
        timeout=10,
    )
    replica = rt.get_actor(info["replica_names"][0], namespace="serve")
    with qos.request_context(deadline=time.time() - 1.0):
        ref = replica.handle_request.remote("__call__", ("dead",), {})
    with pytest.raises(qos.DeadlineExceeded):
        rt.get(ref, timeout=30)
    assert handle.stats.remote().result(timeout=30)["invoked"] == base
    serve.delete("qworker")


def test_expired_error_is_typed_through_the_streaming_lane(qos_cluster):
    """Regression (found by the verify drive): a DeadlineExceeded raised on
    the executor used to surface from ObjectRefGenerator as the raw
    RemoteError wrapper — the proxy's typed 504 mapping missed it and
    returned 500. The streaming lane now re-raises the picklable cause,
    same contract as rt.get."""
    handle = serve.run(Probe.bind(), name="qstream", http=False)
    assert handle.remote("warm").result(timeout=30) == {"ran": "warm"}
    info = rt.get(
        serve.api._get_controller().get_routing_info.remote("qstream", "Probe"),
        timeout=10,
    )
    replica = rt.get_actor(info["replica_names"][0], namespace="serve")
    with qos.request_context(deadline=time.time() - 1.0):
        gen = replica.handle_request_proxy.options(num_returns="streaming").remote(
            "__call__", ("dead",), {},
        )
    with pytest.raises(qos.DeadlineExceeded):
        next(gen)
    serve.delete("qstream")


def test_cancel_on_client_timeout_frees_replica_capacity(qos_cluster):
    from ray_tpu.serve.handle import _replica_set

    handle = serve.run(Probe.bind(), name="qcancel", http=False)
    resp = handle.options(method_name="wait_for_cancel").remote()
    with pytest.raises(TimeoutError):
        resp.result(timeout=1.0)
    # The handle's admission slot freed IMMEDIATELY (not after the 20s body).
    rs = _replica_set("qcancel", "Probe")
    with rs.cond:
        assert sum(rs.ongoing.values()) == 0
    # The replica-side body observed the cancel and returned early.
    deadline = time.time() + 10
    st = {}
    while time.time() < deadline:
        st = handle.stats.remote().result(timeout=30)
        if st.get("cancelled") == 1:
            break
        time.sleep(0.1)
    assert st.get("cancelled") == 1, st
    serve.delete("qcancel")


def test_rpc_pickle_lane_honors_client_timeout(qos_cluster):
    """Regression for the proxy's legacy dispatch hardcoding
    result(timeout=60): the pickle lane accepts a trailing timeout_s and
    both lanes share one capped-timeout policy."""
    import pickle
    import socket

    from ray_tpu.serve.proxy import _capped_timeout

    assert _capped_timeout(0.0) == 60.0     # no opinion -> default
    assert _capped_timeout(5.5) == 5.5      # client-controlled
    assert _capped_timeout(10_000) == 600.0  # capped
    assert _capped_timeout(None) == 60.0

    serve.run(Probe.bind(), name="qrpc", http=False)
    serve.start(proxy=True)  # rpc ingress rides the proxy actor
    port = serve.rpc_port()

    def rpc(payload_tuple, deadline_s=30):
        from ray_tpu.core import rpc as _rpc

        blob = pickle.dumps(payload_tuple, protocol=5)
        if _rpc.get_auth_token():
            blob = _rpc.frame_tag(blob) + blob
        with socket.create_connection(("127.0.0.1", port), timeout=deadline_s) as s:
            s.settimeout(deadline_s)
            s.sendall(len(blob).to_bytes(4, "little") + blob)
            n = int.from_bytes(s.recv(4), "little")
            buf = b""
            while len(buf) < n:
                buf += s.recv(n - len(buf))
        if _rpc.get_auth_token():
            buf = buf[_rpc.FRAME_TAG_LEN:]
        return pickle.loads(buf)

    # Legacy 5-tuple still works.
    status, result = rpc(("qrpc", "Probe", "__call__", ("five",), {}))
    assert (status, result) == ("ok", {"ran": "five"})
    # 6-tuple with a client timeout: honored end to end — a blocking method
    # fails in ~the client's budget, not the old hardcoded 60s.
    t0 = time.time()
    status, result = rpc(("qrpc", "Probe", "wait_for_cancel", (), {}, 1.0))
    elapsed = time.time() - t0
    assert status == "err", (status, result)
    assert elapsed < 30, f"client timeout ignored: {elapsed:.1f}s"
    serve.delete("qrpc")


def test_qos_queue_delay_histogram_recorded(qos_cluster):
    handle = serve.run(Probe.bind(), name="qmetrics", http=False)
    with qos.request_context(priority="batch", tenant="m"):
        assert handle.remote("m").result(timeout=30) == {"ran": "m"}
    recs = [
        rec for rec in _metrics.snapshot()
        if rec["name"] == "qos.queue.delay_s"
        and rec["tags"].get("class") == "batch"
        and rec["tags"].get("deployment") == "Probe"
    ]
    assert recs and recs[0]["n"] >= 1
    serve.delete("qmetrics")
