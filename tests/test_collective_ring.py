"""Collective performance plane: ring transport, int8 quantization,
bucketed overlap, sharded update.

The load-bearing invariants:
* ring results match the coordinator transport for every op and dtype
  (exact-representable values, so float comparison is equality);
* the coordinator actor carries ZERO tensor payload bytes on the ring path
  (its own counting shim — the PR-3 pickle-bypass proof, collective-shaped);
* quantized allreduce stays inside the codec's documented error bound and
  agrees byte-for-byte across ranks;
* bucketed overlap and the sharded update are bit-equal to their unbucketed
  / unsharded references on exactly-representable grads;
* sharded optimizer state never approaches full-model size.
"""
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import collective as col


def _exact_vals(rank: int, n: int = 64):
    """Small integers: exact in every dtype incl. bf16, so any summation
    order (ring phase, bucketing) produces identical bytes."""
    return np.arange(n) % 3 + rank + 1  # 1..5


def test_ring_matches_coordinator_all_ops_dtypes(shared_ray):
    @rt.remote
    class Member(col.CollectiveActorMixin):
        def run(self, rank, world):
            import ml_dtypes

            out = {}
            dtypes = [np.float32, np.float64, np.int32, np.int64,
                      ml_dtypes.bfloat16]
            for dt in dtypes:
                x = _exact_vals(rank).astype(dt)
                key = np.dtype(dt).name
                for op in ("sum", "max", "min", "prod"):
                    r = col.allreduce(x, op, group_name="eq", transport="ring")
                    c = col.allreduce(x, op, group_name="eq",
                                      transport="coordinator")
                    assert r.dtype == np.dtype(dt), (key, op, r.dtype)
                    out[f"ar.{key}.{op}"] = (np.asarray(r, np.float64),
                                             np.asarray(c, np.float64))
                rg = col.allgather(x, group_name="eq", transport="ring")
                cg = col.allgather(x, group_name="eq", transport="coordinator")
                out[f"ag.{key}"] = ([np.asarray(a, np.float64) for a in rg],
                                    [np.asarray(a, np.float64) for a in cg])
                stack = np.stack([x + i for i in range(world)])
                rs = col.reducescatter(stack, "sum", group_name="eq",
                                       transport="ring")
                cs = col.reducescatter(stack, "sum", group_name="eq",
                                       transport="coordinator")
                out[f"rs.{key}"] = (np.asarray(rs, np.float64),
                                    np.asarray(cs, np.float64))
                src_val = x if rank == 1 else None
                rb = col.broadcast(src_val, src_rank=1, group_name="eq",
                                   transport="ring")
                cb = col.broadcast(src_val, src_rank=1, group_name="eq",
                                   transport="coordinator")
                assert rb.dtype == np.dtype(dt), (key, rb.dtype)
                out[f"bc.{key}"] = (np.asarray(rb, np.float64),
                                    np.asarray(cb, np.float64))
                rr = col.reduce(x, dst_rank=2, op="sum", group_name="eq",
                                transport="ring")
                cr = col.reduce(x, dst_rank=2, op="sum", group_name="eq",
                                transport="coordinator")
                assert (rr is None) == (rank != 2) == (cr is None)
                if rank == 2:
                    out[f"rd.{key}"] = (np.asarray(rr, np.float64),
                                        np.asarray(cr, np.float64))
            # Degenerate shard sizes: fewer elements than ranks.
            tiny = col.allreduce(np.full((2,), rank + 1.0, np.float32),
                                 group_name="eq", transport="ring")
            out["tiny"] = (np.asarray(tiny, np.float64),
                           np.full((2,), 6.0))
            return out

    world = 3
    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, list(range(world)),
                                group_name="eq")
    outs = rt.get([m.run.remote(i, world) for i, m in enumerate(members)],
                  timeout=180)
    for rank, res in enumerate(outs):
        for name, (ring_v, coord_v) in res.items():
            if name.startswith("ag."):
                assert len(ring_v) == len(coord_v) == world
                for a, b in zip(ring_v, coord_v):
                    assert np.array_equal(a, b), (rank, name)
            else:
                assert np.array_equal(ring_v, coord_v), (rank, name)
    col.destroy_collective_group("eq")


def test_coordinator_carries_zero_payload_bytes_on_ring_path(shared_ray):
    """The acceptance invariant, PR-3 counting-shim style: the coordinator's
    own payload-byte counters stay at zero across a full suite of ring ops —
    and the shim itself is proven live by one legacy-transport op after."""
    @rt.remote
    class Member(col.CollectiveActorMixin):
        def ring_ops(self, rank, world):
            x = np.full((4096,), rank + 1.0, np.float32)
            col.allreduce(x, group_name="zb")
            col.allreduce(x, group_name="zb", quantization="int8")
            col.allgather(x, group_name="zb")
            col.reducescatter(np.stack([x] * world), group_name="zb")
            col.broadcast(x if rank == 0 else None, src_rank=0, group_name="zb")
            col.reduce(x, dst_rank=0, group_name="zb")
            return True

        def legacy_op(self, rank):
            col.allreduce(np.full((256,), rank + 1.0, np.float32),
                          group_name="zb", transport="coordinator")
            return True

    world = 2
    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, [0, 1], group_name="zb")
    rt.get([m.ring_ops.remote(i, world) for i, m in enumerate(members)],
           timeout=120)
    from ray_tpu.collective.collective import _GROUP_PREFIX

    actor = rt.get_actor(_GROUP_PREFIX + "zb")
    stats = rt.get(actor.get_stats.remote(), timeout=30)
    assert stats == {"payload_in": 0, "payload_out": 0}, stats
    # Shim liveness: the legacy transport must move the counters, or the
    # zero above is green-by-vacuity.
    rt.get([m.legacy_op.remote(i) for i, m in enumerate(members)], timeout=60)
    stats = rt.get(actor.get_stats.remote(), timeout=30)
    assert stats["payload_in"] == world * 256 * 4, stats
    assert stats["payload_out"] == world * world * 256 * 4, stats
    col.destroy_collective_group("zb")


def test_legacy_reduce_broadcast_ship_only_whats_consumed(shared_ray):
    """Satellite: on the coordinator transport, reduce() serves the
    all-ranks box ONLY to dst (was: every rank), and broadcast() publishes
    one value (was: an all-ranks box with W-1 Nones that everyone fetched)."""
    n = 512
    nbytes = n * 4

    @rt.remote
    class Member(col.CollectiveActorMixin):
        def run(self, rank, world):
            x = np.full((n,), rank + 1.0, np.float32)
            r = col.reduce(x, dst_rank=1, group_name="slim",
                           transport="coordinator")
            b = col.broadcast(x if rank == 0 else None, src_rank=0,
                              group_name="slim", transport="coordinator")
            return (None if r is None else float(r[0]), float(b[0]))

    world = 3
    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, [0, 1, 2], group_name="slim")
    outs = rt.get([m.run.remote(i, world) for i, m in enumerate(members)],
                  timeout=60)
    assert [o[0] for o in outs] == [None, 6.0, None]
    assert [o[1] for o in outs] == [1.0, 1.0, 1.0]
    from ray_tpu.collective.collective import _GROUP_PREFIX

    stats = rt.get(rt.get_actor(_GROUP_PREFIX + "slim").get_stats.remote(),
                   timeout=30)
    # reduce: W contributions in, ONE box (W arrays) out to dst.
    # broadcast: 1 contribution in, W single-value fetches out.
    assert stats["payload_in"] == world * nbytes + nbytes, stats
    assert stats["payload_out"] == world * nbytes + world * nbytes, stats
    col.destroy_collective_group("slim")


def test_quantized_allreduce_error_gate(shared_ray):
    """int8 ring allreduce: inside the codec's DOCUMENTED bound
    (quantize.max_abs_error_bound), byte-identical across ranks, dtype
    preserved — on adversarially scaled random data."""
    @rt.remote
    class Member(col.CollectiveActorMixin):
        def run(self, rank, world):
            rng = np.random.default_rng(1234 + rank)
            # Mixed scales stress the per-block absmax: big blocks next to
            # tiny ones.
            x = (rng.standard_normal(5000) *
                 np.repeat([1.0, 100.0, 0.01, 10.0, 1.0], 1000)
                 ).astype(np.float32)
            q = col.allreduce(x, group_name="qt", quantization="int8")
            exact = col.allreduce(x.astype(np.float64), group_name="qt")
            assert q.dtype == np.float32
            return x, q, exact

    world = 3
    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, [0, 1, 2], group_name="qt")
    outs = rt.get([m.run.remote(i, world) for i, m in enumerate(members)],
                  timeout=120)
    from ray_tpu.collective import quantize

    absmax_in = max(float(np.abs(o[0]).max()) for o in outs)
    bound = quantize.max_abs_error_bound(world, absmax_in)
    for rank, (x, q, exact) in enumerate(outs):
        err = float(np.abs(q.astype(np.float64) - outs[0][2]).max())
        assert err <= bound, (rank, err, bound)
    # An allreduce must agree everywhere — quantized included (the owner
    # ships its encoding verbatim and adopts its own dequantized image).
    for o in outs[1:]:
        assert o[1].tobytes() == outs[0][1].tobytes()
    # bf16 in, bf16 out (fp32 accumulation is internal).
    col.destroy_collective_group("qt")


def test_quantization_rejects_bad_combinations(shared_ray):
    @rt.remote
    class Member(col.CollectiveActorMixin):
        def run(self, rank):
            import pytest as pt

            with pt.raises(ValueError, match="sum"):
                col.allreduce(np.ones(8, np.float32), "max", group_name="qv",
                              quantization="int8")
            with pt.raises(ValueError, match="floating"):
                col.allreduce(np.ones(8, np.int32), group_name="qv",
                              quantization="int8")
            with pt.raises(ValueError, match="ring"):
                col.allreduce(np.ones(8, np.float32), group_name="qv",
                              quantization="int8", transport="coordinator")
            return True

    members = [Member.options(max_concurrency=2).remote() for _ in range(2)]
    col.create_collective_group(members, 2, [0, 1], group_name="qv")
    assert rt.get([m.run.remote(i) for i, m in enumerate(members)], timeout=60)
    col.destroy_collective_group("qv")


def test_bf16_quantizes_and_averages(shared_ray):
    """ml_dtypes bfloat16 reports numpy kind 'V', not 'f' — the plane's
    flagship dtype must still pass the int8 float gate (result dtype
    preserved) and still be AVERAGED by BucketedGradSync (a kind=='f'
    check silently handed every rank grad sums, W times too large)."""
    @rt.remote
    class Member(col.CollectiveActorMixin):
        def run(self, rank, world):
            import ml_dtypes

            from ray_tpu.train.grad_sync import BucketedGradSync

            x = np.full((256,), float(rank + 1), ml_dtypes.bfloat16)
            q = col.allreduce(x, group_name="bf16", quantization="int8")
            assert q.dtype == x.dtype, q.dtype
            gs = BucketedGradSync(group_name="bf16", bucket_bytes=1024)
            out = gs.allreduce(
                {"w": np.full((64,), float(rank + 1), ml_dtypes.bfloat16)})
            assert out["w"].dtype == np.dtype(ml_dtypes.bfloat16)
            # mean of 1, 2 = 1.5: exact in bf16 — sums (3.0) would betray
            # the skipped division.
            return (float(np.asarray(q, np.float64)[0]),
                    float(np.asarray(out["w"], np.float64)[0]))

    world = 2
    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, [0, 1], group_name="bf16")
    outs = rt.get([m.run.remote(i, world) for i, m in enumerate(members)],
                  timeout=60)
    for q0, avg0 in outs:
        assert q0 == 3.0  # 1 + 2, exactly representable -> quant exact
        assert avg0 == 1.5
    col.destroy_collective_group("bf16")


def test_bucketed_overlap_bit_identical_to_unbucketed(shared_ray):
    """Satellite gate: the bucketed-overlap path produces byte-identical
    reduced grads vs one unbucketed fp32 allreduce (exact-representable
    grads), and stays allclose on arbitrary floats."""
    @rt.remote
    class Member(col.CollectiveActorMixin):
        def run(self, rank, world):
            from ray_tpu.train.grad_sync import BucketedGradSync

            rng = np.random.default_rng(7 + rank)
            grads = {
                "w1": (rng.integers(-8, 8, (100, 33)).astype(np.float32)),
                "b1": (rng.integers(-8, 8, (257,)).astype(np.float32)),
                "w2": (rng.integers(-8, 8, (41, 19)).astype(np.float32)),
                "b2": (rng.integers(-8, 8, (5,)).astype(np.float32)),
            }
            many = BucketedGradSync("ov", bucket_bytes=4096).allreduce(grads)
            one = BucketedGradSync("ov", bucket_bytes=1 << 30).allreduce(grads)
            fuzzy = {k: rng.standard_normal(v.shape).astype(np.float32)
                     for k, v in grads.items()}
            fm = BucketedGradSync("ov", bucket_bytes=4096).allreduce(fuzzy)
            fo = BucketedGradSync("ov", bucket_bytes=1 << 30).allreduce(fuzzy)
            return many, one, fm, fo

    world = 2
    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, [0, 1], group_name="ov")
    outs = rt.get([m.run.remote(i, world) for i, m in enumerate(members)],
                  timeout=120)
    for many, one, fm, fo in outs:
        for k in many:
            assert many[k].tobytes() == one[k].tobytes(), k
            np.testing.assert_allclose(fm[k], fo[k], rtol=1e-6, atol=1e-6)
    # Ranks agree with each other too.
    for k in outs[0][0]:
        assert outs[0][0][k].tobytes() == outs[1][0][k].tobytes()
    col.destroy_collective_group("ov")


def test_sharded_update_matches_reference_and_bounds_state(shared_ray):
    """Sharded optimizer step: bit-equal to a full (unsharded) Adam given
    exact grads, and per-rank optimizer state is ~1/W of full-model state
    (the no-host-materializes-full-state invariant, by byte accounting)."""
    shapes = {"w1": (64, 33), "b1": (257,), "w2": (41, 19)}

    def make(rank, seed_off=0):
        rng = np.random.default_rng(11 + rank + seed_off)
        return ({k: rng.integers(-4, 4, s).astype(np.float32)
                 for k, s in shapes.items()})

    params0 = make(100)  # same on every rank (seed ignores rank via offset)

    @rt.remote
    class Member(col.CollectiveActorMixin):
        def run(self, rank, world):
            from ray_tpu.train.grad_sync import ShardedOptimizerStep

            params = {k: v.copy() for k, v in make(100).items()}
            opt = ShardedOptimizerStep("adam", lr=0.1, group_name="sh",
                                       bucket_bytes=8192)
            for step in range(3):
                grads = make(rank, seed_off=step + 1)
                params = opt.step(params, grads)
            return params, opt.state_bytes(), opt.peak_state_bytes

    world = 2
    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, [0, 1], group_name="sh")
    outs = rt.get([m.run.remote(i, world) for i, m in enumerate(members)],
                  timeout=120)

    # Reference: full-model Adam over the mean grads, mirroring
    # _update_shard's exact op order (elementwise => shard-invariant).
    ref = {k: v.copy() for k, v in params0.items()}
    m = {k: np.zeros(s, np.float32) for k, s in shapes.items()}
    v = {k: np.zeros(s, np.float32) for k, s in shapes.items()}
    b1, b2, lr, eps = 0.9, 0.999, 0.1, 1e-8
    for step in range(3):
        gsum = {k: sum(make(r, seed_off=step + 1)[k] for r in range(world))
                for k in shapes}
        for k in shapes:
            g = (gsum[k] / world).astype(np.float32)
            m[k] *= b1
            m[k] += (1 - b1) * g
            v[k] *= b2
            v[k] += (1 - b2) * np.square(g)
            mhat = m[k] / (1 - b1 ** (step + 1))
            vhat = v[k] / (1 - b2 ** (step + 1))
            ref[k] = ref[k] - lr * mhat / (np.sqrt(vhat) + eps)

    full_state_bytes = 2 * sum(
        int(np.prod(s)) * 4 for s in shapes.values())  # adam m+v, full model
    for params, state_bytes, peak in outs:
        for k in shapes:
            assert params[k].dtype == np.float32
            assert params[k].tobytes() == ref[k].tobytes(), k
        # Shard-sized state: ~full/W plus per-bucket ceil padding; far from
        # ever materializing the full slots.
        assert state_bytes == peak
        assert state_bytes < full_state_bytes * 0.6, (
            state_bytes, full_state_bytes)
    assert outs[0][0]["w1"].tobytes() == outs[1][0]["w1"].tobytes()
    col.destroy_collective_group("sh")


def test_async_collectives_overlap_in_flight(shared_ray):
    """Several allreduces in flight on one ring at once (the overlap
    substrate): results arrive correct and per-op, regardless of launch
    interleaving with result collection."""
    @rt.remote
    class Member(col.CollectiveActorMixin):
        def run(self, rank, world):
            works = [col.allreduce_async(
                np.full((2048,), float((rank + 1) * (i + 1)), np.float32),
                group_name="ov2") for i in range(4)]
            return [float(w.result(60)[0]) for w in works]

    world = 2
    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, [0, 1], group_name="ov2")
    outs = rt.get([m.run.remote(i, world) for i, m in enumerate(members)],
                  timeout=60)
    want = [3.0 * (i + 1) for i in range(4)]  # (1+2) * (i+1)
    assert outs == [want, want]
    col.destroy_collective_group("ov2")


def test_trainer_session_grad_sync_end_to_end(shared_ray):
    """The tentpole wiring at the trainer layer: a DataParallelTrainer train
    fn reaches the gang-bound overlap path via train.grad_sync() /
    train.sharded_optimizer() — no hand-built collective group, ranks
    rendezvous through the session's world info."""
    import ray_tpu.train as train
    from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig

    def train_fn(config):
        import numpy as np
        import ray_tpu.train as train

        ctx = train.get_context()
        rank = ctx.get_world_rank()
        grads = {"w": np.full((64, 16), float(rank + 1), np.float32)}
        reduced = train.grad_sync(bucket_bytes=1024).allreduce(grads)
        params = {"w": np.ones((64, 16), np.float32)}
        opt = train.sharded_optimizer("sgd", lr=0.5, bucket_bytes=1024)
        params = opt.step(params, grads)
        train.report({
            "reduced0": float(reduced["w"][0, 0]),
            "param0": float(params["w"][0, 0]),
            "state_bytes": opt.state_bytes(),
        })

    result = DataParallelTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ring_gs_e2e"),
    ).fit()
    m = result.metrics
    assert m["reduced0"] == 1.5        # mean of 1, 2
    assert m["param0"] == 0.25         # 1 - 0.5 * 1.5 (sgd on mean grad)
    assert m["state_bytes"] == 0       # plain sgd: no slots
    # The controller reaps the run's gang coordinator when fit() returns
    # (world-size-keyed name: an elastic resize rendezvouses fresh).
    with pytest.raises(ValueError):
        rt.get_actor("raytpu_collective:train:ring_gs_e2e:w2")


def test_broadcast_meta_survives_late_receiver(shared_ray):
    """src's establish is not gated on its successor's, so the broadcast
    meta notify can land before the receiver has built its ring. It must be
    stashed and adopted at establish (like pending hellos) — not silently
    dropped, which stranded the late rank until the step timeout."""
    @rt.remote
    class Member(col.CollectiveActorMixin):
        def run(self, rank, world):
            if rank == 1:
                time.sleep(1.5)  # src's successor reaches its first op late
            v = col.broadcast(
                np.full((32,), 7.0, np.float32) if rank == 0 else None,
                src_rank=0, group_name="latemeta")
            return float(v[0])

    world = 3
    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, [0, 1, 2],
                                group_name="latemeta")
    outs = rt.get([m.run.remote(i, world) for i, m in enumerate(members)],
                  timeout=60)
    assert outs == [7.0] * world
    col.destroy_collective_group("latemeta")


def test_ring_recovers_from_single_link_death(shared_ray):
    """A dead peer socket must not strand the gang. For world >= 3 the
    failing rank's predecessor is healthy and will never re-dial, so
    re-establish must carry the surviving inbound link — and the op counter,
    which the untouched ranks keep — for the next collective on the SAME
    group/epoch to succeed."""
    @rt.remote
    class Member(col.CollectiveActorMixin):
        def sync(self, rank, world):
            r = col.allreduce(np.full((512,), rank + 1.0, np.float32),
                              group_name="heal")
            return float(r[0])

        def kill_succ_link(self):
            import asyncio
            from ray_tpu.collective import ring as _ring
            from ray_tpu.core import api as _api

            core = _api._require_worker()
            with _ring._LOCK:
                ring = next(r for (g, _b, _e), r in _ring._RINGS.items()
                            if g.endswith(":heal"))
            asyncio.run_coroutine_threadsafe(
                ring.succ_conn.close(), core.loop).result(10)
            return True

    world = 3
    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, [0, 1, 2], group_name="heal")
    outs = rt.get([m.sync.remote(i, world) for i, m in enumerate(members)],
                  timeout=60)
    assert outs == [6.0] * world
    rt.get(members[0].kill_succ_link.remote(), timeout=30)
    time.sleep(1.0)  # let the EOF reach the successor's read loop
    outs = rt.get([m.sync.remote(i, world) for i, m in enumerate(members)],
                  timeout=60)
    assert outs == [6.0] * world
    col.destroy_collective_group("heal")


def test_recv_honors_full_timeout_in_one_wait(shared_ray):
    """Satellite: recv() with no sender fails at ~timeout (one server-side
    event wait), not timeout+30 (the old rt.get over-wait) and not in 30s
    polling slices."""
    @rt.remote
    class Member(col.CollectiveActorMixin):
        def lonely_recv(self):
            t0 = time.monotonic()
            try:
                col.recv(src_rank=0, group_name="p2p", timeout=2.0)
            except TimeoutError:
                return time.monotonic() - t0
            return -1.0

        def ping(self):
            return True

    members = [Member.options(max_concurrency=2).remote() for _ in range(2)]
    col.create_collective_group(members, 2, [0, 1], group_name="p2p")
    rt.get([m.ping.remote() for m in members], timeout=30)
    elapsed = rt.get(members[1].lonely_recv.remote(), timeout=30)
    assert 1.5 <= elapsed <= 6.0, elapsed  # ~2s wait + rpc slack, never 32s
    col.destroy_collective_group("p2p")


def test_ring_failure_is_typed_not_hung(shared_ray):
    """A rank that never joins the op (here: simply absent from the second
    collective) must surface as a typed CollectiveError at the step
    deadline on the ranks that did show up — the no-hang contract without
    chaos machinery (the injected-fault shapes live in scenario
    ring_link_loss)."""
    @rt.remote
    class Member(col.CollectiveActorMixin):
        def good(self, rank, world):
            out = col.allreduce(np.full((64,), rank + 1.0, np.float32),
                                group_name="tf")
            return float(out[0])

        def maybe_second(self, rank, participate):
            from ray_tpu.collective import ring as _ring

            with _ring._LOCK:
                r = next(v for k, v in _ring._RINGS.items()
                         if k[0].endswith("tf"))
            r.step_timeout = 1.0  # fail fast for the test
            if not participate:
                return "sat_out"
            try:
                col.allreduce(np.full((64,), 1.0, np.float32),
                              group_name="tf", timeout=20.0)
                return "completed"
            except col.CollectiveError as e:
                # Which typed shape depends on ring position: the absent
                # rank's predecessor sees "never armed", others see the
                # step timeout or the fanned abort.
                shapes = ("timed out", "aborted", "never armed")
                return f"typed:{any(s in str(e) for s in shapes)}"

    world = 3
    members = [Member.options(max_concurrency=2).remote() for _ in range(world)]
    col.create_collective_group(members, world, [0, 1, 2], group_name="tf")
    outs = rt.get([m.good.remote(i, world) for i, m in enumerate(members)],
                  timeout=60)
    assert outs == [6.0, 6.0, 6.0]
    t0 = time.monotonic()
    outs = rt.get([m.maybe_second.remote(i, i != 1) for i, m in
                   enumerate(members)], timeout=60)
    elapsed = time.monotonic() - t0
    assert outs[1] == "sat_out"
    assert outs[0] == "typed:True" and outs[2] == "typed:True", outs
    assert elapsed < 15, elapsed
    col.destroy_collective_group("tf")
