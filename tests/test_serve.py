"""Serve layer: deployments, handles, router, proxy, batching, autoscaling,
controller recovery. Mirrors the reference's serve test strategy
(python/ray/serve/tests/test_standalone.py, test_autoscaling_policy.py)."""
import json
import threading
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_cluster():
    rt.init(num_cpus=16)
    serve.start(proxy=False)
    yield rt
    serve.shutdown()
    rt.shutdown()


def _http(method, port, path, payload=None):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}", data=data, method=method)
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, resp.read()


# ---------------------------------------------------------------------------
# basics
# ---------------------------------------------------------------------------

def test_function_deployment_handle(serve_cluster):
    @serve.deployment
    def echo(x):
        return {"got": x}

    handle = serve.run(echo.bind(), name="fn_app", http=False)
    assert handle.remote(41).result() == {"got": 41}
    serve.delete("fn_app")


def test_class_deployment_methods_and_user_config(serve_cluster):
    @serve.deployment(user_config={"scale": 10})
    class Scaler:
        def __init__(self, base):
            self.base = base
            self.scale = 1

        def reconfigure(self, cfg):
            self.scale = cfg["scale"]

        def __call__(self, x):
            return (x + self.base) * self.scale

        def describe(self):
            return {"base": self.base, "scale": self.scale}

    handle = serve.run(Scaler.bind(5), name="cls_app", http=False)
    assert handle.remote(1).result() == 60
    assert handle.describe.remote().result() == {"base": 5, "scale": 10}
    serve.delete("cls_app")


def test_composition_child_handle(serve_cluster):
    @serve.deployment
    class Tokenizer:
        def __call__(self, text):
            return text.split()

    @serve.deployment
    class Pipeline:
        def __init__(self, tok):
            self.tok = tok

        def __call__(self, text):
            words = self.tok.remote(text).result()
            return {"n_words": len(words)}

    app = Pipeline.bind(Tokenizer.bind())
    handle = serve.run(app, name="compose", http=False)
    assert handle.remote("a b c d").result() == {"n_words": 4}
    st = serve.status()["apps"]["compose"]
    assert set(st) == {"Tokenizer", "Pipeline"}
    assert all(d["status"] == "HEALTHY" for d in st.values())
    serve.delete("compose")


def test_replicas_load_balanced(serve_cluster):
    @serve.deployment(num_replicas=3, max_ongoing_requests=2)
    class WhoAmI:
        def __init__(self):
            import os

            self.pid_tag = f"{os.getpid()}-{id(self)}"

        def __call__(self):
            time.sleep(0.05)
            return self.pid_tag

    handle = serve.run(WhoAmI.bind(), name="lb", http=False)
    responses = []
    lock = threading.Lock()

    def call():
        r = handle.remote().result()
        with lock:
            responses.append(r)

    threads = [threading.Thread(target=call) for _ in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(responses) == 12
    # With 12 concurrent requests and cap 2/replica, >1 replica must serve.
    assert len(set(responses)) >= 2
    serve.delete("lb")


# ---------------------------------------------------------------------------
# HTTP proxy
# ---------------------------------------------------------------------------

def test_http_proxy_routes_and_json(serve_cluster):
    @serve.deployment
    class Api:
        def __call__(self, request):
            body = request.json()
            return {"path": request.path, "sum": sum(body["xs"])}

    serve.run(Api.bind(), name="http_app", route_prefix="/api")
    port = serve.http_port()
    status, raw = _http("POST", port, "/api/add", {"xs": [1, 2, 3]})
    assert status == 200
    assert json.loads(raw) == {"path": "/add", "sum": 6}
    status, raw = _http("GET", port, "/-/routes")
    assert status == 200
    assert json.loads(raw)["/api"] == "http_app/Api"
    with pytest.raises(urllib.error.HTTPError) as err:
        _http("GET", port, "/nope")
    assert err.value.code == 404
    serve.delete("http_app")


# ---------------------------------------------------------------------------
# batching
# ---------------------------------------------------------------------------

def test_serve_batch_groups_requests(serve_cluster):
    @serve.deployment(max_ongoing_requests=16)
    class Batcher:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        def _infer(self, items):
            self.batch_sizes.append(len(items))
            return [i * 2 for i in items]

        def __call__(self, x):
            return self._infer(x)

        def get_batch_sizes(self):
            return self.batch_sizes

    handle = serve.run(Batcher.bind(), name="batch_app", http=False)
    results = {}
    lock = threading.Lock()

    def call(i):
        r = handle.remote(i).result()
        with lock:
            results[i] = r

    threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == {i: i * 2 for i in range(8)}
    sizes = handle.get_batch_sizes.remote().result()
    assert sum(sizes) == 8
    assert max(sizes) > 1  # at least one real batch formed
    serve.delete("batch_app")


# ---------------------------------------------------------------------------
# autoscaling
# ---------------------------------------------------------------------------

@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_autoscaling_up_and_down(serve_cluster):
    @serve.deployment(
        max_ongoing_requests=4,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1,
            max_replicas=3,
            target_ongoing_requests=1.0,
            upscale_delay_s=0.2,
            downscale_delay_s=0.5,
        ),
    )
    class Slow:
        def __call__(self):
            time.sleep(0.3)
            return "ok"

    handle = serve.run(Slow.bind(), name="auto", http=False)
    assert serve.status()["apps"]["auto"]["Slow"]["replicas"] == 1

    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                handle.remote().result(timeout=30)
            except Exception:
                return

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 20
        scaled_up = False
        while time.time() < deadline:
            if serve.status()["apps"]["auto"]["Slow"]["replicas"] >= 2:
                scaled_up = True
                break
            time.sleep(0.2)
        assert scaled_up, "autoscaler never scaled up under load"
    finally:
        stop.set()
        for t in threads:
            t.join()
    deadline = time.time() + 20
    scaled_down = False
    while time.time() < deadline:
        if serve.status()["apps"]["auto"]["Slow"]["target"] == 1:
            scaled_down = True
            break
        time.sleep(0.2)
    assert scaled_down, "autoscaler never scaled back down after load stopped"
    serve.delete("auto")


# ---------------------------------------------------------------------------
# fault tolerance
# ---------------------------------------------------------------------------

def test_replica_death_recovers(serve_cluster):
    @serve.deployment(num_replicas=2)
    class Sturdy:
        def __call__(self):
            return "alive"

    handle = serve.run(Sturdy.bind(), name="ft", http=False)
    info = rt.get(
        serve.api._get_controller().get_routing_info.remote("ft", "Sturdy"), timeout=10
    )
    victim = rt.get_actor(info["replica_names"][0], namespace="serve")
    rt.kill(victim)
    # Requests keep succeeding (retry/fail-over) while the controller heals.
    for _ in range(10):
        assert handle.remote().result(timeout=30) == "alive"
        time.sleep(0.05)
    deadline = time.time() + 20
    while time.time() < deadline:
        st = serve.status()["apps"]["ft"]["Sturdy"]
        if st["replicas"] == 2 and st["status"] == "HEALTHY":
            break
        time.sleep(0.2)
    st = serve.status()["apps"]["ft"]["Sturdy"]
    assert st["replicas"] == 2
    serve.delete("ft")


def test_controller_crash_recovery(serve_cluster):
    @serve.deployment(num_replicas=2)
    class Persist:
        def __call__(self, x):
            return x + 1

    handle = serve.run(Persist.bind(), name="ctl_ft", http=False)
    assert handle.remote(1).result() == 2

    ctl = serve.api._get_controller(create=False)
    rt.kill(ctl, no_restart=False)  # restartable: comes back and restores
    deadline = time.time() + 30
    ok = False
    while time.time() < deadline:
        try:
            st = serve.status()
            if st["apps"]["ctl_ft"]["Persist"]["replicas"] == 2:
                ok = True
                break
        except Exception:
            pass
        time.sleep(0.2)
    assert ok, "controller did not recover state from checkpoint"
    # Data path still works on the recovered control plane.
    assert handle.remote(5).result(timeout=30) == 6
    serve.delete("ctl_ft")


def test_redeploy_rolls_replicas_to_new_code(serve_cluster):
    """Redeploying changed code must retire old-code replicas (rolling update;
    reference: deployment_state.py)."""
    @serve.deployment
    def versioned(x):
        return {"version": 1, "x": x}

    h = serve.run(versioned.bind(), name="roll_app", http=False)
    assert h.remote(0).result()["version"] == 1

    @serve.deployment(name="versioned")
    def versioned2(x):
        return {"version": 2, "x": x}

    h2 = serve.run(versioned2.bind(), name="roll_app", http=False)
    deadline = time.time() + 30
    seen = None
    while time.time() < deadline:
        seen = h2.remote(0).result()["version"]
        if seen == 2:
            break
        time.sleep(0.25)
    assert seen == 2, f"still serving old code: {seen}"
    serve.delete("roll_app")
