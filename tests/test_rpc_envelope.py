"""Coalesced-envelope wire protocol (rpc.py envelope lane; WIRE_VERSION 3
since the raw chunk lane landed): a frame's payload pickles to either ONE
(kind, msg_id, method, payload) tuple or a LIST of them. N messages enqueued
in one loop tick ship as one envelope — one length header, one version byte,
one keyed-BLAKE2b tag, one pickle — and a lone frame is flushed the same
tick (call_soon, never a timer)."""
import asyncio
import pickle
import time

import pytest

from ray_tpu.core import rpc

TRIPPED = []


class Echo:
    def handle_echo(self, conn, p):
        return p

    def handle_trip(self, conn, p):
        TRIPPED.append(p)
        return p


@pytest.fixture(autouse=True)
def _no_token_leak():
    yield
    rpc.set_auth_token(None)


def test_mixed_single_and_batched_frames_one_connection():
    """Lone calls ride single-message envelopes; a synchronous burst of
    call_starts coalesces into ONE envelope; both interleave freely on one
    connection and every call gets its own reply."""

    async def go():
        server = rpc.RpcServer(Echo())
        await server.start()
        conn = await rpc.connect(server.address)
        try:
            # Lone call round trip (single-message envelope).
            assert await conn.call("echo", "solo-1", timeout=30) == "solo-1"

            rpc.batch_stats(reset=True)
            futs = [conn.call_start("echo", i) for i in range(32)]
            await conn.flush()
            assert await asyncio.gather(*futs) == list(range(32))
            st = rpc.batch_stats()
            # The whole burst left this process as one 32-message envelope.
            assert st["send"].get(32, 0) >= 1, st
            # The server (same process) received it as one envelope too.
            assert st["recv"].get(32, 0) >= 1, st

            # Back to lone frames on the same connection.
            assert await conn.call("echo", "solo-2", timeout=30) == "solo-2"

            # And a concurrent gather of plain calls still works (replies
            # may arrive batched or single — decode handles both).
            vals = await asyncio.gather(*(conn.call("echo", i, timeout=30) for i in range(10)))
            assert vals == list(range(10))
        finally:
            await conn.close()
            await server.close()

    asyncio.run(go())


def test_mac_tamper_rejects_whole_batch():
    """One tag covers the whole envelope: a single flipped byte anywhere in
    a batched frame drops the peer before ANY message reaches pickle/dispatch."""

    async def go():
        rpc.set_auth_token("envelope-tamper-test")
        server = rpc.RpcServer(Echo())
        await server.start()
        try:
            TRIPPED.clear()
            # Positive control: a correctly-tagged hand-built batch executes.
            reader, writer = await asyncio.open_connection(server.host, server.port)
            msgs = [(0, 1, "trip", "a"), (0, 2, "trip", "b")]
            body = pickle.dumps(msgs, protocol=5)
            frame = bytes([rpc.WIRE_VERSION]) + rpc.frame_tag(body) + body
            writer.write(len(frame).to_bytes(8, "little") + frame)
            await writer.drain()
            deadline = time.monotonic() + 30
            while len(TRIPPED) < 2 and time.monotonic() < deadline:
                await asyncio.sleep(0.01)
            assert TRIPPED == ["a", "b"]
            writer.close()

            # Tampered batch: flip one payload byte, keep the stale tag.
            TRIPPED.clear()
            reader, writer = await asyncio.open_connection(server.host, server.port)
            bad = bytearray(body)
            bad[-1] ^= 0x01
            frame = bytes([rpc.WIRE_VERSION]) + rpc.frame_tag(body) + bytes(bad)
            writer.write(len(frame).to_bytes(8, "little") + frame)
            await writer.drain()
            data = await reader.read(1024)
            assert data == b"", f"tampered batch got a reply: {data!r}"
            assert TRIPPED == [], "a message from a tampered batch was dispatched"
            writer.close()
        finally:
            await server.close()
            rpc.set_auth_token(None)

    asyncio.run(go())


def test_version_byte_mismatch_refuses_batched_frame():
    """A batched envelope stamped with a foreign wire generation is refused
    before unpickling, exactly like a single frame."""

    async def go():
        server = rpc.RpcServer(Echo())
        await server.start()
        try:
            TRIPPED.clear()
            reader, writer = await asyncio.open_connection(server.host, server.port)
            body = pickle.dumps([(0, 1, "trip", "x"), (0, 2, "trip", "y")], protocol=5)
            frame = bytes([rpc.WIRE_VERSION + 1]) + body
            writer.write(len(frame).to_bytes(8, "little") + frame)
            await writer.drain()
            data = await reader.read(1024)
            assert data == b"", f"mismatched-version batch got a reply: {data!r}"
            assert TRIPPED == []
            writer.close()
        finally:
            await server.close()

    asyncio.run(go())


def test_unpicklable_payload_does_not_sink_batchmates():
    """One unpicklable message must not drop the envelope it coalesced
    into: batchmates still deliver, the offender gets a clean RpcError
    (reply side: an 'err' reply, mirroring pre-batching _dispatch; request
    side: the local reply future fails instead of hanging)."""
    import threading

    class H:
        def handle_echo(self, conn, p):
            return p

        def handle_bad(self, conn, p):
            return threading.Lock()  # unpicklable reply payload

    async def go():
        server = rpc.RpcServer(H())
        await server.start()
        conn = await rpc.connect(server.address)
        try:
            futs = [
                conn.call_start("echo", 1),
                conn.call_start("bad", None),
                conn.call_start("echo", 2),
            ]
            await conn.flush()
            results = await asyncio.gather(*futs, return_exceptions=True)
            assert results[0] == 1 and results[2] == 2, results
            assert isinstance(results[1], rpc.RpcError), results[1]

            # Unpicklable REQUEST payload: the caller gets an error, not a
            # hang, and the connection survives for the next call.
            with pytest.raises(rpc.RpcError):
                await conn.call("echo", threading.Lock(), timeout=30)
            assert await conn.call("echo", "still-alive", timeout=30) == "still-alive"
        finally:
            await conn.close()
            await server.close()

    asyncio.run(go())


def test_lone_call_never_waits_on_flush_timer():
    """Regression guard for the flush policy: coalescing must be
    queue-depth-driven (call_soon at tick end), NEVER a timer — a lone sync
    call must not sit in the buffer waiting for a batching window."""

    async def go():
        server = rpc.RpcServer(Echo())
        await server.start()
        conn = await rpc.connect(server.address)
        loop = asyncio.get_running_loop()
        short_timers: list = []
        orig_call_later = loop.call_later

        def spy(delay, cb, *args, **kw):
            # Any sub-5s timer during lone calls would be a batching window
            # (the only legit timers here are this test's own long call
            # timeouts, if any).
            if delay < 5.0:
                short_timers.append(delay)
            return orig_call_later(delay, cb, *args, **kw)

        loop.call_later = spy
        try:
            rpc.batch_stats(reset=True)
            t0 = time.perf_counter()
            for i in range(50):
                assert await conn.call("echo", i, timeout=None) == i
            elapsed = time.perf_counter() - t0
        finally:
            loop.call_later = orig_call_later
            await conn.close()
            await server.close()
        assert short_timers == [], f"flush used timers: {short_timers[:5]}"
        st = rpc.batch_stats()
        # Sequential lone calls never coalesce: every envelope carries 1.
        assert set(st["send"]) == {1}, st
        # 50 local round trips in well under any plausible batching-timer
        # regime (50 x even a 10ms window would be >= 0.5s).
        assert elapsed < 30, f"50 lone calls took {elapsed:.1f}s"

    asyncio.run(go())
