"""Flash-attention kernel exactness vs the jnp oracle, run in Pallas
interpret mode on CPU (the kernels themselves, not the fallback; real-TPU
execution is covered by bench.py). Covers MHA, native GQA (grouped KV heads,
no repeat), segment masking (packed sequences), and backward gradients."""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import flash_attention, mha_reference

B, S, D = 2, 256, 64


def _qkv(key, H, KV):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, KV, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, KV, D), jnp.float32)
    return q, k, v


def _segs():
    # Two segments per row, boundary at different positions per batch row.
    bounds = jnp.array([100, 160])
    pos = jnp.arange(S)[None, :]
    return (pos >= bounds[:, None]).astype(jnp.int32)


@pytest.mark.parametrize("H,KV", [(4, 4), (4, 2), (8, 2)])
def test_flash_forward_matches_reference(H, KV):
    q, k, v = _qkv(jax.random.PRNGKey(0), H, KV)
    ref = mha_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5, rtol=2e-5)


def test_flash_segment_mask_matches_reference():
    q, k, v = _qkv(jax.random.PRNGKey(1), 4, 2)
    segs = _segs()
    ref = mha_reference(q, k, v, causal=True, segment_ids=segs)
    out = flash_attention(
        q, k, v, causal=True, segment_ids=segs, block_q=128, block_k=128, interpret=True
    )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5, rtol=2e-5)


def test_flash_segment_isolation():
    """Tokens after a segment boundary must be unaffected by tokens before it."""
    q, k, v = _qkv(jax.random.PRNGKey(2), 4, 4)
    segs = _segs()
    out1 = flash_attention(q, k, v, segment_ids=segs, block_q=128, block_k=128, interpret=True)
    # Perturb segment-0 keys/values of row 0; segment-1 outputs must not move.
    k2 = k.at[0, :100].add(1.0)
    v2 = v.at[0, :100].add(1.0)
    out2 = flash_attention(q, k2, v2, segment_ids=segs, block_q=128, block_k=128, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out1[0, 100:]), np.asarray(out2[0, 100:]), atol=1e-6
    )
    assert not np.allclose(np.asarray(out1[0, :100]), np.asarray(out2[0, :100]))


@pytest.mark.parametrize("H,KV", [(4, 4), (4, 2)])
def test_flash_backward_matches_reference(H, KV):
    q, k, v = _qkv(jax.random.PRNGKey(3), H, KV)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=128, block_k=128, interpret=True)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(mha_reference(q, k, v, causal=True)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4, err_msg=f"d{name}"
        )


def test_flash_backward_with_segments():
    q, k, v = _qkv(jax.random.PRNGKey(4), 4, 2)
    segs = _segs()

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, segment_ids=segs, block_q=128, block_k=128, interpret=True)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(mha_reference(q, k, v, causal=True, segment_ids=segs)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, rtol=5e-4, err_msg=f"d{name}"
        )


def test_packed_sequence_training_step():
    """End-to-end: packed batch (segment_ids + restarting positions) trains
    and matches the loss of the equivalent unpacked batch."""
    from ray_tpu.models import TransformerConfig, cross_entropy_loss
    from ray_tpu.models.transformer import init_params

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32, attention_impl="reference",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    # Two examples of length 8 packed into one row of 16.
    ex = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
    packed_tokens = ex.reshape(1, 16)
    segs = jnp.array([[0] * 8 + [1] * 8])
    positions = jnp.array([list(range(8)) + list(range(8))])
    packed_loss = cross_entropy_loss(
        params,
        {"tokens": packed_tokens, "segment_ids": segs, "positions": positions},
        cfg,
    )
    # Unpacked: mean of the two examples' per-token NLL (equal lengths).
    unpacked_loss = cross_entropy_loss(params, {"tokens": ex}, cfg)
    np.testing.assert_allclose(float(packed_loss), float(unpacked_loss), rtol=1e-5)
