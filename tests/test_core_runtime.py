"""Core runtime tests: tasks, actors, objects, wait, errors.

Modeled on the reference's python/ray/tests/ suite style: a shared in-process
cluster fixture (conftest ray_start_shared equivalent) and small, focused
cases.
"""
import time

import numpy as np
import pytest

import ray_tpu as rt


def test_simple_task(shared_ray):
    @rt.remote
    def add(a, b):
        return a + b

    assert rt.get(add.remote(1, 2), timeout=30) == 3


def test_task_with_kwargs(shared_ray):
    @rt.remote
    def f(a, b=10, c=0):
        return a + b + c

    assert rt.get(f.remote(1, c=5), timeout=30) == 16


def test_chained_dependencies(shared_ray):
    @rt.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(4):
        ref = inc.remote(ref)
    assert rt.get(ref, timeout=30) == 5


def test_parallel_tasks(shared_ray):
    @rt.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(8)]
    assert rt.get(refs, timeout=30) == [i * i for i in range(8)]


def test_num_returns(shared_ray):
    @rt.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert rt.get([a, b, c], timeout=30) == [1, 2, 3]


def test_task_exception_propagates(shared_ray):
    @rt.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(ValueError, match="kaboom"):
        rt.get(boom.remote(), timeout=30)


def test_nested_tasks(shared_ray):
    @rt.remote
    def inner(x):
        return x * 2

    @rt.remote
    def outer(x):
        return rt.get(inner.remote(x), timeout=30) + 1

    assert rt.get(outer.remote(10), timeout=60) == 21


def test_put_get_small(shared_ray):
    ref = rt.put({"a": [1, 2, 3]})
    assert rt.get(ref, timeout=10) == {"a": [1, 2, 3]}


def test_put_get_large_zero_copy(shared_ray):
    arr = np.random.rand(500_000)  # 4MB -> shared memory path
    ref = rt.put(arr)
    out = rt.get(ref, timeout=10)
    assert np.array_equal(arr, out)


def test_large_arg_to_task(shared_ray):
    arr = np.ones(300_000)

    @rt.remote
    def total(a):
        return float(a.sum())

    assert rt.get(total.remote(rt.put(arr)), timeout=30) == 300_000.0


def test_ref_inside_container(shared_ray):
    inner_ref = rt.put(41)

    @rt.remote
    def deref(d):
        return rt.get(d["ref"], timeout=10) + 1

    assert rt.get(deref.remote({"ref": inner_ref}), timeout=30) == 42


def test_wait(shared_ray):
    @rt.remote
    def slow(t):
        time.sleep(t)
        return t

    fast = slow.remote(0.05)
    slow_ref = slow.remote(5.0)
    ready, not_ready = rt.wait([fast, slow_ref], num_returns=1, timeout=10)
    assert ready == [fast] and not_ready == [slow_ref]


def test_wait_timeout(shared_ray):
    @rt.remote
    def slow():
        time.sleep(10)

    ready, not_ready = rt.wait([slow.remote()], num_returns=1, timeout=0.2)
    assert not ready and len(not_ready) == 1


def test_actor_basics(shared_ray):
    @rt.remote
    class Counter:
        def __init__(self, start=0):
            self.v = start

        def incr(self, by=1):
            self.v += by
            return self.v

    c = Counter.remote(5)
    assert rt.get(c.incr.remote(), timeout=30) == 6
    assert rt.get(c.incr.remote(4), timeout=10) == 10


def test_actor_ordering(shared_ray):
    @rt.remote
    class Acc:
        def __init__(self):
            self.log = []

        def add(self, i):
            self.log.append(i)
            return list(self.log)

    a = Acc.remote()
    refs = [a.add.remote(i) for i in range(10)]
    final = rt.get(refs[-1], timeout=30)
    assert final == list(range(10))


def test_async_actor(shared_ray):
    @rt.remote
    class AsyncActor:
        async def work(self, x):
            import asyncio

            await asyncio.sleep(0.01)
            return x * 2

    a = AsyncActor.options(max_concurrency=4).remote()
    refs = [a.work.remote(i) for i in range(4)]
    assert rt.get(refs, timeout=30) == [0, 2, 4, 6]


def test_named_actor(shared_ray):
    @rt.remote
    class Store:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v
            return True

        def get(self, k):
            return self.d.get(k)

    s = Store.options(name="kvstore").remote()
    rt.get(s.set.remote("x", 1), timeout=30)
    h = rt.get_actor("kvstore")
    assert rt.get(h.get.remote("x"), timeout=10) == 1
    names = rt.list_named_actors()
    assert any(n["name"] == "kvstore" for n in names)


def test_actor_exception(shared_ray):
    @rt.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor oops")

    b = Bad.remote()
    with pytest.raises(RuntimeError, match="actor oops"):
        rt.get(b.fail.remote(), timeout=30)


def test_kill_actor(shared_ray):
    @rt.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert rt.get(v.ping.remote(), timeout=30) == "pong"
    rt.kill(v)
    time.sleep(0.2)
    with pytest.raises(Exception):
        rt.get(v.ping.remote(), timeout=5)


def test_actor_handle_passing(shared_ray):
    @rt.remote
    class Counter2:
        def __init__(self):
            self.v = 0

        def incr(self):
            self.v += 1
            return self.v

    @rt.remote
    def bump(handle):
        return rt.get(handle.incr.remote(), timeout=10)

    c = Counter2.remote()
    assert rt.get(bump.remote(c), timeout=60) == 1
    assert rt.get(c.incr.remote(), timeout=10) == 2


def test_cluster_resources(shared_ray):
    total = rt.cluster_resources()
    assert total.get("CPU", 0) >= 8


def test_runtime_context(shared_ray):
    @rt.remote
    def whoami():
        ctx = rt.get_runtime_context()
        return (ctx.node_id, ctx.worker_id)

    node_id, worker_id = rt.get(whoami.remote(), timeout=30)
    assert node_id and worker_id
