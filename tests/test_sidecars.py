"""Sidecar services: metrics pipeline, job submission, dashboard JSON/Prom
endpoints, autoscaler. Reference analogues: python/ray/tests/test_metrics*,
dashboard/modules/job/tests, autoscaler/v2/tests."""
import json
import sys
import time
import urllib.request

import pytest

import ray_tpu as rt


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, r.read()


def test_metrics_counter_gauge_histogram(shared_ray):
    from ray_tpu.core import api
    from ray_tpu.util import metrics

    c = metrics.Counter("test_requests", description="reqs")
    c.inc(2.0, tags={"route": "/a"})
    c.inc(3.0, tags={"route": "/a"})
    g = metrics.Gauge("test_depth")
    g.set(7.0)
    h = metrics.Histogram("test_latency", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    # Metrics emitted inside a task (another process) aggregate too.
    @rt.remote
    def emit():
        from ray_tpu.util import metrics as m

        m.Counter("test_requests").inc(5.0, tags={"route": "/a"})
        # Force an immediate report instead of waiting for the 5s timer.
        from ray_tpu.core import api as wapi

        core = wapi._require_worker()
        import asyncio

        asyncio.run_coroutine_threadsafe(core._report_metrics(), core.loop).result(10)
        return True

    assert rt.get(emit.remote(), timeout=60)
    core = api._require_worker()
    core._run(core._report_metrics())
    series = core._run(core.controller.call("get_metrics", {}))
    byname = {(s["name"], tuple(sorted(s["tags"].items()))): s for s in series}
    assert byname[("test_requests", (("route", "/a"),))]["value"] == 10.0
    # Gauges merge as per-reporter series (a `reporter` tag is added —
    # summing point-in-time values across processes is nonsense; see
    # handle_get_metrics), so the lookup matches by name, not exact tags.
    depth = [s for s in series if s["name"] == "test_depth"]
    assert depth, "driver gauge never reached the merged view"
    assert all(s["tags"].get("reporter") for s in depth), depth
    assert any(s["value"] == 7.0 for s in depth), depth
    hist = byname[("test_latency", ())]
    assert hist["counts"] == [1, 1, 1] and hist["n"] == 3

    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text(series)
    assert "raytpu_test_requests" in text and 'le="+Inf"' in text


def test_job_submission_lifecycle(shared_ray, tmp_path):
    from ray_tpu.job import JobStatus, JobSubmissionClient

    client = JobSubmissionClient(log_dir=str(tmp_path))
    job_id = client.submit_job(f"{sys.executable} -c \"print('hello from job')\"")
    assert client.wait_until_finished(job_id, timeout_s=120) == JobStatus.SUCCEEDED
    assert "hello from job" in client.get_job_logs(job_id)
    assert any(j["job_id"] == job_id for j in client.list_jobs())

    bad = client.submit_job(f"{sys.executable} -c \"import sys; sys.exit(3)\"")
    assert client.wait_until_finished(bad, timeout_s=120) == JobStatus.FAILED

    slow = client.submit_job(f"{sys.executable} -c \"import time; time.sleep(60)\"")
    time.sleep(0.5)
    assert client.stop_job(slow)
    assert client.wait_until_finished(slow, timeout_s=30) == JobStatus.STOPPED


def test_dashboard_endpoints(shared_ray):
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    port = start_dashboard(0)
    try:
        status, body = _get(f"http://127.0.0.1:{port}/api/cluster")
        assert status == 200
        state = json.loads(body)
        assert "nodes" in state and "actors" in state
        status, body = _get(f"http://127.0.0.1:{port}/metrics")
        assert status == 200
        status, body = _get(f"http://127.0.0.1:{port}/")
        assert status == 200 and b"ray_tpu" in body
    finally:
        stop_dashboard()


def test_cli_status_and_list(shared_ray, capsys):
    from ray_tpu.core import api

    from ray_tpu import __main__ as cli

    addr = api._require_worker().controller_addr

    # Reuse the existing session: _connect's rt.init is a no-op when already
    # initialized in-process.
    cli.main(["--address", addr, "status"])
    cli.main(["--address", addr, "list", "nodes"])
    out = capsys.readouterr().out
    assert "nodes:" in out and "== nodes ==" in out


def test_timeline_export(shared_ray, tmp_path):
    from ray_tpu.util.tracing import export_timeline, get_task_events

    @rt.remote
    def traced_task(x):
        time.sleep(0.02)
        return x

    rt.get([traced_task.remote(i) for i in range(4)], timeout=120)
    time.sleep(0.1)
    # Worker-side exec events reach the controller via the reporter; force
    # one reporter tick worker-side by running another task round.
    rt.get([traced_task.remote(i) for i in range(2)], timeout=120)

    out = str(tmp_path / "trace.json")
    deadline = time.time() + 30
    spans = 0
    while time.time() < deadline and spans == 0:
        n = export_timeline(out)
        data = json.load(open(out))
        spans = sum(1 for e in data["traceEvents"] if e["ph"] == "X")
        if spans == 0:
            time.sleep(1.0)
    assert spans >= 1, "no execution spans in exported timeline"
    assert any(e["ph"] == "i" for e in data["traceEvents"])  # control instants


def test_dashboard_profile_and_ui(shared_ray):
    """On-demand worker CPU profile through the dashboard (py-spy-equiv,
    reference: reporter/profile_manager.py) + the HTML UI renders."""
    import json as _json
    import urllib.request

    import ray_tpu as rt
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @rt.remote
    class Spinner:
        def __init__(self):
            self.spinning = False

        def busy(self, n):
            import time as _t

            self.spinning = True
            t0 = _t.time()
            while _t.time() - t0 < n:
                sum(range(2000))
            self.spinning = False
            return True

        def is_busy(self):
            return self.spinning

    # max_concurrency 2: is_busy must answer WHILE busy holds the default
    # lane (the deterministic started-signal the profile gates on).
    a = Spinner.options(max_concurrency=2).remote()
    rt.get(a.busy.remote(0.01), timeout=60)  # barrier: actor ALIVE + registered
    ref = a.busy.remote(6.0)  # keep a thread hot while we sample
    # Deterministic gate: sample only once the busy body is actually on its
    # executor thread — profiling the dispatch window instead was the old
    # flake (stacks full of idle pool threads, "busy" absent).
    deadline = time.time() + 30
    while not rt.get(a.is_busy.remote(), timeout=30):
        assert time.time() < deadline, "busy call never started"
        time.sleep(0.05)
    # Find the actor's worker address from cluster state.
    from ray_tpu.core import api as _api

    core = _api._require_worker()
    state = core._run(core.controller.call("get_cluster_state", {}))
    addr = state["actors"][a._actor_id.hex()]["worker_addr"]
    assert addr, "spinner actor has no worker address"
    port = start_dashboard(0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/profile?addr={addr}&duration=2.0", timeout=60
        ) as resp:
            prof = _json.loads(resp.read())
        # The busy loop starves the sampler of the GIL on a loaded 1-core
        # host (~5-10 samples/s observed); the floor asserts liveness, not
        # cadence.
        assert prof["samples"] >= 5, prof
        assert any("busy" in stack for stack in prof["stacks"]), (
            f"hot method not in sampled stacks: {list(prof['stacks'])[:3]}"
        )
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/", timeout=30) as resp:
            html = resp.read().decode()
        assert "Nodes" in html and "/api/cluster" in html
    finally:
        stop_dashboard()
        rt.get(ref, timeout=60)
        rt.kill(a)


def test_cli_drain_and_profile(shared_ray, capsys):
    """`python -m ray_tpu drain/profile` operator commands."""
    import ray_tpu as rt
    from ray_tpu.__main__ import main as cli
    from ray_tpu.core import api as _api

    @rt.remote
    class Idler:
        def __init__(self):
            self.spinning = False

        def spin(self, n):
            import time as _t

            self.spinning = True
            t0 = _t.time()
            while _t.time() - t0 < n:
                sum(range(1000))
            self.spinning = False
            return True

        def is_busy(self):
            return self.spinning

    a = Idler.options(max_concurrency=2).remote()
    rt.get(a.spin.remote(0.01), timeout=60)
    core = _api._require_worker()
    state = core._run(core.controller.call("get_cluster_state", {}))
    node_id = next(iter(state["nodes"]))
    addr = state["actors"][a._actor_id.hex()]["worker_addr"]

    caddr = core.controller_addr
    try:
        cli(["--address", caddr, "drain", node_id])
        assert "draining" in capsys.readouterr().out
        assert core._run(core.controller.call("get_cluster_state", {}))["nodes"][node_id]["draining"]
    finally:
        # The shared cluster's only node must never stay drained (every later
        # test in this module would pend forever).
        cli(["--address", caddr, "drain", node_id, "--undo"])
    assert "reopened" in capsys.readouterr().out

    ref = a.spin.remote(5.0)
    deadline = time.time() + 30
    while not rt.get(a.is_busy.remote(), timeout=30):
        assert time.time() < deadline, "spin call never started"
        time.sleep(0.05)
    cli(["--address", caddr, "profile", addr, "--duration", "1.5"])
    out = capsys.readouterr().out
    assert "samples over" in out and "spin" in out
    rt.get(ref, timeout=60)
    rt.kill(a)
