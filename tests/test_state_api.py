"""State introspection: task lifecycle FSM completeness, the controller's
bounded per-task index (filters, truncation, eviction accounting), the
`since` event cursor, and the live state API (`ray_tpu.state`) against a
real cluster — RUNNING attribution and the `ray memory` equivalent's
owner/borrower round trip. Mirrors the reference's state-API tests
(python/ray/tests/test_state_api.py) at this controller's layer."""
import time

import pytest

import ray_tpu as rt
from ray_tpu.core import task_state as ts


# ---------------------------------------------------------------------------
# FSM definition + emitter lint (no cluster)
# ---------------------------------------------------------------------------

def test_fsm_tables_consistent():
    # Every mapped state is a declared state; terminal states emit nothing.
    for state in ts.EVENT_STATE.values():
        assert state is None or state in ts.STATES
    for src, dsts in ts.TRANSITIONS.items():
        assert src in ts.STATES
        for dst in dsts:
            assert dst in ts.STATES
    for terminal in ts.TERMINAL:
        assert not ts.TRANSITIONS[terminal]
    # Every non-initial state is reachable.
    reachable = set()
    for dsts in ts.TRANSITIONS.values():
        reachable |= dsts
    assert reachable | {ts.PENDING_ARGS_AVAIL, ts.PENDING_NODE_ASSIGNMENT} == set(ts.STATES)


def test_every_worker_event_kind_maps_to_fsm():
    """Thin wrapper over graftlint's fsm-emitter rule (the ad-hoc AST scan
    that used to live here migrated into ray_tpu/analysis/rules_fsm.py).
    Asserts the rule still SEES emitters — a scan that finds zero emitters
    has silently gone dead and gates nothing — and that worker.py's kinds
    all map into the FSM."""
    import ray_tpu.core.worker as worker_mod
    from ray_tpu.analysis import lint_paths

    result = lint_paths([worker_mod.__file__])
    stats = result.stats.get(worker_mod.__file__, {}).get("fsm-emitter")
    assert stats and stats["emitters"] >= 1, "fsm-emitter scan found no emitters — the scan is broken"
    fsm_findings = [f for f in result.findings if f.rule == "fsm-emitter"]
    assert not fsm_findings, "\n".join(f.render() for f in fsm_findings)


def test_fold_converges_regardless_of_arrival_order():
    """Caller and executor report through different buffers: the fold must
    reach the same record for any interleaving of the same events."""
    evs = [
        {"kind": "task_pending_args", "task_id": "t1", "attempt": 0, "ts": 1.0, "fn": "f"},
        {"kind": "task_submitted", "task_id": "t1", "attempt": 0, "ts": 2.0, "fn": "f"},
        {"kind": "task_dispatched", "task_id": "t1", "attempt": 0, "ts": 3.0,
         "node": "nodeA", "exec_worker": "workerB"},
        {"kind": "task_exec_start", "task_id": "t1", "attempt": 0, "ts": 4.0,
         "worker": "workerB", "node": "nodeA"},
        {"kind": "task_exec_end", "task_id": "t1", "attempt": 0, "ts": 5.0, "worker": "workerB"},
        {"kind": "task_finished", "task_id": "t1", "attempt": 0, "ts": 6.0, "status": "ok"},
    ]
    import itertools

    records = []
    for perm in itertools.permutations(evs):
        rec = {"task_id": "t1", "attempt": 0}
        for ev in perm:
            ts.fold(rec, ev)
        records.append(rec)
    first = records[0]
    assert first["state"] == ts.FINISHED
    assert first["node_id"] == "nodeA" and first["worker_id"] == "workerB"
    assert first["times"][ts.RUNNING] == 4.0 and first["times"]["exec_end"] == 5.0
    for rec in records[1:]:
        assert rec == first


def test_fold_failed_is_terminal_and_carries_error_type():
    rec = {"task_id": "t", "attempt": 0}
    ts.fold(rec, {"kind": "task_failed", "task_id": "t", "ts": 1.0,
                  "error_type": "ValueError"})
    ts.fold(rec, {"kind": "task_exec_start", "task_id": "t", "ts": 2.0})
    assert rec["state"] == ts.FAILED  # terminal: a late exec event can't revive it
    assert rec["error_type"] == "ValueError"
    # task_finished with status=error maps to FAILED too.
    rec2 = {"task_id": "t2", "attempt": 0}
    ts.fold(rec2, {"kind": "task_finished", "task_id": "t2", "ts": 1.0,
                   "status": "error", "error_type": "ZeroDivisionError"})
    assert rec2["state"] == ts.FAILED and rec2["error_type"] == "ZeroDivisionError"


# ---------------------------------------------------------------------------
# controller index: bounds, eviction, filters, truncation, cursor (no sockets)
# ---------------------------------------------------------------------------

def _mk_controller(**cfg_overrides):
    from ray_tpu.core.config import Config
    from ray_tpu.core.controller import Controller

    cfg = Config()
    for k, v in cfg_overrides.items():
        setattr(cfg, k, v)
    return Controller(cfg)


def _report(c, *events):
    c.handle_report_task_events(None, {"events": list(events)})


def _lifecycle(task_id, kind, attempt=0, **kw):
    return {"kind": kind, "task_id": task_id, "attempt": attempt,
            "ts": time.time(), **kw}


def test_task_index_bounded_terminal_first_eviction():
    c = _mk_controller(task_index_size=32)
    # 8 live tasks first (oldest), then a flood of finished ones.
    for i in range(8):
        _report(c, _lifecycle(f"live{i}", "task_exec_start", fn="live"))
    for i in range(100):
        _report(c, _lifecycle(f"done{i}", "task_finished", status="ok", fn="done"))
    assert len(c.task_index) == 32
    assert c.tasks_evicted == 76
    # The live (non-terminal) records survived: finished ones were shed first.
    live = [r for r in c.task_index.values() if r["state"] == ts.RUNNING]
    assert len(live) == 8
    # Eviction is surfaced on the events endpoint and list replies.
    out = c.handle_get_events(None, {"with_stats": True})
    assert out["dropped"]["tasks_evicted"] == 76
    assert c.handle_list_tasks(None, {})["evicted"] == 76
    # ... and raw-buffer trims don't touch the index (live-task state
    # survives task_events trims — the point of the index).
    c.task_events_dropped += 0
    before = dict(c.task_index)
    c.task_events.clear()
    assert c.task_index == before


def test_task_index_keyed_per_attempt():
    c = _mk_controller()
    _report(c, _lifecycle("t", "task_submitted", attempt=0, fn="f"))
    _report(c, _lifecycle("t", "task_failed", attempt=0, error_type="ConnectionLost"))
    _report(c, _lifecycle("t", "task_submitted", attempt=1, fn="f"))
    _report(c, _lifecycle("t", "task_finished", attempt=1, status="ok"))
    attempts = c.handle_get_task(None, {"task_id": "t"})
    assert [a["attempt"] for a in attempts] == [0, 1]
    assert attempts[0]["state"] == ts.FAILED
    assert attempts[0]["error_type"] == "ConnectionLost"
    assert attempts[1]["state"] == ts.FINISHED


def test_list_tasks_filters_and_truncation():
    c = _mk_controller()
    for i in range(10):
        _report(c, _lifecycle(f"a{i:02d}", "task_exec_start", fn="alpha_fn",
                              node="node1", job="jobA"))
    for i in range(5):
        _report(c, _lifecycle(f"b{i:02d}", "task_finished", status="ok",
                              fn="beta_fn", job="jobB"))
    out = c.handle_list_tasks(None, {"state": "RUNNING"})
    assert out["total"] == 10 and out["truncated"] == 0
    assert all(t["state"] == "RUNNING" for t in out["tasks"])
    out = c.handle_list_tasks(None, {"fn": "beta"})
    assert out["total"] == 5
    out = c.handle_list_tasks(None, {"job": "jobA"})
    assert out["total"] == 10
    out = c.handle_list_tasks(None, {"node": "node1"})
    assert out["total"] == 10
    # Truncation marker: total counts matches, tasks holds only the limit.
    out = c.handle_list_tasks(None, {"limit": 3})
    assert out["total"] == 15 and out["truncated"] == 12 and len(out["tasks"]) == 3
    # Newest first.
    assert out["tasks"][0]["task_id"] == "b04"
    # Summary rollup.
    s = c.handle_summary_tasks(None, {})
    assert s["summary"]["alpha_fn"]["states"]["RUNNING"] == 10
    assert s["summary"]["beta_fn"]["states"]["FINISHED"] == 5
    assert s["total_tasks"] == 15
    s = c.handle_summary_tasks(None, {"job": "jobB"})
    assert list(s["summary"]) == ["beta_fn"]


def test_unknown_event_kinds_do_not_index():
    c = _mk_controller()
    _report(c, {"kind": "x", "ts": 0.0}, {"kind": "span", "ts": 0.0, "task_id": "s"})
    assert c.task_index == {}


def test_get_task_events_since_cursor():
    c = _mk_controller(event_buffer_size=8)
    _report(c, *[_lifecycle(f"t{i}", "task_submitted") for i in range(6)])
    out = c.handle_get_task_events(None, {"since": 0, "limit": 4})
    assert len(out["events"]) == 4 and out["next"] == 4 and out["missed"] == 0
    assert out["truncated"] is True
    out = c.handle_get_task_events(None, {"since": out["next"], "limit": 100})
    assert len(out["events"]) == 2 and out["next"] == 6 and not out["truncated"]
    # Nothing new: an idle poll is an empty copy, not a 20k-event re-send.
    out = c.handle_get_task_events(None, {"since": out["next"], "limit": 100})
    assert out["events"] == [] and out["next"] == 6
    # Force a trim; a stale cursor reports exactly how many events it missed.
    _report(c, *[_lifecycle(f"u{i}", "task_submitted") for i in range(30)])
    assert c.task_events_dropped > 0
    out = c.handle_get_task_events(None, {"since": 6, "limit": 1000})
    assert out["missed"] == c.task_events_dropped - 6
    assert out["next"] == c.task_events_dropped + len(c.task_events)
    # The legacy no-cursor form still returns a plain list.
    assert isinstance(c.handle_get_task_events(None, {"limit": 5}), list)
    # A cursor past the end (controller restarted: base + buffer reset)
    # REWINDS to the current end instead of freezing on empty replies —
    # the poller adopts the smaller `next` and self-heals.
    end = c.task_events_dropped + len(c.task_events)
    out = c.handle_get_task_events(None, {"since": end + 10_000, "limit": 100})
    assert out["events"] == [] and out["next"] == end


# ---------------------------------------------------------------------------
# live cluster: RUNNING attribution + memory round trip
# ---------------------------------------------------------------------------

@rt.remote
def _sleepy(barrier_dir, i):
    import os
    import time as _t

    open(os.path.join(barrier_dir, f"started-{i}"), "w").close()
    _t.sleep(8)
    return i


@rt.remote
def _boom():
    raise ValueError("intended")


@rt.remote
class _Owner:
    def make(self, nbytes):
        self.ref = rt.put(b"m" * nbytes)
        return [self.ref]


@rt.remote
class _Borrower:
    def take(self, refs):
        self.held = refs[0]
        return len(rt.get(refs[0]))


def _wait_for(fn, timeout=20.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_state_api_live_cluster(tmp_path):
    from ray_tpu import state

    rt.init(num_cpus=4)
    try:
        refs = [_sleepy.remote(str(tmp_path), i) for i in range(2)]
        _wait_for(lambda: len(list(tmp_path.iterdir())) >= 1, what="task start")

        # RUNNING with node/worker attribution (events ride the debounced
        # flush, so poll briefly).
        running = _wait_for(
            lambda: state.list_tasks(state="RUNNING", fn="_sleepy")["tasks"],
            what="RUNNING task in index",
        )
        workers = {w["worker_id"]: w for w in state.list_workers()["workers"]}
        nodes = {n["node_id"] for n in state.list_nodes()["nodes"]}
        for t in running:
            assert t["node_id"] in nodes
            # worker ids in events are the 12-char form.
            assert any(w.startswith(t["worker_id"]) for w in workers)
            assert t["times"]["RUNNING"] >= t["times"]["PENDING_NODE_ASSIGNMENT"]

        # A failing task lands FAILED with the user exception's type.
        with pytest.raises(ValueError):
            rt.get(_boom.remote(), timeout=60)
        failed = _wait_for(
            lambda: [t for t in state.list_tasks(fn="_boom")["tasks"]
                     if t["state"] == "FAILED"],
            what="FAILED record",
        )
        assert failed[0]["error_type"] == "ValueError"

        assert rt.get(refs, timeout=60) == [0, 1]
        done = _wait_for(
            lambda: [t for t in state.list_tasks(fn="_sleepy")["tasks"]
                     if t["state"] == "FINISHED"] or None,
            what="FINISHED records",
        )
        assert {t["task_id"] for t in done} == {r.id.task_id().hex() for r in refs}
        summary = state.summary_tasks()["summary"]
        assert summary["_sleepy"]["states"]["FINISHED"] == 2

        # Nodes report object-store occupancy; workers are listed.
        n = state.list_nodes()["nodes"][0]
        assert "capacity" in n["store"] and n["workers"] >= 1

        # Dashboard passthrough: same queries over HTTP with query-string
        # filters (the /api/tasks|summary endpoints).
        import json as _json
        import urllib.request

        from ray_tpu.dashboard import start_dashboard, stop_dashboard

        port = start_dashboard(0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/tasks?fn=_sleepy&state=FINISHED", timeout=10
            ).read()
            payload = _json.loads(body)
            assert payload["total"] == 2 and len(payload["tasks"]) == 2
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/summary", timeout=10
            ).read()
            assert "_sleepy" in _json.loads(body)["summary"]
        finally:
            stop_dashboard()
    finally:
        rt.shutdown()


def test_memory_summary_owner_and_borrower():
    from ray_tpu import state

    rt.init(num_cpus=4)
    try:
        owner = _Owner.remote()
        borrower = _Borrower.remote()
        refs = rt.get(owner.make.remote(512 * 1024), timeout=60)  # shm-sized
        assert rt.get(borrower.take.remote(refs), timeout=60) == 512 * 1024
        oid = refs[0].id.hex()

        def check():
            ms = state.memory_summary()
            owners = [
                (w, o)
                for node in ms["nodes"] for w in node.get("workers", [])
                if "error" not in w for o in w.get("owned", []) if o["oid"] == oid
            ]
            borrows = [
                (w, b)
                for node in ms["nodes"] for w in node.get("workers", [])
                if "error" not in w for b in w.get("borrowed", []) if b["oid"] == oid
            ]
            drv = [b for b in ms["driver"]["borrowed"] if b["oid"] == oid]
            if owners and borrows and drv:
                return ms, owners, borrows, drv
            return None

        ms, owners, borrows, drv = _wait_for(check, what="owner+borrower visibility")
        (owner_w, owned_rec) = owners[0]
        # The object is attributed to its owning worker with both borrowers
        # counted (the borrower actor + the driver's ref).
        assert owned_rec["where"] == "shm" and owned_rec["size"] >= 512 * 1024
        assert owned_rec["borrowers"] == 2
        # ... and the borrower names the owner it borrows from.
        assert borrows[0][1]["owner_addr"] == owner_w["address"]
        assert drv[0]["owner_addr"] == owner_w["address"]
        # Per-node store occupancy rides the same reply.
        assert all("store" in node for node in ms["nodes"])
    finally:
        rt.shutdown()
