"""graftlint: the AST invariant checker for the async runtime.

Per-rule fixtures (firing / clean / suppressed-with-reason / suppressed-
without-reason) plus the whole-tree regression gate: the committed tree is
always at ZERO findings, and the machine-readable report lands in LINT.json
so the suppression inventory is diffable across PRs. Re-introducing a bare
``asyncio.create_task`` fire-and-forget fails both the tier-1 gate here and
``python -m ray_tpu lint``.
"""
import os
import textwrap

import pytest

import ray_tpu
from ray_tpu.analysis import (
    BAD_SUPPRESSION,
    UNUSED_SUPPRESSION,
    lint_paths,
    lint_source,
)

PKG_DIR = os.path.dirname(os.path.abspath(ray_tpu.__file__))
REPO_ROOT = os.path.dirname(PKG_DIR)


def _lint(src: str, path: str = "fixture.py"):
    return lint_source(textwrap.dedent(src), path)


def _rules_hit(result):
    return {f.rule for f in result.findings}


# ---------------------------------------------------------------------------
# bg-strong-ref
# ---------------------------------------------------------------------------

def test_bg_strong_ref_fires_on_dropped_task():
    r = _lint("""
        import asyncio

        async def f():
            asyncio.create_task(g())
            asyncio.ensure_future(h())
            loop.create_task(i())
    """)
    assert [f.line for f in r.findings if f.rule == "bg-strong-ref"] == [5, 6, 7]


def test_bg_strong_ref_quiet_when_retained():
    r = _lint("""
        import asyncio

        async def f(registry):
            t = asyncio.create_task(g())            # assigned AND used below
            self._task = asyncio.create_task(h())   # attribute
            registry.add(asyncio.create_task(i()))  # nested in a call
            await asyncio.create_task(j())          # awaited
            await t
            return asyncio.ensure_future(k())       # returned
    """)
    assert "bg-strong-ref" not in _rules_hit(r)


def test_bg_strong_ref_loop_carried_handle_is_used():
    """Cancel-previous/start-next: the load sits ABOVE the assignment but
    both live in the same loop — that is a use."""
    r = _lint("""
        import asyncio

        async def pump():
            t = None
            while True:
                if t is not None:
                    await t
                t = asyncio.create_task(g())
    """)
    assert "bg-strong-ref" not in _rules_hit(r)


def test_mac_before_pickle_recv_into_taints_the_buffer():
    r = _lint("""
        import pickle

        async def read_loop(loop, sock):
            buf = bytearray(1024)
            await loop.sock_recv_into(sock, buf)
            return pickle.loads(buf)
    """)
    assert "mac-before-pickle" in _rules_hit(r)


def test_bg_strong_ref_tuple_targets_and_load_order():
    # Tuple-positional assignment with no later use fires per dropped name.
    r = _lint("""
        import asyncio

        async def handler():
            t, u = asyncio.create_task(a()), asyncio.create_task(b())
    """)
    assert len([f for f in r.findings if f.rule == "bg-strong-ref"]) == 2
    # A load BEFORE the assignment is not a later use.
    r = _lint("""
        import asyncio

        async def handler():
            t = None
            print(t)
            t = asyncio.create_task(foo())
    """)
    assert "bg-strong-ref" in _rules_hit(r)


def test_bg_strong_ref_assigned_but_never_used_local():
    """A local only pins the task while the frame lives — assign-and-forget
    (or a mechanical `_ = create_task(...)`) is the bare-Expr bug aliased."""
    r = _lint("""
        import asyncio

        async def handler():
            t = asyncio.create_task(g())
            return True
    """)
    hits = [f for f in r.findings if f.rule == "bg-strong-ref"]
    assert len(hits) == 1 and "'t'" in hits[0].message
    # A use from a nested def (closure) counts.
    r = _lint("""
        import asyncio

        async def handler():
            t = asyncio.create_task(g())

            def on_done():
                t.cancel()

            register(on_done)
    """)
    assert "bg-strong-ref" not in _rules_hit(r)


def test_bg_strong_ref_suppressed_with_reason():
    r = _lint("""
        import asyncio

        async def f():
            asyncio.create_task(g())  # graftlint: disable=bg-strong-ref  droppable: best-effort cache warm
    """)
    assert "bg-strong-ref" not in _rules_hit(r)
    assert len(r.suppressions) == 1
    assert "cache warm" in r.suppressions[0].reason


def test_bg_strong_ref_suppression_without_reason_still_fails():
    r = _lint("""
        import asyncio

        async def f():
            asyncio.create_task(g())  # graftlint: disable=bg-strong-ref
    """)
    # The original finding survives AND the reasonless disable is reported.
    assert _rules_hit(r) == {"bg-strong-ref", BAD_SUPPRESSION}
    assert not r.suppressions


# ---------------------------------------------------------------------------
# no-blocking-in-async
# ---------------------------------------------------------------------------

def test_no_blocking_fires_inside_async_def():
    r = _lint("""
        import subprocess
        import time

        async def f(fut):
            time.sleep(1)
            subprocess.run(["ls"])
            fut.result(timeout=5)
    """)
    lines = [f.line for f in r.findings if f.rule == "no-blocking-in-async"]
    assert lines == [6, 7, 8]


def test_no_blocking_quiet_in_sync_and_executor_thunks():
    r = _lint("""
        import asyncio
        import time

        def sync_path():
            time.sleep(1)  # sync function: its caller owns the thread

        async def f(loop, fut):
            await asyncio.sleep(1)
            fut.result()  # bare result() on a done future is legal

            def thunk():
                time.sleep(1)  # nested sync def: runs on an executor thread

            await loop.run_in_executor(None, thunk)
    """)
    assert "no-blocking-in-async" not in _rules_hit(r)


def test_no_blocking_quiet_in_lambda_bodies():
    """A lambda body is deferred code — the idiomatic executor offload
    `run_in_executor(None, lambda: blocking())` must lint clean."""
    r = _lint("""
        import subprocess
        import time

        async def f(loop):
            await loop.run_in_executor(None, lambda: subprocess.run(["ls"]))
            cb = lambda: time.sleep(1)
            return cb
    """)
    assert "no-blocking-in-async" not in _rules_hit(r)


def test_no_blocking_quiet_in_decorators_and_defaults():
    """Decorator arguments and parameter defaults run at DEFINITION time on
    the defining thread — not inside the coroutine."""
    r = _lint("""
        import time

        @retry(delay=time.sleep(0))
        async def f(x=time.sleep(0)):
            pass
    """)
    assert "no-blocking-in-async" not in _rules_hit(r)


def test_no_blocking_suppression_cases():
    ok = _lint("""
        import time

        async def f():
            time.sleep(0)  # graftlint: disable=no-blocking-in-async  yields GIL only; sub-us by design
    """)
    assert "no-blocking-in-async" not in _rules_hit(ok)
    bad = _lint("""
        import time

        async def f():
            time.sleep(0)  # graftlint: disable=no-blocking-in-async
    """)
    assert _rules_hit(bad) == {"no-blocking-in-async", BAD_SUPPRESSION}


# ---------------------------------------------------------------------------
# mac-before-pickle
# ---------------------------------------------------------------------------

def test_mac_before_pickle_fires_on_unverified_wire_bytes():
    r = _lint("""
        import pickle

        async def read_loop(reader):
            data = await reader.readexactly(100)
            return pickle.loads(data)
    """)
    assert [f.line for f in r.findings if f.rule == "mac-before-pickle"] == [6]


def test_mac_before_pickle_quiet_when_verified_first():
    r = _lint("""
        import hmac
        import pickle

        async def read_loop(reader):
            data = await reader.readexactly(100)
            tag, body = data[:16], data[16:]
            if not hmac.compare_digest(tag, compute_tag(body)):
                return None
            return pickle.loads(body)

        def not_wire_data(blob):
            return pickle.loads(blob)  # not tainted: no socket read here
    """)
    assert "mac-before-pickle" not in _rules_hit(r)


def test_mac_before_pickle_taint_propagates_through_assignments():
    r = _lint("""
        import pickle

        async def read_loop(reader):
            raw = await reader.readexactly(100)
            view = memoryview(raw)
            body = view[16:]
            return pickle.loads(body)
    """)
    assert "mac-before-pickle" in _rules_hit(r)


def test_mac_before_pickle_tracks_taint_groups_separately():
    """Verifying ONE read must not whitelist a different, never-verified
    read later in the same function (per-taint-group dominance, not a
    function-global verified flag)."""
    r = _lint("""
        import hmac
        import pickle

        async def read_loop(reader):
            hdr = await reader.readexactly(16)
            if not hmac.compare_digest(hdr, expected_tag()):
                return None
            payload = await reader.readexactly(1000)  # second, unverified read
            return pickle.loads(payload)
    """)
    assert "mac-before-pickle" in _rules_hit(r)
    # And the verified group stays clean when both reads are bound by the
    # same verify call (tag compared against a digest of the payload).
    r = _lint("""
        import hmac
        import pickle

        async def read_loop(reader):
            tag = await reader.readexactly(16)
            payload = await reader.readexactly(1000)
            if not hmac.compare_digest(tag, digest_of(payload)):
                return None
            return pickle.loads(payload)
    """)
    assert "mac-before-pickle" not in _rules_hit(r)


def test_mac_before_pickle_direct_read_expression():
    """No assignment needed: unpickling the read expression itself fires."""
    r = _lint("""
        import pickle

        async def read_loop(reader):
            return pickle.loads(await reader.readexactly(10))
    """)
    assert "mac-before-pickle" in _rules_hit(r)


def test_mac_before_pickle_length_from_verified_header_does_not_launder():
    """A payload read SIZED by a verified header is still new, unverified
    wire bytes."""
    r = _lint("""
        import hmac
        import pickle

        async def read_loop(reader):
            hdr = await reader.readexactly(20)
            if not hmac.compare_digest(hdr[:16], expected()):
                return None
            plen = int.from_bytes(hdr[16:], "little")
            payload = await reader.readexactly(plen)
            return pickle.loads(payload)
    """)
    assert "mac-before-pickle" in _rules_hit(r)


def test_mac_before_pickle_augassign_accumulation_loop():
    r = _lint("""
        import pickle

        async def read_loop(reader):
            buf = b""
            while True:
                buf += await reader.read(100)
                if done(buf):
                    break
            return pickle.loads(buf)
    """)
    assert "mac-before-pickle" in _rules_hit(r)


def test_mac_before_pickle_mixed_groups_stay_unverified():
    """Mixing a never-verified read into verified data poisons the result —
    it does not launder the unverified bytes."""
    r = _lint("""
        import hmac
        import pickle

        async def read_loop(reader):
            a = await reader.readexactly(16)
            if not hmac.compare_digest(a, tag()):
                return None
            b = await reader.readexactly(1000)
            c = a + b
            return pickle.loads(c)
    """)
    assert "mac-before-pickle" in _rules_hit(r)


def test_mac_before_pickle_tracks_instance_attributes():
    r = _lint("""
        import pickle

        async def read_loop(self, reader):
            self.buf = await reader.readexactly(100)
            return pickle.loads(self.buf)
    """)
    assert "mac-before-pickle" in _rules_hit(r)


def test_mac_before_pickle_reassignment_is_a_strong_update():
    """Rebinding a verified name to a FRESH read must not inherit the old
    group's verified status — the common receive-loop shape reuses names."""
    r = _lint("""
        import hmac
        import pickle

        async def read_loop(reader):
            data = await reader.readexactly(16)
            if not hmac.compare_digest(data, session_tag()):
                return None
            data = await reader.readexactly(1000)  # reuse of a verified name
            return pickle.loads(data)
    """)
    assert "mac-before-pickle" in _rules_hit(r)
    # And rebinding to clean data drops the taint entirely.
    clean = _lint("""
        import pickle

        async def read_loop(reader):
            data = await reader.readexactly(100)
            data = local_cache()
            return pickle.loads(data)
    """)
    assert "mac-before-pickle" not in _rules_hit(clean)


def test_mac_before_pickle_walrus_and_annotated_assign_taint():
    walrus = _lint("""
        import pickle

        async def read_loop(reader):
            while (data := await reader.readexactly(100)):
                yield pickle.loads(data)
    """)
    assert "mac-before-pickle" in _rules_hit(walrus)
    annotated = _lint("""
        import pickle

        async def read_loop(reader):
            data: bytes = await reader.readexactly(100)
            return pickle.loads(data)
    """)
    assert "mac-before-pickle" in _rules_hit(annotated)


def test_mac_before_pickle_suppression_cases():
    ok = _lint("""
        import pickle

        async def read_loop(reader):
            data = await reader.readexactly(100)
            return pickle.loads(data)  # graftlint: disable=mac-before-pickle  loopback-only diagnostic socket
    """)
    assert "mac-before-pickle" not in _rules_hit(ok)
    bad = _lint("""
        import pickle

        async def read_loop(reader):
            data = await reader.readexactly(100)
            return pickle.loads(data)  # graftlint: disable=mac-before-pickle
    """)
    assert _rules_hit(bad) == {"mac-before-pickle", BAD_SUPPRESSION}


# ---------------------------------------------------------------------------
# counted-trims
# ---------------------------------------------------------------------------

def test_counted_trims_fires_on_silent_slice_delete_and_evict_pop():
    r = _lint("""
        class Buf:
            def trim(self):
                del self.events[:100]

            def evict(self):
                self.index.pop(next(iter(self.index)))
    """)
    lines = [f.line for f in r.findings if f.rule == "counted-trims"]
    assert lines == [4, 7]


def test_counted_trims_ignores_unbounded_clear():
    """`del x[:]` clears/consumes everything — not a bounded eviction."""
    r = _lint("""
        class Buf:
            def reset(self):
                del self.pending[:]
    """)
    assert "counted-trims" not in _rules_hit(r)


def test_counted_trims_quiet_with_counter():
    r = _lint("""
        class Buf:
            def trim(self):
                self.events_dropped += 100
                del self.events[:100]

            def evict(self):
                self.index.pop(next(iter(self.index)))
                self.entries_evicted += 1

            def evict_metric(self):
                self.cache.pop(next(iter(self.cache)))
                self._cache_evicted.inc()
    """)
    assert "counted-trims" not in _rules_hit(r)


def test_counted_trims_deque_maxlen():
    silent = _lint("""
        from collections import deque

        class Buf:
            def __init__(self):
                self.recent = deque(maxlen=128)
    """)
    assert "counted-trims" in _rules_hit(silent)
    counted = _lint("""
        from collections import deque

        class Buf:
            def __init__(self):
                self.recent = deque(maxlen=128)

            def add(self, x):
                if len(self.recent) == self.recent.maxlen:
                    self.recent_dropped += 1
                self.recent.append(x)
    """)
    assert "counted-trims" not in _rules_hit(counted)
    unbounded = _lint("""
        from collections import deque

        q = deque(maxlen=None)
    """)
    assert "counted-trims" not in _rules_hit(unbounded)


def test_counted_trims_deque_positional_maxlen():
    """maxlen passed positionally — deque(iterable, N) — bounds the buffer
    exactly like the keyword form and must not slip past the rule (coverage
    gap found reviewing the streaming fast lane's bounded buffer)."""
    silent = _lint("""
        from collections import deque

        class Buf:
            def __init__(self):
                self.recent = deque([], 128)
    """)
    assert "counted-trims" in _rules_hit(silent)
    counted = _lint("""
        from collections import deque

        class Buf:
            def __init__(self):
                self.recent = deque([], 128)

            def add(self, x):
                if len(self.recent) == self.recent.maxlen:
                    self.recent_dropped += 1
                self.recent.append(x)
    """)
    assert "counted-trims" not in _rules_hit(counted)
    # deque(iterable) alone and an explicit positional None stay unbounded.
    unbounded = _lint("""
        from collections import deque

        a = deque(range(3))
        b = deque([], None)
    """)
    assert "counted-trims" not in _rules_hit(unbounded)


def test_counted_trims_fires_outside_functions_too():
    module_level = _lint("""
        CACHE = {}
        CACHE.pop(next(iter(CACHE)))
        del HISTORY[:100]
    """)
    lines = [f.line for f in module_level.findings if f.rule == "counted-trims"]
    assert lines == [3, 4]
    module_counted = _lint("""
        CACHE = {}
        CACHE.pop(next(iter(CACHE)))
        cache_evicted += 1
    """)
    assert "counted-trims" not in _rules_hit(module_counted)


def test_counted_trims_suppression_cases():
    ok = _lint("""
        class Buf:
            def consume(self):
                del self.buf[:4]  # graftlint: disable=counted-trims  consuming parsed bytes, not discarding data
    """)
    assert "counted-trims" not in _rules_hit(ok)
    # Closing-line placement on a black-formatted multi-line evict works too
    # (findings carry the statement's whole span, not just its first line).
    multiline = _lint("""
        class Buf:
            def evict(self):
                self.index.pop(
                    next(iter(self.index))
                )  # graftlint: disable=counted-trims  LRU routing hints, not data
    """)
    assert not multiline.findings and len(multiline.suppressions) == 1
    bad = _lint("""
        class Buf:
            def consume(self):
                del self.buf[:4]  # graftlint: disable=counted-trims
    """)
    assert _rules_hit(bad) == {"counted-trims", BAD_SUPPRESSION}


# ---------------------------------------------------------------------------
# loop-thread-race
# ---------------------------------------------------------------------------

_RACE_SRC = """
    class W:
        async def on_loop(self):
            self.state = "loop"

        def on_thread(self):
            self.state = "thread"{suffix}

        async def go(self, loop):
            await loop.run_in_executor(None, self.on_thread)
"""


def test_loop_thread_race_fires_without_lock():
    r = _lint(_RACE_SRC.format(suffix=""))
    hits = [f for f in r.findings if f.rule == "loop-thread-race"]
    assert len(hits) == 1 and hits[0].line == 7
    assert "self.state" in hits[0].message


def test_loop_thread_race_quiet_with_lock_or_without_dispatch():
    locked = _lint("""
        class W:
            async def on_loop(self):
                with self._lock:
                    self.state = "loop"

            def on_thread(self):
                with self._lock:
                    self.state = "thread"

            async def go(self, loop):
                await loop.run_in_executor(None, self.on_thread)
    """)
    assert "loop-thread-race" not in _rules_hit(locked)
    undispatched = _lint("""
        class W:
            async def on_loop(self):
                self.state = "loop"

            def plain_method(self):
                self.state = "sync"  # never handed to an executor
    """)
    assert "loop-thread-race" not in _rules_hit(undispatched)


def test_loop_thread_race_suppression_cases():
    ok = _lint(_RACE_SRC.format(
        suffix='  # graftlint: disable=loop-thread-race  single int store; torn reads impossible'
    ))
    assert "loop-thread-race" not in _rules_hit(ok)
    bad = _lint(_RACE_SRC.format(suffix="  # graftlint: disable=loop-thread-race"))
    assert _rules_hit(bad) == {"loop-thread-race", BAD_SUPPRESSION}


# ---------------------------------------------------------------------------
# fsm-emitter (path-scoped to core/worker.py)
# ---------------------------------------------------------------------------

_FSM_FULL = """
    class W:
        def run(self, spec):
            self._task_event("task_pending_args", spec)
            self._task_event("task_submitted", spec)
            self._task_event("task_dispatched", spec)
            self._task_event("task_exec_start", spec)
            self._task_event("task_finished", spec){extra}
"""


def test_fsm_emitter_fires_on_unmapped_kind():
    src = _FSM_FULL.format(extra='\n            self._task_event("task_went_sideways", spec)')
    r = _lint(src, path="fake/core/worker.py")
    hits = [f for f in r.findings if f.rule == "fsm-emitter"]
    assert len(hits) == 1 and "task_went_sideways" in hits[0].message


def test_fsm_emitter_quiet_on_mapped_kinds_and_scoped_to_worker():
    r = _lint(_FSM_FULL.format(extra=""), path="fake/core/worker.py")
    assert "fsm-emitter" not in _rules_hit(r)
    # Same unmapped kind outside core/worker.py: rule does not apply.
    src = _FSM_FULL.format(extra='\n            self._task_event("task_went_sideways", spec)')
    r = _lint(src, path="fake/other.py")
    assert "fsm-emitter" not in _rules_hit(r)


def test_fsm_emitter_coverage_check():
    # Dropping a whole lifecycle phase (no exec_start emitter) is a finding.
    r = _lint("""
        class W:
            def run(self, spec):
                self._task_event("task_finished", spec)
    """, path="fake/core/worker.py")
    hits = [f for f in r.findings if f.rule == "fsm-emitter"]
    assert hits and any("RUNNING" in f.message for f in hits)


def test_fsm_emitter_suppression_cases():
    src = _FSM_FULL.format(
        extra='\n            self._task_event("task_debug_probe", spec)'
              '  # graftlint: disable=fsm-emitter  debug-only kind, index ignores it on purpose'
    )
    r = _lint(src, path="fake/core/worker.py")
    assert "fsm-emitter" not in _rules_hit(r)
    src = _FSM_FULL.format(
        extra='\n            self._task_event("task_debug_probe", spec)'
              '  # graftlint: disable=fsm-emitter'
    )
    r = _lint(src, path="fake/core/worker.py")
    assert _rules_hit(r) == {"fsm-emitter", BAD_SUPPRESSION}


# ---------------------------------------------------------------------------
# suppression machinery
# ---------------------------------------------------------------------------

def test_suppression_prose_after_comma_and_unknown_rule():
    # A reason whose first word follows the comma is prose, not a rule id.
    r = _lint("""
        import asyncio

        async def f():
            asyncio.create_task(g())  # graftlint: disable=bg-strong-ref, intentional best-effort probe
    """)
    assert not r.findings
    assert r.suppressions[0].rules == ("bg-strong-ref",)
    assert r.suppressions[0].reason == "intentional best-effort probe"
    # A misspelled rule id fails loud instead of silently suppressing nothing.
    r = _lint("""
        x = 1  # graftlint: disable=bg-strongref  typo in the rule id
    """)
    hits = [f for f in r.findings if f.rule == BAD_SUPPRESSION]
    assert len(hits) == 1 and "not a rule id" in hits[0].message


def test_multi_rule_suppression_with_spaces():
    r = _lint("""
        import asyncio
        import time

        async def f():
            time.sleep(asyncio.ensure_future(g()))  # graftlint: disable=no-blocking-in-async, bg-strong-ref  fixture exercising both rules at once
    """)
    assert not r.findings
    assert len(r.suppressions) == 1 and r.suppressions[0].rules == (
        "no-blocking-in-async",
        "bg-strong-ref",
    )
    assert r.suppressions[0].reason.startswith("fixture")


def test_bad_suppression_is_a_finding_even_with_nothing_to_suppress():
    r = _lint("""
        x = 1  # graftlint: disable=bg-strong-ref
    """)
    assert _rules_hit(r) == {BAD_SUPPRESSION}


def test_suppression_only_silences_named_rules():
    r = _lint("""
        import asyncio
        import time

        async def f():
            time.sleep(asyncio.create_task(g()))  # graftlint: disable=no-blocking-in-async  fixture: wrong-rule disable
    """)
    # The sleep is silenced; the create_task inside it is retained (call
    # argument), so the only signal left is... nothing. Now the inverse:
    r = _lint("""
        import asyncio

        async def f():
            asyncio.create_task(g())  # graftlint: disable=no-blocking-in-async  wrong rule named
    """)
    assert "bg-strong-ref" in _rules_hit(r)


def test_suppression_inside_string_literal_is_data_not_directive():
    r = _lint('''
        FIXTURE = """
        asyncio.create_task(g())  # graftlint: disable=bg-strong-ref
        """
        OTHER = "x  # graftlint: disable=counted-trims"
    ''')
    assert not r.findings and not r.suppressions


def test_suppression_on_closing_line_of_multiline_statement():
    """A disable comment where formatters put it — on the closing line of a
    multi-line call — still suppresses, and is counted as used."""
    r = _lint("""
        import asyncio

        async def f():
            asyncio.create_task(
                g()
            )  # graftlint: disable=bg-strong-ref  best-effort prefetch, droppable
    """)
    assert not r.findings and len(r.suppressions) == 1


def test_unused_suppression_is_a_finding():
    r = _lint("""
        x = compute()  # graftlint: disable=bg-strong-ref  was needed before the refactor
    """)
    hits = [f for f in r.findings if f.rule == UNUSED_SUPPRESSION]
    assert len(hits) == 1 and "stale" in hits[0].message
    assert not r.suppressions  # an unused disable is not part of the inventory


def test_syntax_error_is_reported_not_crashed():
    r = _lint("def broken(:\n")
    assert r.errors and not r.findings


# ---------------------------------------------------------------------------
# counted-sheds
# ---------------------------------------------------------------------------

def test_counted_sheds_fires_on_uncounted_deadline_raise():
    r = _lint("""
        def gate(ctx, now):
            if now >= ctx.deadline:
                raise DeadlineExceeded("expired at gate")
    """)
    hits = [f for f in r.findings if f.rule == "counted-sheds"]
    assert len(hits) == 1 and hits[0].line == 4


def test_counted_sheds_fires_on_uncounted_shed_function():
    r = _lint("""
        class Proxy:
            def _shed_response(self, klass):
                return ("429 Too Many Requests", b"{}", "application/json")
    """)
    hits = [f for f in r.findings if f.rule == "counted-sheds"]
    assert len(hits) == 1 and "shed path" in hits[0].message


def test_counted_sheds_quiet_when_counted():
    r = _lint("""
        class Proxy:
            def _shed_response(self, klass):
                self._shed_total.inc(tags={"class": klass})
                return ("429 Too Many Requests", b"{}", "application/json")

        def gate(ctx, now, stats):
            if now >= ctx.deadline:
                stats.expired_count += 1
                raise DeadlineExceeded("expired at gate")
    """)
    assert "counted-sheds" not in _rules_hit(r)


def test_counted_sheds_ignores_shed_substrings_and_other_raises():
    """"finished"/"watershed" contain "shed" as a substring but are not shed
    paths; raising other exception types is not a request drop."""
    r = _lint("""
        def on_finished(self):
            raise TimeoutError("not a qos drop")

        def watershed_model(x):
            return x
    """)
    assert "counted-sheds" not in _rules_hit(r)


def test_counted_sheds_suppressed_with_reason():
    r = _lint("""
        def gate(ctx, now):
            if now >= ctx.deadline:
                raise DeadlineExceeded("x")  # graftlint: disable=counted-sheds  caller tallies this drop
    """)
    assert "counted-sheds" not in _rules_hit(r)
    assert len(r.suppressions) == 1


def test_counted_sheds_suppressed_without_reason_still_fires():
    r = _lint("""
        def gate(ctx, now):
            if now >= ctx.deadline:
                raise DeadlineExceeded("x")  # graftlint: disable=counted-sheds
    """)
    assert "counted-sheds" in _rules_hit(r)
    assert BAD_SUPPRESSION in _rules_hit(r)


# ---------------------------------------------------------------------------
# counted-transfers
# ---------------------------------------------------------------------------

def test_counted_transfers_fires_on_uncounted_sendfile():
    r = _lint("""
        import os

        def serve(self, fd, pos, left):
            os.sendfile(self.sock.fileno(), fd, pos, left)
    """)
    hits = [f for f in r.findings if f.rule == "counted-transfers"]
    assert len(hits) == 1 and hits[0].line == 5


def test_counted_transfers_fires_on_uncounted_sendmsg():
    r = _lint("""
        def ship(self, bufs):
            sent = self.sock.sendmsg(bufs)
            return sent
    """)
    hits = [f for f in r.findings if f.rule == "counted-transfers"]
    assert len(hits) == 1 and "sendmsg" in hits[0].message


def test_counted_transfers_quiet_when_counted():
    r = _lint("""
        import os

        def serve(self, fd, pos, left):
            n = os.sendfile(self.sock.fileno(), fd, pos, left)
            self.bytes_out += n

        def ship(self, bufs, metrics):
            sent = self.sock.sendmsg(bufs)
            metrics.transfer_bytes.inc(sent)
    """)
    assert "counted-transfers" not in _rules_hit(r)


def test_counted_transfers_ignores_plain_send_and_names():
    """Bare socket.send/sendall and functions merely named sendfile are not
    kernel-assisted transfer syscalls tracked by this rule."""
    r = _lint("""
        def relay(self, data):
            self.sock.sendall(data)

        def sendfile(path):
            return path
    """)
    assert "counted-transfers" not in _rules_hit(r)


def test_counted_transfers_suppressed_with_reason():
    r = _lint("""
        def finish(self, mv):
            await_result = self.loop.sock_sendall(self.sock, mv)  # graftlint: disable=counted-transfers  caller counted the whole frame
            return await_result
    """)
    assert "counted-transfers" not in _rules_hit(r)
    assert len(r.suppressions) == 1


# ---------------------------------------------------------------------------
# the tier-1 gate: whole tree at zero, report written, CLI contract
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# chaos-gate
# ---------------------------------------------------------------------------

def test_chaos_gate_fires_on_dynamic_site_name():
    r = _lint("""
        from ray_tpu import chaos

        def serve(name):
            chaos.maybe_inject(name)
            chaos.maybe_inject("prefix." + name)
            chaos.maybe_inject(f"site.{name}")
    """)
    assert [f.line for f in r.findings if f.rule == "chaos-gate"] == [5, 6, 7]


def test_chaos_gate_fires_on_duplicate_site_name():
    r = _lint("""
        from ray_tpu import chaos as _chaos

        def a():
            _chaos.maybe_inject("node.thing")

        def b():
            _chaos.maybe_inject("node.thing")
    """)
    hits = [f for f in r.findings if f.rule == "chaos-gate"]
    assert len(hits) == 1 and hits[0].line == 8 and "duplicate" in hits[0].message


def test_chaos_gate_duplicate_detection_is_tree_wide(tmp_path):
    from ray_tpu.analysis import lint_paths

    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("from ray_tpu import chaos\n\n\ndef f():\n    chaos.maybe_inject('x.y')\n")
    b.write_text("from ray_tpu import chaos\n\n\ndef g():\n    chaos.maybe_inject('x.y')\n")
    result = lint_paths([str(a), str(b)])
    hits = [f for f in result.findings if f.rule == "chaos-gate"]
    assert len(hits) == 1 and hits[0].path == str(b), hits


def test_chaos_gate_fires_on_adhoc_branching_and_internals():
    r = _lint("""
        from ray_tpu import chaos

        def f():
            if chaos.active() is not None:   # ad-hoc chaos branch
                raise RuntimeError("my own fault")
            chaos._PLAN = None               # plan internals
    """)
    assert [f.line for f in r.findings if f.rule == "chaos-gate"] == [5, 7]


def test_chaos_gate_fires_on_internal_imports_outside_pkg():
    r = _lint("""
        from ray_tpu.chaos import injection_log
        from ray_tpu.chaos.plan import maybe_inject
    """)
    assert [f.line for f in r.findings if f.rule == "chaos-gate"] == [2, 3]


def test_chaos_gate_clean_on_sanctioned_idiom():
    r = _lint("""
        from ray_tpu import chaos as _chaos

        def write_frame(self, data):
            fault = _chaos.maybe_inject("my.site", peer=self.peer)
            if fault is not None and fault.kind == "drop":
                return
            _chaos.install_from_json("{}")
            series = _chaos.metrics_series()
    """)
    assert "chaos-gate" not in _rules_hit(r)


def test_chaos_gate_exempts_the_chaos_package_itself():
    r = _lint("""
        from ray_tpu.chaos import plan as _plan

        def runner():
            if _plan.active() is not None:
                pass
    """, path="ray_tpu/chaos/scenarios.py")
    assert "chaos-gate" not in _rules_hit(r)


def test_chaos_gate_suppression_cases():
    fires = """
        from ray_tpu import chaos

        def f(name):
            chaos.maybe_inject(name){}
    """
    r = _lint(fires.format("  # graftlint: disable=chaos-gate  fixture exercises dynamic names"))
    assert "chaos-gate" not in _rules_hit(r)
    r = _lint(fires.format("  # graftlint: disable=chaos-gate"))
    assert {"chaos-gate", BAD_SUPPRESSION} <= _rules_hit(r)


def test_chaos_site_catalog_matches_tree():
    """Every cataloged site has exactly one gate in the tree and every gate
    is cataloged — the catalog IS the schedule-validation ground truth."""
    from ray_tpu.analysis import lint_paths
    from ray_tpu.chaos.sites import SITES

    result = lint_paths([PKG_DIR])
    woven = set()
    for _path, stats in result.stats.items():
        woven.update(stats.get("chaos-gate", {}).get("sites", []))
    assert woven == set(SITES), (
        f"cataloged-but-unwoven: {sorted(set(SITES) - woven)}; "
        f"woven-but-uncataloged: {sorted(woven - set(SITES))}"
    )


def test_whole_tree_zero_findings():
    """The regression gate that keeps future PRs honest: every invariant
    violation in the shipped tree is either fixed or suppressed with a
    written reason. (LINT.json is written by test_aaa_lint_gate.py — the
    fail-fast gate that runs first — so the report has a single writer.)"""
    result = lint_paths([PKG_DIR])
    assert not result.errors, result.errors
    assert not result.findings, "\n" + "\n".join(f.render() for f in result.findings)
    # The scan is alive: it saw the tree's suppressions and the fsm emitters.
    assert result.files > 50
    worker_stats = next(
        (s["fsm-emitter"] for p, s in result.stats.items() if "fsm-emitter" in s), None
    )
    assert worker_stats and worker_stats["emitters"] >= 1


def test_overlapping_paths_lint_each_file_once(tmp_path):
    bad = tmp_path / "regress.py"
    bad.write_text("import asyncio\n\n\nasync def f():\n    asyncio.create_task(g())\n")
    result = lint_paths([str(bad), str(tmp_path)])
    assert result.files == 1
    assert len(result.findings) == 1


def test_nonexistent_path_is_an_error_not_a_green_gate(tmp_path):
    """`lint <typo>` must not exit 0 having linted zero files."""
    result = lint_paths([str(tmp_path / "no_such_dir")])
    assert result.errors and result.files == 0
    from ray_tpu.__main__ import main

    with pytest.raises(SystemExit) as exc:
        main(["lint", str(tmp_path / "no_such_dir")])
    assert exc.value.code == 1


def test_cli_exits_nonzero_on_reintroduced_fire_and_forget(tmp_path):
    bad = tmp_path / "regress.py"
    bad.write_text(
        "import asyncio\n\n\nasync def f():\n    asyncio.create_task(g())\n"
    )
    from ray_tpu.__main__ import main

    with pytest.raises(SystemExit) as exc:
        main(["lint", str(bad)])
    assert exc.value.code == 1
    with pytest.raises(SystemExit) as exc:
        main(["lint", os.path.join(PKG_DIR, "analysis")])
    assert exc.value.code == 0


def test_json_report_shape_is_stable(tmp_path):
    bad = tmp_path / "regress.py"
    bad.write_text("import asyncio\n\n\nasync def f():\n    asyncio.create_task(g())\n")
    result = lint_paths([str(bad)])
    report = result.to_json()
    assert report["version"] == 2
    # v2: every registered rule gets a rollup, firing or not.
    assert {"bg-strong-ref", "chaos-gate", "rpc-verb-contract",
            "metric-contract", "dtype-kind"} <= set(report["rules"])
    entry = report["rules"]["bg-strong-ref"]
    assert entry["findings"] == 1 and entry["suppressed"] == 0
    assert entry["sites"][0].startswith(str(bad) + ":5:")
    assert report["rules"]["chaos-gate"] == {
        "findings": 0, "suppressed": 0, "sites": []}
    assert "index" in report
