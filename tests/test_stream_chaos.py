"""Seeded chaos on the streaming fast lane: a dropped generator batch frame
must surface through the EXISTING retry/failure path, not a silent stall.

Own module (no shared rt.init fixture): the chaos spec must be armed in the
cluster config BEFORE the driver connects so the spawned executor worker
installs it ahead of its first task.
"""
import json
import time

import ray_tpu as rt


def test_dropped_batch_frame_retries_and_dedups():
    """rpc.stream.item kind=drop: the SECOND batch frame of the first
    attempt is lost along with its transport (the shape a real frame loss
    takes — a conn that eats a frame dies), which the caller observes as
    connection loss on the in-flight push. The existing retry path resubmits
    on a fresh worker; the replay re-ships indices from 0 and the owner-side
    reserve() dedups, so the consumer still sees every index exactly once,
    in order."""
    from ray_tpu.core import api as _api
    from ray_tpu.core.api import Cluster, init, shutdown
    from ray_tpu.core.config import Config

    cfg = Config().apply_env()
    cfg.chaos_spec = json.dumps({"seed": 7, "rules": [
        # attempt-scoped: only the FIRST attempt's frames count hits, so the
        # replay (a fresh worker process with fresh per-rule counters) ships
        # clean instead of deterministically re-dropping its own 2nd frame.
        {"site": "rpc.stream.item", "kind": "drop", "nth": 2,
         "ctx": {"attempt": "0"}},
    ]})
    cluster = Cluster(initialize_head=False, config=cfg)
    cluster.add_node(num_cpus=2)
    init(address=cluster.address, config=cfg)
    try:
        @rt.remote(num_returns="streaming")
        def tokens(n):
            for i in range(n):
                time.sleep(0.05)  # paces frames: >= 2 per attempt
                yield i

        got = [rt.get(ref, timeout=120) for ref in tokens.remote(8)]
        assert got == list(range(8)), got
        core = _api._require_worker()
        retried = [e for e in core.task_events
                   if e["kind"] == "task_failed" and e.get("retrying")]
        assert retried, (
            "no retrying task_failed event — the chaos drop never fired and "
            "this test asserted nothing"
        )
    finally:
        shutdown()
        cluster.shutdown()
        # The driver adopted the cluster's chaos spec at register_job; disarm
        # so later test modules in this process run chaos-free.
        from ray_tpu import chaos

        chaos.uninstall()
