"""Checkpoint & weight-publication plane (ray_tpu/ckpt/): async sharded
saves with content-addressed dedup, atomic manifest commit, resharded
restore, chunk-refcount retention, controller registry, serve hot-swap.

The pure-plane tests run against tmp storage with no cluster; the
registry/publication tests run one shared session; the chaos smoke runs the
seeded ckpt_kill_mid_save scenario end to end.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import ckpt


@pytest.fixture(autouse=True)
def _chaos_clean():
    from ray_tpu.chaos import plan as _plan

    _plan.uninstall()
    yield
    _plan.uninstall()


_FROZEN = np.arange(32 * 24, dtype=np.float32).reshape(32, 24)


def _tree(step: int) -> dict:
    # hot: distinct bytes per chunk AND per step (value-offset keeps it
    # disjoint from frozen's bytes, so within-save dedup stays zero).
    hot = (1000.0 + np.arange(32 * 16, dtype=np.float32) * (step + 1)).reshape(32, 16)
    return {
        "model": {
            "frozen": _FROZEN,  # never changes across steps: dedup fodder
            "hot": hot,
        },
        "opt": {"step": np.int64(step), "nested": [np.ones(7), np.zeros((3, 3))]},
    }


# ---------------------------------------------------------------------------
# save / dedup / restore (no cluster)
# ---------------------------------------------------------------------------

def test_save_restore_roundtrip_nested_tree(tmp_path):
    saver = ckpt.AsyncSaver(str(tmp_path), chunk_size=1024)
    try:
        m = saver.save(1, _tree(1))
        got = ckpt.restore_tree(m, saver.chunks, verify=True)
        assert np.array_equal(got["model"]["frozen"], _FROZEN)
        assert np.array_equal(got["model"]["hot"], _tree(1)["model"]["hot"])
        assert got["opt"]["step"] == 1 and got["opt"]["step"].shape == ()
        assert isinstance(got["opt"]["nested"], list)
        assert np.array_equal(got["opt"]["nested"][1], np.zeros((3, 3)))
    finally:
        saver.close()


def test_incremental_save_dedups_unchanged_chunks(tmp_path):
    saver = ckpt.AsyncSaver(str(tmp_path), chunk_size=512)
    try:
        m1 = saver.save(1, _tree(1))
        m2 = saver.save(2, _tree(2))  # only "hot" (and the scalar) changed
        assert m1["bytes_new"] == m1["bytes_total"]  # cold store: full save
        assert m2["bytes_new"] < m2["bytes_total"]
        assert m2.dedup_ratio > 0.4, m2.summary()
        # The listing carries the ratio (the /api/checkpoints column).
        rows = saver.manifests.list()
        assert rows[-1]["dedup_ratio"] == round(m2.dedup_ratio, 4)
    finally:
        saver.close()


def test_async_save_overlaps_step_path(tmp_path):
    """save_async returns after the snapshot; the commit lands in the
    background and the future resolves to the committed manifest."""
    saver = ckpt.AsyncSaver(str(tmp_path), chunk_size=4096)
    try:
        futs = [saver.save_async(s, _tree(s)) for s in range(3)]
        assert saver.last_stall_s < 10  # the handoff timed, not the write
        manifests = [f.result(timeout=60) for f in futs]
        assert [m["step"] for m in manifests] == [0, 1, 2]
        assert saver.manifests.list_ids() == sorted(m.ckpt_id for m in manifests)
    finally:
        saver.close()


def test_manifest_atomicity_under_injected_chunk_write_failure(tmp_path):
    """The satellite invariant: a failed chunk write aborts the WHOLE
    attempt — nothing staged survives, no uncommitted manifest is ever
    listed, and the attempt's already-written chunks are reclaimed."""
    from ray_tpu import chaos

    chaos.install(chaos.FaultSchedule.from_spec({
        "seed": 0,
        "rules": [{"site": "ckpt.chunk.write", "kind": "error", "nth": 3}],
    }))
    saver = ckpt.AsyncSaver(str(tmp_path), chunk_size=512)
    try:
        fut = saver.save_async(1, _tree(1))
        with pytest.raises(chaos.ChaosError):
            fut.result(timeout=60)
        assert saver.manifests.list_ids() == []
        assert saver.manifests.verify()["ok"], saver.manifests.verify()
        assert os.listdir(saver.manifests.staging) == []
        chaos.uninstall()
        m = saver.save(2, _tree(2))  # the plane recovers on the next step
        assert saver.manifests.list_ids() == [m.ckpt_id]
        got = ckpt.restore(m, saver.chunks)
        assert np.array_equal(got["model/frozen"], _FROZEN)
    finally:
        saver.close()


def test_worker_death_mid_save_never_commits(tmp_path):
    """Gang protocol: one of two workers dies mid-save (its part never
    acks) — commit_parts discards the attempt and reclaims the orphaned
    chunks of the dead attempt."""
    from ray_tpu import chaos

    store = ckpt.ChunkStore(str(tmp_path), chunk_size=1024)
    ms = ckpt.ManifestStore(str(tmp_path), chunk_store=store)
    rows = 16
    data = np.arange(rows * 32, dtype=np.float32).reshape(rows, 32)

    def snap(rank):
        lo, hi = rank * (rows // 2), (rank + 1) * (rows // 2)
        return {"w": {"dtype": "float32", "shape": [rows, 32],
                      "shards": [([[lo, hi], [0, 32]], data[lo:hi])]}}

    chaos.install(chaos.FaultSchedule.from_spec({
        "seed": 1,
        "rules": [{"site": "ckpt.worker.kill_mid_save", "kind": "kill",
                   "ctx": {"rank": "1"}}],
    }))
    parts = []
    for rank in range(2):
        try:
            parts.append(ckpt.write_part(store, snap(rank), rank=rank, step=1))
        except ckpt.WorkerKilledMidSave:
            pass
    chaos.uninstall()
    assert len(parts) == 1
    with pytest.raises(ckpt.CommitAborted):
        ckpt.commit_parts(ms, ckpt.new_ckpt_id(1), 1, parts, expected_workers=2)
    assert ms.list_ids() == []
    assert ms.verify()["ok"], ms.verify()  # rank 0's chunks reclaimed
    # Same snapshot with both workers alive commits and restores whole.
    parts = [ckpt.write_part(store, snap(r), rank=r, step=2) for r in range(2)]
    m = ckpt.commit_parts(ms, ckpt.new_ckpt_id(2), 2, parts, expected_workers=2)
    assert np.array_equal(ckpt.restore(m, store)["w"], data)


def test_resharded_restore_n_to_m_byte_identical(tmp_path):
    """An N-shard checkpoint restores onto M target shards byte-identically
    to the same-mesh restore — rows, columns, and 2-D tiles."""
    store = ckpt.ChunkStore(str(tmp_path), chunk_size=256)
    ms = ckpt.ManifestStore(str(tmp_path), chunk_store=store)
    rows, cols = 24, 20
    data = np.arange(rows * cols, dtype=np.float64).reshape(rows, cols)
    parts = []
    for rank in range(4):  # N=4 source hosts, row-sharded
        lo, hi = rank * (rows // 4), (rank + 1) * (rows // 4)
        parts.append(ckpt.write_part(store, {
            "w": {"dtype": "float64", "shape": [rows, cols],
                  "shards": [([[lo, hi], [0, cols]], data[lo:hi])]}
        }, rank=rank, step=1))
    m = ckpt.commit_parts(ms, ckpt.new_ckpt_id(1), 1, parts, expected_workers=4)
    same_mesh = ckpt.restore(m, store)["w"]
    assert same_mesh.tobytes() == data.tobytes()
    # M=3 uneven row shards (crossing source boundaries).
    cuts = [0, 5, 17, rows]
    got = np.concatenate([
        ckpt.restore(m, store, target_indices={"w": [[cuts[i], cuts[i + 1]], [0, cols]]})["w"]
        for i in range(3)
    ])
    assert got.tobytes() == data.tobytes()
    # M=2 COLUMN shards: every target fetches strided ranges from every
    # source shard (the general redistribution case).
    left = ckpt.restore(m, store, target_indices={"w": [[0, rows], [0, 7]]})["w"]
    right = ckpt.restore(m, store, target_indices={"w": [[0, rows], [7, cols]]})["w"]
    assert np.array_equal(np.concatenate([left, right], axis=1), data)
    # A 2-D tile in the middle.
    tile = ckpt.restore(m, store, target_indices={"w": [[3, 21], [4, 15]]})["w"]
    assert np.array_equal(tile, data[3:21, 4:15])


def test_restore_reads_only_needed_bytes(tmp_path):
    """The memory-efficiency contract: restoring a small slice reads a
    small fraction of the checkpoint's bytes (ranged preads, not whole
    chunks of the whole array)."""
    store = ckpt.ChunkStore(str(tmp_path), chunk_size=1024)
    ms = ckpt.ManifestStore(str(tmp_path), chunk_store=store)
    data = np.zeros((256, 256), np.float32)  # 256 KiB
    part = ckpt.write_part(store, {
        "w": {"dtype": "float32", "shape": [256, 256],
              "shards": [([[0, 256], [0, 256]], data)]}}, step=1)
    m = ckpt.commit_parts(ms, ckpt.new_ckpt_id(1), 1, [part], 1)

    read = {"n": 0}
    orig = store.pread

    def counting_pread(digest, off, ln):
        read["n"] += ln
        return orig(digest, off, ln)

    store.pread = counting_pread
    got = ckpt.restore(m, store, target_indices={"w": [[0, 8], [0, 256]]})["w"]
    assert got.shape == (8, 256)
    assert read["n"] == 8 * 256 * 4  # exactly the slice, not the array


def test_chunk_refcount_eviction_topk(tmp_path):
    """Top-K retention deletes only chunks no surviving manifest references;
    the shared frozen chunk outlives every eviction."""
    saver = ckpt.AsyncSaver(str(tmp_path), chunk_size=1 << 20, num_to_keep=2)
    try:
        frozen_digest = None
        for s in range(4):
            m = saver.save(s, _tree(s))
            for d, _sz in m["arrays"]["model/frozen"]["shards"][0]["chunks"]:
                frozen_digest = d
        ids = saver.manifests.list_ids()
        assert len(ids) == 2
        assert [saver.manifests.load(i)["step"] for i in sorted(ids,
                key=lambda i: saver.manifests.load(i)["step"])] == [2, 3]
        assert saver.manifests.evicted_manifests == 2
        assert saver.manifests.evicted_chunks > 0  # old hot chunks reclaimed
        assert saver.chunks.contains(frozen_digest)  # shared chunk survived
        ver = saver.manifests.verify()
        assert ver["ok"], ver  # refcounts balance: no orphans, no missing
    finally:
        saver.close()


def test_manifest_corruption_detected_on_verify(tmp_path):
    saver = ckpt.AsyncSaver(str(tmp_path), chunk_size=1024)
    try:
        m = saver.save(1, _tree(1))
        digest = m["arrays"]["model/hot"]["shards"][0]["chunks"][0][0]
        with open(saver.chunks.path(digest), "r+b") as f:
            f.seek(0)
            f.write(b"\xff\xff\xff\xff")
        with pytest.raises(ckpt.ChunkCorruption):
            ckpt.restore(m, saver.chunks, verify=True)
    finally:
        saver.close()


# ---------------------------------------------------------------------------
# CheckpointManager satellites (train/checkpoint.py)
# ---------------------------------------------------------------------------

def test_checkpoint_manager_torn_register_not_adopted(tmp_path, monkeypatch):
    """Kill mid-copy: the out-of-storage copy path stages first, so a crash
    leaves only .staging garbage — a reloaded manager never lists (and the
    storage root never contains) a torn checkpoint_NNNNNN dir."""
    from ray_tpu.train import CheckpointManager

    storage = str(tmp_path / "runs")
    src = tmp_path / "src"
    src.mkdir()
    (src / "a.bin").write_bytes(b"x" * 128)
    (src / "b.bin").write_bytes(b"y" * 128)
    mgr = CheckpointManager(storage)

    def torn_copytree(s, d, **kw):
        os.makedirs(d)
        shutil.copy(os.path.join(s, "a.bin"), d)  # half the payload...
        raise OSError("killed mid-copy")  # ...then the crash

    monkeypatch.setattr("ray_tpu.train.checkpoint.shutil.copytree", torn_copytree)
    with pytest.raises(OSError):
        mgr.register(str(src), {"acc": 0.5})
    monkeypatch.undo()
    assert not [d for d in os.listdir(storage) if d.startswith("checkpoint_")]
    mgr2 = CheckpointManager(storage)  # reload: sweeps staging, adopts nothing
    assert mgr2.latest is None
    assert not os.path.exists(os.path.join(storage, ".staging"))
    # And a clean register still lands atomically afterwards.
    c = mgr2.register(str(src), {"acc": 0.9})
    assert sorted(os.listdir(c.path)) == ["a.bin", "b.bin"]


def test_checkpoint_manager_dangling_evict_entries_repaired(tmp_path):
    """An eviction that crashed after rmtree but before the index
    repersisted: reload filters the dangling entry AND rewrites the state
    file, and evictions are tallied."""
    from ray_tpu.train import CheckpointManager

    storage = str(tmp_path / "runs")
    mgr = CheckpointManager(storage)
    paths = []
    for i in range(3):
        src = tmp_path / f"src{i}"
        src.mkdir()
        (src / "x.txt").write_text(str(i))
        paths.append(mgr.register(str(src), {"i": i}).path)
    shutil.rmtree(paths[0])  # the simulated crash-after-rmtree
    mgr2 = CheckpointManager(storage)
    assert [c.path for _s, _i, c in mgr2._checkpoints] == paths[1:]
    st = json.load(open(os.path.join(storage, "checkpoint_manager.json")))
    assert len(st["checkpoints"]) == 2  # filter-AND-repersist
    # Eviction tally (train.checkpoint.evicted_total feeds the reporter).
    mgr3 = CheckpointManager(str(tmp_path / "runs2"), num_to_keep=1)
    for i in range(3):
        src = tmp_path / f"top{i}"
        src.mkdir()
        (src / "x.txt").write_text(str(i))
        mgr3.register(str(src), {"i": i})
    assert mgr3.evicted_total == 2


def test_checkpoint_manager_releases_manifest_refs_on_eviction(tmp_path):
    """The retention fold: evicting a manifest_ref checkpoint dir releases
    its manifest's chunk refcounts through the attached ManifestStore."""
    from ray_tpu.train import CheckpointManager

    storage = str(tmp_path / "plane")
    saver = ckpt.AsyncSaver(storage, chunk_size=1024)
    try:
        mgr = CheckpointManager(str(tmp_path / "runs"), num_to_keep=1,
                                manifest_store=saver.manifests)
        for s in range(2):
            m = saver.save(s, _tree(s))
            ref = tmp_path / f"ref{s}"
            ref.mkdir()
            (ref / "manifest_ref.json").write_text(json.dumps(
                {"ckpt_id": m.ckpt_id, "step": s, "storage": storage}))
            mgr.register(str(ref), {"step": s})
        assert len(saver.manifests.list_ids()) == 1  # step 0's manifest released
        assert saver.manifests.load(saver.manifests.list_ids()[0])["step"] == 1
        assert saver.manifests.verify()["ok"]
    finally:
        saver.close()


def test_checkpoint_manager_lazy_manifest_fold(tmp_path):
    """Without an attached store (the TrainController shape — eviction in a
    different process than the savers), the fold opens a ManifestStore
    lazily from the ref's storage root and still reclaims chunks."""
    from ray_tpu.train import CheckpointManager

    storage = str(tmp_path / "plane")
    saver = ckpt.AsyncSaver(storage, chunk_size=1024)
    try:
        mgr = CheckpointManager(str(tmp_path / "runs"), num_to_keep=1)
        for s in range(3):
            m = saver.save(s, _tree(s))
            ref = tmp_path / f"ref{s}"
            ref.mkdir()
            (ref / "manifest_ref.json").write_text(json.dumps(
                {"ckpt_id": m.ckpt_id, "step": s, "storage": storage}))
            mgr.register(str(ref), {"step": s})
        remaining = ckpt.ManifestStore(storage)
        assert len(remaining.list_ids()) == 1
        assert remaining.load(remaining.list_ids()[0])["step"] == 2
        assert remaining.verify()["ok"]
    finally:
        saver.close()


def test_close_drains_queued_saves(tmp_path):
    """close() writes queued saves out (their futures resolve) instead of
    dropping them — a dropped save would hang any result() waiter."""
    saver = ckpt.AsyncSaver(str(tmp_path), chunk_size=4096)
    futs = [saver.save_async(s, _tree(s)) for s in range(3)]
    saver.close()
    ids = [f.result(timeout=1).ckpt_id for f in futs]  # already resolved
    assert saver.manifests.list_ids() == sorted(ids)


def test_commit_parts_dedups_replicated_rectangles(tmp_path):
    """A leaf replicated across ranks contributes ONE shard per rectangle
    to the merged manifest (restore reads it once, coverage stays exact)."""
    store = ckpt.ChunkStore(str(tmp_path), chunk_size=1024)
    ms = ckpt.ManifestStore(str(tmp_path), chunk_store=store)
    rep = np.arange(64, dtype=np.float32)
    parts = [ckpt.write_part(store, {
        "rep": {"dtype": "float32", "shape": [64],
                "shards": [([[0, 64]], rep)]}}, rank=r, step=1) for r in range(3)]
    m = ckpt.commit_parts(ms, ckpt.new_ckpt_id(1), 1, parts, expected_workers=3)
    assert len(m["arrays"]["rep"]["shards"]) == 1
    assert np.array_equal(ckpt.restore(m, store)["rep"], rep)


# ---------------------------------------------------------------------------
# the seeded chaos scenario (fresh in-process cluster per run — MUST come
# before the shared-session tests: a scenario refuses to run while this
# process is already a driver, and module fixtures tear down at module end)
# ---------------------------------------------------------------------------

def test_ckpt_chaos_scenario_smoke():
    """The seeded ckpt_kill_mid_save scenario end to end (quick shape):
    aborted attempts invisible, committed manifests byte-identical after
    the faults, refcounts balanced after eviction, delayed swap lands."""
    from ray_tpu.chaos.scenarios import run_scenario

    report = run_scenario("ckpt_kill_mid_save", seed=11, quick=True)
    assert report["ok"], report
    assert report["details"]["aborted"] >= 2
    assert report["details"]["committed"] >= 2
    assert report["invariants"]["faults_visible_in_metrics"]["ok"]


def test_ckpt_scenario_replays_identically():
    from ray_tpu.chaos.scenarios import run_scenario

    r1 = run_scenario("ckpt_kill_mid_save", seed=77, quick=True)
    assert r1["ok"], r1
    r2 = run_scenario("ckpt_kill_mid_save", seed=77, quick=True)
    assert r2["ok"], r2
    assert r1["injections"] and r1["injections"] == r2["injections"]


# ---------------------------------------------------------------------------
# controller registry + publication + hot-swap (one shared session)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ckpt_cluster():
    from ray_tpu import serve

    rt.init(num_cpus=8)
    serve.start(proxy=False)
    yield rt
    serve.shutdown()
    rt.shutdown()


def test_registry_state_api_and_dashboard(ckpt_cluster, tmp_path):
    from ray_tpu import chaos, state

    saver = ckpt.AsyncSaver(str(tmp_path), chunk_size=2048, channel="regtest")
    try:
        m1 = saver.save(1, _tree(1))
        chaos.install(chaos.FaultSchedule.from_spec({
            "seed": 0,
            "rules": [{"site": "ckpt.chunk.write", "kind": "error", "nth": 1}]}))
        with pytest.raises(chaos.ChaosError):
            saver.save(2, _tree(2))
        chaos.uninstall()
        out = state.list_checkpoints(channel="regtest")
        by_status = {c["status"] for c in out["checkpoints"]}
        assert by_status == {"committed", "aborted"}
        committed = [c for c in out["checkpoints"] if c["status"] == "committed"]
        assert committed[0]["ckpt_id"] == m1.ckpt_id
        assert committed[0]["dedup_ratio"] == 0.0  # cold store: full save
        assert out["channels"]["regtest"] == m1.ckpt_id  # aborted never published
        # Filters + truncation markers follow the list conventions.
        only_aborted = state.list_checkpoints(channel="regtest", status="aborted")
        assert only_aborted["total"] == 1 and only_aborted["truncated"] == 0
        # Dashboard route.
        import urllib.request

        from ray_tpu.dashboard import start_dashboard, stop_dashboard

        port = start_dashboard(0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/checkpoints?channel=regtest&status=committed",
                    timeout=30) as resp:
                body = json.loads(resp.read())
            assert [c["ckpt_id"] for c in body["checkpoints"]] == [m1.ckpt_id]
        finally:
            stop_dashboard()
    finally:
        saver.close()


def test_list_checkpoints_cli(ckpt_cluster, tmp_path, capsys, monkeypatch):
    import argparse

    from ray_tpu import scripts

    saver = ckpt.AsyncSaver(str(tmp_path), chunk_size=2048, channel="clitest")
    try:
        m = saver.save(7, _tree(7))
    finally:
        saver.close()
    # The session is already this process's driver; skip the CLI redial.
    monkeypatch.setattr(scripts, "_connect_driver", lambda addr: rt)
    scripts.cmd_list(argparse.Namespace(
        address=None, kind="checkpoints", state=None, fn="clitest",
        node=None, job=None, limit=50))
    out = capsys.readouterr().out
    assert m.ckpt_id in out and "committed" in out and "dedup" in out


def _plane_train_fn(config):
    import numpy as np

    from ray_tpu import train as _train

    ctx = _train.get_context()
    fut = None
    for s in range(config["steps"]):
        tree = {"w": np.full(128, float(s), np.float32)}
        if ctx.get_world_rank() == 0:
            fut = _train.save_pytree_async(tree, {"step": s})
        else:
            _train.report({"step": s})
    if fut is not None:
        # The session guarantee: result() happens-after the checkpoint
        # report is queued, so the controller's final poll absorbs it.
        fut.result(timeout=120)


def test_train_session_plane_saves_fold_into_manager(ckpt_cluster, tmp_path):
    """save_pytree_async end to end through a real gang: the committed
    manifest's ref dir rides the normal report/adopt path, and the adopted
    checkpoint restores to the last step's weights."""
    from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig

    trainer = DataParallelTrainer(
        _plane_train_fn,
        train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=2, resources_per_worker={"CPU": 1}),
        run_config=RunConfig(name="plane", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    ref = json.load(open(os.path.join(result.checkpoint.path, "manifest_ref.json")))
    m = ckpt.load_manifest(ref["storage"], ref["ckpt_id"])
    got = ckpt.restore(m, ckpt.ChunkStore(ref["storage"]))
    assert np.array_equal(got["w"], np.full(128, 2.0, np.float32))


class _Weighted:
    """Serve callable whose responses must never tear: (version, sum) are
    read under the same lock the swap writes them under."""

    def __init__(self, storage, channel):
        self._lock = threading.Lock()
        self.version = "init"
        self.w = np.ones(512, np.float64)
        self._sub = ckpt.WeightSubscriber(
            channel, self._swap, poll_interval_s=0.2, storage_root=storage)

    def _swap(self, tree, summary):
        with self._lock:  # the admission gate: one pointer flip, atomic
            self.w = tree["w"]
            self.version = summary["ckpt_id"]

    def __call__(self, _request):
        with self._lock:
            return {"version": self.version, "sum": float(self.w.sum())}

    def swaps(self):
        return self._sub.swaps

    def __raytpu_exit__(self):
        self._sub.stop()


def test_serve_replica_hot_swap_no_torn_reads(ckpt_cluster, tmp_path):
    """Replicas serve the OLD weights until the swap completes, then the
    new — and every response is internally consistent (its sum matches its
    version's weights: a torn read would pair old sum with new version or
    a half-swapped tree)."""
    from ray_tpu import serve

    storage = str(tmp_path / "weights")
    channel = "swaptest"
    app = serve.deployment(_Weighted, name="Weighted", max_ongoing_requests=4)
    handle = serve.run(app.bind(storage, channel), name="swapapp", http=False)
    expected = {"init": float(np.ones(512).sum())}
    try:
        r = handle.remote({}).result(timeout=60)
        assert (r["version"], r["sum"]) == ("init", expected["init"])
        # Background load while checkpoints publish underneath it.
        stop = threading.Event()
        seen: list = []
        errs: list = []

        def flood():
            while not stop.is_set():
                try:
                    seen.append(handle.remote({}).result(timeout=30))
                except Exception as e:  # pragma: no cover - fails the assert below
                    errs.append(repr(e))

        threads = [threading.Thread(target=flood) for _ in range(3)]
        for t in threads:
            t.start()
        store = ckpt.ChunkStore(storage, chunk_size=4096)
        ms = ckpt.ManifestStore(storage, chunk_store=store)
        last_id = None
        for s in range(1, 4):
            w = np.full(512, float(s * 10), np.float64)
            part = ckpt.write_part(store, {
                "w": {"dtype": "float64", "shape": [512],
                      "shards": [([[0, 512]], w)]}}, step=s)
            m = ckpt.commit_parts(ms, ckpt.new_ckpt_id(s), s, [part], 1)
            ckpt.publish_checkpoint(m, channel)
            expected[m.ckpt_id] = float(w.sum())
            last_id = m.ckpt_id
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if handle.remote({}).result(timeout=30)["version"] == m.ckpt_id:
                    break
                time.sleep(0.05)
            else:
                raise AssertionError(f"replica never swapped to {m.ckpt_id}")
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs
        assert len(seen) > 0
        for r in seen:  # the no-torn-read invariant
            assert r["sum"] == expected[r["version"]], r
        versions = {r["version"] for r in seen}
        assert "init" in versions or len(seen) < 5  # old weights served pre-swap
        assert handle.remote({}).result(timeout=30)["version"] == last_id
    finally:
        serve.delete("swapapp")
