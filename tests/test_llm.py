"""LLM engine + serving: KV-cache decode correctness vs full forward,
continuous batching consistency, TTFT reporting, serve integration.
Reference analogue: python/ray/llm/tests (MockVLLMEngine-based serving tests,
SURVEY §4) — here the engine is real, just tiny and on CPU."""
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm import EngineConfig, LLMEngine
from ray_tpu.models import TransformerConfig
from ray_tpu.models.transformer import forward, init_params

CFG = TransformerConfig(
    vocab_size=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
    max_seq_len=128, dtype=jnp.float32, attention_impl="reference",
)


def _naive_greedy(params, prompt, n):
    toks = list(map(int, prompt))
    out = []
    for _ in range(n):
        logits, _ = forward(params, jnp.asarray([toks], jnp.int32), CFG)
        nxt = int(jnp.argmax(logits[0, -1]))
        toks.append(nxt)
        out.append(nxt)
    return out


@pytest.fixture(scope="module")
def engine():
    return LLMEngine(CFG, engine_config=EngineConfig(max_slots=4, max_seq=128, prefill_buckets=(16, 32, 64)))


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_cached_decode_matches_full_forward(engine):
    prompt = np.array([5, 17, 42, 7, 23], np.int32)
    want = _naive_greedy(engine.params, prompt, 12)
    got = engine.generate(prompt, max_tokens=12)
    assert got["tokens"] == want
    assert got["ttft_s"] is not None and got["ttft_s"] > 0


def test_continuous_batching_matches_solo(engine):
    """A request joining mid-decode must not perturb an in-flight one, and
    both must equal their solo outputs (slot isolation)."""
    p1 = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)
    p2 = np.array([2, 7, 1, 8], np.int32)
    solo1 = engine.generate(p1, max_tokens=10)["tokens"]
    solo2 = engine.generate(p2, max_tokens=10)["tokens"]

    engine.add_request("a", p1, 10)
    results = {}
    for _ in range(3):  # a starts decoding alone
        for rid, ev in engine.step().items():
            if ev.get("finished"):
                results[rid] = ev["tokens"]
    engine.add_request("b", p2, 10)  # b joins mid-flight
    while engine.has_work():
        for rid, ev in engine.step().items():
            if ev.get("finished"):
                results[rid] = ev["tokens"]
    assert results["a"] == solo1
    assert results["b"] == solo2


def test_slot_reuse_after_finish(engine):
    """More requests than slots: queueing + slot recycling must preserve
    per-request outputs."""
    prompts = [np.arange(3 + i, dtype=np.int32) % 97 for i in range(9)]
    solos = [engine.generate(p, max_tokens=6)["tokens"] for p in prompts]
    for i, p in enumerate(prompts):
        engine.add_request(f"r{i}", p, 6)
    results = {}
    while engine.has_work():
        for rid, ev in engine.step().items():
            if ev.get("finished"):
                results[rid] = ev["tokens"]
    for i in range(9):
        assert results[f"r{i}"] == solos[i], i


def test_eos_stops_generation():
    eng = LLMEngine(
        CFG,
        engine_config=EngineConfig(max_slots=2, max_seq=128, prefill_buckets=(16,), eos_id=0),
    )
    out = eng.generate(np.array([5, 6, 7], np.int32), max_tokens=40)
    if 0 in out["tokens"]:
        assert out["tokens"].index(0) == len(out["tokens"]) - 1


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_llm_serve_deployment():
    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    rt.init(num_cpus=8)
    serve.start(proxy=False)
    try:
        app = build_llm_app(
            model_config=dict(
                vocab_size=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                d_ff=128, max_seq_len=128, attention_impl="reference",
            ),
            engine_config={"max_slots": 4, "max_seq": 128, "prefill_buckets": (16, 32)},
        )
        handle = serve.run(app, name="llm_app", http=False)
        # Concurrent requests batch at iteration level on one replica.
        resps = [
            handle.remote({"tokens": [3, 1, 4, 1, 5], "max_tokens": 8})
            for _ in range(4)
        ]
        outs = [r.result(timeout=120) for r in resps]
        first = outs[0]["tokens"]
        assert len(first) == 8
        for o in outs:
            assert o["tokens"] == first  # same prompt, greedy -> same output
            assert o["ttft_s"] is not None
        serve.delete("llm_app")
    finally:
        serve.shutdown()
        rt.shutdown()


# ---------------------------------------------------------------------------
# paged KV cache
# ---------------------------------------------------------------------------

def test_paged_pool_memory_independent_of_slots():
    """The point of paging: slot count is a scheduling knob, not a memory
    multiplier. 32 slots over a 16-page pool uses 16 pages of HBM, not
    32 x max_seq."""
    ec = EngineConfig(max_slots=32, max_seq=128, kv_layout="paged", page_size=16, total_pages=17,
                      prefill_buckets=(16,), decode_block=2)
    eng = LLMEngine(CFG, engine_config=ec)
    assert eng.k_pages.shape[2] == 17 * 16  # pool tokens, NOT 32*128
    out = eng.generate([1, 2, 3], max_tokens=4)
    assert len(out["tokens"]) == 4


def test_paged_admission_waits_for_pages_then_proceeds():
    """Pool smaller than the aggregate demand: admission queues on the page
    budget (not slot count) and every request still completes."""
    ec = EngineConfig(max_slots=8, max_seq=128, kv_layout="paged", page_size=16, total_pages=9,
                      prefill_buckets=(16,), decode_block=2)
    eng = LLMEngine(CFG, engine_config=ec)
    # Each request needs ceil((3 + 8 + 2)/16) = 1 page prompt... force more:
    # prompt 3 + max_tokens 20 + block 2 = 25 -> 2 pages. Pool has 8 usable.
    for r in range(8):
        eng.add_request(f"q{r}", [1, 2, 3], 20)
    results = {}
    concurrent_seen = 0
    while eng.has_work():
        active = sum(1 for s in eng.slots if s is not None)
        concurrent_seen = max(concurrent_seen, active)
        for rid, ev in eng.step().items():
            if ev.get("finished"):
                results[rid] = ev["tokens"]
    assert len(results) == 8
    assert concurrent_seen <= 4  # 8 usable pages / 2 pages each
    first = results["q0"]
    assert all(results[f"q{r}"] == first for r in range(8))  # same prompt, greedy


def test_paged_pages_recycled_after_finish():
    ec = EngineConfig(max_slots=2, max_seq=128, kv_layout="paged", page_size=16, total_pages=9,
                      prefill_buckets=(16,), decode_block=2)
    eng = LLMEngine(CFG, engine_config=ec)
    free0 = len(eng.free_pages)
    for _ in range(3):
        eng.generate([4, 5, 6], max_tokens=6)
    assert len(eng.free_pages) == free0  # every reservation returned


def test_paged_abort_frees_pages():
    ec = EngineConfig(max_slots=2, max_seq=128, kv_layout="paged", page_size=16, total_pages=9,
                      prefill_buckets=(16,), decode_block=2)
    eng = LLMEngine(CFG, engine_config=ec)
    free0 = len(eng.free_pages)
    eng.add_request("gone", [1, 2, 3], 100)
    eng.step()  # admitted: pages reserved, decoding
    assert len(eng.free_pages) < free0
    eng.abort("gone")
    assert len(eng.free_pages) == free0
    assert not eng.has_work()
    # Engine still serves after the abort.
    out = eng.generate([1, 2, 3], max_tokens=4)
    assert len(out["tokens"]) == 4


def test_paged_decode_matches_across_pool_layouts():
    """Same request, different page pools (dense parity vs tight pool with
    non-trivial page scatter): identical greedy tokens."""
    prompt = [9, 8, 7, 6, 5, 4, 3, 2, 1]
    outs = []
    for total_pages in (0, 12):
        ec = EngineConfig(max_slots=3, max_seq=128, kv_layout="paged", page_size=16,
                          prefill_buckets=(16,), total_pages=total_pages,
                          decode_block=4)
        eng = LLMEngine(CFG, engine_config=ec)
        # Fragment the free list so page tables are non-contiguous.
        eng.generate([1, 2], max_tokens=3)
        eng.generate([3, 4, 5], max_tokens=5)
        outs.append(eng.generate(prompt, max_tokens=12)["tokens"])
    assert outs[0] == outs[1]


def test_dense_and_paged_layouts_agree():
    """Same request through both KV layouts: greedy tokens agree (the layout
    is a memory/performance knob, not a numerics change). The two attention
    algorithms accumulate in different orders, so a near-tie between top-2
    logits could legitimately flip ONE argmax and cascade — require exact
    agreement up to such a first divergence, with a long matching prefix."""
    prompt = [7, 3, 11, 2]
    outs = {}
    for layout in ("dense", "paged"):
        eng = LLMEngine(CFG, engine_config=EngineConfig(
            max_slots=2, max_seq=128, kv_layout=layout,
            **({"page_size": 16} if layout == "paged" else {}),
            prefill_buckets=(16,), decode_block=4,
        ))
        outs[layout] = eng.generate(prompt, max_tokens=10)["tokens"]
    a, b = outs["dense"], outs["paged"]
    # First token comes from the (identical) prefill math: must match exactly.
    assert a[0] == b[0], outs
    agree = next((i for i in range(10) if a[i] != b[i]), 10)
    assert agree >= 6, f"layouts diverged at step {agree}: {outs}"
