"""Log monitor: tailer unit tests + end-to-end worker-print-to-driver."""
import asyncio
import os
import time

import ray_tpu as rt
from ray_tpu.log_monitor import LogMonitor


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_tailer_reads_incrementally(tmp_path):
    batches = []

    async def publish(b):
        batches.append(b)

    mon = LogMonitor(str(tmp_path), publish)
    p = tmp_path / "worker-abc123.out"
    p.write_bytes(b"hello\nworld\npart")
    _run(mon.poll_once())
    assert batches == [
        {"worker_id": "abc123", "stream": "stdout", "lines": ["hello", "world"]}
    ]
    # The partial line is held back until its newline arrives.
    with open(p, "ab") as f:
        f.write(b"ial\n")
    _run(mon.poll_once())
    assert batches[-1]["lines"] == ["partial"]


def test_tailer_stderr_and_truncation(tmp_path):
    batches = []

    async def publish(b):
        batches.append(b)

    mon = LogMonitor(str(tmp_path), publish)
    p = tmp_path / "worker-w1.err"
    p.write_bytes(b"boom\n")
    _run(mon.poll_once())
    assert batches[-1]["stream"] == "stderr"
    # Truncation (log rotation) restarts from byte 0 on the next poll.
    p.write_bytes(b"x\n")
    _run(mon.poll_once())  # detects shrink
    _run(mon.poll_once())  # reads from the top
    assert batches[-1]["lines"] == ["x"]


def test_tailer_truncation_emits_same_poll(tmp_path):
    """A shrunk file resets the read offset AND re-reads in the same poll —
    no silent gap until the next write lands."""
    batches = []

    async def publish(b):
        batches.append(b)

    mon = LogMonitor(str(tmp_path), publish)
    p = tmp_path / "worker-w3.out"
    p.write_bytes(b"one\ntwo\n")
    _run(mon.poll_once())
    assert batches[-1]["lines"] == ["one", "two"]
    p.write_bytes(b"fresh\n")  # in-place truncate + rewrite, smaller
    _run(mon.poll_once())
    assert batches[-1]["lines"] == ["fresh"]


def test_tailer_rotation_new_inode_resets_offset(tmp_path):
    """Rotation replaces the path with a NEW file. When the replacement has
    already grown past the old offset, size alone cannot detect it — the
    inode check must reset the offset or lines are skipped/garbled."""
    batches = []

    async def publish(b):
        batches.append(b)

    mon = LogMonitor(str(tmp_path), publish)
    p = tmp_path / "worker-w4.out"
    p.write_bytes(b"aaaa\n")
    _run(mon.poll_once())
    assert batches[-1]["lines"] == ["aaaa"]
    # Rotate: move the old file away, recreate the path BIGGER than the old
    # offset (5 bytes) so the size heuristic alone would not fire.
    os.rename(p, tmp_path / "worker-w4.out.1")
    p.write_bytes(b"rotated-1\nrotated-2\n")
    assert os.path.getsize(p) > 5
    _run(mon.poll_once())
    assert batches[-1]["lines"] == ["rotated-1", "rotated-2"]
    # Tailing continues from the new file's offset afterwards.
    with open(p, "ab") as f:
        f.write(b"rotated-3\n")
    _run(mon.poll_once())
    assert batches[-1]["lines"] == ["rotated-3"]


def test_tailer_skips_huge_backlog(tmp_path):
    from ray_tpu import log_monitor as lm

    batches = []

    async def publish(b):
        batches.append(b)

    mon = LogMonitor(str(tmp_path), publish)
    p = tmp_path / "worker-w2.out"
    p.write_bytes(b"y" * (lm.MAX_BACKLOG_BYTES + 50) + b"\ntail-line\n")
    _run(mon.poll_once())
    # Only the bounded backlog is replayed; the tail line must be present.
    assert batches and batches[-1]["lines"][-1] == "tail-line"


@rt.remote
def _shout(msg):
    print(msg, flush=True)
    return True


def test_worker_prints_reach_driver(capsys):
    rt.init(num_cpus=2)
    try:
        assert rt.get(_shout.remote("log-monitor-e2e-sentinel"), timeout=60)
        deadline = time.time() + 15
        seen = ""
        while time.time() < deadline:
            seen += capsys.readouterr().out
            if "log-monitor-e2e-sentinel" in seen:
                break
            time.sleep(0.2)
        assert "log-monitor-e2e-sentinel" in seen
        # The line carries the producing worker prefix.
        line = next(l for l in seen.splitlines() if "sentinel" in l)
        assert line.startswith("(")
    finally:
        rt.shutdown()
