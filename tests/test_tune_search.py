"""Tune search layer: Searcher ABC plumbing, TPE model-based search beating
random on a seeded synthetic objective, and sweep-level resume after the
controller dies mid-sweep (reference: tune/search/searcher.py contract,
optuna-style model-based plugins, experiment-state restore)."""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

# Search-algorithm batteries (TPE/BOHB/median-stopping statistical runs dominate the tier-1 budget); tier-1 runs -m "not slow".
pytestmark = pytest.mark.slow

import ray_tpu as rt
from ray_tpu import tune
from ray_tpu.train.config import RunConfig
from ray_tpu.tune import TPESearcher, TuneConfig, Tuner


@pytest.fixture(scope="module", autouse=True)
def _session():
    rt.init(num_cpus=8)
    yield
    rt.shutdown()


def _objective(config):
    # Smooth unimodal bowl: best at x=0.3, lr=1e-2.
    x = config["x"]
    lr = config["lr"]
    score = -((x - 0.3) ** 2) - (np.log10(lr) + 2.0) ** 2
    tune.report({"score": float(score)})


def _run_search(search_alg, num_samples, seed, tmp):
    tuner = Tuner(
        _objective,
        param_space={"x": tune.uniform(-2.0, 2.0), "lr": tune.loguniform(1e-5, 1.0)},
        tune_config=TuneConfig(
            num_samples=num_samples, metric="score", mode="max",
            search_alg=search_alg, max_concurrent_trials=1, seed=seed,
        ),
        run_config=RunConfig(name=f"s{seed}-{'tpe' if search_alg else 'rnd'}",
                             storage_path=tmp),
    )
    grid = tuner.fit()
    return max(r.metrics["score"] for r in grid if r.error is None)


def test_tpe_beats_random_on_synthetic_objective(tmp_path):
    n = 24
    best_tpe = _run_search(
        TPESearcher(
            {"x": tune.uniform(-2.0, 2.0), "lr": tune.loguniform(1e-5, 1.0)},
            metric="score", mode="max", n_initial=6, seed=0,
        ),
        n, 0, str(tmp_path),
    )
    best_rnd = _run_search(None, n, 0, str(tmp_path))
    # Same budget: the model-based searcher concentrates near the optimum.
    assert best_tpe > best_rnd, (best_tpe, best_rnd)
    assert best_tpe > -0.4, f"TPE best {best_tpe} nowhere near the optimum"


def test_searcher_observes_and_suggests():
    sp = {"x": tune.uniform(0.0, 1.0)}
    s = TPESearcher(sp, metric="m", mode="max", n_initial=3, seed=1)
    for i in range(6):
        cfg = s.suggest(f"t{i}")
        assert 0.0 <= cfg["x"] <= 1.0
        s.on_trial_complete(f"t{i}", {"m": -abs(cfg["x"] - 0.5)})
    # Post-warmup suggestions are model-based: clustered near 0.5.
    sugg = [s.suggest(f"p{i}")["x"] for i in range(8)]
    assert np.mean(np.abs(np.asarray(sugg) - 0.5)) < 0.35
    # State round-trips through JSON (sweep persistence).
    state = json.loads(json.dumps(s.get_state()))
    s2 = TPESearcher(sp, metric="m", mode="max", n_initial=3, seed=1)
    s2.set_state(state)
    assert len(s2._observations) == len(s._observations)


def test_bohb_learns_from_intermediate_budgets():
    """BOHB's defining behavior vs plain TPE: intermediate results at rung
    budgets feed the model, and the model pool tracks the DEEPEST budget
    with enough observations (reference: tune/search/bohb/ TuneBOHB)."""
    from ray_tpu.tune import BOHBSearcher

    sp = {"x": tune.uniform(0.0, 1.0)}
    s = BOHBSearcher(sp, metric="m", mode="max", n_initial=3,
                     min_points_in_model=3, seed=1)
    # Three trials report at budgets 1 and 2 WITHOUT completing.
    for i in range(3):
        cfg = s.suggest(f"t{i}")
        s.on_trial_result(f"t{i}", {"m": -abs(cfg["x"] - 0.5), "training_iteration": 1})
        s.on_trial_result(f"t{i}", {"m": -abs(cfg["x"] - 0.5), "training_iteration": 2})
    # Model is live from intermediate results alone (budget 2 has 3 points).
    assert len(s._observations) == 3
    assert s._budget_obs.keys() == {1, 2}
    # The controller reports the FINAL result via on_trial_result AND
    # on_trial_complete — the pool must not double-count it.
    s.on_trial_complete("t0", {"m": 0.0, "training_iteration": 2})
    assert len(s._budget_obs[2]) == 3, "final result double-recorded"
    sugg = [s.suggest(f"p{i}")["x"] for i in range(8)]
    assert np.mean(np.abs(np.asarray(sugg) - 0.5)) < 0.35
    # State round-trips (sweep persistence), budgets intact.
    state = json.loads(json.dumps(s.get_state()))
    s2 = BOHBSearcher(sp, metric="m", mode="max", n_initial=3,
                      min_points_in_model=3, seed=1)
    s2.set_state(state)
    assert {int(k) for k in s2._budget_obs} == {1, 2}
    assert len(s2._observations) == 3


def test_bohb_with_asha_end_to_end(tmp_path):
    """BOHB + ASHA sweep through the Tuner: multi-iteration trials report
    per-iteration scores; the sweep finds a near-optimal x and the searcher
    accumulated rung observations along the way."""
    from ray_tpu.tune import ASHAScheduler, BOHBSearcher

    def trainable(config):
        for it in range(1, 5):
            # Score improves with budget; ordering by |x-0.3| is stable.
            tune.report({"score": -abs(config["x"] - 0.3) + 0.01 * it,
                         "training_iteration": it})

    space = {"x": tune.uniform(-2.0, 2.0)}
    searcher = BOHBSearcher(space, metric="score", mode="max",
                            n_initial=4, min_points_in_model=4, seed=3)
    tuner = Tuner(
        trainable,
        param_space=space,
        tune_config=TuneConfig(
            num_samples=16, metric="score", mode="max",
            search_alg=searcher,
            scheduler=ASHAScheduler(metric="score", mode="max", max_t=4,
                                    grace_period=1, reduction_factor=2),
            max_concurrent_trials=1, seed=3,
        ),
        run_config=RunConfig(name="bohb-asha", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    best = max(r.metrics["score"] for r in grid if r.error is None and r.metrics)
    assert best > -0.3, f"BOHB+ASHA best {best} nowhere near optimum"
    assert searcher._budget_obs, "no rung observations reached the searcher"


def test_median_stopping_rule_unit():
    from ray_tpu.tune import MedianStoppingRule
    from ray_tpu.tune.schedulers import CONTINUE, STOP

    class T:
        def __init__(self, tid):
            self.trial_id = tid

    rule = MedianStoppingRule(metric="score", mode="max", grace_period=2,
                              min_samples_required=2)
    # Two healthy trials establish the median bar.
    for t in (1, 2, 3):
        assert rule.on_trial_result(T("good1"), {"score": 10.0, "training_iteration": t}) == CONTINUE
        assert rule.on_trial_result(T("good2"), {"score": 9.0, "training_iteration": t}) == CONTINUE
    # Within grace: a bad trial survives.
    assert rule.on_trial_result(T("bad"), {"score": 1.0, "training_iteration": 1}) == CONTINUE
    # Past grace and below the median of running averages: stopped.
    assert rule.on_trial_result(T("bad"), {"score": 1.0, "training_iteration": 2}) == STOP
    # A trial ABOVE the median keeps going at the same step.
    assert rule.on_trial_result(T("good3"), {"score": 12.0, "training_iteration": 2}) == CONTINUE


def test_median_stopping_in_sweep(tmp_path):
    """End-to-end: bad trials stop early (fewer iterations reported), good
    trials run to completion."""
    from ray_tpu.tune import MedianStoppingRule

    def trainable(config):
        import time as _t

        base = config["q"]
        for it in range(1, 7):
            tune.report({"score": base, "training_iteration": it})
            _t.sleep(0.4)  # let the controller poll between reports

    tuner = Tuner(
        trainable,
        param_space={"q": tune.grid_search([1.0, 1.0, 10.0, 10.0])},
        tune_config=TuneConfig(
            num_samples=1, metric="score", mode="max",
            scheduler=MedianStoppingRule(metric="score", mode="max",
                                         grace_period=2, min_samples_required=2),
            max_concurrent_trials=4, seed=0,
        ),
        run_config=RunConfig(name="medstop", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    by_q = {}
    for r in grid:
        if r.error is None and r.metrics:
            by_q.setdefault(r.config["q"], []).append(
                int(r.metrics.get("training_iteration", 0)))
    assert max(by_q[10.0]) == 6, by_q  # good trials ran out the budget
    assert min(by_q[1.0]) < 6, by_q  # at least one bad trial stopped early


_RESUME_SCRIPT = """
import os, sys, json, tempfile
sys.path.insert(0, {repo!r})
import jax; jax.config.update("jax_platforms", "cpu")
import ray_tpu as rt
from ray_tpu import tune
from ray_tpu.train.config import RunConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.tune import TuneConfig, Tuner

MARKS = {marks!r}

def slow_trainable(config):
    import time, uuid, os, json, tempfile
    open(os.path.join(MARKS, f"{{config['i']}}-{{uuid.uuid4().hex[:6]}}"), "w").close()
    start = 0
    ckpt = tune.get_checkpoint()
    if ckpt is not None:
        with ckpt.as_directory() as d:
            start = json.load(open(os.path.join(d, "s.json")))["it"] + 1
    for it in range(start, 4):
        time.sleep({sleep})
        d = tempfile.mkdtemp()
        json.dump({{"it": it}}, open(os.path.join(d, "s.json"), "w"))
        tune.report({{"score": config["i"] * 10 + it}}, checkpoint=Checkpoint.from_directory(d))

rt.init(num_cpus=4)
tuner = Tuner(
    slow_trainable,
    param_space={{"i": tune.grid_search([0, 1, 2, 3])}},
    tune_config=TuneConfig(num_samples=1, metric="score", mode="max",
                           max_concurrent_trials=1),
    run_config=RunConfig(name="resume_sweep", storage_path={storage!r}),
    resume={resume},
)
grid = tuner.fit()
print("RESULTS", json.dumps([{{ "id": r.trial_id, "err": bool(r.error), "score": r.metrics.get("score") }} for r in grid]))
rt.shutdown()
"""


def test_sweep_resumes_after_controller_killed(tmp_path):
    repo = "/root/repo"
    storage = str(tmp_path / "sweep")
    marks = str(tmp_path / "marks")
    os.makedirs(marks)
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "RAYTPU_FORCE_JAX_PLATFORM": "cpu"}
    # Phase 1: kill the controller process mid-sweep (trial 0/1 done or
    # running, later trials not started).
    p = subprocess.Popen(
        [sys.executable, "-c",
         _RESUME_SCRIPT.format(repo=repo, marks=marks, storage=storage,
                               sleep=0.4, resume=False)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    deadline = time.time() + 120
    state_file = os.path.join(storage, "resume_sweep", "tune_state.json")
    while time.time() < deadline:
        if os.path.exists(state_file):
            st = json.load(open(state_file))
            if any(t["state"] == "TERMINATED" for t in st["trials"]):
                break
        time.sleep(0.3)
    else:
        p.kill()
        raise AssertionError("no trial terminated before kill window")
    p.send_signal(signal.SIGKILL)
    p.wait(timeout=30)
    runs_phase1 = os.listdir(marks)
    st = json.load(open(state_file))
    done_phase1 = {t["trial_id"] for t in st["trials"] if t["state"] == "TERMINATED"}
    assert done_phase1, st

    # Phase 2: resume completes the sweep without re-running finished trials.
    out = subprocess.run(
        [sys.executable, "-c",
         _RESUME_SCRIPT.format(repo=repo, marks=marks, storage=storage,
                               sleep=0.05, resume=True)],
        env=env, capture_output=True, text=True, timeout=300,
    )
    line = next(l for l in out.stdout.splitlines() if l.startswith("RESULTS"))
    results = json.loads(line[len("RESULTS "):])
    assert len(results) == 4 and all(not r["err"] for r in results), results
    assert {r["score"] for r in results} == {3, 13, 23, 33}  # all completed through it=3
    # Finished trials did NOT restart: no new marker for their trial index.
    new_runs = set(os.listdir(marks)) - set(runs_phase1)
    done_idx = {int(t.rsplit("_", 1)[-1]) for t in done_phase1}
    for m in new_runs:
        assert int(m.split("-")[0]) not in done_idx, (
            f"finished trial re-executed: {m} (done: {done_idx})"
        )
