"""RL layer: GAE correctness, learner step, and PPO learning CartPole to
>450 mean return on the actor runtime (reference analogue: rllib per-algorithm
CartPole smoke learning tests, SURVEY §4)."""
import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.rl import PPO, PPOConfig
from ray_tpu.rl.learner import compute_gae
from ray_tpu.rl.module import init_params, jax_logits_values, np_logits_values


def test_gae_matches_reference_recursion():
    rng = np.random.default_rng(0)
    T, N = 6, 2
    rewards = rng.standard_normal((T, N)).astype(np.float32)
    values = rng.standard_normal((T, N)).astype(np.float32)
    dones = (rng.random((T, N)) < 0.2).astype(np.float32)
    last_values = rng.standard_normal(N).astype(np.float32)
    gamma, lam = 0.9, 0.8
    adv, ret = compute_gae(rewards, values, dones, dones, last_values, gamma, lam)
    # brute force per env
    for n in range(N):
        expected = np.zeros(T)
        for t in range(T):
            acc, discount = 0.0, 1.0
            for k in range(t, T):
                nv = last_values[n] if k + 1 == T else values[k + 1, n]
                delta = rewards[k, n] + gamma * nv * (1 - dones[k, n]) - values[k, n]
                acc += discount * delta
                discount *= gamma * lam * (1 - dones[k, n])
                if dones[k, n]:
                    break
            expected[t] = acc
        np.testing.assert_allclose(adv[:, n], expected, rtol=1e-5, atol=1e-5)


def test_gae_truncation_bootstraps_value():
    """A time-limit truncation must bootstrap gamma*V(next) (terms=0) while a
    true termination must not (terms=1)."""
    rewards = np.array([[1.0], [1.0]], np.float32)
    values = np.array([[0.0], [5.0]], np.float32)  # V at t=1 = V(final_obs)
    dones = np.array([[1.0], [0.0]], np.float32)  # boundary after t=0
    last_values = np.array([9.0], np.float32)
    # termination: no bootstrap at t=0
    adv_term, _ = compute_gae(rewards, values, dones, dones, last_values, 0.9, 0.95)
    assert adv_term[0, 0] == pytest.approx(1.0)  # r - V = 1 - 0
    # truncation: bootstraps gamma * values[t+1] = 0.9 * 5
    zeros = np.zeros_like(dones)
    adv_trunc, _ = compute_gae(rewards, values, dones, zeros, last_values, 0.9, 0.95)
    assert adv_trunc[0, 0] == pytest.approx(1.0 + 0.9 * 5.0)


def test_numpy_and_jax_forwards_agree():
    rng = np.random.default_rng(1)
    params = init_params(rng, 4, 2, (32, 32))
    obs = rng.standard_normal((7, 4)).astype(np.float32)
    nl, nv = np_logits_values(params, obs)
    jl, jv = jax_logits_values(params, obs)
    np.testing.assert_allclose(nl, np.asarray(jl), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(nv, np.asarray(jv), rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_ppo_learns_cartpole(shared_ray):
    algo = PPOConfig(
        num_env_runners=2,
        num_envs_per_runner=8,
        rollout_len=128,
        lr=2.5e-4,
        minibatch_size=256,
        seed=3,
    ).build()
    best = -np.inf
    try:
        for _ in range(250):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if result["episode_return_mean"] >= 450.0:
                break
        assert best >= 450.0, f"PPO failed to learn CartPole: best mean return {best}"
    finally:
        algo.stop()
