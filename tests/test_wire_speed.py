"""Wire-speed campaign regressions (raw object lane).

The contracts this battery pins:

- send_raw never materializes its payload — no bytes()/tobytes() on any
  part, single- or multi-part, vectored or legacy sequential path.
- The window MAC (one HMAC tag per pull window instead of one per chunk)
  still covers every payload byte: divergence between shipped and hashed
  bytes is detected, a tampered window fails TYPED (RawWindowTamperError),
  the source is dropped, and the run refetches per-chunk byte-identical.
- A pre-window (v3 per-chunk) peer interops via capability negotiation:
  the "no handler" refusal is remembered on the connection and the pull
  silently runs per-chunk — no retries burned, no error surfaced.
- bytes_out/bytes_in accounting covers the vectored-window serve AND the
  sendfile (spilled, auth-off) serve.
- keep_live(copy=False)/export_state(copy=False) park REFERENCES: a jax
  snapshot survives the next (rebinding) step untouched, and a parked
  numpy leaf shares memory with the caller's array.
- Degraded-network tooling: the in-process token-bucket pacer actually
  throttles the raw lane; the netem-marked test auto-skips with a reason
  where tc/CAP_NET_ADMIN/sch_netem is unavailable.
"""
import asyncio
import logging
import os
import subprocess
import time

import numpy as np
import pytest

from ray_tpu.core import rpc
from ray_tpu.core.ids import ObjectID


@pytest.fixture(autouse=True)
def _restore_raw_lane_state():
    yield
    rpc.set_auth_token(None)
    rpc.configure_raw_lane(vectored=True, mac_granularity="window")
    rpc.set_net_shape("")


def _seed_object(daemon, payload: bytes) -> ObjectID:
    oid = ObjectID.from_put()
    daemon.store.put(oid, payload)
    return oid


def _locs(*daemons):
    return [{"node_id": d.node_id, "address": d.address} for d in daemons]


# ---------------------------------------------------------------------------
# zero-copy send path
# ---------------------------------------------------------------------------


class _CountingArray(np.ndarray):
    """ndarray whose bytes()/tobytes() calls are counted: the raw lane must
    ship payloads through the buffer protocol (memoryview slices straight to
    the socket), so ANY materialization on the send path is a regression."""

    copies = 0

    def tobytes(self, *a, **kw):  # noqa: D102
        type(self).copies += 1
        return super().tobytes(*a, **kw)

    def __bytes__(self):
        type(self).copies += 1
        return super().tobytes()


class _RawSource:
    def __init__(self, parts):
        self.parts = parts

    async def handle_fetch(self, conn, p):
        payload = self.parts if len(self.parts) > 1 else self.parts[0]
        await conn.send_raw(p["key"], payload)
        return True


@pytest.mark.parametrize("vectored", [True, False], ids=["vectored", "legacy"])
@pytest.mark.parametrize("nparts", [1, 3])
def test_send_raw_never_copies_payload(vectored, nparts):
    """A raw frame's payload crosses as buffer-protocol views on both the
    single-sendmsg vectored path and the legacy sequential path — zero
    bytes()/tobytes() materializations, single- and multi-part."""

    async def go():
        rpc.set_auth_token("wire-speed-nocopy")
        rpc.configure_raw_lane(vectored=vectored)
        raw = [os.urandom(512 * 1024 + 7 * i) for i in range(nparts)]
        parts = [np.frombuffer(r, dtype=np.uint8).view(_CountingArray) for r in raw]
        expected = b"".join(raw)
        _CountingArray.copies = 0

        server = rpc.RpcServer(_RawSource(parts))
        await server.start()
        conn = await rpc.connect(server.address)
        try:
            key = os.urandom(12)
            dest = bytearray(len(expected))
            fut = conn.expect_raw(key, memoryview(dest))
            assert await conn.call("fetch", {"key": key}, timeout=30)
            assert await asyncio.wait_for(fut, 30) is True
            assert bytes(dest) == expected
        finally:
            await conn.close()
            await server.close()
        assert _CountingArray.copies == 0, (
            f"send path materialized the payload {_CountingArray.copies}x")

    asyncio.run(go())


# ---------------------------------------------------------------------------
# window MAC: wire-level
# ---------------------------------------------------------------------------


class _WindowSource:
    def __init__(self, chunks):
        self.chunks = chunks

    async def handle_win(self, conn, p):
        hasher = rpc.raw_window_hasher()
        if p.get("diverge"):
            # Model on-the-wire tamper: the MAC stream sees bytes the
            # receiver never gets. The reply tag must then mismatch the
            # receiver's hash of what actually landed.
            hasher.update(b"\x01")
        base = p["key"]
        for i, c in enumerate(self.chunks):
            await conn.send_raw(base + i.to_bytes(4, "little"), c, hasher=hasher)
        return {"ok": True, "tag": hasher.digest()[: rpc.FRAME_TAG_LEN]}


@pytest.mark.parametrize("diverge", [False, True], ids=["clean", "tampered"])
def test_window_hasher_covers_exactly_the_landed_bytes(diverge):
    """One HMAC per window, no per-chunk trailer: the receiver hashes the
    bytes that LAND, the sender the bytes it SHIPS, and the tags agree iff
    those streams are identical — any divergence anywhere in the run is
    caught by the single compare."""
    import hmac as _hmac

    async def go():
        rpc.set_auth_token("wire-speed-window")
        chunks = [os.urandom(256 * 1024 + i) for i in range(4)]
        server = rpc.RpcServer(_WindowSource(chunks))
        await server.start()
        conn = await rpc.connect(server.address)
        try:
            base = os.urandom(12)
            hasher = rpc.raw_window_hasher()
            dests = [bytearray(len(c)) for c in chunks]
            futs = [conn.expect_raw(base + i.to_bytes(4, "little"),
                                    memoryview(d), hasher)
                    for i, d in enumerate(dests)]
            ack = await conn.call("win", {"key": base, "diverge": diverge},
                                  timeout=30)
            assert all(await asyncio.wait_for(asyncio.gather(*futs), 30))
            for d, c in zip(dests, chunks):
                assert bytes(d) == c  # payloads landed byte-identical
            match = _hmac.compare_digest(
                ack["tag"], hasher.digest()[: rpc.FRAME_TAG_LEN])
            assert match is (not diverge)
        finally:
            await conn.close()
            await server.close()

    asyncio.run(go())


# ---------------------------------------------------------------------------
# window MAC: pull-path tamper + capability negotiation (cluster level)
# ---------------------------------------------------------------------------


def test_window_tamper_fails_whole_window_typed_then_refetches(fresh_cluster, caplog):
    """A tampered window fails TYPED (RawWindowTamperError, an RpcError),
    the source connection is hard-dropped, and the run refetches per-chunk —
    the object still lands byte-identical."""
    assert issubclass(rpc.RawWindowTamperError, rpc.RpcError)
    cluster = fresh_cluster
    a = cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    b.config.pull_chunk_size = 1024 * 1024
    assert rpc.get_auth_token(), "window MAC rides the authed wire (auto-mint)"
    payload = os.urandom(6 * 1024 * 1024 + 13)
    oid = _seed_object(a, payload)

    orig = a.handle_read_object_window_raw
    tampered = [0]

    async def tamper_first(conn, p):
        res = await orig(conn, p)
        if not tampered[0] and res.get("tag"):
            tampered[0] += 1
            tag = res["tag"]
            res = dict(res, tag=bytes([tag[0] ^ 0xFF]) + tag[1:])
        return res

    a.handle_read_object_window_raw = tamper_first
    with caplog.at_level(logging.WARNING, logger="ray_tpu.core.node"):
        assert cluster.host.call(b.pull_manager.pull(oid, _locs(a)), timeout=120)
    assert tampered[0] == 1
    assert b.store.get_copy(oid) == payload
    assert b.pull_manager.chunks_retried >= 1  # the whole window was retried
    assert "RawWindowTamperError" in caplog.text


def test_pre_window_peer_negotiates_per_chunk(fresh_cluster):
    """A v3 per-chunk-only peer (no read_object_window_raw handler) is
    detected on first use ("no handler" RpcError), remembered on the
    connection, and served per-chunk from then on — silently: no retry
    counters burn, and later pulls skip the window RPC outright."""
    cluster = fresh_cluster
    a = cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    b.config.pull_chunk_size = 1024 * 1024
    a.handle_read_object_window_raw = None  # simulate the older build

    for rep in range(2):
        payload = os.urandom(4 * 1024 * 1024 + rep)
        oid = _seed_object(a, payload)
        assert cluster.host.call(b.pull_manager.pull(oid, _locs(a)), timeout=120)
        assert b.store.get_copy(oid) == payload
    assert b.pull_manager.chunks_retried == 0  # negotiation, not failure
    assert any(c.meta.get("no_window_raw") for c in b._peer_conns.values())


# ---------------------------------------------------------------------------
# bytes accounting: vectored window serve + sendfile serve
# ---------------------------------------------------------------------------


def test_window_serve_accounts_bytes_both_sides(fresh_cluster):
    cluster = fresh_cluster
    a = cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    b.config.pull_chunk_size = 1024 * 1024
    payload = os.urandom(4 * 1024 * 1024 + 21)
    oid = _seed_object(a, payload)
    out0, in0 = a.pull_manager.bytes_out, b.pull_manager.bytes_in
    assert cluster.host.call(b.pull_manager.pull(oid, _locs(a)), timeout=120)
    assert b.pull_manager.last_pull["mode"] == "window"
    assert a.pull_manager.bytes_out - out0 == len(payload)
    assert b.pull_manager.bytes_in - in0 == len(payload)


def test_sendfile_serve_accounts_bytes_and_lands_identical(monkeypatch):
    """A spilled source on an auth-off link serves fd->socket via
    os.sendfile; the kernel-assisted path still lands byte-identical and is
    fully covered by bytes_out/bytes_in accounting."""
    from ray_tpu.core.api import Cluster
    from ray_tpu.core.config import get_config

    cfg = get_config()
    snap = cfg.to_dict()
    monkeypatch.setenv("RAYTPU_AUTO_TOKEN", "0")
    cfg.auth_token = ""
    rpc.set_auth_token(None)
    cluster = Cluster(initialize_head=False)
    try:
        spill = "/tmp/raytpu_wire_spill_%d" % os.getpid()
        a = cluster.add_node(num_cpus=1, object_store_memory=24 * 1024 * 1024)
        b = cluster.add_node(num_cpus=1)
        b.config.pull_chunk_size = 1024 * 1024
        a.store.spill_dir = spill
        payload = os.urandom(5 * 1024 * 1024 + 3)
        oid = _seed_object(a, payload)
        assert a.store.spill(a.store.capacity)
        assert a.store.is_spilled(oid)
        # Pin the serve to the disk path: an arena restore would hand the
        # transfer a memoryview and bypass sendfile.
        monkeypatch.setattr(a, "_restore_local", lambda _oid: False)
        sendfile_calls = [0]
        real_sendfile = os.sendfile

        def counting_sendfile(out_fd, in_fd, offset, count):
            sendfile_calls[0] += 1
            return real_sendfile(out_fd, in_fd, offset, count)

        monkeypatch.setattr(os, "sendfile", counting_sendfile)
        out0, in0 = a.pull_manager.bytes_out, b.pull_manager.bytes_in
        assert cluster.host.call(b.pull_manager.pull(oid, _locs(a)), timeout=120)
        assert b.store.get_copy(oid) == payload
        assert sendfile_calls[0] >= 1, "disk serve did not take the sendfile path"
        assert a.pull_manager.bytes_out - out0 == len(payload)
        assert b.pull_manager.bytes_in - in0 == len(payload)
    finally:
        cluster.shutdown()
        for k, v in snap.items():
            setattr(cfg, k, v)
        rpc.set_auth_token(None)


# ---------------------------------------------------------------------------
# chaos replay determinism across MAC granularities
# ---------------------------------------------------------------------------


def test_corrupt_mac_chaos_replays_identically_under_both_granularities(fresh_cluster):
    """The rpc.frame.send corrupt_mac fault injects exactly as scheduled
    under BOTH MAC granularities and the pull survives it identically in
    each: envelope-MAC rejection drops the poisoned link, the transfer
    fails over to the surviving replica, the object lands byte-identical.
    Two sources because the fault may land on the very first envelope to a
    peer (the size probe) — single-source pulls legitimately fail there."""
    from ray_tpu.chaos import plan as _plan

    cluster = fresh_cluster
    a = cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    c = cluster.add_node(num_cpus=1)
    c.config.pull_chunk_size = 1024 * 1024
    injected = {}
    for gran in ("window", "chunk"):
        c.config.raw_mac_granularity = gran
        payload = os.urandom(4 * 1024 * 1024 + 5)
        oid = _seed_object(a, payload)
        # Replicate a -> b on a clean wire so c has two sources under fire.
        assert cluster.host.call(b.pull_manager.pull(oid, _locs(a)), timeout=120)
        _plan.install(_plan.FaultSchedule.from_spec({
            "seed": 16,
            "rules": [{"site": "rpc.frame.send", "kind": "corrupt_mac",
                       "every": 1, "max_faults": 1}],
        }))
        try:
            ok = cluster.host.call(c.pull_manager.pull(oid, _locs(a, b)), timeout=120)
            injected[gran] = len(_plan.injection_log())
        finally:
            _plan.uninstall()
        assert ok, f"pull under corrupt_mac failed (granularity={gran})"
        assert c.store.get_copy(oid) == payload
        c.store.delete(oid)
    assert injected["window"] == injected["chunk"] == 1


# ---------------------------------------------------------------------------
# copy elision: keep_live(copy=False) / export_state(copy=False)
# ---------------------------------------------------------------------------


def test_keep_live_copy_false_jax_snapshot_survives_next_step(tmp_path):
    """copy=False parks REFERENCES: for immutable jax leaves the reference
    IS the snapshot — the next step's rebinding updates cannot tear it, the
    step pays zero per-leaf memcpys, and export_state(copy=False) ships
    exactly the parked values."""
    jnp = pytest.importorskip("jax.numpy")
    from ray_tpu.elastic import transfer
    from ray_tpu.train.session import TrainSession

    sess = TrainSession(0, 1, 0, "wire-speed", str(tmp_path))
    params = jnp.arange(1024, dtype=jnp.float32)
    opt_m = jnp.zeros(2048, dtype=jnp.float32)
    sess.keep_live({"params": params}, sharded={"opt.m": (opt_m, 0, 4096)},
                   meta={"step": 1}, copy=False)
    snap = sess.live_snapshot()
    assert snap["state"]["params"] is params  # a reference, not a copy

    # The "next step": jax arrays are immutable, so updates rebind.
    params = params + 1.0
    opt_m = opt_m + 0.5

    np.testing.assert_array_equal(
        np.asarray(snap["state"]["params"]), np.arange(1024, dtype=np.float32))
    arr, lo, n = snap["sharded"]["opt.m"]
    assert (lo, n) == (0, 4096) and float(np.asarray(arr).sum()) == 0.0

    tid = "wire-speed-export"
    transfer.export_state(tid, 0, snap["state"], snap["sharded"],
                          seq=snap["seq"], meta=snap["meta"], copy=False)
    try:
        exp = transfer._EXPORTS[tid]
        np.testing.assert_array_equal(
            exp.arrays["params"], np.arange(1024, dtype=np.float32))
    finally:
        transfer.release(tid)


def test_export_state_copy_false_parks_numpy_reference():
    from ray_tpu.elastic import transfer

    arr = np.arange(4096, dtype=np.float32)  # a keep_live(copy=True) private copy
    transfer.export_state("wire-ref", 0, {"w": arr}, copy=False)
    try:
        assert np.shares_memory(transfer._EXPORTS["wire-ref"].arrays["w"], arr)
    finally:
        transfer.release("wire-ref")
    transfer.export_state("wire-copy", 0, {"w": arr})  # default copies
    try:
        assert not np.shares_memory(transfer._EXPORTS["wire-copy"].arrays["w"], arr)
    finally:
        transfer.release("wire-copy")


# ---------------------------------------------------------------------------
# degraded-network profile tooling
# ---------------------------------------------------------------------------


def _netem_probe(rate_mbit=800, delay_ms=1) -> tuple:
    """Try to install netem on loopback; (ok, skip_reason). On ok=True the
    qdisc is LIVE — the caller must tear it down."""
    cmd = ["tc", "qdisc", "add", "dev", "lo", "root", "netem",
           "delay", f"{delay_ms}ms", "rate", f"{rate_mbit}mbit"]
    try:
        p = subprocess.run(cmd, capture_output=True, text=True, timeout=10)
    except FileNotFoundError:
        return False, "tc not installed"
    except Exception as e:  # noqa: BLE001 - probe must never error the suite
        return False, f"tc probe failed: {e}"
    if p.returncode == 0:
        return True, ""
    return False, (p.stderr or p.stdout).strip() or f"tc exited {p.returncode}"


def _netem_teardown():
    subprocess.run(["tc", "qdisc", "del", "dev", "lo", "root"],
                   capture_output=True, timeout=10)


def test_netem_probe_always_yields_a_skip_reason():
    """The auto-skip contract: wherever netem cannot be installed the probe
    says WHY (missing tc, missing CAP_NET_ADMIN, missing sch_netem), so the
    skipped test and the bench row both carry the reason."""
    ok, reason = _netem_probe()
    if ok:
        _netem_teardown()
        assert reason == ""
    else:
        assert reason, "probe failed without a reason"


@pytest.mark.netem
def test_netem_shaped_loopback_bounds_throughput():
    import socket
    import threading

    ok, reason = _netem_probe(rate_mbit=400, delay_ms=1)
    if not ok:
        pytest.skip(f"netem unavailable on this host: {reason}")
    try:
        srv = socket.create_server(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        total = 16 * 1024 * 1024
        got = [0]

        def drain():
            c, _ = srv.accept()
            buf = bytearray(1 << 20)
            while got[0] < total:
                n = c.recv_into(buf)
                if not n:
                    break
                got[0] += n
            c.close()

        t = threading.Thread(target=drain)
        t.start()
        s = socket.create_connection(("127.0.0.1", port))
        data = b"\x00" * (1 << 20)
        t0 = time.perf_counter()
        for _ in range(total // len(data)):
            s.sendall(data)
        t.join(timeout=120)
        elapsed = time.perf_counter() - t0
        s.close()
        srv.close()
        mb_s = total / 1e6 / elapsed
        assert got[0] == total
        # 400 mbit = 50 MB/s; allow 2x slack for token-bucket burst.
        assert mb_s <= 100, f"netem did not shape loopback: {mb_s:.0f} MB/s"
    finally:
        _netem_teardown()


def test_net_shape_pacing_throttles_raw_lane():
    """The in-process fallback profile (Config.net_shape_spec): the token
    bucket paces raw-frame sends to the configured rate, so a degraded_sim
    bench row measures a genuinely thinner pipe."""

    async def go():
        payload = np.ones(1 << 20, dtype=np.uint8)
        server = rpc.RpcServer(_RawSource([payload]))
        await server.start()
        conn = await rpc.connect(server.address)
        try:
            async def pump(n):
                t0 = time.perf_counter()
                for _ in range(n):
                    key = os.urandom(12)
                    dest = bytearray(len(payload))
                    fut = conn.expect_raw(key, memoryview(dest))
                    assert await conn.call("fetch", {"key": key}, timeout=30)
                    assert await asyncio.wait_for(fut, 30) is True
                return time.perf_counter() - t0

            quiet = await pump(6)
            rpc.set_net_shape('{"rate_mb_s": 40.0, "delay_ms": 0.0}')
            shaped = await pump(6)
            rpc.set_net_shape("")
            # 6 MiB at 40 MB/s minus the 1 MiB burst allowance: >= ~0.13 s
            # of pacing the quiet run never pays.
            assert shaped >= quiet + 0.09, (quiet, shaped)
        finally:
            await conn.close()
            await server.close()

    asyncio.run(go())
