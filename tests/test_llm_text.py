"""Text-in/text-out LLM serving: tokenizer, per-request sampling, and the
OpenAI-compatible ingress (reference: llm/_internal/serve/core/ingress/
ingress.py:145 /v1 routes; vLLM per-request SamplingParams)."""
import json
import socket
import time

import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm import EngineConfig, LLMEngine, SamplingParams, Tokenizer
from ray_tpu.models import TransformerConfig

CFG = TransformerConfig(
    vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
    max_seq_len=128, dtype=jnp.float32, attention_impl="reference",
)

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the quick brown fox is quick and the dog is lazy",
    "distributed systems schedule tasks over the cluster",
    "the scheduler places the tasks on the nodes of the cluster",
] * 4


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

def test_tokenizer_roundtrip_any_unicode():
    tok = Tokenizer()  # merge-less: pure byte fallback
    for s in ("hello world", "héllo wörld", "日本語のテスト", "mixed 英語 & emoji 🎉", ""):
        assert tok.decode(tok.encode(s)) == s


def test_tokenizer_train_compresses_and_roundtrips(tmp_path):
    tok = Tokenizer.train(CORPUS, vocab_size=3 + 256 + 64)
    assert len(tok.merges) > 0
    s = "the quick brown fox jumps over the lazy dog"
    ids = tok.encode(s)
    assert tok.decode(ids) == s
    # Learned merges beat byte fallback on in-domain text.
    assert len(ids) < len(Tokenizer().encode(s))
    # Round-trips out-of-domain text too (byte fallback).
    assert tok.decode(tok.encode("zebra xylophone 🦓")) == "zebra xylophone 🦓"
    # Persistence.
    p = str(tmp_path / "tok.json")
    tok.save(p)
    tok2 = Tokenizer.load(p)
    assert tok2.encode(s) == ids
    assert tok2.vocab_size == tok.vocab_size


def test_tokenizer_specials():
    tok = Tokenizer()
    ids = tok.encode("hi", add_bos=True, add_eos=True)
    assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
    assert tok.decode(ids) == "hi"  # specials render as nothing


def test_tokenizer_deep_merge_chain_decodes():
    """A degenerate corpus can learn a merge chain nested deeper than
    Python's recursion limit; decode must expand iteratively."""
    import sys

    base = 3 + ord("a")  # byte token for 'a'
    depth = sys.getrecursionlimit() + 500
    merges = [(base, base)] + [(3 + 256 + i, base) for i in range(depth - 1)]
    tok = Tokenizer(merges)
    deepest = 3 + 256 + len(merges) - 1
    assert tok.decode([deepest]) == "a" * (depth + 1)


# ---------------------------------------------------------------------------
# per-request sampling
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine():
    return LLMEngine(CFG, engine_config=EngineConfig(
        max_slots=4, max_seq=128, prefill_buckets=(16, 32)))


def _drain(engine):
    results = {}
    while engine.has_work():
        for rid, ev in engine.step().items():
            if ev.get("finished"):
                results[rid] = ev["tokens"]
    return results


def test_mixed_batch_greedy_rows_stay_deterministic(engine):
    """One batch holding a greedy row and a hot sampled row: the greedy
    row's output must equal its solo run (per-row params, no bleed)."""
    prompt = np.array([5, 17, 42, 7, 23], np.int32)
    solo = engine.generate(prompt, max_tokens=10)["tokens"]
    engine.add_request("greedy", prompt, sampling=SamplingParams(temperature=0.0, max_tokens=10))
    engine.add_request("hot", prompt, sampling=SamplingParams(temperature=1.5, max_tokens=10))
    results = _drain(engine)
    assert results["greedy"] == solo
    assert len(results["hot"]) == 10


def test_topk1_equals_greedy(engine):
    """top_k=1 at any temperature collapses to argmax."""
    prompt = np.array([3, 1, 4, 1, 5], np.int32)
    solo = engine.generate(prompt, max_tokens=8)["tokens"]
    engine.add_request("k1", prompt, sampling=SamplingParams(temperature=2.0, top_k=1, max_tokens=8))
    assert _drain(engine)["k1"] == solo


def test_temperature_actually_randomizes(engine):
    """Two hot rows with the same prompt in one batch should (overwhelmingly)
    diverge — the per-request temperature is really applied."""
    prompt = np.array([9, 9, 9, 9], np.int32)
    sp = SamplingParams(temperature=3.0, max_tokens=16)
    engine.add_request("h1", prompt, sampling=sp)
    engine.add_request("h2", prompt, sampling=sp)
    results = _drain(engine)
    assert results["h1"] != results["h2"]


def test_stop_token_ids(engine):
    """A per-request stop token retires the request the moment it appears."""
    prompt = np.array([5, 17, 42, 7, 23], np.int32)
    solo = engine.generate(prompt, max_tokens=10)["tokens"]
    stop_tok = solo[3]
    engine.add_request("s", prompt, sampling=SamplingParams(
        max_tokens=10, stop_token_ids=(int(stop_tok),)))
    got = _drain(engine)["s"]
    assert got == solo[:4]  # stops AT the stop token (inclusive emission)


def test_finish_reason_length_at_context_cap(engine):
    """A request force-retired at the max_seq context ceiling reports
    finish_reason 'length' even though fewer than max_tokens were generated
    (previously mislabeled 'stop' by the under-max_tokens heuristic)."""
    prompt = [3 + (i % 200) for i in range(120)]  # 120 of 128 context
    engine.add_request("ctxcap", prompt, sampling=SamplingParams(max_tokens=64))
    reasons = {}
    while engine.has_work():
        for rid, ev in engine.step().items():
            if ev.get("finished"):
                reasons[rid] = (ev.get("finish_reason"), len(ev["tokens"]))
    reason, n = reasons["ctxcap"]
    assert n < 64, "context cap should have cut generation short"
    assert reason == "length", reasons


def test_top_p_restricts_support(engine):
    """top_p≈0 keeps only the most probable token -> equals greedy."""
    prompt = np.array([2, 7, 1, 8], np.int32)
    solo = engine.generate(prompt, max_tokens=8)["tokens"]
    engine.add_request("p", prompt, sampling=SamplingParams(
        temperature=1.0, top_p=1e-6, max_tokens=8))
    assert _drain(engine)["p"] == solo


# ---------------------------------------------------------------------------
# OpenAI-compatible ingress end-to-end over the HTTP proxy
# ---------------------------------------------------------------------------

def _http(port, method, path, payload=None, timeout=120):
    body = json.dumps(payload).encode() if payload is not None else b""
    s = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    req = (
        f"{method} {path} HTTP/1.1\r\nhost: x\r\ncontent-type: application/json\r\n"
        f"content-length: {len(body)}\r\nconnection: close\r\n\r\n"
    ).encode() + body
    s.sendall(req)
    raw = b""
    while True:
        chunk = s.recv(65536)
        if not chunk:
            break
        raw += chunk
    s.close()
    head, _, rest = raw.partition(b"\r\n\r\n")
    status = head.split(b"\r\n", 1)[0].decode()
    headers = {}
    for line in head.split(b"\r\n")[1:]:
        k, _, v = line.decode().partition(":")
        headers[k.strip().lower()] = v.strip()
    if headers.get("transfer-encoding") == "chunked":
        body_out = b""
        while rest:
            size_line, _, rest = rest.partition(b"\r\n")
            size = int(size_line.strip() or b"0", 16)
            if size == 0:
                break
            body_out += rest[:size]
            rest = rest[size + 2:]
        return status, headers, body_out
    return status, headers, rest


TINY_MODEL = dict(
    vocab_size=512, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
    d_ff=64, max_seq_len=64, attention_impl="reference",
)
TINY_ENGINE = {"max_slots": 2, "max_seq": 64, "prefill_buckets": (16,)}

CHATML = (
    "{% for message in messages %}"
    "<|im_start|>{{ message.role }}\n{{ message.content }}<|im_end|>\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|im_start|>assistant\n{% endif %}"
)

CONVERSATION = [
    {"role": "system", "content": "You are a helpful assistant."},
    {"role": "user", "content": "What is a TPU?"},
    {"role": "assistant", "content": "A matrix-multiply accelerator."},
    {"role": "user", "content": "Thanks!"},
]


def test_chat_template_jinja_golden():
    """A jinja chat template renders a multi-turn conversation into the
    exact prompt format the checkpoint expects (golden: ChatML, the format
    Qwen-family checkpoints are tuned on)."""
    from ray_tpu.llm.openai import OpenAIServer

    srv = OpenAIServer(TINY_MODEL, TINY_ENGINE, chat_template=CHATML)
    got, templated = srv._chat_prompt(CONVERSATION)
    assert templated  # rendered prompts must not get a second BOS
    assert got == (
        "<|im_start|>system\nYou are a helpful assistant.<|im_end|>\n"
        "<|im_start|>user\nWhat is a TPU?<|im_end|>\n"
        "<|im_start|>assistant\nA matrix-multiply accelerator.<|im_end|>\n"
        "<|im_start|>user\nThanks!<|im_end|>\n"
        "<|im_start|>assistant\n"
    )
    srv.__raytpu_exit__()


def test_chat_template_tokenizer_precedence(monkeypatch):
    """No explicit template + a tokenizer that ships one (HF checkpoints
    do) -> the checkpoint's own template is used; an explicit template
    still wins; without either, the legacy role:content fallback."""
    import ray_tpu.llm.openai as oai

    class TokWithTemplate:
        eos_id, bos_id, vocab_size = 2, 1, 512
        chat_template = "non-none"

        def encode(self, text, add_bos=False, add_eos=False):
            return [1, 3, 4]

        def decode(self, ids):
            return "x"

        def apply_chat_template(self, messages, add_generation_prompt=True):
            return "|".join(m["role"] for m in messages) + (
                "|gen" if add_generation_prompt else "")

    monkeypatch.setattr(oai, "load_tokenizer", lambda spec: TokWithTemplate())
    srv = oai.OpenAIServer(TINY_MODEL, TINY_ENGINE)
    assert srv._chat_prompt(CONVERSATION) == ("system|user|assistant|user|gen", True)
    srv.__raytpu_exit__()
    # Explicit jinja template beats the tokenizer's.
    srv2 = oai.OpenAIServer(TINY_MODEL, TINY_ENGINE, chat_template=CHATML)
    assert srv2._chat_prompt([{"role": "user", "content": "q"}])[0].startswith(
        "<|im_start|>user")
    srv2.__raytpu_exit__()


def test_chat_template_legacy_fallback():
    from ray_tpu.llm.openai import OpenAIServer

    srv = OpenAIServer(TINY_MODEL, TINY_ENGINE)  # byte tokenizer: no template
    got, templated = srv._chat_prompt([{"role": "user", "content": "hi"}])
    assert got == "user: hi\nassistant:" and not templated
    srv.__raytpu_exit__()


def test_openai_ingress_end_to_end():
    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.llm import build_openai_app

    rt.init(num_cpus=8)
    serve.start()
    try:
        app = build_openai_app(
            model_config=dict(
                vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                d_ff=128, max_seq_len=128, attention_impl="reference",
            ),
            engine_config={"max_slots": 4, "max_seq": 128, "prefill_buckets": (16, 32)},
            model_name="tiny-test-model",
        )
        serve.run(app, name="oai", route_prefix="/")
        port = serve.http_port()

        # /v1/models
        status, _, body = _http(port, "GET", "/v1/models")
        assert "200" in status
        models = json.loads(body)
        assert models["data"][0]["id"] == "tiny-test-model"

        # /v1/completions non-streaming (greedy => deterministic).
        req = {"model": "tiny-test-model", "prompt": "hello world", "max_tokens": 8}
        status, _, body = _http(port, "POST", "/v1/completions", req)
        assert "200" in status, body
        out = json.loads(body)
        assert out["object"] == "text_completion"
        assert out["usage"]["completion_tokens"] == 8
        text1 = out["choices"][0]["text"]
        status, _, body = _http(port, "POST", "/v1/completions", req)
        assert json.loads(body)["choices"][0]["text"] == text1
        assert json.loads(body)["choices"][0]["finish_reason"] == "length"

        # Per-request temperature: a hot request through the SAME engine.
        hot = dict(req, temperature=3.0, top_p=0.95)
        status, _, body = _http(port, "POST", "/v1/completions", hot)
        assert "200" in status

        # /v1/chat/completions streaming: OpenAI chunk objects over SSE.
        chat = {
            "model": "tiny-test-model", "stream": True, "max_tokens": 8,
            "messages": [{"role": "user", "content": "hi there"}],
        }
        status, headers, body = _http(port, "POST", "/v1/chat/completions", chat)
        assert "200" in status
        assert headers.get("content-type") == "text/event-stream"
        frames = [line[6:] for line in body.decode().split("\n") if line.startswith("data: ")]
        assert frames[-1] == "[DONE]"
        chunks = [json.loads(f) for f in frames[:-1]]
        assert chunks[0]["object"] == "chat.completion.chunk"
        assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
        assert chunks[-1]["choices"][0]["finish_reason"] in ("stop", "length")

        # Error paths: missing prompt -> 400 with an OpenAI error body.
        status, _, body = _http(port, "POST", "/v1/completions", {"model": "m"})
        assert "400" in status
        assert json.loads(body)["error"]["type"] == "invalid_request_error"
        status, _, body = _http(port, "POST", "/v1/embeddings", {"input": "x"})
        assert "404" in status

        serve.delete("oai")
    finally:
        serve.shutdown()
        rt.shutdown()


def test_stop_strings_truncate():
    """Stop strings are applied at the text layer, spanning decode blocks."""
    from ray_tpu.llm.openai import _StopTruncator

    tok = Tokenizer()
    full = "abcSTOPdef"
    ids = tok.encode(full)
    tr = _StopTruncator(tok, ("STOP",))
    out = ""
    for tid in ids:  # worst case: one token per feed
        out += tr.feed([tid])
    out += tr.flush()
    assert out == "abc"
    assert tr.stopped

    # No stop present: everything (including held-back prefixes) flushes.
    tr2 = _StopTruncator(tok, ("XYZ",))
    out2 = "".join(tr2.feed([t]) for t in tok.encode("plain text X here"))
    out2 += tr2.flush()
    assert out2 == "plain text X here"
    assert not tr2.stopped
