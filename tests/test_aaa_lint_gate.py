"""The tier-1 lint gate. Named to sort FIRST in the test run: a tree with
lint findings fails here in seconds, before the heavyweight suites spin up
(the CLI twin — ``python -m ray_tpu lint --json`` — runs even earlier in the
tier-1 command itself; this is the in-process backstop that also owns
writing LINT.json).

The committed tree is always at ZERO findings with the full rule set —
per-file rules AND the whole-program phase (RPC verb contracts, adopted
config, ctx propagation, the metrics surface, dtype-kind) — with README.md
folded in as a metric-reference source. The v2 report (per-rule finding and
suppression rollups + the project-index summary) is committed as LINT.json
so the trajectory of findings and suppressions is diffable across PRs.
"""
import json
import os

import ray_tpu
from ray_tpu.analysis import lint_paths

PKG_DIR = os.path.dirname(os.path.abspath(ray_tpu.__file__))
REPO_ROOT = os.path.dirname(PKG_DIR)

XFILE_RULES = (
    "rpc-verb-contract",
    "adopted-config",
    "ctx-propagation",
    "metric-contract",
    "dtype-kind",
)


def test_lint_gate_zero_findings_and_write_lint_json():
    result = lint_paths(
        [PKG_DIR], readme=os.path.join(REPO_ROOT, "README.md")
    )
    assert not result.errors, result.errors
    assert not result.findings, "\n" + "\n".join(
        f.render() for f in result.findings
    )
    report = result.to_json()

    # Schema v2: EVERY registered rule gets a rollup with finding AND
    # suppression counts — absence of a rule id means the rule didn't run.
    assert report["version"] == 2
    assert report["total"] == 0
    for rid in XFILE_RULES + ("bg-strong-ref", "chaos-gate"):
        entry = report["rules"][rid]
        assert set(entry) >= {"findings", "suppressed", "sites"}, rid
        assert entry["findings"] == 0 and entry["sites"] == [], rid
    # The whole-program phase ran over the real tree, not a stub index.
    for rid in XFILE_RULES:
        assert "stats" in report["rules"][rid], rid
    idx = report["index"]
    assert idx["send_sites"] > 50 and idx["handlers"] > 30
    assert {"Controller", "CoreWorker", "NodeDaemon"} <= set(
        idx["server_classes"]
    )
    assert idx["metrics_emitted"] > 30 and idx["metric_refs"] > 10
    # Suppressions are inventoried with reasons, and the per-rule rollups
    # agree with the inventory (one comment can cover several rule ids).
    assert all(s["reason"] for s in report["suppressions"])
    assert sum(e["suppressed"] for e in report["rules"].values()) >= len(
        report["suppressions"]
    )
    assert report["rules"]["metric-contract"]["suppressed"] >= 1  # autopsy span name

    # Paths in the committed report are repo-relative: stable across hosts.
    blob = json.dumps(report, indent=2, sort_keys=True).replace(
        REPO_ROOT + os.sep, ""
    )
    try:
        with open(os.path.join(REPO_ROOT, "LINT.json"), "w") as f:
            f.write(blob + "\n")
    except OSError:
        pass  # read-only checkout: the assertions above still gate
