"""Train layer tests: gang training, reports, checkpoints, failure recovery.

Multi-node + fake-TPU-topology technique per SURVEY.md §4 (reference:
test_jax_trainer.py:17-57 fakes v6e-8 slices with env vars + resources).
"""
import json
import os
import time

import pytest

import ray_tpu as rt
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    DataParallelTrainer,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu import train


def _simple_fn(config):
    ctx = train.get_context()
    for i in range(config["steps"]):
        train.report({"step": i, "rank": ctx.get_world_rank(), "loss": 1.0 / (i + 1)})


def test_data_parallel_trainer_basic(shared_ray, tmp_path):
    trainer = DataParallelTrainer(
        _simple_fn,
        train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=2, resources_per_worker={"CPU": 1}),
        run_config=RunConfig(name="basic", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3
    assert result.metrics["rank"] == 0  # rank-0 metrics are canonical


def _ckpt_fn(config):
    import tempfile

    ctx = train.get_context()
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with ckpt.as_directory() as d:
            start = json.load(open(os.path.join(d, "state.json")))["step"] + 1
    for i in range(start, config["steps"]):
        if config.get("die_at") is not None and i == config["die_at"] and not ckpt:
            raise RuntimeError("boom")
        if ctx.get_world_rank() == 0:
            d = tempfile.mkdtemp()
            json.dump({"step": i}, open(os.path.join(d, "state.json"), "w"))
            train.report({"step": i}, checkpoint=Checkpoint.from_directory(d))
        else:
            train.report({"step": i})


def test_checkpoint_and_gang_restart(shared_ray, tmp_path):
    """Worker failure -> whole gang restarts and resumes from checkpoint."""
    trainer = DataParallelTrainer(
        _ckpt_fn,
        train_loop_config={"steps": 5, "die_at": 3},
        scaling_config=ScalingConfig(num_workers=2, resources_per_worker={"CPU": 1}),
        run_config=RunConfig(
            name="restart", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    with result.checkpoint.as_directory() as d:
        assert json.load(open(os.path.join(d, "state.json")))["step"] == 4
    # resumed from step 3's checkpoint: steps 0,1,2 then 3,4 after restart
    steps = [m["step"] for m in result.metrics_history]
    assert steps[-1] == 4 and 3 in steps


def test_failure_budget_exhausted(shared_ray, tmp_path):
    def bad_fn(config):
        raise ValueError("always fails")

    trainer = DataParallelTrainer(
        bad_fn,
        scaling_config=ScalingConfig(num_workers=1, resources_per_worker={"CPU": 1}),
        run_config=RunConfig(
            name="fail", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.error is not None and "always fails" in result.error


def test_checkpoint_manager_topk(tmp_path):
    from ray_tpu.train import CheckpointManager

    mgr = CheckpointManager(
        str(tmp_path / "runs"), num_to_keep=2,
        score_attribute="acc", score_order="max",
    )
    for i, acc in enumerate([0.1, 0.9, 0.5]):
        src = tmp_path / f"src{i}"
        src.mkdir()
        (src / "x.txt").write_text(str(acc))
        mgr.register(str(src), {"acc": acc})
    assert mgr.best.metrics["acc"] == 0.9
    kept = sorted(p.name for p in (tmp_path / "runs").iterdir() if p.is_dir())
    assert len(kept) == 2  # 0.1 evicted


def test_tpu_slice_gang_scheduling(fresh_cluster):
    """Fake v4-16 slice: 2 hosts x 4 chips; gang lands on slice hosts only."""
    from ray_tpu.accel.tpu import (
        TPU_SLICE_NAME_LABEL,
        TPU_WORKER_ID_LABEL,
        reserve_tpu_slice,
    )

    if rt.is_initialized():
        rt.shutdown()  # detach from the module-scoped shared cluster
    cluster = fresh_cluster
    # worker 0 advertises the slice-head resource (reference tpu.py:224)
    cluster.add_node(
        num_cpus=4,
        resources={"TPU": 4, "TPU-v4-16-head": 1},
        labels={TPU_SLICE_NAME_LABEL: "slice-a", TPU_WORKER_ID_LABEL: "0"},
    )
    cluster.add_node(
        num_cpus=4,
        resources={"TPU": 4},
        labels={TPU_SLICE_NAME_LABEL: "slice-a", TPU_WORKER_ID_LABEL: "1"},
    )
    cluster.add_node(num_cpus=4)  # non-TPU node: must NOT get gang workers
    rt.init(address=cluster.address)
    try:
        reservation = reserve_tpu_slice("v4-16")
        sel = reservation.label_selector
        assert sel == {TPU_SLICE_NAME_LABEL: "slice-a"}

        @rt.remote
        class Rank:
            def where(self):
                return rt.get_runtime_context().node_id

        # Actors hold TPU chips concurrently -> the gang must span both
        # slice hosts and never the unlabeled node.
        actors = [
            Rank.options(resources={"TPU": 2}, label_selector=sel).remote()
            for _ in range(4)
        ]
        node_ids = set(rt.get([a.where.remote() for a in actors], timeout=30))
        tpu_nodes = {
            n["NodeID"] for n in rt.nodes()
            if n.get("labels", {}).get(TPU_SLICE_NAME_LABEL) == "slice-a"
        }
        assert node_ids <= tpu_nodes and len(node_ids) == 2
    finally:
        rt.shutdown()


def _jax_train_fn(config):
    """End-to-end: jitted transformer train loop + orbax checkpoint."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import TransformerConfig, make_train_step
    from ray_tpu.train import Checkpoint, save_pytree, load_pytree

    ctx = train.get_context()
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
        max_seq_len=16, dtype=jnp.float32, attention_impl="reference",
    )
    init_state, train_step, _ = make_train_step(cfg)
    state = init_state(jax.random.PRNGKey(0))
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with ckpt.as_directory() as d:
            meta = json.load(open(os.path.join(d, "meta.json")))
            start = meta["step"] + 1
            state = load_pytree(os.path.join(d, "state"), state)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, cfg.vocab_size)
    step = jax.jit(train_step)
    for i in range(start, config["steps"]):
        state, m = step(state, {"tokens": tokens})
        if ctx.get_world_rank() == 0:
            import tempfile

            d = tempfile.mkdtemp()
            save_pytree(state, os.path.join(d, "state"))
            json.dump({"step": i}, open(os.path.join(d, "meta.json"), "w"))
            train.report(
                {"step": i, "loss": float(m["loss"])},
                checkpoint=Checkpoint.from_directory(d),
            )
        else:
            train.report({"step": i, "loss": float(m["loss"])})


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_jax_trainer_end_to_end(shared_ray, tmp_path):
    from ray_tpu.train import JaxTrainer

    trainer = JaxTrainer(
        _jax_train_fn,
        train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=2, resources_per_worker={"CPU": 1}),
        run_config=RunConfig(
            name="jax_e2e", storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(num_to_keep=2),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    losses = [m["loss"] for m in result.metrics_history]
    assert losses[-1] < losses[0]
    # top-K retention: only 2 checkpoint dirs remain
    ckpts = [
        p for p in os.listdir(str(tmp_path / "jax_e2e"))
        if p.startswith("checkpoint_") and os.path.isdir(str(tmp_path / "jax_e2e" / p))
    ]
    assert len(ckpts) == 2
    # restored state round-trips through orbax
    from ray_tpu.train import load_pytree

    restored = load_pytree(os.path.join(result.checkpoint.path, "state"))
    assert int(restored["step"]) == 3


def test_slice_reservation_release_allows_rereserve(fresh_cluster):
    """Releasing the head PG frees the slice for the next gang (restart path)."""
    from ray_tpu.accel.tpu import TPU_SLICE_NAME_LABEL, reserve_tpu_slice

    if rt.is_initialized():
        rt.shutdown()
    cluster = fresh_cluster
    cluster.add_node(
        num_cpus=2, resources={"TPU": 4, "TPU-v4-8-head": 1},
        labels={TPU_SLICE_NAME_LABEL: "s0"},
    )
    rt.init(address=cluster.address)
    try:
        r1 = reserve_tpu_slice("v4-8")
        assert r1.label_selector[TPU_SLICE_NAME_LABEL] == "s0"
        # Second reservation must block (head consumed) -> release -> succeeds
        with pytest.raises(TimeoutError):
            reserve_tpu_slice("v4-8", timeout=0.5)
        r1.release()
        r2 = reserve_tpu_slice("v4-8", timeout=10)
        assert r2.label_selector[TPU_SLICE_NAME_LABEL] == "s0"
        r2.release()
    finally:
        rt.shutdown()


def test_pg_label_selector_constrains_bundles(fresh_cluster):
    if rt.is_initialized():
        rt.shutdown()
    cluster = fresh_cluster
    cluster.add_node(num_cpus=4, labels={"zone": "a"})
    cluster.add_node(num_cpus=4, labels={"zone": "b"})
    rt.init(address=cluster.address)
    try:
        pg = rt.placement_group(
            [{"CPU": 1}, {"CPU": 1}], strategy="PACK", label_selector={"zone": "b"}
        )
        assert pg.ready(timeout=10)
        zone_b = {
            n["NodeID"] for n in rt.nodes() if n.get("labels", {}).get("zone") == "b"
        }
        assert set(pg.bundle_nodes()) <= zone_b
    finally:
        rt.shutdown()
