"""Polyglot protobuf serve ingress (reference: gRPCProxy,
serve/_private/proxy.py:534 — a schema'd RPC surface non-Python clients can
codegen against; here: serve/protocol/serve_rpc.proto over the proxy's
length-prefixed binary port, JSON-in-protobuf, session-HMAC framed)."""
import json
import socket

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture(scope="module")
def proto_app():
    rt.init(num_cpus=8)
    serve.start()

    @serve.deployment(num_replicas=2, max_ongoing_requests=8)
    class Calc:
        def __call__(self, payload):
            return {"echo": payload}

        def add(self, a, b, scale=1):
            return (a + b) * scale

        def whoami(self):
            import os

            return os.getpid()

        def boom(self):
            raise ValueError("kaboom")

    serve.run(Calc.bind(), name="calc", route_prefix="/calc")
    yield
    serve.shutdown()
    rt.shutdown()


def test_proto_client_calls_and_errors(proto_app):
    from ray_tpu.serve import ProtoServeClient, ProtoServeError

    with ProtoServeClient(port=serve.rpc_port()) as c:
        assert c.call("calc", "Calc", {"x": 1}) == {"echo": {"x": 1}}
        assert c.call("calc", "Calc", 2, 3, method="add", kwargs={"scale": 10}) == 50
        with pytest.raises(ProtoServeError, match="ValueError: kaboom"):
            c.call("calc", "Calc", method="boom")
        # Affinity: same key -> same replica across calls.
        pids = {c.call("calc", "Calc", method="whoami", affinity_key="k1")
                for _ in range(5)}
        assert len(pids) == 1, pids


def test_raw_socket_speaks_only_the_proto_schema(proto_app):
    """A 'foreign' client built from NOTHING but the generated schema + the
    framing documented in serve_rpc.proto — no ray_tpu client code — proves
    the surface is codegen-sufficient for polyglot callers."""
    import hashlib

    from ray_tpu.core import rpc as _rpc
    from ray_tpu.serve.protocol import serve_rpc_pb2 as pb

    req = pb.ServeRequest(
        app="calc", deployment="Calc", method="add",
        json_payload=json.dumps({"args": [20, 22], "kwargs": {}}).encode(),
    )
    payload = b"PB1\x00" + req.SerializeToString()
    # Framing per the .proto comment: optional session tag + magic + message.
    tag = b""
    if _rpc.get_auth_token():
        tag = hashlib.blake2b(payload, key=_rpc.get_auth_token(),
                              digest_size=_rpc.FRAME_TAG_LEN).digest()
    frame = tag + payload
    s = socket.create_connection(("127.0.0.1", serve.rpc_port()), timeout=60)
    s.sendall(len(frame).to_bytes(4, "little") + frame)
    raw = b""
    n = None
    while n is None or len(raw) < 4 + n:
        chunk = s.recv(65536)
        assert chunk, "proxy closed the connection (bad frame?)"
        raw += chunk
        if n is None and len(raw) >= 4:
            n = int.from_bytes(raw[:4], "little")
    s.close()
    body = raw[4:4 + n]
    if _rpc.get_auth_token():
        body = body[_rpc.FRAME_TAG_LEN:]
    assert body.startswith(b"PB1\x00")
    reply = pb.ServeReply()
    reply.ParseFromString(body[4:])
    assert reply.status == pb.ServeReply.OK
    assert json.loads(reply.json_result) == 42


def test_pickle_path_still_works_alongside(proto_app):
    """The trusted in-datacenter pickle format coexists on the same port
    (frames without the PB1 magic)."""
    import pickle

    from ray_tpu.core import rpc as _rpc

    payload = pickle.dumps(("calc", "Calc", "add", (1, 2), {}), protocol=5)
    frame = _rpc.frame_tag(payload) + payload
    s = socket.create_connection(("127.0.0.1", serve.rpc_port()), timeout=60)
    s.sendall(len(frame).to_bytes(4, "little") + frame)
    raw = b""
    n = None
    while n is None or len(raw) < 4 + n:
        chunk = s.recv(65536)
        assert chunk
        raw += chunk
        if n is None and len(raw) >= 4:
            n = int.from_bytes(raw[:4], "little")
    s.close()
    body = raw[4:4 + n]
    if _rpc.get_auth_token():
        tag, body = body[:_rpc.FRAME_TAG_LEN], body[_rpc.FRAME_TAG_LEN:]
        # Verify the reply MAC, not just strip it — the client-side half of
        # the contract the proxy enforces on ingress.
        assert _rpc.frame_verify(tag, body)
    status, result = pickle.loads(body)
    assert (status, result) == ("ok", 3)
