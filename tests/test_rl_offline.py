"""Offline RL: BC + CQL on saved transition datasets (reference:
rllib/algorithms/bc/, rllib/algorithms/cql/; datasets stream through
ray_tpu.data like the reference's ray.data input pipelines).

Dataset generation uses scripted competent controllers (CartPole pole-PD,
Pendulum energy swing-up) so the tests stay minutes-fast; the pipeline the
data flows through (collect -> npz -> data blocks -> shuffled batches ->
jitted learner) is exactly the user path.
"""
import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.rl.offline import (
    BCConfig,
    CQLConfig,
    collect_transitions,
    evaluate_policy,
    iter_offline_batches,
    load_transitions,
    save_transitions,
)


@pytest.fixture(scope="module", autouse=True)
def _session():
    rt.init(num_cpus=4)
    yield
    rt.shutdown()


def _cartpole_teacher(obs):
    # Pole-angle PD: a competent CartPole policy (~400 return).
    return (0.5 * obs[:, 2] + obs[:, 3] > 0).astype(np.int64)


def _pendulum_expert(obs):
    # Energy swing-up + PD catch: ~-155 mean return (near-optimal ~-150).
    c, s, thdot = obs[:, 0], obs[:, 1], obs[:, 2]
    th = np.arctan2(s, c)
    energy = 0.5 * thdot ** 2 + 10.0 * c
    u = np.where(
        np.abs(th) < 0.35,
        -(16.0 * th + 4.0 * thdot),
        np.sign(thdot) * np.clip(2.0 * (10.0 - energy), -2, 2),
    )
    return np.clip(u, -2, 2).astype(np.float32)[:, None]


def test_offline_dataset_roundtrip_and_batches(tmp_path):
    """collect -> save -> load -> shuffled full-size batches through the
    data pipeline, dtypes and shapes intact."""
    rng = np.random.default_rng(0)

    def policy(obs):
        return rng.integers(0, 2, len(obs)).astype(np.int64)

    data = collect_transitions("CartPole-v1", policy, 1_000, seed=1)
    assert len(data["obs"]) == 1_000 and data["obs"].dtype == np.float32
    path = str(tmp_path / "ds.npz")
    save_transitions(path, data)
    loaded = load_transitions(path)
    np.testing.assert_array_equal(loaded["obs"], data["obs"])
    n = 0
    for b in iter_offline_batches(loaded, 256, epochs=2, seed=0):
        assert b["obs"].shape == (256, 4) and b["obs"].dtype == np.float32
        assert b["actions"].dtype == np.int64
        n += 1
    assert n == 2 * (1_000 // 256)


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_bc_clones_competent_cartpole_policy():
    """BC recovers a competent discrete policy from logged data alone
    (reference: rllib/algorithms/bc): trained on noisy-teacher rollouts,
    the clone's eval return reaches the teacher's."""
    teacher_ret = evaluate_policy("CartPole-v1", _cartpole_teacher, episodes=10, seed=1)
    assert teacher_ret > 250, f"teacher too weak to clone: {teacher_ret}"

    rng = np.random.default_rng(0)

    def noisy_teacher(obs):
        a = _cartpole_teacher(obs)
        flip = rng.random(len(a)) < 0.1
        return np.where(flip, rng.integers(0, 2, len(a)), a).astype(np.int64)

    data = collect_transitions("CartPole-v1", noisy_teacher, 10_000, seed=2)
    bc = BCConfig(env="CartPole-v1", epochs_per_iter=5, seed=0).build(data)
    losses = [bc.train()["bc_loss"] for _ in range(3)]
    assert losses[-1] < losses[0]
    bc_ret = bc.evaluate(episodes=10, seed=3)
    assert bc_ret >= 0.85 * teacher_ret, (
        f"BC return {bc_ret} not near teacher {teacher_ret}"
    )


def test_cql_beats_bc_on_mixed_pendulum():
    """The offline-RL payoff (reference: rllib/algorithms/cql): on a
    trajectory-level mixture (half noisy-expert episodes, half random — the
    D4RL medium-expert shape), BC can only imitate the AVERAGE behavior,
    while CQL's conservative Bellman backup stitches the good actions and
    lands far above it."""
    prng = np.random.default_rng(1)

    def noisy_expert(obs):
        a = _pendulum_expert(obs) + prng.normal(0, 0.15, (len(obs), 1)).astype(np.float32)
        return np.clip(a, -2, 2)

    def random_pol(obs):
        return prng.uniform(-2, 2, (len(obs), 1)).astype(np.float32)

    d1 = collect_transitions("Pendulum-v1", noisy_expert, 10_000, seed=4)
    d2 = collect_transitions("Pendulum-v1", random_pol, 10_000, seed=5)
    data = {k: np.concatenate([d1[k], d2[k]]) for k in d1}

    bc = BCConfig(env="Pendulum-v1", epochs_per_iter=5, seed=0).build(data)
    for _ in range(3):
        bc.train()
    bc_ret = bc.evaluate(episodes=10, seed=6)

    # Measured trajectory (20-episode evals, this exact config): CQL sits
    # near the dataset average for ~5k updates, then takes off and
    # converges to ~-135 — near the scripted expert's -155 — by ~9k, while
    # BC stays at ~-1060. The margin below is ~500 under the converged gap.
    cql = CQLConfig(env="Pendulum-v1", updates_per_iter=1000, seed=0).build(data)
    best = -np.inf
    for _ in range(10):
        cql.train()
        best = max(best, cql.evaluate(episodes=10, seed=6))
        if best > bc_ret + 400:
            break  # already conclusive; keep the test fast
    assert best > bc_ret + 400, (
        f"CQL best {best:.0f} does not beat BC {bc_ret:.0f} on the same data"
    )
