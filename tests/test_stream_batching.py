"""Streaming semantics under the batched generator-item fast lane.

The executor ships generator yields through a bounded per-stream buffer
drained by a loop-side pump into ``generator_items`` BATCH frames
(worker._StreamShipper). These tests pin the contract that batching must
not change: order across batch boundaries, backpressure pause/resume with
batch-granular acks, mid-stream close cancelling the user generator exactly
once, single-item flush latency (TTFT path), and duplicate-index dedup when
a dropped batch frame rides the connection-loss retry (seeded chaos).
"""
import time

import pytest

import ray_tpu as rt
from ray_tpu.core import worker as worker_mod


@pytest.fixture(scope="module", autouse=True)
def _session():
    rt.init(num_cpus=4)
    yield
    rt.shutdown()


@rt.remote(num_returns="streaming")
def burst(n):
    for i in range(n):
        yield i


@rt.remote(num_returns="streaming")
def paced(n, delay):
    for i in range(n):
        if i and delay:
            time.sleep(delay)
        yield i


def test_order_preserved_across_batch_boundaries():
    """A producer faster than the pump forces multi-item batch frames; the
    consumer must still observe every index exactly once, in order."""
    worker_mod.stream_batch_stats(reset=True)
    got = [rt.get(ref, timeout=120) for ref in burst.remote(400)]
    assert got == list(range(400))
    hist = worker_mod.stream_batch_stats()
    assert sum(hist.values()) >= 1
    assert any(size > 1 for size in hist), (
        f"a 400-item burst never coalesced a batch frame: {hist}"
    )
    # The histogram also ships as a first-class metric via the reporter.
    from ray_tpu.core import api as _api

    series = [r for r in _api._require_worker()._runtime_series()
              if r["name"] == "stream.batch.items"]
    assert series and series[0]["n"] == sum(hist.values())


def test_single_item_flushes_same_tick():
    """A lone item must not wait for batchmates: the first yield reaches the
    consumer while the producer is still sleeping toward its second (the
    TTFT contract of the serve/LLM token path)."""
    list(paced.remote(1, 0))  # warm: worker spawned, callable cached
    t0 = time.monotonic()
    gen = paced.remote(2, 1.2)
    first = rt.get(next(gen), timeout=60)
    t_first = time.monotonic() - t0
    rest = [rt.get(r, timeout=60) for r in gen]
    t_total = time.monotonic() - t0
    assert first == 0 and rest == [1]
    assert t_first < t_total - 0.8, (
        f"first item buffered behind the stream ({t_first:.2f}s vs {t_total:.2f}s total)"
    )


def test_backpressure_pauses_and_resumes_with_batch_acks(tmp_path):
    """generator_backpressure=2 under the batched lane: the producer stalls
    whenever it runs more than bp items ahead of ACKED consumption (acks are
    coalesced per consumed burst), and resumes as acks land."""
    stamp = str(tmp_path / "yields")
    bp = 2

    @rt.remote(num_returns="streaming", generator_backpressure=bp)
    def gated(path, n):
        for i in range(n):
            with open(path, "a") as f:
                f.write(f"{i} {time.time()}\n")
            yield i

    consumed_at = {}
    gen = gated.remote(stamp, 8)
    for ref in gen:
        i = rt.get(ref, timeout=60)
        consumed_at[i] = time.time()
        time.sleep(0.15)
    assert sorted(consumed_at) == list(range(8))
    produced_at = {}
    with open(stamp) as f:
        for line in f:
            i, ts = line.split()
            produced_at[int(i)] = float(ts)
    assert sorted(produced_at) == list(range(8)), "replay/duplicate yields"
    for i in range(bp + 2, 8):
        # The stamp for item i lands before put(i) — it is gated by put(i-1),
        # which needs the ack covering consumption of item i-bp-1 (small
        # slack for same-host clock granularity).
        gate = i - bp - 1
        assert produced_at[i] >= consumed_at[gate] - 0.05, (
            f"producer ran ahead of the ack window at item {i}: "
            f"produced {produced_at[i]:.3f} vs consumed[{gate}] {consumed_at[gate]:.3f}"
        )


def test_midstream_close_cancels_user_generator_exactly_once(tmp_path):
    """Consumer close mid-stream: the user generator's finally runs exactly
    once (cancellation reaches the producer; no double-close, no run-on)."""
    marker = str(tmp_path / "closes")

    @rt.remote(num_returns="streaming")
    def slow(path, n):
        try:
            for i in range(n):
                time.sleep(0.05)
                yield i
        finally:
            with open(path, "a") as f:
                f.write("CLOSED\n")

    gen = slow.remote(marker, 200)
    assert rt.get(next(gen), timeout=60) == 0
    gen.close()
    deadline = time.time() + 8
    while time.time() < deadline:
        try:
            with open(marker) as f:
                if f.read().count("CLOSED") >= 1:
                    break
        except FileNotFoundError:
            pass
        time.sleep(0.1)
    time.sleep(0.5)  # settle: catch a late double-close
    with open(marker) as f:
        closes = f.read().count("CLOSED")
    assert closes == 1, f"user generator closed {closes} times"


# The seeded dropped-batch-frame replay test needs a cluster armed with a
# chaos spec BEFORE the driver connects, so it lives in its own module
# (tests/test_stream_chaos.py) — rt.init here would shadow it.
