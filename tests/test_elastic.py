"""Elastic training plane: shard-rectangle planning, raw-lane live
transfer, and in-place N->M gang resize (ray_tpu/elastic/)."""
import hashlib
import json
import os
import tempfile
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.elastic import plan as eplan


# ---------------------------------------------------------------------------
# Property test: the shared rectangle-intersection module
# ---------------------------------------------------------------------------


def _random_partition(rng, extent: int) -> list[tuple[int, int]]:
    """Split [0, extent) into 1..4 contiguous blocks (extent 0 => one empty
    block — zero-length dims are legal layouts)."""
    if extent == 0:
        return [(0, 0)]
    k = int(rng.integers(1, min(4, extent) + 1))
    cuts = sorted(rng.choice(np.arange(1, extent), size=k - 1, replace=False).tolist()) if k > 1 else []
    edges = [0] + [int(c) for c in cuts] + [extent]
    return list(zip(edges[:-1], edges[1:]))


def _grid_tiles(rng, shape) -> list[list]:
    """A random grid partition of the whole array: the cross product of a
    random contiguous partition per axis (rows/cols/tiles)."""
    per_axis = [_random_partition(rng, d) for d in shape]
    tiles = [[]]
    for blocks in per_axis:
        tiles = [t + [list(b)] for t in tiles for b in blocks]
    return tiles


def test_plan_pull_tiles_destination_exactly_once_randomized():
    """Randomized N->M layouts (rows/cols/tiles, odd shapes, itemsize>1,
    zero-length dims, replicated extras): planned runs must tile every
    destination byte exactly once, and executing them must materialize the
    right bytes."""
    rng = np.random.default_rng(20260804)
    for case in range(60):
        ndim = int(rng.integers(0, 4))
        shape = tuple(int(rng.integers(0, 8)) for _ in range(ndim))
        dtype = np.dtype(rng.choice(["u1", "f4", "f8"]))
        total = int(np.prod(shape)) if shape else 1
        world = np.arange(total, dtype=np.int64).reshape(shape) if shape else np.int64(7)
        world = (world + 1).astype(dtype) if dtype.kind != "u" else world.astype(dtype)
        src_tiles = _grid_tiles(rng, shape)
        src_rects = {r: rect for r, rect in enumerate(src_tiles)}
        # Replication: sometimes one extra source holds the WHOLE array.
        if rng.random() < 0.3:
            src_rects[len(src_rects)] = [[0, d] for d in shape]
        dst_tiles = _grid_tiles(rng, shape)
        dst_rect = dst_tiles[int(rng.integers(0, len(dst_tiles)))]
        prefer = eplan.rotated(src_rects, int(rng.integers(0, 5)))
        runs = eplan.plan_pull("a", shape, dtype.itemsize, src_rects,
                               dst_rect, prefer)
        # Exact-once: coverage counters over the destination region.
        dst_nbytes = eplan.rect_nbytes(eplan.norm_index(dst_rect, shape),
                                       dtype.itemsize)
        hits = np.zeros(dst_nbytes, dtype=np.int32)
        for r in runs:
            hits[r.dst_off:r.dst_off + r.nbytes] += 1
        assert (hits == 1).all() if dst_nbytes else not runs, (
            f"case {case}: shape={shape} dst={dst_rect} "
            f"multi/zero-covered bytes: {np.unique(hits)}")
        # Execute the runs against materialized source regions and compare
        # with the ground-truth slice.
        def region(rect):
            idx = tuple(slice(a, b) for a, b in eplan.norm_index(rect, shape))
            return np.ascontiguousarray(world[idx] if shape else world)

        buf = bytearray(dst_nbytes)
        for r in runs:
            src = memoryview(region(src_rects[r.src_rank])).cast("B")
            buf[r.dst_off:r.dst_off + r.nbytes] = src[r.src_off:r.src_off + r.nbytes]
        expect = region(dst_rect)
        assert bytes(buf) == expect.tobytes(), f"case {case}: wrong bytes"


def test_plan_pull_window_layouts_n_to_m():
    """1-D optimizer-window reshard N->M for odd sizes, including n <
    world (empty tail windows) and the degenerate n=0."""
    rng = np.random.default_rng(7)
    for n, N, M in [(10, 3, 2), (10, 2, 3), (7, 4, 2), (5, 8, 3), (0, 2, 3),
                    (1, 3, 1), (64, 1, 5), (17, 5, 5)]:
        flat = rng.integers(0, 255, size=max(n, 1)).astype(np.uint8)[:n]
        src_rects = {r: eplan.window_rect(n, N, r) for r in range(N)}
        for m_rank in range(M):
            dst = eplan.window_rect(n, M, m_rank)
            runs = eplan.plan_pull("w", [n], 1, src_rects, dst,
                                   eplan.rotated(src_rects, m_rank))
            lo, hi = dst[0]
            buf = bytearray(hi - lo)
            for r in runs:
                s_lo = src_rects[r.src_rank][0][0]
                buf[r.dst_off:r.dst_off + r.nbytes] = \
                    flat.tobytes()[s_lo + r.src_off:s_lo + r.src_off + r.nbytes]
            assert bytes(buf) == flat.tobytes()[lo:hi], (n, N, M, m_rank)


def test_plan_pull_prefers_sources_in_order_and_fails_loud():
    rects = {0: [[0, 8]], 1: [[0, 8]], 2: [[0, 8]]}  # fully replicated
    runs = eplan.plan_pull("p", [8], 4, rects, [[0, 8]], [2, 0, 1])
    assert [r.src_rank for r in runs] == [2]  # first preference takes all
    # A hole no source covers is a typed CoverageError, never zero-fill.
    with pytest.raises(eplan.CoverageError):
        eplan.plan_pull("p", [8], 4, {0: [[0, 3]], 1: [[5, 8]]},
                        [[0, 8]], [0, 1])
    # The failover-retry form: only the requested intervals get planned.
    runs = eplan.plan_pull("p", [8], 1, rects, [[0, 8]], [1],
                           uncovered=[(2, 5)])
    assert len(runs) == 1 and (runs[0].dst_off, runs[0].nbytes) == (2, 3)


def test_sharded_optimizer_window_export_adopt_matches_reference(monkeypatch):
    """ShardedOptimizerStep windows exported at world 3, resharded through
    the plan layer, adopted at world 2: every adopted window must be
    byte-identical to slicing the known full state."""
    from ray_tpu.train.grad_sync import ShardedOptimizerStep

    from ray_tpu import collective as col

    n_by_bucket = {0: 300, 1: 17}
    full = {
        (bi, slot): np.random.default_rng(bi * 10 + hash(slot) % 7).normal(
            size=n).astype(np.float32)
        for bi, n in n_by_bucket.items() for slot in ("m", "v")
    }

    def make_opt(world, rank):
        opt = ShardedOptimizerStep("adam", group_name="g", bucket_bytes=1024)
        opt._t = 5
        for bi, n in n_by_bucket.items():
            shard = -(-n // world)
            opt._bucket_n[bi] = n
            slots = opt._state.setdefault(bi, {})
            for slot in ("m", "v"):
                padded = np.zeros(shard, dtype=np.float32)
                lo = min(n, rank * shard)
                hi = min(n, lo + shard)
                padded[:hi - lo] = full[(bi, slot)][lo:hi]
                slots[slot] = padded
        return opt

    exports = {}
    for r in range(3):
        monkeypatch.setattr(col, "get_rank", lambda g, _r=r: _r)
        exports[r] = make_opt(3, r).live_shards()
    # Every exported window carries its clipped rect [lo, lo+len) over n.
    for r, shards in exports.items():
        for path, (arr, lo, n) in shards.items():
            bi = int(path.split(".")[1])
            assert n == n_by_bucket[bi]
            assert lo == r * -(-n // 3)
            assert arr.size == max(0, min(-(-n // 3), n - lo))
    monkeypatch.setattr(col, "get_collective_group_size", lambda g: 2)
    for new_rank in range(2):
        # Reshard each path via the plan layer (what transfer.pull_state
        # does over the wire, here executed as local copies).
        adopted = {}
        for path in exports[0]:
            _arr0, _lo0, n = exports[0][path]
            src_rects = {r: [[exports[r][path][1],
                              exports[r][path][1] + exports[r][path][0].size]]
                         for r in range(3)}
            dst = eplan.window_rect(n, 2, new_rank)
            itemsize = 4
            buf = bytearray(eplan.rect_nbytes(dst, itemsize))
            for run in eplan.plan_pull(path, [n], itemsize, src_rects, dst,
                                       eplan.rotated(src_rects, new_rank)):
                src_bytes = exports[run.src_rank][path][0].tobytes()
                buf[run.dst_off:run.dst_off + run.nbytes] = \
                    src_bytes[run.src_off:run.src_off + run.nbytes]
            adopted[path] = (np.frombuffer(bytes(buf), np.float32),
                             dst[0][0], n)
        opt2 = ShardedOptimizerStep("adam", group_name="g", bucket_bytes=1024)
        opt2.adopt_shards(adopted, t=5)
        assert opt2._t == 5
        for bi, n in n_by_bucket.items():
            shard = -(-n // 2)
            lo = min(n, new_rank * shard)
            hi = min(n, lo + shard)
            for slot in ("m", "v"):
                window = opt2._state[bi][slot]
                assert window.size == shard  # uniform re-padded allocation
                assert window[:hi - lo].tobytes() == \
                    full[(bi, slot)][lo:hi].tobytes()
                assert not window[hi - lo:].any()  # pad stays exact zeros


# ---------------------------------------------------------------------------
# Raw-lane transfer between workers
# ---------------------------------------------------------------------------


class _Party:
    """Actor hosting one side of a transfer (runs in its own worker)."""

    def export(self, tid, rank, seed, sharded_n=None):
        from ray_tpu.core import api as _api
        from ray_tpu.elastic import transfer

        rng = np.random.default_rng(seed)
        rep = {"w": rng.normal(size=(33, 17)).astype(np.float32),
               "b": rng.normal(size=()).astype(np.float64)}
        sharded = None
        if sharded_n is not None:
            n, world = sharded_n
            shard = -(-n // world)
            lo = min(n, rank * shard)
            win = np.arange(lo, min(n, lo + shard), dtype=np.float32) * (1 + seed)
            sharded = {"opt.0.m": (win, lo, n)}
        meta = transfer.export_state(tid, rank, rep, sharded,
                                     seq=3, meta={"step": 9})
        meta["addr"] = _api._require_worker().address
        return meta

    def pull(self, tid, sources, world, rank, self_rank=None):
        from ray_tpu.core import api as _api
        from ray_tpu.elastic import transfer

        core = _api._require_worker()
        res = core._run(
            transfer.pull_state(core, tid, sources, world, rank,
                                self_rank=self_rank), timeout=120)
        out = {"stats": res["stats"], "meta": res["meta"], "seq": res["seq"],
               "state": {k: v.tobytes() for k, v in res["state"].items()},
               "sharded": {k: (a.tobytes(), lo, n)
                           for k, (a, lo, n) in res["sharded"].items()}}
        # Counting-shim proof, strongest form: the live pull path never even
        # LOADS the blob-store/checkpoint machinery in this process, let
        # alone reads from it.
        import sys

        out["ckpt_modules"] = sorted(
            m for m in sys.modules if m.startswith("ray_tpu.ckpt"))
        return out

    def release(self, tid):
        from ray_tpu.elastic import transfer

        return transfer.release(tid)


def _expected_rep(seed):
    rng = np.random.default_rng(seed)
    return {"w": rng.normal(size=(33, 17)).astype(np.float32),
            "b": rng.normal(size=()).astype(np.float64)}


def test_transfer_pull_replicated_and_windows_zero_pickle(fresh_cluster):
    """Two workers: B pulls A's replicated arrays + its 1-D window over the
    raw lane; payload bytes identical, wire counters move, and the pulling
    worker never loads any ckpt/blob-store module (the no-disk proof)."""
    fresh_cluster.add_node(num_cpus=2)
    rt.init(address=fresh_cluster.address)
    try:
        Party = rt.remote(_Party)
        a, b = Party.remote(), Party.remote()
        tid = "t-unit-1"
        meta_a = rt.get(a.export.remote(tid, 0, seed=1, sharded_n=(10, 2)), timeout=60)
        meta_b = rt.get(b.export.remote(tid, 1, seed=1, sharded_n=(10, 2)), timeout=60)
        # World 1 target on B: full windows + replicated arrays, sources
        # rank0=A (remote) and rank1=B (local fast path).
        out = rt.get(b.pull.remote(tid, [meta_a, meta_b], 1, 0, 1), timeout=120)
        exp = _expected_rep(1)
        assert out["state"]["w"] == exp["w"].tobytes()
        assert out["state"]["b"] == exp["b"].tobytes()
        arr_bytes, lo, n = out["sharded"]["opt.0.m"]
        assert (lo, n) == (0, 10)
        got = np.frombuffer(arr_bytes, np.float32)
        # rank0's window [0,5) scaled by (1+seed)=2, rank1's [5,10) too.
        assert got.tobytes() == (np.arange(10, dtype=np.float32) * 2).tobytes()
        assert out["meta"] == {"step": 9} and out["seq"] == 3
        st = out["stats"]
        assert st["wire_bytes"] > 0 and st["local_bytes"] > 0
        assert st["bytes"] == st["wire_bytes"] + st["local_bytes"]
        assert st["mb_s"] > 0 and st["failovers"] == 0
        assert out["ckpt_modules"] == [], (
            f"live pull loaded blob-store code: {out['ckpt_modules']}")
        assert rt.get(a.release.remote(tid), timeout=30)
        assert not rt.get(a.release.remote(tid), timeout=30)  # idempotent
    finally:
        rt.shutdown()


def test_transfer_failover_reroutes_dropped_source(fresh_cluster):
    """Chaos-dropped frames from the first source: the puller's deadline
    fails that source typed, re-plans onto the replica, and the assembled
    bytes are still exact."""
    from ray_tpu.chaos import plan as chaos_plan
    from ray_tpu.core.config import get_config

    cfg = get_config()
    cfg.elastic_transfer_timeout_s = 3.0
    cfg.chaos_spec = json.dumps({
        "seed": 5,
        "rules": [{"site": "elastic.reshard.transfer", "kind": "drop",
                   "nth": 1, "ctx": {"src": "0"}}],
    })
    chaos_plan.install_from_json(cfg.chaos_spec)
    fresh_cluster.add_node(num_cpus=3)
    rt.init(address=fresh_cluster.address)
    try:
        Party = rt.remote(_Party)
        a, b, c = Party.remote(), Party.remote(), Party.remote()
        tid = "t-unit-drop"
        metas = [rt.get(w.export.remote(tid, r, seed=4), timeout=60)
                 for r, w in ((0, a), (1, b))]
        # C (no local export) pulls; the preferred source's first frame is
        # chaos-dropped -> after the 3s deadline its runs re-plan onto the
        # other replica.
        out = rt.get(c.pull.remote(tid, metas, 1, 0, None), timeout=120)
        exp = _expected_rep(4)
        assert out["state"]["w"] == exp["w"].tobytes()
        assert out["state"]["b"] == exp["b"].tobytes()
        assert out["stats"]["failovers"] >= 1, out["stats"]
    finally:
        rt.shutdown()
        chaos_plan.uninstall()


def test_transfer_uncoverable_window_fails_typed(fresh_cluster):
    """A window whose only holder is gone must raise the typed error (the
    controller's checkpoint-fallback trigger), never hand back zeros."""
    fresh_cluster.add_node(num_cpus=2)
    rt.init(address=fresh_cluster.address)
    try:
        Party = rt.remote(_Party)
        a, b = Party.remote(), Party.remote()
        tid = "t-unit-hole"
        meta_a = rt.get(a.export.remote(tid, 0, seed=2, sharded_n=(10, 2)), timeout=60)
        # Only rank 0's half of the window is offered; world-1 target needs
        # [0, 10).
        with pytest.raises(Exception) as ei:
            rt.get(b.pull.remote(tid, [meta_a], 1, 0, None), timeout=120)
        assert "ElasticTransferError" in str(ei.value) or "uncoverable" in str(ei.value)
    finally:
        rt.shutdown()


# ---------------------------------------------------------------------------
# End-to-end: live in-place gang resize on a preemption notice
# ---------------------------------------------------------------------------


def _live_fn(config):
    """Deterministic SPMD steps with a ShardedOptimizerStep; state kept
    live every step. Parks at the barrier step (first incarnation) so the
    resize point is a deterministic boundary."""
    from ray_tpu import train

    ctx = train.get_context()
    world = ctx.get_world_size()
    steps, barrier = config["steps"], config["barrier_step"]
    opt = ctx.sharded_optimizer("adam", lr=0.1, bucket_bytes=512)
    d = 96
    resumed = train.live_resume()
    if resumed is not None:
        params = np.array(resumed["state"]["params"], copy=True)
        opt.adopt_shards(resumed["sharded"], t=resumed["meta"]["t"])
        start = resumed["meta"]["step"] + 1
        full = opt.full_state()
        h = hashlib.blake2b(params.tobytes(), digest_size=12)
        for k in sorted(full):
            h.update(full[k].tobytes())
        train.report({"resume_digest": h.hexdigest(), "world_size": world,
                      "resume_step": start - 1})
    else:
        params = np.zeros(d, dtype=np.float32)
        start = 0
    for i in range(start, steps):
        target = np.random.default_rng(100 + i).normal(size=d).astype(np.float32)
        params = opt.step({"p": params}, {"p": params - target})["p"]
        full = opt.full_state()
        h = hashlib.blake2b(params.tobytes(), digest_size=12)
        for k in sorted(full):
            h.update(full[k].tobytes())
        train.report({"step": i, "digest": h.hexdigest(), "world_size": world})
        train.keep_live({"params": params}, sharded=opt.live_shards(),
                        meta={"step": i, "t": opt._t})
        if i == barrier and world == config["start_world"]:
            if ctx.get_world_rank() == 0:
                open(config["marker"], "w").close()
            while not ctx.should_stop():
                time.sleep(0.05)
            raise RuntimeError("stopped at resize barrier")


def test_live_resize_on_preemption_is_byte_exact(fresh_cluster):
    """2-worker gang on two nodes; one node drains mid-run (the preemption
    notice surface). The controller live-reshards to world 1 in place: no
    checkpoint restore, optimizer windows byte-identical across the resize
    (resume digest == the parked boundary's digest), steps contiguous."""
    import threading

    from ray_tpu.core.config import get_config
    from ray_tpu.train import (
        DataParallelTrainer,
        ElasticScalingPolicy,
        FailureConfig,
        RunConfig,
        ScalingConfig,
    )

    get_config().elastic_transfer_timeout_s = 15.0
    n1 = fresh_cluster.add_node(num_cpus=1)
    n2 = fresh_cluster.add_node(num_cpus=1)
    rt.init(address=fresh_cluster.address)
    try:
        tmp = tempfile.mkdtemp()
        marker = os.path.join(tmp, "progress")
        steps = 6
        scaling = ScalingConfig(num_workers=2, resources_per_worker={"CPU": 1})
        trainer = DataParallelTrainer(
            _live_fn,
            train_loop_config={"steps": steps, "barrier_step": 2,
                               "start_world": 2, "marker": marker},
            scaling_config=scaling,
            run_config=RunConfig(
                name="live-e2e", storage_path=tmp,
                failure_config=FailureConfig(max_failures=0),
                elastic_live=True,
            ),
            scaling_policy=ElasticScalingPolicy(
                scaling, min_workers=1, max_workers=2,
                resize_cooldown_s=3600.0),
            controller_as_actor=False,
        )

        from ray_tpu.core import api as _api

        def drain_when_progressing():
            deadline = time.time() + 90
            while not os.path.exists(marker) and time.time() < deadline:
                time.sleep(0.1)
            core = _api._require_worker()
            # Drain one gang node (whichever rank landed there — survivor
            # ranks reassign in old-rank order and new rank 0 stays
            # canonical either way).
            core._run(core.controller.call("drain_node",
                                           {"node_id": n2.node_id}))

        t = threading.Thread(target=drain_when_progressing, daemon=True)
        t.start()
        result = trainer.fit()
        t.join()
        assert result.error is None, result.error
        by_step, resume = {}, None
        for m in result.metrics_history:
            if "resume_digest" in m:
                resume = m
            elif "step" in m:
                by_step[m["step"]] = m
        assert sorted(by_step) == list(range(steps)), sorted(by_step)
        sizes = [by_step[i]["world_size"] for i in range(steps)]
        assert sizes[0] == 2 and sizes[-1] == 1, sizes
        assert resume is not None, "no live resume happened"
        assert resume["world_size"] == 1
        bstep = resume["resume_step"]
        # Byte-exactness across the wire: the reassembled full state on the
        # 1-host mesh digests identically to the parked 2-host boundary.
        assert resume["resume_digest"] == by_step[bstep]["digest"]
    finally:
        rt.shutdown()
