"""graftlint phase 2: the whole-program rules over the project index.

Per-rule fixtures (firing / clean / suppressed-with-reason) for the five
cross-file contracts, a two-file pair proving the index actually crosses
file boundaries, and the parse-cache behavior tests: an unchanged tree is
served entirely from cache (much faster), and editing one file re-parses
only that file.
"""
import textwrap
import time

from ray_tpu.analysis import BAD_SUPPRESSION, lint_paths, lint_sources

SERVER = """
    class Controller:
        async def handle_ping(self, conn, p):
            return {"ok": True}
"""


def _xlint(sources: dict, readme=None):
    return lint_sources(
        {path: textwrap.dedent(src) for path, src in sources.items()},
        readme=readme,
    )


def _hits(result, rule):
    return [f for f in result.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# rpc-verb-contract
# ---------------------------------------------------------------------------

def test_rpc_unknown_verb_fires():
    r = _xlint({
        "server.py": SERVER,
        "client.py": """
            async def go(conn):
                await conn.call("ping", {})
                await conn.call("pingg", {})
        """,
    })
    hits = _hits(r, "rpc-verb-contract")
    assert len(hits) == 1 and hits[0].path == "client.py"
    assert "'pingg'" in hits[0].message and "no server class" in hits[0].message


def test_rpc_wrong_server_fires():
    r = _xlint({
        "server.py": SERVER + """
            class NodeDaemon:
                async def handle_pull_chunk(self, conn, p):
                    return {}
        """,
        "client.py": """
            async def go(self):
                await self.controller.call("ping", {})
                await self.controller.call("pull_chunk", {})
        """,
    })
    hits = _hits(r, "rpc-verb-contract")
    assert len(hits) == 1 and "wrong server" in hits[0].message


def test_rpc_handler_arity_fires():
    r = _xlint({
        # On a class that IS a server (handle_ping qualifies it), a handler
        # whose shape dispatch can't satisfy is a finding.
        "server.py": SERVER + """
            class NodeDaemon:
                async def handle_pull_chunk(self, conn, p):
                    return {}

                async def handle_push_part(self, conn, p, extra):
                    return {}
        """,
        "client.py": """
            async def go(conn):
                await conn.call("ping", {})
                await conn.call("pull_chunk", {})
                await conn.call("push_part", {})
        """,
    })
    hits = _hits(r, "rpc-verb-contract")
    assert len(hits) == 1 and "required args after self" in hits[0].message


def test_rpc_dead_verb_fires_and_string_pool_keeps_alive():
    sources = {
        "server.py": SERVER + """
            class NodeDaemon:
                async def handle_orphan_thing(self, conn, p):
                    return {}
        """,
        "client.py": """
            async def go(conn):
                await conn.call("ping", {})
        """,
    }
    r = _xlint(sources)
    hits = _hits(r, "rpc-verb-contract")
    assert len(hits) == 1 and "dead verb" in hits[0].message
    # Dynamic dispatch pools (`_call("orphan_thing", ...)` style constants)
    # keep a verb alive even with no direct send site.
    sources["client.py"] += '\nVERBS = ["orphan_thing"]\n'
    assert not _hits(_xlint(sources), "rpc-verb-contract")


def test_rpc_dead_verb_suppressed_with_reason():
    r = _xlint({
        "server.py": SERVER + """
            class NodeDaemon:
                async def handle_orphan_thing(self, conn, p):  # graftlint: disable=rpc-verb-contract  kept one release for rollback compat
                    return {}
        """,
        "client.py": """
            async def go(conn):
                await conn.call("ping", {})
        """,
    })
    assert not _hits(r, "rpc-verb-contract")
    assert r.suppressed_counts.get("rpc-verb-contract") == 1


def test_rpc_skips_without_server_classes():
    # Partial tree (a lone client file): no RPC surface, no guessing.
    r = _xlint({"client.py": 'async def go(conn):\n    await conn.call("zz_q", {})\n'})
    assert not _hits(r, "rpc-verb-contract")


# ---------------------------------------------------------------------------
# adopted-config
# ---------------------------------------------------------------------------

def test_adopted_config_bare_read_fires():
    r = _xlint({
        "ray_tpu/ckpt/thing.py": """
            from ray_tpu.core.config import get_config

            def poll_interval():
                return get_config().poll_s
        """,
    })
    hits = _hits(r, "adopted-config")
    assert len(hits) == 1 and "adopted core.config" in hits[0].message


def test_adopted_config_fallback_idiom_and_home_modules_clean():
    r = _xlint({
        "ray_tpu/ckpt/thing.py": """
            def poll_interval(core):
                cfg = getattr(core, "config", None) or get_config()
                return cfg.poll_s
        """,
        "ray_tpu/core/api.py": """
            def bootstrap():
                return get_config()
        """,
    })
    assert not _hits(r, "adopted-config")


def test_adopted_config_suppressed_with_reason():
    r = _xlint({
        "ray_tpu/tools/head_only.py": """
            def show():
                return get_config().to_dict()  # graftlint: disable=adopted-config  head-process CLI tool, never runs in a spawned worker
        """,
    })
    assert not _hits(r, "adopted-config")
    assert r.suppressed_counts.get("adopted-config") == 1


# ---------------------------------------------------------------------------
# ctx-propagation
# ---------------------------------------------------------------------------

def test_ctx_handler_hard_read_crosses_files():
    """The index-crossing pair: the handler's unconditional p["tc"] read
    lives in server.py, the violating send site in client.py — neither file
    alone contains the contract."""
    r = _xlint({
        "server.py": """
            class NodeDaemon:
                async def handle_fetch_shard(self, conn, p):
                    token = activate(tuple(p["tc"]))
                    return {"ok": True}
        """,
        "client.py": """
            async def pull(conn):
                return await conn.call("fetch_shard", {"items": []})
        """,
    })
    hits = _hits(r, "ctx-propagation")
    assert len(hits) == 1 and hits[0].path == "client.py"
    assert "its handler reads it unconditionally" in hits[0].message


def test_ctx_sibling_senders_define_the_contract():
    r = _xlint({
        "a.py": """
            async def one(conn, t):
                await conn.call("sync_thing", {"x": 1, "tc": t})
        """,
        "b.py": """
            async def two(conn):
                await conn.call("sync_thing", {"x": 2})
        """,
    })
    hits = _hits(r, "ctx-propagation")
    assert len(hits) == 1 and hits[0].path == "b.py"
    assert "other send sites of this verb set it" in hits[0].message


def test_ctx_lean_frames_need_both_planes():
    r = _xlint({
        "a.py": """
            async def push(conn, t):
                await conn.call("task_go", {"lean": 1, "tc": t})
        """,
    })
    hits = _hits(r, "ctx-propagation")
    assert len(hits) == 1 and "'qc'" in hits[0].message


def test_ctx_conditional_subscript_store_counts_as_set():
    # The task lane's idiom: set tc only when a trace is live.
    r = _xlint({
        "server.py": """
            class NodeDaemon:
                async def handle_fetch_shard(self, conn, p):
                    return {"t": p["tc"]}
        """,
        "client.py": """
            async def pull(conn, t):
                payload = {"items": []}
                if t is not None:
                    payload["tc"] = t
                return await conn.call("fetch_shard", payload)
        """,
    })
    assert not _hits(r, "ctx-propagation")


def test_ctx_opaque_payloads_are_not_guessed_at():
    r = _xlint({
        "server.py": """
            class NodeDaemon:
                async def handle_fetch_shard(self, conn, p):
                    return {"t": p["tc"]}
        """,
        "client.py": """
            async def pull(conn, payload):
                return await conn.call("fetch_shard", payload)
        """,
    })
    assert not _hits(r, "ctx-propagation")


def test_ctx_suppressed_with_reason():
    r = _xlint({
        "a.py": """
            async def one(conn, t):
                await conn.call("sync_thing", {"x": 1, "tc": t})
        """,
        "b.py": """
            async def two(conn):
                await conn.call("sync_thing", {"x": 2})  # graftlint: disable=ctx-propagation  loopback self-send, trace already active on this thread
        """,
    })
    assert not _hits(r, "ctx-propagation")
    assert r.suppressed_counts.get("ctx-propagation") == 1


# ---------------------------------------------------------------------------
# metric-contract
# ---------------------------------------------------------------------------

EMIT = """
    from ray_tpu.util import metrics as _metrics

    C = _metrics.Counter("pool.live_total", "live things")
"""


def test_metric_dead_reference_fires():
    r = _xlint({
        "emit.py": EMIT,
        "pkg/obs/dash.py": """
            def scan(rows):
                return [r for r in rows if r.get("name") == "pool.dead_total"]
        """,
    })
    hits = _hits(r, "metric-contract")
    assert len(hits) == 1 and hits[0].path == "pkg/obs/dash.py"
    assert "no code path emits it" in hits[0].message


def test_metric_live_reference_clean_and_scope_gated():
    r = _xlint({
        "emit.py": EMIT,
        "pkg/obs/dash.py": """
            def scan(rows):
                return [r for r in rows if r.get("name") == "pool.live_total"]
        """,
        # Same compare OUTSIDE obs/chaos scope: not a metric reference.
        "pkg/data/misc.py": """
            def scan(rows):
                return [r for r in rows if r.get("name") == "pool.dead_total"]
        """,
    })
    assert not _hits(r, "metric-contract")


def test_metric_kind_and_labelset_consistency():
    r = _xlint({
        "a.py": EMIT,
        "b.py": """
            from ray_tpu.util import metrics as _metrics

            G = _metrics.Gauge("pool.live_total", "same name, wrong kind")
            C1 = _metrics.Counter("pool.shed_total", "x", tag_keys=("reason",))
            C2 = _metrics.Counter("pool.shed_total", "x", tag_keys=("zone",))
        """,
    })
    msgs = [f.message for f in _hits(r, "metric-contract")]
    assert any("one name, one kind" in m for m in msgs)
    assert any("inconsistent label sets" in m for m in msgs)


def test_metric_readme_labels_checked_against_tagsets(tmp_path):
    readme = tmp_path / "README.md"
    readme.write_text(
        "Shedding shows up in `pool.shed_total{reason}` and\n"
        "`pool.shed_total{zone}` on the dashboard.\n"
    )
    r = _xlint({
        "a.py": """
            from ray_tpu.util import metrics as _metrics

            C = _metrics.Counter("pool.shed_total", "x", tag_keys=("reason", "qos"))
        """,
    }, readme=str(readme))
    hits = _hits(r, "metric-contract")
    assert len(hits) == 1 and hits[0].path == "README.md"
    assert "{zone}" in hits[0].message


def test_metric_suppressed_with_reason():
    r = _xlint({
        "emit.py": EMIT,
        "pkg/obs/dash.py": """
            def scan(rows):
                return [r for r in rows if r.get("name") == "pool.request"]  # graftlint: disable=metric-contract  span name, not a metric series
        """,
    })
    assert not _hits(r, "metric-contract")
    assert r.suppressed_counts.get("metric-contract") == 1


def test_metric_skips_without_any_emits():
    # Partial tree (dashboards linted alone): nothing to check against.
    r = _xlint({
        "pkg/obs/dash.py": """
            def scan(rows):
                return [r for r in rows if r.get("name") == "pool.dead_total"]
        """,
    })
    assert not _hits(r, "metric-contract")


# ---------------------------------------------------------------------------
# dtype-kind
# ---------------------------------------------------------------------------

def test_dtype_kind_raw_check_fires():
    r = _xlint({
        "pkg/data/part.py": """
            def pick(arr):
                if arr.dtype.kind == "f":
                    return 1
        """,
    })
    hits = _hits(r, "dtype-kind")
    assert len(hits) == 1 and "bf16" in hits[0].message


def test_dtype_kind_predicate_and_home_module_clean():
    r = _xlint({
        "pkg/x.py": """
            def _is_float_dtype(dt):
                return dt.kind == "f"
        """,
        "ray_tpu/util/dtypes.py": """
            def is_float_dtype(dt):
                return dt.kind == "f"
        """,
    })
    assert not _hits(r, "dtype-kind")


def test_dtype_kind_suppressed_with_reason():
    r = _xlint({
        "pkg/data/part.py": """
            def pick(arr):
                if arr.dtype.kind == "f":  # graftlint: disable=dtype-kind  numpy-only input path, bf16 cannot reach here
                    return 1
        """,
    })
    assert not _hits(r, "dtype-kind")
    assert r.suppressed_counts.get("dtype-kind") == 1


# ---------------------------------------------------------------------------
# chaos-gate (the tree-wide half: duplicate site names across files)
# ---------------------------------------------------------------------------

def test_chaos_duplicate_site_across_files_fires():
    src = """
        from ray_tpu import chaos

        def f():
            chaos.maybe_inject("xfixture.site")
    """
    r = _xlint({"a.py": src, "b.py": src})
    hits = _hits(r, "chaos-gate")
    assert len(hits) == 1 and hits[0].path == "b.py"
    assert "first used at a.py" in hits[0].message
    assert not _hits(_xlint({"a.py": src}), "chaos-gate")


# ---------------------------------------------------------------------------
# parse cache
# ---------------------------------------------------------------------------

def test_cache_replays_findings_and_reparses_only_the_edited_file(tmp_path):
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("import asyncio\n\n\nasync def f():\n    asyncio.create_task(g())\n")
    b.write_text("Y = 2\n")
    cp = str(tmp_path / "cache" / "pc.json")

    r1 = lint_paths([str(tmp_path)], cache_path=cp)
    assert r1.cache_info == {"hits": 0, "misses": 2}
    r2 = lint_paths([str(tmp_path)], cache_path=cp)
    assert r2.cache_info == {"hits": 2, "misses": 0}
    # Cached units replay findings identically — a cache hit is not a skip.
    assert [f.render() for f in r2.findings] == [f.render() for f in r1.findings]
    assert len(r2.findings) == 1 and r2.findings[0].rule == "bg-strong-ref"

    b.write_text("Y = 3\n")  # same size: forces the content-hash path
    r3 = lint_paths([str(tmp_path)], cache_path=cp)
    assert r3.cache_info == {"hits": 1, "misses": 1}


def test_cache_suppressions_survive_the_round_trip(tmp_path):
    a = tmp_path / "a.py"
    a.write_text(
        "import asyncio\n\n\nasync def f():\n"
        "    asyncio.create_task(g())  # graftlint: disable=bg-strong-ref  fixture: handle kept by caller\n"
    )
    cp = str(tmp_path / "pc.json")
    r1 = lint_paths([str(a)], cache_path=cp)
    r2 = lint_paths([str(a)], cache_path=cp)
    for r in (r1, r2):
        assert not r.findings
        assert r.suppressed_counts.get("bg-strong-ref") == 1
    assert r2.cache_info["hits"] == 1


def test_unchanged_tree_rerun_is_served_from_cache_and_much_faster(tmp_path):
    # A tree big enough that parsing + rule walking dominates.
    body = "".join(
        f"async def f{i}(x):\n    return await g(x + {i})\n\n" for i in range(200)
    )
    for i in range(20):
        (tmp_path / f"m{i}.py").write_text(body)
    cp = str(tmp_path / "pc.json")

    t0 = time.perf_counter()
    r1 = lint_paths([str(tmp_path)], cache_path=cp)
    cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    r2 = lint_paths([str(tmp_path)], cache_path=cp)
    warm = time.perf_counter() - t0

    assert r1.cache_info == {"hits": 0, "misses": 20}
    assert r2.cache_info == {"hits": 20, "misses": 0}
    assert not r2.findings and not r2.errors
    assert warm * 10 < cold, f"cold={cold:.3f}s warm={warm:.3f}s"


def test_cache_never_caches_parse_errors(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(:\n")
    cp = str(tmp_path / "pc.json")
    r1 = lint_paths([str(bad)], cache_path=cp)
    r2 = lint_paths([str(bad)], cache_path=cp)
    assert r1.errors and r2.errors
    assert r2.cache_info == {"hits": 0, "misses": 1}


# ---------------------------------------------------------------------------
# report plumbing for the new phase
# ---------------------------------------------------------------------------

def test_report_carries_rule_stats_and_index_summary():
    r = _xlint({
        "server.py": SERVER,
        "client.py": """
            async def go(conn):
                await conn.call("ping", {})
        """,
    })
    report = r.to_json()
    assert report["version"] == 2
    assert report["index"]["send_sites"] == 1
    assert report["index"]["server_classes"] == ["Controller"]
    assert report["rules"]["rpc-verb-contract"]["stats"]["send_sites"] == 1
    assert report["rules"]["adopted-config"]["stats"]["reads"] == 0


def test_bad_suppression_on_cross_file_rule_still_fires():
    r = _xlint({
        "pkg/data/part.py": """
            def pick(arr):
                if arr.dtype.kind == "f":  # graftlint: disable=dtype-kind
                    return 1
        """,
    })
    assert _hits(r, "dtype-kind") and _hits(r, BAD_SUPPRESSION)
