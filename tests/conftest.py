"""Test harness config.

JAX tests run on a virtual 8-device CPU mesh (the reference tests multi-host
TPU scheduling with fake resources the same way — SURVEY §4 "fake TPU
topology"); real TPU runs are reserved for bench.py.
"""
import os

# Force CPU regardless of ambient JAX_PLATFORMS (the env tunnels one real TPU
# chip and its sitecustomize overrides the env var; tests must run on the
# virtual 8-device CPU mesh, bench.py on the TPU).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("RAYTPU_OBJECT_STORE_MEMORY", str(64 * 1024 * 1024))
# Disarm the always-on profiler for suites that don't exercise it: on the
# 1-core CI box every armed process's 19 Hz frame-walk steals ~0.7% of the
# one core, and a multi-node test runs ~10 processes — enough aggregate drag
# (~15-20% measured on worker-heavy modules) to push tier-1 past its wall
# budget. Profiler tests arm explicitly (profiler.arm(...) ignores the env;
# cluster fixtures set cfg.profile_hz after apply_env), and chaos scenarios
# that assert the alert->flamegraph chain pin cfg.profile_hz themselves, so
# coverage of the armed path is unchanged. setdefault: export a nonzero
# RAYTPU_PROFILE_HZ to run the whole suite armed.
os.environ.setdefault("RAYTPU_PROFILE_HZ", "0")
# Spawned workers must also land on CPU (their sitecustomize re-pins the
# tunneled TPU backend regardless of JAX_PLATFORMS).
os.environ["RAYTPU_FORCE_JAX_PLATFORM"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture(scope="module")
def shared_ray():
    import ray_tpu as rt

    rt.init(num_cpus=8)
    yield rt
    rt.shutdown()


@pytest.fixture
def fresh_cluster():
    from ray_tpu.core.api import Cluster
    from ray_tpu.core.config import get_config

    # Tests tune the cluster's knobs (inline caps, chunk sizes) through
    # cluster.config — which IS the process-global Config. Snapshot and
    # restore it, or one test's tuning silently reshapes every later module
    # (a 4 MiB inline cap left by test_object_transfer flipped
    # test_state_api's shm attribution to "memory" 40 tests later).
    cfg = get_config()
    snap = cfg.to_dict()
    cluster = Cluster(initialize_head=False)
    yield cluster
    cluster.shutdown()
    for k, v in snap.items():
        setattr(cfg, k, v)


@pytest.fixture(autouse=True, scope="module")
def _no_cluster_leaks(request):
    """Module-boundary leak sentinel (round-5 verdict action item): a module
    that leaves a live in-process Cluster, an initialized driver session, or
    a session auth token behind fails HERE — at the leak's source — instead
    of poisoning whatever module happens to run 40 tests later (the
    test_start_cli order-sensitivity was exactly such a leak: clusters whose
    tests called only rt.shutdown(), which detaches the driver but never
    stops an address-connected cluster). The sentinel also cleans up so one
    leaky module still can't cascade."""
    from ray_tpu.core import api, rpc
    from ray_tpu.core.config import get_config

    before = list(api._LIVE_CLUSTERS)
    cfg_before = get_config().to_dict()
    yield
    leaks = []
    if api._global_worker is not None:
        leaks.append("driver session left initialized (missing rt.shutdown())")
        try:
            api.shutdown()
        except Exception:
            pass
    for c in [c for c in list(api._LIVE_CLUSTERS) if c not in before]:
        leaks.append(
            f"in-process Cluster {getattr(c, 'controller_addr', '?')} left running "
            "(rt.shutdown() detaches the driver; call cluster.shutdown() too)"
        )
        try:
            c.shutdown()
        except Exception:
            pass
    cfg = get_config()
    env_token = type(cfg)().apply_env().auth_token
    if cfg.auth_token and cfg.auth_token != env_token and not api._token_owned_by_live_cluster(cfg.auth_token):
        leaks.append(f"session auth token '{cfg.auth_token[:8]}…' leaked into the global config")
        cfg.auth_token = env_token
        rpc.set_auth_token(env_token or None)
    # Config drift: tests tune cluster knobs through the process-global
    # Config (cluster.config aliases it); a module must put back what it
    # changed or it silently reshapes every later module's clusters.
    drift = {
        k: (cfg_before[k], v) for k, v in get_config().to_dict().items()
        if k != "auth_token" and v != cfg_before[k]
    }
    if drift:
        leaks.append(f"process-global Config drifted: {drift}")
        for k, v in cfg_before.items():
            if k != "auth_token":
                setattr(cfg, k, v)
    assert not leaks, f"{request.module.__name__} leaked cross-test state:\n  " + "\n  ".join(leaks)


# Per-test timeout (reference: pytest.ini's 180s default): one hung
# collective/RPC must not eat the whole suite. SIGALRM-based (no
# pytest-timeout in this image); generous default because CartPole learning
# tests legitimately run minutes on this 1-core host.
import signal

TEST_TIMEOUT_S = int(os.environ.get("RAYTPU_TEST_TIMEOUT_S", "600"))


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    def _handler(signum, frame):
        raise TimeoutError(f"test exceeded {TEST_TIMEOUT_S}s timeout")

    old = signal.signal(signal.SIGALRM, _handler)
    signal.alarm(TEST_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
