"""Test harness config.

JAX tests run on a virtual 8-device CPU mesh (the reference tests multi-host
TPU scheduling with fake resources the same way — SURVEY §4 "fake TPU
topology"); real TPU runs are reserved for bench.py.
"""
import os

# Force CPU regardless of ambient JAX_PLATFORMS (the env tunnels one real TPU
# chip and its sitecustomize overrides the env var; tests must run on the
# virtual 8-device CPU mesh, bench.py on the TPU).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("RAYTPU_OBJECT_STORE_MEMORY", str(64 * 1024 * 1024))
# Spawned workers must also land on CPU (their sitecustomize re-pins the
# tunneled TPU backend regardless of JAX_PLATFORMS).
os.environ["RAYTPU_FORCE_JAX_PLATFORM"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture(scope="module")
def shared_ray():
    import ray_tpu as rt

    rt.init(num_cpus=8)
    yield rt
    rt.shutdown()


@pytest.fixture
def fresh_cluster():
    from ray_tpu.core.api import Cluster

    cluster = Cluster(initialize_head=False)
    yield cluster
    cluster.shutdown()


# Per-test timeout (reference: pytest.ini's 180s default): one hung
# collective/RPC must not eat the whole suite. SIGALRM-based (no
# pytest-timeout in this image); generous default because CartPole learning
# tests legitimately run minutes on this 1-core host.
import signal

TEST_TIMEOUT_S = int(os.environ.get("RAYTPU_TEST_TIMEOUT_S", "600"))


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    def _handler(signum, frame):
        raise TimeoutError(f"test exceeded {TEST_TIMEOUT_S}s timeout")

    old = signal.signal(signal.SIGALRM, _handler)
    signal.alarm(TEST_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
