"""Chaos plane: seeded deterministic fault injection + scenario runner.

Reference analogue: the nightly chaos_test suites (kill raylets/workers on a
wall-clock schedule). Here every fault is a pure function of
(seed, rule, hit-counter), so these tests can assert REPLAY: the same seed
reproduces the identical injection sequence, diffed across two real runs.

Tier-1 keeps the unit layer + one fast seeded worker-kill smoke scenario +
the replay-diff; the full five-scenario battery is the `-m slow` soak.
"""
from __future__ import annotations

import json

import pytest

from ray_tpu.chaos import plan as _plan
from ray_tpu.chaos.plan import ChaosError, FaultRule, FaultSchedule
from ray_tpu.chaos.scenarios import SCENARIOS, run_scenario


@pytest.fixture(autouse=True)
def _chaos_clean():
    """Every test starts and ends with the chaos plane disarmed — an armed
    schedule leaking out of a test would inject faults into later modules."""
    _plan.uninstall()
    yield
    _plan.uninstall()


def _schedule(rules, seed=0):
    return FaultSchedule([FaultRule.from_spec(r) for r in rules], seed=seed)


# ---------------------------------------------------------------------------
# the gate + schedule mechanics (no cluster)
# ---------------------------------------------------------------------------

def test_gate_disabled_path_returns_none():
    assert _plan.active() is None
    assert _plan.maybe_inject("rpc.frame.send") is None
    assert _plan.injection_log() == []


def test_nth_hit_fires_exactly_once():
    _plan.install(_schedule([{"site": "rpc.frame.send", "kind": "drop", "nth": 3}]))
    fired = [_plan.maybe_inject("rpc.frame.send") for _ in range(6)]
    assert [f.kind if f else None for f in fired] == [None, None, "drop", None, None, None]
    assert _plan.injection_log(normalize=True) == [
        {"site": "rpc.frame.send", "kind": "drop", "rule": 0, "hit": 3}
    ]


def test_every_and_max_faults():
    _plan.install(_schedule([
        {"site": "worker.exec", "kind": "error", "every": 2, "max_faults": 2}
    ]))
    fired = [_plan.maybe_inject("worker.exec") is not None for _ in range(8)]
    assert fired == [False, True, False, True, False, False, False, False]


def test_pattern_and_ctx_matching():
    _plan.install(_schedule([
        {"site": "node.*", "kind": "error", "ctx": {"source": "nodeB"}},
    ]))
    assert _plan.maybe_inject("node.pull.source", source="nodeA") is None
    assert _plan.maybe_inject("rpc.frame.send", source="nodeB") is None  # pattern miss
    f = _plan.maybe_inject("node.pull.source", source="nodeB")
    assert f is not None and f.kind == "error"
    # ctx-filtered misses do not consume the rule's hit counter
    assert f.hit == 1


def test_probability_is_seed_deterministic():
    def decisions(seed):
        _plan.install(_schedule(
            # wildcard pattern: synthetic sites validate only when concrete
            [{"site": "s.p*", "kind": "drop", "p": 0.5}], seed=seed
        ))
        return [
            _plan.maybe_inject("s.p") is not None
            for _ in range(200)
        ]

    a, b, c = decisions(42), decisions(42), decisions(7)
    assert a == b, "same seed must replay the identical decision sequence"
    assert a != c, "different seeds must differ (2^-200 false-failure odds)"
    assert 40 < sum(a) < 160, "p=0.5 should fire roughly half the time"


def test_first_matching_rule_wins_and_counters_are_per_rule():
    _plan.install(_schedule([
        {"site": "a.*", "kind": "drop", "nth": 2},
        {"site": "a.x*", "kind": "error"},
    ]))
    f1 = _plan.maybe_inject("a.x")  # rule0 hit1 (no fire), rule1 hit1 fires
    f2 = _plan.maybe_inject("a.x")  # rule0 hit2 fires first
    assert (f1.rule_index, f1.kind) == (1, "error")
    assert (f2.rule_index, f2.kind) == (0, "drop")


def test_schedule_validation_rejects_typos():
    with pytest.raises(ValueError, match="unknown chaos site"):
        _schedule([{"site": "rpc.frame.snd", "kind": "drop"}])
    with pytest.raises(ValueError, match="does not support kind"):
        _schedule([{"site": "rpc.frame.send", "kind": "evict"}])
    with pytest.raises(ValueError, match="unknown fault-rule keys"):
        _schedule([{"site": "rpc.frame.send", "kind": "drop", "nthh": 1}])
    # wildcards validate at runtime, not compile time
    _schedule([{"site": "rpc.*", "kind": "drop"}])


def test_install_from_json_is_idempotent_for_identical_spec():
    spec = json.dumps({"seed": 5, "rules": [{"site": "worker.exec", "kind": "error", "nth": 1}]})
    _plan.install_from_json(spec)
    assert _plan.maybe_inject("worker.exec") is not None
    _plan.install_from_json(spec)  # re-registration path: must NOT reset counters
    assert len(_plan.injection_log()) == 1
    assert _plan.active().rules[0].hits == 1
    # a DIFFERENT spec is a fresh scenario: counters and log reset
    _plan.install_from_json(json.dumps(
        {"seed": 6, "rules": [{"site": "worker.exec", "kind": "error", "nth": 1}]}
    ))
    assert _plan.injection_log() == [] and _plan.active().rules[0].hits == 0


def test_fault_error_carries_site_and_hit():
    _plan.install(_schedule([{"site": "worker.exec", "kind": "error"}]))
    f = _plan.maybe_inject("worker.exec")
    err = f.error("task foo")
    assert isinstance(err, ChaosError)
    assert "worker.exec#1" in str(err) and "task foo" in str(err)


def test_metrics_series_counts_by_site_and_kind():
    _plan.install(_schedule([{"site": "s.*", "kind": "drop"}]))
    for _ in range(3):
        _plan.maybe_inject("s.a")
    _plan.maybe_inject("s.b")
    series = {(r["tags"]["site"], r["tags"]["kind"]): r["value"]
              for r in _plan.metrics_series() if r["name"] == "chaos.injected_total"}
    assert series == {("s.a", "drop"): 3.0, ("s.b", "drop"): 1.0}


def test_schedule_spec_roundtrip():
    spec = {"seed": 9, "rules": [
        {"site": "node.chunk.serve", "kind": "evict", "nth": 2,
         "ctx": {"oid": "ab"}, "delay_s": 0.2},
        {"site": "rpc.frame.send", "kind": "drop", "every": 4, "p": 0.5, "max_faults": 3},
    ]}
    sched = FaultSchedule.from_spec(json.dumps(spec))
    again = FaultSchedule.from_spec(sched.to_json())
    assert again.to_spec() == sched.to_spec() == spec


# ---------------------------------------------------------------------------
# scenario runner (real clusters)
# ---------------------------------------------------------------------------

def test_worker_kill_scenario_smoke():
    """The tier-1 chaos smoke: one seeded worker-kill scenario, CPU-only —
    retried tasks complete, and every cluster invariant holds afterward."""
    report = run_scenario("worker_kill", seed=3, quick=True)
    assert report["ok"], report
    assert report["invariants"]["no_stuck_tasks"]["ok"]
    assert report["details"]["retried_attempts"] >= 1
    # Observability acceptance (ISSUE 15): the kill left a black box behind —
    # the dying worker dumped its flight ring, the daemon harvested it, and
    # the dump's autopsy attributes the in-flight task the kill interrupted.
    fd = report["details"]["flight_dump"]
    assert fd["trigger"] == "worker.death"
    assert fd["events"] >= 1
    assert fd["in_flight"], "post-mortem failed to attribute the killed task"


def test_day_in_the_life_scenario_smoke():
    """Tier-1 replay smoke: the quick-mode day_in_the_life run — a seeded
    trace replayed open-loop through a compiled chaos timeline, judged by
    the run ledger's own gates. The full-length run rides the `-m slow`
    scenario battery. Seed 0 is the canonical seed: the trace it produces
    must match the committed tests/data artifact byte for byte."""
    import hashlib
    import pathlib

    report = run_scenario("day_in_the_life", seed=0, quick=True)
    assert report["ok"], report
    d = report["details"]
    committed = (pathlib.Path(__file__).parent / "data"
                 / "day_in_the_life_seed0.trace.jsonl").read_bytes()
    assert d["trace_sha256"] == hashlib.sha256(committed).hexdigest()
    assert d["gate"]["ok"], d["gate"]
    # the mid-run weight publication landed and both replicas swapped to it
    assert any(e["action"] == "publish_weights" and e["ok"]
               for e in d["timeline"])
    assert report["injections"], "timeline compiled no driver-side faults"


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_overload_storm_scenario_smoke():
    """The QoS acceptance scenario: ~3x overload with chaos-injected replica
    slowness — interactive goodput holds (p99 bounded), every shed/expiry is
    visible on /metrics with exact accounting, and no deadline-expired
    request ever reaches user code."""
    report = run_scenario("overload_storm", seed=5, quick=True)
    assert report["ok"], report
    assert report["details"]["shed"] >= 1
    assert report["details"]["invoked"] > 0
    assert report["invariants"]["faults_visible_in_metrics"]["ok"]


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_autoscale_flap_scenario_smoke():
    """The scale-plane acceptance scenario: chaos-delayed replica startup
    (site scale.replica.start) under sustained load — the policy upscales,
    the replica set grows, and the applied decision sequence contains no
    direction flip inside the cooldown window."""
    report = run_scenario("autoscale_flap", seed=11, quick=True)
    assert report["ok"], report
    assert report["details"]["replicas"] >= 2
    assert any(d["action"] == "upscale"
               for d in report["details"]["applied_decisions"])


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_ring_link_loss_scenario_smoke():
    """The collective-plane acceptance scenario: ring frames dropped and
    corrupted in flight — every rank fails with a typed CollectiveError
    inside the step deadline (never a hang), the same gang completes a
    clean round afterward, and the coordinator's payload-byte counter
    stays at zero throughout."""
    report = run_scenario("ring_link_loss", seed=9, quick=True)
    assert report["ok"], report
    rounds = report["details"]["rounds"]
    assert [r["round"] for r in rounds] == ["drop", "corrupt", "clean"]
    assert all(r["elapsed_s"] < 25 for r in rounds)
    assert report["details"]["coordinator_stats"] == {
        "payload_in": 0, "payload_out": 0}
    assert report["invariants"]["faults_visible_in_metrics"]["ok"]


def test_same_seed_replays_identical_injection_sequence():
    """The replay contract, asserted on two REAL runs: identical seed +
    schedule + workload => byte-identical normalized injection logs."""
    r1 = run_scenario("pull_source_death", seed=1234, quick=True)
    assert r1["ok"], r1
    r2 = run_scenario("pull_source_death", seed=1234, quick=True)
    assert r2["ok"], r2
    assert r1["injections"], "scenario injected nothing — vacuous replay"
    assert r1["injections"] == r2["injections"]


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_battery(name):
    """The full five-scenario soak (worker kill, pull-source death,
    controller restart under live submissions, MAC-corrupt storm,
    TPU-preemption drain) — all invariants green."""
    report = run_scenario(name, seed=17)
    assert report["ok"], report


@pytest.mark.slow
def test_multi_fault_soak():
    """Several fault families armed at once over a mixed workload — the
    long-haul shape of the nightly chaos suites."""
    import ray_tpu as rt
    from ray_tpu.chaos import invariants as _inv
    from ray_tpu.core import api
    from ray_tpu.core.api import Cluster, init
    from ray_tpu.core.config import Config

    cfg = Config().apply_env()
    cfg.metrics_report_interval_s = 0.5
    cfg.chaos_spec = json.dumps({"seed": 99, "rules": [
        {"site": "worker.exec", "kind": "error", "every": 7},
        {"site": "worker.task.dispatch", "kind": "error", "every": 11},
        {"site": "controller.lease.grant", "kind": "delay", "every": 5, "delay_s": 0.02},
        {"site": "rpc.recv.dispatch", "kind": "delay", "every": 40, "delay_s": 0.05},
    ]})
    _plan.install_from_json(cfg.chaos_spec)
    cluster = Cluster(initialize_head=False, config=cfg)
    cluster.add_node(num_cpus=2)
    init(address=cluster.address, config=cfg)
    try:
        @rt.remote(max_retries=8)
        def work(i):
            return i * i

        for _wave in range(4):
            refs = [work.remote(i) for i in range(10)]
            out = []
            for i, r in enumerate(refs):
                try:
                    out.append(rt.get(r, timeout=240))
                except Exception:
                    out.append(i * i)  # injected app-level errors are expected
            assert all(isinstance(v, int) for v in out)
        core = api._require_worker()
        inv = _inv.check_all(core, cluster, min_injections=3)
        assert inv["ok"], inv
    finally:
        api.shutdown()
        cluster.shutdown()
