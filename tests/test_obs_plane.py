"""Production observability plane (ISSUE 15): flight recorder, SLO burn
rates, critical-path autopsy, loop-lag probe.

Layers, cheapest first:
  * pure units (no cluster): ring bounds + counted evictions, dump file
    round trip, the closed dump-trigger catalog (AST cross-check, same
    pattern as the chaos site catalog), burn-rate window math on synthetic
    cumulative series, the multi-window alert FSM, autopsy hop arithmetic
    on a synthetic trace, the daemon harvest path, controller registries;
  * one live serve cluster: autopsy on a real proxy->replica request
    (hop-sum vs wall), trace reassembly from live recorders, SLO
    register/evaluate/unregister round trip through the serve API.
"""
from __future__ import annotations

import ast
import asyncio
import json
import os
import time
import types
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu import serve
from ray_tpu.obs import autopsy as obs_autopsy
from ray_tpu.obs import flight as obs_flight
from ray_tpu.obs import health as obs_health
from ray_tpu.obs import slo as obs_slo


# ---------------------------------------------------------------------------
# flight recorder: ring semantics + dump files (no cluster)
# ---------------------------------------------------------------------------

def test_ring_bounds_and_counted_evictions():
    rec = obs_flight.FlightRecorder(capacity=16)
    for i in range(40):
        rec.record("unit.tick", i=i)
    st = rec.stats()
    assert st["len"] == 16 and st["capacity"] == 16
    assert st["events_evicted"] == 24  # every displaced event is counted
    # The survivors are the NEWEST 16, each stamped with the shared clock.
    held = rec.snapshot()
    assert [e["i"] for e in held] == list(range(24, 40))
    assert all(e["ts"] > 0 for e in held)


def test_configure_shrink_counts_evictions():
    rec = obs_flight.FlightRecorder(capacity=64)
    for i in range(64):
        rec.record("unit.tick", i=i)
    rec.configure(capacity=16)
    st = rec.stats()
    assert st["len"] == 16 and st["events_evicted"] == 48


def test_dump_roundtrip_and_autopsy(tmp_path):
    rec = obs_flight.FlightRecorder(capacity=64)
    rec.configure(proc_id="unitproc", dump_dir=str(tmp_path))
    seen_hook = []
    rec.set_dump_hook(lambda path, trigger: seen_hook.append((path, trigger)))
    # One finished task and one task the process "died" holding.
    t = 100.0
    rec.absorb({"ts": t + 0.0, "kind": "task_submitted", "task_id": "t-done", "attempt": 0})
    rec.absorb({"ts": t + 0.1, "kind": "task_exec_start", "task_id": "t-done", "attempt": 0})
    rec.absorb({"ts": t + 0.2, "kind": "task_finished", "task_id": "t-done", "attempt": 0})
    rec.absorb({"ts": t + 0.3, "kind": "task_submitted", "task_id": "t-kill", "attempt": 1})
    rec.absorb({"ts": t + 0.4, "kind": "task_exec_start", "task_id": "t-kill", "attempt": 1})
    path = rec.dump("manual", reason="unit round trip")
    assert path and os.path.dirname(path) == str(tmp_path)
    assert seen_hook == [(path, "manual")]

    header, events = obs_flight.load_dump(path)
    assert header["magic"] == obs_flight.DUMP_MAGIC
    assert header["version"] == obs_flight.DUMP_VERSION
    assert header["proc_id"] == "unitproc"
    assert header["trigger"] == "manual" and header["reason"] == "unit round trip"
    assert header["events"] == 5 and len(events) == 5

    aut = obs_flight.dump_autopsy(events)
    assert aut["tasks"] == 2 and aut["terminal"] == 1
    running = [r for r in aut["in_flight"] if r.get("state") == "RUNNING"]
    assert [r["task_id"] for r in running] == ["t-kill"]
    assert aut["event_counts"]["task_exec_start"] == 2

    # Determinism form: ids/timestamps stripped, kinds kept in order.
    norm = obs_flight.normalize_dump(events)
    assert [k for k, _ in norm] == ["task_submitted", "task_exec_start",
                                    "task_finished", "task_submitted",
                                    "task_exec_start"]


def test_dump_rate_limit_and_unknown_trigger(tmp_path):
    rec = obs_flight.FlightRecorder(capacity=16)
    rec.configure(proc_id="ratelim", dump_dir=str(tmp_path))
    rec.record("unit.tick")
    first = rec.dump("tpu.preempt", reason="a")
    assert first is not None
    # Same trigger inside the rate-limit window: suppressed.
    assert rec.dump("tpu.preempt", reason="b") is None
    # "manual" is exempt — an operator asking twice means it twice.
    assert rec.dump("manual") is not None
    assert rec.dump("manual") is not None
    with pytest.raises(ValueError, match="unknown flight dump trigger"):
        rec.dump("made.up.trigger")
    # Disabled recorder records nothing and dumps nothing.
    rec.enabled = False
    rec.record("unit.after")
    assert rec.dump("manual") is None
    assert all(e.get("kind") != "unit.after" for e in rec.snapshot())


def test_truncated_dump_fails_to_parse(tmp_path):
    rec = obs_flight.FlightRecorder(capacity=16)
    rec.configure(proc_id="trunc", dump_dir=str(tmp_path))
    for i in range(4):
        rec.record("unit.tick", i=i)
    path = rec.dump("manual")
    lines = open(path).read().splitlines()
    open(path, "w").write("\n".join(lines[:-1]) + "\n")
    with pytest.raises(ValueError, match="truncated"):
        obs_flight.load_dump(path)


def test_dump_timeline_renders_through_shared_renderer(tmp_path):
    """Dumps render through the SAME chrome-trace path as export_timeline —
    one renderer for live clusters and black boxes."""
    rec = obs_flight.FlightRecorder(capacity=16)
    rec.configure(proc_id="tl", dump_dir=str(tmp_path))
    rec.absorb({"ts": 10.0, "kind": "span", "name": "unit.span", "dur": 0.5,
                "trace_id": "tr1", "span_id": "s1", "parent_id": "",
                "worker": "w1"})
    path = rec.dump("manual")
    out = str(tmp_path / "timeline.json")
    n = obs_flight.export_dump_timeline(path, out)
    assert n >= 1
    data = json.load(open(out))
    assert any(e.get("name") == "unit.span" for e in data["traceEvents"])


def test_dump_trigger_catalog():
    """The closed-catalog cross-check the flight.py docstring promises: every
    `*.dump("<literal>")` call site in the tree uses a registered trigger,
    and every registered trigger has at least one call site. Same two-way
    discipline as the chaos site catalog (test_graftlint.py)."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(obs_flight.__file__)))
    used: dict[str, set] = {}
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if not d.startswith(".") and d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            tree = ast.parse(open(path, encoding="utf-8").read(), filename=path)
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "dump"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                # Only flight-recorder receivers: the conventional aliases
                # (`flight.dump`, `_flight.dump`) plus the recorder's own
                # `self.dump`. pickle.dump(obj, f) never passes a str first.
                recv = node.func.value
                if not (isinstance(recv, ast.Name)
                        and recv.id in ("flight", "_flight", "self")):
                    continue
                used.setdefault(node.args[0].value, set()).add(
                    os.path.relpath(path, pkg_root))
    unknown = set(used) - set(obs_flight.TRIGGERS)
    assert not unknown, (
        f"dump call sites use unregistered triggers {sorted(unknown)} "
        f"(sites: { {t: sorted(used[t]) for t in unknown} }); "
        "register them in obs.flight.TRIGGERS")
    unused = set(obs_flight.TRIGGERS) - set(used)
    assert not unused, (
        f"TRIGGERS entries with no call site anywhere in the tree: "
        f"{sorted(unused)} — dead catalog entries are lies")


def test_deadline_storm_detector_dumps_once(tmp_path):
    rec = obs_flight.FlightRecorder(capacity=64)
    rec.configure(proc_id="storm", dump_dir=str(tmp_path),
                  storm_expiries=5, storm_window_s=60.0)
    for _ in range(5):
        rec.note_expiry()
    assert rec.dumps_written == 1  # 5th expiry inside the window tripped it
    # The burst continues: the per-trigger rate limit holds it to one dump.
    for _ in range(5):
        rec.note_expiry()
    assert rec.dumps_written == 1


# ---------------------------------------------------------------------------
# daemon harvest round trip (the dump-on-kill path, minus the cluster; the
# live end-to-end is the tier-1 chaos smoke test_chaos.py::worker_kill)
# ---------------------------------------------------------------------------

def _offline_controller():
    from ray_tpu.core.config import Config
    from ray_tpu.core.controller import Controller

    return Controller(Config())


def test_dump_on_kill_harvest_roundtrip(tmp_path):
    """A dying worker's last-gasp worker.death dump lands in
    <log_dir>/flight; the daemon harvest picks it up exactly once and the
    controller registry + dump autopsy attribute the in-flight task."""
    from ray_tpu.core.node import NodeDaemon

    worker_id = "deadbeefcafe0123"
    fdir = tmp_path / "flight"
    rec = obs_flight.FlightRecorder(capacity=64)
    rec.configure(proc_id=worker_id[:12], dump_dir=str(fdir))
    rec.absorb({"ts": 50.0, "kind": "task_submitted", "task_id": "t-kill", "attempt": 0})
    rec.absorb({"ts": 50.1, "kind": "task_exec_start", "task_id": "t-kill", "attempt": 0})
    path = rec.dump("worker.death", reason="chaos kill")
    assert path and os.path.dirname(path) == str(fdir)

    daemon = types.SimpleNamespace(log_dir=str(tmp_path), _flight_reported=set())
    harvested = NodeDaemon._harvest_flight_dumps(daemon, worker_id)
    assert harvested == [path]
    # Idempotent: the same file is never reported twice.
    assert NodeDaemon._harvest_flight_dumps(daemon, worker_id) == []

    ctl = _offline_controller()
    ctl.handle_report_flight_dump(None, {
        "proc": worker_id[:12], "path": harvested[0],
        "trigger": "worker.death", "reason": "worker process died"})
    out = ctl.handle_list_flight_dumps(None, {})
    assert out["dropped"] == 0
    assert out["dumps"][0]["path"] == path
    assert out["dumps"][0]["trigger"] == "worker.death"
    # The controller event log points at the same artifact (/api/events).
    assert any(e["kind"] == "flight_dump" and e.get("path") == path
               for e in ctl.events)

    header, events = obs_flight.load_dump(out["dumps"][0]["path"])
    assert header["trigger"] == "worker.death"
    aut = obs_flight.dump_autopsy(events)
    running = [r for r in aut["in_flight"] if r.get("state") == "RUNNING"]
    assert [r["task_id"] for r in running] == ["t-kill"]


def test_flight_dump_registry_bounded():
    ctl = _offline_controller()
    ctl.MAX_FLIGHT_DUMPS = 3
    for i in range(5):
        ctl.handle_report_flight_dump(None, {
            "proc": f"p{i}", "path": f"/tmp/d{i}.jsonl", "trigger": "manual"})
    assert len(ctl.flight_dumps) == 3
    assert ctl.flight_dumps_dropped == 2  # counted trim, newest kept
    out = ctl.handle_list_flight_dumps(None, {})
    assert out["dropped"] == 2
    assert [d["proc"] for d in out["dumps"]] == ["p4", "p3", "p2"]


def test_trace_eviction_names_victims():
    """Index overflow logs WHICH trace_ids were lost — a later 'trace not
    found' can then distinguish evicted-but-recoverable from never-existed."""
    ctl = _offline_controller()
    ctl.MAX_TRACES = 4
    for i in range(6):
        ctl._index_trace_event(f"tr{i}", {
            "ts": float(i), "kind": "span", "name": "serve.request",
            "trace_id": f"tr{i}", "span_id": f"s{i}", "parent_id": "",
            "worker": "w", "dur": 0.1})
    assert ctl.traces_evicted == 2
    evs = [e for e in ctl.events if e["kind"] == "trace_evicted"]
    assert [e["trace_id"] for e in evs] == ["tr0", "tr1"]
    assert all(e["name"] == "serve.request" for e in evs)
    assert set(ctl.traces) == {"tr2", "tr3", "tr4", "tr5"}


# ---------------------------------------------------------------------------
# SLO burn-rate math (synthetic cumulative series; no cluster)
# ---------------------------------------------------------------------------

def test_burn_rate_window_math():
    br = obs_slo.burn_rate
    assert br([], now=10.0, window_s=5.0, budget=0.01) is None
    # 10% bad over the window at a 1% budget: burn 10.
    samples = [(0.0, 0.0, 0.0), (10.0, 90.0, 100.0)]
    assert br(samples, now=10.0, window_s=10.0, budget=0.01) == pytest.approx(10.0)
    # No traffic inside the window (cumulative counters flat): None, not 0 —
    # an idle deployment is not violating its SLO.
    flat = [(0.0, 90.0, 100.0), (10.0, 90.0, 100.0)]
    assert br(flat, now=10.0, window_s=5.0, budget=0.01) is None
    # Baseline selection: the last sample AT/BEFORE the window start, so the
    # delta covers exactly the window. Bad burst before the window start
    # must not leak in.
    samples = [
        (0.0, 0.0, 0.0),
        (5.0, 50.0, 100.0),   # 50 bad, all before the window
        (10.0, 150.0, 200.0),  # window [5, 10]: 100 good / 100 total
    ]
    assert br(samples, now=10.0, window_s=5.0, budget=0.01) == pytest.approx(0.0)
    # ...and with bad traffic only inside the window: full attribution.
    samples = [(0.0, 0.0, 0.0), (5.0, 100.0, 100.0), (10.0, 150.0, 200.0)]
    assert br(samples, now=10.0, window_s=5.0, budget=0.1) == pytest.approx(5.0)


def test_multi_window_alert_fsm():
    """SRE-workbook shape: a fresh burst trips the fast window first
    (BURNING), sustained burn trips both (ALERT), recovery returns to OK.
    1 Hz samples, availability budget 5%, threshold 5, windows 4s/10s."""
    o = obs_slo.Objective(name="fsm", metric="availability", budget=0.05,
                          fast_window_s=4.0, slow_window_s=10.0,
                          burn_threshold=5.0)
    tr = obs_slo.SloTracker(o)
    good = total = 0.0
    states = {}
    for t in range(0, 22):
        if t <= 6:
            good += 10.0
            total += 10.0     # healthy: 10 good/s
        elif t <= 12:
            good += 5.0
            total += 10.0     # outage: 50% bad => burn 10 at 5% budget
        else:
            good += 10.0
            total += 10.0     # recovered
        tr.observe(float(t), good, total)
        states[t] = tr.evaluate(float(t))["state"]
    # t=8: fast window [4,8] is half-bad (burn 10 >= 5) but the slow window
    # still averages mostly-healthy traffic => BURNING, not yet ALERT.
    assert states[8] == obs_slo.BURNING
    # t=12: both windows over threshold => ALERT, counted once.
    assert states[12] == obs_slo.ALERT
    assert tr.alerts_fired == 1
    # Recovery: the fast window goes clean well before the slow one.
    assert states[21] == obs_slo.OK
    # Re-judging a steady state does not refire the alert.
    assert tr.alerts_fired == 1


def test_objective_validation_and_budget_fraction():
    with pytest.raises(ValueError, match="metric"):
        obs_slo.Objective(name="x", metric="throughput")
    with pytest.raises(ValueError, match="needs a name"):
        obs_slo.Objective(name="")
    with pytest.raises(ValueError, match="fast window"):
        obs_slo.Objective(name="x", fast_window_s=300.0, slow_window_s=60.0)
    # latency budget derives from the compliance quantile; availability
    # defaults to 0.1% unless given explicitly.
    assert obs_slo.Objective(name="l", quantile=0.99).budget_fraction == pytest.approx(0.01)
    assert obs_slo.Objective(name="a", metric="availability").budget_fraction == pytest.approx(0.001)
    assert obs_slo.Objective(name="b", metric="availability",
                             budget=0.05).budget_fraction == pytest.approx(0.05)


def _hist(name, tags, buckets, counts, n):
    return {"name": name, "kind": "histogram", "tags": tags,
            "buckets": buckets, "counts": counts, "n": n,
            "value": 0.0, "ts": 0.0}


def _ctr(name, tags, value):
    return {"name": name, "kind": "counter", "tags": tags,
            "value": value, "ts": 0.0}


def test_slo_engine_extract_and_gauges():
    eng = obs_slo.SloEngine()
    eng.register({"name": "lat", "metric": "latency", "target": 0.1,
                  "quantile": 0.9, "deployment": "D",
                  "fast_window_s": 5.0, "slow_window_s": 30.0,
                  "burn_threshold": 2.0})
    eng.register({"name": "avail", "metric": "availability", "budget": 0.1,
                  "fast_window_s": 5.0, "slow_window_s": 30.0,
                  "burn_threshold": 2.0})
    buckets = [0.01, 0.1, 1.0]

    def series(n_fast, n_slow, shed):
        return [
            # In scope for "lat": deployment D; 0.1s boundary counts as good.
            _hist("serve.request.latency_s", {"app": "a", "deployment": "D"},
                  buckets, [n_fast // 2, n_fast - n_fast // 2, n_slow], n_fast + n_slow),
            # Out of scope for "lat" (other deployment), still availability-good.
            _hist("serve.request.latency_s", {"app": "a", "deployment": "E"},
                  buckets, [5, 0, 0], 5),
            _ctr("serve.request.shed_total", {"reason": "q", "class": "batch"}, shed),
        ]

    t0 = 100.0
    assert eng.ingest(t0, series(0, 0, 0)) == []  # no traffic: no changes
    # 20 requests on D, every one over the 0.1s target; 10% budget => the
    # latency objective burns 10x; availability sees 25 good vs 8 shed.
    changes = eng.ingest(t0 + 1.0, series(0, 20, 8))
    changed_names = {c["objective"]["name"] for c in changes}
    assert "lat" in changed_names and "avail" in changed_names
    by_name = {r["objective"]["name"]: r for r in eng.status()}
    assert by_name["lat"]["state"] == obs_slo.ALERT
    assert by_name["lat"]["burn_fast"] == pytest.approx(10.0)
    assert by_name["avail"]["state"] == obs_slo.ALERT
    # window delta vs the baseline sample: 20 new good, 8 new shed => bad
    # fraction 8/28 at a 10% budget
    assert by_name["avail"]["burn_fast"] == pytest.approx((8 / 28) / 0.1)

    gauges = eng.gauges(t0 + 1.0)
    names = {(g["name"], g["tags"].get("objective"), g["tags"].get("window"))
             for g in gauges}
    assert ("slo.burn_rate", "lat", "fast") in names
    assert ("slo.state", "lat", None) in names
    state_vals = {g["tags"]["objective"]: g["value"] for g in gauges
                  if g["name"] == "slo.state"}
    assert state_vals == {"lat": 2.0, "avail": 2.0}

    summ = eng.summary()
    assert summ["total"] == 2 and set(summ["alert"]) == {"lat", "avail"}
    assert eng.unregister("lat") and not eng.unregister("lat")
    assert [r["objective"]["name"] for r in eng.status()] == ["avail"]


# ---------------------------------------------------------------------------
# autopsy hop arithmetic (synthetic trace; no cluster)
# ---------------------------------------------------------------------------

def _synthetic_trace():
    t0 = 100.0
    return [
        {"ts": t0, "kind": "span", "name": "serve.request", "dur": 1.0,
         "trace_id": "tr", "span_id": "root", "parent_id": "", "worker": "proxy"},
        # handle began waiting at t0+0.10, admitted at t0+0.25 (waited 0.15)
        {"ts": t0 + 0.25, "kind": "event", "name": "qos.admitted",
         "attrs": {"waited_s": 0.15}, "trace_id": "tr", "worker": "proxy"},
        {"ts": t0 + 0.30, "kind": "task_submitted", "task_id": "t1",
         "trace_id": "tr", "worker": "proxy"},
        {"ts": t0 + 0.35, "kind": "task_dispatched", "task_id": "t1",
         "trace_id": "tr", "worker": "proxy"},
        {"ts": t0 + 0.40, "kind": "task_exec_start", "task_id": "t1",
         "trace_id": "tr", "span_id": "exec", "parent_id": "root",
         "worker": "replica"},
        {"ts": t0 + 0.40, "kind": "span", "name": "serve.replica.Pinger",
         "dur": 0.5, "trace_id": "tr", "span_id": "rep", "parent_id": "root",
         "worker": "replica"},
    ]


def test_autopsy_synthetic_hops_sum_to_wall():
    a = obs_autopsy.autopsy(_synthetic_trace())
    assert a["root"] == "serve.request" and a["deployment"] == "Pinger"
    assert a["total_s"] == pytest.approx(1.0)
    hops = {h["hop"]: h["dur_s"] for h in a["hops"]}
    assert hops == {
        "proxy": pytest.approx(0.10), "admission": pytest.approx(0.15),
        "dispatch": pytest.approx(0.05), "wire": pytest.approx(0.05),
        "exec": pytest.approx(0.50), "drain": pytest.approx(0.10),
    }
    assert set(hops) == set(obs_autopsy.HOPS)
    assert a["attributed_s"] == pytest.approx(0.95)
    assert a["unattributed_s"] == pytest.approx(0.05)
    # hop-sum + residue == wall, exactly: the decomposition never invents time.
    assert a["attributed_s"] + a["unattributed_s"] == pytest.approx(a["total_s"])
    assert all(a["anchors"].values())


def test_autopsy_tolerates_partial_traces():
    events = [e for e in _synthetic_trace()
              if e.get("kind") not in ("task_submitted", "task_dispatched")]
    a = obs_autopsy.autopsy(events)
    hop_names = [h["hop"] for h in a["hops"]]
    # Missing anchors drop their hops (no guessing); the rest survive.
    assert "dispatch" not in hop_names and "wire" not in hop_names
    assert {"proxy", "admission", "exec", "drain"} <= set(hop_names)
    assert not a["anchors"]["submitted"] and a["anchors"]["replica_span"]
    assert obs_autopsy.autopsy([]) == {"error": "no spans in trace",
                                       "hops": [], "total_s": 0.0}


def test_autopsy_aggregate_shares():
    auts = [obs_autopsy.autopsy(_synthetic_trace()) for _ in range(3)]
    agg = obs_autopsy.aggregate(auts)
    assert set(agg) == {"Pinger"}
    p = agg["Pinger"]
    assert p["requests"] == 3 and p["total_s"] == pytest.approx(3.0)
    assert p["hops"]["exec"]["total_s"] == pytest.approx(1.5)
    assert p["hops"]["exec"]["share"] == pytest.approx(0.5)
    assert p["hops"]["exec"]["max_s"] == pytest.approx(0.5)
    assert p["unattributed_s"] == pytest.approx(0.15)


# ---------------------------------------------------------------------------
# loop-lag probe: injected stall -> spike event with thread dump
# ---------------------------------------------------------------------------

def test_loop_lag_probe_fires_on_stall():
    probe = obs_health.LoopLagProbe("obs-test-loop", interval_s=0.05,
                                    spike_s=0.2)
    loop = asyncio.new_event_loop()
    try:
        # A sync callback that blocks the loop: every probe sleep in flight
        # overshoots by the stall length.
        loop.call_later(0.1, lambda: time.sleep(0.5))

        async def run_probe():
            task = asyncio.ensure_future(probe.run())
            await asyncio.sleep(0.9)
            task.cancel()

        loop.run_until_complete(run_probe())
    finally:
        loop.close()
    assert probe.spikes >= 1
    spikes = [e for e in obs_flight.recorder().snapshot()
              if e.get("kind") == "loop.lag_spike"
              and e.get("loop") == "obs-test-loop"]
    assert spikes, "no lag-spike event reached the flight recorder"
    assert spikes[-1]["lag_s"] >= 0.2
    assert spikes[-1]["threads"] and all("stack" in t for t in spikes[-1]["threads"])
    # The lag histogram reports through the standard metrics pipeline.
    from ray_tpu.util import metrics as _metrics

    recs = [r for r in _metrics.snapshot()
            if r["name"] == "runtime.loop.lag_s"
            and r["tags"].get("loop") == "obs-test-loop"]
    assert recs and recs[0]["n"] >= 1


# ---------------------------------------------------------------------------
# live cluster: autopsy on a real request, trace reassembly, SLO round trip
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def obs_serve_cluster():
    rt.init(num_cpus=16)
    serve.start(proxy=True)

    @serve.deployment
    class Pinger:
        def __call__(self, request):
            time.sleep(0.05)
            return {"pong": True}

    serve.run(Pinger.bind(), name="obs_app", route_prefix="/obs")
    yield serve.http_port()
    serve.shutdown()
    rt.shutdown()


def _get(port, traced=False):
    headers = {"x-trace": "1"} if traced else {}
    req = urllib.request.Request(f"http://127.0.0.1:{port}/obs", headers=headers)
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert resp.status == 200
        return json.loads(resp.read())


def _controller_call(method, payload):
    from ray_tpu.core import api

    core = api._require_worker()
    core._run(core._flush_task_events())
    return core._run(core.controller.call(method, payload))


def test_autopsy_on_real_request_and_reassembly(obs_serve_cluster):
    port = obs_serve_cluster
    assert _get(port, traced=True) == {"pong": True}

    # Find the request's trace by its root span name.
    deadline = time.time() + 45
    trace_id = None
    while time.time() < deadline and trace_id is None:
        traces = _controller_call("list_traces", {"q": "serve.request"})
        if traces:
            trace_id = traces[0]["trace_id"]
            break
        time.sleep(0.5)
    assert trace_id, "no serve.request trace was indexed"

    # All the autopsy anchors flush on their own reporter ticks.
    def anchored(evs):
        kinds = {e.get("kind") for e in evs}
        return ("task_exec_start" in kinds
                and any(str(e.get("name", "")).startswith("serve.replica.")
                        for e in evs)
                and any(e.get("name") == "qos.admitted" for e in evs))

    deadline = time.time() + 90
    while time.time() < deadline:
        events = _controller_call("get_trace", {"trace_id": trace_id})
        if anchored(events):
            break
        time.sleep(0.5)
    assert anchored(events), f"anchors never landed: {sorted({e.get('kind') for e in events})}"

    from ray_tpu import obs

    a = obs.trace_autopsy(trace_id)
    assert not a.get("error"), a
    assert a["deployment"] == "Pinger"
    hops = {h["hop"]: h["dur_s"] for h in a["hops"]}
    assert "exec" in hops and hops["exec"] >= 0.04  # the handler's sleep
    assert set(hops) <= set(obs_autopsy.HOPS)
    assert a["total_s"] >= hops["exec"]
    # Hop-sum ~= wall: attribution never exceeds the request's wall time by
    # more than clock-skew noise, and the residue closes the books.
    assert a["attributed_s"] <= a["total_s"] + 0.05
    assert a["attributed_s"] + a["unattributed_s"] == pytest.approx(a["total_s"], abs=0.06)
    assert a["anchors"]["replica_span"] and a["anchors"]["exec_start"]

    # Per-deployment rollup sees the same request.
    summary = obs.autopsy_summary()
    assert "Pinger" in summary
    assert summary["Pinger"]["requests"] >= 1
    assert summary["Pinger"]["hops"]["exec"]["share"] > 0

    # Full-trace reassembly from live flight recorders: at least one live
    # ring still holds the story, merged with the surviving index slice.
    res = obs.collect_flight_trace(trace_id)
    assert res["indexed"] and not res["evicted"]
    assert res["sources"] >= 1, res
    assert any(e.get("name") == "serve.request" and e.get("kind") == "span"
               for e in res["events"])
    assert res["events"] == sorted(res["events"], key=lambda e: e.get("ts", 0.0))


def test_slo_register_roundtrip_on_live_cluster(obs_serve_cluster):
    port = obs_serve_cluster
    spec = {"name": "obs-lat", "metric": "latency", "target": 5.0,
            "quantile": 0.5, "deployment": "Pinger",
            "fast_window_s": 5.0, "slow_window_s": 30.0,
            "burn_threshold": 10.0}
    obj = serve.register_slo(spec)
    assert obj["name"] == "obs-lat" and obj["deployment"] == "Pinger"
    with pytest.raises(ValueError, match="metric"):
        serve.register_slo({"name": "bad", "metric": "nope"})
    try:
        # Let the evaluator take a baseline sample, then add traffic so the
        # windows see a cumulative delta.
        time.sleep(1.5)
        for _ in range(5):
            assert _get(port) == {"pong": True}
        deadline = time.time() + 30
        row = None
        while time.time() < deadline:
            rows = serve.slo_status()
            row = next((r for r in rows if r["objective"]["name"] == "obs-lat"), None)
            if row and row["burn_fast"] is not None:
                break
            time.sleep(0.3)
        assert row, "objective vanished from slo_status"
        assert row["burn_fast"] is not None, \
            "evaluator never saw the deployment's traffic (scope extraction broke)"
        # 50ms handlers against a 5s target: zero budget burn, state ok.
        assert row["burn_fast"] == pytest.approx(0.0)
        assert row["state"] == obs_slo.OK and row["alerts_fired"] == 0

        # The engine's gauges ride the standard merged metrics pipeline.
        series = _controller_call("get_metrics", {})
        states = [r for r in series if r["name"] == "slo.state"
                  and r["tags"].get("objective") == "obs-lat"]
        assert states and states[0]["value"] == 0.0
        assert states[0]["tags"].get("reporter") == "controller"

        summ = _controller_call("slo_summary", {})
        assert summ["total"] >= 1 and "obs-lat" not in summ["alert"]
        evs = _controller_call("get_events", {"limit": 2000})
        assert any(e.get("kind") == "slo_registered"
                   and e.get("objective") == "obs-lat" for e in evs)
    finally:
        assert serve.unregister_slo("obs-lat") is True
    assert all(r["objective"]["name"] != "obs-lat" for r in serve.slo_status())
