"""Off-policy RL: replay buffers (uniform + prioritized sum-tree), the
buffer actor's backpressure, DQN learning CartPole through the buffer, and
the sampling/learning overlap (reference analogues:
rllib/utils/replay_buffers tests + per-algorithm CartPole smoke learning)."""
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.rl import DQN, DQNConfig, PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rl.replay_buffer import SumTree


# ---------------------------------------------------------------------------
# data structures (no cluster needed)
# ---------------------------------------------------------------------------

def test_sum_tree_matches_naive_sampling():
    rng = np.random.default_rng(0)
    n = 37
    tree = SumTree(n)
    pri = rng.uniform(0.1, 5.0, n)
    tree.set(np.arange(n), pri)
    assert tree.total == pytest.approx(pri.sum())
    # Prefix-sum inversion: sampled leaf must be the one whose cumulative
    # range contains s.
    cum = np.cumsum(pri)
    for s in rng.uniform(0, pri.sum(), 200):
        leaf = tree.sample(np.array([s]))[0]
        expected = int(np.searchsorted(cum, s))
        assert leaf == min(expected, n - 1)
    # Updates propagate.
    tree.set(np.array([3]), np.array([100.0]))
    assert tree.total == pytest.approx(pri.sum() - pri[3] + 100.0)


def _mk_batch(n, rng, obs_dim=4):
    return {
        "obs": rng.standard_normal((n, obs_dim)).astype(np.float32),
        "actions": rng.integers(0, 2, n),
        "rewards": rng.standard_normal(n).astype(np.float32),
        "next_obs": rng.standard_normal((n, obs_dim)).astype(np.float32),
        "terms": (rng.random(n) < 0.1).astype(np.float32),
    }


def test_concurrent_first_push_cannot_split_allocation():
    """Regression (round-4 SAC flake): two collectors' FIRST add_batch calls
    racing on an empty buffer must not split the lazy store allocation —
    thread B used to see a partially-built store (truthy after 'obs'), skip
    allocation, and die with KeyError: 'actions'. Mutation is now atomic."""
    import threading

    errors = []
    for trial in range(50):
        buf = ReplayBuffer(capacity=256, seed=trial)
        barrier = threading.Barrier(3)

        def push():
            try:
                barrier.wait()
                for _ in range(4):
                    buf.add_batch(_mk_batch(16, np.random.default_rng(trial)))
            except Exception as e:  # noqa: BLE001 — collecting for assert
                errors.append(e)

        def drain():
            try:
                barrier.wait()
                for _ in range(8):
                    s = buf.sample(8)
                    if s is not None:
                        assert set(s) >= {"obs", "actions", "rewards",
                                          "next_obs", "terms"}
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        ts = [threading.Thread(target=push), threading.Thread(target=push),
              threading.Thread(target=drain)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert not errors, errors
    s = buf.sample(32)
    assert s is not None and s["actions"].shape == (32,)


def test_uniform_buffer_ring_semantics():
    rng = np.random.default_rng(1)
    buf = ReplayBuffer(capacity=100, seed=1)
    assert buf.sample(4) is None
    buf.add_batch(_mk_batch(60, rng))
    assert len(buf) == 60
    buf.add_batch(_mk_batch(60, rng))
    assert len(buf) == 100  # wrapped
    s = buf.sample(32)
    assert s["obs"].shape == (32, 4)
    assert np.all(s["weights"] == 1.0)


def test_prioritized_buffer_prefers_high_priority():
    rng = np.random.default_rng(2)
    buf = PrioritizedReplayBuffer(capacity=128, alpha=1.0, beta=1.0, seed=2)
    buf.add_batch(_mk_batch(128, rng))
    # Demote everything except index 7.
    pri = np.full(128, 1e-3)
    pri[7] = 10.0
    buf.update_priorities(np.arange(128), pri)
    counts = np.zeros(128)
    for _ in range(50):
        s = buf.sample(32)
        for i in s["indices"]:
            counts[i] += 1
    assert counts[7] > 0.8 * counts.sum(), "high-priority transition not dominant"
    # Importance weights: the dominant sample gets the SMALLEST weight.
    s = buf.sample(64)
    w7 = s["weights"][s["indices"] == 7]
    assert len(w7) and np.all(w7 <= s["weights"].max())
    assert s["weights"].max() == pytest.approx(1.0)


def test_prioritized_priority_update_shifts_distribution():
    rng = np.random.default_rng(3)
    buf = PrioritizedReplayBuffer(capacity=64, alpha=1.0, beta=0.4, seed=3)
    buf.add_batch(_mk_batch(64, rng))
    pri = np.full(64, 1e-3)
    pri[5] = 50.0
    buf.update_priorities(np.arange(64), pri)
    assert 5 in buf.sample(16)["indices"]
    # Demote 5, promote 9: sampling follows.
    pri[5] = 1e-3
    pri[9] = 50.0
    buf.update_priorities(np.arange(64), pri)
    idx = np.concatenate([buf.sample(16)["indices"] for _ in range(10)])
    assert (idx == 9).sum() > (idx == 5).sum()


# ---------------------------------------------------------------------------
# actor pipeline
# ---------------------------------------------------------------------------

def test_buffer_actor_backpressure(shared_ray):
    from ray_tpu.rl.replay_buffer import ReplayBufferActor

    buf = rt.remote(ReplayBufferActor).remote(
        10_000, prioritized=False, max_ahead_ratio=2.0, warmup=100,
    )
    rng = np.random.default_rng(0)
    # Push without any sampling: throttle must flip on after warmup.
    throttled = False
    for _ in range(10):
        reply = rt.get(buf.add_batch.remote(_mk_batch(64, rng)), timeout=60)
        throttled = throttled or reply["throttle"]
    assert throttled, "collector never throttled despite zero consumption"
    # Consume: throttle releases.
    for _ in range(12):
        rt.get(buf.sample.remote(64), timeout=60)
    reply = rt.get(buf.add_batch.remote(_mk_batch(64, rng)), timeout=60)
    assert not reply["throttle"]
    rt.kill(buf)


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_dqn_learns_cartpole_with_overlap(shared_ray):
    algo = DQNConfig(
        num_env_runners=2,
        num_envs_per_runner=8,
        collect_steps=32,
        batch_size=64,
        updates_per_iter=64,
        learning_starts=500,
        eps_decay_steps=4_000,
        target_update_every=100,
        prioritized=True,
        seed=7,
    ).build()
    best = -np.inf
    try:
        for _ in range(300):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if result["episode_return_mean"] >= 200.0:
                break
        assert best >= 200.0, f"DQN failed to learn CartPole via the buffer: best {best}"
        # Overlap evidence: buffer adds (collection) kept happening between
        # the first and last learner-side sample — i.e. sampling and learning
        # ran concurrently, not in alternating phases of a single thread.
        stats = rt.get(algo.buffer.stats.remote(), timeout=60)
        assert stats["sampled"] > 0 and stats["added"] > 1000
        adds = stats["add_times"]
        spread = adds[-1] - adds[0]
        gaps = np.diff(adds)
        # Collection ran continuously: no gap remotely close to the whole
        # training window (a serial design would show one giant learn-phase gap).
        assert len(adds) > 20
        assert gaps.max() < 0.5 * spread
    finally:
        algo.stop()


def test_sac_learns_pendulum(shared_ray):
    """SAC (continuous control: twin soft-Q, tanh-Gaussian policy, learned
    temperature) drives Pendulum from ~-1200 (random) to > -350 mean return
    through the same async buffer pipeline as DQN (reference analogue:
    rllib/algorithms/sac CartPole/Pendulum smokes)."""
    from ray_tpu.rl import SACConfig

    algo = SACConfig(seed=0).build()
    try:
        best = -1e9
        for i in range(200):
            r = algo.train()
            m = r["episode_return_mean"]
            if m != 0.0:  # 0.0 = no episodes finished yet
                best = max(best, m)
            if best > -350.0:
                break
        assert best > -350.0, f"SAC failed to learn Pendulum: best mean {best}"
    finally:
        algo.stop()
