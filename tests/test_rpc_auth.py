"""RPC auth: with a session token configured, unauthenticated peers are
rejected before any unpickling (reference analogue: src/ray/rpc/
authentication token auth). Own isolated cluster: auth is opt-in per session."""
import pickle
import socket

import pytest

import ray_tpu as rt


def test_token_cluster_end_to_end_and_rejects_raw_peers():
    from ray_tpu.core import rpc
    from ray_tpu.core.api import Cluster, init, shutdown
    from ray_tpu.core.config import Config

    cfg = Config().apply_env()
    cfg.auth_token = "s3cret-session-token"
    cluster = Cluster(initialize_head=False, config=cfg)
    cluster.add_node(num_cpus=4)
    init(address=cluster.address, config=cfg)
    try:
        assert rpc.get_auth_token(), "token should be installed"

        # Full stack (driver -> controller -> daemon -> spawned worker) works
        # with every frame tagged.
        @rt.remote
        def f(x):
            return x + 1

        assert rt.get(f.remote(41), timeout=60) == 42

        # A raw TCP client without the token is dropped — its frames never
        # reach pickle.loads.
        host, port = cluster.address.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=10)
        frame = pickle.dumps((0, 1, "get_cluster_state", {}), protocol=5)
        s.sendall(len(frame).to_bytes(8, "little") + frame)
        s.settimeout(5)
        data = s.recv(1024)
        assert data == b"", f"unauthenticated peer got a reply: {data!r}"
        s.close()

        # Cluster still healthy after the rejected peer.
        assert rt.get(f.remote(1), timeout=60) == 2
    finally:
        shutdown()
        cluster.shutdown()
        rpc.set_auth_token(None)  # don't leak the token into later sessions


def test_auto_session_token(tmp_path):
    """Clusters mint a session RPC token by default; same-host drivers pick
    it up from the session token file; raw unauthenticated peers are dropped
    (reference: rpc/authentication — auth required by default)."""
    import pickle
    import socket

    import ray_tpu as rt
    from ray_tpu.core import rpc
    from ray_tpu.core.api import Cluster, init, shutdown

    cluster = Cluster(initialize_head=False)  # no explicit token
    cluster.add_node(num_cpus=2)
    assert cluster.config.auth_token, "auto token not minted"
    init(address=cluster.address)
    try:
        assert rpc.get_auth_token(), "driver did not adopt the session token"

        @rt.remote
        def f(x):
            return x * 2

        assert rt.get(f.remote(21), timeout=60) == 42
        # Raw peer without the token: dropped before unpickling.
        host, port = cluster.address.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=10)
        frame = pickle.dumps((0, 1, "get_cluster_state", {}), protocol=5)
        s.sendall(len(frame).to_bytes(8, "little") + frame)
        s.settimeout(5)
        assert s.recv(1024) == b""
        s.close()
    finally:
        shutdown()
        cluster.shutdown()
        rpc.set_auth_token(None)
