"""RPC auth: with a session token configured, unauthenticated peers are
rejected before any unpickling (reference analogue: src/ray/rpc/
authentication token auth). Own isolated cluster: auth is opt-in per session."""
import pickle
import socket

import pytest

import ray_tpu as rt


TRIPPED = []


def _trip():
    """Sentinel reconstructor: executes iff a frame reaches pickle.loads."""
    TRIPPED.append(1)


class _Boom:
    def __reduce__(self):
        return (_trip, ())


def test_wire_version_mismatch_refused():
    """A frame stamped with a different wire-format generation is refused —
    connection dropped with no reply — and its bytes NEVER reach pickle
    (reference analogue: protobuf schema versioning; here a version byte
    guards the pickle frames against mixed-build clusters)."""
    import asyncio

    from ray_tpu.core import rpc

    async def go():
        class H:
            def handle_ping(self, conn, p):
                return "pong"

        server = rpc.RpcServer(H())
        await server.start()
        try:
            # Same-build peer round-trips fine.
            conn = await rpc.connect(server.address)
            assert await conn.call("ping", timeout=10) == "pong"
            await conn.close()

            # Mismatched version byte: refused before unpickling.
            reader, writer = await asyncio.open_connection(server.host, server.port)
            body = pickle.dumps((0, 1, "ping", _Boom()), protocol=5)
            frame = bytes([rpc.WIRE_VERSION + 1]) + body
            writer.write(len(frame).to_bytes(8, "little") + frame)
            await writer.drain()
            data = await reader.read(1024)
            assert data == b"", f"mismatched-version peer got a reply: {data!r}"
            writer.close()
            # A legacy pre-version frame (starts with the pickle PROTO opcode
            # 0x80, not a version byte) is refused the same way.
            reader, writer = await asyncio.open_connection(server.host, server.port)
            writer.write(len(body).to_bytes(8, "little") + body)
            await writer.drain()
            assert await reader.read(1024) == b""
            writer.close()
        finally:
            await server.close()

    asyncio.run(go())
    assert not TRIPPED, "booby-trapped frame was unpickled despite version mismatch"


def test_token_cluster_end_to_end_and_rejects_raw_peers():
    from ray_tpu.core import rpc
    from ray_tpu.core.api import Cluster, init, shutdown
    from ray_tpu.core.config import Config

    cfg = Config().apply_env()
    cfg.auth_token = "s3cret-session-token"
    cluster = Cluster(initialize_head=False, config=cfg)
    cluster.add_node(num_cpus=4)
    init(address=cluster.address, config=cfg)
    try:
        assert rpc.get_auth_token(), "token should be installed"

        # Full stack (driver -> controller -> daemon -> spawned worker) works
        # with every frame tagged.
        @rt.remote
        def f(x):
            return x + 1

        assert rt.get(f.remote(41), timeout=60) == 42

        # A raw TCP client without the token is dropped — its frames never
        # reach pickle.loads.
        host, port = cluster.address.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=10)
        frame = pickle.dumps((0, 1, "get_cluster_state", {}), protocol=5)
        s.sendall(len(frame).to_bytes(8, "little") + frame)
        s.settimeout(5)
        data = s.recv(1024)
        assert data == b"", f"unauthenticated peer got a reply: {data!r}"
        s.close()

        # Cluster still healthy after the rejected peer.
        assert rt.get(f.remote(1), timeout=60) == 2
    finally:
        shutdown()
        cluster.shutdown()
        rpc.set_auth_token(None)  # don't leak the token into later sessions


def test_auto_session_token(tmp_path):
    """Clusters mint a session RPC token by default; same-host drivers pick
    it up from the session token file; raw unauthenticated peers are dropped
    (reference: rpc/authentication — auth required by default)."""
    import pickle
    import socket

    import ray_tpu as rt
    from ray_tpu.core import rpc
    from ray_tpu.core.api import Cluster, init, shutdown

    cluster = Cluster(initialize_head=False)  # no explicit token
    cluster.add_node(num_cpus=2)
    assert cluster.config.auth_token, "auto token not minted"
    init(address=cluster.address)
    try:
        assert rpc.get_auth_token(), "driver did not adopt the session token"

        @rt.remote
        def f(x):
            return x * 2

        assert rt.get(f.remote(21), timeout=60) == 42
        # Raw peer without the token: dropped before unpickling.
        host, port = cluster.address.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=10)
        frame = pickle.dumps((0, 1, "get_cluster_state", {}), protocol=5)
        s.sendall(len(frame).to_bytes(8, "little") + frame)
        s.settimeout(5)
        assert s.recv(1024) == b""
        s.close()
    finally:
        shutdown()
        cluster.shutdown()
        rpc.set_auth_token(None)
