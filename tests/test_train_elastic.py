"""Elastic train: ScalingPolicy-driven gang resize (reference:
train/v2/_internal/execution/scaling_policy/ + controller.py:183
_execute_resize_decision). A fake cluster gains a node mid-run; the gang
grows to the new capacity, resumes from the latest checkpoint, and finishes
without losing progress or consuming the failure budget."""
import json
import os
import tempfile
import time

import pytest

import ray_tpu as rt
from ray_tpu import train
from ray_tpu.core.api import Cluster
from ray_tpu.train import (
    Checkpoint,
    DataParallelTrainer,
    ElasticScalingPolicy,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)


@pytest.fixture
def elastic_cluster():
    cluster = Cluster(initialize_head=False)
    cluster.add_node(num_cpus=1)  # head: room for exactly ONE train worker
    rt.init(address=cluster.address)
    yield cluster
    rt.shutdown()
    cluster.shutdown()


def _elastic_fn(config):
    ctx = train.get_context()
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with ckpt.as_directory() as d:
            start = json.load(open(os.path.join(d, "state.json")))["step"] + 1
    for i in range(start, config["steps"]):
        time.sleep(0.3)  # slow steps: give the resize a window
        if ctx.get_world_rank() == 0:
            d = tempfile.mkdtemp()
            json.dump({"step": i}, open(os.path.join(d, "state.json"), "w"))
            train.report(
                {"step": i, "world_size": ctx.get_world_size()},
                checkpoint=Checkpoint.from_directory(d),
            )
            marker = config.get("progress_marker")
            if marker and i >= 2:
                open(marker, "w").close()
        else:
            train.report({"step": i, "world_size": ctx.get_world_size()})


def test_gang_grows_when_cluster_gains_a_node(elastic_cluster):
    tmp = tempfile.mkdtemp()
    marker = os.path.join(tmp, "progress")
    trainer = DataParallelTrainer(
        _elastic_fn,
        train_loop_config={"steps": 12, "progress_marker": marker},
        scaling_config=ScalingConfig(num_workers=2, resources_per_worker={"CPU": 1}),
        run_config=RunConfig(
            name="elastic", storage_path=tmp,
            failure_config=FailureConfig(max_failures=0),  # resize must not consume this
        ),
        scaling_policy=ElasticScalingPolicy(
            ScalingConfig(num_workers=2, resources_per_worker={"CPU": 1}),
            min_workers=1, max_workers=2, resize_cooldown_s=0.5,
        ),
        controller_as_actor=False,  # in-driver controller: we add the node mid-run
    )

    import threading

    def add_node_later():
        # Deterministic trigger: wait until the 1-worker gang has really made
        # progress (rank 0 marks step >= 2), THEN grow the cluster.
        deadline = time.time() + 60
        while not os.path.exists(marker) and time.time() < deadline:
            time.sleep(0.1)
        elastic_cluster.add_node(num_cpus=1)

    t = threading.Thread(target=add_node_later, daemon=True)
    t.start()
    result = trainer.fit()
    t.join()
    assert result.error is None
    # Finished all steps; final checkpoint is the last step.
    with result.checkpoint.as_directory() as d:
        assert json.load(open(os.path.join(d, "state.json")))["step"] == 11
    sizes = [m["world_size"] for m in result.metrics_history]
    steps = [m["step"] for m in result.metrics_history]
    # Started at capacity (1 worker), grew to 2 after the node joined.
    assert sizes[0] == 1
    assert sizes[-1] == 2, sizes
    # No lost progress: every step 0..11 reported exactly once in order.
    assert steps == list(range(12)), steps


def test_fixed_policy_never_resizes(elastic_cluster):
    tmp = tempfile.mkdtemp()
    trainer = DataParallelTrainer(
        _elastic_fn,
        train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=1, resources_per_worker={"CPU": 1}),
        run_config=RunConfig(name="fixed", storage_path=tmp),
        controller_as_actor=False,
    )
    elastic_cluster.add_node(num_cpus=1)  # capacity appears; fixed policy ignores it
    result = trainer.fit()
    assert result.error is None
    assert all(m["world_size"] == 1 for m in result.metrics_history)


def _chaos_fn(config):
    """Dies once at step 4 (first incarnation only) while the cluster is
    simultaneously gaining a node — the resize/failure race."""
    ctx = train.get_context()
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        with ckpt.as_directory() as d:
            start = json.load(open(os.path.join(d, "state.json")))["step"] + 1
    for i in range(start, config["steps"]):
        time.sleep(0.25)
        die_marker = config["die_marker"]
        if ctx.get_world_rank() == 0 and i == 4 and not os.path.exists(die_marker):
            open(die_marker, "w").close()
            os._exit(1)  # hard kill mid-gang, no cleanup
        if ctx.get_world_rank() == 0:
            d = tempfile.mkdtemp()
            json.dump({"step": i}, open(os.path.join(d, "state.json"), "w"))
            train.report(
                {"step": i, "world_size": ctx.get_world_size()},
                checkpoint=Checkpoint.from_directory(d),
            )
            marker = config.get("progress_marker")
            if marker and i >= 2:
                open(marker, "w").close()
        else:
            train.report({"step": i, "world_size": ctx.get_world_size()})


def test_resize_racing_worker_failure(elastic_cluster):
    """Chaos: a node joins (upscale trigger) in the same window a worker
    hard-dies. The gang must restart from the checkpoint, the resize must
    still land, and no step may be lost (VERDICT r3 weak #8)."""
    tmp = tempfile.mkdtemp()
    marker = os.path.join(tmp, "progress")
    die_marker = os.path.join(tmp, "died_once")
    trainer = DataParallelTrainer(
        _chaos_fn,
        train_loop_config={"steps": 10, "progress_marker": marker,
                           "die_marker": die_marker},
        scaling_config=ScalingConfig(num_workers=2, resources_per_worker={"CPU": 1}),
        run_config=RunConfig(
            name="chaos", storage_path=tmp,
            failure_config=FailureConfig(max_failures=2),
        ),
        scaling_policy=ElasticScalingPolicy(
            ScalingConfig(num_workers=2, resources_per_worker={"CPU": 1}),
            min_workers=1, max_workers=2, resize_cooldown_s=0.5,
        ),
        controller_as_actor=False,
    )

    import threading

    def add_node_when_progressing():
        deadline = time.time() + 60
        while not os.path.exists(marker) and time.time() < deadline:
            time.sleep(0.1)
        # Node joins JUST before the step-4 death: the upscale decision and
        # the gang failure land in the same window.
        elastic_cluster.add_node(num_cpus=1)

    t = threading.Thread(target=add_node_when_progressing, daemon=True)
    t.start()
    result = trainer.fit()
    t.join()
    assert result.error is None, result.error
    assert os.path.exists(die_marker), "failure injection never fired"
    with result.checkpoint.as_directory() as d:
        assert json.load(open(os.path.join(d, "state.json")))["step"] == 9
    steps = [m["step"] for m in result.metrics_history]
    sizes = [m["world_size"] for m in result.metrics_history]
    # Every step reached the metrics stream (restart resumes from the last
    # checkpoint, so repeats are legal; holes are not).
    assert set(steps) >= set(range(10)), steps
    assert steps[-1] == 9
    # The resize survived the chaos: the run ends at the grown world size.
    assert sizes[-1] == 2, sizes
