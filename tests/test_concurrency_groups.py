"""Actor concurrency groups (reference: ConcurrencyGroupManager +
@ray.method(concurrency_group=...), core_worker/task_execution)."""
import time

import pytest

import ray_tpu as rt


@pytest.fixture(scope="module", autouse=True)
def _session():
    rt.init(num_cpus=4)
    yield
    rt.shutdown()


@rt.remote(concurrency_groups={"io": 2, "compute": 1})
class Grouped:
    def __init__(self):
        self.log = []

    @rt.method(concurrency_group="compute")
    def crunch(self, t):
        time.sleep(t)
        self.log.append("crunch")
        return "crunched"

    @rt.method(concurrency_group="io")
    def probe(self):
        return "alive"

    def default_lane(self):
        return "default"


def test_group_lane_not_blocked_by_default_lane():
    """A long call on the compute lane must not block the io lane: the probe
    returns while crunch is still sleeping."""
    a = Grouped.remote()
    rt.get(a.probe.remote(), timeout=60)  # actor constructed
    slow = a.crunch.remote(3.0)
    t0 = time.perf_counter()
    assert rt.get(a.probe.remote(), timeout=60) == "alive"
    probe_latency = time.perf_counter() - t0
    assert probe_latency < 2.0, f"io-lane probe stuck behind compute: {probe_latency:.2f}s"
    assert rt.get(slow, timeout=60) == "crunched"


def test_per_call_group_override():
    a = Grouped.remote()
    assert rt.get(a.default_lane.options(concurrency_group="io").remote(), timeout=60) == "default"


def test_unknown_group_is_an_error():
    a = Grouped.remote()
    with pytest.raises(Exception, match="unknown concurrency group"):
        rt.get(a.default_lane.options(concurrency_group="nope").remote(), timeout=60)


def test_group_parallelism_capped():
    """The io lane has 2 threads: three 0.8s sleeps take >=1.6s end-to-end,
    while two take ~0.8s wall (capped parallelism, not serialization)."""

    @rt.remote(concurrency_groups={"io": 2})
    class Sleeper:
        @rt.method(concurrency_group="io")
        def nap(self, t):
            time.sleep(t)
            return True

    s = Sleeper.remote()
    rt.get(s.nap.remote(0.01), timeout=60)
    t0 = time.perf_counter()
    assert all(rt.get([s.nap.remote(0.8) for _ in range(2)], timeout=60))
    two = time.perf_counter() - t0
    t0 = time.perf_counter()
    assert all(rt.get([s.nap.remote(0.8) for _ in range(3)], timeout=60))
    three = time.perf_counter() - t0
    assert two < 1.55, f"2 naps should overlap on a 2-thread lane: {two:.2f}s"
    assert three >= 1.5, f"3 naps on a 2-thread lane must take 2 rounds: {three:.2f}s"


def test_chained_actor_calls_do_not_deadlock():
    """a.m2.remote(a.m1.remote()) lands both calls in one pump drain; the
    dep on m1's result must not hold m1's send hostage (review regression)."""

    @rt.remote
    class Chain:
        def m1(self):
            return 5

        def m2(self, x):
            return x + 1

    a = Chain.remote()
    r1 = a.m1.remote()
    r2 = a.m2.remote(r1)
    assert rt.get(r2, timeout=30) == 6


def test_inherited_method_decorator_honored():
    class Base:
        @rt.method(num_returns=2)
        def pair(self):
            return 1, 2

    @rt.remote
    class Child(Base):
        pass

    c = Child.remote()
    r1, r2 = c.pair.remote()
    assert rt.get([r1, r2], timeout=60) == [1, 2]
