"""Hash-shuffle data operators: hash repartition, hash groupby, inner/left
join — map-side partition tasks + per-partition reduce over the object store
(reference: data/_internal/execution/operators/hash_shuffle.py, join.py) —
plus streaming_split locality hints and per-op in-flight budgets."""
import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import data as rd


@pytest.fixture(scope="module", autouse=True)
def _session():
    rt.init(num_cpus=8)
    yield
    rt.shutdown()


def test_hash_repartition_colocates_keys():
    ds = rd.from_items([{"k": i % 5, "v": i} for i in range(200)]).repartition(
        4, hash_key="k"
    )
    blocks = [b for b in ds.iter_blocks() if b.num_rows]
    assert len(blocks) == 4
    # Every key lives in exactly one block.
    seen = {}
    for bi, blk in enumerate(blocks):
        for k in set(blk.column("k").to_pylist()):
            assert k not in seen, f"key {k} split across blocks {seen[k]} and {bi}"
            seen[k] = bi
    assert sorted(seen) == [0, 1, 2, 3, 4]
    # No rows lost.
    assert sum(b.num_rows for b in blocks) == 200


def test_hash_groupby_agg_matches_naive():
    rows = [{"g": f"g{i % 7}", "x": float(i)} for i in range(211)]
    ds = rd.from_items(rows)
    got = {r["g"]: r["sum(x)"] for r in ds.groupby("g").sum("x").take_all()}
    want = {}
    for r in rows:
        want[r["g"]] = want.get(r["g"], 0.0) + r["x"]
    assert got == pytest.approx(want)
    counts = {r["g"]: r["count()"] for r in ds.groupby("g").count().take_all()}
    assert sum(counts.values()) == 211


def test_inner_join():
    users = rd.from_items([{"uid": i, "name": f"u{i}"} for i in range(30)])
    orders = rd.from_items(
        [{"uid": i % 40, "amount": float(i)} for i in range(100)]
    )
    joined = orders.join(users, on="uid").take_all()
    # Orders with uid >= 30 have no user: inner join drops them.
    expect_rows = sum(1 for i in range(100) if i % 40 < 30)
    assert len(joined) == expect_rows
    for r in joined:
        assert r["name"] == f"u{r['uid']}"


def test_left_join_keeps_unmatched():
    left = rd.from_items([{"k": i, "a": i} for i in range(10)])
    right = rd.from_items([{"k": i, "b": i * 10} for i in range(0, 10, 2)])
    out = left.join(right, on="k", how="left").take_all()
    assert len(out) == 10
    matched = [r for r in out if "b" in r and r.get("b") is not None]
    assert len(matched) == 5


def test_join_column_collision_suffix():
    left = rd.from_items([{"k": 1, "v": "L"}])
    right = rd.from_items([{"k": 1, "v": "R"}])
    (row,) = left.join(right, on="k").take_all()
    assert row["v"] == "L" and row["v_1"] == "R"


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_shuffle_beyond_memory_with_spill(tmp_path):
    """Groupby+join at > object-store scale: the 16MB store must spill to
    disk and the shuffle still completes exactly."""
    import os

    from ray_tpu.core.api import Cluster, init, shutdown
    from ray_tpu.core.config import Config

    rt.shutdown()
    cfg = Config().apply_env()
    cfg.object_store_memory = 16 * 1024 * 1024
    cfg.object_spill_dir = str(tmp_path / "spill")
    cluster = Cluster(initialize_head=False, config=cfg)
    cluster.add_node(num_cpus=4)
    init(address=cluster.address, config=cfg)
    try:
        n_rows, payload = 6_000, 8_000  # ~48MB of payload through a 16MB store
        ds = rd.from_items(
            [{"g": i % 13, "i": i} for i in range(n_rows)], parallelism=24
        ).map(lambda r: {**r, "pad": "x" * payload})
        agg = {r["g"]: r["count()"] for r in ds.groupby("g").count().take_all()}
        assert sum(agg.values()) == n_rows
        assert os.path.isdir(cfg.object_spill_dir) and os.listdir(cfg.object_spill_dir), (
            "spill dir untouched: the test did not exceed memory"
        )
    finally:
        shutdown()
        cluster.shutdown()
        rt.init(num_cpus=8)  # restore module fixture session


def test_streaming_split_locality_hints():
    """Blocks are dealt preferentially to the consumer on the block's node;
    wrong-length hints rejected."""
    from ray_tpu.core.api import Cluster, init, shutdown

    rt.shutdown()
    cluster = Cluster(initialize_head=False)
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    init(address=cluster.address)
    try:
        ds = rd.range(400).map_batches(lambda b: b)  # blocks land on both nodes
        with pytest.raises(ValueError, match="one entry per split"):
            ds.streaming_split(2, locality_hints=[n1.node_id])
        its = ds.streaming_split(2, locality_hints=[n1.node_id, n2.node_id])
        rows = []
        for it in its:
            for blk in it.iter_blocks():
                rows.extend(blk.column("id").to_pylist())
        assert sorted(rows) == list(range(400))
    finally:
        shutdown()
        cluster.shutdown()
        rt.init(num_cpus=8)


def test_per_op_budget_caps_inflight():
    """A budgets entry caps that stage's concurrency: with budget 1 the map
    stage never has 2 tasks in flight (observed via a shared marker dir)."""
    import os
    import tempfile
    import time

    from ray_tpu.data.executor import StreamingExecutor

    marker = tempfile.mkdtemp()

    def slow_mark(batch):
        me = os.path.join(marker, f"{time.monotonic_ns()}")
        open(me, "w").close()
        live = len(os.listdir(marker))
        time.sleep(0.15)
        os.unlink(me)
        batch["live"] = np.full(len(next(iter(batch.values()))), live)
        return batch

    ds = rd.range(8).map_batches(slow_mark)
    ex = StreamingExecutor(max_in_flight=8, budgets={"map_batches": 1})
    out = [rt.get(r) for r in ex.execute(ds._leaf)]
    max_live = max(max(b.column("live").to_pylist()) for b in out if b.num_rows)
    assert max_live == 1, f"budget 1 but {max_live} tasks overlapped"


def test_join_across_numeric_dtypes():
    """int64 keys join float64 keys: equal values agree on a partition
    (dtype-canonicalized hashing), so matches are not silently dropped."""
    left = rd.from_items([{"k": i, "a": i} for i in range(12)])          # int keys
    right = rd.from_items([{"k": float(i), "b": i * 2} for i in range(12)])  # 1.0, 2.0...
    out = left.join(right, on="k").take_all()
    assert len(out) == 12, f"cross-dtype join dropped rows: {len(out)}"
    assert all(r["b"] == r["a"] * 2 for r in out)


def test_distributed_sort_multiblock_global_order():
    """Sample-sort (reference: SortTaskSpec sample->boundaries->partition->
    merge): many input blocks, output streams in GLOBAL key order as
    multiple range partitions — no task ever saw the whole dataset."""
    rng = np.random.default_rng(7)
    vals = rng.permutation(200).tolist()
    ds = rd.from_items([{"v": int(v), "tag": f"t{v}"} for v in vals], parallelism=10)
    out_blocks = [rt.get(r) for r in ds.sort("v").iter_block_refs()]
    # Multiple range partitions, each a separate merge task's output.
    nonempty = [b for b in out_blocks if b.num_rows]
    assert len(nonempty) > 1, "sort collapsed to a single task"
    assert max(b.num_rows for b in nonempty) < 200, \
        "one sort task materialized the whole dataset"
    rows = [r for b in nonempty for r in b.to_pylist()]
    assert [r["v"] for r in rows] == sorted(vals)
    assert all(r["tag"] == f"t{r['v']}" for r in rows)  # rows stay intact


def test_distributed_sort_descending_and_strings():
    words = ["pear", "apple", "fig", "kiwi", "lime", "date", "plum", "mango"] * 5
    ds = rd.from_items([{"w": w, "i": i} for i, w in enumerate(words)], parallelism=8)
    got = [r["w"] for r in ds.sort("w", descending=True).take_all()]
    assert got == sorted(words, reverse=True)


def test_distributed_sort_skewed_keys():
    """Heavy key skew (duplicate boundaries) must not lose or duplicate
    rows."""
    vals = [1] * 50 + [2] * 3 + [99] * 20
    ds = rd.from_items([{"v": v} for v in vals], parallelism=8)
    got = [r["v"] for r in ds.sort("v").take_all()]
    assert got == sorted(vals)
