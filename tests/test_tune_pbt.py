"""PBT sweep over fake v4-16 TPU slices (own cluster: init/shutdown)."""
import os

import pytest

import ray_tpu as rt
from ray_tpu import tune
from ray_tpu.tune.search import grid_search


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_pbt_improves_population(tmp_path):
    """PBT on fake v4-16 TPU slices: bad lr trials clone good ones and the
    whole population converges (BASELINE.md Tune target)."""
    from ray_tpu.accel.tpu import TPU_POD_TYPE_LABEL, TPU_SLICE_NAME_LABEL, TPU_WORKER_ID_LABEL
    from ray_tpu.core.api import Cluster
    from ray_tpu.train import Checkpoint, RunConfig

    cluster = Cluster(initialize_head=False)
    tpu_nodes = [
        cluster.add_node(
            num_cpus=1,
            resources={"TPU": 4.0, f"TPU-v4-16-head": 1.0},
            labels={TPU_SLICE_NAME_LABEL: f"slice-{i}",
                    TPU_WORKER_ID_LABEL: "0",
                    TPU_POD_TYPE_LABEL: "v4-16"},
        )
        for i in range(4)
    ]
    rt.init(address=cluster.address)
    try:
        def trainable(config):
            import json
            import tempfile
            import time

            ckpt = tune.get_checkpoint()
            theta = 0.0
            if ckpt:
                with open(os.path.join(ckpt.path, "s.json")) as f:
                    theta = json.load(f)["theta"]
            for step in range(1, 17):
                time.sleep(0.25)  # pace steps so the controller sees
                                  # mid-run results (PBT acts on them)
                # Good lr -> fast approach to 10; lr near 0 -> crawl.
                theta = theta + config["lr"] * (10.0 - theta)
                d = tempfile.mkdtemp()
                with open(os.path.join(d, "s.json"), "w") as f:
                    json.dump({"theta": theta}, f)
                tune.report({"obj": theta, "training_iteration": step},
                            checkpoint=Checkpoint.from_directory(d))

        pbt = tune.PopulationBasedTraining(
            metric="obj", mode="max", perturbation_interval=4,
            hyperparam_mutations={"lr": tune.uniform(0.05, 0.9)},
            quantile_fraction=0.25, seed=0,
        )
        results = tune.Tuner(
            trainable,
            param_space={"lr": grid_search([0.001, 0.002, 0.5, 0.6])},
            tune_config=tune.TuneConfig(
                metric="obj", mode="max", scheduler=pbt,
                resources_per_trial={"TPU": 4.0},
            ),
            run_config=RunConfig(name="pbt", storage_path=str(tmp_path)),
        ).fit()
        assert not results.errors
        finals = sorted(r.metrics["obj"] for r in results)
        # Without PBT, lr=0.001 ends at ~0.16; with exploit/explore every
        # trial must end well above that.
        assert finals[0] > 2.0, finals
        assert results.get_best_result().metrics["obj"] > 9.0
    finally:
        rt.shutdown()
        cluster.shutdown()


