"""Data layer: Dataset API, execution, splits, train ingest (8-dev CPU mesh)."""
import os

import numpy as np
import pytest

import ray_tpu as rt
import ray_tpu.data as rd


def test_range_count_take(shared_ray):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_from_items_map(shared_ray):
    ds = rd.from_items([{"x": i} for i in range(10)], parallelism=3)
    out = ds.map(lambda r: {"y": r["x"] * 2}).take_all()
    assert sorted(r["y"] for r in out) == [i * 2 for i in range(10)]


def test_map_batches_numpy(shared_ray):
    ds = rd.range(16, parallelism=2)

    def double(batch):
        assert isinstance(batch["id"], np.ndarray)
        assert batch["id"].dtype == np.int64
        return {"id": batch["id"], "sq": batch["id"].astype(np.float32) ** 2}

    out = ds.map_batches(double).take_all()
    assert {r["id"] for r in out} == set(range(16))
    assert all(abs(r["sq"] - r["id"] ** 2) < 1e-6 for r in out)


def test_filter_flat_map(shared_ray):
    ds = rd.range(10, parallelism=2).filter(lambda r: r["id"] % 2 == 0)
    assert sorted(r["id"] for r in ds.take_all()) == [0, 2, 4, 6, 8]
    ds2 = rd.from_items([{"n": 2}, {"n": 3}]).flat_map(
        lambda r: [{"v": r["n"]}] * r["n"]
    )
    assert sorted(r["v"] for r in ds2.take_all()) == [2, 2, 3, 3, 3]


def test_parquet_roundtrip(shared_ray, tmp_path):
    d = str(tmp_path / "pq")
    rd.range(50, parallelism=4).map(
        lambda r: {"id": r["id"], "val": float(r["id"]) * 0.5}
    ).write_parquet(d)
    assert len(os.listdir(d)) >= 1
    back = rd.read_parquet(d)
    assert back.count() == 50
    rows = back.sort("id").take_all()
    assert rows[10]["val"] == 5.0


def test_csv_roundtrip(shared_ray, tmp_path):
    d = str(tmp_path / "csv")
    rd.from_items([{"a": i, "b": f"s{i}"} for i in range(12)]).write_csv(d)
    back = rd.read_csv(d)
    rows = back.sort("a").take_all()
    assert len(rows) == 12 and rows[3]["b"] == "s3"


def test_json_roundtrip(shared_ray, tmp_path):
    d = str(tmp_path / "js")
    rd.from_items([{"k": i} for i in range(7)]).write_json(d)
    back = rd.read_json(d)
    assert sorted(r["k"] for r in back.take_all()) == list(range(7))


def test_read_text(shared_ray, tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("alpha\nbeta\ngamma\n")
    rows = rd.read_text(str(p)).take_all()
    assert [r["text"] for r in rows] == ["alpha", "beta", "gamma"]


def test_repartition_stats(shared_ray):
    ds = rd.range(40, parallelism=2).repartition(5)
    st = ds.stats()
    assert st["num_blocks"] == 5
    assert st["num_rows"] == 40


def test_random_shuffle(shared_ray):
    ds = rd.range(64, parallelism=4).random_shuffle(seed=7)
    vals = [r["id"] for r in ds.take_all()]
    assert sorted(vals) == list(range(64))
    assert vals != list(range(64))  # astronomically unlikely to be identity


def test_sort(shared_ray):
    ds = rd.from_items([{"v": x} for x in [5, 1, 4, 2, 3]]).sort("v")
    assert [r["v"] for r in ds.take_all()] == [1, 2, 3, 4, 5]
    dsd = rd.from_items([{"v": x} for x in [5, 1, 4]]).sort("v", descending=True)
    assert [r["v"] for r in dsd.take_all()] == [5, 4, 1]


def test_groupby(shared_ray):
    ds = rd.from_items([{"k": i % 3, "v": i} for i in range(12)], parallelism=3)
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 4, 1: 4, 2: 4}
    sums = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    assert sums == {0: 0 + 3 + 6 + 9, 1: 1 + 4 + 7 + 10, 2: 2 + 5 + 8 + 11}
    top = ds.groupby("k").map_groups(
        lambda rows: {"k": rows[0]["k"], "top": max(r["v"] for r in rows)}
    ).take_all()
    assert {r["k"]: r["top"] for r in top} == {0: 9, 1: 10, 2: 11}


def test_limit_union(shared_ray):
    a = rd.range(10, parallelism=2)
    b = rd.from_items([{"id": 100 + i} for i in range(5)])
    u = a.union(b)
    assert u.count() == 15
    assert len(a.limit(4).take_all()) == 4


def test_iter_batches(shared_ray):
    ds = rd.range(25, parallelism=3)
    batches = list(ds.iter_batches(batch_size=10))
    sizes = [len(b["id"]) for b in batches]
    assert sum(sizes) == 25
    assert all(s == 10 for s in sizes[:-1])
    dropped = list(ds.iter_batches(batch_size=10, drop_last=True))
    assert all(len(b["id"]) == 10 for b in dropped)
    assert sum(len(b["id"]) for b in dropped) == 20


def test_from_converters(shared_ray):
    import pandas as pd
    import pyarrow as pa

    dsp = rd.from_pandas(pd.DataFrame({"a": [1, 2, 3]}))
    assert dsp.count() == 3
    dsa = rd.from_arrow(pa.table({"b": [4, 5]}))
    assert sorted(r["b"] for r in dsa.take_all()) == [4, 5]
    dsn = rd.from_numpy(np.ones((4, 2), np.float32))
    batch = dsn.take_batch(4)
    assert batch["data"].shape == (4, 2)


def test_nd_tensor_columns(shared_ray):
    ds = rd.range_tensor(6, shape=(2, 3), parallelism=2)
    batch = ds.take_batch(6)
    assert batch["data"].shape == (6, 2, 3)
    assert batch["data"].dtype != object


def test_column_ops(shared_ray):
    ds = rd.from_items([{"a": i, "b": i * 2} for i in range(6)])
    added = ds.add_column("c", lambda r: r["a"] + r["b"]).take_all()
    assert all(r["c"] == r["a"] + r["b"] for r in added)
    only_a = ds.select_columns(["a"]).schema()
    assert only_a.names == ["a"]
    no_b = ds.drop_columns(["b"]).schema()
    assert "b" not in no_b.names


def test_streaming_split_disjoint_and_epochs(shared_ray):
    ds = rd.range(40, parallelism=8)
    it0, it1 = ds.streaming_split(2)
    # Interleave pulls so both consumers get a share of the stream.
    g0, g1 = it0.iter_block_refs(), it1.iter_block_refs()
    rows0, rows1 = [], []
    done0 = done1 = False
    while not (done0 and done1):
        if not done0:
            try:
                rows0.extend(rd.dataset.B.block_rows(rt.get(next(g0))))
            except StopIteration:
                done0 = True
        if not done1:
            try:
                rows1.extend(rd.dataset.B.block_rows(rt.get(next(g1))))
            except StopIteration:
                done1 = True
    ids0 = {r["id"] for r in rows0}
    ids1 = {r["id"] for r in rows1}
    assert ids0 | ids1 == set(range(40))
    assert not (ids0 & ids1)  # exactly-once across splits
    assert ids0 and ids1      # both actually consumed
    # Second epoch replays the whole dataset.
    total2 = sum(
        b.num_rows for it in (it0, it1) for b in it.iter_blocks()
    )
    assert total2 == 40


def test_train_ingest_end_to_end(shared_ray, tmp_path):
    """The full path: parquet on disk -> Dataset -> streaming_split across a
    2-worker gang -> get_dataset_shard().iter_batches() in the train fn."""
    import ray_tpu.train as train
    from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig

    d = str(tmp_path / "ingest")
    rd.range(64, parallelism=8).map(
        lambda r: {"id": r["id"], "w": float(r["id"])}
    ).write_parquet(d)
    ds = rd.read_parquet(d)

    seen_dir = str(tmp_path / "seen")
    os.makedirs(seen_dir, exist_ok=True)

    def train_fn(config):
        import json

        shard = train.get_dataset_shard("train")
        ctx = train.get_context()
        seen = []
        for batch in shard.iter_batches(batch_size=8):
            seen.extend(int(x) for x in batch["id"])
        with open(os.path.join(config["seen_dir"],
                               f"rank{ctx.get_world_rank()}.json"), "w") as f:
            json.dump(seen, f)
        train.report({"n": len(seen)})

    trainer = DataParallelTrainer(
        train_fn,
        train_loop_config={"seen_dir": seen_dir},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ingest", storage_path=str(tmp_path / "st")),
        datasets={"train": ds},
    )
    result = trainer.fit()
    assert result.error is None, result.error
    import json

    all_ids, per_rank = [], []
    for fname in sorted(os.listdir(seen_dir)):
        with open(os.path.join(seen_dir, fname)) as f:
            ids = json.load(f)
        per_rank.append(len(ids))
        all_ids.extend(ids)
    assert sorted(all_ids) == list(range(64))  # exactly-once across the gang
    assert len(per_rank) == 2


def test_prefetch_to_device(shared_ray):
    import jax

    from ray_tpu.data.infeed import prefetch_to_device

    ds = rd.range(32, parallelism=2)
    batches = ds.iter_batches(batch_size=8)
    out = list(prefetch_to_device(batches, size=2))
    assert len(out) == 4
    assert all(isinstance(b["id"], jax.Array) for b in out)
    assert int(out[0]["id"].sum() + out[1]["id"].sum()
               + out[2]["id"].sum() + out[3]["id"].sum()) == sum(range(32))


def test_zip(shared_ray):
    import ray_tpu.data as rd

    a = rd.range(20)
    b = rd.range(20).map(lambda r: {"sq": r["id"] ** 2})
    rows = a.zip(b).take_all()
    assert len(rows) == 20
    assert all(r["sq"] == r["id"] ** 2 for r in rows)


def test_zip_name_collision_and_length_mismatch(shared_ray):
    import pytest as _pytest

    import ray_tpu.data as rd

    rows = rd.range(5).zip(rd.range(5)).take_all()
    assert set(rows[0]) == {"id", "id_1"}
    with _pytest.raises(Exception, match="equal row counts"):
        rd.range(4).zip(rd.range(5)).take_all()


def test_random_sample(shared_ray):
    import ray_tpu.data as rd

    n = rd.range(2000).random_sample(0.25, seed=7).count()
    assert 350 < n < 650  # ~500 expected


def test_iter_torch_batches(shared_ray):
    import torch

    import ray_tpu.data as rd

    batches = list(rd.range(100).iter_torch_batches(batch_size=40))
    assert all(isinstance(b["id"], torch.Tensor) for b in batches)
    assert sum(len(b["id"]) for b in batches) == 100


def test_to_pandas(shared_ray):
    import ray_tpu.data as rd

    df = rd.range(10).map(lambda r: {"id": r["id"], "y": r["id"] * 2}).to_pandas()
    assert len(df) == 10 and list(df.columns) == ["id", "y"]
    assert (df["y"] == df["id"] * 2).all()
