"""Prefix KV cache + prefix-aware routing (reference: vLLM automatic prefix
caching + PrefixCacheAffinityRouter, prefix_aware_router.py:39)."""
import time

import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm import EngineConfig, LLMEngine, SamplingParams
from ray_tpu.models import TransformerConfig

CFG = TransformerConfig(
    vocab_size=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=1024, dtype=jnp.float32, attention_impl="reference",
)


def _engine(**kw):
    defaults = dict(max_slots=4, max_seq=1024, prefill_buckets=(64, 512),
                    kv_layout="paged", page_size=64, prefix_cache=True)
    defaults.update(kw)
    return LLMEngine(CFG, engine_config=EngineConfig(**defaults))


def test_hit_is_exact_and_skips_prefill():
    """A cache hit produces byte-identical greedy output with ZERO prefill
    dispatches (the whole point: prompt KV comes from the cache)."""
    eng = _engine()
    prompt = np.arange(1, 70, dtype=np.int32) % 97

    cold = eng.generate(prompt, max_tokens=8)
    # 69 tokens @ page 64: chain entry for the 64-token page-aligned prefix
    # + the full-prompt entry, sharing page 0 -> 2 distinct cached pages.
    assert eng.prefix_cache_stats == {"hits": 0, "partial_hits": 0, "misses": 1,
                                      "entries": 2, "cached_pages": 2}
    calls = []
    orig = eng._prefill

    def counting(bucket, k):
        calls.append((bucket, k))
        return orig(bucket, k)

    eng._prefill = counting
    warm = eng.generate(prompt, max_tokens=8)
    assert warm["tokens"] == cold["tokens"]
    assert calls == [], f"cache hit still dispatched prefill: {calls}"
    assert eng.prefix_cache_stats["hits"] == 1
    assert warm["ttft_s"] is not None and warm["ttft_s"] > 0


def test_hit_respects_per_request_sampling():
    """Two hot-sampled hits on the same cached prompt diverge (the cache
    reuses KV, not tokens)."""
    eng = _engine()
    prompt = np.arange(1, 70, dtype=np.int32) % 97
    eng.generate(prompt, max_tokens=4)  # populate cache
    a = eng.generate(prompt, max_tokens=16,
                     sampling=SamplingParams(temperature=3.0, max_tokens=16))
    b = eng.generate(prompt, max_tokens=16,
                     sampling=SamplingParams(temperature=3.0, max_tokens=16))
    assert eng.prefix_cache_stats["hits"] >= 2
    assert a["tokens"] != b["tokens"]


def test_lru_eviction_under_page_pressure():
    """A tight page pool evicts cached prefixes rather than starving
    admission; everything still completes correctly."""
    # Pool sized so ~2 cached prompts exhaust it.
    eng = _engine(max_slots=2, total_pages=9)
    prompts = [np.arange(1 + i, 66 + i, dtype=np.int32) % 97 for i in range(4)]
    outs = [eng.generate(p, max_tokens=4)["tokens"] for p in prompts]
    stats = eng.prefix_cache_stats
    assert stats["cached_pages"] <= 8
    # Re-running the LAST prompt (most recently cached) still hits.
    again = eng.generate(prompts[-1], max_tokens=4)
    assert again["tokens"] == outs[-1]


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_cold_warm_ttft_gap():
    """Cache-hit TTFT beats cold TTFT (the routing payoff): prefilling a
    ~500-token prompt costs real compute; the hit replaces it with a page
    copy. Both paths pre-warmed so compile time is excluded."""
    eng = _engine()
    eng.warmup(buckets=(512,))
    warm_decoy = np.arange(3, 500, dtype=np.int32) % 97
    eng.generate(warm_decoy, max_tokens=2)  # warm every program incl. copy
    eng.generate(warm_decoy, max_tokens=2)

    prompt = np.arange(5, 500, dtype=np.int32) % 97
    colds, warms = [], []
    for trial in range(3):
        p = (prompt + trial) % 97
        colds.append(eng.generate(p, max_tokens=2)["ttft_s"])
        warms.append(eng.generate(p, max_tokens=2)["ttft_s"])
    cold, warm = min(colds), min(warms)
    assert warm < cold, f"cache-hit ttft {warm:.4f}s not below cold {cold:.4f}s"


def _count_prefills(eng):
    calls = []
    orig_full, orig_tail = eng._prefill, eng._tail_prefill

    def full(bucket, k):
        calls.append(("full", bucket, k))
        return orig_full(bucket, k)

    def tail(tb, c):
        calls.append(("tail", tb, c))
        return orig_tail(tb, c)

    eng._prefill, eng._tail_prefill = full, tail
    return calls


def test_partial_prefix_tail_prefill_matches_cold():
    """The canonical shared-system-prompt workload: a prompt EXTENDING a
    cached page-aligned prefix prefills only the tail, attending over the
    cached pages — greedy output is identical to a cold engine's, and no
    full-length prefill is dispatched."""
    sys_prompt = (np.arange(7, 7 + 128, dtype=np.int32) % 96) + 1  # 2 pages
    q1 = np.concatenate([sys_prompt, np.array([3, 1, 4, 1, 5], np.int32)])
    q2 = np.concatenate([sys_prompt, np.array([2, 7, 1, 8], np.int32)])

    warm_eng = _engine()
    warm_eng.generate(q1, max_tokens=8)  # populates chain entries for sys
    calls = _count_prefills(warm_eng)
    warm = warm_eng.generate(q2, max_tokens=8)
    assert warm_eng.prefix_cache_stats["partial_hits"] == 1
    assert all(c[0] == "tail" for c in calls), f"partial hit ran full prefill: {calls}"
    assert calls and calls[0][1] == 64, f"tail bucket should be 64: {calls}"

    cold_eng = _engine()  # same seed -> same params
    cold = cold_eng.generate(q2, max_tokens=8)
    assert warm["tokens"] == cold["tokens"], (
        f"partial-prefix output diverged: {warm['tokens']} vs {cold['tokens']}"
    )


def test_partial_prefix_page_aligned_extension():
    """A prompt that extends the cached prefix by exactly whole pages (the
    new length is page-aligned and fully covered by a chain entry of an
    earlier LONGER prompt's prefix) restarts decode with no prefill."""
    base = (np.arange(11, 11 + 200, dtype=np.int32) % 96) + 1  # 3 full pages + tail
    eng = _engine()
    eng.generate(base, max_tokens=4)
    calls = _count_prefills(eng)
    # First 128 tokens = exactly 2 cached full pages -> exact-length chain
    # hit: decode re-derives position 127, no prefill of any kind.
    out = eng.generate(base[:128], max_tokens=4)
    assert calls == [], f"page-aligned covered prompt dispatched prefill: {calls}"
    assert eng.prefix_cache_stats["hits"] == 1
    cold = _engine().generate(base[:128], max_tokens=4)
    assert out["tokens"] == cold["tokens"]


def test_shared_page_refcounts_and_conservation():
    """Chain entries share pages; eviction frees a page only when its last
    referencing entry goes, and no page is ever leaked or double-freed."""
    eng = _engine(max_slots=2, total_pages=12)
    total = eng.ec.total_pages - 1  # page 0 reserved

    def conserved():
        held = sum(len(s.pages) for s in eng.slots if s is not None)
        return len(eng.free_pages) + len(eng._page_refs) + held == total

    p1 = (np.arange(1, 1 + 150, dtype=np.int32) % 96) + 1
    eng.generate(p1, max_tokens=4)
    assert conserved()
    stats = eng.prefix_cache_stats
    assert stats["entries"] == 3  # 64-prefix, 128-prefix, full 150
    assert stats["cached_pages"] == 3  # 3 distinct pages, shared by chain
    # Page 0 of the chain is referenced by all three entries.
    first_page = next(iter(eng._prefix_cache.values()))["pages"][0]
    assert eng._page_refs[first_page] == 3
    # Evict one entry's worth: LRU entry (the 64-token prefix) goes first,
    # but its page is shared -> nothing frees until all referents go.
    before_free = len(eng.free_pages)
    eng._evict_prefix_cache(1)
    assert conserved()
    assert len(eng.free_pages) >= before_free + 1
    # Full drain.
    eng._evict_prefix_cache(100)
    assert not eng._prefix_cache and not eng._page_refs
    assert len(eng.free_pages) == total
    assert conserved()


def test_partial_hit_retire_shares_prefix_pages():
    """N requests extending one system prompt must not cache N copies of
    it: a retiring partial-hit slot's new chain entries reference the
    ALREADY-cached prefix pages, and the slot's duplicate copies free."""
    sys_prompt = (np.arange(7, 7 + 128, dtype=np.int32) % 96) + 1  # 2 pages
    eng = _engine()
    q1 = np.concatenate([sys_prompt, np.array([3, 1, 4, 1, 5], np.int32)])
    eng.generate(q1, max_tokens=4)
    assert eng.prefix_cache_stats["cached_pages"] == 3  # 2 sys + 1 tail
    for t in range(3):
        q = np.concatenate([sys_prompt, np.array([10 + t, 2, 6], np.int32)])
        eng.generate(q, max_tokens=4)
    stats = eng.prefix_cache_stats
    assert stats["partial_hits"] == 3
    # Each extension adds ONE page (its own tail), never a sys copy.
    assert stats["cached_pages"] == 6, stats
    # The shared system-prompt pages are referenced by every full entry.
    first = next(iter(eng._prefix_cache.values()))["pages"][0]
    assert eng._page_refs[first] >= 4


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_admission_does_not_evict_its_own_prefix():
    """Under page pressure a request must not evict the very entry it is
    about to hit (lookup now precedes eviction, hit entry protected)."""
    # Pool: 12 usable pages. Prompt ~150 tokens -> needs 4 pages/request
    # (prompt 3 + budget slack). Decoy fills the cache so admission must
    # evict; the protected entry must survive and the request must hit.
    eng = _engine(max_slots=1, total_pages=13, prefill_buckets=(64, 256))
    p1 = (np.arange(1, 1 + 150, dtype=np.int32) % 96) + 1
    decoy = (np.arange(50, 50 + 150, dtype=np.int32) % 96) + 1
    eng.generate(p1, max_tokens=4)
    eng.generate(decoy, max_tokens=4)
    # Cache now holds both prompts' chains; a re-run of p1 needs eviction
    # room but must still hit p1's own entry.
    out = eng.generate(p1, max_tokens=4)
    assert eng.prefix_cache_stats["hits"] >= 1, eng.prefix_cache_stats
    cold = _engine().generate(p1, max_tokens=4)
    assert out["tokens"] == cold["tokens"]


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_partial_hit_ttft_beats_cold():
    """Tail prefill over cached pages is measurably cheaper than a cold
    full prefill (the routing payoff for shared system prompts). Programs
    pre-warmed so compile time is excluded."""
    eng = _engine()
    eng.warmup(buckets=(512,))
    sys_prompt = (np.arange(9, 9 + 448, dtype=np.int32) % 96) + 1  # 7 pages
    tails = [np.array([3 + t, 1, 4], np.int32) for t in range(8)]
    # Warm every program variant (full 512 prefill, tail-64 prefill, copy).
    eng.generate(np.concatenate([sys_prompt, tails[6]]), max_tokens=2)
    eng.generate(np.concatenate([sys_prompt, tails[7]]), max_tokens=2)
    colds, warms = [], []
    for t in range(3):
        shifted = ((sys_prompt + 17 * (t + 1)) % 96) + 1  # new sys -> cold
        colds.append(eng.generate(
            np.concatenate([shifted, tails[t]]), max_tokens=2)["ttft_s"])
        warms.append(eng.generate(
            np.concatenate([shifted, tails[t + 3]]), max_tokens=2)["ttft_s"])
    assert eng.prefix_cache_stats["partial_hits"] >= 4
    cold, warm = min(colds), min(warms)
    assert warm < cold, f"partial-hit ttft {warm:.4f}s not below cold {cold:.4f}s"


def test_dense_layout_rejects_prefix_cache():
    with pytest.raises(ValueError):
        LLMEngine(CFG, engine_config=EngineConfig(
            max_slots=2, max_seq=1024, kv_layout="dense", prefix_cache=True))


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

def test_openai_prefix_router_keys():
    from ray_tpu.llm.openai import openai_prefix_router
    from ray_tpu.serve.proxy import Request
    import json

    def req(body):
        return Request("POST", "/v1/completions", {}, {}, json.dumps(body).encode())

    long_prefix = "shared conversation history " * 20  # > 256 chars
    a = openai_prefix_router(req({"prompt": long_prefix + "question one"}))
    b = openai_prefix_router(req({"prompt": long_prefix + "another question"}))
    c = openai_prefix_router(req({"prompt": "totally different"}))
    assert a and a == b, "same 256-char prefix must share a key"
    assert c != a
    m = openai_prefix_router(req({"messages": [{"role": "user", "content": "hi"}]}))
    assert m and m != a
    assert openai_prefix_router(req({"no": "prompt"})) == ""


def test_tokenized_router_keys_on_first_page():
    """With a tokenizer, the affinity key is the digest of the first
    page_size TOKENS — exactly the engine's first chain-digest boundary —
    so page-cache-compatible requests co-locate and others spread."""
    import json

    from ray_tpu.llm.openai import make_prefix_router
    from ray_tpu.llm.tokenizer import load_tokenizer
    from ray_tpu.serve.proxy import Request

    tok = load_tokenizer(None)
    policy = make_prefix_router(tok, page_size=8)

    def req(prompt):
        return Request("POST", "/v1/completions", {}, {},
                       json.dumps({"prompt": prompt}).encode())

    shared = "a shared system prompt that spans well past eight tokens of text"
    a = policy(req(shared + " question one"))
    b = policy(req(shared + " other question"))
    assert a and a == b, "first-token-page sharers must co-locate"
    # Divergence INSIDE the first page -> different keys.
    c = policy(req("b shared system prompt that spans well past eight tokens"))
    assert c != a


def test_affinity_key_sticks_and_proxy_header_routes():
    import json
    import socket

    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=8)
    try:
        @serve.deployment(num_replicas=2, max_ongoing_requests=8)
        class Who:
            def __call__(self, request):
                import os
                return {"pid": os.getpid()}

            def pid(self):
                import os
                return os.getpid()

        serve.run(Who.bind(), name="who", route_prefix="/who")
        h = serve.get_deployment_handle("Who", "who")
        # Handle-level affinity: same key -> same replica, across calls.
        pids_a = {h.options(affinity_key="conv-a").pid.remote().result(timeout=60)
                  for _ in range(6)}
        assert len(pids_a) == 1
        # Proxy header affinity: x-affinity-key pins the replica.
        port = serve.http_port()

        def post(key):
            body = b"{}"
            s = socket.create_connection(("127.0.0.1", port), timeout=60)
            s.sendall((f"POST /who HTTP/1.1\r\nhost: x\r\nx-affinity-key: {key}\r\n"
                       f"content-length: {len(body)}\r\nconnection: close\r\n\r\n"
                       ).encode() + body)
            raw = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                raw += chunk
            s.close()
            return json.loads(raw.split(b"\r\n\r\n", 1)[1])["pid"]

        pids = {post("session-1") for _ in range(5)}
        assert len(pids) == 1, f"header affinity bounced replicas: {pids}"
        serve.delete("who")
    finally:
        serve.shutdown()
        rt.shutdown()
