"""Prefix KV cache + prefix-aware routing (reference: vLLM automatic prefix
caching + PrefixCacheAffinityRouter, prefix_aware_router.py:39)."""
import time

import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm import EngineConfig, LLMEngine, SamplingParams
from ray_tpu.models import TransformerConfig

CFG = TransformerConfig(
    vocab_size=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=1024, dtype=jnp.float32, attention_impl="reference",
)


def _engine(**kw):
    defaults = dict(max_slots=4, max_seq=1024, prefill_buckets=(64, 512),
                    kv_layout="paged", page_size=64, prefix_cache=True)
    defaults.update(kw)
    return LLMEngine(CFG, engine_config=EngineConfig(**defaults))


def test_hit_is_exact_and_skips_prefill():
    """A cache hit produces byte-identical greedy output with ZERO prefill
    dispatches (the whole point: prompt KV comes from the cache)."""
    eng = _engine()
    prompt = np.arange(1, 70, dtype=np.int32) % 97

    cold = eng.generate(prompt, max_tokens=8)
    assert eng.prefix_cache_stats == {"hits": 0, "misses": 1, "entries": 1,
                                      "cached_pages": 2}
    calls = []
    orig = eng._prefill

    def counting(bucket, k):
        calls.append((bucket, k))
        return orig(bucket, k)

    eng._prefill = counting
    warm = eng.generate(prompt, max_tokens=8)
    assert warm["tokens"] == cold["tokens"]
    assert calls == [], f"cache hit still dispatched prefill: {calls}"
    assert eng.prefix_cache_stats["hits"] == 1
    assert warm["ttft_s"] is not None and warm["ttft_s"] > 0


def test_hit_respects_per_request_sampling():
    """Two hot-sampled hits on the same cached prompt diverge (the cache
    reuses KV, not tokens)."""
    eng = _engine()
    prompt = np.arange(1, 70, dtype=np.int32) % 97
    eng.generate(prompt, max_tokens=4)  # populate cache
    a = eng.generate(prompt, max_tokens=16,
                     sampling=SamplingParams(temperature=3.0, max_tokens=16))
    b = eng.generate(prompt, max_tokens=16,
                     sampling=SamplingParams(temperature=3.0, max_tokens=16))
    assert eng.prefix_cache_stats["hits"] >= 2
    assert a["tokens"] != b["tokens"]


def test_lru_eviction_under_page_pressure():
    """A tight page pool evicts cached prefixes rather than starving
    admission; everything still completes correctly."""
    # Pool sized so ~2 cached prompts exhaust it.
    eng = _engine(max_slots=2, total_pages=9)
    prompts = [np.arange(1 + i, 66 + i, dtype=np.int32) % 97 for i in range(4)]
    outs = [eng.generate(p, max_tokens=4)["tokens"] for p in prompts]
    stats = eng.prefix_cache_stats
    assert stats["cached_pages"] <= 8
    # Re-running the LAST prompt (most recently cached) still hits.
    again = eng.generate(prompts[-1], max_tokens=4)
    assert again["tokens"] == outs[-1]


def test_cold_warm_ttft_gap():
    """Cache-hit TTFT beats cold TTFT (the routing payoff): prefilling a
    ~500-token prompt costs real compute; the hit replaces it with a page
    copy. Both paths pre-warmed so compile time is excluded."""
    eng = _engine()
    eng.warmup(buckets=(512,))
    warm_decoy = np.arange(3, 500, dtype=np.int32) % 97
    eng.generate(warm_decoy, max_tokens=2)  # warm every program incl. copy
    eng.generate(warm_decoy, max_tokens=2)

    prompt = np.arange(5, 500, dtype=np.int32) % 97
    colds, warms = [], []
    for trial in range(3):
        p = (prompt + trial) % 97
        colds.append(eng.generate(p, max_tokens=2)["ttft_s"])
        warms.append(eng.generate(p, max_tokens=2)["ttft_s"])
    cold, warm = min(colds), min(warms)
    assert warm < cold, f"cache-hit ttft {warm:.4f}s not below cold {cold:.4f}s"


def test_dense_layout_rejects_prefix_cache():
    with pytest.raises(ValueError):
        LLMEngine(CFG, engine_config=EngineConfig(
            max_slots=2, max_seq=1024, kv_layout="dense", prefix_cache=True))


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

def test_openai_prefix_router_keys():
    from ray_tpu.llm.openai import openai_prefix_router
    from ray_tpu.serve.proxy import Request
    import json

    def req(body):
        return Request("POST", "/v1/completions", {}, {}, json.dumps(body).encode())

    long_prefix = "shared conversation history " * 20  # > 256 chars
    a = openai_prefix_router(req({"prompt": long_prefix + "question one"}))
    b = openai_prefix_router(req({"prompt": long_prefix + "another question"}))
    c = openai_prefix_router(req({"prompt": "totally different"}))
    assert a and a == b, "same 256-char prefix must share a key"
    assert c != a
    m = openai_prefix_router(req({"messages": [{"role": "user", "content": "hi"}]}))
    assert m and m != a
    assert openai_prefix_router(req({"no": "prompt"})) == ""


def test_affinity_key_sticks_and_proxy_header_routes():
    import json
    import socket

    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=8)
    try:
        @serve.deployment(num_replicas=2, max_ongoing_requests=8)
        class Who:
            def __call__(self, request):
                import os
                return {"pid": os.getpid()}

            def pid(self):
                import os
                return os.getpid()

        serve.run(Who.bind(), name="who", route_prefix="/who")
        h = serve.get_deployment_handle("Who", "who")
        # Handle-level affinity: same key -> same replica, across calls.
        pids_a = {h.options(affinity_key="conv-a").pid.remote().result(timeout=60)
                  for _ in range(6)}
        assert len(pids_a) == 1
        # Proxy header affinity: x-affinity-key pins the replica.
        port = serve.http_port()

        def post(key):
            body = b"{}"
            s = socket.create_connection(("127.0.0.1", port), timeout=60)
            s.sendall((f"POST /who HTTP/1.1\r\nhost: x\r\nx-affinity-key: {key}\r\n"
                       f"content-length: {len(body)}\r\nconnection: close\r\n\r\n"
                       ).encode() + body)
            raw = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                raw += chunk
            s.close()
            return json.loads(raw.split(b"\r\n\r\n", 1)[1])["pid"]

        pids = {post("session-1") for _ in range(5)}
        assert len(pids) == 1, f"header affinity bounced replicas: {pids}"
        serve.delete("who")
    finally:
        serve.shutdown()
        rt.shutdown()
