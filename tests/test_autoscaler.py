"""Autoscaler: demand-driven scale-up and idle scale-down on a fake provider
(reference analogue: autoscaler/v2/tests with FakeMultiNodeProvider). Own
module: needs its own cluster session with infeasible_as_pending set."""
import time

import pytest

import ray_tpu as rt


def test_autoscaler_scales_up_and_down():
    from ray_tpu.autoscaler import Autoscaler, LocalNodeProvider, NodeType
    from ray_tpu.core.api import Cluster, init, shutdown
    from ray_tpu.core.config import Config

    cfg = Config().apply_env()
    cfg.infeasible_as_pending = True
    cluster = Cluster(initialize_head=False, config=cfg)
    cluster.add_node(num_cpus=1)
    init(address=cluster.address, config=cfg)
    try:
        provider = LocalNodeProvider(cluster)
        autoscaler = Autoscaler(
            [NodeType("cpu4", {"CPU": 4.0}, max_workers=3)], provider, idle_timeout_s=1.0
        )
        # Demand exceeding the 1-CPU head: a pending lease + pending PG.
        @rt.remote(num_cpus=4)
        def heavy():
            return 42

        ref = heavy.remote()
        pg = rt.placement_group([{"CPU": 4}], strategy="PACK")
        time.sleep(0.5)  # demand lands in pending queues
        result = autoscaler.update()
        assert sum(result["launched"].values()) >= 1, result
        assert rt.get(ref, timeout=120) == 42
        assert pg.ready(timeout=30)
        rt.remove_placement_group(pg)
        # Scale-down is three-phase now: arm idle timers -> drain -> terminate.
        time.sleep(3.0)
        autoscaler.update()  # arms idle timers (post-workload idle)
        time.sleep(1.5)
        result = autoscaler.update()  # idle past timeout: drains first
        assert result["draining"], result
        result = autoscaler.update()  # still idle: terminates
        assert result["terminated"], result
    finally:
        shutdown()
        cluster.shutdown()


def test_external_demand_drives_node_launch_and_clears():
    """Scale plane hand-off: demand registered through the core
    controller's external-demand table (the serve controller's
    unplaceable-replica path) makes the NODE autoscaler launch capacity;
    clearing the source stops holding nodes up."""
    from ray_tpu.autoscaler import Autoscaler, LocalNodeProvider, NodeType
    from ray_tpu.core import api
    from ray_tpu.core.api import Cluster, init, shutdown

    cluster = Cluster(initialize_head=False)
    cluster.add_node(num_cpus=1)
    init(address=cluster.address)
    try:
        core = api._require_worker()

        def ctl(method, payload):
            return core._run(core.controller.call(method, payload))

        provider = LocalNodeProvider(cluster)
        autoscaler = Autoscaler(
            [NodeType("cpu4", {"CPU": 4.0}, max_workers=3)], provider,
            idle_timeout_s=3600.0)
        # No external demand: nothing to launch.
        assert autoscaler.update()["launched"] == {}
        # Two unplaceable 3-CPU replicas -> two cpu4 nodes.
        out = ctl("set_external_demand", {
            "source": "serve:app/dep",
            "items": [{"demand": {"CPU": 3.0}}, {"demand": {"CPU": 3.0}}],
        })
        assert out["ok"]
        state = ctl("get_autoscaler_state", {})
        assert sum(1 for p in state["pending"] if p.get("kind") == "external") == 2
        result = autoscaler.update()
        assert result["launched"].get("cpu4") == 2, result
        # Satisfied: the source clears and pending demand drops to zero.
        assert ctl("set_external_demand", {"source": "serve:app/dep", "items": []})["ok"]
        state = ctl("get_autoscaler_state", {})
        assert not any(p.get("kind") == "external" for p in state["pending"])
        assert autoscaler.update()["launched"] == {}
    finally:
        shutdown()
        cluster.shutdown()


@pytest.mark.slow
def test_serve_unplaceable_replica_requests_node_capacity():
    """E2E scale-plane hand-off: a serve replica whose footprint fits NO
    live node makes the serve controller register external demand, the
    node autoscaler launches a matching node, and the deployment then
    converges HEALTHY on the new capacity."""
    import threading

    from ray_tpu import serve
    from ray_tpu.autoscaler import Autoscaler, LocalNodeProvider, NodeType
    from ray_tpu.core.api import Cluster, init, shutdown

    cluster = Cluster(initialize_head=False)
    cluster.add_node(num_cpus=4)  # no SRV resource anywhere
    init(address=cluster.address)
    try:
        @serve.deployment(name="Pinned",
                          ray_actor_options={"resources": {"SRV": 1.0}})
        class Pinned:
            def __call__(self, x="-"):
                return "ok"

        serve.start(proxy=False)
        err: list = []

        def deploy():
            try:
                serve.run(Pinned.bind(), name="pinned", http=False, timeout_s=120)
            except Exception as e:  # noqa: BLE001 — surfaced by the assert below
                err.append(e)

        th = threading.Thread(target=deploy, daemon=True)
        th.start()
        provider = LocalNodeProvider(cluster)
        autoscaler = Autoscaler(
            [NodeType("srv", {"CPU": 2.0, "SRV": 4.0}, max_workers=2)],
            provider, idle_timeout_s=3600.0)
        launched = {}
        deadline = time.time() + 60
        while time.time() < deadline and not launched:
            launched = autoscaler.update()["launched"]
            time.sleep(0.5)
        assert launched.get("srv") == 1, (
            f"unplaceable replica never became node-autoscaler demand: {launched}")
        th.join(timeout=120)
        assert not th.is_alive() and not err, f"app never became healthy: {err}"
        h = serve.get_deployment_handle("Pinned", "pinned")
        assert h.remote("x").result(timeout=30) == "ok"
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        shutdown()
        cluster.shutdown()


def test_drain_excludes_node_from_scheduling():
    """A draining node accepts no new work but keeps serving running actors
    (reference: DrainRaylet semantics)."""
    from ray_tpu.core.api import Cluster, init, shutdown

    cluster = Cluster(initialize_head=False)
    cluster.add_node(num_cpus=1, resources={"head": 1.0})
    victim = cluster.add_node(num_cpus=2, resources={"spec": 2.0})
    init(address=cluster.address)
    try:
        @rt.remote(num_cpus=1, resources={"spec": 1.0})
        class Pinned:
            def ping(self):
                return "alive"

        a = Pinned.remote()
        assert rt.get(a.ping.remote(), timeout=60) == "alive"

        from ray_tpu.core import api
        core = api._require_worker()
        reply = core._run(core.controller.call("drain_node", {"node_id": victim.node_id}))
        assert reply["ok"] and not reply["idle"]  # actor still holds resources

        # Existing actor keeps serving.
        assert rt.get(a.ping.remote(), timeout=60) == "alive"
        # New demand for that node's resources cannot schedule (drained).
        @rt.remote(num_cpus=1, resources={"spec": 1.0})
        def probe():
            return 1
        ref = probe.remote()
        ready, not_ready = rt.wait([ref], timeout=2.0)
        assert not ready, "task scheduled onto a draining node"
        # Undrain: the task proceeds.
        core._run(core.controller.call("undrain_node", {"node_id": victim.node_id}))
        assert rt.get(ref, timeout=60) == 1
    finally:
        shutdown()
        cluster.shutdown()


def test_gce_tpu_provider_lifecycle():
    """GCE TPU provider against the mocked API: single-host via nodes API,
    multi-host via queuedResources; list/terminate round-trip."""
    from ray_tpu.autoscaler import NodeType
    from ray_tpu.gcp import FakeTPUAPI, GCETPUNodeProvider, PROVIDER_ID_LABEL

    api = FakeTPUAPI()
    prov = GCETPUNodeProvider("proj", "us-central2-b", api)
    single = NodeType("v5e-1", {"TPU": 1.0}, labels={"accelerator_type": "v5litepod-1"})
    multi = NodeType("v4-16", {"TPU": 4.0}, labels={"accelerator_type": "v4-16"})

    pid1 = prov.create_node(single)
    pid2 = prov.create_node(multi)
    assert ("create_node", pid1) in api.calls
    assert ("create_qr", pid2) in api.calls  # multi-host -> queued resource
    live = prov.non_terminated_nodes()
    assert live == {pid1: "v5e-1", pid2: "v4-16"}

    # controller_node_id maps through the daemon-registered label.
    nodes = {"n1": {"labels": {PROVIDER_ID_LABEL: pid1}}}
    assert prov.controller_node_id(pid1, nodes) == "n1"
    assert prov.controller_node_id(pid2, nodes) is None  # not yet registered

    prov.terminate_node(pid1)
    prov.terminate_node(pid2)
    assert prov.non_terminated_nodes() == {}
    assert ("delete_node", pid1) in api.calls
    assert ("delete_qr", pid2) in api.calls


def test_gce_queued_resource_waits_not_respawned():
    """A parked queued resource (no capacity) still counts as non-terminated,
    so the autoscaler does not re-request the slice every update."""
    from ray_tpu.autoscaler import NodeType
    from ray_tpu.gcp import FakeTPUAPI, GCETPUNodeProvider

    api = FakeTPUAPI(qr_capacity=0)  # everything parks in ACCEPTED
    prov = GCETPUNodeProvider("proj", "us-central2-b", api)
    multi = NodeType("v4-32", {"TPU": 4.0}, labels={"accelerator_type": "v4-32"})
    pid = prov.create_node(multi)
    for _ in range(3):
        assert pid in prov.non_terminated_nodes()
    assert sum(1 for c in api.calls if c[0] == "create_qr") == 1
