"""Autoscaler: demand-driven scale-up and idle scale-down on a fake provider
(reference analogue: autoscaler/v2/tests with FakeMultiNodeProvider). Own
module: needs its own cluster session with infeasible_as_pending set."""
import time

import ray_tpu as rt


def test_autoscaler_scales_up_and_down():
    from ray_tpu.autoscaler import Autoscaler, LocalNodeProvider, NodeType
    from ray_tpu.core.api import Cluster, init, shutdown
    from ray_tpu.core.config import Config

    cfg = Config().apply_env()
    cfg.infeasible_as_pending = True
    cluster = Cluster(initialize_head=False, config=cfg)
    cluster.add_node(num_cpus=1)
    init(address=cluster.address, config=cfg)
    try:
        provider = LocalNodeProvider(cluster)
        autoscaler = Autoscaler(
            [NodeType("cpu4", {"CPU": 4.0}, max_workers=3)], provider, idle_timeout_s=1.0
        )
        # Demand exceeding the 1-CPU head: a pending lease + pending PG.
        @rt.remote(num_cpus=4)
        def heavy():
            return 42

        ref = heavy.remote()
        pg = rt.placement_group([{"CPU": 4}], strategy="PACK")
        time.sleep(0.5)  # demand lands in pending queues
        result = autoscaler.update()
        assert sum(result["launched"].values()) >= 1, result
        assert rt.get(ref, timeout=120) == 42
        assert pg.ready(timeout=30)
        rt.remove_placement_group(pg)
        # Drain: demand gone; idle autoscaled nodes terminate after timeout.
        time.sleep(3.0)
        autoscaler.update()  # arms idle timers (post-workload idle)
        time.sleep(1.5)
        result = autoscaler.update()
        assert result["terminated"], result
    finally:
        shutdown()
        cluster.shutdown()
