"""Replay plane: trace codec, seeded synthesizer, open-loop replayer,
chaos-timeline compilation, and the run-ledger report engine.

Everything here is offline or loopback-only (a stdlib no-op HTTP server
stands in for the serve proxy) — the full day_in_the_life scenario runs in
tests/test_chaos.py. The canonical-artifact tests pin the synthesizer to
the committed seed-0 trace: if the generator drifts, the byte-identity
contract (one seed -> one day) is broken and these fail first.
"""
from __future__ import annotations

import copy
import hashlib
import json
import pathlib
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from ray_tpu.chaos import plan as _plan
from ray_tpu.chaos.plan import FaultRule, FaultSchedule
from ray_tpu.obs import ledger as _ledger
from ray_tpu.obs.slo import SloEngine, SloTracker, Objective
from ray_tpu.replay import (CompiledTimeline, Replayer, Timeline,
                            TimelineDriver, default_params, dumps_trace,
                            envelope, phase_spans, read_trace, summarize,
                            synthesize, write_trace)

DATA = pathlib.Path(__file__).parent / "data"


@pytest.fixture(autouse=True)
def _chaos_clean():
    """The replayer's send path has a chaos gate — keep the plane disarmed
    around every test so an installed schedule never leaks."""
    _plan.uninstall()
    yield
    _plan.uninstall()


# ---------------------------------------------------------------------------
# trace codec + synthesizer
# ---------------------------------------------------------------------------

def test_trace_codec_roundtrip(tmp_path):
    header, records = synthesize(7, duration_s=4.0, base_rps=30.0)
    path = str(tmp_path / "t.jsonl")
    sha = write_trace(path, header, records)
    assert sha == hashlib.sha256(dumps_trace(header, records)).hexdigest()
    h2, r2 = read_trace(path)
    assert h2 == header
    assert r2 == records
    # re-serializing the parsed trace reproduces the original bytes
    assert dumps_trace(h2, r2) == dumps_trace(header, records)


def test_trace_read_validates(tmp_path):
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"format": "something-else", "version": 1}\n')
    with pytest.raises(ValueError, match="not a raytpu-trace"):
        read_trace(str(bad))
    header, records = synthesize(1, duration_s=2.0, base_rps=20.0)
    records[0], records[1] = records[1], records[0]  # break arrival order
    shuffled = tmp_path / "shuffled.jsonl"
    shuffled.write_bytes(dumps_trace(header, records))
    with pytest.raises(ValueError, match="out of arrival order"):
        read_trace(str(shuffled))
    header2, records2 = synthesize(1, duration_s=2.0, base_rps=20.0)
    header2["requests"] += 1  # header promise vs body mismatch
    lying = tmp_path / "lying.jsonl"
    lying.write_bytes(dumps_trace(header2, records2))
    with pytest.raises(ValueError, match="promises"):
        read_trace(str(lying))


def test_synthesizer_byte_determinism():
    a = dumps_trace(*synthesize(42, duration_s=6.0, base_rps=25.0, tenants=3))
    b = dumps_trace(*synthesize(42, duration_s=6.0, base_rps=25.0, tenants=3))
    assert a == b
    c = dumps_trace(*synthesize(43, duration_s=6.0, base_rps=25.0, tenants=3))
    assert a != c


def test_synthesizer_matches_committed_artifact():
    """The committed seed-0 quick trace IS synthesize(0, quick params) —
    byte for byte. Generator drift = a broken replay contract."""
    committed = (DATA / "day_in_the_life_seed0.trace.jsonl").read_bytes()
    fresh = dumps_trace(*synthesize(0, **default_params(quick=True)))
    assert hashlib.sha256(fresh).hexdigest() == hashlib.sha256(committed).hexdigest()
    assert fresh == committed


def test_envelope_and_phase_spans():
    p = default_params(quick=True)
    # calm shoulders sit at 1.0, the spike mid-window at spike_mult
    assert envelope(0.1, p["spike_start"], p["spike_end"], p["spike_mult"]) == 1.0
    assert envelope(0.9, p["spike_start"], p["spike_end"], p["spike_mult"]) == 1.0
    mid = (p["spike_start"] + p["spike_end"]) / 2
    assert envelope(mid, p["spike_start"], p["spike_end"],
                    p["spike_mult"]) == pytest.approx(p["spike_mult"])
    spans = phase_spans(p)
    assert set(spans) == {"calm", "storm", "recovery"}
    assert spans["calm"][1] == spans["storm"][0]
    assert spans["storm"][1] == spans["recovery"][0]
    assert spans["recovery"][1] == p["duration_s"]


def test_synthesizer_class_and_tenant_mix():
    header, records = synthesize(5, duration_s=20.0, base_rps=40.0, tenants=4)
    assert header["requests"] == len(records) > 200
    assert set(header["classes"]) == {"interactive", "batch", "best_effort"}
    # Zipf skew: the head tenant dominates the tail tenant
    assert header["tenants"]["t0"] > header["tenants"]["t3"]


# ---------------------------------------------------------------------------
# FaultRule.skip — the hit-space window primitive the compiler targets
# ---------------------------------------------------------------------------

def test_fault_rule_skip_window():
    sched = FaultSchedule([FaultRule.from_spec(
        {"site": "worker.exec", "kind": "error", "skip": 3, "every": 2,
         "max_faults": 2})], seed=0)
    _plan.install(sched)
    fired = [_plan.maybe_inject("worker.exec") is not None for _ in range(10)]
    # hits 1..3 skipped; eligible hits 1.. start at hit 4 -> every=2 fires
    # at eligible 2, 4 == hits 5, 7; max_faults caps it there.
    assert fired == [False, False, False, False, True,
                     False, True, False, False, False]


def test_fault_rule_skip_spec_roundtrip():
    r = FaultRule.from_spec({"site": "worker.exec", "kind": "error",
                             "skip": 9, "every": 4, "max_faults": 2})
    spec = r.to_spec()
    assert spec["skip"] == 9
    assert FaultRule.from_spec(spec).skip == 9
    # zero skip stays off the wire (canonical spec minimalism)
    r0 = FaultRule.from_spec({"site": "worker.exec", "kind": "error", "nth": 1})
    assert "skip" not in r0.to_spec()


# ---------------------------------------------------------------------------
# timeline compilation
# ---------------------------------------------------------------------------

def _fake_records(ts):
    return [{"i": i, "t": t} for i, t in enumerate(ts)]


def test_timeline_compiles_windows_into_hit_space():
    spans = {"calm": (0.0, 4.0), "storm": (4.0, 8.0), "recovery": (8.0, 12.0)}
    # ten arrivals: 3 calm, 4 storm, 3 recovery
    records = _fake_records([0.5, 1.5, 2.5, 4.5, 5.5, 6.5, 7.5, 8.5, 9.5, 10.5])
    tl = Timeline(spans, [
        {"action": "slow_replica_window", "phase": "storm", "delay_s": 0.02,
         "deployment": "DayApp"},
        {"action": "client_flap", "phase": "calm", "every": 2},
        {"action": "tpu_preempt", "phase": "recovery", "offset_s": 1.0,
         "worker_id": "1", "grace_s": 0.3},
        {"action": "publish_weights", "phase": "recovery", "offset_s": 0.5,
         "channel": "w"},
    ])
    compiled = tl.compile(0, records, time_warp=2.0, heartbeat_s=0.5, lead_s=1.0)
    assert isinstance(compiled, CompiledTimeline)
    assert compiled.spans == spans
    by_site = {r["site"]: r for r in compiled.spec["rules"]}
    slow = by_site["serve.replica.slow"]
    assert slow["skip"] == 3              # the calm arrivals
    assert slow["max_faults"] == 4        # the storm arrivals
    assert slow["ctx"] == {"deployment": "DayApp"}
    flap = by_site["replay.request.send"]
    assert flap["skip"] == 0 and flap["every"] == 2
    assert flap["max_faults"] == 1        # 3 calm hits // every 2
    pre = by_site["tpu.preempt"]
    # wall anchor = lead 1.0 + (8.0 + 1.0)/warp 2.0 = 5.5s -> nth = 5.5/0.5
    assert pre["nth"] == 11
    assert pre["ctx"] == {"worker_id": "1"}
    # control actions stay off the fault spec and sort by trace time
    assert [a["action"] for _, a in compiled.control] == ["publish_weights"]
    assert compiled.control[0][0] == 8.5
    # the compiled spec installs cleanly (site/kind validation happened)
    FaultSchedule.from_spec(compiled.spec)


def test_timeline_rejects_unknown_action_and_phase():
    spans = {"calm": (0.0, 1.0)}
    with pytest.raises(ValueError, match="unknown timeline action"):
        Timeline(spans, [{"action": "meteor_strike", "phase": "calm"}])
    with pytest.raises(ValueError, match="unknown phase"):
        Timeline(spans, [{"action": "client_flap", "phase": "rush_hour"}])


def test_timeline_driver_executes_and_records_failures():
    fired = []
    driver = TimelineDriver(
        [(0.0, {"action": "publish_weights", "channel": "w"}),
         (0.2, {"action": "chaos_rule"})],
        {"publish_weights": lambda a: fired.append(a["channel"]) or "ok"},
        time_warp=2.0)
    log = driver.start().join(timeout=10)
    assert fired == ["w"]
    assert [(e["action"], e["ok"]) for e in log] == [
        ("publish_weights", True), ("chaos_rule", False)]
    assert "no handler" in log[1]["detail"]


# ---------------------------------------------------------------------------
# open-loop replayer against a no-op server
# ---------------------------------------------------------------------------

class _NoopHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_POST(self):
        self.rfile.read(int(self.headers.get("content-length", 0)))
        body = b"ok"
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def noop_server():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _NoopHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address[1]
    srv.shutdown()


def test_open_loop_fidelity(noop_server):
    """Against an instant server the replayer must hit its schedule: every
    request lands, arrival error stays small, and the run takes roughly the
    warped trace duration (open loop = the trace sets the clock)."""
    header, records = synthesize(3, duration_s=4.0, base_rps=25.0, tenants=2)
    rp = Replayer(noop_server, time_warp=4.0, max_workers=16)
    t0 = time.perf_counter()
    outcomes = rp.run(header, records)
    elapsed = time.perf_counter() - t0
    assert len(outcomes) == len(records)
    assert all(o["code"] == 200 for o in outcomes)
    assert 0.7 <= elapsed <= 3.0  # ~1s of warped trace time + shutdown slack
    summ = summarize(outcomes, phase_spans(
        {"duration_s": 4.0, "spike_start": 0.35, "spike_end": 0.7}))
    tot = summ["total"]
    assert tot["n"] == len(records) and tot["goodput"] == 1.0
    assert tot["late_p99_s"] < 0.25  # open-loop scheduling error bound
    # streams got a TTFT; phases partition the traffic
    assert tot["ttft_p95_s"] is not None
    phase_n = sum(b["phases"][ph]["n"] for b in summ["classes"].values()
                  for ph in ("calm", "storm", "recovery"))
    assert phase_n == tot["n"]


def test_replayer_chaos_gate_drops_client_side(noop_server):
    """A seeded drop rule on replay.request.send loses the request before
    the wire: code 0 (client_dropped), nothing sent."""
    _plan.install(FaultSchedule([FaultRule.from_spec(
        {"site": "replay.request.send", "kind": "drop", "every": 1})], seed=0))
    rp = Replayer(noop_server)
    rec = {"i": 0, "t": 0.0, "cls": "interactive", "tenant": "t0",
           "route": "/", "size": 8, "stream": 0, "timeout_s": 1.0}
    out = rp._fire(rec, time.perf_counter())
    assert out["code"] == 0
    assert summarize([out])["total"]["client_dropped"] == 1


def test_summarize_buckets_outcomes():
    rows = [
        {"i": 0, "t": 0.1, "cls": "interactive", "tenant": "t0", "stream": 1,
         "code": 200, "latency_s": 0.05, "ttft_s": 0.01, "late_s": 0.001},
        {"i": 1, "t": 0.2, "cls": "interactive", "tenant": "t1", "stream": 0,
         "code": 429, "latency_s": 0.002, "ttft_s": None, "late_s": 0.001},
        {"i": 2, "t": 1.6, "cls": "batch", "tenant": "t0", "stream": 0,
         "code": 504, "latency_s": 0.9, "ttft_s": None, "late_s": 0.002},
        {"i": 3, "t": 1.7, "cls": "batch", "tenant": "t0", "stream": 0,
         "code": -1, "latency_s": 0.0, "ttft_s": None, "late_s": 0.002},
    ]
    s = summarize(rows, {"early": (0.0, 1.0), "late": (1.0, 2.0)})
    assert s["total"]["n"] == 4 and s["total"]["ok"] == 1
    assert s["total"]["shed"] == 1 and s["total"]["expired"] == 1
    assert s["total"]["errors"] == 1
    inter = s["classes"]["interactive"]
    assert inter["_total"]["goodput"] == 0.5
    assert inter["phases"]["early"]["n"] == 2
    assert inter["phases"]["late"]["n"] == 0
    assert set(inter["tenants"]) == {"t0", "t1"}
    assert s["classes"]["batch"]["phases"]["late"]["n"] == 2


# ---------------------------------------------------------------------------
# run ledger: build/gate/diff + the CLI exit codes
# ---------------------------------------------------------------------------

def _baseline_ledger():
    return _ledger.load(str(DATA / "day_in_the_life_seed0.ledger.json"))


def test_committed_ledger_passes_its_own_gates():
    led = _baseline_ledger()
    res = _ledger.gate(led)
    assert res["ok"], res
    assert {c["name"] for c in res["checks"]} >= {
        "interactive_storm_p99", "interactive_storm_goodput",
        "weight_swap_happened", "swap_blip_bounded",
        "burn_trajectory_per_objective"}
    # and it names the trace that produced it
    assert led["meta"]["trace_sha256"] == hashlib.sha256(
        (DATA / "day_in_the_life_seed0.trace.jsonl").read_bytes()).hexdigest()


def test_gate_fails_without_swap_or_on_slow_storm():
    led = _baseline_ledger()
    no_swap = copy.deepcopy(led)
    no_swap["counters"]["ckpt.publish.swaps_total"] = 0
    res = _ledger.gate(no_swap)
    assert not res["ok"]
    assert any(c["name"] == "weight_swap_happened" and not c["ok"]
               for c in res["checks"])
    slow = copy.deepcopy(led)
    slow["load"]["classes"]["interactive"]["phases"]["storm"]["p99_s"] = 9.0
    res = _ledger.gate(slow)
    assert any(c["name"] == "interactive_storm_p99" and not c["ok"]
               for c in res["checks"])


def test_report_diff_trips_on_p99_regression(tmp_path):
    base = _baseline_ledger()
    assert _ledger.diff(base, base)["ok"]  # self-diff is clean
    worse = copy.deepcopy(base)
    storm = worse["load"]["classes"]["interactive"]["phases"]["storm"]
    storm["p99_s"] = storm["p99_s"] * 2 + 0.2  # past both pct and abs margins
    res = _ledger.diff(base, worse)
    assert not res["ok"]
    assert any(r["metric"] == "p99_s" and r["bucket"] == "interactive/storm"
               for r in res["regressions"])
    # tiny wiggles below the absolute margin are NOT regressions
    wiggle = copy.deepcopy(base)
    tot = wiggle["load"]["total"]
    tot["p99_s"] = tot["p99_s"] + 0.01
    assert _ledger.diff(base, wiggle)["ok"]
    # goodput is judged on absolute drop
    starved = copy.deepcopy(base)
    starved["load"]["total"]["goodput"] = base["load"]["total"]["goodput"] - 0.2
    res = _ledger.diff(base, starved)
    assert any(r["metric"] == "goodput" for r in res["regressions"])


def test_report_cli_exit_codes(tmp_path, capsys):
    """`raytpu report diff` is the CI gate: exit 0 clean, 1 on regression."""
    from ray_tpu.__main__ import main

    base_path = str(DATA / "day_in_the_life_seed0.ledger.json")
    worse = copy.deepcopy(_baseline_ledger())
    storm = worse["load"]["classes"]["interactive"]["phases"]["storm"]
    storm["p99_s"] = storm["p99_s"] * 2 + 0.2
    worse_path = str(tmp_path / "worse.json")
    _ledger.save(worse_path, worse)
    with pytest.raises(SystemExit) as e:
        main(["report", "diff", base_path, base_path])
    assert e.value.code == 0
    with pytest.raises(SystemExit) as e:
        main(["report", "diff", base_path, worse_path])
    assert e.value.code == 1
    out = capsys.readouterr().out
    assert "REGRESSION interactive/storm p99_s" in out
    # a tighter threshold flips a clean self... candidate comparison stays
    # clean, but loose overrides relax a tripped one
    with pytest.raises(SystemExit) as e:
        main(["report", "diff", base_path, worse_path,
              "--thresholds", '{"p99_latency_abs_s": 99}'])
    assert e.value.code == 0
    with pytest.raises(SystemExit) as e:
        main(["report", "render", base_path])
    assert e.value.code in (0, None)
    rendered = capsys.readouterr().out
    assert "day_in_the_life" in rendered and "interactive/_total" in rendered
    with pytest.raises(SystemExit) as e:
        main(["report", "gate", base_path])
    assert e.value.code == 0


def test_ledger_build_and_roundtrip(tmp_path):
    led = _ledger.build(
        meta={"scenario": "unit", "seed": 1, "time_warp": 1.0, "requests": 2},
        spans={"calm": (0.0, 1.0)},
        load={"total": {"n": 2, "ok": 2, "goodput": 1.0},
              "classes": {}},
        counters={"ckpt.publish.swaps_total": 1.0})
    path = str(tmp_path / "led.json")
    _ledger.save(path, led)
    again = _ledger.load(path)
    assert again == json.loads(json.dumps(led))  # tuple/list normalization
    assert again["phases"]["calm"] == [0.0, 1.0]
    with pytest.raises(ValueError, match="not a raytpu-report"):
        bad = tmp_path / "bad.json"
        bad.write_text('{"format": "nope"}')
        _ledger.load(str(bad))


# ---------------------------------------------------------------------------
# SLO burn-trajectory history ring
# ---------------------------------------------------------------------------

def test_slo_history_ring_bounded_and_counted():
    tr = SloTracker(Objective(name="u", metric="availability",
                              fast_window_s=1.0, slow_window_s=5.0),
                    max_history=4)
    for i in range(7):
        tr.observe(float(i), good=90.0 + i, total=100.0 + i)
        tr.evaluate(float(i))
    rows = tr.history_rows()
    assert len(rows["points"]) == 4          # ring holds only the tail
    assert rows["dropped"] == 3              # counted trim, not silent
    assert rows["points"][-1]["ts"] == 6.0
    assert {"ts", "burn_fast", "burn_slow", "state"} <= set(rows["points"][0])


def test_slo_engine_history_shape():
    eng = SloEngine()
    eng.register({"name": "avail", "metric": "availability",
                  "fast_window_s": 1.0, "slow_window_s": 5.0})
    series = [
        {"name": "serve.request.latency_s", "tags": {}, "n": 100,
         "buckets": [1.0], "counts": [100]},
        {"name": "serve.request.shed_total", "tags": {}, "value": 50.0},
    ]
    for i in range(3):
        eng.ingest(float(i), series)
    hist = eng.history()
    assert set(hist) == {"avail"}
    assert len(hist["avail"]["points"]) == 3
    assert hist["avail"]["dropped"] == 0
