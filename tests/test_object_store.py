"""Native shared-memory store unit tests (reference analogue:
src/ray/object_manager/plasma tests)."""
import os

import pytest

# Triage-friendly collection: a host without a working g++ (or with a broken
# native toolchain) must SKIP these tests with the compiler error as the
# reason, not explode at collection/fixture time.
try:
    from ray_tpu.core.native.build import build_lib

    build_lib("shm_store")
    _NATIVE_ERR = None
except Exception as e:  # pragma: no cover - toolchain-dependent
    _NATIVE_ERR = f"{type(e).__name__}: {e}"

# Per-test, not module-wide: test_memory_store is pure Python and must keep
# running on toolchain-less hosts.
needs_native = pytest.mark.skipif(
    _NATIVE_ERR is not None, reason=f"native shm store unavailable: {_NATIVE_ERR}"
)

# The module import itself is pure Python (the C library compiles lazily on
# first store construction), so these names are importable either way.
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import (
    SUPPORTS_PEP688,
    MemoryStore,
    ObjectExistsError,
    ObjectStoreFullError,
    SharedMemoryClient,
)


@pytest.fixture
def store(tmp_path):
    path = str(tmp_path / "store")
    s = SharedMemoryClient(path, capacity=4 * 1024 * 1024, create=True)
    yield s
    s.close()


@needs_native
def test_put_get_roundtrip(store):
    oid = ObjectID.from_put()
    data = os.urandom(1000)
    store.put(oid, data)
    assert store.contains(oid)
    assert store.get_copy(oid) == data


@needs_native
def test_create_seal_zero_copy(store):
    oid = ObjectID.from_put()
    buf = store.create(oid, 8)
    buf[:] = b"abcdefgh"
    del buf
    assert not store.contains(oid)  # not sealed yet
    store.seal(oid)
    view = store.get(oid)
    assert bytes(view) == b"abcdefgh"
    view.release()
    store.release(oid)


@needs_native
def test_duplicate_create_raises(store):
    oid = ObjectID.from_put()
    store.put(oid, b"x")
    with pytest.raises(ObjectExistsError):
        store.create(oid, 1)


@needs_native
def test_delete(store):
    oid = ObjectID.from_put()
    store.put(oid, b"x")
    assert store.delete(oid)
    assert not store.contains(oid)
    assert store.get(oid) is None


@needs_native
def test_pinned_object_not_deleted(store):
    oid = ObjectID.from_put()
    store.put(oid, b"hello")
    view = store.get(oid)  # pins
    assert not store.delete(oid)
    view.release()
    store.release(oid)
    assert store.delete(oid)


@needs_native
def test_lru_eviction_under_pressure(store):
    oids = []
    for _ in range(8):
        oid = ObjectID.from_put()
        store.put(oid, os.urandom(700 * 1024))
        oids.append(oid)
    # 8 * 700KB > 4MB: the oldest objects must have been evicted.
    assert store.num_objects < 8
    assert store.contains(oids[-1])
    assert not store.contains(oids[0])


@needs_native
def test_pinned_objects_survive_eviction(store):
    first = ObjectID.from_put()
    store.put(first, os.urandom(700 * 1024))
    view = store.get(first)  # pin
    for _ in range(8):
        store.put(ObjectID.from_put(), os.urandom(400 * 1024))
    assert store.contains(first)
    view.release()
    store.release(first)


@needs_native
def test_oversize_object_rejected(store):
    with pytest.raises(ObjectStoreFullError):
        store.put(ObjectID.from_put(), b"x" * (8 * 1024 * 1024))


@needs_native
def test_cross_client_visibility(store, tmp_path):
    other = SharedMemoryClient(str(tmp_path / "store"))
    oid = ObjectID.from_put()
    store.put(oid, b"shared")
    assert other.get_copy(oid) == b"shared"
    other.close()


@needs_native
def test_free_list_reuse(store):
    # Fill, delete, refill — allocator must reuse space (coalescing).
    for _ in range(3):
        oids = []
        for _ in range(4):
            oid = ObjectID.from_put()
            store.put(oid, os.urandom(900 * 1024))
            oids.append(oid)
        for oid in oids:
            store.delete(oid)
    assert store.used < 100 * 1024


def test_memory_store():
    ms = MemoryStore()
    oid = ObjectID.from_put()
    ms.put(oid, b"v")
    assert ms.contains(oid)
    assert ms.get(oid) == b"v"
    ms.delete(oid)
    assert not ms.contains(oid)


@pytest.mark.skipif(
    not SUPPORTS_PEP688,
    reason="zero-copy pinned reads need PEP 688 (__buffer__), Python 3.12+; "
    "pre-3.12 interpreters read shm objects through a safe copy instead",
)
@needs_native
def test_pinned_buffer_zero_copy_get():
    """get() of a big ndarray views the arena zero-copy: the array is
    read-only, the object stays pinned (undeletable) while the array lives,
    and the pin drops when the array is collected."""
    import gc

    import numpy as np

    import ray_tpu as rt
    from ray_tpu.core import api as _api

    rt.init(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    try:
        src = np.arange(1 << 20, dtype=np.int64)  # 8MB, well over inline cap
        ref = rt.put(src)
        arr = rt.get(ref, timeout=60)
        np.testing.assert_array_equal(arr, src)
        assert not arr.flags.writeable  # shared pages must be read-only
        store = _api._require_worker().store
        assert store is not None
        # Pinned by the live view: delete must refuse.
        assert not store.delete(ref.id)
        del arr
        gc.collect()
        assert store.delete(ref.id)  # pin dropped with the last view
    finally:
        rt.shutdown()


@needs_native
def test_big_object_get_any_interpreter():
    """Value correctness of a big shm-object get on EVERY interpreter: on
    3.12+ the read is a zero-copy pinned view; pre-3.12 it degrades to a
    safe copy (deserialize's PinnedBuffer fallback) — either way the bytes
    must round-trip."""
    import numpy as np

    import ray_tpu as rt

    rt.init(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    try:
        src = np.arange(1 << 20, dtype=np.int64)  # 8MB, well over inline cap
        ref = rt.put(src)
        np.testing.assert_array_equal(rt.get(ref, timeout=60), src)
    finally:
        rt.shutdown()
