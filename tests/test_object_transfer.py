"""Streaming object transfer plane: rpc raw-frame lane + node PullManager.

Raw lane (WIRE_VERSION 3): a frame carrying a small pickled header plus an
out-of-band binary payload that is NEVER pickled — the sender writes arena
memoryview slices straight to the transport and the receiver recv's into a
pre-registered destination buffer; keyed-BLAKE2b is verified on the header
before it reaches pickle and streamed over header+payload for the chunk.
PullManager: a window of K chunks in flight per object, chunk ranges striped
across replicas, per-chunk failover to alternate sources, global admission
(max concurrent pulls / max inflight bytes), and same-oid coalescing.
"""
import asyncio
import hashlib
import hmac
import os
import pickle

import pytest

from ray_tpu.core import rpc
from ray_tpu.core.ids import ObjectID


@pytest.fixture(autouse=True)
def _no_token_leak():
    yield
    rpc.set_auth_token(None)


class _ChunkServer:
    """Minimal raw-lane source: serves slices of one payload."""

    def __init__(self, payload: bytes):
        self.payload = payload
        self.requests = 0

    async def handle_fetch(self, conn, p):
        self.requests += 1
        await conn.send_raw(p["key"], memoryview(self.payload)[p["off"] : p["off"] + p["ln"]])
        return True


# ---------------------------------------------------------------------------
# raw lane wire-level tests
# ---------------------------------------------------------------------------


def test_raw_frame_roundtrip_interleaved_with_envelopes():
    """Chunks ride the raw lane while normal calls keep flowing on the same
    connection; the reassembled payload is byte-identical and was never
    pickled by the sender."""

    async def go():
        payload = os.urandom(3 * 1024 * 1024 + 17)
        srv = _ChunkServer(payload)
        server = rpc.RpcServer(srv)
        await server.start()
        conn = await rpc.connect(server.address)
        try:
            dest = bytearray(len(payload))
            view = memoryview(dest)
            chunk = 512 * 1024
            for off in range(0, len(payload), chunk):
                ln = min(chunk, len(payload) - off)
                key = os.urandom(12)
                fut = conn.expect_raw(key, view[off : off + ln])
                assert await conn.call("fetch", {"key": key, "off": off, "ln": ln}, timeout=30)
                assert await asyncio.wait_for(fut, 30) is True
                # control plane stays live mid-transfer
                assert await conn.call("fetch", {"key": os.urandom(12), "off": 0, "ln": 1}, timeout=30)
            assert bytes(dest) == payload
        finally:
            await conn.close()
            await server.close()

    asyncio.run(go())


def _build_raw_frame(key: bytes, payload: bytes, token_key: bytes,
                     tamper_payload: bool = False, tamper_header: bool = False) -> bytes:
    """Hand-build a raw-lane frame byte-for-byte (the test's independent
    encoder: must match rpc.send_raw's layout)."""
    hdr = pickle.dumps((key, len(payload)), protocol=5)
    body = bytearray()
    body += bytes([rpc._RAW_MARKER])
    htag = hashlib.blake2b(rpc._RAW_HDR_DOMAIN + hdr, key=token_key, digest_size=rpc.FRAME_TAG_LEN).digest()
    h = hmac.new(token_key, None, hashlib.sha256)  # bulk-lane payload MAC
    h.update(hdr)
    h.update(payload)
    ptag = h.digest()[: rpc.FRAME_TAG_LEN]
    if tamper_header:
        hdr = bytearray(hdr)
        hdr[-1] ^= 0xFF
        hdr = bytes(hdr)
    if tamper_payload:
        payload = bytearray(payload)
        payload[len(payload) // 2] ^= 0x01
        payload = bytes(payload)
    body += htag
    body += len(hdr).to_bytes(4, "little")
    body += hdr
    body += payload
    body += ptag
    return len(body).to_bytes(8, "little") + bytes(body)


def test_raw_frame_mac_tamper_and_truncation():
    """A flipped payload bit fails the streamed MAC: the chunk is never
    acked and the peer is dropped. A tampered header is rejected BEFORE the
    header reaches pickle. A mid-payload disconnect (truncation) resolves
    the chunk future False instead of hanging."""

    async def go():
        rpc.set_auth_token("transfer-tamper-test")
        token_key = rpc.get_auth_token()
        payload = os.urandom(256 * 1024)

        async def run_case(tamper_payload=False, tamper_header=False, truncate=False):
            client_conn = {}
            accepted = asyncio.Event()

            async def on_sock(reader, writer):
                client_conn["rw"] = (reader, writer)
                accepted.set()

            fake_src = await asyncio.start_server(on_sock, "127.0.0.1", 0)
            addr = "127.0.0.1:%d" % fake_src.sockets[0].getsockname()[1]
            conn = await rpc.connect(addr)
            await accepted.wait()
            _, w = client_conn["rw"]
            key = os.urandom(12)
            dest = bytearray(len(payload))
            fut = conn.expect_raw(key, memoryview(dest))
            loads_before = _LOADS[0]
            frame = _build_raw_frame(key, payload, token_key,
                                     tamper_payload=tamper_payload, tamper_header=tamper_header)
            if truncate:
                frame = frame[: len(frame) // 2]
            w.write(frame)
            await w.drain()
            if truncate:
                w.close()
            landed = await asyncio.wait_for(fut, 30)
            assert landed is False
            # tampered/truncated source is dropped
            for _ in range(100):
                if conn.closed:
                    break
                await asyncio.sleep(0.02)
            assert conn.closed
            if tamper_header:
                # the garbled header never reached pickle.loads
                assert _LOADS[0] == loads_before
            fake_src.close()

        # Count pickle.loads calls inside rpc to prove pre-pickle rejection.
        _LOADS = [0]
        real_loads = rpc.pickle.loads

        class _CountingPickle:
            def __getattr__(self, name):
                return getattr(pickle, name)

            @staticmethod
            def loads(*a, **kw):
                _LOADS[0] += 1
                return real_loads(*a, **kw)

        rpc.pickle, saved = _CountingPickle(), rpc.pickle
        try:
            await run_case(tamper_payload=True)
            await run_case(tamper_header=True)
            await run_case(truncate=True)
        finally:
            rpc.pickle = saved

    asyncio.run(go())


def test_wire_version_mismatch_rejected():
    """WIRE_VERSION is 3 (raw lane generation): a v2 frame — what a PR-1
    build would send — is refused before any byte reaches pickle, and the
    peer is dropped."""
    assert rpc.WIRE_VERSION == 3

    class Echo:
        def handle_echo(self, conn, p):
            return p

    async def go():
        server = rpc.RpcServer(Echo())
        await server.start()
        reader, writer = await asyncio.open_connection(server.host, server.port)
        body = pickle.dumps((0, 1, "echo", "old-build"), protocol=5)
        frame = bytes([2]) + body  # v2 layout: version byte + pickle
        writer.write(len(frame).to_bytes(8, "little") + frame)
        await writer.drain()
        assert await reader.read(100) == b""  # server hung up on us
        writer.close()
        await server.close()

    asyncio.run(go())


# ---------------------------------------------------------------------------
# PullManager tests (daemon-level, in-process cluster)
# ---------------------------------------------------------------------------


def _seed_object(daemon, payload: bytes) -> ObjectID:
    oid = ObjectID.from_put()
    daemon.store.put(oid, payload)
    return oid


def _locs(*daemons):
    return [{"node_id": d.node_id, "address": d.address} for d in daemons]


def test_windowed_pull_with_eviction_pressure(fresh_cluster):
    """Pull an object larger than the destination arena's free space: the
    windowed transfer lands, auto-evicting residents, and the payload is
    byte-identical."""
    cluster = fresh_cluster
    a = cluster.add_node(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    b = cluster.add_node(num_cpus=1, object_store_memory=24 * 1024 * 1024)
    payload = os.urandom(16 * 1024 * 1024 + 321)
    oid = _seed_object(a, payload)
    # Fill most of B's arena so the pull must evict.
    for _ in range(2):
        b.store.put(ObjectID.from_put(), os.urandom(8 * 1024 * 1024))
    assert cluster.host.call(b.pull_manager.pull(oid, _locs(a)))
    assert b.store.get_copy(oid) == payload
    cs = b.config.pull_chunk_size
    assert b.pull_manager.last_pull["chunks"] == (len(payload) + cs - 1) // cs
    assert b.pull_manager.bytes_in == len(payload)
    assert a.pull_manager.bytes_out == len(payload)


def test_multi_source_failover_mid_object(fresh_cluster):
    """Stripe across two replicas; one replica dies after serving k chunks —
    its remaining chunks fail over to the surviving replica and the object
    still verifies byte-identical."""
    cluster = fresh_cluster
    a = cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    c = cluster.add_node(num_cpus=1)
    c.config.pull_chunk_size = 1024 * 1024  # 13 chunks: failure lands mid-object
    c.config.raw_mac_granularity = "chunk"  # per-chunk striping/failover is what's under test
    payload = os.urandom(12 * 1024 * 1024 + 7)
    oid = _seed_object(a, payload)
    # replicate A -> B so C has two sources
    assert cluster.host.call(b.pull_manager.pull(oid, _locs(a)))

    served = [0]
    orig = a.handle_read_object_chunk_raw

    async def dies_after_two(conn, p):
        served[0] += 1
        if served[0] > 2:
            raise RuntimeError("replica A died mid-object")
        return await orig(conn, p)

    a.handle_read_object_chunk_raw = dies_after_two
    assert cluster.host.call(c.pull_manager.pull(oid, _locs(a, b)), timeout=120)
    assert c.store.get_copy(oid) == payload
    assert c.pull_manager.chunks_retried > 0
    assert c.pull_manager.last_pull["sources"] == 2


def test_concurrent_pulls_coalesce(fresh_cluster):
    """Two concurrent pulls of one oid ride ONE transfer: the source serves
    each chunk exactly once."""
    cluster = fresh_cluster
    a = cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    b.config.pull_chunk_size = 1024 * 1024
    b.config.raw_mac_granularity = "chunk"  # count per-chunk serves
    payload = os.urandom(6 * 1024 * 1024)
    oid = _seed_object(a, payload)

    served = [0]
    orig = a.handle_read_object_chunk_raw

    async def counting(conn, p):
        served[0] += 1
        return await orig(conn, p)

    a.handle_read_object_chunk_raw = counting

    async def both():
        return await asyncio.gather(
            b.pull_manager.pull(oid, _locs(a)),
            b.pull_manager.pull(oid, _locs(a)),
        )

    assert cluster.host.call(both()) == [True, True]
    assert served[0] == 6  # 6 x 1MiB chunks, no duplicate chunk requests
    assert b.store.get_copy(oid) == payload


def test_admission_inflight_byte_cap(fresh_cluster):
    """Pulls admit chunks against the global inflight-bytes budget: with a
    2-chunk budget and an 8-chunk window, inflight bytes never exceed the
    cap and the pull still completes."""
    cluster = fresh_cluster
    a = cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    payload = os.urandom(8 * 1024 * 1024)
    oid = _seed_object(a, payload)
    b.config.pull_chunk_size = 1024 * 1024
    budget = 2 * b.config.pull_chunk_size
    b.config.max_inflight_pull_bytes = budget
    pm = b.pull_manager
    peak = [0]
    orig_acquire = pm._acquire_bytes

    async def tracking(n):
        await orig_acquire(n)
        peak[0] = max(peak[0], pm._inflight_bytes)

    pm._acquire_bytes = tracking
    assert cluster.host.call(pm.pull(oid, _locs(a)), timeout=120)
    assert 0 < peak[0] <= budget
    assert b.store.get_copy(oid) == payload


def test_peer_connection_reuse(fresh_cluster):
    """Back-to-back pulls from one source reuse a single cached peer
    connection instead of dialing per object."""
    cluster = fresh_cluster
    a = cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    for _ in range(3):
        oid = _seed_object(a, os.urandom(2 * 1024 * 1024))
        assert cluster.host.call(b.pull_manager.pull(oid, _locs(a)))
    assert len(b._peer_conns) == 1
    assert b.pull_manager.pulls_ok == 3


def test_spilled_source_streams_with_single_open(fresh_cluster):
    """A spilled source object streams through the same raw lane; the spill
    file is opened once per transfer session (pread per chunk), not once per
    chunk."""
    cluster = fresh_cluster
    spill = "/tmp/raytpu_test_spill_%d" % os.getpid()
    a = cluster.add_node(num_cpus=1, object_store_memory=24 * 1024 * 1024)
    b = cluster.add_node(num_cpus=1)
    a.store.spill_dir = spill
    payload = os.urandom(6 * 1024 * 1024)
    oid = _seed_object(a, payload)
    assert a.store.spill(a.store.capacity)  # push everything unpinned to disk
    assert not a.store.contains(oid) and a.store.is_spilled(oid)
    # Arena is big enough: the source restores once and streams from the
    # arena. Shrink the restore path away by filling the arena with pinned
    # objects? Simpler: verify the pull works and, when the restore path was
    # taken, the object is resident again.
    opens = [0]
    real_open = os.open

    def counting_open(path, *a_, **kw):
        if isinstance(path, str) and path.startswith(spill):
            opens[0] += 1
        return real_open(path, *a_, **kw)

    os.open, saved = counting_open, os.open
    try:
        assert cluster.host.call(b.pull_manager.pull(oid, _locs(a)))
    finally:
        os.open = saved
    assert b.store.get_copy(oid) == payload
    # restore-once (arena had room) or fd-cache (arena full): either way the
    # spill file was opened at most once by the transfer.
    assert opens[0] <= 1


def test_pull_failure_when_no_source(fresh_cluster):
    cluster = fresh_cluster
    cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    oid = ObjectID.from_put()
    assert cluster.host.call(b.pull_manager.pull(oid, [])) is False
    assert b.pull_manager.pulls_failed >= 0  # no crash; nothing partial left
    assert not b.store.contains(oid)


def test_failed_pull_aborts_cleanly_and_oid_stays_pullable(fresh_cluster):
    """A pull that dies mid-transfer (every source lost) must abort its
    created-but-unsealed arena entry: a plain delete refuses the writer pin,
    which would leak the allocation AND poison the oid — every later pull
    of the same object on this node would raise ObjectExistsError forever."""
    cluster = fresh_cluster
    a = cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    payload = os.urandom(9 * 1024 * 1024 + 7)
    oid = _seed_object(a, payload)
    b.config.raw_mac_granularity = "chunk"  # the sabotaged handler is the per-chunk one

    async def fail_then_recover():
        # Sabotage: every chunk read on A explodes after the probe, so the
        # transfer starts (arena entry created on B) and then loses all
        # sources mid-object.
        orig = type(a).handle_read_object_chunk_raw

        async def boom(self, conn, p):
            raise RuntimeError("source lost mid-transfer")

        type(a).handle_read_object_chunk_raw = boom
        try:
            assert not await b.pull_manager.pull(oid, _locs(a))
        finally:
            type(a).handle_read_object_chunk_raw = orig
        assert not b.store.contains(oid), "failed pull left a partial object"
        used_after_fail = b.store.used
        # The source comes back healthy: the SAME oid must pull cleanly
        # (no ObjectExistsError poison, no leaked allocation).
        assert await b.pull_manager.pull(oid, _locs(a))
        assert b.store.get_copy(oid) == payload
        assert b.store.used >= used_after_fail  # sanity: the object landed

    cluster.host.call(fail_then_recover())


def test_get_owned_promotes_oversized_inline(fresh_cluster):
    """A memory-store object above object_chunk_size is promoted to the shm
    arena when a borrower asks for it, so the borrower takes the streaming
    pull path instead of receiving megabytes pickled inside one RPC reply."""
    import numpy as np

    import ray_tpu as rt
    from ray_tpu.core import api as _api

    cluster = fresh_cluster
    # Inline cap above chunk size: task returns up to 4 MiB stay in the
    # owner's memory store — the configuration the promotion path exists for.
    cluster.config.max_inline_object_size = 4 * 1024 * 1024
    cluster.add_node(num_cpus=2)
    b = cluster.add_node(num_cpus=2, resources={"borrower": 2.0})
    rt.init(address=cluster.address)
    try:
        src = np.arange((2 * 1024 * 1024) // 8, dtype=np.int64)  # 2 MiB
        # rt.put of a big value goes straight to shm; the memory-store case
        # is a task RETURN under the raised inline cap:
        @rt.remote
        def make():
            return np.arange((2 * 1024 * 1024) // 8, dtype=np.int64)

        ref = make.remote()
        rt.wait([ref], num_returns=1, timeout=60)
        core = _api._require_worker()
        assert core.memory_store.get(ref.id) is not None, "test premise: object lives in memory store"

        @rt.remote(resources={"borrower": 1.0})
        def consume(arr):
            return int(arr.sum())

        assert rt.get(consume.remote(ref), timeout=60) == int(src.sum())
        # the owner promoted it into the head node's arena
        assert any(d.store.contains(ref.id) for d in cluster.daemons)
    finally:
        rt.shutdown()


# ---------------------------------------------------------------------------
# pickle-bypass proof at the cluster level
# ---------------------------------------------------------------------------


def test_chunk_payloads_bypass_pickle(fresh_cluster):
    """During a cross-node pull of an 8 MiB object, no pickle.dumps result in
    this process (driver + both daemons) approaches chunk size, and no
    payload-sized bytes object materializes through StreamReader.readexactly
    — the chunks move as raw frames straight into the arena."""
    cluster = fresh_cluster
    a = cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    payload = os.urandom(8 * 1024 * 1024)
    oid = _seed_object(a, payload)

    max_dump = [0]
    real_dumps = pickle.dumps

    class _ShimPickle:
        def __getattr__(self, name):
            return getattr(pickle, name)

        @staticmethod
        def dumps(obj, *a_, **kw):
            data = real_dumps(obj, *a_, **kw)
            max_dump[0] = max(max_dump[0], len(data))
            return data

    big_reads = [0]
    real_readexactly = asyncio.StreamReader.readexactly

    async def counting_readexactly(self, n):
        if n >= 256 * 1024:
            big_reads[0] += 1
        return await real_readexactly(self, n)

    rpc.pickle, saved = _ShimPickle(), rpc.pickle
    asyncio.StreamReader.readexactly = counting_readexactly
    try:
        assert cluster.host.call(b.pull_manager.pull(oid, _locs(a)), timeout=120)
    finally:
        rpc.pickle = saved
        asyncio.StreamReader.readexactly = real_readexactly
    assert b.store.get_copy(oid) == payload
    chunk = b.config.object_chunk_size
    assert max_dump[0] < chunk // 2, f"a chunk-sized pickle happened ({max_dump[0]} bytes)"
    assert big_reads[0] == 0, "payload bytes materialized through readexactly"
