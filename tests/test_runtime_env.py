"""Runtime environments: env_vars, working_dir, py_modules on tasks/actors,
idle-pool isolation by env hash. Reference analogue:
python/ray/tests/test_runtime_env*.py (working_dir upload, env_vars
propagation, per-env worker reuse)."""
import os

import pytest

import ray_tpu as rt


def test_env_vars_on_task(shared_ray):
    @rt.remote(runtime_env={"env_vars": {"MY_FLAG": "hello-42"}})
    def read_flag():
        return os.environ.get("MY_FLAG")

    assert rt.get(read_flag.remote(), timeout=120) == "hello-42"

    # A plain task must NOT see the env var (pool isolation by env hash).
    @rt.remote
    def read_plain():
        return os.environ.get("MY_FLAG")

    assert rt.get(read_plain.remote(), timeout=120) is None


def test_working_dir_ships_code_and_cwd(shared_ray, tmp_path):
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "helper_mod_xyz.py").write_text("VALUE = 1234\n")
    (proj / "data.txt").write_text("payload!")

    @rt.remote(runtime_env={"working_dir": str(proj)})
    def use_workdir():
        import helper_mod_xyz  # importable from the shipped dir

        with open("data.txt") as f:  # cwd == extracted working_dir
            return helper_mod_xyz.VALUE, f.read()

    value, data = rt.get(use_workdir.remote(), timeout=120)
    assert value == 1234 and data == "payload!"


def test_py_modules_on_actor(shared_ray, tmp_path):
    mod_dir = tmp_path / "libs"
    (mod_dir / "shipped_pkg_abc").mkdir(parents=True)
    (mod_dir / "shipped_pkg_abc" / "__init__.py").write_text("NAME = 'shipped'\n")

    @rt.remote(runtime_env={"py_modules": [str(mod_dir)]})
    class Uses:
        def get(self):
            import shipped_pkg_abc

            return shipped_pkg_abc.NAME

    a = Uses.remote()
    assert rt.get(a.get.remote(), timeout=120) == "shipped"
    rt.kill(a)


def test_unknown_key_rejected(shared_ray):
    @rt.remote(runtime_env={"conda": "env"})
    def f():
        return 1

    with pytest.raises(ValueError, match="unsupported runtime_env"):
        f.remote()


def _write_pkg(root, name, version):
    """A minimal installable package exposing conflictlib.__version__."""
    import os

    pkg = os.path.join(str(root), f"{name}_v{version.replace('.', '_')}")
    os.makedirs(os.path.join(pkg, "conflictlib"), exist_ok=True)
    with open(os.path.join(pkg, "pyproject.toml"), "w") as f:
        f.write(
            "[build-system]\nrequires = []\nbuild-backend = 'setuptools.build_meta'\n"
            f"[project]\nname = 'conflictlib'\nversion = '{version}'\n"
        )
    with open(os.path.join(pkg, "conflictlib", "__init__.py"), "w") as f:
        f.write(f"__version__ = {version!r}\n")
    with open(os.path.join(pkg, "setup.py"), "w") as f:
        f.write(
            "from setuptools import setup\n"
            f"setup(name='conflictlib', version={version!r}, packages=['conflictlib'])\n"
        )
    return pkg


def test_pip_venv_isolation_and_cache(shared_ray, tmp_path):
    """Two actors with CONFLICTING package versions coexist on one cluster
    (each runs from its own cached venv — reference: _private/runtime_env/
    pip.py + uri_cache.py); a second use of the same env hits the venv cache
    (no rebuild)."""
    import glob
    import os

    import ray_tpu as rt

    p1 = _write_pkg(tmp_path, "conflictlib", "1.0")
    p2 = _write_pkg(tmp_path, "conflictlib", "2.0")
    opts = ["--no-index", "--no-build-isolation"]  # zero-egress host

    @rt.remote
    class Probe:
        def version(self):
            import conflictlib

            return conflictlib.__version__

    a1 = Probe.options(runtime_env={"pip": [p1], "pip_install_options": opts}).remote()
    a2 = Probe.options(runtime_env={"pip": [p2], "pip_install_options": opts}).remote()
    # Concurrent: both alive at once, each seeing ITS version.
    v1 = rt.get(a1.version.remote(), timeout=300)
    v2 = rt.get(a2.version.remote(), timeout=300)
    assert (v1, v2) == ("1.0", "2.0")
    # Venvs were built once each, content-hash keyed.
    venv_dirs = glob.glob("/tmp/raytpu_*/runtime_envs/venvs/*")
    assert len({os.path.basename(d) for d in venv_dirs}) >= 2

    # Cache hit: a THIRD actor with the same env reuses the built venv (fast
    # path returns the existing python; no .tmp build dir appears).
    before = set(glob.glob("/tmp/raytpu_*/runtime_envs/venvs/*"))
    a3 = Probe.options(runtime_env={"pip": [p1], "pip_install_options": opts}).remote()
    assert rt.get(a3.version.remote(), timeout=300) == "1.0"
    after = set(glob.glob("/tmp/raytpu_*/runtime_envs/venvs/*"))
    assert after == before, "same env rebuilt instead of cache hit"
    for a in (a1, a2, a3):
        rt.kill(a)
