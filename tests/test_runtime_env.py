"""Runtime environments: env_vars, working_dir, py_modules on tasks/actors,
idle-pool isolation by env hash. Reference analogue:
python/ray/tests/test_runtime_env*.py (working_dir upload, env_vars
propagation, per-env worker reuse)."""
import os

import pytest

import ray_tpu as rt


def test_env_vars_on_task(shared_ray):
    @rt.remote(runtime_env={"env_vars": {"MY_FLAG": "hello-42"}})
    def read_flag():
        return os.environ.get("MY_FLAG")

    assert rt.get(read_flag.remote(), timeout=120) == "hello-42"

    # A plain task must NOT see the env var (pool isolation by env hash).
    @rt.remote
    def read_plain():
        return os.environ.get("MY_FLAG")

    assert rt.get(read_plain.remote(), timeout=120) is None


def test_working_dir_ships_code_and_cwd(shared_ray, tmp_path):
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "helper_mod_xyz.py").write_text("VALUE = 1234\n")
    (proj / "data.txt").write_text("payload!")

    @rt.remote(runtime_env={"working_dir": str(proj)})
    def use_workdir():
        import helper_mod_xyz  # importable from the shipped dir

        with open("data.txt") as f:  # cwd == extracted working_dir
            return helper_mod_xyz.VALUE, f.read()

    value, data = rt.get(use_workdir.remote(), timeout=120)
    assert value == 1234 and data == "payload!"


def test_py_modules_on_actor(shared_ray, tmp_path):
    mod_dir = tmp_path / "libs"
    (mod_dir / "shipped_pkg_abc").mkdir(parents=True)
    (mod_dir / "shipped_pkg_abc" / "__init__.py").write_text("NAME = 'shipped'\n")

    @rt.remote(runtime_env={"py_modules": [str(mod_dir)]})
    class Uses:
        def get(self):
            import shipped_pkg_abc

            return shipped_pkg_abc.NAME

    a = Uses.remote()
    assert rt.get(a.get.remote(), timeout=120) == "shipped"
    rt.kill(a)


def test_unknown_key_rejected(shared_ray):
    @rt.remote(runtime_env={"docker_image": "x"})  # not a supported key
    def f():
        return 1

    with pytest.raises(ValueError, match="unsupported runtime_env"):
        f.remote()


def _write_pkg(root, name, version):
    """A minimal installable package exposing conflictlib.__version__."""
    import os

    pkg = os.path.join(str(root), f"{name}_v{version.replace('.', '_')}")
    os.makedirs(os.path.join(pkg, "conflictlib"), exist_ok=True)
    with open(os.path.join(pkg, "pyproject.toml"), "w") as f:
        f.write(
            "[build-system]\nrequires = []\nbuild-backend = 'setuptools.build_meta'\n"
            f"[project]\nname = 'conflictlib'\nversion = '{version}'\n"
        )
    with open(os.path.join(pkg, "conflictlib", "__init__.py"), "w") as f:
        f.write(f"__version__ = {version!r}\n")
    with open(os.path.join(pkg, "setup.py"), "w") as f:
        f.write(
            "from setuptools import setup\n"
            f"setup(name='conflictlib', version={version!r}, packages=['conflictlib'])\n"
        )
    return pkg


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_pip_venv_isolation_and_cache(shared_ray, tmp_path):
    """Two actors with CONFLICTING package versions coexist on one cluster
    (each runs from its own cached venv — reference: _private/runtime_env/
    pip.py + uri_cache.py); a second use of the same env hits the venv cache
    (no rebuild)."""
    import glob
    import os

    import ray_tpu as rt

    p1 = _write_pkg(tmp_path, "conflictlib", "1.0")
    p2 = _write_pkg(tmp_path, "conflictlib", "2.0")
    opts = ["--no-index", "--no-build-isolation"]  # zero-egress host

    @rt.remote
    class Probe:
        def version(self):
            import conflictlib

            return conflictlib.__version__

    a1 = Probe.options(runtime_env={"pip": [p1], "pip_install_options": opts}).remote()
    a2 = Probe.options(runtime_env={"pip": [p2], "pip_install_options": opts}).remote()
    # Concurrent: both alive at once, each seeing ITS version.
    v1 = rt.get(a1.version.remote(), timeout=300)
    v2 = rt.get(a2.version.remote(), timeout=300)
    assert (v1, v2) == ("1.0", "2.0")
    # Venvs were built once each, content-hash keyed.
    venv_dirs = glob.glob("/tmp/raytpu_*/runtime_envs/venvs/*")
    assert len({os.path.basename(d) for d in venv_dirs}) >= 2

    # Cache hit: a THIRD actor with the same env reuses the built venv (fast
    # path returns the existing python; no .tmp build dir appears).
    before = set(glob.glob("/tmp/raytpu_*/runtime_envs/venvs/*"))
    a3 = Probe.options(runtime_env={"pip": [p1], "pip_install_options": opts}).remote()
    assert rt.get(a3.version.remote(), timeout=300) == "1.0"
    after = set(glob.glob("/tmp/raytpu_*/runtime_envs/venvs/*"))
    assert after == before, "same env rebuilt instead of cache hit"
    for a in (a1, a2, a3):
        rt.kill(a)


# ---------------------------------------------------------------------------
# conda + container (reference: _private/runtime_env/conda.py, image_uri.py)
# ---------------------------------------------------------------------------

def _write_fake_conda(tmp_path):
    """A fake conda binary implementing the two subcommands the backend
    uses: `info --base` and `env create -y -p DIR -f FILE`. The created
    "env" is a dir whose bin/python symlinks this interpreter and which
    drops a marker module on the env's path — enough to prove the worker
    really ran on the env's interpreter."""
    import stat
    import sys

    base = tmp_path / "conda_base"
    (base / "envs" / "named-env" / "bin").mkdir(parents=True)
    named_py = base / "envs" / "named-env" / "bin" / "python"
    named_py.symlink_to(sys.executable)
    script = tmp_path / "conda"
    script.write_text(f"""#!/bin/bash
set -e
if [ "$1" == "info" ]; then echo "{base}"; exit 0; fi
if [ "$1" == "env" ] && [ "$2" == "create" ]; then
  # args: env create -y -p DIR -f FILE
  while [ $# -gt 0 ]; do
    case "$1" in
      -p) DIR="$2"; shift 2;;
      -f) FILE="$2"; shift 2;;
      *) shift;;
    esac
  done
  mkdir -p "$DIR/bin"
  ln -s "{sys.executable}" "$DIR/bin/python"
  cp "$FILE" "$DIR/env.yml"
  exit 0
fi
echo "unexpected conda invocation: $@" >&2; exit 2
""")
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    return script, base


def test_conda_named_env_runs_worker(shared_ray, tmp_path, monkeypatch):
    script, base = _write_fake_conda(tmp_path)
    monkeypatch.setenv("RAYTPU_CONDA_EXE", str(script))

    @rt.remote(runtime_env={"conda": "named-env"})
    def which_python():
        import sys

        return sys.executable

    exe = rt.get(which_python.remote(), timeout=120)
    assert "named-env" in exe, exe


def test_conda_dict_env_created_once_and_cached(shared_ray, tmp_path, monkeypatch):
    import glob

    script, _ = _write_fake_conda(tmp_path)
    monkeypatch.setenv("RAYTPU_CONDA_EXE", str(script))
    env_spec = {"conda": {"name": "job-env", "channels": ["conda-forge"],
                          "dependencies": ["python=3.12", {"pip": ["left-pad==1.0"]}]}}

    @rt.remote(runtime_env=env_spec)
    def which_python():
        import sys

        return sys.executable

    exe = rt.get(which_python.remote(), timeout=120)
    assert "/conda/" in exe, exe
    env_dir = os.path.dirname(os.path.dirname(exe))
    # The environment.yml really reached conda (spec round-tripped).
    yml = open(os.path.join(env_dir, "env.yml")).read()
    assert "job-env" in yml and "conda-forge" in yml and "left-pad==1.0" in yml
    # Cache: a second task with the SAME spec reuses the env (no new dirs).
    before = set(glob.glob("/tmp/raytpu_*/runtime_envs/conda/*"))
    assert rt.get(rt.remote(lambda: 1).options(runtime_env=env_spec).remote(), timeout=120) == 1
    assert set(glob.glob("/tmp/raytpu_*/runtime_envs/conda/*")) == before


def test_conda_missing_binary_errors_cleanly(shared_ray, monkeypatch):
    monkeypatch.setenv("RAYTPU_CONDA_EXE", "/nonexistent/conda")
    monkeypatch.setenv("PATH", "/usr/bin:/bin")  # no real conda either

    @rt.remote(runtime_env={"conda": "whatever"})
    def f():
        return 1

    with pytest.raises(Exception, match="conda"):
        rt.get(f.remote(), timeout=120)


def test_conda_and_pip_rejected(shared_ray):
    @rt.remote(runtime_env={"conda": "x", "pip": ["y"]})
    def f():
        return 1

    with pytest.raises(ValueError, match="conda"):
        f.remote()


def test_container_with_pip_or_conda_rejected(shared_ray):
    """The worker runs the image's interpreter; a host-built venv/conda env
    would be silently ignored — reject the combination up front."""
    for extra in ({"pip": ["x"]}, {"conda": "y"}):
        @rt.remote(runtime_env={"container": {"image": "img"}, **extra})
        def f():
            return 1

        with pytest.raises(ValueError, match="container"):
            f.remote()


def test_conda_unknown_named_env_fails_fast(shared_ray, tmp_path, monkeypatch):
    """A typo'd env NAME (conda exists, env doesn't) is permanent: the task
    fails with the creation error instead of the lease retrying forever."""
    import time

    script, _ = _write_fake_conda(tmp_path)
    monkeypatch.setenv("RAYTPU_CONDA_EXE", str(script))

    @rt.remote(runtime_env={"conda": "no-such-env"})
    def f():
        return 1

    t0 = time.monotonic()
    with pytest.raises(Exception, match="no-such-env"):
        rt.get(f.remote(), timeout=120)
    assert time.monotonic() - t0 < 60, "lease retried instead of failing fast"


def test_container_command_construction():
    from ray_tpu.core.runtime_env import container_spawn_command

    env = {"RAYTPU_WORKER_ID": "w1", "PYTHONPATH": "/repo", "HOME": "/root",
           "JAX_PLATFORMS": "cpu", "SECRET_TOKEN": "nope"}
    cmd = container_spawn_command(
        {"image": "img:latest", "run_options": ["--cpus", "2"]},
        "/usr/bin/podman", env, "/sess", "/repo",
    )
    assert cmd[:3] == ["/usr/bin/podman", "run", "--rm"]
    assert "--network=host" in cmd and "--ipc=host" in cmd
    assert "-v" in cmd and "/sess:/sess" in cmd and "/repo:/repo" in cmd
    # Control-plane env forwarded; arbitrary host env NOT leaked.
    assert "RAYTPU_WORKER_ID=w1" in cmd and "JAX_PLATFORMS=cpu" in cmd
    assert not any("SECRET_TOKEN" in c or "HOME=" in c for c in cmd)
    # run_options precede the image; worker command trails it.
    assert cmd.index("--cpus") < cmd.index("img:latest")
    assert cmd[-3:] == ["img:latest", "python", "-m"] or cmd[-4:] == [
        "img:latest", "python", "-m", "ray_tpu.core.worker_main"]


def test_auth_token_value_never_on_container_argv():
    """The session MAC secret must not be readable via /proc/<pid>/cmdline:
    RAYTPU_AUTH_TOKEN is forwarded as a VALUE-LESS `--env K` flag (engine
    inherits the value from the client env Popen receives), never `K=V`
    (ADVICE r05, medium)."""
    from ray_tpu.core.runtime_env import container_spawn_command

    secret = "deadbeefcafef00d" * 2
    env = {"RAYTPU_AUTH_TOKEN": secret, "RAYTPU_WORKER_ID": "w1",
           "RAYTPU_CONTROLLER_ADDR": "127.0.0.1:1"}
    cmd = container_spawn_command(
        {"image": "img:latest"}, "/usr/bin/podman", env, "/sess", "/repo",
    )
    assert not any(secret in c for c in cmd), f"token value leaked into argv: {cmd}"
    # The variable is still forwarded — by name only.
    i = cmd.index("RAYTPU_AUTH_TOKEN")
    assert cmd[i - 1] == "--env"
    # Non-secret control-plane vars keep the explicit K=V form.
    assert "RAYTPU_WORKER_ID=w1" in cmd


def test_container_fake_engine_end_to_end(shared_ray, tmp_path, monkeypatch):
    """Behind the seam: a fake engine script that applies the --env args and
    execs the command after the image name — the worker runs as a plain
    subprocess, proving the command construction + env threading without
    podman/docker on the host."""
    import stat

    engine = tmp_path / "fake-engine"
    engine.write_text("""#!/bin/bash
envs=()
args=("$@")
i=0
n=${#args[@]}
while [ $i -lt $n ]; do
  a="${args[$i]}"
  if [ "$a" == "--env" ]; then
    i=$((i+1)); e="${args[$i]}"
    case "$e" in
      *=*) envs+=("$e");;
      # Value-less --env K: inherit from the engine client's own env —
      # podman/docker semantics; how secrets (RAYTPU_AUTH_TOKEN) arrive.
      *) envs+=("$e=${!e}");;
    esac
  elif [ "$a" == "test-image:v1" ]; then i=$((i+1)); break; fi
  i=$((i+1))
done
export RAYTPU_IN_FAKE_CONTAINER=1
exec env "${envs[@]}" RAYTPU_IN_FAKE_CONTAINER=1 "${args[@]:$i}"
""")
    engine.chmod(engine.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("RAYTPU_CONTAINER_ENGINE", str(engine))

    @rt.remote(runtime_env={"container": {"image": "test-image:v1"}})
    def probe():
        import os

        return os.environ.get("RAYTPU_IN_FAKE_CONTAINER"), os.environ.get("RAYTPU_WORKER_ID") is not None

    in_container, has_worker_id = rt.get(probe.remote(), timeout=120)
    assert in_container == "1"
    assert has_worker_id


def test_container_missing_engine_errors_cleanly(shared_ray, monkeypatch):
    monkeypatch.delenv("RAYTPU_CONTAINER_ENGINE", raising=False)
    monkeypatch.setenv("PATH", "/nonexistent")

    @rt.remote(runtime_env={"container": {"image": "img"}})
    def f():
        return 1

    with pytest.raises(Exception, match="podman nor docker"):
        rt.get(f.remote(), timeout=120)


def test_container_bad_spec_rejected(shared_ray):
    @rt.remote(runtime_env={"container": "not-a-dict"})
    def f():
        return 1

    with pytest.raises(ValueError, match="container"):
        f.remote()
