"""Runtime environments: env_vars, working_dir, py_modules on tasks/actors,
idle-pool isolation by env hash. Reference analogue:
python/ray/tests/test_runtime_env*.py (working_dir upload, env_vars
propagation, per-env worker reuse)."""
import os

import pytest

import ray_tpu as rt


def test_env_vars_on_task(shared_ray):
    @rt.remote(runtime_env={"env_vars": {"MY_FLAG": "hello-42"}})
    def read_flag():
        return os.environ.get("MY_FLAG")

    assert rt.get(read_flag.remote(), timeout=120) == "hello-42"

    # A plain task must NOT see the env var (pool isolation by env hash).
    @rt.remote
    def read_plain():
        return os.environ.get("MY_FLAG")

    assert rt.get(read_plain.remote(), timeout=120) is None


def test_working_dir_ships_code_and_cwd(shared_ray, tmp_path):
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "helper_mod_xyz.py").write_text("VALUE = 1234\n")
    (proj / "data.txt").write_text("payload!")

    @rt.remote(runtime_env={"working_dir": str(proj)})
    def use_workdir():
        import helper_mod_xyz  # importable from the shipped dir

        with open("data.txt") as f:  # cwd == extracted working_dir
            return helper_mod_xyz.VALUE, f.read()

    value, data = rt.get(use_workdir.remote(), timeout=120)
    assert value == 1234 and data == "payload!"


def test_py_modules_on_actor(shared_ray, tmp_path):
    mod_dir = tmp_path / "libs"
    (mod_dir / "shipped_pkg_abc").mkdir(parents=True)
    (mod_dir / "shipped_pkg_abc" / "__init__.py").write_text("NAME = 'shipped'\n")

    @rt.remote(runtime_env={"py_modules": [str(mod_dir)]})
    class Uses:
        def get(self):
            import shipped_pkg_abc

            return shipped_pkg_abc.NAME

    a = Uses.remote()
    assert rt.get(a.get.remote(), timeout=120) == "shipped"
    rt.kill(a)


def test_unknown_key_rejected(shared_ray):
    @rt.remote(runtime_env={"conda": "env"})
    def f():
        return 1

    with pytest.raises(ValueError, match="unsupported runtime_env"):
        f.remote()
