"""Tune layer: variant generation, ASHA, PBT, trial fault tolerance."""
import os

import pytest

import ray_tpu as rt
from ray_tpu import tune
from ray_tpu.tune.search import generate_variants, grid_search, mutate_config


def test_generate_variants_grid_and_sample():
    space = {
        "lr": tune.loguniform(1e-4, 1e-1),
        "depth": grid_search([2, 4]),
        "opt": {"name": grid_search(["sgd", "adam"]), "momentum": tune.uniform(0, 1)},
    }
    cfgs = generate_variants(space, num_samples=3, seed=0)
    assert len(cfgs) == 3 * 2 * 2
    assert {c["depth"] for c in cfgs} == {2, 4}
    assert {c["opt"]["name"] for c in cfgs} == {"sgd", "adam"}
    assert all(1e-4 <= c["lr"] <= 1e-1 for c in cfgs)
    assert all(0 <= c["opt"]["momentum"] <= 1 for c in cfgs)
    # Deterministic under the same seed.
    assert generate_variants(space, num_samples=3, seed=0) == cfgs


def test_mutate_config():
    import random

    cfg = {"lr": 0.01, "batch": 32, "fixed": "x"}
    out = mutate_config(
        cfg, {"lr": tune.uniform(0.001, 1.0), "batch": [16, 32, 64]},
        random.Random(0),
    )
    assert out["fixed"] == "x"
    assert out["lr"] in (0.008, 0.012) or 0.001 <= out["lr"] <= 1.0
    assert out["batch"] in (16, 32, 64)


def test_tuner_grid_sweep(shared_ray, tmp_path):
    from ray_tpu.train import RunConfig

    def trainable(config):
        score = -((config["x"] - 3) ** 2)
        tune.report({"score": score})

    grid = tune.Tuner(
        trainable,
        param_space={"x": grid_search([0, 1, 2, 3, 4, 5])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)),
    )
    results = grid.fit()
    assert len(results) == 6
    assert not results.errors
    best = results.get_best_result()
    assert best.config["x"] == 3
    assert best.metrics["score"] == 0


class _FakeTrial:
    def __init__(self, trial_id):
        self.trial_id = trial_id


def test_asha_rung_pruning_unit():
    """Deterministic ASHA semantics: a trial crossing a rung below the
    cutoff stops; rung leaders continue (async-optimism)."""
    from ray_tpu.tune.schedulers import CONTINUE, STOP

    asha = tune.ASHAScheduler(metric="acc", mode="max", max_t=16,
                              grace_period=2, reduction_factor=2)
    strong, weak = _FakeTrial("strong"), _FakeTrial("weak")
    # Strong trial races ahead through rungs 2, 4, 8 — first at each rung,
    # so it always continues.
    for t, acc in [(2, 2.0), (4, 4.0), (8, 8.0)]:
        assert asha.on_trial_result(strong, {"acc": acc, "training_iteration": t}) == CONTINUE
    # Weak trial now crosses rung 2 with a worse value -> pruned.
    assert asha.on_trial_result(weak, {"acc": 0.2, "training_iteration": 2}) == STOP
    # A third trial beating the rung-2 cutoff continues.
    ok = _FakeTrial("ok")
    assert asha.on_trial_result(ok, {"acc": 3.0, "training_iteration": 2}) == CONTINUE
    # max_t is a hard stop for everyone.
    assert asha.on_trial_result(strong, {"acc": 99.0, "training_iteration": 16}) == STOP


def test_asha_sweep_end_to_end(shared_ray, tmp_path):
    from ray_tpu.train import RunConfig

    def trainable(config):
        import time

        for step in range(1, 21):
            tune.report({"acc": config["quality"] * step,
                         "training_iteration": step})
            time.sleep(0.02)

    results = tune.Tuner(
        trainable,
        param_space={"quality": grid_search([0.1, 0.2, 0.5, 1.0])},
        tune_config=tune.TuneConfig(
            metric="acc", mode="max",
            scheduler=tune.ASHAScheduler(
                metric="acc", mode="max", max_t=20, grace_period=2,
                reduction_factor=2,
            ),
        ),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
    ).fit()
    assert not results.errors
    best = results.get_best_result()
    assert best.config["quality"] == 1.0
    assert best.metrics["acc"] == pytest.approx(20.0)


def test_trial_checkpoint_and_retry(shared_ray, tmp_path):
    """A crashing trial restarts from its checkpoint when retries remain."""
    from ray_tpu.train import Checkpoint, RunConfig

    def trainable(config):
        import json
        import tempfile

        ckpt = tune.get_checkpoint()
        start = 0
        if ckpt:
            with open(os.path.join(ckpt.path, "state.json")) as f:
                start = json.load(f)["step"]
        for step in range(start + 1, 6):
            d = tempfile.mkdtemp()
            with open(os.path.join(d, "state.json"), "w") as f:
                json.dump({"step": step}, f)
            tune.report({"step": step}, checkpoint=Checkpoint.from_directory(d))
            if step == 3 and start == 0:
                raise RuntimeError("injected crash at step 3")

    results = tune.Tuner(
        trainable,
        param_space={"x": grid_search([1])},
        tune_config=tune.TuneConfig(metric="step", mode="max",
                                    max_failures_per_trial=1),
        run_config=RunConfig(name="retry", storage_path=str(tmp_path)),
    ).fit()
    assert not results.errors
    r = results[0]
    assert r.metrics["step"] == 5
    # Restarted from step 3's checkpoint: steps 4,5 after the crash, not 1..5.
    steps = [m["step"] for m in r.metrics_history]
    assert steps == [1, 2, 3, 4, 5]


def test_max_concurrent_trials(shared_ray, tmp_path):
    from ray_tpu.train import RunConfig

    def trainable(config):
        tune.report({"ok": 1})

    results = tune.Tuner(
        trainable,
        param_space={"x": grid_search(list(range(5)))},
        tune_config=tune.TuneConfig(metric="ok", mode="max",
                                    max_concurrent_trials=2),
        run_config=RunConfig(name="conc", storage_path=str(tmp_path)),
    ).fit()
    assert len(results) == 5 and not results.errors
