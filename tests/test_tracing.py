"""Distributed tracing: span propagation through every cross-process hop.

The acceptance path (ISSUE 2): one serve HTTP request drives
proxy -> replica -> nested actor; every resulting span must share one
trace_id with correct parent/child links, and export_timeline must emit
connected flow events (ph s/f) for the hops."""
import json
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu import serve
from ray_tpu.util import tracing


@pytest.fixture(scope="module")
def serve_cluster():
    rt.init(num_cpus=16)
    serve.start(proxy=True)
    yield rt
    serve.shutdown()
    rt.shutdown()


# ---------------------------------------------------------------------------
# span API semantics (in-process)
# ---------------------------------------------------------------------------

def test_span_nesting_and_context(serve_cluster):
    assert tracing.current_trace() is None
    with tracing.span("outer") as outer:
        assert tracing.current_trace() == (outer.trace_id, outer.span_id)
        assert outer.parent_id == ""
        with tracing.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            assert tracing.current_trace() == (inner.trace_id, inner.span_id)
        assert tracing.current_trace() == (outer.trace_id, outer.span_id)
    assert tracing.current_trace() is None


def test_child_span_noop_without_active_trace(serve_cluster):
    with tracing.child_span("ignored") as s:
        assert s is None  # nullcontext: no ids minted, nothing recorded
        assert tracing.current_trace() is None
    with tracing.span("root") as root:
        with tracing.child_span("kid") as kid:
            assert kid is not None and kid.parent_id == root.span_id


def test_task_spans_share_trace_and_parent(serve_cluster):
    @rt.remote
    def leaf(x):
        return x + 1

    with tracing.span("task-root") as root:
        assert rt.get(leaf.remote(1), timeout=60) == 2

    events = _wait_trace(root.trace_id, want_kinds={"task_submitted", "task_exec_start"})
    subs = [e for e in events if e["kind"] == "task_submitted"]
    execs = [e for e in events if e["kind"] == "task_exec_start"]
    assert subs and execs
    assert all(e["trace_id"] == root.trace_id for e in subs + execs)
    assert subs[0]["span_id"] == root.span_id  # submission annotated with caller span
    assert execs[0]["parent_id"] == root.span_id  # exec span is the caller's child


def _wait_trace(trace_id: str, want_kinds=frozenset(), min_workers: int = 1,
                predicate=None, timeout_s: float = 90.0):
    """Poll the controller's trace index until the wanted event kinds, enough
    distinct worker processes, AND an optional predicate over the events all
    hold (remote workers flush their buffers on the reporter tick, so hops
    arrive staggered — see tracing.get_trace's staleness note)."""
    from ray_tpu.core import api

    core = api._require_worker()
    deadline = time.time() + timeout_s
    events: list = []
    while time.time() < deadline:
        core._run(core._flush_task_events())
        events = core._run(core.controller.call("get_trace", {"trace_id": trace_id}))
        if (set(want_kinds) <= {e.get("kind") for e in events}
                and len({e.get("worker") for e in events}) >= min_workers
                and (predicate is None or predicate(events))):
            return events
        time.sleep(0.5)
    return events


# ---------------------------------------------------------------------------
# acceptance: serve request through proxy -> replica -> actor
# ---------------------------------------------------------------------------

def test_serve_request_single_trace_across_hops(serve_cluster, tmp_path):
    @rt.remote
    class Shouter:
        def shout(self, s):
            return s.upper()

    @serve.deployment
    class Ingress:
        def __init__(self, downstream):
            self.downstream = downstream

        def __call__(self, request):
            return {"msg": rt.get(self.downstream.shout.remote("hello"), timeout=30)}

    downstream = Shouter.remote()
    rt.get(downstream.shout.remote("warm"), timeout=60)
    serve.run(Ingress.bind(downstream), name="traced_app", route_prefix="/traced")
    port = serve.http_port()

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/traced", headers={"x-trace": "1"}
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert resp.status == 200
        assert json.loads(resp.read()) == {"msg": "HELLO"}

    # Find the request's trace via the root span name.
    from ray_tpu.core import api

    core = api._require_worker()
    deadline = time.time() + 45
    trace_id = None
    while time.time() < deadline and trace_id is None:
        traces = core._run(core.controller.call("list_traces", {"q": "serve.request"}))
        if traces:
            trace_id = traces[0]["trace_id"]
            break
        time.sleep(0.5)
    assert trace_id, "no serve.request trace was indexed"

    events = _wait_trace(
        trace_id, want_kinds={"span", "task_exec_start"}, min_workers=3,
        # All three hops must have landed: the replica's serve span and the
        # downstream actor's exec span arrive on their own reporter ticks.
        predicate=lambda evs: (
            any(e.get("name", "").startswith("serve.replica.") for e in evs)
            and any(e.get("fn") == "shout" and e["kind"] == "task_exec_start" for e in evs)
        ),
    )
    assert all(e.get("trace_id") == trace_id for e in events)

    spans = {}  # span_id -> event (spans + exec spans both mint span ids)
    for e in events:
        if e.get("span_id") and e["kind"] in ("span", "task_exec_start"):
            spans[e["span_id"]] = e

    roots = [e for e in spans.values() if e["kind"] == "span" and not e.get("parent_id")]
    assert len(roots) == 1 and roots[0]["name"] == "serve.request"

    # The request crossed at least proxy + replica + downstream-actor
    # processes, each contributing spans to the SAME trace.
    workers = {e.get("worker") for e in spans.values()}
    assert len(workers) >= 3, f"expected >=3 processes in trace, got {workers}"

    # Every non-root span's parent resolves inside the trace: one connected
    # tree, no orphaned hops.
    ids = set(spans)
    for e in spans.values():
        if e is roots[0]:
            continue
        assert e.get("parent_id") in ids, f"orphaned span {e}"

    # The replica's serve span and the downstream actor's exec span are on
    # the path: replica span parents the shout exec (via the replica's
    # active context at submission).
    replica_spans = [e for e in spans.values()
                     if e["kind"] == "span" and e["name"].startswith("serve.replica.")]
    assert replica_spans
    shout_execs = [e for e in spans.values()
                   if e["kind"] == "task_exec_start" and e.get("fn") == "shout"]
    assert shout_execs

    # Flow events connect the hops in the exported timeline.
    out = str(tmp_path / "serve_trace.json")
    tracing.export_timeline(out)
    data = json.load(open(out))
    flows = [e for e in data["traceEvents"] if e.get("ph") in ("s", "f")
             and e.get("args", {}).get("trace_id") == trace_id]
    starts = {e["id"] for e in flows if e["ph"] == "s"}
    finishes = {e["id"] for e in flows if e["ph"] == "f"}
    assert starts & finishes, "no connected flow (s/f) pair for the request's hops"

    serve.delete("traced_app")


def test_trace_overhead_guard_no_context_cost(serve_cluster):
    """With no span active, submission attaches None and no trace events are
    recorded — the guard path."""
    @rt.remote
    class Quiet:
        def ping(self):
            return b"ok"

    a = Quiet.remote()
    rt.get(a.ping.remote(), timeout=60)
    from ray_tpu.core import api

    core = api._require_worker()
    before = len(core.task_events)
    rt.get([a.ping.remote() for _ in range(50)], timeout=120)
    # Untraced actor calls emit no tracing events (task_finished bookkeeping
    # predates this feature and stays).
    new = core.task_events[before:]
    assert not [e for e in new
                if "trace_id" in e
                or e["kind"] in ("span", "task_submitted", "task_exec_start")]
