"""End-to-end response streaming: replica generators -> streaming handle ->
HTTP proxy chunked transfer -> SSE LLM tokens (reference: serve streaming
responses via ASGI proxy.py:710 + streaming replica calls; llm SSE ingress).

The load-bearing property under test: a client observes the FIRST item while
the producer is still generating (TTFT != total latency)."""
import json
import socket
import time

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_cluster():
    rt.init(num_cpus=16)
    serve.start(proxy=False)
    yield rt
    serve.shutdown()
    rt.shutdown()


# ---------------------------------------------------------------------------
# streaming through DeploymentHandle
# ---------------------------------------------------------------------------

def test_handle_stream_option(serve_cluster):
    @serve.deployment
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                yield {"i": i}

        def slow(self, n, delay):
            for i in range(n):
                time.sleep(delay)
                yield i

    handle = serve.run(Streamer.bind(), name="stream_app", http=False)
    got = list(handle.options(stream=True).remote(5))
    assert got == [{"i": i} for i in range(5)]

    # Incremental delivery: first item arrives well before the stream ends.
    t0 = time.time()
    gen = handle.options(stream=True).slow.remote(5, 0.3)
    first = next(gen)
    t_first = time.time() - t0
    rest = list(gen)
    t_total = time.time() - t0
    assert first == 0 and rest == [1, 2, 3, 4]
    assert t_first < t_total - 0.5, (t_first, t_total)
    serve.delete("stream_app")


def test_handle_stream_non_generator_errors(serve_cluster):
    @serve.deployment
    def scalar(x):
        return x + 1

    handle = serve.run(scalar.bind(), name="scalar_app", http=False)
    with pytest.raises(Exception, match="not a generator"):
        list(handle.options(stream=True).remote(1))
    # Buffered path unaffected.
    assert handle.remote(1).result() == 2
    serve.delete("scalar_app")


def test_stream_releases_capacity(serve_cluster):
    """Exhausting (or closing) a stream releases the replica's ongoing slot:
    max_ongoing_requests streams in sequence never deadlock."""

    @serve.deployment(max_ongoing_requests=2)
    class Tight:
        def __call__(self, n):
            yield from range(n)

    handle = serve.run(Tight.bind(), name="tight_app", http=False)
    for _ in range(6):  # 3x the budget; fails if slots leak
        assert list(handle.options(stream=True).remote(3)) == [0, 1, 2]
    # Abandoned (closed, not exhausted) stream also releases.
    for _ in range(4):
        gen = handle.options(stream=True).remote(3)
        next(gen)
        gen.close()
    assert list(handle.options(stream=True).remote(2)) == [0, 1]
    serve.delete("tight_app")


# ---------------------------------------------------------------------------
# streaming through the HTTP proxy (chunked transfer at a raw socket)
# ---------------------------------------------------------------------------

def _read_chunked(sock_file):
    """Parse HTTP/1.1 chunked body incrementally; yields (bytes, t_arrival)."""
    while True:
        size_line = sock_file.readline()
        size = int(size_line.strip(), 16)
        if size == 0:
            sock_file.readline()  # trailing CRLF
            return
        data = sock_file.read(size)
        sock_file.read(2)  # CRLF
        yield data, time.time()


def _stream_request(port, path, payload):
    body = json.dumps(payload).encode()
    s = socket.create_connection(("127.0.0.1", port), timeout=60)
    req = (
        f"POST {path} HTTP/1.1\r\nhost: x\r\ncontent-type: application/json\r\n"
        f"content-length: {len(body)}\r\n\r\n"
    ).encode() + body
    s.sendall(req)
    f = s.makefile("rb")
    status = f.readline().decode()
    headers = {}
    while True:
        line = f.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode().partition(":")
        headers[k.strip().lower()] = v.strip()
    return s, f, status, headers


def test_proxy_chunked_streaming(serve_cluster):
    @serve.deployment
    class SSEApp:
        def __call__(self, request):
            n = int(request.json()["n"])

            def gen():
                for i in range(n):
                    time.sleep(0.25)
                    yield f"data: {i}\n\n"

            return gen()

    serve.run(SSEApp.bind(), name="sse_app", route_prefix="/sse")
    port = serve.http_port()
    t0 = time.time()
    s, f, status, headers = _stream_request(port, "/sse", {"n": 4})
    assert "200" in status
    assert headers.get("transfer-encoding") == "chunked"
    assert headers.get("content-type") == "text/event-stream"
    chunks = list(_read_chunked(f))
    s.close()
    t_first = chunks[0][1] - t0
    t_last = chunks[-1][1] - t0
    assert b"".join(c for c, _ in chunks) == b"".join(
        f"data: {i}\n\n".encode() for i in range(4)
    )
    # First chunk must land ~3 sleeps before the last one: streaming, not
    # buffering.
    assert t_first < t_last - 0.5, (t_first, t_last)
    serve.delete("sse_app")


def test_proxy_buffered_json_unaffected(serve_cluster):
    @serve.deployment
    class Plain:
        def __call__(self, request):
            return {"ok": request.json()["x"] * 2}

    serve.run(Plain.bind(), name="plain_app", route_prefix="/plain")
    port = serve.http_port()
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/plain",
        data=json.dumps({"x": 21}).encode(),
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert json.loads(resp.read()) == {"ok": 42}
    serve.delete("plain_app")


# ---------------------------------------------------------------------------
# LLM SSE token streaming end-to-end
# ---------------------------------------------------------------------------

def test_llm_sse_streaming_end_to_end(serve_cluster):
    from ray_tpu.llm import build_llm_app

    app = build_llm_app(
        model_config=dict(
            vocab_size=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=128, attention_impl="reference",
        ),
        engine_config={"max_slots": 4, "max_seq": 128, "prefill_buckets": (16, 32),
                       "decode_block": 4},
    )
    serve.run(app, name="llm_sse", route_prefix="/llm")
    port = serve.http_port()

    # Non-streaming reference completion (greedy -> deterministic).
    handle = serve.get_deployment_handle("llm", "llm_sse")
    expect = handle.remote({"tokens": [3, 1, 4, 1, 5], "max_tokens": 12}).result(
        timeout=120
    )["tokens"]

    s, f, status, headers = _stream_request(
        port, "/llm", {"tokens": [3, 1, 4, 1, 5], "max_tokens": 12, "stream": True}
    )
    assert "200" in status
    assert headers.get("content-type") == "text/event-stream"
    frames = []
    times = []
    for data, t in _read_chunked(f):
        frames.append(data)
        times.append(t)
    s.close()
    text = b"".join(frames).decode()
    events = []
    for line in text.split("\n\n"):
        if not line.startswith("data: "):
            continue
        payload = line[len("data: "):]
        if payload == "[DONE]":
            events.append("DONE")
        else:
            events.append(json.loads(payload))
    assert events[-1] == "DONE"
    streamed = [t for ev in events[:-1] for t in ev["new_tokens"]]
    assert streamed == expect
    # More than one token-bearing frame: tokens streamed per decode block,
    # not buffered to completion (12 tokens / decode_block=4 >= 3 frames).
    assert len(events) - 1 >= 3
    serve.delete("llm_sse")


def test_llm_abandoned_stream_frees_engine_slot(serve_cluster):
    from ray_tpu.llm import build_llm_app

    app = build_llm_app(
        model_config=dict(
            vocab_size=97, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=512, attention_impl="reference",
        ),
        engine_config={"max_slots": 2, "max_seq": 512, "prefill_buckets": (16,),
                       "decode_block": 2},
    )
    handle = serve.run(app, name="llm_abort", http=False)
    # Long generation we will abandon after the first event.
    gen = handle.options(stream=True).generate_stream.remote([1, 2, 3], 400)
    first = next(gen)
    assert first["new_tokens"]
    gen.close()
    # The engine must retire the slot well before the 400 tokens complete.
    deadline = time.time() + 15
    while time.time() < deadline:
        stats = handle.stats.remote().result(timeout=30)
        if stats["active_slots"] == 0 and stats["waiting"] == 0:
            break
        time.sleep(0.2)
    else:
        raise AssertionError(f"slot not freed after abandon: {stats}")
    serve.delete("llm_abort")
