"""Tensor-parallel LLM serving: the engine sharded over a `tensor` mesh axis
(params Megatron-split, KV pools split by kv_heads) must produce byte-identical
greedy output to the single-device engine, for both KV layouts, and a serve
replica must gang-schedule onto a host advertising the TP degree's chips.

Reference analogue: TP degree -> placement-group bundle mapping
(llm/_internal/serve/engines/vllm/vllm_models.py:233-238; vLLM executes the
sharded model — here the sharded execution is native, ray_tpu/llm/engine.py).
Runs on the virtual 8-device CPU mesh (conftest).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm import EngineConfig, LLMEngine
from ray_tpu.models import TransformerConfig

CFG = TransformerConfig(
    vocab_size=96, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
    max_seq_len=128, dtype=jnp.float32, attention_impl="reference",
)
PROMPT = [5, 17, 42, 7, 23, 11, 2]


def _engine(tp: int, layout: str, **ec_kw) -> LLMEngine:
    kw = dict(max_slots=4, max_seq=128, prefill_buckets=(16, 32),
              kv_layout=layout, tensor_parallel=tp)
    if layout == "paged":
        kw["page_size"] = 32
    kw.update(ec_kw)
    return LLMEngine(CFG, engine_config=EngineConfig(**kw))


@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_tp_greedy_matches_single_device(layout):
    """mesh=tensor(2) must not change greedy output vs one device — the
    round-5 acceptance bar for sharded serving."""
    ref = _engine(1, layout).generate(PROMPT, max_tokens=10)["tokens"]
    tp = _engine(2, layout).generate(PROMPT, max_tokens=10)["tokens"]
    assert tp == ref, f"{layout}: TP output diverged: {tp} vs {ref}"


def test_tp_actually_shards_params_and_kv():
    eng = _engine(2, "paged")
    wq = eng.params["layers"]["wq"]  # [L, D, H, Hd]: heads sharded
    assert wq.addressable_shards[0].data.shape[2] == CFG.n_heads // 2
    mlp = eng.params["layers"]["w_gate"]  # [L, D, F]: ffn hidden sharded
    assert mlp.addressable_shards[0].data.shape[2] == CFG.d_ff // 2
    # Paged KV pool [L, KV, pages*ps, Hd]: kv_heads sharded.
    assert eng.k_pages.addressable_shards[0].data.shape[1] == CFG.kv_heads // 2
    dense = _engine(2, "dense")
    # Dense cache [L, B, S, KV, Hd]: kv_heads sharded.
    assert dense.k_pages.addressable_shards[0].data.shape[3] == CFG.kv_heads // 2


def test_tp_weight_handoff_through_object_store():
    """train->serve handoff of a TP-SHARDED param tree through the object
    store: every leaf ships one OOB buffer per unique shard (no host gather
    — core/serialization.py sharded transport), and an engine constructed
    from the fetched tree serves byte-identical greedy output."""
    import ray_tpu as rt

    src = _engine(2, "dense")
    ref_out = src.generate(PROMPT, max_tokens=10)["tokens"]
    wq = src.params["layers"]["wq"]
    assert len(wq.sharding.device_set) == 2  # really sharded going in

    rt.init(num_cpus=2)
    try:
        ref = rt.put(src.params)
        fetched = rt.get(ref, timeout=120)
    finally:
        rt.shutdown()
    # Shards survived the hop: same per-device layout, no gather artifact.
    fq = fetched["layers"]["wq"]
    assert len(fq.sharding.device_set) == 2
    assert fq.addressable_shards[0].data.shape == wq.addressable_shards[0].data.shape
    served = LLMEngine(CFG, params=fetched, engine_config=EngineConfig(
        max_slots=4, max_seq=128, prefill_buckets=(16, 32),
        kv_layout="dense", tensor_parallel=2))
    assert served.generate(PROMPT, max_tokens=10)["tokens"] == ref_out


def test_tp_params_ref_served_through_deployment():
    """The wired train->serve path: build_llm_app(params=ObjectRef) — the
    REPLICA (a separate worker process) fetches the sharded tree from the
    object store and serves it, output matching the source engine."""
    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    src = _engine(2, "dense")
    ref_out = src.generate(PROMPT, max_tokens=8)["tokens"]

    rt.init(num_cpus=8, resources={"TPU": 2.0})
    try:
        serve.start(proxy=False)
        ref = rt.put(src.params)
        app = build_llm_app(
            model_config=dict(
                vocab_size=96, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                d_ff=128, max_seq_len=128, attention_impl="reference",
            ),
            engine_config={"max_slots": 4, "max_seq": 128,
                           "prefill_buckets": (16, 32), "kv_layout": "dense",
                           "tensor_parallel": 2},
            params=ref,
        )
        serve.run(app, name="tp-handoff", http=False)
        h = serve.get_deployment_handle("llm", "tp-handoff")
        out = h.generate.remote(PROMPT, 8).result(timeout=300)
        assert out["tokens"] == ref_out, (out["tokens"], ref_out)
        serve.delete("tp-handoff")
    finally:
        serve.shutdown()
        rt.shutdown()


def test_tp_rejects_indivisible_model():
    with pytest.raises(ValueError, match="not divisible"):
        _engine(4, "dense")  # kv_heads=2 % 4 != 0


def test_tp_mixed_batch_and_sampling():
    """Continuous batching under TP: concurrent requests with different
    per-request sampling params behave like the single-device engine."""
    from ray_tpu.llm.sampling import SamplingParams

    eng = _engine(2, "paged")
    eng.add_request("greedy", PROMPT, 8,
                    sampling=SamplingParams(temperature=0.0, max_tokens=8))
    eng.add_request("hot", list(reversed(PROMPT)), 8,
                    sampling=SamplingParams(temperature=0.9, top_k=20, max_tokens=8))
    done = {}
    while eng.has_work():
        for rid, ev in eng.step().items():
            if ev.get("finished"):
                done[rid] = ev["tokens"]
    ref = _engine(1, "paged").generate(
        PROMPT, 8, sampling=SamplingParams(temperature=0.0, max_tokens=8)
    )["tokens"]
    assert done["greedy"] == ref
    assert len(done["hot"]) == 8


def test_tp_prefix_cache_hit_correct():
    """Prefix-cache page copy works on a kv_heads-sharded pool (the copy
    slices the token axis; the sharded axis rides along)."""
    eng = _engine(2, "paged", prefix_cache=True, temperature=0.0)
    cold = eng.generate(PROMPT, max_tokens=8)["tokens"]
    warm = eng.generate(PROMPT, max_tokens=8)["tokens"]
    assert eng.prefix_cache_stats["hits"] == 1
    assert warm == cold


def test_tp_serve_replica_gang():
    """A TP-2 deployment declares {"TPU": 2}; the replica lands on the node
    advertising those chips and serves correctly."""
    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    rt.init(num_cpus=8, resources={"TPU": 2.0})
    serve.start(proxy=False)
    try:
        app = build_llm_app(
            model_config=dict(
                vocab_size=96, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                d_ff=128, max_seq_len=128, attention_impl="reference",
            ),
            engine_config={"max_slots": 4, "max_seq": 128,
                           "prefill_buckets": (16, 32), "tensor_parallel": 2},
        )
        handle = serve.run(app, name="llm_tp_app", http=False)
        out = handle.remote({"tokens": PROMPT, "max_tokens": 8}).result(timeout=300)
        assert len(out["tokens"]) == 8
        # The gang reservation is real: the TPU capacity is now held, so a
        # second TP-2 replica cannot also fit on this 2-chip node.
        from ray_tpu.core import api

        state = api._cluster_state()
        tpu_avail = [
            n.get("available", {}).get("TPU", 0.0)
            for n in state["nodes"].values()
            if n["state"] == "ALIVE"
        ]
        assert max(tpu_avail, default=0.0) == 0.0, tpu_avail
        serve.delete("llm_tp_app")
    finally:
        serve.shutdown()
        rt.shutdown()
