"""Object spilling and lineage reconstruction.

Reference analogues: python/ray/tests/test_object_spilling*.py (spill under
store pressure, restore on get) and test_reconstruction*.py (lost objects
re-created by re-executing the producing task — task_manager.h:184,
object_recovery_manager.h:41).

The reconstruction tests force object loss with DETERMINISTIC chaos
schedules (seeded nth-hit eviction at the ``node.chunk.serve`` gate) rather
than the original remove-node/add-node dance: under full-suite load the
node-churn version raced worker-spawn and re-registration timing and went
flaky (tier-1 triage, PR 5); a chaos-evicted object is lost at an exact,
replayable point with zero cluster churn.
"""
import os

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import chaos
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import SharedMemoryClient


@pytest.fixture
def chaos_evict():
    """Arm an eviction schedule for named object ids; disarm on exit."""

    def arm(*refs, seed=7):
        chaos.install(chaos.FaultSchedule.from_spec({
            "seed": seed,
            "rules": [
                {"site": "node.chunk.serve", "kind": "evict", "nth": 1,
                 "ctx": {"oid": r.id.hex()[:16]}}
                for r in refs
            ],
        }))

    yield arm
    chaos.uninstall()


# ---------------------------------------------------------------- spilling


def test_store_spill_and_restore(tmp_path):
    s = SharedMemoryClient(
        str(tmp_path / "store"), capacity=4 * 1024 * 1024, create=True, spill_dir=str(tmp_path / "spill")
    )
    blobs = {}
    for _ in range(12):  # 12 * 700KB ≈ 2x capacity
        oid = ObjectID.from_put()
        data = os.urandom(700 * 1024)
        s.put(oid, data)
        blobs[oid] = data
    # Everything is still retrievable: resident or restored from disk.
    for oid, data in blobs.items():
        if not s.contains(oid):
            assert s.is_spilled(oid)
            assert s.restore(oid)
        assert s.get_copy(oid) == data
    s.close()


def test_store_spill_delete_drops_file(tmp_path):
    s = SharedMemoryClient(
        str(tmp_path / "store"), capacity=1024 * 1024, create=True, spill_dir=str(tmp_path / "spill")
    )
    a = ObjectID.from_put()
    s.put(a, os.urandom(700 * 1024))
    s.put(ObjectID.from_put(), os.urandom(700 * 1024))  # pressure -> a spills
    assert s.is_spilled(a)
    s.delete(a, drop_spilled=True)
    assert not s.is_spilled(a)
    assert not s.contains_or_spilled(a)
    s.close()


def test_spill_integration_10x_capacity():
    """Fill the store ~10x over capacity through the public API; every object
    must come back (reference: test_object_spilling.py fill-beyond-capacity)."""
    from ray_tpu.core.api import Cluster, init, shutdown

    cluster = Cluster(initialize_head=False)
    cluster.add_node(num_cpus=2, object_store_memory=16 * 1024 * 1024)
    init(address=cluster.address)
    try:
        arrays = [np.full(1_000_000, i, dtype=np.float64) for i in range(20)]  # 20 x 8MB = 160MB
        refs = [rt.put(a) for a in arrays]
        for i, ref in enumerate(refs):
            got = rt.get(ref, timeout=60)
            assert got[0] == float(i) and got.shape == (1_000_000,)
    finally:
        shutdown()
        cluster.shutdown()


# ------------------------------------------------- lineage reconstruction


@pytest.fixture
def recovery_cluster():
    from ray_tpu.core.api import Cluster, init, shutdown

    cluster = Cluster(initialize_head=False)
    head = cluster.add_node(num_cpus=2)
    init(address=cluster.address)
    yield cluster
    shutdown()
    cluster.shutdown()


def _exec_marker_dir(tmp_path):
    d = str(tmp_path / "exec_markers")
    os.makedirs(d, exist_ok=True)
    return d


def test_lost_object_reexecuted(recovery_cluster, tmp_path, chaos_evict):
    cluster = recovery_cluster
    marker_dir = _exec_marker_dir(tmp_path)
    cluster.add_node(num_cpus=2, resources={"special": 1.0})

    @rt.remote(resources={"special": 1.0}, max_retries=2)
    def make():
        with open(os.path.join(marker_dir, os.urandom(6).hex()), "w"):
            pass
        return np.arange(500_000, dtype=np.float64)  # 4MB -> shm on the special node

    ref = make.remote()
    ready, _ = rt.wait([ref], timeout=120)  # completes WITHOUT pulling payload to the driver node
    assert ready
    n0 = len(os.listdir(marker_dir))
    assert n0 >= 1  # >=: a retried first attempt is legal, not what we test
    # The ONLY copy is chaos-evicted the instant the driver's pull asks for
    # it (deterministic nth=1 on that oid) — the get must fall through the
    # empty directory to lineage re-execution on the same live node.
    chaos_evict(ref)
    got = rt.get(ref, timeout=120)
    assert got.shape == (500_000,) and got[-1] == 499_999.0
    assert len(os.listdir(marker_dir)) > n0  # really re-executed
    assert [e["site"] for e in chaos.injection_log()] == ["node.chunk.serve"]


def test_lineage_chain_recovers_dependencies(recovery_cluster, tmp_path, chaos_evict):
    cluster = recovery_cluster
    marker_dir = _exec_marker_dir(tmp_path)
    # Producer and consumer on DIFFERENT nodes: the consumer pulls its
    # dependency over the transfer plane, so both the result AND the
    # dependency have a chunk-serve gate their loss can strike through.
    cluster.add_node(num_cpus=2, resources={"specialA": 1.0})
    cluster.add_node(num_cpus=2, resources={"specialB": 1.0})

    @rt.remote(resources={"specialA": 1.0}, max_retries=2)
    def produce():
        with open(os.path.join(marker_dir, "p_" + os.urandom(6).hex()), "w"):
            pass
        return np.ones(400_000, dtype=np.float64)

    @rt.remote(resources={"specialB": 1.0}, max_retries=2)
    def double(a):
        with open(os.path.join(marker_dir, "d_" + os.urandom(6).hex()), "w"):
            pass
        return a * 2.0

    a = produce.remote()
    ready, _ = rt.wait([a], timeout=120)
    assert ready
    p0 = sum(m.startswith("p_") for m in os.listdir(marker_dir))
    # Two deterministic losses, one per lineage level, each armed BEFORE the
    # pull it strikes (no submit-vs-arm race). Level 1: `a` evicts on its
    # FIRST serve — which is double's argument pull — so the borrowing
    # worker must recover its dependency through the owner (produce re-runs)
    # before double's body can start.
    chaos_evict(a)
    b = double.remote(a)
    ready, _ = rt.wait([b], timeout=120)
    assert ready
    markers = os.listdir(marker_dir)
    assert sum(m.startswith("p_") for m in markers) > p0  # dependency recovered
    assert [e["site"] for e in chaos.injection_log()] == ["node.chunk.serve"]
    d1 = sum(m.startswith("d_") for m in markers)
    # Level 2: `b` evicts on ITS first serve — the driver's get — so the
    # owner re-executes double from lineage (arg `a` is resident again).
    chaos_evict(b)
    got = rt.get(b, timeout=120)
    assert got[0] == 2.0
    markers = os.listdir(marker_dir)
    assert sum(m.startswith("d_") for m in markers) > d1  # consumer re-ran
    assert [e["site"] for e in chaos.injection_log()] == ["node.chunk.serve"]


def test_no_recovery_when_retries_disabled(recovery_cluster, tmp_path, chaos_evict):
    cluster = recovery_cluster
    cluster.add_node(num_cpus=2, resources={"special": 1.0})

    @rt.remote(resources={"special": 1.0}, max_retries=0)
    def make():
        return np.zeros(400_000, dtype=np.float64)

    ref = make.remote()
    ready, _ = rt.wait([ref], timeout=120)
    assert ready
    chaos_evict(ref)  # the only copy dies on its next serve, deterministically
    from ray_tpu.core.object_ref import ObjectLostError

    with pytest.raises(ObjectLostError):
        rt.get(ref, timeout=60)
