"""Multi-agent RL: env contract, per-policy runner batching, and
independent PPO learning a cooperative game with shared and per-agent
policies (reference: rllib/env/multi_agent_env.py + multi_agent_env_runner.py
+ the policy_mapping_fn contract)."""
import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.rl.multi_agent import (
    CueMatchEnv,
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)


@pytest.fixture(scope="module", autouse=True)
def _session():
    rt.init(num_cpus=4)
    yield
    rt.shutdown()


def test_env_contract():
    env = CueMatchEnv(n_agents=3, n_cues=4, ep_len=5)
    obs, _ = env.reset(seed=0)
    assert set(obs) == set(env.possible_agents)
    assert all(o.shape == (4,) and o.sum() == 1.0 for o in obs.values())
    for t in range(5):
        obs, rew, term, trunc, _ = env.step({a: 0 for a in env.possible_agents})
        assert set(rew) == set(env.possible_agents)
        assert term["__all__"] == (t == 4)


def test_runner_groups_by_policy():
    """The runner batches agents BY policy: one forward per policy over
    [E * agents_of_policy] rows, trajectories in [T, N] layout."""
    from ray_tpu.rl.module import init_params

    rng = np.random.default_rng(0)
    mapping = {"agent_0": "a", "agent_1": "b", "agent_2": "a"}
    runner = MultiAgentEnvRunner(
        lambda: CueMatchEnv(n_agents=3, n_cues=4, ep_len=8),
        num_envs=4, rollout_len=8, policy_mapping=mapping, seed=1,
    )
    runner.set_weights({
        "a": init_params(rng, 4, 4, (16,)),
        "b": init_params(rng, 4, 4, (16,)),
    })
    out = runner.sample()
    pa, pb = out["policies"]["a"], out["policies"]["b"]
    assert pa["obs"].shape == (8, 8, 4)  # 4 envs x 2 agents on policy a
    assert pb["obs"].shape == (8, 4, 4)  # 4 envs x 1 agent on policy b
    assert pa["last_values"].shape == (8,)
    assert out["steps"] == 8 * 4 * 3
    assert out["episode_returns"], "episodes should complete at ep_len=8"
    # Episodes ended on the last row -> the NEXT rollout starts with a
    # next-step-reset junk row (valids=0), the contract compute_gae's
    # bootstrapping requires (truncated episodes must not bootstrap into
    # the next episode's value).
    out2 = runner.sample()
    assert (out2["policies"]["a"]["valids"][0] == 0.0).all()
    assert (out2["policies"]["a"]["rewards"][0] == 0.0).all()
    assert (out2["policies"]["a"]["valids"][1] == 1.0).all()
    runner.close()


def test_mismatched_policy_group_rejected():
    class Lopsided(CueMatchEnv):
        def __init__(self):
            super().__init__(n_agents=2, n_cues=4)
            self.n_actions = {"agent_0": 4, "agent_1": 2}

    with pytest.raises(ValueError, match="mismatched spaces"):
        MultiAgentPPOConfig(
            env_ctor=Lopsided, policy_mapping_fn=lambda a: "shared",
        ).build()


def test_shared_policy_learns_cue_match():
    """Parameter sharing: one policy for all agents solves the cue game
    (near-max team reward: 2 agents x 16 steps x ~1.0)."""
    algo = MultiAgentPPOConfig(
        env_ctor=lambda: CueMatchEnv(n_agents=2, n_cues=4, ep_len=16),
        num_env_runners=2, num_envs_per_runner=8, rollout_len=64,
        lr=3e-3, seed=0,
    ).build()
    try:
        result = {}
        for _ in range(12):
            result = algo.train()
            if result["episode_return_mean"] > 26:  # max 32, random ~1.4
                break
        assert result["episode_return_mean"] > 26, result
        assert set(result["policies"]) == {"shared"}
    finally:
        algo.stop()


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_per_agent_policies_learn_independently():
    """policy_mapping_fn routes each agent to its own policy; both learn,
    and the two learners really hold different weights (independent
    updates from their own streams)."""
    algo = MultiAgentPPOConfig(
        env_ctor=lambda: CueMatchEnv(n_agents=2, n_cues=3, ep_len=16),
        policy_mapping_fn=lambda a: f"pi_{a}",
        num_env_runners=2, num_envs_per_runner=8, rollout_len=64,
        lr=3e-3, seed=1,
    ).build()
    try:
        result = {}
        for _ in range(12):
            result = algo.train()
            if result["episode_return_mean"] > 26:
                break
        assert result["episode_return_mean"] > 26, result
        assert set(result["policies"]) == {"pi_agent_0", "pi_agent_1"}
        w0 = algo.learners["pi_agent_0"].get_weights()
        w1 = algo.learners["pi_agent_1"].get_weights()
        assert any(
            not np.array_equal(w0[k], w1[k]) for k in w0
        ), "per-agent policies should diverge"
    finally:
        algo.stop()
