"""Actor-pool map_batches (reference: ActorPoolMapOperator,
actor_pool_map_operator.py:70) + the LLM batch-inference stage built on it
(reference: vLLMEngineStage, vllm_engine_stage.py:794)."""
import os
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import data


@pytest.fixture(scope="module")
def pool_ray():
    rt.init(num_cpus=8)
    yield rt
    rt.shutdown()


class StatefulUDF:
    """Counts per-actor constructions + calls via instance state."""

    def __init__(self, bias):
        self.bias = bias
        self.calls = 0
        self.ident = f"{os.getpid()}-{id(self)}"

    def __call__(self, batch):
        self.calls += 1
        return {
            "id": batch["id"] + self.bias,
            "actor": np.array([self.ident] * len(batch["id"])),
            "call_no": np.array([self.calls] * len(batch["id"])),
        }


def test_actor_pool_constructs_once_and_reuses(pool_ray):
    ds = data.range(48, parallelism=12).map_batches(
        StatefulUDF, concurrency=2, fn_constructor_args=(100,)
    )
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(100, 148))
    actors = {r["actor"] for r in rows}
    # 12 blocks ran on a FIXED pool of 2 stateful actors (one construction
    # each), so each actor served multiple blocks (state reuse).
    assert len(actors) <= 2
    assert max(r["call_no"] for r in rows) >= 3


def test_actor_pool_plain_function(pool_ray):
    ds = data.range(16, parallelism=4).map_batches(
        lambda b: {"id": b["id"] * 2}, compute="actors", concurrency=1
    )
    assert sorted(r["id"] for r in ds.take_all()) == [2 * i for i in range(16)]


def test_actor_pool_autoscales_within_bounds(pool_ray):
    ds = data.range(40, parallelism=10).map_batches(
        StatefulUDF, concurrency=(1, 3), fn_constructor_args=(0,)
    )
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(40))
    assert 1 <= len({r["actor"] for r in rows}) <= 3


def test_class_udf_requires_no_explicit_compute(pool_ray):
    # A class fn implies compute="actors" (reference: map_batches(ClassUDF,
    # concurrency=N)).
    ds = data.range(8, parallelism=2).map_batches(
        StatefulUDF, concurrency=1, fn_constructor_args=(1,)
    )
    assert sorted(r["id"] for r in ds.take_all()) == list(range(1, 9))


class DieOnceUDF:
    """Kills its own worker process the first time it sees the marker file
    absent — the restarted actor (max_restarts) must finish the job."""

    def __init__(self, marker):
        self.marker = marker

    def __call__(self, batch):
        if not os.path.exists(self.marker):
            open(self.marker, "w").write("died")
            os._exit(1)
        return {"id": batch["id"]}


def test_pool_actor_failure_restarts_and_completes(pool_ray, tmp_path):
    marker = str(tmp_path / "died_once")
    ds = data.range(24, parallelism=6).map_batches(
        DieOnceUDF, concurrency=1, fn_constructor_args=(marker,)
    )
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(24))
    assert os.path.exists(marker), "the failure injection never fired"


def test_fn_constructor_args_rejected_for_tasks(pool_ray):
    with pytest.raises(ValueError):
        data.range(4).map_batches(lambda b: b, fn_constructor_args=(1,))


# ---------------------------------------------------------------------------
# LLM batch inference stage
# ---------------------------------------------------------------------------

@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_llm_batch_generate(pool_ray):
    from ray_tpu.llm import batch_generate

    prompts = ["hello world", "the quick brown fox", "hello world", "tpu go brrr"]
    ds = data.from_items([{"prompt": p, "i": i} for i, p in enumerate(prompts)],
                         parallelism=2)
    out = batch_generate(
        ds,
        model_config=dict(
            vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=128, attention_impl="reference",
        ),
        engine_config={"max_slots": 4, "max_seq": 128, "prefill_buckets": (16, 32)},
        sampling={"max_tokens": 8},
        concurrency=1,
    )
    rows = sorted(out.take_all(), key=lambda r: r["i"])
    assert len(rows) == 4
    by_prompt = {}
    for r in rows:
        assert isinstance(r["generated_text"], str)
        assert len(r["generated_text_tokens"]) == 8  # greedy, no eos in tiny vocab
        by_prompt.setdefault(r["prompt"], set()).add(tuple(r["generated_text_tokens"]))
    # Same prompt in DIFFERENT blocks decodes identically (greedy engine
    # state is clean across blocks on the same pool actor).
    assert all(len(v) == 1 for v in by_prompt.values()), by_prompt
