"""Device-tensor object transport (reference: gpu_object_manager — tensors
bypass the generic serialization path; here a single-device jax.Array rides
the protocol-5 out-of-band buffer path as one host copy)."""
import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu as rt
from ray_tpu.core import serialization as S


def test_jax_array_out_of_band_serialization():
    x = jnp.arange(1 << 16, dtype=jnp.float32)
    parts, _refs, total = S.serialize_parts(x)
    # OOB path: tag part + (len, payload) per buffer + body = >= 4 parts.
    assert len(parts) >= 4
    y = S.deserialize(b"".join(bytes(p) for p in parts))
    assert isinstance(y, jax.Array)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_jax_array_bfloat16_roundtrip():
    x = jnp.linspace(-2, 2, 4096, dtype=jnp.bfloat16)
    data, _ = S.serialize(x)
    y = S.deserialize(data)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_sharded_array_ships_per_shard_buffers():
    """A sharded array rides the wire as one OOB buffer PER SHARD (no
    whole-array host gather) and reassembles onto an equivalent mesh of the
    receiver's devices with the sharding intact."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel import MeshSpec

    mesh = MeshSpec(data=-1).build()
    n_dev = len(mesh.devices.flat)
    x = jax.device_put(
        jnp.arange(64 * n_dev, dtype=jnp.float32).reshape(n_dev * 8, 8),
        NamedSharding(mesh, P("data")),
    )
    assert len(x.sharding.device_set) > 1
    parts, _refs, _total = S.serialize_parts(x)
    # OOB layout: tag, then (len, payload) per buffer, then the pickle body.
    # Every shard is its own buffer, each exactly shard-sized — the absence
    # of any full-array-sized buffer proves no host gather happened.
    payloads = parts[2:-1:2]
    shard_bytes = x.nbytes // n_dev
    assert len(payloads) == n_dev, f"expected {n_dev} shard buffers"
    assert all(len(p) == shard_bytes for p in payloads)
    y = S.deserialize(b"".join(bytes(p) for p in parts))
    assert isinstance(y, jax.Array)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    # Sharding survives: same axis layout, one shard per device again.
    assert len(y.sharding.device_set) == n_dev
    assert [s.index for s in y.addressable_shards] == [s.index for s in x.addressable_shards]


def test_sharded_replicated_axis_roundtrip():
    """Partial replication (a spec that leaves an axis unused) round-trips:
    every device gets its (duplicate) shard, values and layout intact."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel import MeshSpec

    mesh = MeshSpec(data=2, tensor=-1).build()
    n_tensor = mesh.shape["tensor"]
    x = jax.device_put(
        jnp.arange(256, dtype=jnp.float32).reshape(16, 16),
        NamedSharding(mesh, P(None, "tensor")),  # replicated over data
    )
    parts, _refs, _total = S.serialize_parts(x)
    # Replicated shards dedup on the wire: one buffer per UNIQUE shard
    # (n_tensor), not one per device (2 * n_tensor).
    payloads = parts[2:-1:2]
    assert len(payloads) == n_tensor, f"replicas not deduped: {len(payloads)}"
    y = S.deserialize(b"".join(bytes(p) for p in parts))
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    assert len(y.sharding.device_set) == len(x.sharding.device_set)


def test_device_array_through_object_store():
    rt.init(num_cpus=2)
    try:
        x = jnp.full((1 << 20,), 3.5, dtype=jnp.float32)  # 4MB: shm path
        ref = rt.put(x)
        y = rt.get(ref, timeout=120)
        assert isinstance(y, jax.Array)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))

        @rt.remote
        def double(a):
            return a * 2

        # Generous timeout: a fresh worker pays the full jax import under
        # whatever CPU contention the rest of the suite left behind.
        z = rt.get(double.remote(ref), timeout=300)
        assert isinstance(z, jax.Array)
        assert float(z[0]) == 7.0
    finally:
        rt.shutdown()
