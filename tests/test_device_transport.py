"""Device-tensor object transport (reference: gpu_object_manager — tensors
bypass the generic serialization path; here a single-device jax.Array rides
the protocol-5 out-of-band buffer path as one host copy)."""
import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu as rt
from ray_tpu.core import serialization as S


def test_jax_array_out_of_band_serialization():
    x = jnp.arange(1 << 16, dtype=jnp.float32)
    parts, _refs, total = S.serialize_parts(x)
    # OOB path: tag part + (len, payload) per buffer + body = >= 4 parts.
    assert len(parts) >= 4
    y = S.deserialize(b"".join(bytes(p) for p in parts))
    assert isinstance(y, jax.Array)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_jax_array_bfloat16_roundtrip():
    x = jnp.linspace(-2, 2, 4096, dtype=jnp.bfloat16)
    data, _ = S.serialize(x)
    y = S.deserialize(data)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_sharded_array_falls_back_to_default_pickle():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel import MeshSpec

    mesh = MeshSpec(data=-1).build()
    x = jax.device_put(
        jnp.arange(64, dtype=jnp.float32),
        NamedSharding(mesh, P("data")),
    )
    assert len(x.sharding.device_set) > 1
    data, _ = S.serialize(x)
    y = S.deserialize(data)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_device_array_through_object_store():
    rt.init(num_cpus=2)
    try:
        x = jnp.full((1 << 20,), 3.5, dtype=jnp.float32)  # 4MB: shm path
        ref = rt.put(x)
        y = rt.get(ref, timeout=120)
        assert isinstance(y, jax.Array)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))

        @rt.remote
        def double(a):
            return a * 2

        # Generous timeout: a fresh worker pays the full jax import under
        # whatever CPU contention the rest of the suite left behind.
        z = rt.get(double.remote(ref), timeout=300)
        assert isinstance(z, jax.Array)
        assert float(z[0]) == 7.0
    finally:
        rt.shutdown()
