"""`ray_tpu start` bootstrap: a REAL two-host-shaped cluster formed from two
separate OS processes — no `Cluster`, no shared Python state — then driven
purely via `--address` (reference: `ray start --head` / `--address`,
/root/reference/python/ray/scripts/scripts.py:682).

The head and the joining node are each `python -m ray_tpu start` subprocesses
(the CLI's detached mode, exactly what an operator types on each pod host);
the driver is THIS process connecting by address. Token distribution rides
RAYTPU_AUTH_TOKEN, the multi-host path.
"""
import json
import os
import subprocess
import sys
import time

import pytest

TOKEN = "start-cli-test-token"


@pytest.fixture(autouse=True)
def _fresh_auth_state():
    """These tests assert properties of THIS test's session lifecycle
    (mint -> scrub). Under a sequential full-suite run, an EARLIER module's
    leaked state — an unscrubbed token in the process-global Config, a
    Cluster record left in _LIVE_CLUSTERS by a crashed teardown, a stale
    rpc frame key — made all three fail while each passes in isolation
    (VERDICT r05 Weak #1). Force a clean slate on entry and exit instead of
    asserting the previous module behaved: prior-test hygiene is not what
    these tests are for, and a stale key would also make this module's
    driver MAC-fail every frame against its own freshly-tokened cluster
    (the observed connect timeout)."""
    from ray_tpu.core import api, rpc
    from ray_tpu.core.config import get_config

    def scrub():
        cfg = get_config()
        cfg.auth_token = type(cfg)().apply_env().auth_token
        rpc.set_auth_token(cfg.auth_token or None)
        # Drop dead Cluster records: a live cluster's service thread is
        # running; anything else only serves to make
        # _token_owned_by_live_cluster veto the stale-mint drop for a
        # session that no longer exists.
        api._LIVE_CLUSTERS[:] = [
            c for c in api._LIVE_CLUSTERS
            if getattr(getattr(c, "host", None), "thread", None) is not None
            and c.host.thread.is_alive()
        ]

    scrub()
    yield
    scrub()


def _cli(env, *args, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu", *args],
        env=env, capture_output=True, text=True, timeout=timeout,
    )


@pytest.fixture
def cli_cluster(tmp_path):
    env = dict(os.environ)
    env["RAYTPU_STATE_DIR"] = str(tmp_path / "state")
    env["RAYTPU_AUTH_TOKEN"] = TOKEN
    addr_file = str(tmp_path / "head_addr")

    head = _cli(env, "start", "--head", "--port", "0", "--num-cpus", "4",
                "--no-tpu-autodetect", "--address-file", addr_file,
                "--startup-timeout", "240")
    assert head.returncode == 0, f"head start failed:\n{head.stdout}\n{head.stderr}"
    addr = open(addr_file).read().strip()

    join = _cli(env, "start", f"--address={addr}", "--num-cpus", "4",
                "--resources", '{"joiner": 1}', "--no-tpu-autodetect",
                "--startup-timeout", "240")
    assert join.returncode == 0, f"join failed:\n{join.stdout}\n{join.stderr}"

    yield addr, env

    stop = _cli(env, "stop")
    assert "stopped" in stop.stdout


def test_minted_token_scrubbed_on_shutdown():
    """Regression (round-4 order-sensitive ConnectionLost): an in-process
    session auto-mints its RPC token into the process-global Config; shutdown
    must scrub it, or the next init(address=...) in the same process
    authenticates to a fresh cluster with the dead session's secret and every
    frame fails the MAC check."""
    import ray_tpu as rt
    from ray_tpu.core.config import get_config

    assert not get_config().auth_token
    rt.init(num_cpus=1)
    try:
        assert get_config().auth_token, "in-process cluster should auto-mint"
    finally:
        rt.shutdown()
    assert not get_config().auth_token, "stale session token leaked into global config"


def test_stale_minted_token_dropped_on_head_init():
    """Defense in depth for the suite-scale leak, HEAD-init side: even if
    some teardown DID leave a dead session's auto-minted token in the
    global config (skipped scrub), a new in-process cluster must drop it
    and mint fresh. (The address-connect side of the same guard is
    exercised by test_start_cli_two_process_cluster, which deliberately
    seeds a stale mint before rt.init(address=...).)"""
    from ray_tpu.core import api
    from ray_tpu.core.config import get_config

    cfg = get_config()
    assert not cfg.auth_token
    try:
        cfg.auth_token = "deadbeef" * 4
        api._MINTED_HISTORY.add(cfg.auth_token)  # simulate a leaked mint
        import ray_tpu as rt

        rt.init(num_cpus=1)  # head init re-mints fresh (stale token dropped?)
        rt.shutdown()
        assert not get_config().auth_token
    finally:
        cfg.auth_token = ""


def test_start_cli_two_process_cluster(cli_cluster):
    addr, env = cli_cluster
    import ray_tpu as rt
    from ray_tpu.core import api
    from ray_tpu.core.config import get_config

    # The round-4 flake fired only when OTHER tests' sessions ran first in
    # this process: reproduce that deliberately with a throwaway in-process
    # session before connecting to the CLI-started cluster, AND with a
    # deliberately-leaked stale minted token (the suite-scale failure mode:
    # some earlier teardown skipped its scrub).
    rt.init(num_cpus=1)
    rt.shutdown()
    get_config().auth_token = "feedface" * 4
    api._MINTED_HISTORY.add(get_config().auth_token)

    rt.init(address=addr)  # must rediscover via the session token file
    try:
        # Both standalone daemons registered.
        deadline = time.time() + 60
        while time.time() < deadline:
            s = api._cluster_state()
            if sum(1 for n in s["nodes"].values() if n["state"] == "ALIVE") >= 2:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"joiner never registered: {s['nodes']}")

        # Task — targeted at the joining process's node.
        @rt.remote(resources={"joiner": 1})
        def whoami():
            return rt.get_runtime_context().node_id

        @rt.remote
        def double(x):
            return 2 * x

        joiner_node = rt.get(whoami.remote(), timeout=120)
        assert rt.get(double.remote(21), timeout=120) == 42

        # Actor pinned to the joiner, surviving across calls.
        @rt.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        c = Counter.options(resources={"joiner": 0.5}).remote()
        assert [rt.get(c.inc.remote(), timeout=120) for _ in range(3)] == [1, 2, 3]

        # Placement group spanning both OS processes.
        pg = rt.placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
        assert pg.ready(timeout=120)
        nodes = set(pg.bundle_nodes())
        assert len(nodes) == 2 and joiner_node in nodes

        # Train gang across the two daemons.
        from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig
        from ray_tpu import train

        def loop(config):
            ctx = train.get_context()
            for i in range(2):
                train.report({"step": i, "rank": ctx.get_world_rank()})

        trainer = DataParallelTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2, resources_per_worker={"CPU": 2}),
            run_config=RunConfig(name="cli_gang"),
        )
        result = trainer.fit()
        assert result.error is None
        assert result.metrics["step"] == 1
    finally:
        rt.shutdown()


def test_stop_kills_recorded_processes(tmp_path):
    env = dict(os.environ)
    env["RAYTPU_STATE_DIR"] = str(tmp_path / "state")
    env["RAYTPU_AUTH_TOKEN"] = TOKEN
    addr_file = str(tmp_path / "addr")
    head = _cli(env, "start", "--head", "--port", "0", "--num-cpus", "1",
                "--no-tpu-autodetect", "--address-file", addr_file)
    assert head.returncode == 0, head.stderr
    state_dir = tmp_path / "state"
    recs = [json.load(open(state_dir / f)) for f in os.listdir(state_dir)
            if f.startswith("proc-")]
    assert len(recs) == 1 and recs[0]["role"] == "head"
    pid = recs[0]["pid"]
    _cli(env, "stop")
    deadline = time.time() + 15
    while time.time() < deadline:
        try:
            os.kill(pid, 0)
        except OSError:
            break
        time.sleep(0.1)
    else:
        raise AssertionError(f"head pid {pid} still alive after stop")
    assert not [f for f in os.listdir(state_dir) if f.startswith("proc-")]
