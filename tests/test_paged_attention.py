"""Paged decode attention: reference vs contiguous oracle, Pallas kernel
(interpret mode) vs reference — GQA, ragged lengths, partial pages."""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.paged_attention import (
    paged_attention,
    paged_attention_reference,
)


def _make_case(B, H, KV, D, ps, ppseq, lengths, seed=0):
    """Random paged cache where sequence b owns pages [b*ppseq .. ) shuffled,
    plus a contiguous copy for the oracle."""
    rng = np.random.default_rng(seed)
    P_total = B * ppseq + 1  # page 0 reserved as the dead-entry target
    q = rng.normal(size=(B, H, D)).astype(np.float32)
    k_pages = rng.normal(size=(KV, P_total, ps, D)).astype(np.float32)
    v_pages = rng.normal(size=(KV, P_total, ps, D)).astype(np.float32)
    page_indices = np.zeros((B, ppseq), np.int32)
    for b in range(B):
        n_used = math.ceil(lengths[b] / ps)
        perm = rng.permutation(np.arange(1, P_total))[:n_used]
        page_indices[b, :n_used] = perm
    # Contiguous K/V per sequence for the oracle.
    k_full = np.zeros((B, KV, ppseq * ps, D), np.float32)
    v_full = np.zeros((B, KV, ppseq * ps, D), np.float32)
    for b in range(B):
        for j in range(ppseq):
            pg = page_indices[b, j]
            k_full[b, :, j * ps:(j + 1) * ps] = k_pages[:, pg]
            v_full[b, :, j * ps:(j + 1) * ps] = v_pages[:, pg]
    return (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(np.asarray(lengths, np.int32)), jnp.asarray(page_indices),
            jnp.asarray(k_full), jnp.asarray(v_full))


def _oracle(q, k_full, v_full, lengths):
    B, H, D = q.shape
    KV = k_full.shape[1]
    group = H // KV
    S = k_full.shape[2]
    qg = q.reshape(B, KV, group, D)
    s = jnp.einsum("bkgd,bksd->bkgs", qg, k_full) / math.sqrt(D)
    valid = (jnp.arange(S)[None, :] < lengths[:, None])[:, None, None, :]
    s = jnp.where(valid, s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bkgs,bksd->bkgd", p, v_full).reshape(B, H, D)


@pytest.mark.parametrize("H,KV", [(8, 8), (8, 2), (16, 4)])
def test_reference_matches_oracle(H, KV):
    lengths = [1, 17, 64, 33]
    q, kp, vp, lens, pidx, kf, vf = _make_case(
        B=4, H=H, KV=KV, D=64, ps=16, ppseq=4, lengths=lengths
    )
    got = paged_attention_reference(q, kp, vp, lens, pidx)
    want = _oracle(q, kf, vf, lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("H,KV", [(8, 8), (8, 2), (16, 4)])
def test_kernel_matches_reference(H, KV):
    lengths = [5, 16, 61, 128]
    q, kp, vp, lens, pidx, _, _ = _make_case(
        B=4, H=H, KV=KV, D=64, ps=32, ppseq=4, lengths=lengths, seed=1
    )
    want = paged_attention_reference(q, kp, vp, lens, pidx)
    got = paged_attention(q, kp, vp, lens, pidx, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3)


def test_kernel_ragged_and_single_page():
    # Lengths straddling page boundaries, incl. a 1-token sequence; large
    # group (no sublane padding) and page_size 128 lane-width case.
    q, kp, vp, lens, pidx, _, _ = _make_case(
        B=3, H=16, KV=2, D=128, ps=128, ppseq=2, lengths=[1, 129, 256], seed=2
    )
    want = paged_attention_reference(q, kp, vp, lens, pidx)
    got = paged_attention(q, kp, vp, lens, pidx, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3)


def test_dead_table_entries_are_ignored():
    """Entries past a sequence's length point at page 0 (shared, full of
    data) — they must not contribute."""
    q, kp, vp, lens, pidx, _, _ = _make_case(
        B=2, H=4, KV=4, D=64, ps=16, ppseq=8, lengths=[16, 40], seed=3
    )
    want = paged_attention_reference(q, kp, vp, lens, pidx)
    got = paged_attention(q, kp, vp, lens, pidx, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3)
