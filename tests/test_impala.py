"""IMPALA: v-trace correctness vs a brute-force recursion + async
actor-learner learning CartPole (reference analogue:
rllib/algorithms/impala/impala.py:521 + per-algorithm CartPole smoke)."""
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.rl import IMPALA, IMPALAConfig
from ray_tpu.rl.impala import vtrace_targets


def _vtrace_numpy(values, last_v, rewards, dones, terms, log_rhos,
                  gamma, rho_bar, c_bar):
    """Straight transcription of the v-trace recursion (Espeholt et al. 2018
    eq. 1) with this runtime's done/term conventions."""
    T, N = rewards.shape
    rhos = np.minimum(rho_bar, np.exp(log_rhos))
    cs = np.minimum(c_bar, np.exp(log_rhos))
    v_next = np.concatenate([values[1:], last_v[None]], axis=0)
    deltas = rhos * (rewards + gamma * (1 - terms) * v_next - values)
    vs = np.zeros_like(values)
    acc = np.zeros(N, np.float32)
    for t in range(T - 1, -1, -1):
        acc = deltas[t] + gamma * (1 - dones[t]) * cs[t] * acc
        vs[t] = values[t] + acc
    vs_next = np.concatenate([vs[1:], last_v[None]], axis=0)
    boot = np.where(dones > 0, v_next, vs_next)
    q = rewards + gamma * (1 - terms) * boot
    return vs, q


def test_vtrace_matches_bruteforce():
    rng = np.random.default_rng(3)
    T, N = 9, 4
    values = rng.standard_normal((T, N)).astype(np.float32)
    last_v = rng.standard_normal(N).astype(np.float32)
    rewards = rng.standard_normal((T, N)).astype(np.float32)
    dones = (rng.random((T, N)) < 0.25).astype(np.float32)
    terms = dones * (rng.random((T, N)) < 0.5)
    log_rhos = (0.5 * rng.standard_normal((T, N))).astype(np.float32)
    vs, q = vtrace_targets(values, last_v, rewards, dones, terms, log_rhos,
                           0.97, 1.0, 1.0)
    evs, eq = _vtrace_numpy(values, last_v, rewards, dones, terms, log_rhos,
                            0.97, 1.0, 1.0)
    np.testing.assert_allclose(np.asarray(vs), evs, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(q), eq, rtol=1e-5, atol=1e-5)


def test_vtrace_on_policy_reduces_to_lambda1_gae_targets():
    """With rho == c == 1 (on-policy, no clipping active), vs - V must equal
    the lambda=1 GAE advantage — v-trace generalizes n-step returns."""
    from ray_tpu.rl.learner import compute_gae

    rng = np.random.default_rng(5)
    T, N = 8, 3
    values = rng.standard_normal((T, N)).astype(np.float32)
    last_v = rng.standard_normal(N).astype(np.float32)
    rewards = rng.standard_normal((T, N)).astype(np.float32)
    dones = (rng.random((T, N)) < 0.3).astype(np.float32)
    log_rhos = np.zeros((T, N), np.float32)
    vs, _ = vtrace_targets(values, last_v, rewards, dones, dones, log_rhos,
                           0.95, 1.0, 1.0)
    adv, _ = compute_gae(rewards, values, dones, dones, last_v, 0.95, 1.0)
    np.testing.assert_allclose(np.asarray(vs) - values, adv, rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_impala_learns_cartpole():
    """Async decoupled sampling + v-trace solves CartPole (>=450 mean
    return). Measured on this host, IMPALA reaches 450 in ~105s / ~230k env
    steps where PPO at the same env budget is still below 450 at ~490k steps
    — the wall-clock claim the async pipeline exists for. The test bar stays
    'solves within the step budget' to keep CI robust; env_steps_per_sec is
    asserted present (throughput is a first-class IMPALA metric)."""
    rt.init(num_cpus=8)
    algo = IMPALAConfig(num_env_runners=2, num_envs_per_runner=8,
                        rollout_len=64, batches_per_iter=8, seed=1).build()
    try:
        best = 0.0
        for _ in range(150):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            assert result["env_steps_per_sec"] > 0
            if result["episode_return_mean"] >= 450.0:
                break
        assert best >= 450.0, f"IMPALA failed to learn CartPole: best {best}"
    finally:
        algo.stop()
        rt.shutdown()
