"""Control-plane fault tolerance: controller crash + restart with snapshot
restore, daemon/driver re-registration, actor reconciliation.
Reference analogue: python/ray/tests/test_gcs_fault_tolerance.py (GCS restart
with Redis persistence; detached actors survive, clients reconnect)."""
import time

import pytest

import ray_tpu as rt
from ray_tpu.core.api import Cluster, init, shutdown
from ray_tpu.core.config import Config


@pytest.fixture
def ft_cluster(tmp_path):
    cfg = Config().apply_env()
    cfg.controller_reconcile_grace_s = 3.0
    cluster = Cluster(initialize_head=False, config=cfg, persist_path=str(tmp_path / "controller.snap"))
    cluster.add_node(num_cpus=4)
    init(address=cluster.address, config=cfg)
    yield cluster
    shutdown()
    cluster.shutdown()


def test_state_survives_controller_restart(ft_cluster):
    cluster = ft_cluster

    @rt.remote
    class Counter:
        def __init__(self):
            self.v = 0

        def inc(self):
            self.v += 1
            return self.v

    c = Counter.options(name="survivor", lifetime="detached").remote()
    assert rt.get(c.inc.remote(), timeout=60) == 1
    assert rt.get(c.inc.remote(), timeout=60) == 2

    pg = rt.placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=30)

    from ray_tpu.core import api

    core = api._require_worker()
    core._run(core.controller.call("kv_put", {"ns": "ft", "key": "k", "value": b"v1"}))
    time.sleep(0.6)  # let the snapshot loop persist

    cluster.restart_controller()
    time.sleep(1.5)  # daemons re-register over their persistent connections

    # KV survived.
    assert core._run(core.controller.call("kv_get", {"ns": "ft", "key": "k"})) == b"v1"
    # Named actor survived: same process, state intact, calls still work.
    c2 = rt.get_actor("survivor")
    assert rt.get(c2.inc.remote(), timeout=60) == 3
    # The ORIGINAL handle keeps working too (direct peer connection).
    assert rt.get(c.inc.remote(), timeout=60) == 4
    # PG reservation survived.
    info = core._run(core.controller.call("get_placement_group", {"pg_id": pg.id}))
    assert info is not None and info["state"] == "CREATED"
    # New tasks schedule on the restored control plane.

    @rt.remote
    def add(a, b):
        return a + b

    assert rt.get(add.remote(2, 3), timeout=60) == 5
    rt.remove_placement_group(pg)


def test_actor_lost_during_outage_is_restarted(ft_cluster):
    cluster = ft_cluster
    victim = cluster.add_node(num_cpus=2, resources={"special": 1.0})

    @rt.remote(resources={"special": 1.0}, max_restarts=2)
    class Phoenix:
        def pid(self):
            import os

            return os.getpid()

    p = Phoenix.options(name="phoenix", lifetime="detached").remote()
    pid1 = rt.get(p.pid.remote(), timeout=60)
    time.sleep(0.6)  # snapshot
    # Crash the controller AND kill the actor's node while it is down.
    port = int(cluster.controller_addr.rsplit(":", 1)[1])
    cluster.host.call(cluster.controller.stop())
    cluster.remove_node(victim)
    from ray_tpu.core.controller import Controller

    cluster.controller = Controller(cluster.config, persist_path=cluster.controller.persist_path)
    cluster.host.call(cluster.controller.start(port))
    # A replacement feasible node joins AFTER the restart; once the reconcile
    # grace expires the unconfirmed actor is restarted there.
    cluster.add_node(num_cpus=2, resources={"special": 1.0})
    deadline = time.time() + 40
    pid2 = None
    while time.time() < deadline:
        try:
            pid2 = rt.get(rt.get_actor("phoenix").pid.remote(), timeout=10)
            if pid2 != pid1:
                break
        except Exception:
            time.sleep(0.5)
    assert pid2 is not None and pid2 != pid1
