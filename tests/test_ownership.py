"""Distributed ownership / borrower-chain semantics (reference:
ReferenceCounter borrower bookkeeping, src/ray/core_worker/reference_counter.h
— the owner keeps an object alive while ANY transitive borrower holds a ref,
including borrowers that received the ref from another borrower, not from
the owner)."""
import gc
import time

import numpy as np
import pytest

import ray_tpu as rt


@pytest.fixture(scope="module", autouse=True)
def _session():
    rt.init(num_cpus=3, object_store_memory=128 * 1024 * 1024)
    yield
    rt.shutdown()


@rt.remote
class Holder:
    def __init__(self):
        self.ref = None

    def stash(self, box):
        self.ref = box[0]
        return True

    def read(self):
        return float(rt.get(self.ref, timeout=60).sum())

    def drop(self):
        self.ref = None
        return True


@rt.remote
class Middleman:
    def __init__(self, box):
        self.r = box[0]

    def hand_over(self):
        return [self.r]  # the ref travels borrower -> borrower


def test_borrower_chain_outlives_intermediate():
    """driver -> A (borrower) -> B (borrower-of-borrower): after the driver
    drops its refs and A is killed, B must still resolve the value; the owner
    frees only when B drops too."""
    x = np.ones(1 << 20)  # 8MB: shm object, not inline
    ref = rt.put(x)
    a = Middleman.remote([ref])
    handed = rt.get(a.hand_over.remote(), timeout=60)[0]
    b = Holder.remote()
    rt.get(b.stash.remote([handed]), timeout=60)
    del ref, handed, x
    rt.kill(a)
    gc.collect()
    time.sleep(1.0)
    assert rt.get(b.read.remote(), timeout=60) == float(1 << 20)
    rt.get(b.drop.remote(), timeout=60)


def test_ref_in_container_not_resolved_bare_ref_is():
    """Top-level ObjectRef args resolve to values before the method runs;
    refs nested in containers pass through as refs (reference arg semantics)."""
    ref = rt.put(41)

    @rt.remote
    def probe(bare, boxed):
        return type(bare).__name__, type(boxed[0]).__name__

    bare_t, boxed_t = rt.get(probe.remote(ref, [ref]), timeout=60)
    assert bare_t == "int"
    assert boxed_t == "ObjectRef"
