"""Streaming generator tasks/actor methods (reference: streaming generators,
ReportGeneratorItemReturns + TaskManager streaming returns)."""
import numpy as np
import pytest

import ray_tpu as rt


@pytest.fixture(scope="module", autouse=True)
def _session():
    rt.init(num_cpus=4)
    yield
    rt.shutdown()


@rt.remote(num_returns="streaming")
def count_to(n):
    for i in range(n):
        yield i * 10


@rt.remote(num_returns="streaming")
def big_blocks(n, nbytes):
    for i in range(n):
        yield np.full(nbytes // 8, i, dtype=np.int64)


@rt.remote(num_returns="streaming")
def explodes_midway():
    yield "ok-0"
    yield "ok-1"
    raise ValueError("stream blew up")


@rt.remote(num_returns="streaming")
def not_a_generator():
    return 7


@rt.remote
class Streamer:
    def gen(self, n):
        for i in range(n):
            yield f"item-{i}"

    async def agen(self, n):
        for i in range(n):
            yield i + 100


def test_task_streaming_basic():
    gen = count_to.remote(5)
    assert isinstance(gen, rt.ObjectRefGenerator)
    got = [rt.get(ref, timeout=60) for ref in gen]
    assert got == [0, 10, 20, 30, 40]


def test_task_streaming_incremental_consumption():
    """Items are consumable before the producer finishes (the point of
    streaming): the first ref resolves while later items are still being
    produced."""
    gen = count_to.remote(50)
    first = rt.get(next(gen), timeout=60)
    assert first == 0
    rest = [rt.get(r, timeout=60) for r in gen]
    assert rest == [i * 10 for i in range(1, 50)]


def test_task_streaming_large_items_via_shm():
    gen = big_blocks.remote(3, 1 << 20)  # 1MB blocks: over the inline cap
    vals = [rt.get(r, timeout=120) for r in gen]
    assert [int(v[0]) for v in vals] == [0, 1, 2]
    assert all(v.nbytes == 1 << 20 for v in vals)


def test_task_streaming_error_after_items():
    gen = explodes_midway.remote()
    assert rt.get(next(gen), timeout=60) == "ok-0"
    assert rt.get(next(gen), timeout=60) == "ok-1"
    with pytest.raises(Exception, match="stream blew up"):
        next(gen)


def test_task_streaming_non_generator_is_an_error():
    gen = not_a_generator.remote()
    with pytest.raises(Exception, match="not a generator"):
        next(gen)


def test_actor_streaming_sync_method():
    a = Streamer.remote()
    gen = a.gen.options(num_returns="streaming").remote(4)
    assert [rt.get(r, timeout=60) for r in gen] == [f"item-{i}" for i in range(4)]


def test_actor_streaming_async_method():
    a = Streamer.remote()
    gen = a.agen.options(num_returns="streaming").remote(3)
    assert [rt.get(r, timeout=60) for r in gen] == [100, 101, 102]


def test_streaming_generator_empty():
    gen = count_to.remote(0)
    assert list(gen) == []


def test_streaming_backpressure_paces_producer():
    """generator_backpressure=2: the producer may run at most 2 items ahead
    of consumption."""
    import time

    @rt.remote(num_returns="streaming", generator_backpressure=2)
    def paced():
        for i in range(6):
            yield (i, time.time())

    gen = paced.remote()
    first_ref = next(gen)
    time.sleep(1.5)  # producer should stall at ~index 2 while we sit idle
    vals = [rt.get(first_ref, timeout=60)] + [rt.get(r, timeout=60) for r in gen]
    assert [v[0] for v in vals] == list(range(6))
    # Item 3+ must have been produced AFTER the consumer-side sleep started,
    # i.e. its timestamp is >= item0's + ~1.5s (unbounded streaming would
    # produce all 6 immediately).
    assert vals[5][1] - vals[0][1] > 1.0, "producer ran ahead despite backpressure"


def test_method_decorator_num_returns():
    @rt.remote
    class Declared:
        @rt.method(num_returns=2)
        def pair(self):
            return 1, 2

        @rt.method(num_returns="streaming")
        def stream(self):
            yield "a"
            yield "b"

    d = Declared.remote()
    r1, r2 = d.pair.remote()
    assert rt.get([r1, r2], timeout=60) == [1, 2]
    assert [rt.get(r, timeout=60) for r in d.stream.remote()] == ["a", "b"]


def _count_lines(path):
    try:
        with open(path) as f:
            return len(f.read().splitlines())
    except FileNotFoundError:
        return 0


def test_stream_close_cancels_task_producer(tmp_path):
    """gen.close() reaches the producing worker: the generator stops at its
    next yield instead of running to completion (reference: CancelTask
    applied to streaming generators)."""
    import time

    marker = str(tmp_path / "task_progress")

    @rt.remote(num_returns="streaming")
    def slow_stream(path, n):
        for i in range(n):
            with open(path, "a") as f:
                f.write(f"{i}\n")
            time.sleep(0.05)
            yield i

    gen = slow_stream.remote(marker, 200)
    assert rt.get(next(gen), timeout=60) == 0
    gen.close()
    time.sleep(1.0)
    settled = _count_lines(marker)
    assert settled < 100, f"producer ran on after close ({settled} items)"
    time.sleep(0.7)
    assert _count_lines(marker) == settled, "producer still running after close"


def test_stream_close_cancels_actor_producer(tmp_path):
    import time

    marker = str(tmp_path / "actor_progress")

    @rt.remote
    class Slow:
        def stream(self, path, n):
            for i in range(n):
                with open(path, "a") as f:
                    f.write(f"{i}\n")
                time.sleep(0.05)
                yield i

    a = Slow.remote()
    gen = a.stream.options(num_returns="streaming").remote(marker, 200)
    assert rt.get(next(gen), timeout=60) == 0
    gen.close()
    time.sleep(1.0)
    settled = _count_lines(marker)
    assert settled < 100, f"producer ran on after close ({settled} items)"
    time.sleep(0.7)
    assert _count_lines(marker) == settled, "producer still running after close"
    # The actor itself stays healthy and serves new calls.
    gen2 = a.stream.options(num_returns="streaming").remote(str(tmp_path / "p2"), 3)
    assert [rt.get(r, timeout=60) for r in gen2] == [0, 1, 2]


def test_stream_close_after_exhaustion_is_noop():
    gen = count_to.remote(3)
    assert [rt.get(r, timeout=60) for r in gen] == [0, 10, 20]
    gen.close()  # finished stream: nothing to cancel
