"""Tests: mesh/sharding strategies + flagship transformer on an 8-device CPU
mesh (fake-topology technique, SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import TransformerConfig, cross_entropy_loss, make_train_step
from ray_tpu.models.transformer import forward, init_params, param_logical_axes
from ray_tpu.ops.attention import mha_reference
from ray_tpu.parallel import (
    MeshSpec,
    ShardingStrategy,
    logical_sharding,
    shard_pytree,
)
from ray_tpu.parallel.sharding import use_strategy

CFG = TransformerConfig(
    vocab_size=128, d_model=64, n_layers=2, n_heads=4, d_ff=128,
    max_seq_len=64, dtype=jnp.float32, attention_impl="reference",
)


def test_mesh_spec_infers_axis():
    spec = MeshSpec(data=-1, tensor=2)
    sizes = spec.resolved_sizes(8)
    assert sizes["data"] == 4 and sizes["tensor"] == 2


def test_mesh_spec_rejects_bad_product():
    with pytest.raises(ValueError):
        MeshSpec(data=3, tensor=2).resolved_sizes(8)


def test_mesh_build_8_devices():
    mesh = MeshSpec(data=-1, tensor=2).build()
    assert mesh.shape["tensor"] == 2
    assert np.prod(list(mesh.shape.values())) == 8


def test_strategy_specs():
    from jax.sharding import PartitionSpec as P

    tp = ShardingStrategy.tp()
    assert tp.spec(("embed", "mlp")) == P(None, "tensor")
    fsdp_tp = ShardingStrategy.fsdp() | ShardingStrategy.tp()
    assert fsdp_tp.spec(("embed", "heads", "head_dim")) == P("fsdp", "tensor", None)
    # duplicate mesh axis within one spec is dropped (used once)
    assert fsdp_tp.spec(("mlp", "heads")) == P("tensor", None)
    # batch over combined axes
    assert fsdp_tp.spec(("batch", "seq")) == P(("replica", "data", "fsdp"), None)


def test_strategy_named_composition():
    s = ShardingStrategy.named("fsdp+tp+sp")
    assert s.rules["seq"] == "seq"
    assert s.rules["mlp"] == "tensor"


def test_forward_shapes_and_loss():
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size)
    logits, aux = forward(params, tokens, CFG)
    assert logits.shape == (2, 16, CFG.vocab_size)
    loss = cross_entropy_loss(params, {"tokens": tokens}, CFG)
    assert jnp.isfinite(loss)
    # random init ≈ uniform over vocab
    assert abs(float(loss) - np.log(CFG.vocab_size)) < 1.5


def test_train_step_reduces_loss():
    init_state, train_step, _ = make_train_step(CFG)
    state = init_state(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, CFG.vocab_size)
    step = jax.jit(train_step)
    losses = []
    for _ in range(30):
        state, m = step(state, {"tokens": tokens})
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses[:3] + losses[-3:]


def test_sharded_train_step_matches_single_device():
    """DP+TP sharded step must match unsharded numerics."""
    mesh = MeshSpec(data=2, tensor=4).build()
    strategy = ShardingStrategy.dp() | ShardingStrategy.tp()
    init_state, train_step, state_axes = make_train_step(CFG)
    state = init_state(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, CFG.vocab_size)

    _, m_ref = jax.jit(train_step)(state, {"tokens": tokens})

    axes = state_axes(state)
    with use_strategy(strategy), mesh:
        st = shard_pytree(state, axes, mesh, strategy)
        state_sh = logical_sharding(mesh, strategy, axes)
        batch_sh = strategy.sharding(mesh, ("batch", "seq"))
        data = {"tokens": jax.device_put(tokens, batch_sh)}
        step = jax.jit(
            train_step,
            in_shardings=(state_sh, {"tokens": batch_sh}),
            out_shardings=(state_sh, None),
        )
        _, m_sharded = step(st, data)
    np.testing.assert_allclose(
        float(m_ref["loss"]), float(m_sharded["loss"]), rtol=2e-4
    )


def test_fsdp_actually_shards_params():
    mesh = MeshSpec(fsdp=8).build()
    strategy = ShardingStrategy.fsdp()
    params = init_params(jax.random.PRNGKey(0), CFG)
    axes = param_logical_axes(CFG)
    sharded = shard_pytree(params, axes, mesh, strategy)
    # wq [L, D(embed), H, hd] sharded on dim 1 across 8 devices
    shards = sharded["layers"]["wq"].addressable_shards
    assert len(shards) == 8
    assert shards[0].data.shape[1] == CFG.d_model // 8


def test_moe_forward():
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        n_experts=4, expert_top_k=2, max_seq_len=64, dtype=jnp.float32,
        attention_impl="reference",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits, aux = forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert jnp.isfinite(aux) and float(aux) > 0


def test_ep_shards_experts_and_matches_unsharded():
    """Expert parallelism END-TO-END on the 8-device mesh: ep()|fsdp()
    partitions the expert dim of every expert weight, top-k routed dispatch
    runs sharded, and a sharded train step's loss equals the unsharded twin
    (same init key, same batch) — GSPMD must not change the math."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        n_experts=4, expert_top_k=2, max_seq_len=64, dtype=jnp.float32,
        attention_impl="reference",
    )
    init_state, train_step, state_axes = make_train_step(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (8, 33), 0, cfg.vocab_size)

    mesh = MeshSpec(data=-1, fsdp=2, expert=2).build()
    strategy = ShardingStrategy.ep() | ShardingStrategy.fsdp()
    with use_strategy(strategy), mesh:
        st = init_state(jax.random.PRNGKey(0))
        axes = state_axes(st)
        st = shard_pytree(st, axes, mesh, strategy)
        # Expert weights [L, E, D, F] really partitioned: E over expert (2),
        # D over fsdp (2).
        for name in ("w_gate", "w_up", "w_down"):
            shard = st["params"]["layers"][name].addressable_shards[0].data
            full = st["params"]["layers"][name].shape
            assert shard.shape[1] == cfg.n_experts // 2, (name, shard.shape, full)
        assert st["params"]["layers"]["w_gate"].addressable_shards[0].data.shape[2] \
            == cfg.d_model // 2  # fsdp composes on embed
        st_sh = logical_sharding(mesh, strategy, axes)
        b_sh = strategy.sharding(mesh, ("batch", "seq"))
        batch = {"tokens": jax.device_put(tokens, b_sh)}
        step = jax.jit(train_step, in_shardings=(st_sh, {"tokens": b_sh}),
                       out_shardings=(st_sh, None))
        _, m1 = step(st, batch)
        sharded_loss = float(m1["loss"])

    ref_mesh = MeshSpec(data=-1).build(jax.devices()[:1])
    ref = ShardingStrategy.dp()
    with use_strategy(ref), ref_mesh:
        st = init_state(jax.random.PRNGKey(0))
        axes = state_axes(st)
        st = shard_pytree(st, axes, ref_mesh, ref)
        st_sh = logical_sharding(ref_mesh, ref, axes)
        b_sh = ref.sharding(ref_mesh, ("batch", "seq"))
        batch = {"tokens": jax.device_put(tokens, b_sh)}
        step = jax.jit(train_step, in_shardings=(st_sh, {"tokens": b_sh}),
                       out_shardings=(st_sh, None))
        _, mr = step(st, batch)
        ref_loss = float(mr["loss"])
    np.testing.assert_allclose(sharded_loss, ref_loss, rtol=2e-3)


def test_moe_topk_routing_actually_routes():
    """_moe_ffn's dispatch really routes token s to expert s (hand-crafted
    router): zeroing ONE expert's down-projection changes exactly the tokens
    routed to it and no others."""
    from ray_tpu.models.transformer import _moe_ffn

    cfg = TransformerConfig(
        vocab_size=128, d_model=8, n_layers=1, n_heads=2, d_ff=16,
        n_experts=4, expert_top_k=1, max_seq_len=64, dtype=jnp.float32,
        attention_impl="reference",
    )
    E, D, F = cfg.n_experts, cfg.d_model, cfg.d_ff
    key = jax.random.PRNGKey(0)
    lp = {
        # Router: dim d votes for expert d (d < E) with a huge margin, so
        # one-hot input e_s routes deterministically to expert s.
        "router": jnp.eye(D, E) * 50.0,
        "w_gate": jax.random.normal(key, (E, D, F)) * 0.5,
        "w_up": jax.random.normal(jax.random.PRNGKey(1), (E, D, F)) * 0.5,
        "w_down": jax.random.normal(jax.random.PRNGKey(2), (E, F, D)) * 0.5,
    }
    x = jnp.eye(4, D)[None]  # [1, 4, D]: token s = e_s -> expert s
    out, aux = _moe_ffn(x, lp, cfg)
    assert jnp.isfinite(aux)
    lp_cut = dict(lp, w_down=lp["w_down"].at[2].set(0.0))
    out_cut, _ = _moe_ffn(x, lp_cut, cfg)
    changed = np.asarray(jnp.abs(out - out_cut).sum(-1)[0]) > 1e-6  # per token
    assert list(changed) == [False, False, True, False], changed
    # And expert 2's tokens now produce exactly zero (top_k=1: sole expert).
    np.testing.assert_allclose(np.asarray(out_cut[0, 2]), 0.0, atol=1e-6)


def test_attention_reference_causal():
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 2, 16))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 2, 16))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 8, 2, 16))
    o = mha_reference(q, k, v, causal=True)
    # first position attends only to itself
    o0 = mha_reference(q[:, :1], k[:, :1], v[:, :1], causal=True)
    np.testing.assert_allclose(o[:, 0], o0[:, 0], rtol=1e-5)


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_graft_entry_contract():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 2
    ge.dryrun_multichip(8)


def test_ring_train_step_composes_with_sp():
    """FULL train step (fwd + bwd through the ppermute ring, WITH remat)
    using attention_impl='ring' on a seq-sharded mesh: loss and updated
    params must match the unsharded reference-attention step. This is the
    end-to-end CP composition — sp() shards activations' seq dim, ring
    attention provides full-sequence attention over the ring (VERDICT r4
    weak #3: the kernel existed but had never run inside a train step)."""
    import dataclasses

    cfg_ring = dataclasses.replace(
        CFG, attention_impl="ring", remat=True, n_kv_heads=2  # GQA: KV expand path
    )
    cfg_ref = dataclasses.replace(CFG, attention_impl="reference", n_kv_heads=2)

    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, CFG.vocab_size)
    init_ref, step_ref, _ = make_train_step(cfg_ref)
    state0 = init_ref(jax.random.PRNGKey(0))
    ref_state, m_ref = jax.jit(step_ref)(state0, {"tokens": tokens})

    init_ring, step_ring, state_axes = make_train_step(cfg_ring)
    mesh = MeshSpec(data=2, seq=4).build()
    strategy = ShardingStrategy.dp() | ShardingStrategy.sp()
    axes = state_axes(state0)
    with use_strategy(strategy), mesh:
        st = shard_pytree(init_ring(jax.random.PRNGKey(0)), axes, mesh, strategy)
        state_sh = logical_sharding(mesh, strategy, axes)
        # Tokens shard on batch only (S+1 isn't seq-divisible); the model's
        # logical constraints reshard activations onto the seq axis inside.
        batch_sh = strategy.sharding(mesh, ("batch", None))
        data = {"tokens": jax.device_put(tokens, batch_sh)}
        step = jax.jit(
            step_ring,
            in_shardings=(state_sh, {"tokens": batch_sh}),
            out_shardings=(state_sh, None),
        )
        new_state, m_ring = step(st, data)
        # Two consecutive steps: the bwd-through-ppermute gradients feed a
        # real optimizer update that the next fwd consumes.
        _, m_ring2 = step(new_state, data)
    np.testing.assert_allclose(float(m_ref["loss"]), float(m_ring["loss"]), rtol=2e-4)
    np.testing.assert_allclose(
        float(m_ref["grad_norm"]), float(m_ring["grad_norm"]), rtol=2e-3
    )
    # Updated params match leaf-for-leaf (gradient parity, not just loss).
    np.testing.assert_allclose(
        np.asarray(jax.device_get(new_state["params"]["layers"]["wq"])),
        np.asarray(jax.device_get(ref_state["params"]["layers"]["wq"])),
        atol=2e-5, rtol=2e-4,
    )
    assert float(m_ring2["loss"]) < float(m_ring["loss"])  # learning continues


def test_ring_attention_matches_reference():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.ops.ring_attention import ring_attention

    mesh = MeshSpec(seq=4, data=2).build()
    B, S, H, D = 2, 32, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = [jax.random.normal(kk, (B, S, H, D)) for kk in ks]
    ref = mha_reference(q, k, v, causal=True)
    with mesh:
        sh = NamedSharding(mesh, P(None, "seq", None, None))
        qs, ks_, vs = jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh)
        out = jax.jit(lambda a, b, c: ring_attention(a, b, c, axis_name="seq"))(qs, ks_, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_attention_noncausal():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.ops.ring_attention import ring_attention

    mesh = MeshSpec(seq=8).build()
    B, S, H, D = 1, 64, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = [jax.random.normal(kk, (B, S, H, D)) for kk in ks]
    ref = mha_reference(q, k, v, causal=False)
    with mesh:
        sh = NamedSharding(mesh, P(None, "seq", None, None))
        args = [jax.device_put(x, sh) for x in (q, k, v)]
        out = jax.jit(lambda a, b, c: ring_attention(a, b, c, causal=False))(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_pipeline_train_step_matches_sequential():
    """PP (4 stages) x DP (2): pipelined loss AND updated params must match
    the sequential step exactly (GPipe schedule is math-identical; VERDICT
    round-1 item 7)."""
    from ray_tpu.models import make_pipeline_train_step

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=4, n_heads=4, d_ff=128,
        max_seq_len=64, dtype=jnp.float32, attention_impl="reference",
    )
    mesh = MeshSpec(data=2, stage=4).build()
    init_state, seq_step, state_axes = make_train_step(cfg)
    state = init_state(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, cfg.vocab_size)

    ref_state, m_ref = jax.jit(seq_step)(state, {"tokens": tokens})

    _, pp_step, _ = make_pipeline_train_step(cfg, mesh, n_micro=4)
    strategy = ShardingStrategy.dp() | ShardingStrategy.pp()
    axes = state_axes(state)
    with mesh:
        st = shard_pytree(state, axes, mesh, strategy)
        state_sh = logical_sharding(mesh, strategy, axes)
        batch_sh = strategy.sharding(mesh, ("batch", "seq"))
        data = {"tokens": jax.device_put(tokens, batch_sh)}
        step = jax.jit(
            pp_step,
            in_shardings=(state_sh, {"tokens": batch_sh}),
            out_shardings=(state_sh, None),
        )
        new_state, m_pp = step(st, data)
        jax.block_until_ready(m_pp["loss"])
    np.testing.assert_allclose(float(m_ref["loss"]), float(m_pp["loss"]), rtol=2e-4)
    np.testing.assert_allclose(
        float(m_ref["grad_norm"]), float(m_pp["grad_norm"]), rtol=2e-3
    )
    # Parameter updates identical too (whole-state check, not just metrics).
    ref_leaf = ref_state["params"]["layers"]["wq"]
    pp_leaf = jax.device_get(new_state["params"]["layers"]["wq"])
    np.testing.assert_allclose(np.asarray(ref_leaf), pp_leaf, rtol=5e-3, atol=1e-5)


def test_pipeline_single_stage_fallback():
    """stage=1 mesh: pipeline path must degrade to the plain scan."""
    from ray_tpu.models import make_pipeline_train_step

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        max_seq_len=64, dtype=jnp.float32, attention_impl="reference",
    )
    mesh = MeshSpec(data=-1).build()
    init_state, pp_step, _ = make_pipeline_train_step(cfg, mesh, n_micro=2)
    state = init_state(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size)
    with mesh:
        _, m = jax.jit(pp_step)(state, {"tokens": tokens})
    assert jnp.isfinite(m["loss"])


def test_ulysses_attention_matches_reference():
    """Ulysses all-to-all resharding: exact vs the dense oracle, causal."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.ops.ulysses import ulysses_attention

    mesh = MeshSpec(seq=4, data=2).build()
    B, S, H, D = 2, 32, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = [jax.random.normal(kk, (B, S, H, D)) for kk in ks]
    ref = mha_reference(q, k, v, causal=True)
    with mesh:
        sh = NamedSharding(mesh, P(None, "seq", None, None))
        args = [jax.device_put(x, sh) for x in (q, k, v)]
        out = jax.jit(lambda a, b, c: ulysses_attention(a, b, c))(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_attention_gqa_and_segments():
    """Grouped KV heads stay grouped through the all_to_all; packed-sequence
    segment mask composes (segment ids all_gathered to full length)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.ops.ulysses import ulysses_attention

    mesh = MeshSpec(seq=4).build(jax.devices()[:4])
    B, S, H, KV, D = 2, 32, 8, 4, 8
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, S, H, D))
    k = jax.random.normal(ks[1], (B, S, KV, D))
    v = jax.random.normal(ks[2], (B, S, KV, D))
    seg = jnp.concatenate(
        [jnp.zeros((B, S // 2), jnp.int32), jnp.ones((B, S - S // 2), jnp.int32)], axis=1
    )
    kr = jnp.repeat(k, H // KV, axis=2)
    vr = jnp.repeat(v, H // KV, axis=2)
    ref = mha_reference(q, kr, vr, causal=True, segment_ids=seg)
    with mesh:
        sh = NamedSharding(mesh, P(None, "seq", None, None))
        seg_sh = NamedSharding(mesh, P(None, "seq"))
        qs, ks_, vs = (jax.device_put(x, s) for x, s in ((q, sh), (k, sh), (v, sh)))
        segs = jax.device_put(seg, seg_sh)
        out = jax.jit(
            lambda a, b, c, s: ulysses_attention(a, b, c, segment_ids=s)
        )(qs, ks_, vs, segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_attention_head_indivisible_falls_back_to_ring():
    """H < axis size: Ulysses can't shard heads; must still be exact (ring)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.ops.ulysses import ulysses_attention

    mesh = MeshSpec(seq=8).build()
    B, S, H, D = 1, 64, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q, k, v = [jax.random.normal(kk, (B, S, H, D)) for kk in ks]
    ref = mha_reference(q, k, v, causal=True)
    with mesh:
        sh = NamedSharding(mesh, P(None, "seq", None, None))
        args = [jax.device_put(x, sh) for x in (q, k, v)]
        out = jax.jit(lambda a, b, c: ulysses_attention(a, b, c))(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_chunked_ce_matches_unchunked():
    """ce_chunk computes the same loss AND gradients as the materialized
    path (it exists so [B,S,V] logits never hit HBM — PROFILES.md round 4)."""
    import dataclasses

    from ray_tpu.models.transformer import cross_entropy_loss

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        max_seq_len=64, dtype=jnp.float32, attention_impl="reference",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (3, 33), 0, 128)
    mask = (jax.random.uniform(jax.random.PRNGKey(2), (3, 33)) > 0.3).astype(jnp.float32)
    cfgc = dataclasses.replace(cfg, ce_chunk=8)
    for batch in ({"tokens": tokens}, {"tokens": tokens, "mask": mask}):
        l0 = float(cross_entropy_loss(params, batch, cfg))
        l1 = float(cross_entropy_loss(params, batch, cfgc))
        np.testing.assert_allclose(l0, l1, rtol=1e-5)
        g0 = jax.grad(lambda p: cross_entropy_loss(p, batch, cfg))(params)
        g1 = jax.grad(lambda p: cross_entropy_loss(p, batch, cfgc))(params)
        for k in ("lm_head", "embed"):
            np.testing.assert_allclose(
                np.asarray(g0[k]), np.asarray(g1[k]), rtol=2e-4, atol=1e-6
            )


def test_ce_chunk_falls_back_when_not_divisible():
    """A seq length the chunk doesn't divide silently uses the materialized
    path (same value) instead of failing."""
    import dataclasses

    from ray_tpu.models.transformer import cross_entropy_loss

    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=1, n_heads=4, d_ff=128,
        max_seq_len=64, dtype=jnp.float32, attention_impl="reference",
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 30), 0, 128)  # S=29, not %8
    l0 = float(cross_entropy_loss(params, {"tokens": tokens}, cfg))
    l1 = float(cross_entropy_loss(
        params, {"tokens": tokens}, dataclasses.replace(cfg, ce_chunk=8)))
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
