"""Compiled actor-method DAGs with direct channels.
Reference analogue: python/ray/dag/tests/experimental/test_accelerated_dag.py
(compile, execute, pipelining, error propagation, teardown)."""
import time

import pytest

import ray_tpu as rt
from ray_tpu.dag import InputNode


@pytest.fixture(scope="module")
def dag_actors(shared_ray):
    @rt.remote
    class Doubler:
        def apply(self, x):
            return x * 2

    @rt.remote
    class Adder:
        def __init__(self, k):
            self.k = k

        def apply(self, x):
            return x + self.k

        def add2(self, a, b):
            return a + b

        def boom(self, x):
            raise ValueError("stage exploded")

    d = Doubler.remote()
    a = Adder.remote(10)
    rt.get([d.apply.remote(0), a.apply.remote(0)], timeout=60)  # warm
    return d, a


def test_linear_chain(dag_actors):
    d, a = dag_actors
    with InputNode() as inp:
        out = a.apply.bind(d.apply.bind(inp))
    dag = out.experimental_compile()
    try:
        assert dag.execute(5).result(timeout=60) == 20  # 5*2 + 10
        assert dag.execute(0).result(timeout=60) == 10
    finally:
        dag.teardown()


def test_fan_in_join(dag_actors):
    d, a = dag_actors
    with InputNode() as inp:
        left = d.apply.bind(inp)    # x*2
        right = a.apply.bind(inp)   # x+10
        out = a.add2.bind(left, right)
    dag = out.experimental_compile()
    try:
        assert dag.execute(3).result(timeout=60) == 3 * 2 + 3 + 10
    finally:
        dag.teardown()


def test_pipelined_executions(dag_actors):
    d, a = dag_actors
    with InputNode() as inp:
        out = a.apply.bind(d.apply.bind(inp))
    dag = out.experimental_compile(max_in_flight=8)
    try:
        refs = [dag.execute(i) for i in range(20)]
        assert [r.result(timeout=120) for r in refs] == [i * 2 + 10 for i in range(20)]
    finally:
        dag.teardown()


def test_error_propagates_to_driver(dag_actors):
    d, a = dag_actors
    with InputNode() as inp:
        out = d.apply.bind(a.boom.bind(inp))
    dag = out.experimental_compile()
    try:
        with pytest.raises(ValueError, match="stage exploded"):
            dag.execute(1).result(timeout=60)
        # The DAG stays usable for later sequences after an error.
        with InputNode() as inp2:
            ok = d.apply.bind(inp2)
        dag2 = ok.experimental_compile()
        try:
            assert dag2.execute(4).result(timeout=60) == 8
        finally:
            dag2.teardown()
    finally:
        dag.teardown()


def test_faster_than_driver_round_trips(dag_actors):
    """The compiled path must beat chained .remote()+get through the driver
    (that's its reason to exist)."""
    d, a = dag_actors
    with InputNode() as inp:
        out = a.apply.bind(d.apply.bind(inp))
    dag = out.experimental_compile(max_in_flight=16)
    try:
        N = 50
        t0 = time.perf_counter()
        refs = [dag.execute(i) for i in range(N)]
        compiled = [r.result(timeout=120) for r in refs]
        dag_time = time.perf_counter() - t0
        t0 = time.perf_counter()
        classic = [rt.get(a.apply.remote(d.apply.remote(i)), timeout=60) for i in range(N)]
        classic_time = time.perf_counter() - t0
        assert compiled == classic
        assert dag_time < classic_time, (dag_time, classic_time)
    finally:
        dag.teardown()


def test_large_tensor_rides_shm_channel(dag_actors, shared_ray):
    """A multi-MB ndarray between same-node stages moves through the shared
    arena (zero-copy channel; reference: shared_memory_channel.py), and the
    transient channel objects are acked + deleted afterwards."""
    import gc
    import time as _time

    import numpy as np

    from ray_tpu.core import api as _api

    d, a = dag_actors
    with InputNode() as inp:
        out = a.apply.bind(d.apply.bind(inp))
    compiled = out.experimental_compile()
    try:
        x = np.ones(1 << 20, dtype=np.float64)  # 8MB >> inline cap
        res = compiled.execute(x).result(timeout=120)
        np.testing.assert_array_equal(res, x * 2 + 10)
        res2 = compiled.execute(x * 3).result(timeout=120)
        np.testing.assert_array_equal(res2, x * 6 + 10)
    finally:
        compiled.teardown()
    # Transient edge objects must be reclaimed once consumers acked.
    del res, res2
    gc.collect()
    store = _api._require_worker().store
    deadline = _time.time() + 15
    while _time.time() < deadline:
        leaked = [
            oid for oid, _size in store.list_objects()
            if oid.return_index() == 2**32 - 1  # put-style ids (dag transients + puts)
        ]
        if not leaked:
            break
        _time.sleep(0.3)
    # The driver's own puts may linger (owned refs); what must NOT linger
    # grows unboundedly with executions — allow a small constant.
    assert len(leaked) <= 2, f"dag shm channel leaked {len(leaked)} objects"


def test_shm_channel_path_actually_used(dag_actors, shared_ray):
    """The dag_shm_edges counter must tick for large same-node payloads —
    guards against the zero-copy path silently regressing to socket frames."""
    import time as _time

    import numpy as np

    from ray_tpu.core import api as _api

    d, a = dag_actors
    with InputNode() as inp:
        out = a.apply.bind(d.apply.bind(inp))
    compiled = out.experimental_compile()
    try:
        x = np.ones(1 << 20, dtype=np.float64)
        compiled.execute(x).result(timeout=120)
    finally:
        compiled.teardown()
    core = _api._require_worker()
    deadline = _time.time() + 20  # metrics ship on a short timer
    total = 0
    while _time.time() < deadline:
        m = core._run(core.controller.call("get_metrics", {}))
        total = sum(
            s.get("value", 0)
            for s in (m if isinstance(m, list) else [])
            if isinstance(s, dict) and s.get("name") == "dag_shm_edges"
        )
        if total >= 1:
            break
        _time.sleep(0.5)
    assert total >= 1, f"shm edge counter never ticked: {m}"
