"""Serve scale plane: QoS-signal-driven replica autoscaling, KV-cache-aware
routing, and chunked-prefill scheduling.

Layers covered:
  * unit — DemandEstimator folding synthetic QoS telemetry (handle demand,
    replica depths, per-class delay minima, AIMD slope, shed/expiry rates);
    ScalePolicy hysteresis + flip-cooldown edges; the AffinityMap's counted
    LRU and release-on-death semantics; prefix-key derivation.
  * router — the handle's prefix->affinity->p2c pick order: hit, capacity
    fallback, pin release when a replica leaves the membership.
  * engine — chunked prefill: a long prompt prefills in page-aligned chunks
    interleaved with decode blocks (other slots keep decoding between
    chunks), greedy output identical to the unchunked engine.
  * cluster — replica death under prefix routing (pins release, requests
    re-route, nothing routes to the dead replica), and the e2e scale-out:
    the AUTOSCALER (not a static replica count) grows a deployment to 3
    replicas under an overload_storm-style mix and goodput scales with it.

The no-flap story under chaos-delayed replica startup is the seeded
scenario ``autoscale_flap`` (ray_tpu/chaos/scenarios.py), smoke-run here.
"""
from __future__ import annotations

import threading
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import serve
from ray_tpu.scale import AffinityMap, DemandEstimator, ScalePolicy, prefix_key_for_body
from ray_tpu.scale.signals import DemandEstimate
from ray_tpu.util import metrics as _metrics


def _counter_value(name: str, **tags) -> float:
    return sum(
        rec["value"] for rec in _metrics.snapshot()
        if rec["name"] == name
        and all(rec["tags"].get(k) == v for k, v in tags.items())
    )


# ---------------------------------------------------------------------------
# signals: folding synthetic QoS telemetry
# ---------------------------------------------------------------------------

def test_estimator_folds_handle_demand_and_replica_depths():
    est = DemandEstimator().fold(
        handle_demand=[(3.0, 100.0), (2.0, 100.0), (9.0, 1.0)],  # last: stale
        replica_depths=[(1.0, 100.0), (2.0, 100.0)],
        qos_reports=[],
        now=100.0,
    )
    assert est.demand == 5.0
    assert est.replica_depth == 3.0
    assert est.effective_demand == 5.0  # max of the two views
    assert not est.overloaded and est.reasons == ()


def test_estimator_standing_queue_and_aimd_backoff_signal_overload():
    def report(requests):
        return {
            "delay_min_by_class": {"best_effort": 0.4, "interactive": 0.0},
            "target_delay_s": 0.1, "limit_trend": -3.0,
            "sheds_total": 0.0, "expired_total": 0.0,
            "requests_total": requests,
        }

    e = DemandEstimator()
    e.fold([], [], [("p1", report(10.0), 100.0)], now=100.0)  # baseline
    est = e.fold([], [], [("p1", report(20.0), 101.0)], now=101.0)
    assert est.overloaded
    assert "standing_queue" in est.reasons and "aimd_backoff" in est.reasons
    assert est.worst_delay_min == 0.4 and est.limit_trend == -3.0


def test_estimator_idle_deployment_ignores_proxy_global_overload():
    """The delay minima / AIMD slope are proxy-global: a deployment with NO
    recent traffic through the proxy must not ride another deployment's
    overload (it would escalate to max_replicas for nothing)."""
    def report(requests):
        return {
            "delay_min_by_class": {"best_effort": 0.9},
            "target_delay_s": 0.1, "limit_trend": -5.0,
            "sheds_total": 0.0, "expired_total": 0.0,
            "requests_total": requests,
        }

    e = DemandEstimator()
    e.fold([], [], [("p1", report(10.0), 100.0)], now=100.0)
    # No request delta for this deployment: global signals gated off.
    est = e.fold([], [], [("p1", report(10.0), 101.0)], now=101.0)
    assert not est.overloaded and est.worst_delay_min == 0.0
    assert est.limit_trend == 0.0


def test_estimator_differentiates_shed_counters_into_rates():
    e = DemandEstimator()
    mk = lambda sheds, expired: {  # noqa: E731
        "delay_min_by_class": {}, "target_delay_s": 0.1, "limit_trend": 0.0,
        "sheds_total": sheds, "expired_total": expired,
    }
    first = e.fold([], [], [("p1", mk(10.0, 0.0), 100.0)], now=100.0)
    assert first.shed_rate == 0.0  # first sample only sets the baseline
    second = e.fold([], [], [("p1", mk(30.0, 4.0), 102.0)], now=102.0)
    assert second.shed_rate == pytest.approx(10.0)   # 20 sheds / 2s
    assert second.expired_rate == pytest.approx(2.0)
    assert second.overloaded and "shedding" in second.reasons
    # A restarted reporter (counters reset) never yields a negative rate.
    third = e.fold([], [], [("p1", mk(0.0, 0.0), 104.0)], now=104.0)
    assert third.shed_rate == 0.0 and third.expired_rate == 0.0


def test_estimator_expires_stale_qos_reports():
    report = {"delay_min_by_class": {"interactive": 9.0}, "target_delay_s": 0.1,
              "limit_trend": -1.0, "sheds_total": 100.0, "expired_total": 0.0}
    est = DemandEstimator().fold([], [], [("p1", report, 10.0)], now=100.0)
    assert not est.overloaded and est.worst_delay_min == 0.0


# ---------------------------------------------------------------------------
# policy: hysteresis + cooldown edges
# ---------------------------------------------------------------------------

def _est(demand=0.0, overloaded=False):
    e = DemandEstimate(demand=demand, overloaded=overloaded)
    if overloaded:
        e.reasons = ("shedding",)
    return e


def test_policy_overload_requests_capacity_beyond_demand_math():
    p = ScalePolicy(min_replicas=1, max_replicas=8, target_ongoing_requests=4.0,
                    upscale_delay_s=0.0)
    # Demand math alone says 1 replica suffices — but the QoS plane is
    # shedding, so the ask is current+1 (shed demand appears in no queue).
    d = p.decide(_est(demand=2.0, overloaded=True), current=2, now=100.0)
    assert d.applied and d.action == "upscale" and d.target == 3
    assert d.reason == "overload"


def test_policy_hysteresis_holds_until_delay_window_elapses():
    p = ScalePolicy(min_replicas=1, max_replicas=4, target_ongoing_requests=1.0,
                    upscale_delay_s=1.0, downscale_delay_s=2.0, cooldown_s=0.0)
    assert not p.decide(_est(demand=4.0), 1, now=100.0).applied   # window opens
    assert p.decide(_est(demand=4.0), 1, now=100.5).reason == "pending"
    d = p.decide(_est(demand=4.0), 1, now=101.01)
    assert d.applied and d.target == 4
    # A desire that flips direction mid-window restarts the timer.
    assert not p.decide(_est(demand=1.0), 4, now=101.5).applied
    assert not p.decide(_est(demand=1.0), 4, now=103.0).applied   # 1.5s < 2s
    assert p.decide(_est(demand=1.0), 4, now=103.6).applied


def test_policy_cooldown_suppresses_direction_flip():
    p = ScalePolicy(min_replicas=1, max_replicas=4, target_ongoing_requests=1.0,
                    upscale_delay_s=0.0, downscale_delay_s=0.0, cooldown_s=5.0)
    up = p.decide(_est(demand=3.0), 1, now=100.0)
    assert up.applied and up.target == 3
    # Demand evaporates immediately (the slow-replica-arrival illusion):
    # the downscale is SUPPRESSED inside the cooldown window…
    d = p.decide(_est(demand=0.0), 3, now=102.0)
    assert not d.applied and d.reason == "cooldown"
    # …and applies cleanly after it.
    d2 = p.decide(_est(demand=0.0), 3, now=105.1)
    assert d2.applied and d2.action == "downscale" and d2.target == 1
    # Same-direction escalation is never cooldown-blocked: a second
    # upscale right after an applied upscale goes through.
    p2 = ScalePolicy(min_replicas=1, max_replicas=4, target_ongoing_requests=1.0,
                     upscale_delay_s=0.0, downscale_delay_s=0.0, cooldown_s=5.0)
    assert p2.decide(_est(demand=2.0), 1, now=200.0).applied
    d3 = p2.decide(_est(demand=4.0), 2, now=200.5)
    assert d3.applied and d3.action == "upscale" and d3.target == 4


def test_policy_clamps_to_min_max():
    p = ScalePolicy(min_replicas=2, max_replicas=3, target_ongoing_requests=1.0,
                    upscale_delay_s=0.0, downscale_delay_s=0.0, cooldown_s=0.0)
    assert p.decide(_est(demand=100.0), 2, now=1.0).target == 3
    assert p.decide(_est(demand=0.0), 3, now=10.0).target == 2


# ---------------------------------------------------------------------------
# router structures
# ---------------------------------------------------------------------------

def test_affinity_map_counts_cap_evictions_and_releases_dead_replicas():
    evictions = []
    m = AffinityMap(cap=2, on_evict=lambda: evictions.append(1))
    m.pin("p:a", "r1")
    m.pin("p:b", "r2")
    m.get("p:a")          # refresh: "p:b" is now the LRU victim
    m.pin("p:c", "r1")
    assert m.evicted == 1 and len(evictions) == 1
    assert m.get("p:b") is None and m.get("p:a") == "r1"
    # Release-on-death drops every pin to the dead replica, uncounted as
    # cap eviction (it is a release, not capacity pressure).
    assert m.release_replica("r1") == 2
    assert m.evicted == 1 and len(m) == 0


def test_affinity_map_cap_is_per_kind_so_prefixes_cannot_thrash_model_pins():
    """High-cardinality prompt-prefix keys churn at their OWN cap: the
    multiplexed-model pin survives arbitrarily many unique-prompt requests
    (the failure the old separate model-affinity cache was immune to)."""
    m = AffinityMap(cap=4)
    m.pin("m:llama", "r1")
    for i in range(20):
        m.pin(f"p:digest{i}", "r2")
    assert m.get("m:llama") == "r1"           # never evicted by p: churn
    assert m.evicted == 16                    # p: kind churned at its cap
    assert m.snapshot()["by_kind"] == {"m": 1, "p": 4}


def test_prefix_key_for_body_shapes():
    body = b'{"tokens": [1, 2, 3], "max_tokens": 8}'
    k1 = prefix_key_for_body(body, "tA")
    k2 = prefix_key_for_body(b'{"tokens": [1, 2, 3], "max_tokens": 64}', "tA")
    assert k1 and k1 == k2  # same prompt head, different sampling: same key
    assert prefix_key_for_body(body, "tB") != k1  # tenant-scoped
    assert prefix_key_for_body(b'{"x": 1}') == ""  # no prompt: no key
    assert prefix_key_for_body(b"not json") == ""
    # Long prompts sharing their head map to one key (the system-prompt
    # workload): heads equal up to PREFIX_HEAD_TOKENS.
    shared = list(range(100))
    a = prefix_key_for_body(
        ('{"tokens": %s}' % (shared + [1])).encode())
    b = prefix_key_for_body(
        ('{"tokens": %s}' % (shared + [2])).encode())
    assert a == b != ""


def test_replica_set_pick_order_prefix_affinity_p2c():
    """The handle's routing order on a synthetic membership: prefix pin
    wins, then affinity pin, then p2c; pins release when the replica
    leaves; a pinned replica at capacity falls back (and re-pins)."""
    from ray_tpu.serve.handle import _ReplicaSet

    rs = _ReplicaSet("t-app", "t-dep")
    try:
        rs.replicas = {"r1": object(), "r2": object(), "r3": object()}
        rs.max_ongoing = 2
        base_p = _counter_value("serve.routing.cache_hit_total",
                                kind="prefix", app="t-app", deployment="t-dep")
        keys = rs._routing_keys(prefix_key="px", affinity_key="ak")
        assert [k for k, _ in keys] == ["prefix", "affinity"]
        first = rs._pick_locked(keys)
        assert first in rs.replicas
        # Sticky: every later pick with the same prefix lands on `first`.
        for _ in range(5):
            assert rs._pick_locked(keys) == first
        assert _counter_value("serve.routing.cache_hit_total", kind="prefix",
                              app="t-app", deployment="t-dep") == base_p + 5
        # Prefix pin beats the affinity pin when they diverge.
        rs.affinity.pin("k:ak", [n for n in rs.replicas if n != first][0])
        assert rs._pick_locked(keys) == first
        # Affinity pin serves when only it matches.
        other = [n for n in rs.replicas if n != first][0]
        rs.affinity.pin("k:solo", other)
        assert rs._pick_locked((("affinity", "k:solo"),)) == other
        # Pinned replica at capacity: fall back to p2c and RE-PIN.
        rs.ongoing[first] = rs.max_ongoing
        moved = rs._pick_locked(keys)
        assert moved != first
        rs.ongoing[first] = 0
        assert rs._pick_locked(keys) == moved  # the pin moved with the pick
        # Membership departure releases the pin; next pick re-routes.
        del rs.replicas[moved]
        rs.affinity.retain(rs.replicas)
        assert rs.affinity.get("p:px") is None
        assert rs._pick_locked(keys) in rs.replicas
    finally:
        rs.close()


# ---------------------------------------------------------------------------
# engine: chunked prefill
# ---------------------------------------------------------------------------

def _tiny_cfg():
    from ray_tpu.models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=256, attention_impl="reference",
    )


def _mk_engine(chunked: int, seed: int = 7, slots: int = 4):
    from ray_tpu.llm import EngineConfig, LLMEngine

    return LLMEngine(_tiny_cfg(), engine_config=EngineConfig(
        max_slots=slots, max_seq=256, prefill_buckets=(32, 64, 128, 256),
        kv_layout="paged", page_size=32, decode_block=4, seed=seed,
        chunked_prefill=chunked,
    ))


def test_chunked_prefill_requires_paged_and_page_multiple():
    from ray_tpu.llm import EngineConfig, LLMEngine

    with pytest.raises(ValueError, match="paged"):
        LLMEngine(_tiny_cfg(), engine_config=EngineConfig(
            max_slots=2, chunked_prefill=64))
    with pytest.raises(ValueError, match="multiple"):
        LLMEngine(_tiny_cfg(), engine_config=EngineConfig(
            max_slots=2, kv_layout="paged", page_size=32, chunked_prefill=48))


def test_chunked_prefill_interleaves_with_decode_and_matches_unchunked():
    """The interleave contract: a long prompt's prefill spans MULTIPLE
    steps (one chunk per step) while an already-decoding slot keeps
    emitting tokens in those same steps; greedy output is identical to the
    unchunked engine's."""
    rng = np.random.default_rng(0)
    long_prompt = rng.integers(0, 128, 160).tolist()  # 5 chunks of 32
    short_prompt = rng.integers(0, 128, 16).tolist()

    ref = _mk_engine(chunked=0)
    ref.add_request("long", long_prompt, max_tokens=8)
    ref_tokens = None
    while ref.has_work():
        ev = ref.step().get("long")
        if ev and ev.get("finished"):
            ref_tokens = ev["tokens"]
    assert ref_tokens is not None

    eng = _mk_engine(chunked=32)
    eng.add_request("short", short_prompt, max_tokens=24)
    # Let the short request prefill + start decoding alone.
    first = eng.step()
    assert "short" in first and first["short"]["ttft_s"] is not None
    eng.add_request("long", long_prompt, max_tokens=8)
    decode_steps_during_prefill = 0
    prefill_steps = 0
    long_first_step = None
    tokens_long = None
    steps = 0
    while eng.has_work() and steps < 200:
        steps += 1
        mid_prefill = bool(eng._prefilling)
        ev = eng.step()
        if mid_prefill:
            prefill_steps += 1
            if "short" in ev and ev["short"].get("new_tokens"):
                decode_steps_during_prefill += 1
        if "long" in ev and long_first_step is None:
            long_first_step = steps
        if ev.get("long", {}).get("finished"):
            tokens_long = ev["long"]["tokens"]
    # 160 tokens / 32-token chunks = 5 chunks; the admission step runs
    # chunk 1, so >= 4 later steps start with the slot still mid-prefill.
    assert prefill_steps >= 4
    # Decode really interleaved: the short request made progress in steps
    # where the long prompt was still mid-prefill.
    assert decode_steps_during_prefill >= 2
    assert tokens_long == ref_tokens  # greedy: chunking must not change output


def test_chunked_prefill_abort_mid_prefill_frees_pages():
    eng = _mk_engine(chunked=32)
    total_free = len(eng.free_pages)
    prompt = list(range(100)) + list(range(60))
    eng.add_request("a", prompt, max_tokens=4)
    eng.step()  # admits + first chunk only
    assert eng._prefilling, "long prompt should be mid chunked-prefill"
    eng.abort("a")
    assert not eng._prefilling
    assert len(eng.free_pages) == total_free
    assert not eng.has_work()


def test_chunked_prefill_with_prefix_cache_partial_hit():
    """A cached system prompt + long tail: the tail itself chunks (progress
    starts at the cached prefix), and the answer matches the cold run."""
    from ray_tpu.llm import EngineConfig, LLMEngine

    def mk(chunked):
        return LLMEngine(_tiny_cfg(), engine_config=EngineConfig(
            max_slots=4, max_seq=256, prefill_buckets=(32, 64, 128, 256),
            kv_layout="paged", page_size=32, decode_block=4, seed=3,
            chunked_prefill=chunked, prefix_cache=True,
        ))

    rng = np.random.default_rng(1)
    sys_prompt = rng.integers(0, 128, 64).tolist()
    tail = rng.integers(0, 128, 96).tolist()
    cold = mk(0)
    cold.generate(sys_prompt + [5], max_tokens=2)   # seed the prefix cache
    want = cold.generate(sys_prompt + tail, max_tokens=6)["tokens"]
    eng = mk(32)
    eng.generate(sys_prompt + [5], max_tokens=2)    # seed the prefix cache
    got = eng.generate(sys_prompt + tail, max_tokens=6)
    assert eng.prefix_partial_hits >= 1
    assert got["tokens"] == want


# ---------------------------------------------------------------------------
# cluster: prefix routing under replica death + autoscaled scale-out
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def scale_cluster():
    rt.init(num_cpus=16)
    serve.start(proxy=False)
    yield rt
    serve.shutdown()
    rt.shutdown()


@serve.deployment(name="Echo", num_replicas=2, max_ongoing_requests=4)
class Echo:
    def __init__(self):
        import os

        self.pid = os.getpid()

    def __call__(self, x="-"):
        return {"pid": self.pid, "x": x}


def test_prefix_routing_sticks_and_survives_replica_death(scale_cluster):
    handle = serve.run(Echo.bind(), name="pxapp", http=False)
    h = handle.options(prefix_key="sys-prompt-1")
    pids = {h.remote(i).result(timeout=30)["pid"] for i in range(6)}
    assert len(pids) == 1, f"prefix-keyed requests spread across {pids}"
    pinned_pid = pids.pop()
    # Find and kill the pinned replica actor.
    from ray_tpu.serve.handle import SERVE_NAMESPACE, _replica_set

    rs = _replica_set("pxapp", "Echo")
    with rs.cond:
        pinned_name = rs.affinity.get("p:sys-prompt-1")
    assert pinned_name is not None
    rt.kill(rt.get_actor(pinned_name, namespace=SERVE_NAMESPACE))
    # The next prefix-keyed requests re-route (retry-on-death + pin
    # release) and re-stick to a LIVE replica — never the dead one.
    new_pids = {h.remote(i).result(timeout=60)["pid"] for i in range(6)}
    assert len(new_pids) == 1
    assert new_pids.pop() != pinned_pid
    with rs.cond:
        assert rs.affinity.get("p:sys-prompt-1") != pinned_name
    serve.delete("pxapp")


@serve.deployment(name="Busy", max_ongoing_requests=2,
                  autoscaling_config=serve.AutoscalingConfig(
                      min_replicas=1, max_replicas=3,
                      target_ongoing_requests=1.0,
                      upscale_delay_s=0.3, downscale_delay_s=5.0,
                      cooldown_s=1.0))
class Busy:
    def __call__(self, x="-"):
        time.sleep(0.05)
        return "ok"


@pytest.mark.slow  # heavy battery; tier-1 budget (see CHANGES PR-13)
def test_autoscaler_scales_to_three_replicas_and_goodput_grows(scale_cluster):
    """The e2e scale-out: an overload_storm-shaped flood against an
    autoscaling deployment. The AUTOSCALER (not a static count) must grow
    the replica set to max_replicas=3, and the completed-request rate in
    the scaled-out window must beat the 1-replica opening window."""
    handle = serve.run(Busy.bind(), name="scaleout", http=False)
    ctl = rt.get_actor("__serve_controller__", namespace="serve")
    stop_at = time.monotonic() + 12.0
    lock = threading.Lock()
    done: list[float] = []  # completion timestamps

    def flood():
        while time.monotonic() < stop_at:
            try:
                handle.remote("x").result(timeout=30)
                with lock:
                    done.append(time.monotonic())
            except Exception:
                pass

    t0 = time.monotonic()
    threads = [threading.Thread(target=flood) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert all(not t.is_alive() for t in threads), "load threads wedged"

    state = rt.get(ctl.get_serve_state.remote(), timeout=30)
    dep = state["apps"]["scaleout"]["Busy"]
    assert dep["target"] == 3, f"autoscaler never reached 3 replicas: {dep}"
    assert len(dep["replicas"]) == 3
    ups = [d for d in dep["decisions"] if d["applied"] and d["action"] == "upscale"]
    assert ups, f"no applied upscale decision recorded: {dep['decisions']}"
    # Goodput scales: completions/s in the final 4s (scaled out) vs the
    # first 3s (1 replica, scale-out still pending).
    with lock:
        t_end = stop_at
        early = sum(1 for ts in done if ts - t0 <= 3.0) / 3.0
        late = sum(1 for ts in done if t_end - ts <= 4.0) / 4.0
    assert late > early, (
        f"goodput did not scale with replicas: early={early:.1f}/s late={late:.1f}/s"
    )
    serve.delete("scaleout")


# The no-flap seeded chaos scenario (autoscale_flap) is smoke-run from
# tests/test_chaos.py::test_autoscale_flap_scenario_smoke — the scenario
# runner needs a fresh process-level session, which this module's
# scale_cluster fixture holds open.
