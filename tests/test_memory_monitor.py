"""OOM worker-killing policy (reference: raylet memory monitor +
worker_killing_policy: retriable-first/newest-first victim selection)."""
import time
from dataclasses import dataclass, field

import pytest

import ray_tpu as rt
from ray_tpu.core.memory_monitor import (
    MemoryMonitor,
    pick_oom_victim,
    system_memory_usage,
)


@dataclass
class FakeWorker:
    worker_id: str
    state: str
    state_ts: float
    proc: object = None
    actor_ids: list = field(default_factory=list)


def test_system_memory_usage_sane():
    u = system_memory_usage()
    assert 0.0 < u < 1.0


def test_victim_order_idle_first():
    ws = [
        FakeWorker("task-old", "LEASED", 1.0),
        FakeWorker("idle", "IDLE", 0.5),
        FakeWorker("actor", "ACTOR", 2.0),
    ]
    assert pick_oom_victim(ws).worker_id == "idle"


def test_victim_order_newest_leased_then_actor():
    ws = [
        FakeWorker("task-old", "LEASED", 1.0),
        FakeWorker("task-new", "LEASED", 3.0),
        FakeWorker("actor", "ACTOR", 5.0),
    ]
    assert pick_oom_victim(ws).worker_id == "task-new"
    ws = [FakeWorker("a-old", "ACTOR", 1.0), FakeWorker("a-new", "ACTOR", 2.0)]
    assert pick_oom_victim(ws).worker_id == "a-new"
    assert pick_oom_victim([FakeWorker("d", "DEAD", 1.0)]) is None


def test_monitor_kills_only_over_threshold():
    killed = []
    usage = {"v": 0.5}
    mon = MemoryMonitor(
        threshold=0.9,
        interval_s=1.0,
        get_workers=lambda: [FakeWorker("w1", "IDLE", 1.0)],
        kill=lambda w, reason: killed.append((w.worker_id, reason)),
        usage_fn=lambda: usage["v"],
    )
    assert mon.poll_once() is None and not killed
    usage["v"] = 0.95
    assert mon.poll_once().worker_id == "w1"
    assert killed and "OOM" in killed[0][1]
    assert mon.kills == 1


def test_oom_killed_task_is_retried():
    """Kill the worker mid-task via a forced monitor poll: the task must
    retry on a fresh worker and still complete (reference behavior: OOM
    kills surface as worker death -> retriable tasks resubmit)."""
    cluster = rt.Cluster(head_node_args={"num_cpus": 2})
    rt.init_cluster(cluster)
    try:
        @rt.remote(max_retries=2)
        def slow():
            time.sleep(1.5)
            return "done"

        ref = slow.remote()
        daemon = cluster.daemons[0]
        deadline = time.time() + 30
        while time.time() < deadline:
            leased = [w for w in daemon.workers.values() if w.state == "LEASED"]
            if leased:
                break
            time.sleep(0.05)
        assert leased, "task worker never leased"
        mon = daemon._memory_monitor
        mon.usage_fn = lambda: 0.99
        victim = cluster.host.call(_poll_async(mon))
        assert victim is not None
        mon.usage_fn = lambda: 0.0
        assert rt.get(ref, timeout=120) == "done"
        assert mon.kills == 1
    finally:
        rt.shutdown()
        # rt.shutdown() only detaches the driver; the Cluster (service
        # thread, daemons, minted token) must be stopped explicitly or it
        # leaks into every later test module.
        cluster.shutdown()


async def _poll_async_inner(mon):
    return mon.poll_once()


def _poll_async(mon):
    return _poll_async_inner(mon)
