"""Sanitizer builds of the C++ store (reference: TSAN/ASAN Bazel configs,
.bazelrc:112-133 — the native store is where a data race would silently
corrupt user payloads). Compiles the stress harness under ASan+UBSan and
TSan and runs it; sanitizer reports fail the test via nonzero exit."""
import os
import subprocess

import pytest

NATIVE = os.path.join(os.path.dirname(__file__), "..", "ray_tpu", "core", "native")
SRC = [os.path.join(NATIVE, "shm_store.cpp"), os.path.join(NATIVE, "shm_store_stress.cpp")]


def _build_and_run(tag: str, san_flags: list[str], env=None):
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("g++ not available")
    out = os.path.join(NATIVE, f"_stress_{tag}")
    if not os.path.exists(out) or any(
        os.path.getmtime(s) > os.path.getmtime(out) for s in SRC
    ):
        cmd = ["g++", "-std=c++17", "-O1", "-g", "-fno-omit-frame-pointer",
               *san_flags, *SRC, "-o", out, "-lpthread"]
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if res.returncode != 0:
            pytest.fail(f"{tag} build failed:\n{res.stderr[-3000:]}")
    run_env = {**os.environ, **(env or {})}
    res = subprocess.run([out], capture_output=True, text=True, timeout=600, env=run_env)
    assert res.returncode == 0, (
        f"{tag} stress failed (rc={res.returncode}):\n"
        f"{res.stdout[-1000:]}\n{res.stderr[-4000:]}"
    )
    assert "stress ok" in res.stdout


def test_store_stress_asan():
    _build_and_run(
        "asan",
        ["-fsanitize=address,undefined"],
        env={"ASAN_OPTIONS": "detect_leaks=0"},  # arena handles freed at exit
    )


def test_store_stress_tsan():
    _build_and_run("tsan", ["-fsanitize=thread"])
