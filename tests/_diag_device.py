import time


def test_diag2():
    import ray_tpu as rt
    import ray_tpu.core.worker as W

    log = open("/tmp/diag.log", "w")
    orig = W.CoreWorker.start_driver_sync

    def patched(self):
        try:
            orig(self)
        except TimeoutError:
            import asyncio
            import traceback

            def dump():
                import sys
                for t in asyncio.all_tasks():
                    print("== TASK:", t.get_name(), t.get_coro(), file=log, flush=True)
                    t.print_stack(file=log)

            self.loop.call_soon_threadsafe(dump)
            time.sleep(3)
            log.flush()
            raise

    W.CoreWorker.start_driver_sync = patched
    try:
        rt.init(num_cpus=2)
        print("INIT-OK", file=log, flush=True)
    finally:
        W.CoreWorker.start_driver_sync = orig
        rt.shutdown()
