"""Pluggable search algorithms: the Searcher interface + a TPE implementation.

Role-equivalent to the reference's Searcher ABC and its model-based plugins
(/root/reference/python/ray/tune/search/searcher.py — suggest /
on_trial_complete contract; tune/search/optuna/ et al. provide the models).
The TPE searcher is a native implementation of the Tree-structured Parzen
Estimator (the algorithm behind hyperopt/optuna's default): split observed
trials into good/bad by score quantile, model each numeric dimension with
Parzen (Gaussian-kernel) densities l(x) (good) and g(x) (bad), and suggest
the candidate maximizing l(x)/g(x). Categorical dimensions use smoothed
category frequencies from the good split.
"""
from __future__ import annotations

import math
import random
from typing import Any, Optional

from ray_tpu.tune.search import (
    Choice,
    Domain,
    LogUniform,
    Randint,
    Uniform,
    _is_grid,
    _set_path,
    _walk,
    generate_variants,
)


class Searcher:
    """suggest/observe contract (reference: searcher.py). Stateful; driven by
    the TuneController. Implementations must tolerate out-of-order completes
    and may return None from suggest() to signal exhaustion."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, metrics: dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str, metrics: Optional[dict]) -> None:
        pass

    # Sweep resume: searchers persist their observations with the sweep
    # state (reference: Searcher.save/restore).
    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid/random search behind the Searcher interface (reference:
    basic_variant.py). Pre-expands the whole variant list."""

    def __init__(self, param_space: dict, num_samples: int = 1,
                 seed: Optional[int] = None, metric: Optional[str] = None,
                 mode: str = "max"):
        super().__init__(metric, mode)
        self._configs = generate_variants(param_space, num_samples, seed)
        self._next = 0

    @property
    def total(self) -> int:
        return len(self._configs)

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._next >= len(self._configs):
            return None
        cfg = self._configs[self._next]
        self._next += 1
        return cfg

    def get_state(self) -> dict:
        return {"next": self._next}

    def set_state(self, state: dict) -> None:
        self._next = state.get("next", 0)


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator over a param_space of Domains.

    Independent 1-D models per dimension (the classic TPE factorization):
    numeric domains are modeled in their natural space (log space for
    LogUniform) by Parzen windows centered on observed values; categorical
    domains by add-one-smoothed frequencies. The first `n_initial` suggestions
    are random (seeding the model), after which each suggestion draws
    `n_candidates` samples from the good-split density and keeps the one
    with the best l(x)/g(x) ratio.
    """

    def __init__(self, param_space: dict, metric: str, mode: str = "max",
                 n_initial: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.space = param_space
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._dims: list[tuple[tuple, Domain]] = []
        for path, v in _walk(param_space):
            if _is_grid(v):
                raise ValueError("TPESearcher does not support grid_search dims; "
                                 "use Domains (uniform/loguniform/randint/choice)")
            if isinstance(v, Domain):
                self._dims.append((path, v))
        # trial_id -> config (pending observation); observations: (config, score)
        self._pending: dict[str, dict] = {}
        self._observations: list[tuple[dict, float]] = []

    # -- modeling helpers ---------------------------------------------------
    def _to_model_space(self, dom: Domain, v: float) -> float:
        return math.log(v) if isinstance(dom, LogUniform) else float(v)

    def _from_model_space(self, dom: Domain, x: float):
        if isinstance(dom, LogUniform):
            out = math.exp(x)
            return min(max(out, dom.low), dom.high)
        if isinstance(dom, Randint):
            return min(max(int(round(x)), dom.low), dom.high - 1)
        return min(max(x, dom.low), dom.high)

    @staticmethod
    def _bandwidth(xs: list[float], span: float) -> float:
        """Silverman-flavored kernel width, floored so sparse splits still
        explore and capped so the model is never flatter than the prior."""
        if len(xs) < 2:
            return 0.25 * span
        mean = sum(xs) / len(xs)
        sd = (sum((v - mean) ** 2 for v in xs) / len(xs)) ** 0.5
        bw = 1.06 * (sd or 0.1 * span) * len(xs) ** -0.2
        return min(max(bw, 0.02 * span), 0.5 * span)

    @staticmethod
    def _parzen_pdf(xs: list[float], bw: float, x: float) -> float:
        if not xs:
            return 1e-12
        s = 0.0
        for c in xs:
            z = (x - c) / bw
            s += math.exp(-0.5 * z * z)
        return s / (len(xs) * bw * math.sqrt(2 * math.pi)) + 1e-12

    def _split(self) -> tuple[list[dict], list[dict]]:
        obs = sorted(
            self._observations,
            key=lambda cs: cs[1],
            reverse=(self.mode == "max"),
        )
        n_good = max(1, int(self.gamma * len(obs)))
        return [c for c, _ in obs[:n_good]], [c for c, _ in obs[n_good:]]

    def _get_path(self, cfg: dict, path: tuple):
        for k in path:
            cfg = cfg[k]
        return cfg

    # -- Searcher interface -------------------------------------------------
    def suggest(self, trial_id: str) -> Optional[dict]:
        from ray_tpu.tune.search import _copy_structure

        cfg = _copy_structure(self.space)
        if len(self._observations) < self.n_initial or not self._dims:
            for path, dom in self._dims:
                _set_path(cfg, path, dom.sample(self.rng))
        else:
            good, bad = self._split()
            for path, dom in self._dims:
                if isinstance(dom, Choice):
                    # Smoothed frequency draw from the good split.
                    counts = {c: 1.0 for c in dom.categories}
                    for g in good:
                        counts[self._get_path(g, path)] = counts.get(self._get_path(g, path), 1.0) + 1.0
                    total = sum(counts.values())
                    r = self.rng.random() * total
                    acc = 0.0
                    for cat, w in counts.items():
                        acc += w
                        if r <= acc:
                            _set_path(cfg, path, cat)
                            break
                    continue
                g_xs = [self._to_model_space(dom, self._get_path(c, path)) for c in good]
                b_xs = [self._to_model_space(dom, self._get_path(c, path)) for c in bad]
                lo = self._to_model_space(dom, dom.low)
                hi = self._to_model_space(dom, dom.high - 1 if isinstance(dom, Randint) else dom.high)
                span = max(hi - lo, 1e-9)
                bw_g = self._bandwidth(g_xs, span)
                bw_b = self._bandwidth(b_xs, span)
                best_x, best_ratio = None, -1.0
                for _ in range(self.n_candidates):
                    center = self.rng.choice(g_xs) if g_xs else self.rng.uniform(lo, hi)
                    # Resample out-of-range draws (clamping would pile point
                    # mass on the bounds and the argmax degenerates there).
                    for _try in range(8):
                        x = self.rng.gauss(center, bw_g)
                        if lo <= x <= hi:
                            break
                    else:
                        x = min(max(x, lo), hi)
                    ratio = self._parzen_pdf(g_xs, bw_g, x) / self._parzen_pdf(b_xs, bw_b, x)
                    if ratio > best_ratio:
                        best_x, best_ratio = x, ratio
                _set_path(cfg, path, self._from_model_space(dom, best_x))
        self._pending[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str, metrics: Optional[dict]) -> None:
        cfg = self._pending.pop(trial_id, None)
        if cfg is None or not metrics or self.metric not in metrics:
            return
        self._observations.append((cfg, float(metrics[self.metric])))

    def get_state(self) -> dict:
        # _pending too: a trial in flight at checkpoint time completes after
        # resume, and its (config, score) must still reach the model.
        return {"observations": self._observations, "rng": self.rng.getstate(),
                "pending": self._pending}

    def set_state(self, state: dict) -> None:
        self._observations = [
            (c, float(s)) for c, s in state.get("observations", [])
        ]
        self._pending = dict(state.get("pending", {}))
        rng_state = state.get("rng")
        if rng_state is not None:
            # JSON round-trips tuples as lists; Random.setstate needs tuples.
            self.rng.setstate(tuple(
                tuple(x) if isinstance(x, list) else x for x in rng_state
            ))


class BOHBSearcher(TPESearcher):
    """BOHB-style model-based search (Falkner et al. 2018; reference:
    tune/search/bohb/ TuneBOHB paired with schedulers/hb_bohb.py).

    The BOHB coupling: TPE densities are fitted PER BUDGET from every
    INTERMEDIATE result (on_trial_result, keyed by ``time_attr``), and
    suggestions always come from the LARGEST budget that has accumulated
    ``min_points_in_model`` observations — early rungs seed the model
    cheaply, deep rungs refine it. Pair with ASHAScheduler, the async
    successive-halving counterpart of BOHB's HyperBand: the scheduler
    allocates budgets, this searcher learns from every rung it produces.
    (Plain TPESearcher only learns from terminal results.)
    """

    def __init__(self, param_space: dict, metric: str, mode: str = "max",
                 n_initial: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24,
                 min_points_in_model: Optional[int] = None,
                 time_attr: str = "training_iteration",
                 seed: Optional[int] = None):
        super().__init__(param_space, metric, mode, n_initial, gamma,
                         n_candidates, seed)
        self.time_attr = time_attr
        self.min_points = min_points_in_model or (len(self._dims) + 2)
        # budget -> [(config, score at that budget)]
        self._budget_obs: dict[int, list[tuple[dict, float]]] = {}
        # (trial_id, budget) pairs already recorded: the controller reports
        # the FINAL result through on_trial_result AND on_trial_complete —
        # without dedup every completed trial would be double-weighted in
        # its rung's density model.
        self._seen: set = set()

    def _record(self, trial_id: str, metrics: Optional[dict], pop: bool) -> None:
        cfg = (self._pending.pop(trial_id, None) if pop
               else self._pending.get(trial_id))
        if cfg is None or not metrics or self.metric not in metrics:
            return
        budget = int(metrics.get(self.time_attr, 0))
        if (trial_id, budget) in self._seen:
            return
        self._seen.add((trial_id, budget))
        self._budget_obs.setdefault(budget, []).append(
            (cfg, float(metrics[self.metric]))
        )
        # Model pool <- the deepest budget with enough points (BOHB's rule).
        for b in sorted(self._budget_obs, reverse=True):
            if len(self._budget_obs[b]) >= self.min_points:
                self._observations = self._budget_obs[b]
                return

    def on_trial_result(self, trial_id: str, metrics: dict) -> None:
        self._record(trial_id, metrics, pop=False)

    def on_trial_complete(self, trial_id: str, metrics: Optional[dict]) -> None:
        self._record(trial_id, metrics, pop=True)

    def get_state(self) -> dict:
        state = super().get_state()
        state["budget_obs"] = {str(b): obs for b, obs in self._budget_obs.items()}
        state["seen"] = sorted(list(p) for p in self._seen)
        return state

    def set_state(self, state: dict) -> None:
        super().set_state(state)
        self._budget_obs = {
            int(b): [(c, float(s)) for c, s in obs]
            for b, obs in state.get("budget_obs", {}).items()
        }
        self._seen = {(t, int(b)) for t, b in state.get("seen", [])}
        for b in sorted(self._budget_obs, reverse=True):
            if len(self._budget_obs[b]) >= self.min_points:
                self._observations = self._budget_obs[b]
                break
