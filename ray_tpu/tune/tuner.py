"""Tuner + TuneController: trial FSM over actors and placement groups.

Role-equivalent to the reference's Tuner (tune/tuner.py) and TuneController
(/root/reference/python/ray/tune/execution/tune_controller.py:68 — trial
state machine, actor-per-trial, PG-based resource booking). Trials run the
user function in a TrainWorker-style actor (thread + report queue); the
controller polls, feeds results to the scheduler, and applies decisions
(ASHA early-stop; PBT exploit/explore restarts).
"""
from __future__ import annotations

import dataclasses
import os
import time
import traceback
from typing import Any, Callable, Optional

import ray_tpu as rt
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.worker_group import TrainWorker
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.schedulers import CONTINUE, PERTURB, STOP, FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import generate_variants


@dataclasses.dataclass
class TuneConfig:
    num_samples: int = 1
    metric: Optional[str] = None
    mode: str = "max"
    scheduler: Optional[TrialScheduler] = None
    # Pluggable search algorithm (ray_tpu.tune.TPESearcher etc.); None =
    # grid/random variant generation from param_space (reference:
    # tune_config.search_alg -> Searcher).
    search_alg: Optional[Any] = None
    max_concurrent_trials: Optional[int] = None
    resources_per_trial: dict = dataclasses.field(default_factory=dict)
    seed: Optional[int] = None
    max_failures_per_trial: int = 0


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    config: dict
    metrics: dict
    metrics_history: list
    checkpoint: Optional[Checkpoint]
    best_checkpoint: Optional[Checkpoint]
    error: Optional[str]
    path: str

    @property
    def success(self) -> bool:
        return self.error is None


class ResultGrid:
    def __init__(self, results: list[TrialResult], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, i):
        return self._results[i]

    @property
    def errors(self) -> list[str]:
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (TuneConfig.metric or argument)")
        scored = [r for r in self._results
                  if r.error is None and metric in r.metrics]
        if not scored:
            raise ValueError(f"no successful trial reported metric {metric!r}")
        pick = max if mode == "max" else min
        return pick(scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([
            {"trial_id": r.trial_id, **_flatten(r.config, "config"),
             **r.metrics}
            for r in self._results
        ])


def _flatten(d: dict, prefix: str) -> dict:
    out = {}
    for k, v in d.items():
        key = f"{prefix}/{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


class Trial:
    """One configuration's lifecycle: PENDING -> RUNNING -> (PERTURBED ->
    RUNNING)* -> TERMINATED | ERRORED."""

    def __init__(self, trial_id: str, config: dict, storage_path: str,
                 resources: dict):
        self.trial_id = trial_id
        self.config = config
        self.path = storage_path
        self.resources = resources
        self.ckpt_manager = CheckpointManager(storage_path)
        self.state = "PENDING"
        self.actor = None
        self.pg = None
        self.metrics: dict = {}
        self.metrics_history: list[dict] = []
        self.iteration = 0
        self.error: Optional[str] = None
        self.failures = 0
        self.pbt_exploit: Optional[str] = None  # donor trial id (set by PBT)
        self.resume_path: Optional[str] = None

    def result(self) -> TrialResult:
        return TrialResult(
            trial_id=self.trial_id,
            config=self.config,
            metrics=self.metrics,
            metrics_history=self.metrics_history,
            checkpoint=self.ckpt_manager.latest,
            best_checkpoint=self.ckpt_manager.best,
            error=self.error,
            path=self.path,
        )


class TuneController:
    """Drives all trials to completion (reference: tune_controller.py:68).

    With a `searcher`, trials are created DYNAMICALLY (suggest() as capacity
    frees, so model-based searchers see completed results before proposing).
    Sweep state (trial configs/states/metrics + searcher observations) is
    checkpointed to `<storage>/tune_state.json` on every transition, so a
    controller restart resumes the sweep: finished trials keep their
    results, interrupted ones restart from their latest trial checkpoint
    (reference: the controller's experiment-state snapshots + Tuner.restore).
    """

    def __init__(self, trainable: Callable, trials: list[Trial],
                 tune_config: TuneConfig, poll_interval_s: float = 0.1,
                 searcher=None, storage: Optional[str] = None):
        self.trainable = trainable
        self.trials = trials
        self.cfg = tune_config
        self.scheduler = tune_config.scheduler or FIFOScheduler()
        self.searcher = searcher
        self.storage = storage
        self.poll_interval_s = poll_interval_s
        self._by_id = {t.trial_id: t for t in trials}

    # -- sweep-state persistence -------------------------------------------
    def _state_file(self) -> Optional[str]:
        return os.path.join(self.storage, "tune_state.json") if self.storage else None

    def _save_sweep_state(self) -> None:
        path = self._state_file()
        if path is None:
            return
        import json

        state = {
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "config": t.config,
                    "state": t.state,
                    "iteration": t.iteration,
                    "metrics": t.metrics,
                    "metrics_history": t.metrics_history,
                    "error": t.error,
                    "path": t.path,
                    "resources": t.resources,
                }
                for t in self.trials
            ],
            "searcher": self.searcher.get_state() if self.searcher else None,
        }
        tmp = f"{path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, path)
        except (OSError, TypeError, ValueError):
            traceback.print_exc()  # unserializable config: sweep runs, resume degraded

    @staticmethod
    def load_sweep_state(storage: str) -> Optional[dict]:
        import json

        try:
            with open(os.path.join(storage, "tune_state.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _next_trial_id(self) -> str:
        return f"trial_{len(self.trials):05d}"

    def _maybe_create_trials(self, capacity_left: int) -> list[Trial]:
        """Dynamic trial creation from the searcher, bounded by num_samples
        and free capacity."""
        created: list[Trial] = []
        if self.searcher is None or self.storage is None:
            return created
        # Budget: num_samples, but a searcher carrying its OWN sample count
        # (BasicVariantGenerator.total) must not be silently truncated by the
        # config default of 1 — suggest()->None remains the hard stop.
        budget = max(self.cfg.num_samples, getattr(self.searcher, "total", 0))
        while capacity_left > 0 and len(self.trials) < budget:
            tid = self._next_trial_id()
            cfg = self.searcher.suggest(tid)
            if cfg is None:
                break
            trial = Trial(
                trial_id=tid,
                config=cfg,
                storage_path=os.path.join(self.storage, tid),
                resources=dict(self.cfg.resources_per_trial),
            )
            self.trials.append(trial)
            self._by_id[tid] = trial
            created.append(trial)
            capacity_left -= 1
        return created

    # -- lifecycle ---------------------------------------------------------
    def _try_start(self, trial: Trial) -> bool:
        res = dict(trial.resources) or {"CPU": 1.0}
        pg = rt.placement_group([res], strategy="PACK",
                                name=f"tune-{trial.trial_id}")
        if not pg.ready(timeout=2.0):
            rt.remove_placement_group(pg)
            return False
        worker_cls = rt.remote(TrainWorker)
        trial.pg = pg
        trial.actor = worker_cls.options(
            placement_group=pg, placement_group_bundle_index=0,
            resources=res, max_concurrency=4,
        ).remote(0, 1, trial.trial_id, trial.path)
        # Fire-and-forget: actor cold-start (worker spawn) must not serialize
        # trial launches. A failed start surfaces through the first poll.
        trial.actor.start.remote(self.trainable, trial.config, trial.resume_path)
        trial.state = "RUNNING"
        return True

    def _teardown(self, trial: Trial) -> None:
        if trial.actor is not None:
            try:
                rt.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        if trial.pg is not None:
            try:
                rt.remove_placement_group(trial.pg)
            except Exception:
                pass
            trial.pg = None

    # -- decisions ---------------------------------------------------------
    def _apply_perturb(self, trial: Trial) -> None:
        """PBT exploit/explore: clone donor checkpoint, mutate config,
        restart in place."""
        donor = self._by_id.get(trial.pbt_exploit or "")
        trial.pbt_exploit = None
        donor_ckpt = donor.ckpt_manager.latest if donor else None
        if donor is None or donor_ckpt is None:
            return  # nothing to exploit yet: keep running as-is
        self._teardown(trial)
        trial.config = self.scheduler.explore(dict(donor.config))
        trial.resume_path = donor_ckpt.path
        trial.state = "PENDING"  # restart via the normal scheduling path

    # -- main loop ---------------------------------------------------------
    def run(self) -> list[TrialResult]:
        cap = self.cfg.max_concurrent_trials or max(len(self.trials), 1)
        self._save_sweep_state()
        while True:
            running = [t for t in self.trials if t.state == "RUNNING"]
            pending = [t for t in self.trials if t.state == "PENDING"]
            pending += self._maybe_create_trials(cap - len(running) - len(pending))
            if not running and not pending:
                break  # nothing active and the searcher offered nothing new
            for trial in pending:
                if len(running) >= cap:
                    break
                try:
                    if self._try_start(trial):
                        running.append(trial)
                        self._save_sweep_state()
                    else:
                        break  # no capacity right now; retry next cycle
                except Exception:
                    trial.error = traceback.format_exc()
                    trial.state = "ERRORED"
                    self._teardown(trial)
                    self._save_sweep_state()
            made_progress = False
            for trial in list(running):
                made_progress |= self._poll_trial(trial)
            if not made_progress:
                time.sleep(self.poll_interval_s)
        self._save_sweep_state()
        return [t.result() for t in self.trials]

    def _poll_trial(self, trial: Trial) -> bool:
        try:
            status = rt.get(trial.actor.poll.remote(), timeout=60)
        except Exception as e:
            return self._on_trial_failed(trial, f"trial actor died: {e}")
        progressed = False
        decision = CONTINUE
        for rep in status["reports"]:
            progressed = True
            metrics = dict(rep["metrics"])
            trial.iteration += 1
            metrics.setdefault("training_iteration", trial.iteration)
            if rep.get("checkpoint_dir"):
                try:
                    trial.ckpt_manager.register(rep["checkpoint_dir"], metrics)
                except OSError:
                    traceback.print_exc()
            trial.metrics = metrics
            trial.metrics_history.append(metrics)
            if self.searcher is not None:
                self.searcher.on_trial_result(trial.trial_id, metrics)
            d = self.scheduler.on_trial_result(trial, metrics)
            if d != CONTINUE:
                decision = d
        if progressed:
            self._save_sweep_state()
        if decision == STOP:
            self._complete(trial)
            return True
        if decision == PERTURB:
            self._apply_perturb(trial)
            return True
        if status["error"]:
            return self._on_trial_failed(trial, status["error"])
        if status["finished"]:
            self._complete(trial)
            return True
        return progressed

    def _complete(self, trial: Trial) -> None:
        self._teardown(trial)
        trial.state = "TERMINATED"
        self.scheduler.on_trial_complete(trial, trial.metrics)
        if self.searcher is not None:
            self.searcher.on_trial_complete(trial.trial_id, trial.metrics)
        self._save_sweep_state()

    def _on_trial_failed(self, trial: Trial, err: str) -> bool:
        self._teardown(trial)
        trial.failures += 1
        if trial.failures > self.cfg.max_failures_per_trial:
            trial.error = err
            trial.state = "ERRORED"
            self.scheduler.on_trial_complete(trial, None)
            if self.searcher is not None:
                self.searcher.on_trial_complete(trial.trial_id, None)
        else:
            resume = trial.ckpt_manager.latest
            trial.resume_path = resume.path if resume else None
            trial.state = "PENDING"
        self._save_sweep_state()
        return True


class Tuner:
    """Public API (reference: tune/tuner.py Tuner.fit -> ResultGrid).

    ``resume=True`` restores a sweep from ``<storage>/tune_state.json``
    (reference: Tuner.restore): TERMINATED/ERRORED trials keep their
    recorded results without re-running; interrupted trials restart from
    their latest checkpoint; the searcher's observations are restored so
    model-based search continues where it stopped."""

    def __init__(self, trainable: Callable, *, param_space: dict,
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None, resume: bool = False):
        from ray_tpu.train.config import RunConfig

        self.trainable = trainable
        self.param_space = param_space
        self.cfg = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig(name="tune_run")
        self.resume = resume

    def _restored_trials(self, storage: str) -> Optional[list[Trial]]:
        state = TuneController.load_sweep_state(storage)
        if state is None:
            return None
        trials: list[Trial] = []
        for ts in state["trials"]:
            t = Trial(ts["trial_id"], ts["config"], ts["path"],
                      dict(ts.get("resources", {})))
            t.iteration = ts.get("iteration", 0)
            t.metrics = ts.get("metrics", {})
            t.metrics_history = ts.get("metrics_history", [])
            t.error = ts.get("error")
            if ts["state"] in ("TERMINATED", "ERRORED"):
                t.state = ts["state"]
            else:
                # Interrupted mid-flight: restart from the latest trial
                # checkpoint (the per-trial CheckpointManager reloads its
                # own persisted index).
                resume = t.ckpt_manager.latest
                t.resume_path = resume.path if resume else None
                t.state = "PENDING"
            trials.append(t)
        if self.cfg.search_alg is not None and state.get("searcher") is not None:
            self.cfg.search_alg.set_state(state["searcher"])
        return trials

    def fit(self) -> ResultGrid:
        if not rt.is_initialized():
            rt.init()
        storage = self.run_config.resolved_storage_path()
        trials: Optional[list[Trial]] = None
        if self.resume:
            trials = self._restored_trials(storage)
        if trials is None:
            if self.cfg.search_alg is not None:
                trials = []  # created dynamically by the controller
            else:
                configs = generate_variants(
                    self.param_space, self.cfg.num_samples, self.cfg.seed
                )
                trials = [
                    Trial(
                        trial_id=f"trial_{i:05d}",
                        config=cfg,
                        storage_path=os.path.join(storage, f"trial_{i:05d}"),
                        resources=dict(self.cfg.resources_per_trial),
                    )
                    for i, cfg in enumerate(configs)
                ]
        controller = TuneController(
            self.trainable, trials, self.cfg,
            searcher=self.cfg.search_alg, storage=storage,
        )
        results = controller.run()
        return ResultGrid(results, self.cfg.metric, self.cfg.mode)
