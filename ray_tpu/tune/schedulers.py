"""Trial schedulers: FIFO, ASHA (async successive halving), PBT.

Role-equivalent to the reference's scheduler suite
(/root/reference/python/ray/tune/schedulers/async_hyperband.py ASHA,
schedulers/pbt.py PopulationBasedTraining, trial_scheduler.py decisions).
Schedulers see every trial result and return a decision; PBT additionally
rewrites a trial's config + restart checkpoint (exploit/explore).
"""
from __future__ import annotations

import math
import random
from typing import Any, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
# PBT: restart the SAME trial with new config/checkpoint.
PERTURB = "PERTURB"


class TrialScheduler:
    def on_trial_result(self, trial, metrics: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, metrics: Optional[dict]) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Async successive halving: at each rung (grace_period * rf^k), stop
    trials not in the top 1/reduction_factor of that rung so far."""

    def __init__(self, metric: str, mode: str = "max", max_t: int = 100,
                 grace_period: int = 1, reduction_factor: float = 4,
                 time_attr: str = "training_iteration"):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        # rung level -> {trial_id: metric at the step the trial crossed it}
        self._rungs: dict[int, dict[str, float]] = {}
        levels = []
        t = grace_period
        while t < max_t:
            levels.append(int(t))
            t *= reduction_factor
        self._levels = levels

    def _better(self, a: float, b: float) -> bool:
        return a > b if self.mode == "max" else a < b

    def on_trial_result(self, trial, metrics: dict) -> str:
        t = int(metrics.get(self.time_attr, 0))
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        for level in self._levels:
            if t < level:
                break
            rung = self._rungs.setdefault(level, {})
            if trial.trial_id in rung:
                continue  # milestone recorded once, at its crossing time
            rung[trial.trial_id] = float(value)
            # Cutoff: top 1/rf of results recorded at this rung continue.
            values = sorted(rung.values(), reverse=(self.mode == "max"))
            k = max(1, int(math.ceil(len(values) / self.rf)))
            cutoff = values[k - 1]
            if len(values) >= self.rf and self._better(cutoff, float(value)):
                return STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far falls below the median of the
    other trials' RUNNING AVERAGES at comparable time (Vizier's median
    stopping; reference: tune/schedulers/median_stopping_rule.py).

    Gentler than successive halving: a trial is judged against smoothed
    peers, never a fixed rung cutoff, so noisy-but-promising trials survive
    early wobbles."""

    def __init__(self, metric: str, mode: str = "max", grace_period: int = 4,
                 min_samples_required: int = 3,
                 time_attr: str = "training_iteration"):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        # trial_id -> [(t, value)] in arrival order
        self._results: dict[str, list[tuple[int, float]]] = {}

    def _running_avg(self, trial_id: str, upto_t: int) -> Optional[float]:
        vals = [v for (t, v) in self._results.get(trial_id, []) if t <= upto_t]
        return sum(vals) / len(vals) if vals else None

    def on_trial_result(self, trial, metrics: dict) -> str:
        value = metrics.get(self.metric)
        t = int(metrics.get(self.time_attr, 0))
        if value is None:
            return CONTINUE
        self._results.setdefault(trial.trial_id, []).append((t, float(value)))
        if t < self.grace_period:
            return CONTINUE
        others = [
            avg for tid in self._results if tid != trial.trial_id
            for avg in [self._running_avg(tid, t)] if avg is not None
        ]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        n = len(others)
        median = (others[n // 2] if n % 2 else
                  0.5 * (others[n // 2 - 1] + others[n // 2]))
        own = [v for (_, v) in self._results[trial.trial_id]]
        best = max(own) if self.mode == "max" else min(own)
        worse = best < median if self.mode == "max" else best > median
        return STOP if worse else CONTINUE

    def on_trial_complete(self, trial, metrics):
        # Keep the history: completed trials still define the median bar.
        pass


class PopulationBasedTraining(TrialScheduler):
    """PBT: every perturbation_interval, bottom-quantile trials clone a
    top-quantile trial's checkpoint (exploit) and mutate hyperparams
    (explore). Reference: tune/schedulers/pbt.py."""

    def __init__(self, metric: str, mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[dict] = None,
                 quantile_fraction: float = 0.25,
                 time_attr: str = "training_iteration",
                 seed: Optional[int] = None):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self.rng = random.Random(seed)
        # trial_id -> (last metric value, last perturb time)
        self._scores: dict[str, float] = {}
        self._last_perturb: dict[str, int] = {}

    def _quantiles(self) -> tuple[list[str], list[str]]:
        if len(self._scores) < 2:
            return [], []
        ordered = sorted(self._scores, key=self._scores.get,
                         reverse=(self.mode == "max"))
        k = max(1, int(len(ordered) * self.quantile))
        return ordered[:k], ordered[-k:]

    def on_trial_result(self, trial, metrics: dict) -> str:
        value = metrics.get(self.metric)
        t = int(metrics.get(self.time_attr, 0))
        if value is None:
            return CONTINUE
        self._scores[trial.trial_id] = float(value)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        top, bottom = self._quantiles()
        if trial.trial_id not in bottom or trial.trial_id in top:
            return CONTINUE
        # Exploit: clone a random top trial. The controller applies this.
        donor_id = self.rng.choice(top)
        trial.pbt_exploit = donor_id
        return PERTURB

    def explore(self, config: dict) -> dict:
        from ray_tpu.tune.search import mutate_config

        return mutate_config(config, self.mutations, self.rng)

    def on_trial_complete(self, trial, metrics):
        self._scores.pop(trial.trial_id, None)
