"""Search spaces + variant generation (grid / random sampling).

Role-equivalent to the reference's basic variant generator and sample domains
(/root/reference/python/ray/tune/search/basic_variant.py,
tune/search/sample.py): a param_space dict may contain `grid_search([...])`
markers (cross-producted) and Domain objects (sampled per trial), nested
arbitrarily in dicts.
"""
from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Optional


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError

    def perturb(self, value, rng: random.Random):
        """PBT explore step: nudge a value inside the domain."""
        return self.sample(rng)


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)

    def perturb(self, value, rng):
        out = value * rng.choice([0.8, 1.2])
        return min(max(out, self.low), self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.low, self.high = low, high
        self._lo, self._hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self._lo, self._hi))

    def perturb(self, value, rng):
        out = value * rng.choice([0.8, 1.2])
        return min(max(out, self.low), self.high)


class Randint(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)

    def perturb(self, value, rng):
        out = int(round(value * rng.choice([0.8, 1.2])))
        return min(max(out, self.low), self.high - 1)


class Choice(Domain):
    def __init__(self, categories: list):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> Randint:
    return Randint(low, high)


def choice(categories: list) -> Choice:
    return Choice(categories)


def sample_from(fn: Callable[[dict], Any]) -> "SampleFrom":
    return SampleFrom(fn)


class SampleFrom(Domain):
    """Callable domain: fn(spec_so_far) -> value (reference: tune.sample_from)."""

    def __init__(self, fn):
        self.fn = fn

    def sample(self, rng):  # resolved later with the partial config
        raise RuntimeError("SampleFrom is resolved with the trial config")


def grid_search(values: list) -> dict:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def _walk(space: dict, path=()):  # yields (path, value)
    for k, v in space.items():
        p = path + (k,)
        if isinstance(v, dict) and not _is_grid(v):
            yield from _walk(v, p)
        else:
            yield p, v


def _set_path(d: dict, path: tuple, value):
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def _copy_structure(space: dict) -> dict:
    out = {}
    for k, v in space.items():
        if isinstance(v, dict) and not _is_grid(v):
            out[k] = _copy_structure(v)
        else:
            out[k] = v
    return out


def generate_variants(param_space: dict, num_samples: int = 1,
                      seed: Optional[int] = None) -> list[dict]:
    """Expand grid_search cross-products x num_samples random draws."""
    rng = random.Random(seed)
    grid_items = [(p, v["grid_search"]) for p, v in _walk(param_space)
                  if _is_grid(v)]
    grid_paths = [p for p, _ in grid_items]
    grid_values = [vals for _, vals in grid_items]
    combos = list(itertools.product(*grid_values)) if grid_items else [()]

    configs: list[dict] = []
    for _ in range(num_samples):
        for combo in combos:
            cfg = _copy_structure(param_space)
            for p, val in zip(grid_paths, combo):
                _set_path(cfg, p, val)
            deferred = []
            for p, v in list(_walk(cfg)):
                if isinstance(v, SampleFrom):
                    deferred.append((p, v))
                elif isinstance(v, Domain):
                    _set_path(cfg, p, v.sample(rng))
                elif _is_grid(v):
                    pass  # already substituted
            for p, v in deferred:
                _set_path(cfg, p, v.fn(cfg))
            configs.append(cfg)
    return configs


def mutate_config(config: dict, mutations: dict, rng: random.Random) -> dict:
    """PBT explore: perturb the keys named in `mutations` (Domain -> perturb,
    list -> random choice, callable -> fresh value)."""
    out = {k: (dict(v) if isinstance(v, dict) else v) for k, v in config.items()}
    for key, spec in mutations.items():
        cur = out.get(key)
        if isinstance(spec, Domain):
            out[key] = spec.perturb(cur, rng)
        elif isinstance(spec, list):
            out[key] = rng.choice(spec)
        elif callable(spec):
            out[key] = spec()
        else:
            raise TypeError(f"unsupported mutation spec for {key!r}: {spec}")
    return out
