"""ray_tpu.tune: hyperparameter search over trial actors + placement groups.

Reference surface: ray.tune (SURVEY.md §2.4 Tune row) — Tuner/TuneConfig,
grid/random search spaces, ASHA + PBT schedulers, report/get_checkpoint
from inside a trial fn (shared with ray_tpu.train's session, as in the
reference where Train v2 runs on Tune).
"""
from ray_tpu.train.session import get_checkpoint, report
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.searcher import BasicVariantGenerator, BOHBSearcher, Searcher, TPESearcher
from ray_tpu.tune.tuner import (
    ResultGrid,
    TrialResult,
    TuneConfig,
    TuneController,
    Tuner,
)

__all__ = [
    "ASHAScheduler",
    "BOHBSearcher",
    "BasicVariantGenerator",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "ResultGrid",
    "Searcher",
    "TPESearcher",
    "TrialResult",
    "TrialScheduler",
    "TuneConfig",
    "TuneController",
    "Tuner",
    "choice",
    "get_checkpoint",
    "grid_search",
    "loguniform",
    "randint",
    "report",
    "sample_from",
    "uniform",
]
