"""CoreWorker: per-process runtime — object ownership, task submission and
execution, actor runtime, get/put/wait.

Role-equivalent to the reference's CoreWorker
(/root/reference/src/ray/core_worker/core_worker.h:167) plus its Cython
binding (_raylet.pyx:2678). The same class runs inside drivers and spawned
workers (the reference does the same; drivers are CoreWorker processes,
SURVEY §1). Key flows mirrored:

* task submission with lease caching per scheduling key
  (normal_task_submitter.h:86) — dependencies are resolved *before* the lease
  is requested (dependency_resolver.h) so a waiting task never holds
  resources, which is what makes executor-side blocking deadlock-free;
* ownership: the creating worker owns its return objects and serves them to
  borrowers (reference_counter.h:44; borrowers register with the owner);
* small objects are inlined in replies / the owner's in-process memory store,
  large objects go to the node's shared-memory arena
  (store_provider/memory_store, plasma_store_provider.h);
* actor task queues with per-connection FIFO ordering and
  max_concurrency via thread pool or asyncio (task_execution/
  actor_scheduling_queue.h, concurrency groups + fiber.h).

All networking runs on one asyncio loop (a dedicated thread in drivers, the
main thread in workers); user code runs on executor threads.
"""
from __future__ import annotations

import asyncio
import bisect
import collections
import concurrent.futures
import functools
import hashlib
import inspect
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu import chaos as _chaos
from ray_tpu.core import rpc, serialization
from ray_tpu.core.config import Config
from ray_tpu.core.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import (
    GetTimeoutError,
    ObjectLostError,
    ObjectRef,
    ObjectRefGenerator,
    set_ref_hooks,
)
from ray_tpu.core.object_store import MemoryStore, ObjectExistsError, ObjectStoreFullError, SharedMemoryClient
from ray_tpu.core.serialization import RemoteError
from ray_tpu.core import task_state as _ts
from ray_tpu.core.task_spec import ActorSpec, TaskOptions, TaskSpec, scheduling_key
from ray_tpu.obs import flight as _flight
from ray_tpu.obs import health as _obs_health
from ray_tpu.obs import profiler as _profiler
from ray_tpu.qos import context as _qos
from ray_tpu.util import metrics as _metrics
from ray_tpu.util import tracing as _tracing
from ray_tpu.util.bgtasks import spawn_bg as _spawn_bg_task

logger = logging.getLogger(__name__)

# Task execution latency (first-class runtime metric; ships via the
# reporter -> controller -> /metrics pipeline). Bound series: the observe
# hot path skips per-call tag-dict building.
_task_latency = _metrics.Histogram(
    "task.exec.latency_s",
    "wall-clock task execution latency (seconds)",
    boundaries=[0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30],
    tag_keys=("kind",),
)
_task_latency_task = _task_latency.bind({"kind": "task"})
_task_latency_actor = _task_latency.bind({"kind": "actor"})

# Owner-side streamed-batch histogram ({items-per-generator_items-frame:
# frames}) across every stream this process consumes — the streaming lane's
# analogue of rpc.batch_stats (bench_core reports it in the
# streaming_generator_items row's detail; _runtime_series promotes it to the
# stream.batch.items metric on /metrics).
_STREAM_BATCH_HIST: collections.Counter = collections.Counter()
_STREAM_BATCH_BUCKETS = [1, 2, 4, 8, 16, 32, 64]


def stream_batch_stats(reset: bool = False) -> dict:
    """{items-per-batch-frame: frames} absorbed by this process's streams."""
    out = {k: v for k, v in sorted(_STREAM_BATCH_HIST.items())}
    if reset:
        _STREAM_BATCH_HIST.clear()
    return out


_MISS = object()  # sentinel: value not locally resident


def _spec_fn_name(spec: "TaskSpec") -> str:
    """Human-readable callable name for state-index/event attribution:
    the explicit options name, the actor method, else the export key."""
    return spec.options.name or spec.method_name or spec.fn_id[:24]


def _error_type(err: BaseException) -> str:
    """The FAILED{error_type} discriminator: the USER exception's type when
    a RemoteError wraps one, else the infrastructure error's own type."""
    cause = getattr(err, "cause", None)
    return type(cause).__name__ if cause is not None else type(err).__name__


class ActorDiedError(Exception):
    pass


class TaskCancelledError(Exception):
    pass


class _StreamClosed(Exception):
    """Internal: the consumer closed a streaming generator early; the
    producer stops at its next yield."""


@dataclass
class OwnedObject:
    state: str = "PENDING"  # PENDING | READY | FAILED
    size: int = 0
    in_memory: bool = False
    in_shm: bool = False
    error: Optional[BaseException] = None
    local_refs: int = 0
    borrowers: int = 0
    ready_event: asyncio.Event | None = None


@dataclass
class LeasedWorker:
    address: str
    worker_id: str
    node_addr: str
    lease_id: str
    node_id: str = ""  # controller node id (state-index attribution)
    conn: Any = None
    busy: bool = False
    last_used: float = 0.0


class _KeySubmitter:
    """Per-scheduling-key task queue + lease pool (reference: per-SchedulingKey
    state in NormalTaskSubmitter)."""

    def __init__(self, core: "CoreWorker", key: str, opts: TaskOptions):
        self.core = core
        self.key = key
        self.opts = opts
        self.queue: list[tuple[TaskSpec, asyncio.Future]] = []
        self.workers: list[LeasedWorker] = []
        self.pending_lease_requests = 0

    def pump(self):
        # Batch dispatch: when the queue is deeper than the worker pool, ship
        # several specs per RPC (amortizes frame+serialization overhead; the
        # worker still executes them serially, preserving one-task-at-a-time
        # worker semantics). Shallow queues keep batch=1 for latency.
        while self.queue:
            free_workers = [w for w in self.workers if not w.busy and not (w.conn and w.conn.closed)]
            if not free_workers:
                break
            per = max(1, min(64, (len(self.queue) + len(free_workers) - 1) // len(free_workers)))
            for w in free_workers:
                if not self.queue:
                    break
                # Non-retryable (max_retries=0) tasks ship alone: a worker
                # crash mid-batch loses the whole reply, and tasks that DID
                # execute must not be retro-failed/retried in bulk — singleton
                # dispatch keeps their ambiguity window identical to unbatched.
                items = []
                while self.queue and len(items) < per:
                    spec, fut = self.queue[0]
                    retries = spec.options.max_retries
                    if retries == -1:
                        retries = self.core.config.max_task_retries_default
                    if retries == 0 and items:
                        break  # starts the next batch
                    items.append(self.queue.pop(0))
                    if retries == 0:
                        break
                w.busy = True
                self.core._spawn_bg(self._dispatch(w, items))
        want = len(self.queue)
        while want > 0 and self.pending_lease_requests < min(want, self.core.config.max_pending_lease_requests_per_key):
            self.pending_lease_requests += 1
            self.core._spawn_bg(self._request_lease())
            want -= 1

    async def _request_lease(self):
        try:
            lease_id = os.urandom(8).hex()
            reply = await self.core.controller.call(
                "request_lease",
                {
                    "lease_id": lease_id,
                    "demand": self.opts.resource_demand(),
                    "strategy": self.opts.scheduling_strategy,
                    "label_selector": self.opts.label_selector,
                },
            )
            if reply.get("infeasible"):
                err = RuntimeError(f"infeasible resource demand: {self.opts.resource_demand()} (no node can ever satisfy it)")
                for spec, fut in self.queue:
                    self.core._fail_task_returns(spec, err)
                    if not fut.done():
                        fut.set_result(False)
                self.queue.clear()
                return
            try:
                daemon = await self.core._daemon_conn(reply["address"])
                lease = await daemon.call(
                    "lease_worker",
                    {"lease_id": lease_id, "runtime_env": self.opts.runtime_env or None},
                )
                w = LeasedWorker(lease["address"], lease["worker_id"], reply["address"], lease_id,
                                 node_id=reply.get("node_id", ""))
                w.conn = await self.core._peer_conn(w.address)
            except Exception:
                # The controller already consumed resources for this lease;
                # give them back or the node leaks capacity forever.
                try:
                    await self.core.controller.call(
                        "release_lease", {"lease_id": lease_id, "strategy": self.opts.scheduling_strategy}
                    )
                except Exception:
                    pass
                raise
            self.workers.append(w)
        except Exception as e:
            # DETERMINISTIC runtime-env materialization failures are
            # PERMANENT for this task key (the env spec is part of the key):
            # a missing conda binary / container engine / failed env build
            # will fail identically on every retry — surface it to the
            # caller instead of retrying the lease forever (reference:
            # runtime-env agent setup errors fail the lease with a creation
            # error). The daemon raises RuntimeEnvSetupError for exactly
            # that class (the type survives the RPC hop); transient faults
            # (kv_get hiccup mid-download) take the retry branch.
            from ray_tpu.core.runtime_env import RuntimeEnvSetupError

            if isinstance(e, RuntimeEnvSetupError):
                for spec, fut in self.queue:
                    self.core._fail_task_returns(spec, RuntimeError(str(e)))
                    if not fut.done():
                        fut.set_result(False)
                self.queue.clear()
            else:
                logger.warning("lease request failed for %s: %s", self.key[:40], e)
                await asyncio.sleep(self.core.config.rpc_retry_delay_s)
        finally:
            self.pending_lease_requests -= 1
            self.pump()

    async def _dispatch(self, w: LeasedWorker, items: list[tuple[TaskSpec, asyncio.Future]]):
        try:
            # Lean framing (same scheme as actor pushes): per-conn interning
            # of (options, fn) constants; repeat calls ship small tuples.
            interned = w.conn.meta.setdefault("opts_out", {})
            wire = []
            for spec, _ in items:
                if spec.num_returns == -1:
                    self.core._stream_conns[spec.task_id.binary()] = w.conn
                key = (id(spec.options), spec.fn_id)
                ent = interned.get(key)
                if ent is None:
                    if len(interned) >= 512:
                        # Unbounded distinct options: stop interning.
                        wire.append({"spec": spec})
                        continue
                    oid_small = len(interned)
                    interned[key] = (spec.options, oid_small)  # pin: id() stays valid
                    wire.append({"spec": spec, "oid": oid_small})
                else:
                    msg = {"lean": (
                        spec.task_id.binary(), spec.args_blob, spec.num_returns, ent[1],
                        getattr(spec, "_attempts", 0),
                    )}
                    if spec.trace_ctx is not None:
                        msg["tc"] = spec.trace_ctx
                    if spec.qos_ctx is not None:
                        msg["qc"] = spec.qos_ctx
                    wire.append(msg)
            for spec, _ in items:
                # FSM: the attempt left the submitter queue for a concrete
                # worker — node/worker attribution is known from here on.
                self.core._task_event("task_dispatched", spec,
                                      node=w.node_id, exec_worker=w.worker_id[:12])
            fault = _chaos.maybe_inject("worker.task.dispatch", worker=w.worker_id[:12])
            if fault is not None and fault.kind == "error":
                # Simulated worker loss at dispatch: RpcError lands in the
                # except arm below — the real retry/backoff path, with no
                # process actually harmed (deterministic retry exerciser).
                raise rpc.RpcError(f"chaos[worker.task.dispatch#{fault.hit}] injected dispatch failure")
            reply = await w.conn.call("push_tasks", {"specs": wire})
            for (spec, fut), r in zip(items, reply["results"]):
                self.core._absorb_task_reply(spec, r, fut)
        except (rpc.ConnectionLost, rpc.RpcError) as e:
            await self._drop_worker(w, failed=True)
            for spec, fut in items:
                retries = spec.options.max_retries
                if retries == -1:
                    retries = self.core.config.max_task_retries_default
                attempts = getattr(spec, "_attempts", 0)
                if attempts < retries:
                    # Close the superseded attempt's index record: without a
                    # terminal event it would sit SUBMITTED/RUNNING forever,
                    # and the terminal-first eviction policy would shed real
                    # live state around these immortal ghosts.
                    self.core._task_event("task_failed", spec, attempt=attempts,
                                          error_type=type(e).__name__, retrying=True)
                    spec._attempts = attempts + 1  # type: ignore[attr-defined]
                    logger.warning("task %s lost worker (%s); retry %d", spec.task_id.hex()[:8], e, attempts + 1)
                    self.queue.append((spec, fut))
                else:
                    self.core._fail_task_returns(spec, RemoteError(f"task {spec.task_id.hex()[:8]} failed after retries: {e}"))
                    if not fut.done():
                        fut.set_result(False)
        finally:
            w.busy = False
            w.last_used = time.monotonic()
            self.pump()

    async def _drop_worker(self, w: LeasedWorker, failed: bool = False):
        if w in self.workers:
            self.workers.remove(w)
        try:
            daemon = await self.core._daemon_conn(w.node_addr)
            await daemon.call("return_worker", {"worker_id": w.worker_id, "reusable": not failed})
        except Exception:
            pass
        try:
            await self.core.controller.call("release_lease", {"lease_id": w.lease_id, "strategy": self.opts.scheduling_strategy})
        except Exception:
            pass

    async def reap_idle(self, linger_s: float):
        now = time.monotonic()
        for w in list(self.workers):
            if not w.busy and now - w.last_used > linger_s and not self.queue:
                await self._drop_worker(w)


class _StreamShipper:
    """Executor-side fast lane for one streaming generator task: a bounded
    per-stream buffer the producer appends into (cross-thread ``put`` for
    thread-run generators, loop-side ``aput`` for async generators), drained
    by a single loop-side pump that ships every adjacent item as ONE
    ``generator_items`` batch frame — one pickle+MAC+write per burst instead
    of a full cross-thread round trip per yielded item (the PR-1 coalescing
    move applied to the token path of every streamed response). A lone item
    still flushes the tick it lands: the pump is armed by the buffer's
    empty->nonempty transition, never a timer, so first-item latency stays
    one thread handoff — exactly what the old per-item path paid.

    Backpressure: the producer blocks (or awaits) while the buffer is full,
    and — when ``TaskOptions.generator_backpressure`` is set — while it runs
    more than ``bp`` items ahead of the consumer's acked consumption. Acks
    arrive batch-granular (the owner coalesces per-item consumption into one
    generator_ack per burst; see CoreWorker._install_stream_ack).
    """

    def __init__(self, core: "CoreWorker", conn, spec: TaskSpec, loop):
        self.core = core
        self.conn = conn
        self.spec = spec
        self.loop = loop
        self.tid = spec.task_id.binary()
        bp = getattr(spec.options, "generator_backpressure", -1)
        self.bp = bp if bp and bp > 0 else 0
        self.limit = max(1, core.config.stream_buffer_items)
        self._cond = threading.Condition()
        self.buf: list = []  # [(index, value)] pending ship, index order
        self.consumed = 0  # consumer-acked high-water mark (IO loop writes)
        self.closed = False  # consumer abandoned the stream
        self.error: Optional[BaseException] = None  # ship failure -> producer
        self.items_dropped = 0  # buffered items discarded at close (tallied)
        self._pump_armed = False
        self._aev = asyncio.Event()  # wakes loop-side waiters (async gens)

    # -- producer side --------------------------------------------------
    def _ready_locked(self, index: int) -> bool:
        return len(self.buf) < self.limit and (
            not self.bp or index - self.consumed < self.bp
        )

    def put(self, index: int, value) -> None:
        """Producer-thread append; blocks only while the buffer is full or
        the consumption bound is exhausted (backpressure semantics of the
        old per-item path, preserved)."""
        with self._cond:
            while True:
                if self.closed or self.tid in self.core._cancelled_streams:
                    raise _StreamClosed()
                if self.error is not None:
                    raise self.error
                if self._ready_locked(index):
                    break
                self._cond.wait()
            self.buf.append((index, value))
            arm = not self._pump_armed
            if arm:
                self._pump_armed = True
        if arm:
            self.loop.call_soon_threadsafe(self._pump_start)

    async def aput(self, index: int, value) -> None:
        """Loop-side append for async generators (never blocks the loop;
        room/ack waits ride an asyncio.Event the IO-loop writers set)."""
        while True:
            with self._cond:
                if self.closed or self.tid in self.core._cancelled_streams:
                    raise _StreamClosed()
                if self.error is not None:
                    raise self.error
                if self._ready_locked(index):
                    self.buf.append((index, value))
                    arm = not self._pump_armed
                    if arm:
                        self._pump_armed = True
                    break
                self._aev.clear()
            await self._aev.wait()
        if arm:
            self._pump_start()

    def finish(self) -> None:
        """Producer exhausted: wait for the pump to drain, then surface any
        ship failure (the old per-item path raised it at the failing item;
        here it lands at the next put or at finish)."""
        with self._cond:
            while self._pump_armed and self.error is None:
                self._cond.wait()
            if self.error is not None and not self.closed:
                raise self.error

    async def afinish(self) -> None:
        while True:
            with self._cond:
                if not self._pump_armed or self.error is not None:
                    if self.error is not None and not self.closed:
                        raise self.error
                    return
                self._aev.clear()
            await self._aev.wait()

    # -- IO-loop side ---------------------------------------------------
    def on_ack(self, consumed: int) -> None:
        with self._cond:
            if consumed > self.consumed:
                self.consumed = consumed
                self._cond.notify_all()
        self._aev.set()

    def close_consumer(self) -> None:
        """Consumer abandoned the stream: discard what is buffered (tallied
        — no silent caps) and wake any blocked producer so it observes the
        close at its next yield."""
        with self._cond:
            self.closed = True
            n = len(self.buf)
            if n:
                self.items_dropped += n
                del self.buf[:n]
            self._cond.notify_all()
        self._aev.set()

    def _pump_start(self) -> None:
        self.core._spawn_bg(
            self._pump(), name=f"stream-pump-{self.spec.task_id.hex()[:8]}"
        )

    async def _pump(self) -> None:
        """Drain the buffer until empty: each swap ships as one batch frame.
        Single-instance per stream (the armed flag), so wire order == index
        order; re-armed by the producer's next empty->nonempty append."""
        while True:
            with self._cond:
                batch, self.buf = self.buf, []
                if not batch:
                    self._pump_armed = False
                    self._cond.notify_all()
                    self._aev.set()
                    return
                self._cond.notify_all()  # room freed: unblock the producer
            self._aev.set()
            try:
                items = []
                for index, value in batch:
                    items.append((index, await self.core._package_value(
                        ObjectID.for_return(self.spec.task_id, index), value
                    )))
                fault = _chaos.maybe_inject(
                    "rpc.stream.item", task=self.spec.task_id.hex()[:8],
                    attempt=getattr(self.spec, "_attempts", 0),
                )
                if fault is not None:
                    if fault.kind == "delay":
                        await asyncio.sleep(fault.delay_s)
                    elif fault.kind == "drop":
                        # A lost frame on a healthy-looking conn would strand
                        # the consumer waiting for the missing indices, so a
                        # real transport that eats a frame kills the
                        # connection — emulate exactly that: the caller's
                        # connection-loss retry resubmits on a fresh worker
                        # and the replay's duplicate indices dedup owner-side.
                        await self.conn.close()
                        raise rpc.ConnectionLost(
                            f"chaos[rpc.stream.item#{fault.hit}] dropped "
                            "generator batch frame"
                        )
                await self.conn.notify("generator_items", {
                    "task_id": self.tid,
                    "items": items,
                    "want_ack": bool(self.bp),
                })
            except BaseException as e:  # noqa: BLE001 - surfaced to the producer
                with self._cond:
                    self.error = e
                    self._pump_armed = False
                    self._cond.notify_all()
                self._aev.set()
                return


class CoreWorker:
    def __init__(self, mode: str, controller_addr: str, config: Config | None = None):
        self.mode = mode  # "driver" | "worker"
        self.controller_addr = controller_addr
        self.config = config or Config().apply_env()
        self.worker_id = os.environ.get("RAYTPU_WORKER_ID", WorkerID.from_random().hex())
        self.node_id = os.environ.get("RAYTPU_NODE_ID", "")
        self.job_id = JobID.nil()
        self.loop: asyncio.AbstractEventLoop | None = None
        self._loop_thread: threading.Thread | None = None
        self.server = rpc.RpcServer(self, host=self.config.node_ip)
        self.address = ""
        self.controller: rpc.Connection | None = None
        self.daemon: rpc.Connection | None = None
        self.daemon_addr = os.environ.get("RAYTPU_DAEMON_ADDR", "")
        self.store: SharedMemoryClient | None = None
        self.memory_store = MemoryStore()
        self.owned: dict[ObjectID, OwnedObject] = {}
        self._peer_conns: dict[str, rpc.Connection] = {}
        self._daemon_conns: dict[str, rpc.Connection] = {}
        self._submitters: dict[str, _KeySubmitter] = {}
        self._exported: set[str] = set()
        self._fn_cache: dict[str, Any] = {}
        self._actor_runtime: Optional["ActorRuntime"] = None
        self._actor_send_queues: dict = {}
        self._actor_conns: dict[ActorID, dict] = {}  # actor_id -> {addr, conn, info}
        self._executor = concurrent.futures.ThreadPoolExecutor(max_workers=1, thread_name_prefix="raytpu-exec")
        self._shutdown = False
        # Strong refs to fire-and-forget tasks (asyncio tracks tasks only
        # weakly; a gc cycle landing mid-await kills an unreferenced task
        # with GeneratorExit — the init-task bug class). Everything spawned
        # fire-and-forget on this worker's loop goes through _spawn_bg.
        self._bg_tasks: set = set()
        # Submitted-task dependency pins: holding the ObjectRef objects keeps
        # their refcount registrations alive until the task completes
        # (reference: ReferenceCounter "submitted task references",
        # reference_counter.h:44).
        self._inflight_deps: dict[bytes, list] = {}
        # Lineage: specs (+ pinned dep refs) of finished normal tasks whose
        # shm-resident returns may need re-execution if every copy is lost
        # (reference: TaskManager lineage, task_manager.h:184-217; capped by
        # lineage_max_bytes with oldest-first eviction).
        self._lineage: dict[bytes, tuple[TaskSpec, list, int]] = {}
        self._lineage_bytes = 0
        # In-flight recoveries, one future per object so concurrent getters
        # coalesce (reference: ObjectRecoveryManager idempotent per-object ops,
        # object_recovery_manager.h:62-76).
        self._recovering: dict[bytes, asyncio.Future] = {}
        self._bg: list[asyncio.Task] = []
        # Pubsub subscriptions: channel -> callback(key, data). Re-subscribed
        # on every controller (re)connect (reference: subscribers re-establish
        # long-poll streams after GCS restart).
        self._pub_handlers: dict[str, Any] = {}
        # Live streaming-generator tasks this process submitted:
        # task_id bytes -> ObjectRefGenerator (reference: TaskManager's
        # streaming-generator return bookkeeping).
        self._streaming: dict[bytes, "ObjectRefGenerator"] = {}
        # Executor side: per-stream batch shipper (bounded buffer + pump).
        self._stream_shippers: dict[bytes, "_StreamShipper"] = {}
        # Early-close discards, folded in at stream cleanup (the per-shipper
        # tallies die with their streams; this survives for /metrics).
        self._stream_items_dropped = 0
        # Caller side: the conn each live stream was pushed over, so a
        # consumer close can reach the producing worker (reference:
        # CoreWorkerService.CancelTask applied to streaming generators).
        self._stream_conns: dict[bytes, Any] = {}
        # Executor side: streams whose consumer closed early; the producer
        # stops at its next yield.
        self._cancelled_streams: set[bytes] = set()
        self._live_streams: set[bytes] = set()  # streaming tasks currently executing
        # Transient shm objects (dag zero-copy edges) whose delete was
        # deferred because a consumer view still pins them; reaped later.
        self._shm_garbage: list[ObjectID] = []
        self.task_events: list[dict] = []  # per-task event buffer (task_event_buffer.h equiv)
        self._events_reported = 0  # high-water mark shipped to the controller
        self._events_dropped = 0  # events discarded by buffer trims (observable loss)
        self._events_flush_lock = asyncio.Lock()
        self._event_flush_armed = False  # debounced lifecycle-event flush timer
        # Borrowed-object table: oid bytes -> {"owner_addr", "refs"} — the
        # borrower half of the ownership picture memory_summary reports
        # (the owner half is `owned` with its borrowers counter).
        self._borrowed: dict[bytes, dict] = {}
        # Object-store access counters (plain ints: no lock on the get/put
        # hot paths; shipped as counter series by the metrics reporter).
        self._obj_hits = 0
        self._obj_misses = 0
        self._obj_bytes_read = 0
        self._obj_bytes_written = 0
        self._current_task: Optional[TaskSpec] = None
        # Buffered cross-thread submission lane: sync callers append
        # closures; the IO loop is woken ONCE per burst instead of per call
        # (call_soon_threadsafe writes the loop's self-pipe — a syscall per
        # submission otherwise). FIFO safety: the drain callback is armed
        # before any LATER call_soon_threadsafe / run_coroutine_threadsafe
        # from the same caller thread, so everything posted before a sync
        # get/free still lands first.
        self._post_buf: collections.deque = collections.deque()
        self._post_armed = False
        self._post_lock = threading.Lock()

    # -- lifecycle ------------------------------------------------------
    def start_driver_sync(self):
        """Spin up the IO loop thread and connect (driver mode)."""
        ready = threading.Event()

        def run():
            self.loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self.loop)
            # Strong reference: asyncio only weakly tracks tasks, and an
            # unreferenced init task can be GC'd mid-await (GeneratorExit) —
            # observed as a flaky "driver failed to connect" when import
            # pressure shifted a gc cycle into the dial window.
            self._init_task = self.loop.create_task(self._async_init(ready))
            self.loop.run_forever()

        self._loop_thread = threading.Thread(target=run, name="raytpu-io", daemon=True)
        self._loop_thread.start()
        # Generous margin over the dial timeout: on a loaded single-core host
        # (CI running a full cluster per test module) registration RPCs can
        # take several seconds of scheduler delay without anything being wrong.
        # Margin covers a single-core host where a concurrent XLA compile or
        # the PREVIOUS test cluster's teardown can starve this process for
        # tens of seconds (observed in full-suite runs; the same init passes
        # instantly in isolation).
        if not ready.wait(self.config.rpc_connect_timeout_s + 160):
            raise TimeoutError("driver failed to connect to controller")

    async def _async_init(self, ready: threading.Event | None = None):
        self.address = await self.server.start()
        # Persistent controller link: a controller restart redials and (for
        # drivers) re-registers the job, keeping the same job id (reference:
        # GCS FT — clients reconnect after GCS restart).
        self.controller = rpc.PersistentConnection(
            self.controller_addr, handler=self, on_reconnect=self._controller_handshake
        )
        await self.controller.ensure()
        if self.mode == "driver":
            reply = self._register_reply
            nodes = reply["nodes"]
            # Attach to a local daemon's store if one exists on this host.
            for nid, info in nodes.items():
                if info["state"] == "ALIVE" and info["store_path"] and os.path.exists(info["store_path"]):
                    self.daemon_addr = info["address"]
                    self.node_id = nid
                    break
        if self.daemon_addr:
            self.daemon = await rpc.connect(self.daemon_addr, handler=self, timeout=self.config.rpc_connect_timeout_s)
        store_path = os.environ.get("RAYTPU_STORE_PATH", "")
        if not store_path and self.daemon is not None:
            node_info = await self.controller.call("get_cluster_state", {})
            info = node_info["nodes"].get(self.node_id)
            store_path = info["store_path"] if info else ""
        if store_path and os.path.exists(store_path):
            self.store = SharedMemoryClient(store_path, spill_dir=self.config.object_spill_dir or None)
        if self.mode == "worker":
            reply = await self.daemon.call("register_worker", {"worker_id": self.worker_id, "address": self.address})
            self.node_id = reply["node_id"]
            self.config = self.config.adopt_cluster(reply["config"])
            rpc.apply_transport_config(self.config)
            if self.config.chaos_spec:
                _chaos.install_from_json(self.config.chaos_spec)
            if self.store is not None:
                # The store client predates the config push: re-apply
                # settings that change ITS behavior (a worker without the
                # pushed spill dir could never spill under pressure).
                self.store.spill_dir = self.config.object_spill_dir or None

            # Die with the parent daemon (reference:
            # CoreWorker::ExitIfParentRayletDies, core_worker.h:1427): an
            # orphan that outlives its node would otherwise idle forever,
            # redialing a dead controller and holding memory.
            def _daemon_lost(_conn):
                if not self._shutdown:
                    logger.warning("daemon connection lost; worker exiting")
                    self._shutdown = True
                    try:
                        self.loop.call_soon(self.loop.stop)
                    except Exception:
                        pass

            self.daemon.on_close = _daemon_lost
        set_ref_hooks(self._on_ref_created, self._on_ref_removed)
        self._bg.append(asyncio.create_task(self._reaper_loop()))
        # Observability plane: point the flight recorder at the ADOPTED
        # config (a spawned worker's env defaults differ from the head's)
        # and start the loop-lag probe on this process's IO loop.
        self._setup_observability()
        if ready is not None:
            ready.set()

    def _setup_observability(self):
        cfg = self.config
        _flight.configure(
            proc_id=self.worker_id[:12],
            dump_dir=os.environ.get("RAYTPU_FLIGHT_DIR", "") or cfg.obs_flight_dir,
            capacity=cfg.obs_flight_ring,
            storm_expiries=cfg.obs_storm_expiries,
            storm_window_s=cfg.obs_storm_window_s,
        )
        loop = self.loop

        def _report_dump(path: str, trigger: str):
            # Dumps fire from arbitrary threads (qos hops, chaos sites):
            # hop to the IO loop, then best-effort notify the controller so
            # the path surfaces on /api/events. worker.death dumps skip this
            # (the process exits immediately); the daemon harvest covers them.
            def _post():
                if not self._shutdown and self.controller is not None:
                    self._spawn_bg(self.controller.notify("report_flight_dump", {
                        "proc": self.worker_id[:12], "path": path,
                        "trigger": trigger, "node_id": self.node_id,
                    }), name="flight-dump-report")

            try:
                loop.call_soon_threadsafe(_post)
            except RuntimeError:
                pass  # loop already closed: the file on disk is the artifact

        _flight.set_dump_hook(_report_dump)
        if cfg.obs_loop_probe_interval_s > 0:
            self._loop_probe = _obs_health.LoopLagProbe(
                f"core-{self.mode}",
                interval_s=cfg.obs_loop_probe_interval_s,
                spike_s=cfg.obs_loop_spike_s,
            )
            self._bg.append(asyncio.create_task(self._loop_probe.run()))
        # Continuous profiler: arm (or disarm, hz<=0) THIS process's sampler
        # with the adopted config. Also installs the tracing profile hook so
        # traced exec spans get per-trace accumulators. Idempotent across
        # controller reconnects.
        _profiler.arm(
            hz=cfg.profile_hz,
            proc=self.worker_id[:12],
            max_stacks=cfg.profile_max_stacks,
            epoch_s=cfg.profile_epoch_s,
            window_epochs=cfg.profile_window_epochs,
            max_traces=cfg.profile_max_traces,
        )

    async def _controller_handshake(self, conn):
        for channel in self._pub_handlers:
            await conn.call("subscribe", {"channel": channel})
        if self.mode != "driver":
            return  # workers register with their daemon, not the controller
        payload = {"driver_addr": self.address}
        if not self.job_id.is_nil():
            payload["job_id"] = self.job_id.binary()  # reconnect: keep the job
        reply = await conn.call("register_job", payload)
        self.job_id = JobID(reply["job_id"])
        self.config = Config.from_dict(reply["config"])
        if self.config.chaos_spec:
            # Driver adopts the cluster chaos schedule with the rest of the
            # config (idempotent re-install across controller reconnects).
            _chaos.install_from_json(self.config.chaos_spec)
        if self.store is not None:
            self.store.spill_dir = self.config.object_spill_dir or None
        self._register_reply = reply

    async def subscribe_channel(self, channel: str, callback):
        """Subscribe to a controller pubsub channel; callback(key, data) runs
        on the IO loop for every publish."""
        self._pub_handlers[channel] = callback
        await self.controller.call("subscribe", {"channel": channel})

    def handle_pub(self, conn, p):
        cb = self._pub_handlers.get(p.get("channel"))
        if cb is not None:
            cb(p.get("key"), p.get("data"))

    def attach_loop(self, loop: asyncio.AbstractEventLoop):
        self.loop = loop

    async def _reaper_loop(self):
        last_metrics = 0.0
        while not self._shutdown:
            await asyncio.sleep(0.5)
            for sub in list(self._submitters.values()):
                await sub.reap_idle(linger_s=2.0)
            if self._shm_garbage and self.store is not None:
                self._shm_garbage = [o for o in self._shm_garbage if not self.store.reap(o)]
            now = time.monotonic()
            if now - last_metrics >= self.config.metrics_report_interval_s:
                last_metrics = now
                await self._report_metrics()

    async def _report_metrics(self):
        """Ship this process's metric series + new task events to the
        controller (reference: per-node agent scrape -> dashboard, and the
        TaskEventBuffer -> GcsTaskManager pipeline, task_event_buffer.h)."""
        try:
            series = _metrics.snapshot() + self._runtime_series()
            if series:
                await self.controller.notify("report_metrics", {"reporter": self.worker_id, "series": series})
        except Exception:
            pass
        await self._flush_task_events()

    def _runtime_series(self) -> list[dict]:
        """First-class runtime metrics that live outside the user registry:
        RPC envelope/byte counters (rpc.metrics_series), queue-depth gauges,
        object-store access counters, dropped-event counters. Records are
        snapshot()-shaped so they merge through the same controller
        pipeline."""
        now = time.time()
        out = rpc.metrics_series()

        def rec(name, kind, value, tags, desc=""):
            out.append({"name": name, "kind": kind, "description": desc,
                        "tags": tags, "value": float(value), "ts": now})

        rec("scheduler.queue.depth", "gauge",
            sum(len(s.queue) for s in self._submitters.values()),
            {"queue": "submitter"}, "task specs queued awaiting worker leases")
        rec("scheduler.queue.depth", "gauge",
            sum(q.qsize() for q in self._actor_send_queues.values()),
            {"queue": "actor_pump"}, "actor tasks buffered in send pumps")
        rec("object.store.ops", "counter", self._obj_hits,
            {"result": "hit"}, "object reads resolved from local memory/shm")
        rec("object.store.ops", "counter", self._obj_misses,
            {"result": "miss"}, "object reads that needed a remote fetch/recovery")
        rec("object.store.bytes", "counter", self._obj_bytes_read,
            {"op": "read"}, "object bytes read locally")
        rec("object.store.bytes", "counter", self._obj_bytes_written,
            {"op": "write"}, "object bytes written by put/task returns")
        if self._events_dropped:
            rec("events_dropped_total", "counter", self._events_dropped,
                {"where": "worker"}, "task events lost to buffer trims before reporting")
        fr = _flight.recorder()
        if fr.events_evicted:
            rec("flight.events_evicted", "counter", fr.events_evicted, {},
                "flight-recorder ring evictions (oldest events displaced)")
        if fr.dumps_written:
            rec("flight.dumps_written", "counter", fr.dumps_written, {},
                "flight-recorder dumps written by this process")
        ps = _profiler.status()
        if ps["samples"]:
            rec("profile.samples", "counter", ps["samples"], {},
                "wall-clock sampler stacks folded by this process")
        if ps["samples_dropped"]:
            rec("profile.samples_dropped", "counter", ps["samples_dropped"], {},
                "sampler stacks rejected by the bounded distinct-stack table")
        # Device-side cost gauges: jax local_devices() memory stats, gated
        # hard (never imports jax; CPU backends report None and emit nothing).
        out.extend(_profiler.device_memory_records(now))
        if _STREAM_BATCH_HIST:
            # Streamed-item batch-size histogram (owner side): how many items
            # each generator_items frame carried — the live-cluster view of
            # the streaming fast lane's coalescing (mirrors rpc.envelope.messages).
            counts = [0] * (len(_STREAM_BATCH_BUCKETS) + 1)
            total, n_frames = 0.0, 0
            for size, cnt in _STREAM_BATCH_HIST.items():
                # Same bucket convention as util.metrics._observe_locked.
                counts[bisect.bisect_left(_STREAM_BATCH_BUCKETS, size)] += cnt
                total += size * cnt
                n_frames += cnt
            out.append({
                "name": "stream.batch.items", "kind": "histogram",
                "description": "items coalesced per generator_items batch frame",
                "tags": {}, "value": 0.0, "ts": now,
                "buckets": list(_STREAM_BATCH_BUCKETS), "counts": counts,
                "sum": total, "n": n_frames,
            })
        if self._stream_items_dropped:
            rec("stream.items_dropped", "counter", self._stream_items_dropped, {},
                "buffered stream items discarded when the consumer closed early")
        # chaos.injected_total{site,kind}: THIS process's injections (driver,
        # spawned worker, or in-process daemons co-resident with a driver) —
        # no silent injection, every fault reaches /metrics.
        out.extend(_chaos.metrics_series())
        return out

    async def _flush_task_events(self):
        # Serialize flushes: the periodic reporter and on-demand
        # tracing.get_task_events() flush can interleave at the awaits,
        # double-sending one slice and never sending the next.
        async with self._events_flush_lock:
            try:
                mark = self._events_reported
                new = self.task_events[mark:]
                if new:
                    await self.controller.notify(
                        "report_task_events", {"reporter": self.worker_id, "events": new}
                    )
                    # Commit only AFTER the send: a failed report (controller
                    # down) must retry these events next tick. Recompute against
                    # the current mark — a concurrent trim may have shifted it.
                    self._events_reported = min(self._events_reported + len(new), len(self.task_events))
            except Exception:
                pass

    def shutdown_sync(self):
        if self._shutdown or self.loop is None:
            return
        self._shutdown = True
        set_ref_hooks(None, None)

        async def _stop():
            for sub in self._submitters.values():
                for w in list(sub.workers):
                    await sub._drop_worker(w)
            await self.server.close()
            for c in list(self._peer_conns.values()) + list(self._daemon_conns.values()):
                await c.close()
            if self.controller:
                await self.controller.close()
            if self.daemon:
                await self.daemon.close()
            for t in asyncio.all_tasks():
                if t is not asyncio.current_task():
                    t.cancel()

        # Stop the loop only AFTER _stop()'s result has been delivered back
        # to this thread: loop.stop() inside the coroutine halts the loop
        # before run_coroutine_threadsafe's done-callback can run, so
        # .result() would always ride out its full timeout.
        try:
            asyncio.run_coroutine_threadsafe(_stop(), self.loop).result(timeout=5)
        except Exception:
            pass
        try:
            self.loop.call_soon_threadsafe(self.loop.stop)
        except RuntimeError:
            pass
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=2)
        self._executor.shutdown(wait=False)

    # -- helpers --------------------------------------------------------
    def _post_to_loop(self, fn):
        """Queue ``fn`` to run on the IO loop, coalescing wakeups: a burst
        of submissions from a sync caller pays one self-pipe write, not one
        per call. Posted order == execution order."""
        with self._post_lock:
            self._post_buf.append(fn)
            if self._post_armed:
                return
            self._post_armed = True
        try:
            self.loop.call_soon_threadsafe(self._drain_posts)
        except BaseException:
            # ANY scheduling failure (closed loop RuntimeError, loop-not-
            # started AttributeError) must disarm, or every later post
            # no-ops silently and gets hang instead of this loud error.
            with self._post_lock:
                self._post_armed = False
            raise

    def _drain_posts(self):
        # Loop until empty INSIDE one callback — never re-arm via call_soon.
        # The FIFO contract with later cross-thread work depends on it: a fn
        # posted while this drain runs must execute before a get/free the
        # same caller thread schedules afterwards, and a deferred re-arm
        # callback would land BEHIND that get in the ready queue. With the
        # in-callback loop, either this drain's next round picks the fn up,
        # or the post observed armed=False and scheduled a fresh drain
        # before the caller could schedule the get.
        while True:
            with self._post_lock:
                if not self._post_buf:
                    self._post_armed = False
                    return
                fns = list(self._post_buf)
                self._post_buf.clear()
            for fn in fns:
                try:
                    fn()
                except Exception:  # isolate: one bad post must not drop the rest
                    logger.exception("posted submission callback failed")

    def _spawn_bg(self, coro, name: str | None = None) -> "asyncio.Task":
        """create_task with a strong reference held until completion (see
        _bg_tasks: an unreferenced fire-and-forget task can be GC-killed
        mid-await). Must be called from the IO loop."""
        return _spawn_bg_task(self._bg_tasks, coro, name=name)

    def _run(self, coro, timeout=None):
        """Run a coroutine on the IO loop from a sync context."""
        if self.loop is None:
            raise RuntimeError("core worker not started")
        if threading.current_thread() is self._loop_thread or (
            self._loop_thread is None and threading.current_thread() is threading.main_thread() and self.mode == "worker"
        ):
            raise RuntimeError("cannot block the IO loop thread with a sync call")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        try:
            return fut.result(timeout)
        except concurrent.futures.TimeoutError:
            fut.cancel()
            raise GetTimeoutError(f"timed out after {timeout}s")

    async def _peer_conn(self, addr: str) -> rpc.Connection:
        conn = self._peer_conns.get(addr)
        if conn is None or conn.closed:
            conn = await rpc.connect(addr, handler=self, timeout=self.config.rpc_connect_timeout_s, retry=False)
            self._peer_conns[addr] = conn
        return conn

    async def _daemon_conn(self, addr: str) -> rpc.Connection:
        if addr == self.daemon_addr and self.daemon is not None and not self.daemon.closed:
            return self.daemon
        conn = self._daemon_conns.get(addr)
        if conn is None or conn.closed:
            conn = await rpc.connect(addr, handler=self, timeout=self.config.rpc_connect_timeout_s, retry=False)
            self._daemon_conns[addr] = conn
        return conn

    def _event(self, kind: str, **kw):
        # One timeline: the same clock as Span/event() in util/tracing, so
        # state-index timings and span timings interleave consistently.
        ev = {"ts": _tracing.now(), "kind": kind, "worker": self.worker_id[:12], **kw}
        self.task_events.append(ev)
        # Tee into the process-local flight recorder: the reporter buffer
        # above trims once shipped, the ring RETAINS (bounded) so a dump at
        # death still holds the recent story. Same dict, no copy.
        _flight.absorb(ev)
        if len(self.task_events) > self.config.event_buffer_size:
            trimmed = len(self.task_events) // 2
            # Only events the controller never saw are LOST; already-reported
            # ones were shipped before the trim.
            self._events_dropped += max(0, trimmed - self._events_reported)
            del self.task_events[:trimmed]
            self._events_reported = max(0, self._events_reported - trimmed)

    def _task_event(self, kind: str, spec: TaskSpec, **kw):
        """Emit one task-lifecycle FSM event (task_state.EVENT_STATE keys
        it to a transition) carrying the attempt number and attribution the
        controller's per-task index folds. Gated by task_events_enabled so
        the state pipeline can be A/B'd off; always called on the IO loop."""
        if not self.config.task_events_enabled and spec.trace_ctx is None:
            return  # traced events still flow: tracing must survive the A/B flag
        fields = {
            "task_id": spec.task_id.hex(),
            "attempt": getattr(spec, "_attempts", 0),
            "fn": _spec_fn_name(spec),
            "job": spec.job_id.hex(),
        }
        tc = spec.trace_ctx
        if tc is not None:
            fields["trace_id"], fields["parent_id"] = tc[0], tc[1]
        fields.update(kw)
        self._event(kind, **fields)
        self._arm_event_flush()

    def _arm_event_flush(self):
        """Debounced early flush: lifecycle transitions reach the controller
        within task_event_flush_interval_s instead of riding the (much
        slower) metrics tick, so `raytpu list tasks --state RUNNING` sees a
        task soon after it starts. One timer per window, not per event."""
        if self._event_flush_armed or self._shutdown:
            return
        self._event_flush_armed = True
        try:
            self.loop.call_later(
                self.config.task_event_flush_interval_s, self._event_flush_fire
            )
        except Exception:
            self._event_flush_armed = False

    def _event_flush_fire(self):
        self._event_flush_armed = False
        if not self._shutdown:
            self._spawn_bg(self._flush_task_events())

    # -- ownership / refcounting ---------------------------------------
    def _on_ref_created(self, ref: ObjectRef):
        if self._shutdown or self.loop is None:
            return
        if ref.owner_addr == self.address:
            rec = self.owned.get(ref.id)
            if rec is not None:
                rec.local_refs += 1
        else:
            try:
                self.loop.call_soon_threadsafe(self._notify_owner, ref.owner_addr, "add_borrow", ref.id.binary())
            except RuntimeError:
                pass

    def _on_ref_removed(self, ref: ObjectRef):
        if self._shutdown or self.loop is None:
            return
        try:
            if ref.owner_addr == self.address:
                self.loop.call_soon_threadsafe(self._dec_local_ref, ref.id)
            else:
                self.loop.call_soon_threadsafe(self._notify_owner, ref.owner_addr, "remove_borrow", ref.id.binary())
        except RuntimeError:
            pass

    def _notify_owner(self, owner_addr: str, method: str, oid_bin: bytes):
        # Borrower-side ledger (runs on the IO loop, FIFO with the notify):
        # memory_summary reports who this process borrows from, mirroring
        # the owner's borrowers counter.
        if method == "add_borrow":
            ent = self._borrowed.get(oid_bin)
            if ent is None:
                ent = self._borrowed[oid_bin] = {"owner_addr": owner_addr, "refs": 0}
            ent["refs"] += 1
        elif method == "remove_borrow":
            ent = self._borrowed.get(oid_bin)
            if ent is not None:
                ent["refs"] -= 1
                if ent["refs"] <= 0:
                    del self._borrowed[oid_bin]

        async def go():
            try:
                conn = await self._peer_conn(owner_addr)
                await conn.notify(method, {"oid": oid_bin})
            except Exception:
                pass

        self._spawn_bg(go())

    def _dec_local_ref(self, oid: ObjectID):
        rec = self.owned.get(oid)
        if rec is None:
            return
        rec.local_refs -= 1
        self._maybe_free(oid, rec)

    def handle_add_borrow(self, conn, p):
        rec = self.owned.get(ObjectID(p["oid"]))
        if rec is not None:
            rec.borrowers += 1
        return True

    def handle_remove_borrow(self, conn, p):
        oid = ObjectID(p["oid"])
        rec = self.owned.get(oid)
        if rec is not None:
            rec.borrowers -= 1
            self._maybe_free(oid, rec)
        return True

    def _maybe_free(self, oid: ObjectID, rec: OwnedObject):
        if rec.local_refs <= 0 and rec.borrowers <= 0 and rec.state != "PENDING":
            self.owned.pop(oid, None)
            self.memory_store.delete(oid)
            if rec.in_shm:
                self._spawn_bg(self._free_remote(oid))
            self._maybe_release_lineage(oid)

    def _maybe_release_lineage(self, oid: ObjectID):
        """Drop a task's lineage once none of its returns are referenced
        (reference: ReferenceCounter-driven lineage release)."""
        if oid.is_put():
            return
        tid = oid.task_id()
        entry = self._lineage.get(tid.binary())
        if entry is None:
            return
        spec, _deps, cost = entry
        if any(ObjectID.for_return(tid, i) in self.owned for i in range(spec.num_returns)):
            return
        del self._lineage[tid.binary()]
        self._lineage_bytes -= cost

    async def _free_remote(self, oid: ObjectID):
        try:
            await self.controller.call("free_objects", {"oids": [oid.binary()]})
        except Exception:
            pass

    def _register_owned(self, oid: ObjectID, state="PENDING", **kw) -> OwnedObject:
        rec = self.owned.get(oid)
        if rec is None:
            rec = OwnedObject(state=state, ready_event=asyncio.Event(), **kw)
            self.owned[oid] = rec
        return rec

    def _fail_task_returns(self, spec: TaskSpec, err: BaseException):
        self._inflight_deps.pop(spec.task_id.binary(), None)
        # Terminal failure without a reply (infeasible demand, retries
        # exhausted, actor death, dep-resolution failure).
        self._task_event("task_failed", spec, error_type=_error_type(err))
        if spec.num_returns == -1:
            gen = self._streaming.pop(spec.task_id.binary(), None)
            if gen is not None:
                gen._finish(error=err)
            return
        for i in range(spec.num_returns):
            self._mark_ready(ObjectID.for_return(spec.task_id, i), size=0, in_memory=False, in_shm=False, error=err)

    def _mark_ready(self, oid: ObjectID, *, size: int, in_memory: bool, in_shm: bool, error: BaseException | None = None):
        rec = self._register_owned(oid)
        rec.state = "FAILED" if error is not None else "READY"
        rec.size = size
        rec.in_memory = in_memory
        rec.in_shm = in_shm
        rec.error = error
        if rec.ready_event:
            rec.ready_event.set()
        self._maybe_free(oid, rec)

    # -- put / get / wait ----------------------------------------------
    def put_sync(self, value: Any) -> ObjectRef:
        """Owner-side put without blocking on the IO loop: serialization and
        the store write happen on the caller's thread (both stores are
        thread-safe); ownership registration is queued to the loop FIFO, so it
        lands before any subsequent get/free touching the same object."""
        oid = ObjectID.from_put()
        parts, _refs, total = serialization.serialize_parts(value)
        in_shm = self.store is not None and total > self.config.max_inline_object_size
        evicted: list = []
        if in_shm:
            buf, evicted = self.store.create_autoevict(oid, total)
            off = 0
            for part in parts:  # scatter-write: no intermediate join copy
                n = len(part)
                buf[off : off + n] = part
                off += n
            del buf
            self.store.seal(oid)
        else:
            self.memory_store.put(oid, b"".join(parts))
        self._obj_bytes_written += total

        def _commit():
            rec = self._register_owned(oid)
            rec.local_refs += 1
            self._mark_ready(oid, size=total, in_memory=not in_shm, in_shm=in_shm)
            if in_shm:
                self._spawn_bg(self._report_shm_put(oid, total, evicted))

        self._post_to_loop(_commit)
        ref = ObjectRef(oid, self.address, total, _register=False)
        ref._registered = True
        return ref

    async def _report_shm_put(self, oid: ObjectID, size: int, evicted: list):
        if evicted:
            await self._report_evicted(evicted)
        try:
            if self.daemon is not None:
                await self.daemon.notify("report_sealed", {"oid": oid.binary(), "size": size})
            else:
                await self.controller.notify("report_object", {"oid": oid.binary(), "node_id": self.node_id, "size": size})
        except Exception:
            pass

    async def put_async(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_put()
        data, _refs = serialization.serialize(value)
        rec = self._register_owned(oid)
        # Pre-pin before marking ready, else _maybe_free could reap the object
        # in the window before the returned ObjectRef registers itself.
        rec.local_refs = 1
        if self.store is not None and len(data) > self.config.max_inline_object_size:
            await self._write_shm(oid, data)
            self._mark_ready(oid, size=len(data), in_memory=False, in_shm=True)
        else:
            self.memory_store.put(oid, data)
            self._obj_bytes_written += len(data)
            self._mark_ready(oid, size=len(data), in_memory=True, in_shm=False)
        ref = ObjectRef(oid, self.address, len(data), _register=False)
        ref._registered = True
        return ref

    async def _write_shm(self, oid: ObjectID, data: bytes):
        buf, evicted = self.store.create_autoevict(oid, len(data))
        buf[:] = data
        del buf
        self.store.seal(oid)
        self._obj_bytes_written += len(data)
        if evicted:
            await self._report_evicted(evicted)
        if self.daemon is not None:
            await self.daemon.notify("report_sealed", {"oid": oid.binary(), "size": len(data)})
        else:
            await self.controller.notify("report_object", {"oid": oid.binary(), "node_id": self.node_id, "size": len(data)})

    async def _report_evicted(self, evicted: list[ObjectID]):
        try:
            await self.controller.notify(
                "report_objects_evicted", {"oids": [o.binary() for o in evicted], "node_id": self.node_id}
            )
        except Exception:
            pass

    def get_sync(self, refs, timeout: float | None = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        refs = list(refs)
        # Fast path: values already resident on this host (owner memory store
        # or the local shm arena) deserialize on the caller's thread with no
        # IO-loop round trip — the common case for owner-side gets of finished
        # results (reference: CoreWorkerMemoryStore GetIfExists fast path).
        out: list = []
        for r in refs:
            v = self._try_local_value(r)
            if v is _MISS:
                break
            out.append(v)
        else:
            return out[0] if single else out
        # Keep already-deserialized prefix values; only the remainder goes
        # through the IO loop.
        out = out + self._run(self._get_many(refs[len(out):]), timeout=timeout)
        return out[0] if single else out

    def _try_local_value(self, ref: ObjectRef):
        """Return the deserialized value if locally resident, else _MISS.
        Thread-safe: MemoryStore and SharedMemoryClient both lock internally;
        `owned` is only read (GIL-atomic) to avoid error-state misreads."""
        oid = ref.id
        data = self.memory_store.get(oid)
        if data is None:
            if ref.owner_addr == self.address:
                # Owner-local: the record is authoritative. PENDING, FAILED,
                # or registration still queued on the IO loop (rec None —
                # submit_actor_task_sync registers via the posted-submission
                # lane, and the caller's get usually beats it) must NOT probe the
                # shm arena: a futile get_pinned + spill-restore stat per
                # call was the sync-call hot path's biggest syscall cost.
                rec = self.owned.get(oid)
                if rec is None or rec.state != "READY":
                    return _MISS  # the slow path waits/raises as appropriate
            if self.store is None:
                return _MISS
            data = self._read_shm(oid)
            if data is None:
                return _MISS
        self._obj_hits += 1
        self._obj_bytes_read += len(data)
        return self._deserialize_value(data)

    async def get_async(self, ref: ObjectRef):
        return (await self._get_many([ref]))[0]

    async def _get_many(self, refs: list[ObjectRef]):
        return await asyncio.gather(*(self._get_one(r) for r in refs))

    async def _get_one(self, ref: ObjectRef, _depth: int = 0):
        oid = ref.id
        # 1. in-process memory store
        data = self.memory_store.get(oid)
        if data is not None:
            self._obj_hits += 1
            self._obj_bytes_read += len(data)
            return self._deserialize_value(data)
        # 2. owned & pending -> wait for completion
        rec = self.owned.get(oid)
        if rec is not None and ref.owner_addr == self.address:
            if rec.state == "PENDING":
                await rec.ready_event.wait()
                rec = self.owned.get(oid) or rec
            if rec.state == "FAILED":
                err = rec.error if rec.error is not None else RemoteError("task failed")
                if isinstance(err, RemoteError) and err.cause is not None:
                    raise err.cause
                raise err
            data = self.memory_store.get(oid)
            if data is not None:
                self._obj_hits += 1
                self._obj_bytes_read += len(data)
                return self._deserialize_value(data)
        # 3. local shared memory
        data = self._read_shm(oid)
        if data is not None:
            self._obj_hits += 1
            self._obj_bytes_read += len(data)
            return self._deserialize_value(data)
        # 4. borrowed -> ask the owner (a local miss from here on)
        self._obj_misses += 1
        if ref.owner_addr and ref.owner_addr != self.address:
            try:
                conn = await self._peer_conn(ref.owner_addr)
                reply = await conn.call("get_owned", {"oid": oid.binary()})
            except (rpc.ConnectionLost, rpc.RpcError):
                reply = None
            if reply is not None:
                if "error" in reply:
                    raise reply["error"]
                if "inline" in reply:
                    return self._deserialize_value(reply["inline"])
                if reply.get("in_shm") and await self._pull_to_local(oid, reply.get("locations")):
                    data = self._read_shm(oid)
                    if data is not None:
                        return self._deserialize_value(data)
        # 5. directory fallback
        if self.store is not None and await self._pull_to_local(oid):
            data = self._read_shm(oid)
            if data is not None:
                return self._deserialize_value(data)
        # 6. every copy is gone: recover via lineage re-execution (owner-side;
        # borrowers ask the owner) — reference: ObjectRecoveryManager
        # (object_recovery_manager.h:41) + TaskManager resubmit (task_manager.h:184).
        if _depth < 3 and await self._try_recover(ref):
            return await self._get_one(ref, _depth + 1)
        raise ObjectLostError(f"object {oid.hex()} is unavailable (owner {ref.owner_addr} unreachable or value lost)")

    async def _ensure_dep_available(self, d) -> None:
        """Best-effort: make sure a dependency's payload exists somewhere in
        the cluster, recovering it via its owner if every copy is gone."""
        if not isinstance(d, ObjectRef):
            return
        oid = d.id
        if self.memory_store.contains(oid):
            return
        rec = self.owned.get(oid) if d.owner_addr == self.address else None
        if rec is not None and rec.in_memory:
            return
        if self.store is not None and self.store.contains_or_spilled(oid):
            return
        locs = await self.controller.call("lookup_object", {"oid": oid.binary()})
        if locs:
            return
        await self._try_recover(d)

    async def _try_recover(self, ref: ObjectRef) -> bool:
        if ref.owner_addr == self.address:
            return await self._recover_object(ref.id)
        if ref.owner_addr:
            try:
                conn = await self._peer_conn(ref.owner_addr)
                return bool(await conn.call("recover_object", {"oid": ref.id.binary()}))
            except Exception:
                return False
        return False

    async def handle_recover_object(self, conn, p):
        return await self._recover_object(ObjectID(p["oid"]))

    async def _recover_object(self, oid: ObjectID) -> bool:
        key = oid.binary()
        pending = self._recovering.get(key)
        if pending is not None:  # coalesce concurrent recoveries of one object
            return await asyncio.shield(pending)
        fut = asyncio.get_running_loop().create_future()
        self._recovering[key] = fut
        ok = False
        try:
            ok = await self._recover_impl(oid)
        except Exception as e:
            logger.warning("recovery of %s failed: %s", oid.hex()[:10], e)
        finally:
            # Resolve the future even on cancellation (e.g. a get() timeout
            # cancels this coroutine) or coalesced waiters hang forever.
            self._recovering.pop(key, None)
            if not fut.done():
                fut.set_result(ok)
        return ok

    async def _recover_impl(self, oid: ObjectID) -> bool:
        # Copy-hunting first: a surviving replica beats re-execution
        # (object_recovery_manager.h:62 pins other copies before lineage).
        if self.store is not None and await self._pull_to_local(oid) and self.store.contains_or_spilled(oid):
            return True
        if oid.is_put():
            return False  # ray.put objects have no producing task
        entry = self._lineage.get(oid.task_id().binary())
        if entry is None:
            return False
        spec, deps, _cost = entry
        retries = spec.options.max_retries
        if retries == -1:
            retries = self.config.max_task_retries_default
        attempts = getattr(spec, "_recoveries", 0)
        if attempts >= retries:  # max_retries=0 => never re-execute (non-idempotent task)
            return False
        spec._recoveries = attempts + 1  # type: ignore[attr-defined]
        # Flip every return of the task back to PENDING so getters re-block on
        # a fresh event while the task re-executes.
        for i in range(spec.num_returns):
            rec = self.owned.get(ObjectID.for_return(spec.task_id, i))
            if rec is not None:
                rec.state = "PENDING"
                rec.ready_event = asyncio.Event()
        logger.warning(
            "object %s lost; re-executing task %s from lineage (attempt %d)",
            oid.hex()[:10],
            spec.task_id.hex()[:8],
            attempts + 1,
        )
        self._event("object_recovery", oid=oid.hex(), task_id=spec.task_id.hex())
        # Reconstruct lost dependencies bottom-up BEFORE resubmitting: the
        # re-executed task would otherwise discover the loss mid-execution
        # while holding its resources — deadlock when the dep's re-execution
        # needs those same resources (the reference resolves/pulls args before
        # the lease grant for the same reason, dependency_resolver.h).
        for d in deps:
            try:
                await self._ensure_dep_available(d)
            except Exception:
                pass
        await self._submit(spec, list(deps))
        rec = self.owned.get(oid)
        if rec is None:
            return False
        await rec.ready_event.wait()
        return rec.state == "READY"

    def _read_shm(self, oid: ObjectID):
        """Read an object payload out of the shared-memory arena.

        Zero-copy: returns a PinnedBuffer whose eviction pin lives as long
        as any view deserialization derives from it (ndarrays reconstructed
        from pickle-5 out-of-band buffers wrap the arena pages directly; the
        pin drops when the last one is collected). Spilled objects come back
        as plain bytes off disk.
        """
        if self.store is None:
            return None
        buf = self.store.get_pinned(oid)
        if buf is None:  # spilled? restore (or read straight off disk if full)
            evicted: list = []
            restored = self.store.restore(oid, evicted_out=evicted)
            if evicted:
                try:
                    loop = asyncio.get_running_loop()
                except RuntimeError:
                    loop = None
                if loop is self.loop:
                    _spawn_bg_task(self._bg_tasks, self._report_evicted(evicted), loop=loop)
                elif self.loop is not None:
                    # Caller-thread path — including a DIFFERENT running
                    # loop (user code driving its own asyncio loop calls a
                    # sync get): the report must run on the worker IO loop,
                    # where the controller connection lives.
                    asyncio.run_coroutine_threadsafe(self._report_evicted(evicted), self.loop)
            if restored:
                buf = self.store.get_pinned(oid)
            else:
                return self.store.read_spilled(oid)
        return buf

    async def _pull_to_local(self, oid: ObjectID, locations: list | None = None) -> bool:
        if self.daemon is None:
            return False
        payload: dict = {"oid": oid.binary()}
        if locations:
            # Owner-supplied replica hints close the freshly-sealed race (the
            # directory may not have absorbed report_object yet) and save a
            # controller lookup.
            payload["locations"] = locations
        try:
            with _tracing.child_span("object.pull.wait", oid=oid.hex()[:16]):
                # Capture the trace ctx INSIDE the wait span so the daemon's
                # object.pull span nests under it rather than beside it.
                tc = _tracing.current_trace()
                if tc is not None:
                    payload["tc"] = tc
                reply = await self.daemon.call("pull_object", payload)
            return bool(reply.get("ok"))
        except Exception:
            return False

    def _deserialize_value(self, data):
        value = serialization.deserialize(data)
        if isinstance(value, RemoteError):
            raise value.cause if value.cause is not None else value
        return value

    async def handle_get_owned(self, conn, p):
        """Serve an owned object to a borrower (ownership protocol; the
        reference resolves via OwnershipObjectDirectory + plasma promotion)."""
        oid = ObjectID(p["oid"])
        rec = self.owned.get(oid)
        if rec is None:
            data = self.memory_store.get(oid)
            if data is not None:
                return await self._inline_or_promote(oid, data)
            return None
        if rec.state == "PENDING":
            await rec.ready_event.wait()
            rec = self.owned.get(oid) or rec
        if rec.state == "FAILED":
            return {"error": rec.error}
        data = self.memory_store.get(oid)
        if data is not None:
            return await self._inline_or_promote(oid, data)
        # locations: the freshly-sealed report_object may still be in flight
        # to the directory; hand the borrower this node directly.
        return {"in_shm": True, "locations": self._shm_locations()}

    def _shm_locations(self) -> list:
        return [{"node_id": self.node_id, "address": self.daemon_addr}] if self.daemon_addr else []

    async def _inline_or_promote(self, oid: ObjectID, data) -> dict:
        """Small memory-store objects ship inline in the reply; anything over
        a chunk promotes to the shm arena so the borrower takes the streaming
        pull path instead of receiving megabytes pickled inside one RPC."""
        if self.store is None or self.daemon is None or len(data) <= self.config.object_chunk_size:
            return {"inline": bytes(data)}
        rec = self.owned.get(oid)
        if rec is not None and rec.in_shm:
            # Already promoted by an earlier borrower: don't re-put (raises
            # ObjectExistsError) or re-announce the location per request.
            return {"in_shm": True, "locations": self._shm_locations()}
        if await self._promote_to_shm(oid, data):
            return {"in_shm": True, "locations": self._shm_locations()}
        return {"inline": bytes(data)}

    async def _promote_to_shm(self, oid: ObjectID, data) -> bool:
        announce = True
        try:
            evicted = self.store.put(oid, data)
        except ObjectExistsError:
            evicted = []  # already promoted (concurrent borrowers)
            announce = False
        except ObjectStoreFullError:
            return False  # arena can't take it: fall back to inline
        if evicted:
            await self._report_evicted(evicted)
        rec = self.owned.get(oid)
        if rec is not None:
            rec.in_shm = True
        if announce and self.daemon is not None:
            await self.daemon.notify("report_sealed", {"oid": oid.binary(), "size": len(data)})
        return True

    async def handle_wait_owned(self, conn, p):
        oid = ObjectID(p["oid"])
        rec = self.owned.get(oid)
        if rec is None:
            return self.memory_store.contains(oid) or (self.store is not None and self.store.contains_or_spilled(oid))
        if rec.state == "PENDING":
            try:
                await asyncio.wait_for(rec.ready_event.wait(), timeout=p.get("timeout", 30.0))
            except asyncio.TimeoutError:
                return False
        return True

    def wait_sync(self, refs: list[ObjectRef], num_returns: int, timeout: float | None):
        return self._run(self.wait_async(refs, num_returns, timeout))

    async def wait_async(self, refs: list[ObjectRef], num_returns: int, timeout: float | None):
        """Event-driven wait: owner-local refs block on their ready_event,
        borrowed refs park one wait_owned RPC on the owner (which blocks
        server-side on the same event) — no polling (the reference's Wait
        similarly registers memory-store futures, core_worker.h:697)."""
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")

        deadline = None if timeout is None else time.monotonic() + timeout

        async def wait_one(i: int, r: ObjectRef) -> int:
            if self.memory_store.contains(r.id):
                return i
            rec = self.owned.get(r.id)
            if rec is not None and r.owner_addr == self.address:
                if rec.state == "PENDING":
                    await rec.ready_event.wait()
                return i
            if self.store is not None and self.store.contains_or_spilled(r.id):
                return i
            if r.owner_addr and r.owner_addr != self.address:
                while True:
                    # Bound each server-side park: an abandoned client task
                    # (outer timeout) must not orphan an hour-long handler on
                    # the owner — re-arm at most every 60s.
                    remaining = 60.0 if deadline is None else max(0.05, min(60.0, deadline - time.monotonic()))
                    try:
                        conn = await self._peer_conn(r.owner_addr)
                        if await conn.call("wait_owned", {"oid": r.id.binary(), "timeout": remaining}):
                            return i
                        # Owner says unavailable (freed/lost) or parked past
                        # its window: back off; the outer deadline decides
                        # when to give up.
                        await asyncio.sleep(0.05)
                    except Exception:
                        await asyncio.sleep(self.config.rpc_retry_delay_s)
            # Unknown provenance: resolve via a full get (rare).
            await self._get_one(r)
            return i

        tasks = [asyncio.ensure_future(wait_one(i, r)) for i, r in enumerate(refs)]
        ready_idx: set[int] = set()
        pending = set(tasks)
        try:
            while pending and len(ready_idx) < num_returns:
                remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
                done, pending = await asyncio.wait(pending, timeout=remaining, return_when=asyncio.FIRST_COMPLETED)
                if not done:
                    break  # timed out
                for t in done:
                    if t.exception() is None:
                        ready_idx.add(t.result())
        finally:
            for t in pending:
                t.cancel()
            for t in tasks:
                if not t.done():
                    try:
                        await t
                    except (asyncio.CancelledError, Exception):
                        pass
                elif not t.cancelled():  # retrieve exceptions so GC doesn't log them
                    t.exception()
        ready = [refs[i] for i in sorted(ready_idx)][:num_returns]
        ready_ids = {r.id for r in ready}
        not_ready = [r for r in refs if r.id not in ready_ids]
        return ready, not_ready

    # -- function/class export -----------------------------------------
    def export_callable(self, ns: str, obj: Any) -> str:
        data = serialization.dumps_function(obj)
        key = hashlib.sha1(data + self.job_id.binary()).hexdigest()
        full = f"{ns}:{key}"
        if full not in self._exported:
            self._run(self.controller.call("kv_put", {"ns": "exports", "key": full, "value": data, "overwrite": False}))
            self._exported.add(full)
        return full

    async def _load_callable(self, key: str):
        if key in self._fn_cache:
            return self._fn_cache[key]
        data = await self.controller.call("kv_get", {"ns": "exports", "key": key})
        if data is None:
            raise RuntimeError(f"exported callable {key} not found")
        obj = serialization.loads_function(data)
        self._fn_cache[key] = obj
        return obj

    # -- task submission ------------------------------------------------
    def submit_task_sync(self, fn_id: str, args: tuple, kwargs: dict, opts: TaskOptions):
        task_id = TaskID.from_random()
        streaming = opts.num_returns == "streaming"
        n_returns = -1 if streaming else opts.num_returns
        return_refs = [] if streaming else [
            ObjectRef(ObjectID.for_return(task_id, i), self.address, _register=False) for i in range(n_returns)
        ]
        args_blob, dep_refs = serialization.serialize_args(args, kwargs)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            fn_id=fn_id,
            args_blob=args_blob,
            num_returns=n_returns,
            options=opts,
            caller_addr=self.address,
            trace_ctx=_tracing.current_trace(),  # None unless a span is active
            qos_ctx=_qos.current_wire(),  # None unless a request context is active
        )
        gen = ObjectRefGenerator(task_id, self.address) if streaming else None
        if gen is not None:
            gen._cancel = functools.partial(self.cancel_stream, task_id.binary())

        # One loop hop, no blocking: registration + submission run as a single
        # FIFO callback, so they land before any subsequent get/free from this
        # thread. Ownership records exist before the task can complete, else a
        # fast reply could free the returns before the refs pin them.
        def _go():
            if gen is not None:
                self._streaming[task_id.binary()] = gen
            self._register_returns(return_refs)
            if dep_refs:
                # FSM: the attempt exists but its args aren't resolved yet;
                # _enqueue_submit advances it to PENDING_NODE_ASSIGNMENT.
                self._task_event("task_pending_args", spec)
                self._spawn_bg(self._submit(spec, dep_refs))
            else:
                self._enqueue_submit(spec)

        self._post_to_loop(_go)
        for r in return_refs:
            r._registered = True
        return gen if streaming else return_refs

    def _register_returns(self, refs):
        for r in refs:
            rec = self._register_owned(r.id)
            rec.local_refs += 1

    async def _submit(self, spec: TaskSpec, dep_refs: list[ObjectRef]):
        self._inflight_deps[spec.task_id.binary()] = dep_refs
        # Resolve dependencies BEFORE leasing (dependency_resolver.h) so a
        # queued task never holds a worker while waiting on its args.
        await self._wait_deps(dep_refs)
        self._enqueue_submit(spec)

    def _enqueue_submit(self, spec: TaskSpec):
        """Hand the (dep-free) spec to its scheduling-key submitter. Plain
        function so the no-deps fast path skips a per-call coroutine+task."""
        fault = _chaos.maybe_inject("worker.task.submit", fn=_spec_fn_name(spec))
        if fault is not None and fault.kind == "error":
            # Submission-time failure: the task's returns fail cleanly and
            # its FSM record closes terminal (never enters a queue).
            self._fail_task_returns(spec, fault.error(f"submit {_spec_fn_name(spec)}"))
            return
        key = scheduling_key(spec.fn_id, spec.options)
        sub = self._submitters.get(key)
        if sub is None:
            sub = self._submitters[key] = _KeySubmitter(self, key, spec.options)
        fut = self.loop.create_future()
        fut.add_done_callback(lambda f: f.exception())  # results absorbed via _absorb_task_reply
        sub.queue.append((spec, fut))
        tc = spec.trace_ctx
        if tc is None:
            self._task_event("task_submitted", spec)
        else:
            # span_id rides along for export_timeline's flow arrows.
            self._task_event("task_submitted", spec, span_id=tc[1])
        sub.pump()

    async def _wait_deps(self, dep_refs: list[ObjectRef]):
        for r in dep_refs:
            rec = self.owned.get(r.id)
            if rec is not None and r.owner_addr == self.address:
                if rec.state == "PENDING":
                    await rec.ready_event.wait()
            elif r.owner_addr and r.owner_addr != self.address:
                try:
                    conn = await self._peer_conn(r.owner_addr)
                    await conn.call("wait_owned", {"oid": r.id.binary(), "timeout": 600.0})
                except Exception:
                    pass

    def _add_lineage(self, spec: TaskSpec, deps: list):
        key = spec.task_id.binary()
        if key in self._lineage:
            return
        cost = len(spec.args_blob) + 256
        self._lineage[key] = (spec, deps, cost)
        self._lineage_bytes += cost
        while self._lineage_bytes > self.config.lineage_max_bytes and self._lineage:
            k = next(iter(self._lineage))
            _, _, c = self._lineage.pop(k)
            self._lineage_bytes -= c

    def _absorb_task_reply(self, spec: TaskSpec, reply: dict, fut: asyncio.Future | None = None):
        """Record task return values from a push_task reply."""
        deps = self._inflight_deps.pop(spec.task_id.binary(), None)
        # Untraced actor SUCCESSES stay event-free: the actor call path is
        # the RPC hot row, and one task_finished per ping would both cost
        # per-call CPU and flood the controller's task index with
        # FINISHED-only records that evict real task state. Failures and
        # traced calls always report.
        if spec.actor_id is None or spec.trace_ctx is not None or reply.get("status") == "error":
            extra = {}
            if reply.get("status") == "error" and reply.get("error") is not None:
                extra["error_type"] = _error_type(reply["error"])
            self._task_event("task_finished", spec, status=reply.get("status"), **extra)
        if spec.num_returns == -1:  # streaming: items arrived via notifies
            self._stream_conns.pop(spec.task_id.binary(), None)
            gen = self._streaming.pop(spec.task_id.binary(), None)
            if gen is not None:
                if reply.get("status") == "error":
                    gen._finish(error=reply.get("error") or RemoteError("task failed"))
                else:
                    gen._finish(total=reply.get("streaming_done", 0))
            if fut is not None and not fut.done():
                fut.set_result(reply.get("status") != "error")
            return
        if reply.get("status") == "error":
            err: BaseException = reply.get("error") or RemoteError("task failed")
            for i in range(spec.num_returns):
                oid = ObjectID.for_return(spec.task_id, i)
                self._mark_ready(oid, size=0, in_memory=False, in_shm=False, error=err)
            if fut is not None and not fut.done():
                fut.set_result(False)
            return
        returns = reply.get("returns", [])
        # Shm returns can be lost (eviction, node death): retain the spec for
        # lineage re-execution. Inline returns live in the owner's memory
        # store and die with the owner, which lineage cannot help anyway.
        if any(item.get("inline") is None for item in returns) and spec.actor_id is None:
            self._add_lineage(spec, deps or [])
        for i, item in enumerate(returns):
            self._absorb_return_item(ObjectID.for_return(spec.task_id, i), item)
        if fut is not None and not fut.done():
            fut.set_result(True)

    def handle_generator_items(self, conn, p):
        """Caller side: one BATCH of streamed items from an executing
        generator task (reference: ReportGeneratorItemReturns, coalesced).
        Absorbs N items in one pass — N return objects registered, N refs
        pushed to the consumer under one lock acquisition — so a deep batch
        frame costs one dispatch, not N."""
        gen = self._streaming.get(p["task_id"])
        if gen is None:
            return  # stale task (consumer already gone)
        items = p["items"]
        _STREAM_BATCH_HIST[len(items)] += 1
        if p.get("want_ack") and getattr(gen, "_ack_conn", None) is not conn:
            # Install once per (stream, conn) — never per item. Refreshed
            # only when the conn actually changes: a connection-loss retry
            # replays the stream on a NEW conn, and acks pinned to the dead
            # one would stall a backpressured producer forever.
            self._install_stream_ack(gen, conn, p["task_id"])
        tid = TaskID(p["task_id"])
        pushes = []
        for index, item in items:
            if not gen.reserve(index):
                continue  # duplicate index from a retry replay
            oid = ObjectID.for_return(tid, index)
            rec = self._register_owned(oid)
            rec.local_refs += 1
            self._absorb_return_item(oid, item)
            ref = ObjectRef(oid, self.address, _register=False)
            ref._registered = True
            pushes.append((index, ref))
        if pushes:
            gen._push_many(pushes)

    def _install_stream_ack(self, gen, conn, tb: bytes):
        """Consumption-ack hook, coalescing: consumer-thread acks record the
        latest consumed count and arm ONE loop callback per burst, so N
        items consumed back-to-back cost one self-pipe wakeup and one
        enqueue-only generator_ack covering the whole batch (batch-granular
        acks — the producer's backpressure window advances in batches)."""
        loop = self.loop
        state = {"armed": False, "value": 0}

        def send(conn=conn, tb=tb, state=state):
            # Disarm BEFORE reading the value: a consumption that saw
            # armed=True happened before the disarm, so its count is
            # visible to this read; one that misses the window re-arms.
            state["armed"] = False
            consumed = state["value"]
            if not conn.closed:
                try:
                    conn.notify_soon(
                        "generator_ack", {"task_id": tb, "consumed": consumed}
                    )
                except rpc.ConnectionLost:
                    pass

        def ack(consumed: int, state=state):
            state["value"] = consumed
            if state["armed"]:
                return
            state["armed"] = True
            try:
                loop.call_soon_threadsafe(send)
            except RuntimeError:
                state["armed"] = False

        gen._ack = ack
        gen._ack_conn = conn

    # -- task execution (executor side) --------------------------------
    async def handle_push_tasks(self, conn, p):
        """Execute a batch of pushed tasks sequentially (batched PushTask:
        amortizes per-frame overhead when the submitter's queue is deep;
        execution order and one-at-a-time semantics are unchanged)."""
        return {"results": [await self.handle_push_task(conn, s) for s in p["specs"]]}

    def _decode_pushed(self, conn, p) -> TaskSpec:
        """Wire -> TaskSpec: full spec (interning its constants under the
        caller's small int) or a lean tuple referencing interned constants."""
        spec = p.get("spec")
        if spec is not None:
            oid = p.get("oid")
            if oid is not None:
                conn.meta.setdefault("opts_in", {})[oid] = (
                    spec.options, spec.job_id, spec.caller_addr, spec.fn_id
                )
            return spec
        tid, args_blob, num_returns, oid, attempt = p["lean"]
        options, job_id, caller_addr, fn_id = conn.meta["opts_in"][oid]
        spec = TaskSpec(
            task_id=TaskID(tid), job_id=job_id, fn_id=fn_id, args_blob=args_blob,
            num_returns=num_returns, options=options, caller_addr=caller_addr,
            trace_ctx=p.get("tc"), qos_ctx=p.get("qc"),
        )
        if attempt:
            spec._attempts = attempt  # type: ignore[attr-defined] - retried attempt: exec events key the same index record
        return spec

    async def handle_push_task(self, conn, p):
        """Execute a pushed task (reference: CoreWorkerService.PushTask ->
        TaskReceiver -> scheduling queue -> execute callback)."""
        spec = self._decode_pushed(conn, p)
        streaming = spec.num_returns == -1
        if streaming:
            self._stream_register(spec.task_id.binary())
        try:
            fn = await self._load_callable(spec.fn_id)
            loop = asyncio.get_running_loop()
            tc = spec.trace_ctx
            if tc is None:
                self._task_event("task_exec_start", spec, node=self.node_id)
            else:
                # The execution span: child of the submitter's span; user code
                # inside the task sees (trace_id, exec_span) as its context.
                spec._exec_ctx = (tc[0], _tracing.new_span_id())  # type: ignore[attr-defined]
                self._task_event("task_exec_start", spec, node=self.node_id,
                                 span_id=spec._exec_ctx[1])
            t0 = time.monotonic()
            try:
                # QoS hop "worker": an already-expired request is dropped
                # HERE, before user code — the typed error reply rides the
                # normal error path back to the caller (counted, traced).
                _qos.check_deadline("worker", _qos.from_wire(spec.qos_ctx),
                                    detail=_spec_fn_name(spec))
                fault = _chaos.maybe_inject("worker.exec", fn=_spec_fn_name(spec))
                if fault is not None:
                    if fault.kind == "kill":
                        # Hard worker death mid-task (the SIGKILL shape): no
                        # reply ever leaves this process; the caller's retry
                        # path resubmits on a fresh worker.
                        logger.warning("chaos: worker.exec kill (task %s)", spec.task_id.hex()[:8])
                        # Last-gasp black box: the ring currently holds this
                        # task's exec_start and everything before it. Written
                        # synchronously BEFORE os._exit (no atexit, no flush
                        # window); the node daemon harvests the file alongside
                        # the worker log when it reports the death.
                        _flight.dump("worker.death",
                                     reason=f"chaos worker.exec kill "
                                            f"(task {spec.task_id.hex()[:8]})")
                        os._exit(1)
                    if fault.kind == "delay":
                        await asyncio.sleep(fault.delay_s)  # slow-executor stall
                    elif fault.kind == "error":
                        raise fault.error(f"task {_spec_fn_name(spec)}")
                if streaming:
                    n = await self._execute_streaming_task(conn, fn, spec, loop)
                    return {"status": "ok", "streaming_done": n}
                result = await loop.run_in_executor(self._executor, self._execute_task, fn, spec)
                returns = await self._package_returns(spec, result)
                return {"status": "ok", "returns": returns}
            except BaseException as e:  # noqa: BLE001 - errors propagate to caller
                return {"status": "error", "error": serialization.RemoteError.from_exception(e, where=f"task {spec.fn_id[:24]}")}
            finally:
                _task_latency_task.observe(time.monotonic() - t0)
                if tc is None:
                    self._task_event("task_exec_end", spec, node=self.node_id)
                else:
                    # Carry the trace id so the controller's trace index sees
                    # the execution END too (duration, not just the start).
                    self._task_event("task_exec_end", spec, node=self.node_id,
                                     span_id=spec._exec_ctx[1])
        finally:
            if streaming:
                self._stream_cleanup(spec.task_id.binary())

    async def _execute_streaming_task(self, conn, fn, spec: TaskSpec, loop) -> int:
        """Run a generator task, shipping its yields through the per-stream
        batch lane: the producing thread appends into a bounded buffer (no
        cross-thread round trip per item — the old path paid a full
        run_coroutine_threadsafe().result() per yielded token) and the
        shipper's loop-side pump coalesces adjacent items into one
        generator_items frame. Producer blocking semantics are preserved:
        full buffer (transport backpressure) and, when
        TaskOptions.generator_backpressure is set, the consumer's acked
        consumption bound (reference: _generator_backpressure_num_objects,
        default unbounded)."""
        shipper = _StreamShipper(self, conn, spec, loop)
        self._stream_shippers[spec.task_id.binary()] = shipper

        def run():
            # Context active for the generator BODY too (it runs during the
            # next() calls below, not inside _execute_task's window).
            token = _tracing.activate(getattr(spec, "_exec_ctx", None))
            qtoken = _qos.activate(spec.qos_ctx)
            try:
                out = self._execute_task(fn, spec)
                if not inspect.isgenerator(out):
                    raise TypeError(
                        f"task {spec.fn_id[:24]} declared num_returns='streaming' "
                        f"but returned {type(out).__name__}, not a generator"
                    )
                count = 0
                for value in out:
                    try:
                        shipper.put(count, value)
                    except _StreamClosed:
                        out.close()
                        break
                    count += 1
                shipper.finish()
                return count
            finally:
                _qos.deactivate(qtoken)
                _tracing.deactivate(token)

        # Stream state registered/cleaned by handle_push_task's try/finally.
        return await loop.run_in_executor(self._executor, run)

    def _stream_register(self, tid: bytes):
        """Mark a streaming task live. MUST run synchronously in the push
        handler, before its first await: frames are dispatched in wire order,
        so registering before the handler first yields guarantees a racing
        generator_close (sent after the submit) observes the stream as live."""
        self._live_streams.add(tid)

    def _stream_cleanup(self, tid: bytes):
        """Single place per-stream executor state dies (idempotent)."""
        self._live_streams.discard(tid)
        sh = self._stream_shippers.pop(tid, None)
        if sh is not None:
            # Fold the shipper's early-close discard tally into the process
            # counter before its state dies (stream.items_dropped metric).
            self._stream_items_dropped += sh.items_dropped
        self._cancelled_streams.discard(tid)

    def handle_generator_ack(self, conn, p):
        """Executor side: consumer progress for a backpressured stream —
        one ack can cover a whole consumed batch (the owner coalesces)."""
        sh = self._stream_shippers.get(p["task_id"])
        if sh is not None:
            sh.on_ack(p["consumed"])

    def handle_generator_close(self, conn, p):
        """Executor side: the consumer abandoned this stream. Mark it and
        wake any blocked producer (buffer-full or backpressure wait) so it
        observes the close at its next yield. Only streams still executing
        are marked — a close that races the stream's own completion (its
        finally already discarded the entry) must not re-add the id, or
        long-lived workers leak set entries."""
        tid = p["task_id"]
        if tid not in self._live_streams:
            return
        self._cancelled_streams.add(tid)
        sh = self._stream_shippers.get(tid)
        if sh is not None:
            sh.close_consumer()

    def cancel_stream(self, task_id_bytes: bytes):
        """Caller side: best-effort early termination of a streaming task the
        moment the consumer stops iterating (reference: CancelTask RPC for
        streaming generators). Thread-safe; no-op once the stream finished."""

        def go():
            conn = self._stream_conns.get(task_id_bytes)
            if conn is not None and not conn.closed:
                self._spawn_bg(
                    conn.notify("generator_close", {"task_id": task_id_bytes})
                )

        self.loop.call_soon_threadsafe(go)

    def _execute_task(self, fn, spec: TaskSpec):
        args, kwargs = serialization.deserialize(spec.args_blob)
        args = [self.get_sync(a) if isinstance(a, ObjectRef) else a for a in args]
        kwargs = {k: (self.get_sync(v) if isinstance(v, ObjectRef) else v) for k, v in kwargs.items()}
        self._current_task = spec
        # Executor threads don't inherit the IO loop's contextvars: install
        # the task's execution span (if traced) and QoS context so user-code
        # spans, nested submissions, and deadline checks chain onto them.
        token = _tracing.activate(getattr(spec, "_exec_ctx", None))
        qtoken = _qos.activate(spec.qos_ctx)
        # Tripwire: user code entering with a LONG-expired deadline means a
        # gate was bypassed (qos.exec.expired_total; grace for jitter).
        _qos.mark_exec_start("worker")
        try:
            return fn(*args, **kwargs)
        finally:
            _qos.deactivate(qtoken)
            _tracing.deactivate(token)
            self._current_task = None

    async def _package_value(self, oid: ObjectID, value) -> dict:
        """Serialize one return/stream item: small -> inline bytes in the
        reply frame; large -> local shm under ``oid`` (size in the frame).
        Single source of the inline-vs-shm split for both the plain-return
        and streaming paths."""
        data, _ = serialization.serialize(value)
        if len(data) <= self.config.max_inline_object_size or self.store is None:
            return {"inline": data}
        await self._write_shm(oid, data)
        return {"size": len(data)}

    def _absorb_return_item(self, oid: ObjectID, item: dict):
        """Caller-side mirror of _package_value: register one arrived
        return/stream item under this owner."""
        if item.get("inline") is not None:
            self.memory_store.put(oid, item["inline"])
            self._mark_ready(oid, size=len(item["inline"]), in_memory=True, in_shm=False)
        else:
            self._mark_ready(oid, size=item.get("size", 0), in_memory=False, in_shm=True)

    async def _package_returns(self, spec: TaskSpec, result) -> list[dict]:
        values = (result,) if spec.num_returns == 1 else tuple(result) if spec.num_returns > 1 else ()
        if spec.num_returns > 1 and len(values) != spec.num_returns:
            raise ValueError(f"task declared num_returns={spec.num_returns} but returned {len(values)}")
        return [
            await self._package_value(ObjectID.for_return(spec.task_id, i), v)
            for i, v in enumerate(values)
        ]

    # -- actors: caller side -------------------------------------------
    def create_actor_sync(self, cls_id: str, init_args_blob: bytes, opts, name: str = "", namespace: str = "default") -> ActorID:
        actor_id = ActorID.from_random()
        spec = ActorSpec(
            actor_id=actor_id,
            job_id=self.job_id,
            cls_id=cls_id,
            init_args_blob=init_args_blob,
            options=opts,
            name=name,
            namespace=namespace,
            owner_addr=self.address,
        )
        info = self._run(self.controller.call("register_actor", {"spec": spec}))
        if info["state"] == "DEAD":
            raise ActorDiedError(f"actor failed to start: {info.get('death_cause')}")
        actor_id = ActorID(info["actor_id"])  # may differ under get_if_exists
        # Creation is async; worker_addr may still be empty. The first task
        # push resolves it via wait_actor_alive.
        self._actor_conns[actor_id] = {"addr": info["worker_addr"], "conn": None}
        return actor_id

    def submit_actor_task_sync(self, actor_id: ActorID, method: str, args, kwargs, num_returns, opts,
                               concurrency_group: str = ""):
        task_id = TaskID.from_random()
        streaming = num_returns == "streaming"
        n_returns = -1 if streaming else num_returns
        args_blob, dep_refs = serialization.serialize_args(args, kwargs)
        tc = _tracing.current_trace()
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            fn_id="",
            args_blob=args_blob,
            num_returns=n_returns,
            options=opts,
            caller_addr=self.address,
            actor_id=actor_id,
            method_name=method,
            concurrency_group=concurrency_group,
            trace_ctx=tc,
            qos_ctx=_qos.current_wire(),
        )
        refs = [] if streaming else [
            ObjectRef(ObjectID.for_return(task_id, i), self.address, _register=False) for i in range(n_returns)
        ]
        gen = ObjectRefGenerator(task_id, self.address) if streaming else None
        if gen is not None:
            gen._cancel = functools.partial(self.cancel_stream, task_id.binary())

        def _go():
            if gen is not None:
                self._streaming[task_id.binary()] = gen
            self._register_returns(refs)
            if tc is not None:
                # Submission event ONLY when traced: actor calls are the hot
                # path and normally emit no events at all (export_timeline's
                # flow arrows need the submit side of the hop).
                self._task_event("task_submitted", spec, span_id=tc[1])
            self._submit_actor_task(spec, dep_refs)

        self._post_to_loop(_go)
        for r in refs:
            r._registered = True
        return gen if streaming else refs

    def _submit_actor_task(self, spec: TaskSpec, dep_refs):
        # Per-actor FIFO pump: submission order must equal wire order (actor
        # tasks execute in arrival order on the executor). A create_task per
        # spec would let conn-setup/dep awaits interleave and reorder sends;
        # a plain enqueue also keeps the per-call hot path task-free.
        q = self._actor_send_queues.get(spec.actor_id)
        if q is None:
            q = self._actor_send_queues[spec.actor_id] = asyncio.Queue()
            self._spawn_bg(self._actor_send_pump(spec.actor_id, q))
        q.put_nowait((spec, dep_refs))

    async def _actor_send_pump(self, actor_id: ActorID, q: "asyncio.Queue"):
        while True:
            batch = [await q.get()]
            # Batch-drain: everything already queued ships back-to-back with
            # one transport flush at the end (amortizes the drain under async
            # call storms; pump order still == wire order, and every call
            # keeps its own reply future).
            while len(batch) < 64 and not q.empty():
                batch.append(q.get_nowait())
            # Failure ownership: _push_actor_batch_ordered fails ITS specs'
            # returns itself (raising only ActorDiedError, for retirement);
            # the pump fails exactly the items it has not yet handed over —
            # never work already flushed to the actor, whose reply futures
            # own the outcome.
            pending = collections.deque(batch)
            specs: list[TaskSpec] = []
            died: ActorDiedError | None = None
            try:
                while pending:
                    spec, dep_refs = pending[0]
                    if dep_refs:
                        # Ship everything accumulated BEFORE awaiting this
                        # task's deps: a dep may be produced by an earlier
                        # batchmate (a.m2.remote(a.m1.remote()) lands both in
                        # one drain) — holding m1 unsent while waiting on its
                        # result would deadlock the pump.
                        if specs:
                            to_push, specs = specs, []
                            await self._push_actor_batch_ordered(to_push)
                        self._inflight_deps[spec.task_id.binary()] = dep_refs
                        try:
                            await self._wait_deps(dep_refs)
                        except Exception as e:
                            pending.popleft()
                            self._fail_task_returns(
                                spec,
                                RemoteError(f"task {spec.method_name} dependency resolution failed: {e}"),
                            )
                            continue
                    pending.popleft()
                    specs.append(spec)
                if specs:
                    to_push, specs = specs, []
                    await self._push_actor_batch_ordered(to_push)
            except ActorDiedError as e:
                died = e
            except Exception as e:
                # Safety net: an unexpected error must not kill the pump task
                # while its queue stays registered (later submissions would
                # enqueue into a dead pump and hang forever). Fail the
                # un-pushed work; the pump lives on for the next drain.
                logger.exception("actor send pump error (actor=%s)", actor_id.hex()[:8])
                for spec, _ in pending:
                    self._fail_task_returns(
                        spec,
                        ActorDiedError(
                            f"actor {actor_id.hex()[:8]} task {spec.method_name} failed to submit: {e}"
                        ),
                    )
            if died is not None:
                for spec, _ in pending:  # drained but never handed to a push
                    self._fail_task_returns(spec, died)
                # Actor is gone: fail everything still queued and retire the
                # pump (a later submission spawns a fresh one, which handles
                # the restarted-actor case via address refresh).
                while not q.empty():
                    pending_spec, _ = q.get_nowait()
                    self._fail_task_returns(pending_spec, died)
                if self._actor_send_queues.get(actor_id) is q:
                    del self._actor_send_queues[actor_id]
                return

    async def _push_actor_batch_ordered(self, specs: list[TaskSpec], retried: bool = False):
        """Issue one message per task in pump order, then ONE transport flush
        for the whole drain. The messages are enqueued synchronously (no
        await between call_starts), so the rpc layer coalesces the entire
        drain into a single envelope: one pickle, one MAC, one write, one
        executor wakeup per batch — while each task keeps its own reply
        future, so a fast call's result is never held behind a slow
        batchmate's (replies coalesce symmetrically on the way back).

        Failure ownership: every spec handed to this method gets an outcome
        here — a reply-awaiting task, a retry, or failed returns. Only
        ActorDiedError escapes (so the pump can retire).

        Ordering contract: wire order == pump order == submission order; the
        executor runs tasks in arrival order, so no sequence numbers are
        needed (the reference's ActorTaskSubmitter/ActorSchedulingQueue pair
        achieves the same with explicit seq_nos over unordered gRPC).
        """
        actor_id = specs[0].actor_id
        entry = self._actor_conns.get(actor_id)
        if entry is None:
            entry = self._actor_conns[actor_id] = {"addr": "", "conn": None}
        sent: list[tuple[TaskSpec, asyncio.Future]] = []
        try:
            await self._actor_conn_fresh(specs[0], entry)
            interned = entry["conn"].meta.setdefault("opts_out", {})
            for spec in specs:
                if spec.num_returns == -1:
                    self._stream_conns[spec.task_id.binary()] = entry["conn"]
                # Lean framing: ship the per-handle constants (options, ids,
                # caller) once per conn, then small tuples — a full TaskSpec
                # costs ~15x a tuple to (un)pickle, the dominant per-call
                # cost for tiny actor calls (reference keeps specs on the
                # wire but pickles them in C++).
                key = (id(spec.options), spec.actor_id)
                ent = interned.get(key)
                if ent is None:
                    if len(interned) >= 512:
                        # Unbounded distinct options (per-call .options()
                        # clones): stop interning, ship full specs.
                        sent.append((spec, entry["conn"].call_start("push_actor_task", {"spec": spec})))
                        continue
                    oid_small = len(interned)
                    interned[key] = (spec.options, oid_small)  # pin: id() stays valid
                    payload = {"spec": spec, "oid": oid_small}
                else:
                    payload = {"lean": (
                        spec.task_id.binary(), spec.method_name, spec.args_blob,
                        spec.num_returns, spec.concurrency_group, ent[1],
                    )}
                    if spec.trace_ctx is not None:
                        payload["tc"] = spec.trace_ctx
                    if spec.qos_ctx is not None:
                        payload["qc"] = spec.qos_ctx
                sent.append((spec, entry["conn"].call_start("push_actor_task", payload)))
            # Backpressure: bound the transport buffer before the next drain.
            await entry["conn"].flush()
        except ActorDiedError as e:
            for spec in specs:
                self._fail_task_returns(spec, e)
            raise
        except (rpc.ConnectionLost, rpc.RpcError, OSError) as e:
            # OSError covers raw transport errors (ConnectionResetError from
            # writer.drain()) that the rpc layer does not wrap.
            entry["conn"] = None
            entry["addr"] = ""
            for fut in [f for _, f in sent]:
                fut.cancel()
            if not sent and not retried:
                # Nothing reached the wire (stale address / dial failure):
                # unambiguously safe to retry the whole batch once through
                # the redial path (which refreshes restarted-actor addresses;
                # _refresh_actor_addr raises ActorDiedError for dead ones).
                await self._push_actor_batch_ordered(specs, retried=True)
                return
            # Frames may have been DELIVERED and executed before the drop
            # (TCP delivery is independent of the local error): resending
            # would double-execute non-idempotent methods. Per-task policy,
            # same as a reply lost mid-flight: retry only with the user's
            # opt-in (max_task_retries > 0), else at-most-once wins.
            for spec in specs:
                if getattr(spec.options, "max_task_retries", 0) > 0:
                    try:
                        await self._push_actor_task(spec, attempt=1)
                    except ActorDiedError as e2:
                        self._fail_task_returns(spec, e2)
                else:
                    self._fail_task_returns(
                        spec,
                        ActorDiedError(
                            f"actor {spec.actor_id.hex()[:8]} task {spec.method_name} lost in flight: {e}"
                        ),
                    )
            return
        except Exception as e:
            # Uphold the ownership contract for errors outside the expected
            # set too (every spec handed here gets an outcome): otherwise the
            # callers' reply futures never resolve. Drop the conn as well —
            # it may hold partially-buffered frames for specs whose callers
            # were just told they failed; reusing it would flush those frames
            # and double-execute them.
            logger.exception("actor batch push failed (actor=%s)", actor_id.hex()[:8])
            conn = entry.get("conn")
            entry["conn"] = None
            entry["addr"] = ""
            if conn is not None:
                try:
                    await conn.close()
                except Exception:
                    pass
            for fut in [f for _, f in sent]:
                fut.cancel()
            for spec in specs:
                self._fail_task_returns(
                    spec,
                    ActorDiedError(
                        f"actor {actor_id.hex()[:8]} task {spec.method_name} failed to submit: {e}"
                    ),
                )
            return
        for spec, fut in sent:
            fut.add_done_callback(
                functools.partial(self._on_actor_reply, spec, entry=entry)
            )

    def _on_actor_reply(self, spec: TaskSpec, fut, entry):
        """Reply-future done callback (hot path: NO task per call — absorb
        runs synchronously in the callback; only the exceptional paths spawn
        a coroutine)."""
        exc = fut.cancelled() or fut.exception()
        if not exc:
            self._absorb_task_reply(spec, fut.result())
            return
        self._spawn_bg(self._actor_reply_failed(spec, fut, entry))

    async def _actor_reply_failed(self, spec: TaskSpec, fut, entry):
        try:
            await fut
        except ActorDiedError as e:
            self._fail_task_returns(spec, e)
        except (rpc.ConnectionLost, rpc.RpcError, OSError) as e:
            # Connection dropped mid-flight: the task may or may not have
            # executed. Resend ONLY if the user opted into retries
            # (max_task_retries > 0) — otherwise at-most-once wins.
            entry["conn"] = None
            entry["addr"] = ""
            if getattr(spec.options, "max_task_retries", 0) > 0:
                await self._push_actor_task(spec, attempt=1)
            else:
                self._fail_task_returns(
                    spec,
                    ActorDiedError(
                        f"actor {spec.actor_id.hex()[:8]} task {spec.method_name} lost in flight: {e}"
                    ),
                )

    async def _actor_conn_fresh(self, spec: TaskSpec, entry: dict) -> None:
        """Ensure entry has a LIVE connection to the actor's current worker.

        Evidence-based stale-address handling: refresh from the controller
        and DIAL the address it reports. Only when that dial fails (the
        worker is really gone) poll for the record to move — RESTARTING
        blocks inside wait_actor_alive, a restarted incarnation gets a NEW
        worker address, DEAD raises ActorDiedError. A transient connection
        reset to a healthy actor therefore redials the same address and
        proceeds immediately (no false death)."""
        if entry["conn"] is not None and not entry["conn"].closed:
            return
        if not entry["addr"]:
            await self._refresh_actor_addr(spec.actor_id, entry)
        try:
            entry["conn"] = await self._peer_conn(entry["addr"])
            return
        except (rpc.ConnectionLost, OSError):
            dead = entry["addr"]
        deadline = time.monotonic() + self.config.actor_creation_timeout_s
        while entry["addr"] == dead:
            if time.monotonic() > deadline:
                raise ActorDiedError(
                    f"actor {spec.actor_id.hex()[:8]} never left dead address {dead}"
                )
            await asyncio.sleep(self.config.task_retry_delay_s)
            await self._refresh_actor_addr(spec.actor_id, entry)
        entry["conn"] = await self._peer_conn(entry["addr"])

    async def _push_actor_task(self, spec: TaskSpec, attempt: int = 0):
        entry = self._actor_conns.get(spec.actor_id)
        if entry is None:
            entry = self._actor_conns[spec.actor_id] = {"addr": "", "conn": None}
        try:
            await self._actor_conn_fresh(spec, entry)
            reply = await entry["conn"].call("push_actor_task", {"spec": spec})
            self._absorb_task_reply(spec, reply)
        except ActorDiedError as e:
            self._fail_task_returns(spec, e)
        except (rpc.ConnectionLost, rpc.RpcError, KeyError, OSError) as e:
            # OSError covers raw transport failures (ConnectionReset/BrokenPipe
            # out of writer.drain) — anything escaping here would kill the
            # retry task and leave the caller's ref unresolved forever.
            entry["conn"] = None
            entry["addr"] = ""
            max_task_retries = getattr(spec.options, "max_task_retries", 0)
            if attempt < max_task_retries:
                await asyncio.sleep(self.config.task_retry_delay_s)
                await self._push_actor_task(spec, attempt + 1)
            else:
                self._fail_task_returns(
                    spec, ActorDiedError(f"actor {spec.actor_id.hex()[:8]} task {spec.method_name} failed: {e}")
                )

    async def _refresh_actor_addr(self, actor_id: ActorID, entry: dict):
        info = await self.controller.call("wait_actor_alive", {"actor_id": actor_id.binary()})
        if info is None or info["state"] == "DEAD":
            raise ActorDiedError(f"actor {actor_id.hex()[:8]} is dead: {(info or {}).get('death_cause', 'unknown')}")
        entry["addr"] = info["worker_addr"]

    def kill_actor_sync(self, actor_id: ActorID, no_restart: bool = True):
        self._run(self.controller.call("kill_actor", {"actor_id": actor_id.binary(), "no_restart": no_restart}))

    # -- actors: executor side -----------------------------------------
    async def handle_create_actor(self, conn, p):
        spec: ActorSpec = p["spec"]
        cls = await self._load_callable(spec.cls_id)
        args, kwargs = serialization.deserialize(spec.init_args_blob)
        runtime = ActorRuntime(self, spec, cls)
        await runtime.construct(args, kwargs)
        self._actor_runtime = runtime
        return True

    async def handle_push_actor_task(self, conn, p):
        if self._actor_runtime is None:
            raise rpc.RpcError("no actor hosted on this worker")
        spec = p.get("spec")
        if spec is not None:
            # Full spec: intern its per-handle constants under the caller's
            # small int so subsequent calls can ride the lean frame (a full
            # TaskSpec costs ~15x a small tuple to (un)pickle on the wire —
            # the dominant per-call cost for tiny actor calls on one core).
            oid = p.get("oid")
            if oid is not None:
                conn.meta.setdefault("opts_in", {})[oid] = (
                    spec.options, spec.job_id, spec.caller_addr, spec.actor_id
                )
        else:
            tid, method, args_blob, num_returns, cg, oid = p["lean"]
            options, job_id, caller_addr, actor_id = conn.meta["opts_in"][oid]
            spec = TaskSpec(
                task_id=TaskID(tid), job_id=job_id, fn_id="", args_blob=args_blob,
                num_returns=num_returns, options=options, caller_addr=caller_addr,
                actor_id=actor_id, method_name=method, concurrency_group=cg,
                trace_ctx=p.get("tc"), qos_ctx=p.get("qc"),
            )
        streaming = spec.num_returns == -1
        if streaming:
            # Synchronous registration before the first await — see
            # _stream_register for the ordering contract with generator_close.
            self._stream_register(spec.task_id.binary())
        tc = spec.trace_ctx
        if tc is not None:
            # Exec-span events ONLY when traced: untraced actor calls keep
            # their zero-event hot path (the latency histogram below is the
            # always-on signal).
            spec._exec_ctx = (tc[0], _tracing.new_span_id())  # type: ignore[attr-defined]
            self._task_event("task_exec_start", spec, node=self.node_id,
                             span_id=spec._exec_ctx[1])
        t0 = time.monotonic()
        try:
            return await self._actor_runtime.execute(spec, conn)
        finally:
            _task_latency_actor.observe(time.monotonic() - t0)
            if tc is not None:
                # trace id rides along so the index records the end (duration).
                self._task_event("task_exec_end", spec, node=self.node_id,
                                 span_id=spec._exec_ctx[1])
            if streaming:
                self._stream_cleanup(spec.task_id.binary())


    # -- compiled DAG stages (ray_tpu.dag; channels ride the existing peer
    # connections — reference: compiled_dag_node.py exec loops + channels) --
    def handle_dag_setup(self, conn, p):
        from ray_tpu.dag.runtime import dag_setup

        return dag_setup(self, p)

    async def handle_dag_push(self, conn, p):
        from ray_tpu.dag.runtime import dag_push

        return await dag_push(self, conn, p)

    def handle_dag_teardown(self, conn, p):
        from ray_tpu.dag.runtime import dag_teardown

        return dag_teardown(self, p)

    def handle_store_path(self, conn, p):
        """Arena identity probe: same path = same node = zero-copy dag edges."""
        return self.store.path if self.store is not None else ""

    async def handle_profile_cpu(self, conn, p):
        """On-demand CPU profile of THIS worker (the dashboard's
        py-spy-equivalent, reference: dashboard/modules/reporter/
        profile_manager.py:60-100 — here in-process via sys._current_frames).
        Routed through the obs.profiler capture-session API (one entry point,
        session-bounded, shared frame rendering with every other profile
        surface); runs on an executor thread so the IO loop keeps serving
        while sampling. Reply keeps the original shape plus the fold's
        plane/drop counters."""
        duration = min(float(p.get("duration_s", 2.0)), 30.0)
        hz = None
        if p.get("interval_s"):
            hz = 1.0 / max(float(p["interval_s"]), 0.005)

        loop = asyncio.get_running_loop()
        fold = await loop.run_in_executor(
            None, lambda: _profiler.capture(duration, hz=hz))
        return fold

    async def handle_profile_fold(self, conn, p):
        """This process's leg of cluster profile collection (controller ->
        daemon -> worker fan-out, memory_summary-style). Modes (first match):
        ``status`` -> sampler status row; ``trace_id`` -> that trace's
        accumulator; ``seconds`` -> live bounded capture (executor thread);
        ``window_s`` -> recent-window fold; default -> since-arm totals."""
        if p.get("seconds"):
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(None, lambda: _profiler.local_fold(p))
        return _profiler.local_fold(p)

    def handle_dag_shm_ack(self, conn, p):
        from ray_tpu.dag.runtime import dag_shm_ack

        return dag_shm_ack(self, p)

    def handle_dag_result(self, conn, p):
        from ray_tpu.dag.runtime import dag_result

        return dag_result(self, p)

    # -- collective ring transport (ray_tpu/collective/ring.py) ----------
    # The ring's control plane rides the worker RPC server: a neighbor's
    # hello pins which inbound Connection carries its raw frames; ready/
    # meta/abort notifies key per-op events. Raw tensor frames themselves
    # never reach a handler — they land in expect_raw buffers.

    def handle_collective_ring_hello(self, conn, p):
        from ray_tpu.collective import ring as _colring

        return _colring._on_hello(conn, p)

    def handle_collective_ring_ready(self, conn, p):
        from ray_tpu.collective import ring as _colring

        _colring._on_ready(p)

    def handle_collective_ring_meta(self, conn, p):
        from ray_tpu.collective import ring as _colring

        _colring._on_meta(p)

    def handle_collective_ring_abort(self, conn, p):
        from ray_tpu.collective import ring as _colring

        return _colring._on_abort(p)

    # -- elastic train plane (ray_tpu/elastic/transfer.py) ---------------
    # Live-reshard byte runs ride the same raw lane as object pulls: this
    # handler only slices parked export views and send_raw's them — the
    # payload is never pickled and the reply carries only counters.

    async def handle_elastic_fetch(self, conn, p):
        from ray_tpu.elastic import transfer as _elastic

        return await _elastic.fetch(self, conn, p)

    def handle_shutdown(self, conn, p):
        self._shutdown = True
        if self._actor_runtime is not None:
            self._actor_runtime.on_exit()
        loop = self.loop

        def stop():
            loop.stop()

        loop.call_soon(stop)
        return True

    def handle_memory_summary(self, conn, p):
        """Dump this process's ownership/reference picture (the `ray memory`
        per-worker unit, reference: CoreWorkerService.GetCoreWorkerStats ->
        memory_summary): owned objects with pin counts + borrower counts,
        objects borrowed FROM other owners, lineage pins, and queued
        submissions. Bounded by `limit` with an explicit truncation count."""
        return self.memory_summary(limit=int(p.get("limit", 200)))

    def memory_summary(self, limit: int = 200) -> dict:
        owned = []
        for oid, rec in list(self.owned.items()):
            if len(owned) >= limit:
                break
            owned.append({
                "oid": oid.hex(),
                "state": rec.state,
                "size": rec.size,
                "local_refs": rec.local_refs,
                "borrowers": rec.borrowers,
                "where": "shm" if rec.in_shm else ("memory" if rec.in_memory else "-"),
            })
        borrowed = []
        for oid_bin, ent in list(self._borrowed.items()):
            if len(borrowed) >= limit:
                break
            borrowed.append({
                "oid": ObjectID(oid_bin).hex(),
                "owner_addr": ent["owner_addr"],
                "refs": ent["refs"],
            })
        rt = self._actor_runtime
        return {
            "worker_id": self.worker_id,
            "address": self.address,
            "node_id": self.node_id,
            "actor_id": rt.spec.actor_id.hex() if rt is not None else "",
            "actor_name": rt.spec.name if rt is not None else "",
            "owned": owned,
            "owned_total": len(self.owned),
            "owned_truncated": max(0, len(self.owned) - len(owned)),
            "borrowed": borrowed,
            "borrowed_total": len(self._borrowed),
            "borrowed_truncated": max(0, len(self._borrowed) - len(borrowed)),
            "memory_store_objects": len(self.memory_store),
            "lineage": {"tasks": len(self._lineage), "bytes": self._lineage_bytes},
            "queued": {
                "submitter": sum(len(s.queue) for s in self._submitters.values()),
                "actor_pump": sum(q.qsize() for q in self._actor_send_queues.values()),
                "inflight_deps": len(self._inflight_deps),
            },
        }

    def handle_debug_observability(self, conn, p):
        """Ground-truth snapshot of this worker's observability state (used
        by dashboards/tests to distinguish 'never recorded' from 'never
        flushed' without waiting on reporter ticks)."""
        tail = int(p.get("tail", 5))
        return {
            "worker_id": self.worker_id,
            "task_events_len": len(self.task_events),
            "events_reported": self._events_reported,
            "events_dropped": self._events_dropped,
            "tail": self.task_events[-tail:] if tail > 0 else [],
            "flight": _flight.recorder().stats(),
            "profiler": _profiler.status(),
        }

    def handle_flight_dump(self, conn, p):
        """Operator-requested black-box dump of THIS process (`raytpu debug
        dump <worker>`): writes the ring and returns the path + stats."""
        path = _flight.dump("manual", reason=p.get("reason", "rpc request"))
        return {"path": path, **_flight.recorder().stats()}

    def handle_flight_query(self, conn, p):
        """Events this process's recorder still holds for one trace — the
        per-worker leg of `raytpu trace export` reassembly (controller fans
        out through the daemons, memory_summary-style)."""
        return {"events": _flight.recorder().events_for_trace(p.get("trace_id", ""))}


class ActorRuntime:
    """Hosts one actor instance: FIFO ordering, max_concurrency via thread
    pool (sync methods) or asyncio semaphore (async methods). Named
    concurrency groups get their own lane (pool + semaphore) so e.g. an "io"
    group keeps serving health checks while the default lane is saturated
    (reference: ConcurrencyGroupManager + per-group fiber/thread executors,
    core_worker/task_execution)."""

    def __init__(self, core: CoreWorker, spec: ActorSpec, cls):
        self.core = core
        self.spec = spec
        self.cls = cls
        self.instance = None
        maxc = max(1, spec.options.max_concurrency)
        self.pool = concurrent.futures.ThreadPoolExecutor(max_workers=maxc, thread_name_prefix="actor")
        self.sem = asyncio.Semaphore(maxc)
        self._ordered = maxc == 1
        self._chain: asyncio.Future | None = None
        self._group_pools: dict[str, concurrent.futures.ThreadPoolExecutor] = {}
        self._group_sems: dict[str, asyncio.Semaphore] = {}
        for gname, gmax in (spec.options.concurrency_groups or {}).items():
            gmax = max(1, int(gmax))
            self._group_pools[gname] = concurrent.futures.ThreadPoolExecutor(
                max_workers=gmax, thread_name_prefix=f"actor-{gname}"
            )
            self._group_sems[gname] = asyncio.Semaphore(gmax)

    def _lane(self, spec: TaskSpec, method) -> tuple:
        """(pool, semaphore, ordered) for this call: explicit per-call group,
        else the method's @method default, else the default lane."""
        group = spec.concurrency_group or getattr(
            method, "__raytpu_method_opts__", {}
        ).get("concurrency_group", "")
        if group:
            pool = self._group_pools.get(group)
            if pool is None:
                raise ValueError(
                    f"unknown concurrency group {group!r}: declared groups are "
                    f"{sorted(self._group_pools)}"
                )
            return pool, self._group_sems[group], False
        return self.pool, self.sem, self._ordered

    async def construct(self, args, kwargs):
        loop = asyncio.get_running_loop()
        args = [self.core.get_sync(a) if isinstance(a, ObjectRef) else a for a in args]
        kwargs = {k: (self.core.get_sync(v) if isinstance(v, ObjectRef) else v) for k, v in kwargs.items()}

        def make():
            return self.cls(*args, **kwargs)

        self.instance = await loop.run_in_executor(self.pool, make)

    async def execute(self, spec: TaskSpec, conn=None) -> dict:
        method = getattr(self.instance, spec.method_name, None)
        if method is None:
            return {
                "status": "error",
                "error": RemoteError.from_exception(AttributeError(f"no method {spec.method_name}"), "actor task"),
            }
        try:
            # QoS hop "worker" (actor lane): drop already-expired calls
            # before the method runs; the typed error reply reaches the
            # caller through the normal error path (counted, traced).
            _qos.check_deadline("worker", _qos.from_wire(spec.qos_ctx),
                                detail=spec.method_name)
            fault = _chaos.maybe_inject("worker.actor.exec", method=spec.method_name)
            if fault is not None:
                if fault.kind == "delay":
                    await asyncio.sleep(fault.delay_s)
                elif fault.kind == "error":
                    raise fault.error(f"actor method {spec.method_name}")
            if spec.num_returns == -1:  # streaming generator method
                n = await self._execute_streaming(method, spec, conn)
                return {"status": "ok", "streaming_done": n}
            pool, sem, _ordered = self._lane(spec, method)
            if inspect.iscoroutinefunction(method):
                async with sem:
                    result = await self._call_async(method, spec)
            else:
                loop = asyncio.get_running_loop()
                result = await loop.run_in_executor(pool, self._call_sync, method, spec)
            returns = await self.core._package_returns(spec, result)
            return {"status": "ok", "returns": returns}
        except BaseException as e:  # noqa: BLE001
            return {"status": "error", "error": RemoteError.from_exception(e, where=f"actor method {spec.method_name}")}

    async def _execute_streaming(self, method, spec: TaskSpec, conn) -> int:
        """Stream a generator actor method's yields to the caller through
        the same per-stream batch lane as streaming normal tasks: buffered
        appends drained by a loop-side pump into generator_items frames,
        count in the final reply. Sync generators append cross-thread (no
        per-item loop round trip); async generators append loop-side."""
        loop = asyncio.get_running_loop()
        pool, sem, _ = self._lane(spec, method)
        shipper = _StreamShipper(self.core, conn, spec, loop)
        self.core._stream_shippers[spec.task_id.binary()] = shipper
        if inspect.isasyncgenfunction(method):
            args, kwargs = await loop.run_in_executor(None, self._resolve, spec.args_blob)
            count = 0
            token = _tracing.activate(getattr(spec, "_exec_ctx", None))
            qtoken = _qos.activate(spec.qos_ctx)
            try:
                async with sem:
                    agen = method(*args, **kwargs)
                    try:
                        async for value in agen:
                            try:
                                await shipper.aput(count, value)
                            except _StreamClosed:
                                break
                            count += 1
                    finally:
                        await agen.aclose()
                await shipper.afinish()
                return count
            finally:
                _qos.deactivate(qtoken)
                _tracing.deactivate(token)

        def run():
            # Context active for the generator BODY (runs during next()).
            token = _tracing.activate(getattr(spec, "_exec_ctx", None))
            qtoken = _qos.activate(spec.qos_ctx)
            try:
                out = self._call_sync(method, spec)
                if not inspect.isgenerator(out):
                    raise TypeError(
                        f"actor method {spec.method_name} declared "
                        f"num_returns='streaming' but returned {type(out).__name__}"
                    )
                n = 0
                for value in out:
                    try:
                        shipper.put(n, value)
                    except _StreamClosed:
                        out.close()
                        break
                    n += 1
                shipper.finish()
                return n
            finally:
                _qos.deactivate(qtoken)
                _tracing.deactivate(token)

        # Stream state registered/cleaned by handle_push_actor_task's
        # try/finally around execute().
        return await loop.run_in_executor(pool, run)

    def _resolve(self, blob):
        args, kwargs = serialization.deserialize(blob)
        args = [self.core.get_sync(a) if isinstance(a, ObjectRef) else a for a in args]
        kwargs = {k: (self.core.get_sync(v) if isinstance(v, ObjectRef) else v) for k, v in kwargs.items()}
        return args, kwargs

    def _call_sync(self, method, spec: TaskSpec):
        args, kwargs = self._resolve(spec.args_blob)
        # Pool threads don't inherit the IO loop's contextvars: install the
        # call's execution span (if traced) + QoS context so user code
        # chains onto them.
        token = _tracing.activate(getattr(spec, "_exec_ctx", None))
        qtoken = _qos.activate(spec.qos_ctx)
        _qos.mark_exec_start("worker")
        try:
            return method(*args, **kwargs)
        finally:
            _qos.deactivate(qtoken)
            _tracing.deactivate(token)

    async def _call_async(self, method, spec: TaskSpec):
        args, kwargs = await asyncio.get_running_loop().run_in_executor(None, self._resolve, spec.args_blob)
        token = _tracing.activate(getattr(spec, "_exec_ctx", None))
        qtoken = _qos.activate(spec.qos_ctx)
        _qos.mark_exec_start("worker")
        try:
            return await method(*args, **kwargs)
        finally:
            _qos.deactivate(qtoken)
            _tracing.deactivate(token)

    def on_exit(self):
        inst = self.instance
        if inst is not None and hasattr(inst, "__raytpu_exit__"):
            try:
                inst.__raytpu_exit__()
            except Exception:
                pass
