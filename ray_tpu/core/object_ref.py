"""ObjectRef: a first-class future naming a value owned by some worker.

Mirrors the reference's ObjectRef + ownership model
(/root/reference/src/ray/core_worker/reference_counter.h:44 — the owner is the
worker that created the value; borrowers resolve and refcount through it).
The ref carries its owner's RPC address so any holder can resolve it without a
central directory lookup (the directory is a fallback, as in the reference's
OwnershipObjectDirectory).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from ray_tpu.core.ids import ObjectID

# Set by the core worker at init; used by ObjectRef.__del__ / get.
_ref_removed_hook: Optional[Callable] = None
_ref_created_hook: Optional[Callable] = None


def set_ref_hooks(created: Callable | None, removed: Callable | None):
    global _ref_created_hook, _ref_removed_hook
    _ref_created_hook = created
    _ref_removed_hook = removed


class ObjectRef:
    __slots__ = ("id", "owner_addr", "size_hint", "_registered", "__weakref__")

    def __init__(self, oid: ObjectID, owner_addr: str, size_hint: int = 0, _register: bool = True):
        self.id = oid
        self.owner_addr = owner_addr
        self.size_hint = size_hint
        self._registered = False
        if _register and _ref_created_hook is not None:
            _ref_created_hook(self)
            self._registered = True

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def __reduce__(self):
        return (_reconstruct_ref, (self.id, self.owner_addr, self.size_hint))

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:16]}, owner={self.owner_addr})"

    def __del__(self):
        if self._registered and _ref_removed_hook is not None:
            try:
                _ref_removed_hook(self)
            except Exception:
                pass

    # Allow ``await ref`` inside async actors / driver coroutines.
    def __await__(self):
        from ray_tpu.core import api

        return api.get_async(self).__await__()

    def future(self):
        from ray_tpu.core import api

        return api.get_async(self)


def _reconstruct_ref(oid: ObjectID, owner_addr: str, size_hint: int) -> ObjectRef:
    return ObjectRef(oid, owner_addr, size_hint)


class ObjectLostError(Exception):
    pass


class GetTimeoutError(TimeoutError):
    pass
