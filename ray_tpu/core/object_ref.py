"""ObjectRef: a first-class future naming a value owned by some worker.

Mirrors the reference's ObjectRef + ownership model
(/root/reference/src/ray/core_worker/reference_counter.h:44 — the owner is the
worker that created the value; borrowers resolve and refcount through it).
The ref carries its owner's RPC address so any holder can resolve it without a
central directory lookup (the directory is a fallback, as in the reference's
OwnershipObjectDirectory).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

from ray_tpu.core.ids import ObjectID

# Set by the core worker at init; used by ObjectRef.__del__ / get.
_ref_removed_hook: Optional[Callable] = None
_ref_created_hook: Optional[Callable] = None


def set_ref_hooks(created: Callable | None, removed: Callable | None):
    global _ref_created_hook, _ref_removed_hook
    _ref_created_hook = created
    _ref_removed_hook = removed


class ObjectRef:
    __slots__ = ("id", "owner_addr", "size_hint", "_registered", "__weakref__")

    def __init__(self, oid: ObjectID, owner_addr: str, size_hint: int = 0, _register: bool = True):
        self.id = oid
        self.owner_addr = owner_addr
        self.size_hint = size_hint
        self._registered = False
        if _register and _ref_created_hook is not None:
            _ref_created_hook(self)
            self._registered = True

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def __reduce__(self):
        return (_reconstruct_ref, (self.id, self.owner_addr, self.size_hint))

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:16]}, owner={self.owner_addr})"

    def __del__(self):
        if self._registered and _ref_removed_hook is not None:
            try:
                _ref_removed_hook(self)
            except Exception:
                pass

    # Allow ``await ref`` inside async actors / driver coroutines.
    def __await__(self):
        from ray_tpu.core import api

        return api.get_async(self).__await__()

    def future(self):
        from ray_tpu.core import api

        return api.get_async(self)


def _reconstruct_ref(oid: ObjectID, owner_addr: str, size_hint: int) -> ObjectRef:
    return ObjectRef(oid, owner_addr, size_hint)


class ObjectRefGenerator:
    """Iterator over the streamed returns of a generator task.

    Reference: streaming generators — the executor reports each yielded item
    as its own return object (core_worker.proto ReportGeneratorItemReturns;
    TaskManager streaming-generator returns) and the caller iterates
    ObjectRefs as they arrive, before the task finishes. Items are pushed
    from the IO loop (``_push``); ``__next__`` blocks the consuming thread
    until the next indexed item or end-of-stream. A worker-crash retry
    replays the stream from index 0; ``reserve`` dedups already-seen indices
    so consumers observe each index exactly once.
    """

    def __init__(self, task_id, owner_addr: str):
        import threading

        self.task_id = task_id
        self.owner_addr = owner_addr
        self._cond = threading.Condition()
        self._items: dict[int, ObjectRef] = {}  # arrived, unconsumed
        self._seen: set[int] = set()
        self._next = 0
        self._total: Optional[int] = None
        self._error: Optional[BaseException] = None
        # Consumption-ack hook for backpressured streams (set by the core
        # worker when the producer requests acks).
        self._ack = None
        # Early-close hook (set at submit time): tells the producing worker
        # to stop at its next yield (reference: CancelTask for streaming).
        self._cancel = None
        # Optional arrival callback for async consumers (the serve proxy):
        # invoked after items/finish land so an event loop can wake and
        # drain via poll() instead of parking a thread in __next__.
        self._wakeup = None

    # -- producer side (IO loop) --------------------------------------
    def reserve(self, index: int) -> bool:
        """True if this index is new (caller should register + push)."""
        with self._cond:
            if index in self._seen:
                return False
            self._seen.add(index)
            return True

    def _push(self, index: int, ref: ObjectRef):
        with self._cond:
            self._items[index] = ref
            self._cond.notify_all()
        self._notify_wakeup()

    def _push_many(self, pairs):
        """Absorb one batch frame's refs under a single lock acquisition
        (one notify_all for N items — the owner-side half of the streaming
        fast lane's batching)."""
        with self._cond:
            for index, ref in pairs:
                self._items[index] = ref
            self._cond.notify_all()
        self._notify_wakeup()

    def _finish(self, total: Optional[int] = None, error: BaseException | None = None):
        with self._cond:
            if total is not None:
                self._total = total
            if error is not None:
                # Same contract as rt.get (worker.get_sync): a RemoteError
                # carrying a picklable cause re-raises the TYPED original —
                # a streamed DeadlineExceeded must reach the consumer as
                # DeadlineExceeded, not as a generic RemoteError wrapper.
                cause = getattr(error, "cause", None)
                error = cause if cause is not None else error
                self._error = error
                if self._total is None:
                    # Hand out what already arrived, then raise.
                    self._total = max(self._items, default=-1) + 1
            self._cond.notify_all()
        self._notify_wakeup()

    def _notify_wakeup(self):
        wake = self._wakeup
        if wake is not None:
            try:
                wake()
            except Exception:
                pass  # a dead consumer loop must not poison the producer

    # -- consumer side -------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        return self._next_item(None)

    def next_with_timeout(self, timeout: float) -> ObjectRef:
        return self._next_item(timeout)

    def _next_item(self, timeout) -> ObjectRef:
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._cond:
            while True:
                if self._next in self._items:
                    ref = self._items.pop(self._next)
                    self._next += 1
                    ack, consumed = self._ack, self._next
                    if ack is not None:
                        ack(consumed)
                    return ref
                if self._total is not None and self._next >= self._total:
                    if self._error is not None:
                        raise self._error
                    raise StopIteration
                remaining = None if deadline is None else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError("generator item timeout")
                self._cond.wait(remaining if remaining is not None else 1.0)

    def set_wakeup(self, cb):
        """Register an arrival callback for async consumption (see poll);
        called after every push/finish, outside the lock."""
        self._wakeup = cb
        # Items that landed before registration would otherwise never wake
        # the consumer: fire once so it drains the backlog immediately.
        if cb is not None:
            self._notify_wakeup()

    def poll(self):
        """Non-blocking probe for async consumers: returns one of
        ('item', ObjectRef) — the next indexed item (consumption-acked like
        __next__), ('wait', None) — nothing available yet (await the wakeup
        callback), ('end', None) — exhausted, or ('error', err) — the stream
        failed after handing out everything that arrived."""
        with self._cond:
            if self._next in self._items:
                ref = self._items.pop(self._next)
                self._next += 1
                ack, consumed = self._ack, self._next
                if ack is not None:
                    ack(consumed)
                return ("item", ref)
            if self._total is not None and self._next >= self._total:
                if self._error is not None:
                    return ("error", self._error)
                return ("end", None)
            return ("wait", None)

    def completed(self) -> bool:
        with self._cond:
            return self._total is not None

    def close(self):
        """Stop consuming: best-effort cancellation of the producing task.
        Idempotent; a no-op once the stream has finished."""
        cb, self._cancel = self._cancel, None
        if cb is not None and not self.completed():
            try:
                cb()
            except Exception:
                pass  # core already shut down: nothing to cancel

    def __del__(self):
        self.close()
        # Unconsumed item refs drop their pins through ObjectRef.__del__.
        with self._cond:
            self._items.clear()


class ObjectLostError(Exception):
    pass


class GetTimeoutError(TimeoutError):
    pass
