"""Minimal symmetric asyncio RPC: length-prefixed pickled frames over TCP/UDS.

Role-equivalent to the reference's gRPC scaffolding (/root/reference/src/ray/rpc):
every process exposes a handler object; both ends of a connection can invoke
methods on the other (the reference achieves the same with per-direction gRPC
services, e.g. CoreWorkerService.PushTask flowing caller->callee and
PubsubLongPolling flowing callee->caller). Frames are pickled tuples —
small control messages only; bulk data rides the shared-memory object store.

Wire format: 8-byte little-endian length, then 1 version byte
(WIRE_VERSION — the pickle-frame schema generation; a frame from a build
speaking a different generation is REFUSED with a clear log line before any
byte of it reaches pickle, so two mixed-version hosts fail loud instead of
corrupting each other mid-rolling-upgrade), then [16-byte session tag when a
token is set] + pickle of (kind, msg_id, method_or_status, payload).
kind: 0=request, 1=reply, 2=notify (no reply expected).

Authentication (ON BY DEFAULT): pickle-over-TCP executes arbitrary code on
unpickle, so a session token is installed for every cluster (auto-minted at
head start unless RAYTPU_AUTO_TOKEN=0; pin one with ``Config.auth_token`` /
``RAYTPU_AUTH_TOKEN`` for multi-host; it propagates to daemons/workers/jobs
via config+env). With a token installed, EVERY frame carries a
16-byte keyed-BLAKE2b MAC of its payload, verified constant-time
BEFORE the payload is unpickled. Frames from peers without the token (or
tampered frames) are dropped and the connection closed — their bytes never
reach pickle (reference: token auth, src/ray/rpc/authentication). Stateless
per frame: no handshake ordering to get wrong. Limitation: no replay
nonce — an on-path attacker can replay a previously-sent frame verbatim,
but cannot forge new payloads.
"""
from __future__ import annotations

import asyncio
import hashlib
import hmac
import itertools
import logging
import pickle
import socket
import time
import traceback
from typing import Any

logger = logging.getLogger(__name__)

_REQ, _REP, _NOTIFY = 0, 1, 2
_HDR = 8
_TAG_LEN = 16
# Wire-format generation. Bump when the frame schema changes (pickle tuple
# shape, tag algorithm/length, header layout). Reference: protobuf gives the
# reference schema evolution for free; pickle frames get a refuse-on-mismatch
# version byte instead. Chosen != 0x80 (pickle PROTO opcode) so pre-version
# builds are also rejected, not misparsed.
WIRE_VERSION = 1
_VER = bytes([WIRE_VERSION])
# Sanity cap on a declared frame length: readexactly buffers the whole frame
# BEFORE the auth check can reject the peer, so an untrusted header must not
# be able to demand unbounded memory.
_MAX_FRAME = 1 << 30

_frame_key: bytes = b""  # empty = auth disabled


def set_auth_token(token: str | bytes | None):
    """Install the session token for this process. Every frame sent gets a
    keyed-BLAKE2b(token, payload) tag prepended; every frame received must
    verify. All peers of a session must run the same build (the tag
    algorithm is part of the wire format; there is no version negotiation —
    a mismatched peer is dropped as unauthenticated)."""
    global _frame_key
    if not token:
        _frame_key = b""
    else:
        raw = token.encode() if isinstance(token, str) else bytes(token)
        _frame_key = hashlib.blake2b(raw, digest_size=32, person=b"raytpu-rpc").digest()


def get_auth_token() -> bytes:
    return _frame_key


def _tag(payload: bytes) -> bytes:
    # Keyed BLAKE2b (a PRF by construction — no HMAC wrapper needed): ~2x
    # faster than HMAC-SHA256 on the small frames the actor hot path sends,
    # and this tag is computed 4x per call (send+verify on both ends).
    return hashlib.blake2b(payload, key=_frame_key, digest_size=_TAG_LEN).digest()


def frame_tag(payload: bytes) -> bytes:
    """Public tag helper for auxiliary authenticated protocols (e.g. the
    serve proxy's binary ingress): keyed-BLAKE2b(session key, payload)
    prefix, or b"" when auth is disabled. Verify with frame_verify."""
    return _tag(payload) if _frame_key else b""


def frame_verify(tag: bytes, payload: bytes) -> bool:
    if not _frame_key:
        return True  # auth disabled for this session
    return len(tag) == _TAG_LEN and hmac.compare_digest(tag, _tag(payload))


def derive_frame_key(token: str | bytes) -> bytes:
    """The session token -> frame key derivation (single home: off-cluster
    clients, e.g. serve's ProtoServeClient, must produce byte-identical
    tags to this process's set_auth_token path)."""
    raw = token.encode() if isinstance(token, str) else bytes(token)
    return hashlib.blake2b(raw, digest_size=32, person=b"raytpu-rpc").digest()


def tag_with_key(key: bytes, payload: bytes) -> bytes:
    """frame_tag with an explicit key (off-cluster callers)."""
    return hashlib.blake2b(payload, key=key, digest_size=_TAG_LEN).digest()


FRAME_TAG_LEN = _TAG_LEN


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


def parse_addr(addr: str):
    if addr.startswith("unix:"):
        return ("unix", addr[5:])
    host, _, port = addr.rpartition(":")
    return ("tcp", host, int(port))


class Connection:
    """One live peer connection. ``call`` awaits a reply; ``notify`` doesn't."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter, handler: Any, peer_name: str = "?"):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.peer_name = peer_name
        self._loop = asyncio.get_running_loop()
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._closed = False
        self._send_lock = asyncio.Lock()
        self._task = asyncio.create_task(self._read_loop())
        self.on_close = None  # optional callback
        self.meta: dict = {}  # server-side per-connection state (registration info)

    async def _send(self, frame: tuple):
        data = pickle.dumps(frame, protocol=5)
        data = _VER + _tag(data) + data if _frame_key else _VER + data
        async with self._send_lock:
            self.writer.write(len(data).to_bytes(_HDR, "little") + data)
            await self.writer.drain()

    def call_start(self, method: str, payload: Any = None) -> "asyncio.Future":
        """Synchronously enqueue a request frame; return the reply future.

        Unlike ``call``, the frame hits the transport buffer before this
        returns, so invocation order == wire order — required by per-actor
        FIFO task submission (the reference orders actor tasks with sequence
        numbers in ActorTaskSubmitter; here wire order is the sequence).
        """
        if self._closed:
            raise ConnectionLost(f"connection to {self.peer_name} closed")
        msg_id = next(self._ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        fut.add_done_callback(lambda f: self._pending.pop(msg_id, None))
        data = pickle.dumps((_REQ, msg_id, method, payload), protocol=5)
        data = _VER + _tag(data) + data if _frame_key else _VER + data
        self.writer.write(len(data).to_bytes(_HDR, "little") + data)
        return fut

    async def flush(self):
        """Await transport drain — backpressure for call_start senders."""
        async with self._send_lock:
            await self.writer.drain()

    async def call(self, method: str, payload: Any = None, timeout: float | None = None) -> Any:
        if self._closed:
            raise ConnectionLost(f"connection to {self.peer_name} closed")
        msg_id = next(self._ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        try:
            await self._send((_REQ, msg_id, method, payload))
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(msg_id, None)

    async def notify(self, method: str, payload: Any = None):
        if self._closed:
            raise ConnectionLost(f"connection to {self.peer_name} closed")
        await self._send((_NOTIFY, 0, method, payload))

    async def _read_loop(self):
        try:
            while True:
                hdr = await self.reader.readexactly(_HDR)
                ln = int.from_bytes(hdr, "little")
                if ln > _MAX_FRAME:
                    logger.warning("dropping peer %s: absurd frame length %d", self.peer_name, ln)
                    return
                data = await self.reader.readexactly(ln)
                # Version check BEFORE auth/unpickle: a frame from a build
                # with a different wire generation must never reach pickle.
                if ln < 1 or data[0] != WIRE_VERSION:
                    logger.error(
                        "refusing rpc frame from %s: wire-format version %s, this build speaks %d "
                        "— all hosts of a session must run the same ray_tpu version; dropping peer",
                        self.peer_name, data[0] if ln else "<empty>", WIRE_VERSION,
                    )
                    return
                data = memoryview(data)[1:]
                if _frame_key:
                    # Constant-time per-frame MAC check BEFORE any
                    # unpickling; wrong/missing tag = unauthenticated or
                    # tampered frame, drop the peer.
                    body = data[_TAG_LEN:]
                    if len(data) < _TAG_LEN or not hmac.compare_digest(data[:_TAG_LEN], _tag(body)):
                        logger.warning("rejecting unauthenticated rpc frame from %s", self.peer_name)
                        return
                    data = body
                kind, msg_id, method, payload = pickle.loads(data)
                if kind == _REP:
                    fut = self._pending.get(msg_id)
                    if fut is not None and not fut.done():
                        ok, result = method, payload
                        if ok == "ok":
                            fut.set_result(result)
                        else:
                            fut.set_exception(result if isinstance(result, BaseException) else RpcError(str(result)))
                else:
                    asyncio.create_task(self._dispatch(kind, msg_id, method, payload))
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError, OSError):
            pass
        except asyncio.CancelledError:
            return
        except Exception:
            logger.exception("rpc read loop error (peer=%s)", self.peer_name)
        finally:
            self._teardown()

    async def _dispatch(self, kind, msg_id, method, payload):
        try:
            fn = getattr(self.handler, "handle_" + method, None)
            if fn is None:
                raise RpcError(f"no handler for {method!r} on {type(self.handler).__name__}")
            result = fn(self, payload)
            if asyncio.iscoroutine(result):
                result = await result
            if kind == _REQ:
                await self._send((_REP, msg_id, "ok", result))
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            if kind == _REQ:
                try:
                    pickle.dumps(e)
                    err: Any = e
                except Exception:
                    err = RpcError(f"{type(e).__name__}: {e}\n{traceback.format_exc()}")
                try:
                    await self._send((_REP, msg_id, "err", err))
                except Exception:
                    pass
            else:
                logger.exception("error in notify handler %s", method)

    def _teardown(self):
        if self._closed:
            return
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(f"connection to {self.peer_name} lost"))
                fut.add_done_callback(lambda f: f.exception())
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close:
            cb, self.on_close = self.on_close, None
            try:
                cb(self)
            except Exception:
                if not self._loop.is_closed():
                    logger.debug("on_close callback failed", exc_info=True)

    @property
    def closed(self):
        return self._closed

    async def close(self):
        self._task.cancel()
        self._teardown()


class RpcServer:
    """Listens on tcp host:port (port=0 picks free) and/or a unix path."""

    def __init__(self, handler: Any, host: str = "127.0.0.1"):
        self.handler = handler
        self.host = host
        self.port: int | None = None
        self._server: asyncio.AbstractServer | None = None
        self.connections: set[Connection] = set()

    async def start(self, port: int = 0) -> str:
        self._server = await asyncio.start_server(self._on_client, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.address

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def _on_client(self, reader, writer):
        conn = Connection(reader, writer, self.handler, peer_name="client")
        self.connections.add(conn)
        conn.on_close = self.connections.discard
        cb = getattr(self.handler, "on_connection", None)
        if cb:
            cb(conn)

    async def close(self):
        if self._server:
            self._server.close()
        for conn in list(self.connections):
            await conn.close()
        if self._server:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=1.0)
            except Exception:
                pass


class PersistentConnection:
    """A Connection that transparently redials on loss and replays a
    registration handshake (``on_reconnect``) after each redial.

    Used for the long-lived links to the controller: daemons/drivers survive a
    controller restart (reference: GCS fault tolerance — raylets reconnect on
    RayletNotifyGCSRestart, core_worker.proto:475; here reconnection is
    detected by the TCP close + retried dial). Calls that were in flight when
    the link dropped raise ConnectionLost to THEIR caller (no blind replay of
    possibly non-idempotent operations); subsequent calls redial.
    """

    def __init__(self, addr: str, handler: Any = None, on_reconnect=None,
                 dial_timeout: float = 5.0, give_up_after: float = 120.0):
        self.addr = addr
        self.handler = handler
        self.on_reconnect = on_reconnect
        self.dial_timeout = dial_timeout
        self.give_up_after = give_up_after
        self._conn: Connection | None = None
        self._lock = asyncio.Lock()
        self._closed = False
        self.meta: dict = {}

    async def _ensure(self) -> Connection:
        if self._closed:
            raise ConnectionLost(f"persistent connection to {self.addr} closed")
        if self._conn is not None and not self._conn.closed:
            return self._conn
        async with self._lock:
            if self._conn is not None and not self._conn.closed:
                return self._conn
            deadline = time.monotonic() + self.give_up_after
            attempt = 0
            while True:
                if self._closed:
                    raise ConnectionLost(f"persistent connection to {self.addr} closed")
                conn = None
                try:
                    conn = await connect(self.addr, handler=self.handler, timeout=self.dial_timeout, retry=False)
                    if self.on_reconnect is not None:
                        await self.on_reconnect(conn)
                    self._conn = conn
                    return conn
                except Exception as e:
                    if conn is not None:  # dialed but handshake failed: don't leak it
                        try:
                            await conn.close()
                        except Exception:
                            pass
                    attempt += 1
                    if time.monotonic() > deadline:
                        raise ConnectionLost(f"cannot re-establish {self.addr}: {e}") from e
                    await asyncio.sleep(min(0.05 * attempt, 1.0))

    async def ensure(self) -> Connection:
        """Dial (and run the handshake) now; returns the live Connection."""
        return await self._ensure()

    async def call(self, method: str, payload: Any = None, timeout: float | None = None) -> Any:
        conn = await self._ensure()
        return await conn.call(method, payload, timeout)

    async def notify(self, method: str, payload: Any = None):
        conn = await self._ensure()
        await conn.notify(method, payload)

    @property
    def closed(self) -> bool:
        return self._closed

    async def close(self):
        self._closed = True
        if self._conn is not None:
            await self._conn.close()


async def connect(addr: str, handler: Any = None, timeout: float = 10.0, retry: bool = True) -> Connection:
    kind_parts = parse_addr(addr)
    deadline = time.monotonic() + timeout
    last_err: Exception | None = None
    while True:
        try:
            if kind_parts[0] == "unix":
                reader, writer = await asyncio.open_unix_connection(kind_parts[1])
            else:
                reader, writer = await asyncio.open_connection(kind_parts[1], kind_parts[2])
            sock = writer.get_extra_info("socket")
            if sock is not None and sock.family in (socket.AF_INET, socket.AF_INET6):
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return Connection(reader, writer, handler, peer_name=addr)
        except (ConnectionRefusedError, FileNotFoundError, OSError) as e:
            last_err = e
            if not retry or time.monotonic() > deadline:
                raise ConnectionLost(f"cannot connect to {addr}: {e}") from e
            await asyncio.sleep(0.05)
